"""perfattr — the runtime-attribution sentinel gate (ISSUE 16).

The obs layer's runtime ledger (``singa_tpu.obs.attr``) attributes
measured wall seconds to compiled programs and joins them against the
analytic cost model into a ``perf_attr`` payload.  This module gates
that payload in the house style (committed baseline, named PERF00x
finding per drifted invariant, ``--update-baselines`` reviewed-diff
flow) — closing the hole where a 2x dispatch regression that leaves
the HLO byte-identical sails through the structure and cost gates.

Because the CPU box's absolute speed varies run to run, the committed
sentinel (``tools/lint/data/perf/sentinel.json``) asserts **box-robust
invariants, never milliseconds**:

* **completeness** (PERF002) — the per-program totals still account
  for the committed share of the enclosing measured window (a new
  untimed dispatch path, or a seam that stopped being reached, shows
  up as attribution leaking away);
* **ranking stability** (PERF003) — no DECISIVE inversion of the
  committed p50 cost order.  The committed ranking is a list of cost
  TIERS: programs whose p50s sat within ``TIER_MARGIN`` of the tier's
  dearest member at commit time share a tier (their order was noise,
  not a claim) and never gate against each other; a program in a
  committed-cheaper tier costing more than ``RANK_MARGIN`` times one
  in a committed-dearer tier flips its cost class — exactly the
  program-local 2x-sail-through this gate exists to catch.  Decisive
  on BOTH sides (separated beyond 4x at commit AND flipped beyond 2x
  now), so a pair the baseline run itself could not confidently tell
  apart cannot fire;
* **decode/prefill ratio** (PERF004) — the per-dispatch p50 ratio of
  the two serve programs stays within a wide multiplicative band of
  its committed value (both numerators move with box speed, the ratio
  does not);
* **achieved-fraction sanity** (PERF005) — every program's
  achieved-roofline fraction is positive and below the committed
  ceiling (a non-positive or super-roofline fraction is a broken
  clock or a garbage model join, not a fast machine).

Absolute numbers land UNGATED in the record trajectory
(``python -m tools.obsq diff perf_attr`` / ``obsq attr``) — the gate
polices invariants; the trajectory answers "when did it move".

Run via the lint front door::

    python -m tools.lint --perf PATH            # gate a payload dump
    python -m tools.lint --perf PATH --update-baselines

where PATH is the JSON file ``bench.py --serve --perf-attr PATH``
dumps (a bare payload or a full record entry both work); ci_gate.sh
wires the sentinel off the stage-6 serve smoke.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

from .framework import Finding

_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))


def _ensure_repo_on_path() -> None:
    import sys
    if _REPO_ROOT not in sys.path:
        sys.path.insert(0, _REPO_ROOT)


__all__ = ["PERF_CODES", "SENTINEL_PATH", "SENTINEL_SCHEMA",
           "RATIO_BAND", "RANK_MARGIN", "TIER_MARGIN",
           "COMPLETENESS_BAND",
           "COMPLETENESS_CEILING",
           "sentinel_summary", "gate_findings", "update_baseline",
           "engine_features", "load_payload", "perf_main"]

#: the one committed cross-program baseline — ranking and ratios are
#: relations BETWEEN programs, so unlike the per-program hlo/cost
#: families this gate keeps a single sentinel file
SENTINEL_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "data", "perf", "sentinel.json")

#: sentinel format version — a baseline with another version fails
#: PERF001 instead of diffing garbage (same contract as SUMMARY_SCHEMA)
SENTINEL_SCHEMA = 1

#: finding codes, one per invariant — enumerated by ``--list-rules``
PERF_CODES = {
    "PERF000": ("suppression-hygiene", "a sentinel 'suppress' entry "
                "without a reason, or naming an unknown code, is "
                "itself a finding and cannot be waived"),
    "PERF001": ("payload-shape", "the perf_attr payload validates "
                "against the obs schema, its program keys are a subset "
                "of hlo.FLAGSHIP_PROGRAMS, and a committed same-schema "
                "sentinel exists"),
    "PERF002": ("completeness", "per-program totals still account for "
                "the committed share of the measured window (wide "
                "band — box-robust)"),
    "PERF003": ("ranking", "no decisive inversion of the committed "
                "p50 dispatch-cost tiers (a program in a committed-"
                "cheaper tier costing > RANK_MARGIN x one in a "
                "committed-dearer tier; same-tier near-ties never "
                "gate)"),
    "PERF004": ("decode-prefill-ratio", "decode/prefill p50 per-"
                "dispatch ratio stays within a wide multiplicative "
                "band of its committed value"),
    "PERF005": ("achieved-fraction", "every achieved-roofline "
                "fraction is positive and below the committed "
                "ceiling"),
}

#: multiplicative band for PERF004: current ratio must lie within
#: [committed / BAND, committed * BAND].  4x is deliberately wide —
#: scheduler jitter and warmup skew move the ratio by 2x on a noisy
#: box; a decode-only regression that survives this band has changed
#: the program's cost CLASS, not its noise
RATIO_BAND = 4.0

#: PERF003 firing threshold: an inversion across committed tiers
#: fires only when the committed-cheaper program now costs MORE than
#: this factor times the committed-dearer one — a beyond-2x flip of a
#: committed separation is a cost-class change, not jitter
RANK_MARGIN = 2.0

#: PERF003 commit threshold, deliberately WIDER than the firing one:
#: a program joins the current tier unless the tier's dearest member
#: sits at least this factor above it.  Claiming separation needs
#: stronger evidence than detecting a flip — two real runs measured
#: verify p50 at 0.6 ms then 0.8 ms against prefill at 1.1/1.8 ms
#: (~2x apart, with min_s ordering them the OTHER way), so a 2x-based
#: commit would have pinned an ordering the box cannot reproduce and
#: made ci_gate flaky; both runs produce the SAME tier structure at 4x
TIER_MARGIN = 4.0

#: PERF002 floor: current attributed_frac must reach committed * BAND
#: (an instrumentation seam silently dropped halves attribution;
#: run-to-run harness slack does not)
COMPLETENESS_BAND = 0.5

#: PERF002 ceiling: attribution beyond the window itself (plus clock
#: slack) means double counting — totals summing past the enclosing
#: span is a bug at any box speed
COMPLETENESS_CEILING = 1.05


def sentinel_summary(payload: Dict[str, Any]) -> Dict[str, Any]:
    """The box-robust invariant quantities of one ``perf_attr``
    payload — what the committed sentinel stores and the gate diffs.
    Ranking is a list of cost TIERS, most expensive first: programs
    are ordered by p50 dispatch cost (ties break by name, so the
    summary is deterministic) and a program merges into the current
    tier unless the tier's dearest member sits ``TIER_MARGIN`` or more
    above it — the baseline run could not confidently tell them
    apart, so their order is not committed."""
    programs = payload.get("programs", {})
    p50 = {n: float(programs[n]["p50_s"]) for n in programs}
    order = sorted(programs, key=lambda n: (-p50[n], n))
    ranking: List[List[str]] = []
    for name in order:
        if ranking and p50[ranking[-1][0]] < TIER_MARGIN * p50[name]:
            ranking[-1].append(name)
        else:
            ranking.append([name])
    ratio = None
    if "decode" in programs and "prefill_chunk" in programs:
        pre = float(programs["prefill_chunk"]["p50_s"])
        if pre > 0:
            ratio = float(programs["decode"]["p50_s"]) / pre
    return {
        "schema": SENTINEL_SCHEMA,
        "ranking": ranking,
        "decode_prefill_p50_ratio": ratio,
        "attributed_frac": float(payload.get("attributed_frac", 0.0)),
        "achieved_frac_ceiling": 1.5,
    }


def _load_sentinel(path: str) -> Tuple[Optional[Dict], List[Finding]]:
    if not os.path.exists(path):
        return None, [Finding(
            path, 1, 0, "PERF001",
            "no committed sentinel — run 'python -m tools.lint --perf "
            "PATH --update-baselines' and review the invariant diff it "
            "prints")]
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f), []
    except (OSError, json.JSONDecodeError) as e:
        return None, [Finding(path, 1, 0, "PERF001",
                              f"unreadable sentinel: {e}")]


def gate_findings(payload: Dict[str, Any],
                  sentinel_path: Optional[str] = None) -> List[Finding]:
    """Diff one ``perf_attr`` payload against the committed sentinel;
    the gate's whole verdict as findings ([] = clean)."""
    _ensure_repo_on_path()
    from singa_tpu.obs import schema as obs_schema

    from .hlo import FLAGSHIP_PROGRAMS, _baseline_suppressions

    path = sentinel_path or SENTINEL_PATH
    findings: List[Finding] = []

    # payload shape first: a malformed payload cannot support any
    # invariant check, so PERF001 short-circuits
    try:
        obs_schema.validate_perf_attr_payload(payload)
    except obs_schema.SchemaError as e:
        return [Finding(path, 1, 0, "PERF001", f"payload invalid: {e}")]
    stray = sorted(set(payload["programs"]) - set(FLAGSHIP_PROGRAMS))
    if stray:
        return [Finding(
            path, 1, 0, "PERF001",
            f"program key(s) {stray} are not flagship programs "
            f"(known: {list(FLAGSHIP_PROGRAMS)}) — the cost model "
            f"never lowered them, so there is no modeled side to "
            f"reconcile")]

    base, bad = _load_sentinel(path)
    if base is None:
        return bad
    if base.get("schema") != SENTINEL_SCHEMA:
        return [Finding(
            path, 1, 0, "PERF001",
            f"sentinel schema {base.get('schema')!r} does not match "
            f"the gate's {SENTINEL_SCHEMA} — regenerate with "
            f"--update-baselines")]
    waived, findings = _baseline_suppressions(base, path, PERF_CODES,
                                              "PERF000")
    cur = sentinel_summary(payload)

    def fnd(code: str, msg: str) -> None:
        if code in waived:
            return
        findings.append(Finding(
            path, 1, 0, code,
            f"{msg} — if intentional, re-baseline with 'python -m "
            f"tools.lint --perf PATH --update-baselines'"))

    # PERF002 completeness: wide floor, hard ceiling
    frac = cur["attributed_frac"]
    committed_frac = float(base.get("attributed_frac", 0.0))
    if frac > COMPLETENESS_CEILING:
        fnd("PERF002",
            f"attributed_frac {frac:.3f} exceeds the window itself "
            f"(ceiling {COMPLETENESS_CEILING}) — per-program totals "
            f"double-count the enclosing span")
    elif frac < committed_frac * COMPLETENESS_BAND:
        fnd("PERF002",
            f"attributed_frac {frac:.3f} fell below "
            f"{COMPLETENESS_BAND}x the committed {committed_frac:.3f} "
            f"— a dispatch path lost its attribution seam")

    # PERF003 ranking: cross-TIER and DECISIVE — programs sharing a
    # committed tier were near-ties at commit and never gate against
    # each other; across tiers, a committed-cheaper program costing
    # more than RANK_MARGIN x a committed-dearer one flips cost class
    cur_p50 = {n: float(payload["programs"][n]["p50_s"])
               for n in payload["programs"]}
    tiers = [[p for p in ([t] if isinstance(t, str) else t)
              if p in cur_p50]
             for t in base.get("ranking", [])]
    for i, dear_tier in enumerate(tiers):
        for dear in dear_tier:
            for cheap_tier in tiers[i + 1:]:
                for cheap in cheap_tier:
                    if cur_p50[cheap] > RANK_MARGIN * cur_p50[dear]:
                        fnd("PERF003",
                            f"p50 ranking flipped decisively: "
                            f"committed {dear} >= {cheap} (separate "
                            f"tiers), measured {cheap} p50 "
                            f"{cur_p50[cheap] * 1e3:.3f} ms > "
                            f"{RANK_MARGIN}x {dear} "
                            f"{cur_p50[dear] * 1e3:.3f} ms (a program "
                            f"changed cost class)")

    # PERF004 decode/prefill ratio: wide multiplicative band
    committed_ratio = base.get("decode_prefill_p50_ratio")
    ratio = cur["decode_prefill_p50_ratio"]
    if committed_ratio and ratio is not None:
        lo, hi = committed_ratio / RATIO_BAND, committed_ratio * RATIO_BAND
        if not (lo <= ratio <= hi):
            fnd("PERF004",
                f"decode/prefill p50 ratio {ratio:.4f} left the "
                f"committed band [{lo:.4f}, {hi:.4f}] (committed "
                f"{committed_ratio:.4f} x{RATIO_BAND} either way)")

    # PERF005 achieved-fraction sanity per program
    ceiling = float(base.get("achieved_frac_ceiling", 1.5))
    for name in sorted(payload["programs"]):
        af = float(payload["programs"][name]["achieved_flops_frac"])
        if not (0.0 < af <= ceiling):
            fnd("PERF005",
                f"[{name}] achieved_flops_frac {af:.4g} outside "
                f"(0, {ceiling}] — a broken clock or a garbage "
                f"model join, not a box-speed effect")
    return sorted(findings, key=lambda f: (f.code, f.message))


def update_baseline(payload: Dict[str, Any],
                    sentinel_path: Optional[str] = None) -> str:
    """Write the payload's invariant summary as the new sentinel
    (preserving the ``suppress`` block and the committed
    achieved-fraction ceiling) and return the human-readable invariant
    diff — the reviewed artifact of an intentional change."""
    path = sentinel_path or SENTINEL_PATH
    os.makedirs(os.path.dirname(path), exist_ok=True)
    old, _bad = _load_sentinel(path)
    cur = sentinel_summary(payload)
    lines: List[str] = []
    if old is None:
        lines.append(f"sentinel: NEW (ranking {cur['ranking']}, "
                     f"decode/prefill p50 ratio "
                     f"{cur['decode_prefill_p50_ratio']}, "
                     f"attributed_frac {cur['attributed_frac']:.3f})")
    else:
        for key in ("ranking", "decode_prefill_p50_ratio",
                    "attributed_frac"):
            if old.get(key) != cur.get(key):
                lines.append(f"sentinel: {key}: {old.get(key)!r} -> "
                             f"{cur.get(key)!r}")
        if not lines:
            lines.append("sentinel: unchanged")
        if old.get("suppress"):
            cur["suppress"] = old["suppress"]
        if "achieved_frac_ceiling" in old:
            cur["achieved_frac_ceiling"] = old["achieved_frac_ceiling"]
    with open(path, "w", encoding="utf-8") as f:
        json.dump(cur, f, indent=2, sort_keys=True)
        f.write("\n")
    return "\n".join(lines)


def engine_features(engine) -> Dict[str, Dict]:
    """Per-program analytic features of a LIVE serve engine's OWN
    programs: lower through ``ServeEngine.lower_programs()`` (abstract
    — nothing executes, jit caches untouched) and run the cost model
    over the optimized texts, so the modeled flops/HBM side of the
    reconciliation matches the configs actually serving — not the
    audit's tiny flagship configs."""
    from . import cost

    texts = {name: low.compile().as_text()
             for name, low in engine.lower_programs().items()}
    return cost.cost_features(texts=texts)


def load_payload(path: str) -> Dict[str, Any]:
    """The perf_attr payload of a dump file: a bare payload object or
    a full record entry (``{"kind": "perf_attr", "payload": ...}``)
    both work — ``bench.py --perf-attr`` writes the former, records
    plucked from the store arrive as the latter."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "programs" not in doc \
            and isinstance(doc.get("payload"), dict):
        doc = doc["payload"]
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a perf_attr payload object")
    return doc


def perf_main(path: str, update: bool = False,
              json_out: bool = False,
              sentinel_path: Optional[str] = None) -> int:
    """CLI body behind ``python -m tools.lint --perf PATH``: 0 clean,
    1 findings (exit codes follow the lint front door)."""
    from .framework import render_human, render_json

    try:
        payload = load_payload(path)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        raise RuntimeError(f"--perf: {e}")
    if update:
        print(update_baseline(payload, sentinel_path))
        print(f"perfattr: sentinel updated at "
              f"{sentinel_path or SENTINEL_PATH} — review the diff "
              f"above")
        return 0
    findings = gate_findings(payload, sentinel_path)
    print(render_json(findings) if json_out
          else render_human(findings).replace("singalint:", "perfattr:"))
    return 1 if findings else 0
