"""hlocost — static cost & memory model over lowered HLO (ISSUE 9).

hloaudit (tools/lint/hlo.py) answers *what* XLA emitted; this module
answers *how much* it costs.  Memory traffic — not flops — is what
fusion decisions actually optimize ("Operator Fusion in XLA",
arXiv:2301.13062), and analytic per-op features (flops, bytes,
arithmetic intensity) are exactly the inputs a learned TPU performance
model consumes ("A Learned Performance Model for TPUs",
arXiv:2008.01040).  Per flagship program, from the SAME optimized-HLO
text hloaudit lowers (lower once, audit twice), it computes:

* **flops** — from ``dot``/``convolution`` shapes and contraction dims,
  weighted by execution multiplicity (fusion call sites, and while-loop
  trip counts taken from XLA's ``known_trip_count`` backend config);
* **HBM traffic** — bytes read/written at fusion boundaries: for every
  materializing instruction in a *scheduled* computation (entry, while
  bodies — NOT the interiors of fused computations, which stay in
  registers/cache), operand bytes + output bytes, trip-weighted.  Plus
  per-fusion arithmetic intensity and a roofline class (memory- vs
  compute-bound against :data:`RIDGE_FLOPS_PER_BYTE`);
* **peak live memory** — a liveness scan over the entry computation's
  instruction schedule (``is_scheduled=true`` HLO: text order IS the
  schedule).  Buffer sizes come from shapes/dtypes; pure-aliasing ops
  (``bitcast``/``tuple``/``get-tuple-element``) allocate nothing; outputs
  donated via ``input_output_alias`` write into their parameter's buffer
  and are excluded from the peak — so a LOST donation (the KV arena, the
  optimizer state) visibly inflates this number;
* **collective wire bytes per participant** — ring-algorithm cost per
  collective (all-reduce ``2(P-1)/P``, all-gather/reduce-scatter
  ``(P-1)/P``, permute ``1``) with ``P`` parsed from ``replica_groups``.
  The committed 2-way-DP train-step number is the f32 baseline ROADMAP
  item 2's ``compression="int8_ring"`` will be diffed against.

Results are gated against committed per-program baselines under
``tools/lint/data/hlo/cost/`` with a ``COST00x`` finding family —
RELATIVE tolerances per metric (lowering is deterministic for a fixed
config; the tolerance absorbs cross-version XLA jitter, not intent
drift), the same suppression/waiver contract as the HLO gate, and the
same ``--update-baselines`` flow.  :func:`cost_features` exports the
per-program feature dict the ROADMAP item-4 autotuner trains on.

Scope limits (docs/static-analysis.md "Cost gate"): CPU lowerings with
tiny configs — the numbers gate *relative* drift and feed feature
extraction; they are not latency claims, and TPU-specific passes
(Pallas custom-calls, ICI scheduling) are invisible here.

Everything is purely textual — importing this module never imports jax.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .framework import Finding

__all__ = ["COST_CODES", "COST_SCHEMA", "COST_BASELINE_DIR", "TOLERANCES",
           "RIDGE_FLOPS_PER_BYTE", "parse_module", "summarize_cost",
           "cost_summaries", "diff_cost", "cost_gate_findings",
           "update_cost_baselines", "cost_features", "shape_bytes"]

#: committed per-program cost baselines, next to the structural ones
COST_BASELINE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "data", "hlo", "cost")

#: summary format version — a baseline with another version fails the
#: gate (COST001) instead of diffing garbage
COST_SCHEMA = 1

#: finding codes, one per metric (enumerated by ``--list-rules``)
COST_CODES = {
    "COST000": ("suppression-hygiene", "a cost-baseline 'suppress' entry "
                "without a reason, or naming an unknown metric code, is "
                "itself a finding and cannot be waived"),
    "COST001": ("program-set", "every audited program has a committed, "
                "parseable, same-schema cost baseline — and every "
                "baseline has a lowered program"),
    "COST002": ("flops", "analytic flops (dot/convolution shapes x "
                "contraction dims, trip-weighted) stay within tolerance "
                "of the baseline"),
    "COST003": ("hbm-traffic", "bytes read/written at fusion boundaries "
                "(trip-weighted) stay within tolerance of the baseline"),
    "COST004": ("peak-memory", "peak live bytes over the entry schedule "
                "(donation-aliased outputs excluded) and donated output "
                "bytes stay within tolerance — a lost donation lands "
                "here with its byte cost"),
    "COST005": ("wire-bytes", "collective wire bytes per participant "
                "(ring model over replica_groups) stay within tolerance "
                "— the f32 DP baseline for int8-ring comparisons"),
    "COST006": ("roofline", "the program's roofline class and per-fusion "
                "memory-/compute-bound split match the baseline"),
}

#: relative drift tolerance per gated metric.  Lowerings are
#: deterministic for a fixed config, so these absorb only XLA-version
#: jitter; a config/mesh change moves the numbers far past them.
TOLERANCES = {
    "COST002": 0.02,   # flops
    "COST003": 0.02,   # hbm bytes
    "COST004": 0.02,   # peak bytes
    "COST005": 0.01,   # wire bytes
}

#: nominal machine balance (flops per HBM byte) separating memory-bound
#: from compute-bound — a documented classification constant for the
#: roofline class, not a measured latency model.  Real accelerators sit
#: at O(100) flops/byte; the tiny audited configs run far below it, so
#: a program flipping class means its shape regime genuinely changed.
RIDGE_FLOPS_PER_BYTE = 16.0

#: bytes per element for HLO primitive types
_DTYPE_BYTES = {
    "pred": 1, "s2": 1, "u2": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "tf32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
    "f8e4m3": 1, "f8e5m2": 1, "f8e4m3fn": 1, "f8e4m3b11fnuz": 1,
    "f8e5m2fnuz": 1, "f8e4m3fnuz": 1,
    # token/opaque carry no data
    "token": 0, "opaque": 0,
}

#: opcodes that never allocate: pure views over their operands
_ALIAS_OPS = frozenset({"bitcast", "tuple", "get-tuple-element"})

#: opcodes excluded from the HBM-traffic sum on top of the alias ops
#: (parameters are read by their consumers, not by themselves; constants
#: materialize at compile time)
_NO_TRAFFIC_OPS = _ALIAS_OPS | {"parameter", "constant"}

#: per-participant wire-cost factor of the ring algorithm, as a function
#: of group size P — the committed f32 reference model (int8-ring halves
#: the payload term, not the factor)
_WIRE_FACTOR = {
    "all-reduce": lambda p: 2.0 * (p - 1) / p,
    "all-reduce-start": lambda p: 2.0 * (p - 1) / p,
    "all-gather": lambda p: (p - 1) / p,
    "all-gather-start": lambda p: (p - 1) / p,
    "reduce-scatter": lambda p: (p - 1) / p,
    "all-to-all": lambda p: (p - 1) / p,
    "collective-broadcast": lambda p: (p - 1) / p,
    "collective-permute": lambda p: 1.0,
    "collective-permute-start": lambda p: 1.0,
}


# ---------------------------------------------------------------------------
# shapes
# ---------------------------------------------------------------------------

_LEAF_SHAPE_RE = re.compile(r"([a-z]\w*)\[([\d,]*)\]")


def _leaf_bytes(dtype: str, dims_str: str) -> int:
    size = _DTYPE_BYTES.get(dtype)
    if size is None:
        return 0                      # unknown leaf type: count nothing
    n = 1
    for d in dims_str.split(","):
        if d:
            n *= int(d)
    return n * size


def shape_bytes(shape: str) -> int:
    """Buffer bytes of one HLO shape string — a leaf like
    ``f32[2,16]{1,0}`` or a tuple ``(s32[], f32[30,256]{1,0}, ...)``
    (layouts and ``/*index=N*/`` comments ignored)."""
    return sum(_leaf_bytes(dt, dims)
               for dt, dims in _LEAF_SHAPE_RE.findall(shape))


def _shape_dims(shape: str) -> List[int]:
    """Dims of a LEAF shape (first leaf if somehow a tuple)."""
    m = _LEAF_SHAPE_RE.search(shape)
    if m is None:
        return []
    return [int(d) for d in m.group(2).split(",") if d]


def _prod(xs: Iterable[int]) -> int:
    out = 1
    for x in xs:
        out *= x
    return out


# ---------------------------------------------------------------------------
# HLO text -> module IR
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Instr:
    name: str
    opcode: str
    shape: str
    operands: Tuple[str, ...]         # referenced instruction names
    attrs: str                        # everything after the operand list
    is_root: bool


@dataclasses.dataclass
class Module:
    computations: Dict[str, List[Instr]]
    entry: Optional[str]
    #: (root output tuple index or None, parameter number) per donation
    aliases: List[Tuple[Optional[int], int]]
    num_partitions: int


_COMP_HEADER_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->")
_INSTR_HEAD_RE = re.compile(r"^\s+(ROOT\s+)?%?([\w.\-]+)\s*=\s*(.*)$")
_OPERAND_RE = re.compile(r"%([\w.\-]+)")
_ALIAS_ENTRY_RE = re.compile(r"\{([\d,\s]*)\}:\s*\((\d+)")
_TRIP_COUNT_RE = re.compile(r'known_trip_count\D{0,8}(\d+)')


def _split_rhs(rhs: str) -> Optional[Tuple[str, str, str, str]]:
    """``shape opcode(args), attrs`` -> (shape, opcode, args, attrs).
    Handles tuple shapes (balanced parens) and nested parens in args."""
    rhs = rhs.strip()
    if rhs.startswith("("):           # tuple shape: find its close paren
        depth, i = 0, 0
        while i < len(rhs):
            if rhs[i] == "(":
                depth += 1
            elif rhs[i] == ")":
                depth -= 1
                if depth == 0:
                    break
            i += 1
        shape, rest = rhs[:i + 1], rhs[i + 1:].lstrip()
    else:
        sp = rhs.find(" ")
        if sp < 0:
            return None
        shape, rest = rhs[:sp], rhs[sp + 1:].lstrip()
    op_end = rest.find("(")
    if op_end <= 0:
        return None
    opcode = rest[:op_end]
    if not re.fullmatch(r"[a-z][a-z0-9\-]*", opcode):
        return None
    depth, i = 0, op_end
    while i < len(rest):
        if rest[i] == "(":
            depth += 1
        elif rest[i] == ")":
            depth -= 1
            if depth == 0:
                break
        i += 1
    args = rest[op_end + 1:i]
    attrs = rest[i + 1:].lstrip(", ")
    return shape, opcode, args, attrs


def parse_module(text: str) -> Module:
    """Parse one optimized-HLO module's text into the cost IR.  Purely
    textual — no jax, no XLA."""
    comps: Dict[str, List[Instr]] = {}
    entry: Optional[str] = None
    cur: Optional[str] = None
    for line in text.splitlines():
        if line and not line[0].isspace():
            mh = _COMP_HEADER_RE.match(line)
            if mh:
                cur = mh.group(2)
                comps.setdefault(cur, [])
                if mh.group(1):
                    entry = cur
            continue
        mi = _INSTR_HEAD_RE.match(line)
        if mi is None or cur is None:
            continue
        parts = _split_rhs(mi.group(3))
        if parts is None:
            continue
        shape, opcode, args, attrs = parts
        comps[cur].append(Instr(
            name=mi.group(2), opcode=opcode, shape=shape,
            operands=tuple(_OPERAND_RE.findall(args)), attrs=attrs,
            is_root=bool(mi.group(1))))

    aliases: List[Tuple[Optional[int], int]] = []
    marker = text.find("input_output_alias={")
    if marker >= 0:
        # scan the balanced {...} block (entries nest one level deep)
        start = marker + len("input_output_alias=")
        depth, i = 0, start
        while i < len(text):
            if text[i] == "{":
                depth += 1
            elif text[i] == "}":
                depth -= 1
                if depth == 0:
                    break
            i += 1
        block = text[start:i + 1]
        for m in _ALIAS_ENTRY_RE.finditer(block):
            idx = m.group(1).strip()
            out_idx = int(idx.split(",")[0]) if idx else None
            aliases.append((out_idx, int(m.group(2))))

    mp = re.search(r"num_partitions=(\d+)", text)
    return Module(computations=comps, entry=entry, aliases=aliases,
                  num_partitions=int(mp.group(1)) if mp else 1)


# ---------------------------------------------------------------------------
# execution multiplicity (call graph + known trip counts)
# ---------------------------------------------------------------------------

_CALLEE_ATTR_RE = re.compile(
    r"(calls|body|condition|to_apply|branch_computations|"
    r"true_computation|false_computation)=\{?%?([\w.\-]+)"
    r"((?:,\s*%?[\w.\-]+)*)\}?")


def _callees(instr: Instr) -> List[Tuple[str, str]]:
    """(attr, computation) pairs an instruction calls."""
    out = []
    for m in _CALLEE_ATTR_RE.finditer(instr.attrs):
        out.append((m.group(1), m.group(2)))
        for extra in re.findall(r"%?([\w.\-]+)", m.group(3) or ""):
            out.append((m.group(1), extra))
    return out


def _trip_count(instr: Instr) -> int:
    m = _TRIP_COUNT_RE.search(instr.attrs)
    return int(m.group(1)) if m else 1


def computation_multiplicities(mod: Module) -> Dict[str, int]:
    """How many times each computation executes per program run:
    entry once; fusion/call/conditional/to_apply callees inherit their
    caller's count per call site; while bodies multiply by XLA's
    ``known_trip_count`` (1 when absent — an honest lower bound)."""
    mult: Dict[str, int] = {}
    if mod.entry is None:
        return mult
    frontier: List[Tuple[str, int]] = [(mod.entry, 1)]
    while frontier:
        comp, n = frontier.pop()
        mult[comp] = mult.get(comp, 0) + n
        for instr in mod.computations.get(comp, ()):
            trip = _trip_count(instr) if instr.opcode == "while" else 1
            for attr, callee in _callees(instr):
                if callee not in mod.computations:
                    continue
                k = n * trip if attr in ("body", "condition") else n
                frontier.append((callee, k))
    return mult


def _scheduled_computations(mod: Module) -> set:
    """Computations whose instructions materialize buffers (entry +
    while bodies/conditions + call/conditional targets) — fusion
    interiors and reduce to_apply regions live in registers and are
    reached only through their caller's boundary."""
    sched: set = set()
    if mod.entry is None:
        return sched
    frontier = [mod.entry]
    while frontier:
        comp = frontier.pop()
        if comp in sched:
            continue
        sched.add(comp)
        for instr in mod.computations.get(comp, ()):
            for attr, callee in _callees(instr):
                if attr in ("body", "condition", "branch_computations",
                            "true_computation", "false_computation") \
                        and callee in mod.computations:
                    frontier.append(callee)
                # plain call: scheduled too
                if attr == "to_apply" and instr.opcode == "call" \
                        and callee in mod.computations:
                    frontier.append(callee)
    return sched


# ---------------------------------------------------------------------------
# flops
# ---------------------------------------------------------------------------

_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([\d,]*)\}")
_DIM_LABELS_RE = re.compile(r"dim_labels=(\w+)_(\w+)->(\w+)")


def _def_map(instrs: Sequence[Instr]) -> Dict[str, Instr]:
    return {i.name: i for i in instrs}

def _instr_flops(instr: Instr, defs: Dict[str, Instr]) -> int:
    """Analytic flops of one dot/convolution (0 for everything else):
    2 x output elements x contraction size."""
    if instr.opcode == "dot":
        out = _prod(_shape_dims(instr.shape))
        mc = _CONTRACT_RE.search(instr.attrs)
        contract = 1
        if mc and instr.operands:
            lhs = defs.get(instr.operands[0])
            dims = _shape_dims(lhs.shape) if lhs else []
            for ax in mc.group(1).split(","):
                if ax and int(ax) < len(dims):
                    contract *= dims[int(ax)]
        return 2 * out * contract
    if instr.opcode == "convolution":
        out_dims = _shape_dims(instr.shape)
        out = _prod(out_dims)
        kernel_elems, out_channels = 1, 1
        if len(instr.operands) >= 2:
            rhs = defs.get(instr.operands[1])
            kdims = _shape_dims(rhs.shape) if rhs else []
            kernel_elems = _prod(kdims)
            ml = _DIM_LABELS_RE.search(instr.attrs)
            if ml and kdims:
                o_pos = ml.group(2).find("o")
                if 0 <= o_pos < len(kdims):
                    out_channels = kdims[o_pos]
            elif kdims:
                out_channels = kdims[-1]
        return 2 * out * kernel_elems // max(out_channels, 1)
    return 0


def _computation_flops(mod: Module, comp: str,
                       seen: Optional[Dict[str, int]] = None) -> int:
    """Flops of ONE execution of a computation, recursing through every
    call edge (x trip count for while bodies)."""
    seen = {} if seen is None else seen
    if comp in seen:
        return seen[comp]
    seen[comp] = 0                    # cycles cannot occur in HLO; guard anyway
    instrs = mod.computations.get(comp, [])
    defs = _def_map(instrs)
    total = 0
    for instr in instrs:
        total += _instr_flops(instr, defs)
        trip = _trip_count(instr) if instr.opcode == "while" else 1
        for attr, callee in _callees(instr):
            if callee not in mod.computations:
                continue
            k = trip if attr in ("body", "condition") else 1
            total += k * _computation_flops(mod, callee, seen)
    seen[comp] = total
    return total


# ---------------------------------------------------------------------------
# HBM traffic + per-fusion roofline
# ---------------------------------------------------------------------------

def _instr_traffic(instr: Instr, defs: Dict[str, Instr]) -> int:
    """Bytes read + written at one materializing instruction's boundary
    (unique operands counted once)."""
    read = sum(shape_bytes(defs[o].shape)
               for o in dict.fromkeys(instr.operands) if o in defs)
    return read + shape_bytes(instr.shape)


def _fusion_rows(mod: Module, mult: Dict[str, int]) -> List[Dict]:
    """Per-fusion cost rows: boundary bytes, interior flops, intensity,
    roofline class — trip-weighted by the caller's multiplicity."""
    rows: List[Dict] = []
    seen_flops: Dict[str, int] = {}
    for comp in _scheduled_computations(mod):
        defs = _def_map(mod.computations.get(comp, []))
        n = mult.get(comp, 1)
        for instr in mod.computations.get(comp, []):
            if instr.opcode != "fusion":
                continue
            callee = next((c for a, c in _callees(instr) if a == "calls"),
                          None)
            flops = (n * _computation_flops(mod, callee, seen_flops)
                     if callee else 0)
            traffic = n * _instr_traffic(instr, defs)
            intensity = flops / traffic if traffic else 0.0
            rows.append({
                "name": instr.name, "bytes": traffic, "flops": flops,
                "intensity": round(intensity, 4),
                "class": ("compute-bound"
                          if intensity >= RIDGE_FLOPS_PER_BYTE
                          else "memory-bound"),
            })
    return rows


def _hbm_bytes(mod: Module, mult: Dict[str, int]) -> int:
    total = 0
    for comp in _scheduled_computations(mod):
        instrs = mod.computations.get(comp, [])
        defs = _def_map(instrs)
        n = mult.get(comp, 1)
        for instr in instrs:
            if instr.opcode in _NO_TRAFFIC_OPS:
                continue
            total += n * _instr_traffic(instr, defs)
    return total


# ---------------------------------------------------------------------------
# peak live memory (entry-schedule liveness, donation-aware)
# ---------------------------------------------------------------------------

def peak_live_bytes(mod: Module) -> int:
    """Max over the entry schedule of the live-buffer byte sum.

    Model: each non-alias instruction allocates its output buffer at its
    schedule index and frees it after its last (alias-transitive) use.
    Entry parameters and the root's buffers are live for the WHOLE
    program — the caller owns argument and result buffers across the
    call, which is the runtime contract jax dispatch actually has.
    Outputs aliased to a parameter via ``input_output_alias`` allocate
    NOTHING — they write into the donated parameter in place — which is
    exactly why a lost donation inflates this number by the donated
    buffer's size: the result needs its own allocation on top of the
    still-live argument."""
    if mod.entry is None:
        return 0
    instrs = mod.computations.get(mod.entry, [])
    defs = _def_map(instrs)
    index = {i.name: k for k, i in enumerate(instrs)}

    # alias-transitive underlying allocations of each value
    underlying: Dict[str, Tuple[str, ...]] = {}
    for instr in instrs:
        if instr.opcode in _ALIAS_OPS:
            u: List[str] = []
            for o in instr.operands:
                u.extend(underlying.get(o, (o,) if o in defs else ()))
            underlying[instr.name] = tuple(dict.fromkeys(u))
        else:
            underlying[instr.name] = (instr.name,)

    last_use: Dict[str, int] = {}
    root: Optional[Instr] = None
    for k, instr in enumerate(instrs):
        if instr.is_root:
            root = instr
        for o in instr.operands:
            for b in underlying.get(o, ()):
                last_use[b] = max(last_use.get(b, 0), k)

    end = len(instrs)
    # donated outputs: the producing buffer writes into its parameter
    donated_bufs: set = set()
    if root is not None and mod.aliases:
        root_ops = root.operands
        for out_idx, _param_no in mod.aliases:
            src = None
            if out_idx is None:
                src = root.name
            elif out_idx < len(root_ops):
                src = root_ops[out_idx]
            if src is not None:
                donated_bufs.update(underlying.get(src, ()))
    if root is not None:
        for b in underlying.get(root.name, ()):
            last_use[b] = end         # result buffers: live to the end

    delta = [0] * (end + 2)
    for k, instr in enumerate(instrs):
        if instr.opcode in _ALIAS_OPS:
            continue
        size = shape_bytes(instr.shape)
        if size <= 0:
            continue
        if instr.name in donated_bufs and instr.opcode != "parameter":
            continue                  # writes into its parameter in place
        if instr.opcode == "parameter":
            start, stop = 0, end      # caller-owned across the call
        else:
            start, stop = k, last_use.get(instr.name, k)
        delta[start] += size
        delta[stop + 1] -= size
    peak = live = 0
    for d in delta:
        live += d
        peak = max(peak, live)
    return peak


def donated_bytes(mod: Module) -> int:
    """Bytes of entry outputs aliased to parameters — what donation
    saves per dispatch.  The structural gate (HLO005) counts the alias
    ENTRIES; this weighs them: a lost KV-arena or opt-state donation
    means the result needs its own allocation on top of the still-live
    argument, inflating peak live memory by exactly this many bytes."""
    if mod.entry is None or not mod.aliases:
        return 0
    instrs = mod.computations.get(mod.entry, [])
    defs = _def_map(instrs)
    root = next((i for i in instrs if i.is_root), None)
    if root is None:
        return 0
    total = 0
    for out_idx, _param_no in mod.aliases:
        if out_idx is None:
            total += shape_bytes(root.shape)
        elif out_idx < len(root.operands):
            op = defs.get(root.operands[out_idx])
            if op is not None:
                total += shape_bytes(op.shape)
    return total


# ---------------------------------------------------------------------------
# collective wire bytes
# ---------------------------------------------------------------------------

_REPLICA_GROUPS_RE = re.compile(r"replica_groups=\{\{([\d,]+)\}")


def _group_size(instr: Instr, mod: Module) -> int:
    m = _REPLICA_GROUPS_RE.search(instr.attrs)
    if m:
        return len(m.group(1).split(","))
    return max(mod.num_partitions, 1)


def wire_bytes_per_participant(mod: Module, mult: Dict[str, int]) -> int:
    """Ring-model wire bytes one participant sends, summed over every
    collective (trip-weighted).  ``*-done`` ops carry no new payload."""
    total = 0.0
    for comp, instrs in mod.computations.items():
        n = mult.get(comp, 0)
        if n == 0:
            continue
        defs = _def_map(instrs)
        for instr in instrs:
            factor = _WIRE_FACTOR.get(instr.opcode)
            if factor is None:
                continue
            p = _group_size(instr, mod)
            if p <= 1:
                continue
            if instr.opcode == "reduce-scatter":
                payload = sum(shape_bytes(defs[o].shape)
                              for o in dict.fromkeys(instr.operands)
                              if o in defs)
            else:
                payload = shape_bytes(instr.shape)
            total += n * factor(p) * payload
    return int(round(total))


# ---------------------------------------------------------------------------
# the per-program cost summary
# ---------------------------------------------------------------------------

def summarize_cost(text: str, program: str) -> Dict:
    """One optimized-HLO module's analytic cost summary — the committed,
    gated artifact.  Deterministic for a fixed lowering."""
    mod = parse_module(text)
    mult = computation_multiplicities(mod)
    flops = _computation_flops(mod, mod.entry) if mod.entry else 0
    hbm = _hbm_bytes(mod, mult)
    fusions = _fusion_rows(mod, mult)
    classes = {"memory_bound": 0, "compute_bound": 0}
    for row in fusions:
        classes["memory_bound" if row["class"] == "memory-bound"
                else "compute_bound"] += 1
    intensity = flops / hbm if hbm else 0.0
    return {
        "schema": COST_SCHEMA,
        "program": program,
        "flops": int(flops),
        "hbm_bytes": int(hbm),
        "intensity": round(intensity, 4),
        "roofline": ("compute-bound" if intensity >= RIDGE_FLOPS_PER_BYTE
                     else "memory-bound"),
        "fusion_classes": classes,
        "peak_bytes": int(peak_live_bytes(mod)),
        "donated_bytes": int(donated_bytes(mod)),
        "wire_bytes": wire_bytes_per_participant(mod, mult),
    }


def cost_summaries(texts: Dict[str, str]) -> Dict[str, Dict]:
    """Cost summary per program from already-lowered HLO texts — the
    "lower once, audit twice" half: callers hand over the SAME texts
    the structural gate summarizes."""
    return {name: summarize_cost(text, name)
            for name, text in texts.items()}


# ---------------------------------------------------------------------------
# gate: baselines, tolerance diff, update flow
# ---------------------------------------------------------------------------

def diff_cost(program: str, baseline: Dict, current: Dict,
              path: str) -> List[Finding]:
    """Named COST00x finding per metric drifted past its tolerance."""
    from .hlo import _baseline_suppressions
    waived, findings = _baseline_suppressions(
        baseline, path, COST_CODES, "COST000")

    def fnd(code: str, msg: str) -> None:
        if code in waived:
            return
        findings.append(Finding(path, 1, 0, code,
                                f"[{program}] {msg} — if intentional, "
                                f"re-baseline with 'python -m tools.lint "
                                f"--hlo --update-baselines'"))

    if baseline.get("schema") != current.get("schema"):
        findings.append(Finding(
            path, 1, 0, "COST001",
            f"[{program}] cost baseline schema {baseline.get('schema')!r} "
            f"does not match the auditor's {current.get('schema')!r} — "
            f"regenerate with --update-baselines"))
        return findings

    def rel(code: str, field: str, what: str, unit: str = "") -> None:
        b, c = baseline.get(field), current.get(field)
        if not isinstance(b, (int, float)) or isinstance(b, bool):
            fnd(code, f"baseline {field!r} is {b!r}, not a number — "
                      f"regenerate with --update-baselines")
            return
        tol = TOLERANCES[code]
        drift = abs((c or 0) - b) / max(abs(b), 1.0)
        if drift > tol:
            pct = 100.0 * ((c or 0) - b) / max(abs(b), 1.0)
            fnd(code, f"{what} drifted {b:,}{unit} -> {c:,}{unit} "
                      f"({pct:+.1f}%, tolerance {tol:.0%})")

    rel("COST002", "flops", "analytic flops")
    rel("COST003", "hbm_bytes", "HBM traffic", " B")
    rel("COST004", "peak_bytes", "peak live memory", " B")
    b, c = baseline.get("donated_bytes"), current.get("donated_bytes")
    if isinstance(b, (int, float)) and (c or 0) < b and \
            (b - (c or 0)) / max(b, 1.0) > TOLERANCES["COST004"]:
        fnd("COST004",
            f"donated output bytes dropped {b:,} B -> {c or 0:,} B — a "
            f"donation was LOST: the result (KV arena / opt state) now "
            f"needs its own allocation on top of the still-live "
            f"argument, inflating peak live memory by {b - (c or 0):,} B "
            f"every dispatch")
    rel("COST005", "wire_bytes", "collective wire bytes/participant",
        " B")
    if baseline.get("roofline") != current.get("roofline") or \
            baseline.get("fusion_classes") != current.get("fusion_classes"):
        fnd("COST006",
            f"roofline drifted: {baseline.get('roofline')} "
            f"{baseline.get('fusion_classes')} -> "
            f"{current.get('roofline')} {current.get('fusion_classes')}")
    return findings


def cost_gate_findings(summaries: Dict[str, Dict],
                       baseline_dir: Optional[str] = None) -> List[Finding]:
    """Diff cost summaries against the committed baselines; [] = clean.
    Shares the structural gate's program-set core
    (hlo.gate_findings_dir — misses loud in both directions, COST001)."""
    from .hlo import gate_findings_dir
    return gate_findings_dir(summaries,
                             baseline_dir or COST_BASELINE_DIR,
                             "COST001", "cost baseline", diff_cost,
                             "numbers")


def update_cost_baselines(summaries: Dict[str, Dict],
                          baseline_dir: Optional[str] = None) -> str:
    """Write the cost summaries as the new baselines via the shared
    update core (hlo.update_baselines_dir: suppress blocks preserved,
    stale programs pruned loudly, human-readable metric diff
    returned)."""
    from .hlo import update_baselines_dir
    return update_baselines_dir(
        summaries, baseline_dir or COST_BASELINE_DIR, "COST001",
        "cost baseline", diff_cost,
        lambda s: (f"{s['flops']:,} flops, {s['hbm_bytes']:,} B HBM, "
                   f"peak {s['peak_bytes']:,} B, wire "
                   f"{s['wire_bytes']:,} B, {s['roofline']}"),
        "cost unchanged")


# ---------------------------------------------------------------------------
# feature export (ROADMAP item 4: the autotuner's analytic inputs)
# ---------------------------------------------------------------------------

#: the stable feature keys :func:`cost_features` guarantees per program
#: — the analytic half of a learned performance model's input vector
#: (arXiv:2008.01040 §3: per-kernel flops/bytes/intensity features).
#: Numeric except ``roofline`` (the class string).
FEATURE_KEYS = ("flops", "hbm_bytes", "peak_bytes", "donated_bytes",
                "wire_bytes", "intensity", "roofline",
                "fusions_memory_bound", "fusions_compute_bound")


def cost_features(texts: Optional[Dict[str, str]] = None
                  ) -> Dict[str, Dict]:
    """Per-program analytic feature dict for the record-driven autotuner
    (ROADMAP item 4): exactly :data:`FEATURE_KEYS` per flagship program.

    Pass already-lowered ``texts`` to reuse an audit run's lowering;
    with no argument, lowers the flagship programs (ONE lowering pass,
    jax imported only then)."""
    if texts is None:
        from .hlo import lower_flagship_texts
        texts = lower_flagship_texts()
    out: Dict[str, Dict] = {}
    for name, summary in cost_summaries(texts).items():
        out[name] = {
            "flops": summary["flops"],
            "hbm_bytes": summary["hbm_bytes"],
            "peak_bytes": summary["peak_bytes"],
            "donated_bytes": summary["donated_bytes"],
            "wire_bytes": summary["wire_bytes"],
            "intensity": summary["intensity"],
            "roofline": summary["roofline"],
            "fusions_memory_bound": summary["fusion_classes"][
                "memory_bound"],
            "fusions_compute_bound": summary["fusion_classes"][
                "compute_bound"],
        }
    return out
