"""singalint rules — one per invariant PRs 1-4 established by hand.

| code   | name             | invariant                                      |
|--------|------------------|------------------------------------------------|
| SGL001 | jit-purity       | no host side effects reachable inside jax.jit  |
| SGL002 | donation-safety  | donated jit arguments are dead after the call  |
| SGL003 | recompile-hazard | no jax.jit in loops / .shape branching in jit  |
| SGL004 | (retired)        | thread-seam — folded into SGL010 (conc.py);    |
|        |                  | old disable=SGL004 suppressions fail loudly    |
| SGL005 | wall-clock       | time.time() is banned (monotonic-only rule)    |
| SGL006 | obs-kind         | record kinds are members of obs.schema._KINDS  |
| SGL007 | fault-site       | faults.fire/corrupt/tear sites are registered  |
| SGL008 | host-sync        | no device fetches in hot engine/runner loops   |
| SGL009 | flight-site      | flight-recorder dump sites are registered names|

Rules are module-local static analysis: each builds a one-level call
graph inside the file it lints (jit roots -> direct helper calls,
background entry points -> direct self-method calls) and never chases
imports — deep enough for every real seam in this codebase, shallow
enough to stay fast and predictable.  What a rule cannot see it does
not guess at: dynamic dispatch through variables, cross-module helpers
and exec'd code are out of scope by design (the dynamic checks —
tools/record_check.py, tools/ckpt_fsck.py, the chaos tests — cover the
runtime half).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .framework import Finding, Rule, register

_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))


# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------

def _module_cache(tree: ast.AST) -> Dict[str, object]:
    """Per-parse memo attached to the Module node itself.

    Every rule needs the same module-level artifacts (node list, import
    map, parent links, def table, jit roots); without sharing, seven
    rules each re-walk the full tree and the repo-wide run costs ~8 s —
    past the tier-1 budget for the repo-is-clean gate.  Caching on the
    tree is safe because each ``lint_source`` call parses afresh."""
    cache = getattr(tree, "_singalint_cache", None)
    if cache is None:
        cache = {}
        tree._singalint_cache = cache  # type: ignore[attr-defined]
    return cache


def module_nodes(tree: ast.AST) -> List[ast.AST]:
    """Flat pre-order node list, walked once per parse."""
    cache = _module_cache(tree)
    if "nodes" not in cache:
        cache["nodes"] = list(ast.walk(tree))
    return cache["nodes"]  # type: ignore[return-value]


def module_calls(tree: ast.AST) -> List[ast.Call]:
    cache = _module_cache(tree)
    if "calls" not in cache:
        cache["calls"] = [n for n in module_nodes(tree)
                          if isinstance(n, ast.Call)]
    return cache["calls"]  # type: ignore[return-value]


def dotted_name(node: ast.AST) -> Optional[str]:
    """'self.pool.caches' for nested Attributes over a Name; None for
    anything involving calls/subscripts."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def build_parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    cache = _module_cache(tree)
    if "parents" not in cache:
        parents: Dict[ast.AST, ast.AST] = {}
        for node in module_nodes(tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        cache["parents"] = parents
    return cache["parents"]  # type: ignore[return-value]


def import_map(tree: ast.Module) -> Dict[str, str]:
    """local name -> canonical dotted path, relative dots stripped and a
    leading ``singa_tpu.`` normalized away (so ``from ..obs import
    events`` and ``from singa_tpu.obs import events`` both canonicalize
    to ``obs.events``)."""
    cache = _module_cache(tree)
    if "imports" in cache:
        return cache["imports"]  # type: ignore[return-value]
    mods: Dict[str, str] = {}

    def canon(path: str) -> str:
        path = path.lstrip(".")
        if path.startswith("singa_tpu."):
            path = path[len("singa_tpu."):]
        return path

    for node in module_nodes(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                local = a.asname or a.name.split(".")[0]
                mods[local] = canon(a.name if a.asname else
                                    a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            for a in node.names:
                if a.name == "*":
                    continue
                local = a.asname or a.name
                full = f"{base}.{a.name}" if base else a.name
                mods[local] = canon(full)
    cache["imports"] = mods
    return mods


def resolve(node: ast.AST, imports: Dict[str, str]) -> Optional[str]:
    """Canonical dotted path of an expression ('events.counter' ->
    'obs.events.counter'), or None when it is not a plain dotted name.

    The ``singa_tpu.`` prefix is stripped here as well as at
    import-statement time: ``import singa_tpu.obs.events`` leaves the
    local head as plain ``singa_tpu``, so the full attribute path only
    canonicalizes at use sites."""
    d = dotted_name(node)
    if d is None:
        return None
    head, _, rest = d.partition(".")
    base = imports.get(head, head)
    full = f"{base}.{rest}" if rest else base
    if full.startswith("singa_tpu."):
        full = full[len("singa_tpu."):]
    return full


def _is_jax_jit(call: ast.Call, imports: Dict[str, str]) -> bool:
    full = resolve(call.func, imports)
    if full == "jax.jit":
        return True
    # partial(jax.jit, static_argnums=...) used as a decorator factory
    if full in ("functools.partial", "partial") and call.args:
        return resolve(call.args[0], imports) == "jax.jit"
    return False


def _collect_defs(tree: ast.Module) -> Dict[str, List[ast.FunctionDef]]:
    cache = _module_cache(tree)
    if "defs" not in cache:
        defs: Dict[str, List[ast.FunctionDef]] = {}
        for node in module_nodes(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)
        cache["defs"] = defs
    return cache["defs"]  # type: ignore[return-value]


def _class_of(node: ast.AST,
              parents: Dict[ast.AST, ast.AST]) -> Optional[ast.ClassDef]:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur
        cur = parents.get(cur)
    return None


def _methods(cls: ast.ClassDef) -> Dict[str, ast.FunctionDef]:
    return {n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _jit_roots(tree: ast.Module, imports: Dict[str, str],
               defs: Dict[str, List[ast.FunctionDef]]
               ) -> List[Tuple[ast.AST, ast.Call]]:
    """Functions (or lambdas) that end up wrapped by jax.jit in this
    module: decorated defs plus first arguments of jax.jit(...) calls."""
    cache = _module_cache(tree)
    if "jit_roots" in cache:
        return cache["jit_roots"]  # type: ignore[return-value]
    roots: List[Tuple[ast.AST, ast.Call]] = []
    seen: Set[int] = set()

    def add(fn: ast.AST, site: ast.Call) -> None:
        if id(fn) not in seen:
            seen.add(id(fn))
            roots.append((fn, site))

    for node in module_nodes(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if (resolve(dec, imports) == "jax.jit"
                        or (isinstance(dec, ast.Call)
                            and _is_jax_jit(dec, imports))):
                    add(node, dec if isinstance(dec, ast.Call) else None)
        elif isinstance(node, ast.Call) and node.args:
            # direct form jax.jit(fn, ...) or applied partial factory
            # partial(jax.jit, ...)(fn) — both wrap node.args[0]
            wraps = (resolve(node.func, imports) == "jax.jit"
                     or (isinstance(node.func, ast.Call)
                         and _is_jax_jit(node.func, imports)))
            if not wraps:
                continue
            target = node.args[0]
            if isinstance(target, ast.Lambda):
                add(target, node)
            else:
                name = dotted_name(target)
                if name and "." not in name and name in defs:
                    # nearest textually-preceding def wins (the common
                    # build-closure-then-jit pattern)
                    cands = [d for d in defs[name]
                             if d.lineno <= node.lineno]
                    if cands:
                        add(max(cands, key=lambda d: d.lineno), node)
    cache["jit_roots"] = roots
    return roots


def _reachable_in_jit(root: ast.AST, parents: Dict[ast.AST, ast.AST],
                      defs: Dict[str, List[ast.FunctionDef]]
                      ) -> List[ast.AST]:
    """The jitted function's own subtree plus ONE level of helpers it
    calls directly: locally-defined bare-name functions and same-class
    ``self.<method>()`` calls."""
    bodies: List[ast.AST] = [root]
    inside: Set[int] = {id(n) for n in ast.walk(root)}
    cls = _class_of(root, parents)
    methods = _methods(cls) if cls is not None else {}
    for node in ast.walk(root):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name is None:
            continue
        helper: Optional[ast.AST] = None
        if "." not in name and name in defs:
            cands = [d for d in defs[name] if id(d) not in inside]
            if cands:
                helper = min(
                    cands, key=lambda d: abs(d.lineno - node.lineno))
        elif name.startswith("self.") and name.count(".") == 1:
            helper = methods.get(name.split(".", 1)[1])
        if helper is not None and id(helper) not in {id(b) for b in bodies}:
            bodies.append(helper)
    return bodies


# ---------------------------------------------------------------------------
# SGL001 jit-purity
# ---------------------------------------------------------------------------

#: module canonical-path prefixes whose calls are host side effects —
#: firing them under a jit trace means they run at TRACE time (once per
#: compile, silently skipped on cached executions), which is exactly
#: the bug class PR 4 pinned to "sites fire host-side OUTSIDE jit"
_IMPURE_MODULE_PREFIXES = ("obs.events.", "events.", "faults.",
                           "obs.record.", "record.",
                           "obs.attr.", "attr.")
_IMPURE_CALLS = {"time.time", "time.monotonic", "time.perf_counter",
                 "time.sleep", "print", "open", "input"}


@register
class JitPurityRule(Rule):
    code = "SGL001"
    name = "jit-purity"
    description = ("obs events, fault sites, print/file I/O and host "
                   "clocks must not be reachable inside jax.jit-wrapped "
                   "functions (one helper level followed)")

    def check(self, tree: ast.Module, src: str,
              path: str) -> Iterable[Finding]:
        imports = import_map(tree)
        defs = _collect_defs(tree)
        parents = build_parents(tree)
        reported: Set[Tuple[int, int]] = set()
        for root, _site in _jit_roots(tree, imports, defs):
            root_name = getattr(root, "name", "<lambda>")
            for body in _reachable_in_jit(root, parents, defs):
                for node in ast.walk(body):
                    if not isinstance(node, ast.Call):
                        continue
                    full = resolve(node.func, imports)
                    if full is None:
                        continue
                    # module prefixes only apply when the head is an
                    # actual import — a local variable that happens to
                    # be named `record`/`events` is not a side effect
                    head = (dotted_name(node.func) or "").partition(".")[0]
                    impure = (full in _IMPURE_CALLS
                              or (head in imports
                                  and any(full.startswith(p)
                                          for p in _IMPURE_MODULE_PREFIXES)))
                    key = (node.lineno, node.col_offset)
                    if impure and key not in reported:
                        reported.add(key)
                        shown = dotted_name(node.func) or full
                        yield self.finding(
                            path, node,
                            f"host side effect {shown}() reachable inside "
                            f"jit-wrapped {root_name!r}: it runs at trace "
                            f"time (once per compile), not per step — "
                            f"hoist it outside the jitted region")


# ---------------------------------------------------------------------------
# SGL002 donation-safety
# ---------------------------------------------------------------------------

def _donated_positions(call: ast.Call) -> List[int]:
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return [v.value]
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for e in v.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    out.append(e.value)
            return out
    return []


class _DonationScan:
    """Linear scan of one function body tracking donated-then-dead
    values.  Loops and branches are scanned in statement order (no
    back-edge analysis) — precise enough for the dispatch patterns this
    repo uses, and it never crosses function boundaries."""

    def __init__(self, rule: Rule, path: str,
                 registry: Dict[str, Tuple[List[int], int]]):
        self.rule = rule
        self.path = path
        self.registry = registry
        self.dead: Dict[str, int] = {}      # dotted name -> donation line
        self.findings: List[Finding] = []

    def scan_block(self, stmts: List[ast.stmt]) -> None:
        for stmt in stmts:
            self._scan_stmt(stmt)

    @staticmethod
    def _header_nodes(stmt: ast.stmt) -> List[ast.AST]:
        """The parts of a statement evaluated BEFORE its nested bodies —
        scanning the whole subtree of a compound statement and then
        recursing into its body would visit body expressions twice (and
        flag the donating call's own arguments as reads-after-donate)."""
        if isinstance(stmt, (ast.If, ast.While)):
            return [stmt.test]
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return [stmt.iter, stmt.target]
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            out: List[ast.AST] = []
            for item in stmt.items:
                out.append(item.context_expr)
                if item.optional_vars is not None:
                    out.append(item.optional_vars)
            return out
        if isinstance(stmt, (ast.Try,)):
            return []
        return [stmt]                       # simple statement: whole node

    def _scan_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return                          # separate scope
        header = self._header_nodes(stmt)

        def walk_header():
            for h in header:
                yield from ast.walk(h)

        # 1. loads already known dead -> findings
        if self.dead:
            for node in walk_header():
                if isinstance(node, (ast.Name, ast.Attribute)) and \
                        isinstance(getattr(node, "ctx", None), ast.Load):
                    d = dotted_name(node)
                    if d in self.dead:
                        self.findings.append(self.rule.finding(
                            self.path, node,
                            f"{d!r} was donated to a jitted call on line "
                            f"{self.dead[d]} (donate_argnums) and read "
                            f"afterwards — its buffer may already be "
                            f"aliased/overwritten; use the call's result "
                            f"or drop the donation"))
                        del self.dead[d]    # report once per donation
        # 2. donations made by this statement
        for node in walk_header():
            if isinstance(node, ast.Call):
                fname = dotted_name(node.func)
                entry = self.registry.get(fname) if fname else None
                if entry:
                    for pos in entry[0]:
                        if pos < len(node.args):
                            d = dotted_name(node.args[pos])
                            if d is not None:
                                self.dead[d] = node.lineno
        # 3. stores resurrect (reassignment means a fresh value)
        for node in walk_header():
            if isinstance(node, (ast.Name, ast.Attribute)) and \
                    isinstance(getattr(node, "ctx", None),
                               (ast.Store, ast.Del)):
                d = dotted_name(node)
                if d is not None:
                    for dead in [k for k in self.dead
                                 if k == d or k.startswith(d + ".")]:
                        del self.dead[dead]
        # 4. recurse into compound bodies in program order
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if isinstance(sub, list) and sub and \
                    isinstance(sub[0], ast.stmt):
                self.scan_block(sub)
        for handler in getattr(stmt, "handlers", []) or []:
            self.scan_block(handler.body)


@register
class DonationSafetyRule(Rule):
    code = "SGL002"
    name = "donation-safety"
    description = ("a value passed at a donate_argnums position must "
                   "not be read after the jitted call — the donated "
                   "buffer is dead")

    def check(self, tree: ast.Module, src: str,
              path: str) -> Iterable[Finding]:
        # pass 1: every `target = jax.jit(..., donate_argnums=...)`
        imports = import_map(tree)
        registry: Dict[str, Tuple[List[int], int]] = {}
        for node in module_nodes(tree):
            if not isinstance(node, ast.Assign) or \
                    not isinstance(node.value, ast.Call):
                continue
            call = node.value
            if resolve(call.func, imports) != "jax.jit":
                continue
            donated = _donated_positions(call)
            if not donated:
                continue
            for target in node.targets:
                d = dotted_name(target)
                if d is not None:
                    registry[d] = (donated, node.lineno)
        if not registry:
            return []
        # pass 2: linear read-after-donate scan of every function body
        findings: List[Finding] = []
        for node in module_nodes(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan = _DonationScan(self, path, registry)
                scan.scan_block(node.body)
                findings.extend(scan.findings)
        return findings


# ---------------------------------------------------------------------------
# SGL003 recompile-hazard
# ---------------------------------------------------------------------------

@register
class RecompileHazardRule(Rule):
    code = "SGL003"
    name = "recompile-hazard"
    description = ("jax.jit inside a loop body builds a fresh executable "
                   "cache per iteration; branching on a traced "
                   "argument's .shape inside a jitted function forks the "
                   "compile cache per shape")

    def check(self, tree: ast.Module, src: str,
              path: str) -> Iterable[Finding]:
        imports = import_map(tree)
        defs = _collect_defs(tree)
        parents = build_parents(tree)
        # (a) jax.jit (or a partial(jax.jit, ...) factory) called
        # inside a for/while body
        for node in module_calls(tree):
            if _is_jax_jit(node, imports):
                cur = parents.get(node)
                while cur is not None and not isinstance(
                        cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Module)):
                    if isinstance(cur, (ast.For, ast.While)):
                        yield self.finding(
                            path, node,
                            "jax.jit(...) inside a loop body: every "
                            "iteration wraps a fresh callable, so the "
                            "jit cache never hits — hoist the jit out "
                            "of the loop")
                        break
                    cur = parents.get(cur)
        # (b) if <traced_arg>.shape inside a jitted function
        for root, _site in _jit_roots(tree, imports, defs):
            args = getattr(root, "args", None)
            if args is None:
                continue
            params = {a.arg for a in
                      list(args.posonlyargs) + list(args.args)
                      + list(args.kwonlyargs)} - {"self", "cls"}
            for node in ast.walk(root):
                test = node.test if isinstance(node, (ast.If, ast.IfExp)) \
                    else None
                if test is None:
                    continue
                for sub in ast.walk(test):
                    if isinstance(sub, ast.Attribute) and \
                            sub.attr == "shape":
                        base = dotted_name(sub.value)
                        if base and base.split(".")[0] in params:
                            yield self.finding(
                                path, sub,
                                f"Python branch on {base}.shape inside "
                                f"jit-wrapped "
                                f"{getattr(root, 'name', '<lambda>')!r}: "
                                f"each distinct shape traces a separate "
                                f"executable — make the branch static "
                                f"or move it outside jit")
                            break


# ---------------------------------------------------------------------------
# thread-seam helpers (shared with tools/lint/conc.py — the SGL004 rule
# itself is RETIRED: its check was subsumed by SGL010 conc-shared-state,
# which also covers executor/signal domains, a transitive self.* call
# closure, and unguarded reads paired with locked writes.  The guard
# recognizer below is the ONE implementation both eras share, so the
# recognition semantics could not drift across the migration.)
# ---------------------------------------------------------------------------

def _self_method(node: ast.AST) -> Optional[str]:
    d = dotted_name(node)
    if d and d.startswith("self.") and d.count(".") == 1:
        return d.split(".", 1)[1]
    return None


_GUARD_TOKENS = frozenset(
    {"lock", "rlock", "mutex", "mu", "cond", "condvar", "cv"})


def _is_guard_name(name: str) -> bool:
    """Whole-segment match: `self._lock`, `self.state_lock`,
    `self._rlock` guard; `self._clock` (contains 'lock') does not."""
    last = name.rsplit(".", 1)[-1].lower()
    return any(seg in _GUARD_TOKENS
               for seg in last.strip("_").split("_"))


def _lock_guarded(node: ast.AST, parents: Dict[ast.AST, ast.AST],
                  stop: ast.AST) -> bool:
    cur = parents.get(node)
    while cur is not None and cur is not stop:
        if isinstance(cur, ast.With):
            for item in cur.items:
                d = dotted_name(item.context_expr) or ""
                if d and _is_guard_name(d):
                    return True
        cur = parents.get(cur)
    return False


# ---------------------------------------------------------------------------
# SGL005 wall-clock
# ---------------------------------------------------------------------------

@register
class WallClockRule(Rule):
    code = "SGL005"
    name = "wall-clock"
    description = ("time.time() / datetime.now() / datetime.today() are "
                   "banned (monotonic-only rule): wall-clock jumps (NTP "
                   "step, suspend/resume) corrupt durations and "
                   "deadlines — use time.monotonic()/perf_counter(), or "
                   "suppress with a reason for genuine timestamps")

    #: wall-clock reads, post-``resolve()``: ``time.time`` plus the
    #: datetime spellings that hide the same jumpy clock behind an
    #: object (subtracting two ``datetime.now()`` results is the same
    #: NTP/suspend hazard as subtracting two ``time.time()`` results)
    _WALL_CLOCKS = {
        "time.time": "time.time()",
        "datetime.datetime.now": "datetime.now()",
        "datetime.datetime.today": "datetime.today()",
    }

    def check(self, tree: ast.Module, src: str,
              path: str) -> Iterable[Finding]:
        imports = import_map(tree)
        for node in module_calls(tree):
            spelled = self._WALL_CLOCKS.get(
                resolve(node.func, imports) or "")
            if spelled:
                yield self.finding(
                    path, node,
                    f"{spelled} reads the wall clock, which can jump "
                    f"(NTP, suspend/resume): use time.monotonic() for "
                    f"deadlines/durations or time.perf_counter() for "
                    f"timing; timestamps that must correlate across "
                    f"hosts are the one legitimate use — suppress with "
                    f"that reason")


# ---------------------------------------------------------------------------
# SGL006 obs-kind / SGL007 fault-site — literal-vs-registry checks
# ---------------------------------------------------------------------------

def _registry_literals(rel_path: str, var: str,
                       root: Optional[str] = None) -> Optional[Set[str]]:
    """String keys/members of a module-level literal assignment, parsed
    from source (the linter must not import singa_tpu — linting may run
    where jax cannot)."""
    path = os.path.join(root or _REPO_ROOT, rel_path)
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return None
    for node in tree.body:
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            target = node.target
        if not (isinstance(target, ast.Name) and target.id == var):
            continue
        value = node.value
        out: Set[str] = set()
        if isinstance(value, ast.Dict):
            for k in value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    out.add(k.value)
        elif isinstance(value, (ast.Tuple, ast.List, ast.Set)):
            for e in value.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.add(e.value)
        return out
    return None


_KINDS_CACHE: Dict[str, Optional[Set[str]]] = {}
_SITES_CACHE: Dict[str, Optional[Set[str]]] = {}
_INCIDENT_CACHE: Dict[str, Optional[Set[str]]] = {}


def _call_arg(call: ast.Call, idx: int, kwname: str) -> Optional[ast.AST]:
    """Positional argument ``idx``, or the ``kwname=`` keyword — the
    registry rules must see ``faults.fire(site=...)`` too."""
    if len(call.args) > idx:
        return call.args[idx]
    for kw in call.keywords:
        if kw.arg == kwname:
            return kw.value
    return None


def record_kinds(root: Optional[str] = None) -> Optional[Set[str]]:
    key = root or _REPO_ROOT
    if key not in _KINDS_CACHE:
        _KINDS_CACHE[key] = _registry_literals(
            os.path.join("singa_tpu", "obs", "schema.py"), "_KINDS", root)
    return _KINDS_CACHE[key]


def fault_sites(root: Optional[str] = None) -> Optional[Set[str]]:
    key = root or _REPO_ROOT
    if key not in _SITES_CACHE:
        _SITES_CACHE[key] = _registry_literals(
            os.path.join("singa_tpu", "faults", "sites.py"), "SITES", root)
    return _SITES_CACHE[key]


def incident_sites(root: Optional[str] = None) -> Optional[Set[str]]:
    """SITES ∪ INCIDENT_SITES — the names a flight-recorder dump (or an
    incident record) may carry; None when either registry is
    unloadable."""
    key = root or _REPO_ROOT
    if key not in _INCIDENT_CACHE:
        extra = _registry_literals(
            os.path.join("singa_tpu", "faults", "sites.py"),
            "INCIDENT_SITES", root)
        base = fault_sites(root)
        _INCIDENT_CACHE[key] = (None if base is None or extra is None
                                else base | extra)
    return _INCIDENT_CACHE[key]


@register
class ObsKindRule(Rule):
    code = "SGL006"
    name = "obs-kind"
    description = ("string literals passed as record kinds "
                   "(obs.record.new_entry) must be members of "
                   "obs.schema._KINDS — the static half of what "
                   "tools/record_check.py verifies dynamically")

    def check(self, tree: ast.Module, src: str,
              path: str) -> Iterable[Finding]:
        kinds = record_kinds()
        imports = import_map(tree)
        for node in module_calls(tree):
            full = resolve(node.func, imports) or ""
            if full.rsplit(".", 1)[-1] != "new_entry" or \
                    not ("record" in full or full == "new_entry"):
                continue
            kind = _call_arg(node, 0, "kind")
            if kind is None:
                continue
            if kinds is None:
                # self-disabling here would be a false clean: a renamed
                # or broken schema.py must fail the gate, not pass it
                yield self.finding(
                    path, node,
                    "cannot verify record kind: obs/schema.py _KINDS "
                    "registry could not be loaded — the schema file is "
                    "missing, renamed, or unparsable")
                continue
            if isinstance(kind, ast.Constant) and \
                    isinstance(kind.value, str) and kind.value not in kinds:
                yield self.finding(
                    path, kind,
                    f"record kind {kind.value!r} is not in "
                    f"obs.schema._KINDS ({', '.join(sorted(kinds))}) — "
                    f"register it in the schema (with payload "
                    f"validation) before emitting it")


@register
class FaultSiteRule(Rule):
    code = "SGL007"
    name = "fault-site"
    description = ("literal site names passed to faults.fire/"
                   "faults.corrupt/faults.tear must exist in "
                   "faults.sites.SITES — a typo'd site silently "
                   "injects nothing")

    def check(self, tree: ast.Module, src: str,
              path: str) -> Iterable[Finding]:
        sites = fault_sites()
        imports = import_map(tree)
        for node in module_calls(tree):
            full = resolve(node.func, imports) or ""
            if full not in ("faults.fire", "faults.corrupt",
                            "faults.tear"):
                continue
            site = _call_arg(node, 0, "site")
            if site is None:
                continue
            if sites is None:
                yield self.finding(
                    path, node,
                    "cannot verify fault site: faults/sites.py SITES "
                    "registry could not be loaded — the sites file is "
                    "missing, renamed, or unparsable")
                continue
            if isinstance(site, ast.Constant) and \
                    isinstance(site.value, str) and site.value not in sites:
                yield self.finding(
                    path, site,
                    f"fault site {site.value!r} is not registered in "
                    f"faults.sites.SITES ({', '.join(sorted(sites))}) — "
                    f"an unregistered site never fires; register it or "
                    f"fix the typo")


# ---------------------------------------------------------------------------
# SGL008 host-sync hazard
# ---------------------------------------------------------------------------

#: class-name suffixes whose step loops are "hot": one host sync per
#: tick serializes every dispatch behind a device round trip (r5 probe
#: 3 measured ~RTT per blocking fetch on the tunneled chip)
_HOT_CLASS_SUFFIXES = ("Engine", "Runner")
#: hot entry points on those classes; the step region proper
_HOT_ROOT_NAMES = frozenset({"step", "run", "run_until_idle"})
#: canonical dotted paths that force a device->host transfer
_HOST_SYNC_CALLS = {"jax.device_get", "numpy.asarray", "numpy.array"}


@register
class HostSyncRule(Rule):
    code = "SGL008"
    name = "host-sync"
    description = ("device fetches (.item(), float(x), np.asarray, "
                   "jax.device_get) must not sit in hot engine/runner "
                   "loops (*Engine/*Runner step/run regions, one helper "
                   "level) — each one serializes the loop behind a "
                   "device round trip; suppress with the measured "
                   "justification when the fetch IS the product")

    def _hot_bodies(self, cls: ast.ClassDef):
        """(method name, body, how) for hot roots plus ONE level of
        ``self.helper()`` calls from them — the same reachability
        discipline as SGL004."""
        methods = _methods(cls)
        roots = {name: "hot entry point" for name in methods
                 if name in _HOT_ROOT_NAMES or name.startswith("_step")}
        reach = dict(roots)
        for name in list(roots):
            for node in ast.walk(methods[name]):
                if isinstance(node, ast.Call):
                    h = _self_method(node.func)
                    if h and h in methods and h not in reach:
                        reach[h] = f"called from {name}()"
        return [(name, methods[name], how) for name, how in reach.items()]

    def check(self, tree: ast.Module, src: str,
              path: str) -> Iterable[Finding]:
        imports = import_map(tree)
        for cls in [n for n in module_nodes(tree)
                    if isinstance(n, ast.ClassDef)
                    and n.name.endswith(_HOT_CLASS_SUFFIXES)]:
            for mname, body, how in self._hot_bodies(cls):
                for node in ast.walk(body):
                    if not isinstance(node, ast.Call):
                        continue
                    shown = None
                    full = resolve(node.func, imports)
                    if isinstance(node.func, ast.Attribute) and \
                            node.func.attr == "item" and not node.args:
                        shown = f"{dotted_name(node.func) or '.item'}()"
                    elif full in _HOST_SYNC_CALLS:
                        shown = f"{dotted_name(node.func) or full}()"
                    elif isinstance(node.func, ast.Name) and \
                            node.func.id == "float" and \
                            len(node.args) == 1 and isinstance(
                                node.args[0],
                                (ast.Name, ast.Attribute, ast.Subscript)):
                        shown = "float(...)"
                    if shown is None:
                        continue
                    yield self.finding(
                        path, node,
                        f"host-sync hazard: {shown} in "
                        f"{cls.name}.{mname}() ({how}) blocks on a "
                        f"device->host transfer inside the hot loop — "
                        f"keep values device-resident, batch the fetch, "
                        f"or suppress with the measured justification")


# ---------------------------------------------------------------------------
# SGL009 flight-site — registry check over flight-recorder dump calls
# ---------------------------------------------------------------------------

@register
class FlightSiteRule(Rule):
    code = "SGL009"
    name = "flight-site"
    description = ("literal site names passed to FlightRecorder dump "
                   "calls (obs.flight) must be registered fault sites "
                   "or faults.sites.INCIDENT_SITES members — a typo'd "
                   "dump site would silently never dump (the runtime "
                   "check only fires on the incident path itself)")

    @staticmethod
    def _is_dump_call(node: ast.Call, full: str) -> bool:
        """``obs.flight.dump(...)`` module-level calls, attribute calls
        on anything named like a flight recorder (``self.flight.dump``,
        ``self._flight.dump``), and flight-dump helper methods whose
        own name says both (``self._flight_dump(site, ...)`` — the
        form the engine/runner call with literal sites).  ``rec.dump``
        is NOT matched: something in the call must say 'flight'."""
        if full in ("obs.flight.dump", "flight.dump"):
            return True
        d = (dotted_name(node.func) or "").lower()
        if d.endswith(".dump") and "flight" in d:
            return True
        last = d.rsplit(".", 1)[-1]
        return "flight" in last and "dump" in last

    def check(self, tree: ast.Module, src: str,
              path: str) -> Iterable[Finding]:
        sites = incident_sites()
        imports = import_map(tree)
        for node in module_calls(tree):
            full = resolve(node.func, imports) or ""
            if not self._is_dump_call(node, full):
                continue
            site = _call_arg(node, 0, "site")
            if site is None:
                continue
            if sites is None:
                yield self.finding(
                    path, node,
                    "cannot verify flight-dump site: faults/sites.py "
                    "SITES/INCIDENT_SITES registries could not be "
                    "loaded — the sites file is missing, renamed, or "
                    "unparsable")
                continue
            if isinstance(site, ast.Constant) and \
                    isinstance(site.value, str) and site.value not in sites:
                yield self.finding(
                    path, site,
                    f"flight-dump site {site.value!r} is not a "
                    f"registered fault site or INCIDENT_SITES member "
                    f"({', '.join(sorted(sites))}) — an unregistered "
                    f"site raises at the worst possible moment (the "
                    f"incident) instead of dumping; register it or fix "
                    f"the typo")
