"""singalint core: rule registry, findings, suppressions, file runner.

The linter is the static half of this repo's invariant enforcement: the
conventions PRs 1-4 established (host-side-only obs/fault seams, donated
arenas, monotonic clocks, schema'd record kinds, lock-guarded thread
seams) each get an AST rule with a stable ``SGL0xx`` code, and a tier-1
test asserts the tree is clean — so the next PR cannot silently violate
them the way only a hand-written regression test used to prevent.

Suppression contract: a finding may be silenced inline with

    some_code()   # singalint: disable=SGL005 reason why this is sound

The reason is REQUIRED — a bare ``disable=SGL005`` is itself a finding
(SGL000), because an unexplained suppression is exactly the silent
convention-drift the linter exists to stop.  Multiple codes:
``disable=SGL001,SGL005 reason...``.  A suppression silences findings
on its own line only.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Tuple, Type

__all__ = ["Finding", "Rule", "RULES", "register", "lint_source",
           "lint_file", "iter_python_files", "run_paths", "render_human",
           "render_json", "parse_file", "SUPPRESS_RE",
           "CODE_SUPPRESSION", "RETIRED_CODES"]

#: the hygiene pseudo-rule: malformed suppressions (missing reason,
#: unknown code) are findings under this code and cannot themselves be
#: suppressed
CODE_SUPPRESSION = "SGL000"

#: retired rule codes and their successors.  A suppression naming a
#: retired code FAILS LOUDLY with a migration hint (SGL000) instead of
#: silently deactivating — the dangerous outcome would be an old
#: ``disable=SGL004`` comment still looking authoritative while
#: suppressing nothing.  SGL004 (thread-seam) was folded into SGL010
#: (conc-shared-state, tools/lint/conc.py) in ISSUE 15.
RETIRED_CODES: Dict[str, str] = {"SGL004": "SGL010"}

SUPPRESS_RE = re.compile(
    r"#\s*singalint:\s*disable=([A-Za-z0-9_,]+)[ \t]*(.*?)\s*$")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a file position."""
    path: str
    line: int
    col: int
    code: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.code} " \
               f"{self.message}"

    def to_json(self) -> Dict:
        return {"path": self.path, "line": self.line, "col": self.col,
                "code": self.code, "message": self.message}


class Rule:
    """Base class: subclasses set ``code``/``name``/``description`` and
    implement :meth:`check` over one parsed module."""

    code: str = ""
    name: str = ""
    description: str = ""

    def check(self, tree: ast.Module, src: str,
              path: str) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, path: str, node: ast.AST, message: str) -> Finding:
        return Finding(path, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), self.code, message)


#: code -> rule class, in registration order
RULES: Dict[str, Type[Rule]] = {}


def register(cls: Type[Rule]) -> Type[Rule]:
    if not cls.code or cls.code in RULES:
        raise ValueError(f"rule {cls.__name__} has a missing or duplicate "
                         f"code {cls.code!r}")
    RULES[cls.code] = cls
    return cls


def _suppressions(src: str, path: str) -> Tuple[Dict[int, set], List[Finding]]:
    """Per-line suppressed code sets, plus hygiene findings for
    suppressions that are malformed (no reason / unknown code).

    Comments are found with tokenize so a ``# singalint:`` inside a
    string literal is never treated as a suppression."""
    import io
    lines: Dict[int, set] = {}
    bad: List[Finding] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(src).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return lines, bad
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        lineno = tok.start[0]
        codes = [c.strip() for c in m.group(1).split(",") if c.strip()]
        reason = m.group(2).strip()
        if not reason:
            bad.append(Finding(
                path, lineno, tok.start[1], CODE_SUPPRESSION,
                f"suppression of {','.join(codes)} carries no reason — "
                f"write '# singalint: disable={','.join(codes)} <why this "
                f"is sound>'"))
            continue
        for code in codes:
            if code in RETIRED_CODES:
                bad.append(Finding(
                    path, lineno, tok.start[1], CODE_SUPPRESSION,
                    f"suppression names retired rule code {code!r}, "
                    f"which was superseded by {RETIRED_CODES[code]} "
                    f"(conclint, tools/lint/conc.py) — update the "
                    f"comment to 'disable={RETIRED_CODES[code]}' so "
                    f"it keeps silencing the finding instead of "
                    f"silently deactivating"))
                continue
            if code == CODE_SUPPRESSION or code not in RULES:
                bad.append(Finding(
                    path, lineno, tok.start[1], CODE_SUPPRESSION,
                    f"suppression names unknown rule code {code!r} "
                    f"(known: {', '.join(sorted(RULES))})"))
                continue
            lines.setdefault(lineno, set()).add(code)
    return lines, bad


def lint_source(src: str, path: str = "<string>",
                codes: Optional[Iterable[str]] = None,
                tree: Optional[ast.Module] = None) -> List[Finding]:
    """Run every registered rule (or just ``codes``) over one source
    text; returns findings with suppressions already applied.  An
    already-parsed ``tree`` (the parse cache) skips the re-parse AND
    keeps its per-parse module cache warm across rules and the conc
    thread-model discovery."""
    if tree is None:
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            return [Finding(path, e.lineno or 1, e.offset or 0, "SGL999",
                            f"syntax error: {e.msg}")]
    suppressed, findings = _suppressions(src, path)
    wanted = set(codes) if codes is not None else set(RULES)
    for code, cls in RULES.items():
        if code not in wanted:
            continue
        for f in cls().check(tree, src, path):
            if f.code in suppressed.get(f.line, ()):
                continue
            findings.append(f)
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.code))


#: path -> (mtime_ns, size, tree, src).  One process-wide parse per
#: file version: the bare full audit lints every tree file AND runs
#: the conc thread-model discovery over the same set — without the
#: cache that is two full parses of the repo (the PR 5 per-parse
#: ``_module_cache`` only de-duplicates work WITHIN one parse).
#: Keyed by (mtime_ns, size) so an edited file re-parses; the audited
#: trees are ~130 small files, so holding their trees is cheap.
_PARSE_CACHE: Dict[str, Tuple[int, int, ast.Module, str]] = {}


def parse_file(path: str) -> Optional[Tuple[ast.Module, str]]:
    """(tree, src) for ``path`` through the process-wide parse cache;
    None for unreadable or syntactically-broken files (the lint path
    reports those as SGL999 findings via :func:`lint_file`)."""
    try:
        st = os.stat(path)
        key = (st.st_mtime_ns, st.st_size)
        hit = _PARSE_CACHE.get(path)
        if hit is not None and (hit[0], hit[1]) == key:
            return hit[2], hit[3]
        with open(path, encoding="utf-8") as f:
            src = f.read()
        tree = ast.parse(src, filename=path)
    except (OSError, UnicodeDecodeError, SyntaxError):
        return None
    _PARSE_CACHE[path] = (key[0], key[1], tree, src)
    return tree, src


def lint_file(path: str,
              codes: Optional[Iterable[str]] = None) -> List[Finding]:
    parsed = parse_file(path)
    if parsed is None:
        # fall through to the uncached path for the precise finding
        # (SGL999 with the syntax-error position / unreadable reason)
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except (OSError, UnicodeDecodeError) as e:
            return [Finding(path, 1, 0, "SGL999", f"unreadable: {e}")]
        return lint_source(src, path, codes)
    tree, src = parsed
    return lint_source(src, path, codes, tree=tree)


def iter_python_files(paths: Iterable[str]) -> List[str]:
    """Expand files/directories into a sorted list of ``.py`` files."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return sorted(dict.fromkeys(out))


def run_paths(paths: Iterable[str],
              codes: Optional[Iterable[str]] = None) -> List[Finding]:
    """Lint every Python file under ``paths``.

    A path that expands to zero Python files raises ``ValueError``
    rather than contributing nothing: the repo-is-clean gate calls this
    directly, and a renamed tree must fail the gate, not pass it."""
    files: List[str] = []
    for p in paths:
        matched = iter_python_files([p])
        if not matched:
            raise ValueError(f"path {p!r} matches no Python files")
        files.extend(matched)
    findings: List[Finding] = []
    for path in dict.fromkeys(files):
        findings.extend(lint_file(path, codes))
    return findings


def render_human(findings: List[Finding]) -> str:
    lines = [f.render() for f in findings]
    lines.append(f"singalint: {len(findings)} finding(s)" if findings
                 else "singalint: clean")
    return "\n".join(lines)


def render_json(findings: List[Finding]) -> str:
    return json.dumps(
        {"version": 1, "count": len(findings),
         "findings": [f.to_json() for f in findings]},
        indent=2, sort_keys=True)
