"""singalint — project-specific static analysis for singa_tpu.

Public API re-exported here so tests and tools can do::

    from tools.lint import lint_source, run_paths, RULES

CLI front door (``python -m tools.lint``) lives in ``__main__``; the
AST rules in ``rules``; the dynamic audits (record store, checkpoint
dirs) in ``audit``.
"""

from .framework import (  # noqa: F401
    CODE_SUPPRESSION,
    Finding,
    Rule,
    RULES,
    iter_python_files,
    lint_file,
    lint_source,
    register,
    render_human,
    render_json,
    run_paths,
)
from . import rules  # noqa: F401  (importing registers every rule)
from . import conc  # noqa: F401  (registers SGL010-SGL013, conclint)
from . import proc  # noqa: F401  (registers SGL015/SGL017, proclint)
