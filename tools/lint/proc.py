"""proclint — the process-mesh, resource-lifecycle, and wire-protocol
audit (ISSUE 20).

PRs 18–19 made the serve tier a real multi-process system — spawned
worker processes, SIGKILL/SIGSTOP signal traffic, AF_UNIX sockets, a
hand-rolled framed RPC, env-scrub contracts, respawn/breaker
supervision — but conclint's committed thread model stops at the
process boundary.  proclint extends the same "committed baseline +
named finding + reviewed diff" discipline to the process mesh itself:

1. **a committed process-model baseline** (gate code **SGL019**,
   ``tools/lint/data/proc/model.json``): every process root
   (``subprocess.Popen`` / ``multiprocessing.Process`` construction
   and ``spawn_many`` call sites), every signal send (``os.kill`` with
   an explicit signal, ``.kill()``/``.terminate()``), every reap site
   (``.wait()``/``.join()``/fabric-ledger removal), and every
   socket/socketpair/accept, keyed line-free like conclint's roots.  A
   kill site with no reap reachable in its function (one self-helper
   level deep) carries a ``!noreap`` tag, so a kill LOSING its reap is
   a value change, not silence.  The baseline records a content hash
   of its own sections, so a hand-edited model.json fails the gate
   instead of silently redefining "reviewed".
2. **SGL015 resource-lifecycle**: every socket, spawned process, temp
   file/dir, and opened sink must have a release reachable on the
   exception path — a ``with`` block, a ``try/finally``/``except``
   release, a registered cleanup (``atexit.register`` /
   ``weakref.finalize``), class ownership with a releasing method, or
   an escape (returned, stashed in a ledger).  A release that only
   runs on the straight-line path, or none at all, is a finding —
   with the conclint-style one-helper-level closure
   (``self._reap(procs)`` counts when ``_reap`` releases its param).
3. **SGL016 RPC-protocol conformance**: the worker dispatch table
   (``_op_*`` methods + inline ``op == "..."`` dispatch), the
   supervisor/tool/test call sites (``.call({"op": ...})`` /
   ``.send({"op": ...})``), and the ``_OP_TIMEOUTS`` deadline table
   must agree EXACTLY — an op handled but never called, called but
   never handled, or missing a deadline entry is a named finding, as
   is a codec magic/version literal that differs between the
   ``encode_*`` and ``decode_*`` sides of a wire codec module.
4. **SGL017 child-env contract**: ``subprocess.Popen`` must pass an
   ``env=`` built through a scrub seam that pops ``SINGA_FAULTS``,
   ``SINGA_FAULTS_SEED`` and ``SINGA_OBS`` (the double-fire chaos bug
   class PR 18 fixed by convention), and no code outside such a seam
   may write those vars into an environment mapping.

Scope limits (same contract as conclint, documented in
docs/static-analysis.md): analysis is AST-level, module-local and
name-based — no runtime fd tracking, no cross-module dataflow.  An
env dict mutated through ``env.update(other_mapping)`` is invisible
(only literal keys are seen); ``multiprocessing.Process`` children
inherit by fork/spawn and have no ``env=`` seam to check; a resource
passed as a bare call argument is treated as an ownership transfer.
The chaos campaigns and ``tests/test_net.py`` cover the runtime half.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .framework import Finding, Rule, register, iter_python_files, \
    parse_file
from .conc import _helper_bodies, _load_baseline, _root_file_line, \
    _scope_name, _sync_vars
from .rules import (_class_of, _collect_defs, _methods, _self_method,
                    build_parents, dotted_name, import_map,
                    module_nodes, resolve)

__all__ = ["discover_model", "gate_findings", "protocol_findings",
           "audit_findings", "update_model_baseline", "model_hash",
           "MODEL_PATH", "PROC_SCHEMA", "PROC_GATE_CODES",
           "DEFAULT_TREES", "PROTOCOL_TREES"]

_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

#: the committed process-model baseline — the reviewed record of every
#: spawn site, signal send, reap site, and socket in the audited trees
MODEL_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "data", "proc", "model.json")

#: model format version — bump on incompatible shape changes; a
#: baseline with another version fails the gate instead of diffing
#: garbage (same contract as the conc/HLO schemas)
PROC_SCHEMA = 1

#: the trees the process model covers — the same set the bare full
#: audit lints (tools/lint/__main__._DEFAULT_TREES)
DEFAULT_TREES = ("singa_tpu", "tools")

#: the trees the SGL016 protocol cross-check derives CALL SITES from:
#: tests drive ops (``chaos``) that production code deliberately never
#: sends, so a worker handler exercised only by the chaos campaign is
#: protocol surface, not dead code
PROTOCOL_TREES = ("singa_tpu", "tools", "tests")

#: the model sections, in the order the update diff prints them
_SECTIONS = ("roots", "signals", "reaps", "sockets")

#: the gate's finding codes, enumerated by --list-rules next to the
#: conc/HLO/COST families (gate codes, not per-module rules — they
#: cannot be inline-suppressed; the baseline IS the review mechanism)
PROC_GATE_CODES = {
    "SGL016": ("rpc-protocol", "the worker dispatch table (_op_* "
               "methods + inline op dispatch), the supervisor/tool/"
               "test call sites, and the _OP_TIMEOUTS deadline table "
               "must agree exactly — a one-sided op or a missing "
               "deadline is a named finding, as is codec magic/"
               "version skew between encode and decode"),
    "SGL019": ("process-model", "the discovered process roots, signal "
               "sends, reap sites, and sockets match the committed "
               "baseline tools/lint/data/proc/model.json — a new "
               "spawn site, a vanished reap, or a kill losing its "
               "reap path fails loudly until '--proc "
               "--update-baselines' is run and the diff reviewed"),
}

_UPDATE_HINT = ("run 'python -m tools.lint --proc --update-baselines' "
                "and review the diff it prints")


def _enclosing_function(node: ast.AST,
                        parents: Dict[ast.AST, ast.AST]
                        ) -> Optional[ast.AST]:
    cur = parents.get(node)
    while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
        cur = parents.get(cur)
    return cur


def _on_exception_path(node: ast.AST,
                       parents: Dict[ast.AST, ast.AST],
                       stop: ast.AST) -> bool:
    """True when ``node`` sits in a ``finally`` block or an except
    handler inside ``stop`` — i.e. it still runs when the straight-line
    path raises."""
    cur: Optional[ast.AST] = node
    while cur is not None and cur is not stop:
        p = parents.get(cur)
        if isinstance(cur, ast.ExceptHandler):
            return True
        if isinstance(p, ast.Try) and \
                any(cur is s for s in p.finalbody):
            return True
        cur = p
    return False


def _mentions(node: ast.AST, name: str) -> bool:
    return any(isinstance(n, ast.Name) and n.id == name
               for n in ast.walk(node))


# ---------------------------------------------------------------------------
# SGL015 proc-resource-lifecycle
# ---------------------------------------------------------------------------

#: resolved constructor -> what it acquires (the audit's resource set:
#: exactly the kinds the serve/net process mesh leaks when mishandled)
_ACQUIRE_CTORS = {
    "socket.socket": "socket",
    "socket.socketpair": "socket pair",
    "socket.create_connection": "socket",
    "subprocess.Popen": "child process",
    "multiprocessing.Process": "child process",
    "tempfile.mkdtemp": "temp dir",
    "tempfile.mkstemp": "temp file",
    "tempfile.NamedTemporaryFile": "temp file",
    "tempfile.TemporaryDirectory": "temp dir",
    "open": "file handle",
}

#: method names that release (or reap) the resource they are called on
_RELEASE_METHODS = frozenset({
    "close", "kill", "terminate", "wait", "shutdown", "cleanup",
    "stop", "join", "release", "detach", "unlink",
})

#: module functions that release a resource passed as an argument
_RELEASE_FUNCS = frozenset({
    "os.close", "os.unlink", "os.remove", "os.rmdir", "os.removedirs",
    "shutil.rmtree",
})

#: registering a cleanup makes the release exception-safe by contract
_CLEANUP_REGISTRARS = frozenset({"atexit.register", "weakref.finalize"})

#: receiver methods that stash the resource in a longer-lived owner
_ESCAPE_STASH_METHODS = frozenset({
    "append", "extend", "add", "put", "insert", "register",
    "setdefault", "update",
})


def _recv_base(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        return dotted_name(call.func.value)
    return None


def _helper_releases_params(hfn: ast.AST) -> bool:
    """One helper level of the release closure: the helper's body
    releases one of its own params — directly, or through a for-loop
    target iterating a param (``_reap(procs)``: ``for p in procs:
    p.wait()``) or a ``.values()`` view of one."""
    if not isinstance(hfn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    aliases = {a.arg for a in hfn.args.args if a.arg != "self"}
    if not aliases:
        return False
    for sub in ast.walk(hfn):
        if isinstance(sub, ast.For):
            it = sub.iter
            base = None
            if isinstance(it, ast.Call) and \
                    isinstance(it.func, ast.Attribute):
                base = dotted_name(it.func.value)
            else:
                base = dotted_name(it)
            if base and base.split(".")[0] in aliases:
                for el in ([sub.target] if isinstance(sub.target, ast.Name)
                           else list(getattr(sub.target, "elts", []))):
                    if isinstance(el, ast.Name):
                        aliases.add(el.id)
    for sub in ast.walk(hfn):
        if not isinstance(sub, ast.Call):
            continue
        if isinstance(sub.func, ast.Attribute) and \
                sub.func.attr in _RELEASE_METHODS:
            recv = dotted_name(sub.func.value)
            if recv and recv.split(".")[0] in aliases:
                return True
        d = dotted_name(sub.func)
        if d in _RELEASE_FUNCS and any(
                isinstance(n, ast.Name) and n.id in aliases
                for a in sub.args for n in ast.walk(a)):
            return True
    return False


def _class_releases(cls: ast.ClassDef, attr: str,
                    imports: Dict[str, str]) -> bool:
    """Some method of ``cls`` releases ``self.<attr>`` — the class owns
    the resource and its close()/shutdown() is the lifecycle."""
    target = f"self.{attr}"
    for body in _methods(cls).values():
        for sub in ast.walk(body):
            if not isinstance(sub, ast.Call):
                continue
            if isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr in _RELEASE_METHODS:
                recv = dotted_name(sub.func.value)
                if recv and (recv == target or
                             recv.startswith(target + ".")):
                    return True
            full = resolve(sub.func, imports) or ""
            if full in _RELEASE_FUNCS and any(
                    (dotted_name(a) or "").startswith(target)
                    for a in sub.args):
                return True
    return False


@register
class ResourceLifecycleRule(Rule):
    code = "SGL015"
    name = "proc-resource-lifecycle"
    description = ("sockets, spawned processes, temp files/dirs, and "
                   "opened sinks must have a release reachable on the "
                   "exception path (with block, try/finally, except-"
                   "path release, registered cleanup, owning-class "
                   "release method, or an escape to a longer-lived "
                   "owner) — one helper level deep; a straight-line-"
                   "only release leaks on the first raise")

    def _local_lifecycle(self, name: str, fn: ast.AST,
                         parents: Dict[ast.AST, ast.AST],
                         imports: Dict[str, str],
                         methods: Dict[str, ast.FunctionDef],
                         defs: Dict[str, List[ast.FunctionDef]]) -> str:
        """'exception-safe' | 'escapes' | 'straight-line' | 'none' for
        a locally-bound resource ``name`` inside ``fn``."""
        releases: List[Tuple[ast.AST, bool]] = []
        escapes = False
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Return) and sub.value is not None \
                    and _mentions(sub.value, name):
                escapes = True
            elif isinstance(sub, ast.Assign):
                if any(isinstance(t, (ast.Attribute, ast.Subscript))
                       for t in sub.targets) and \
                        _mentions(sub.value, name):
                    escapes = True
            elif isinstance(sub, ast.With):
                for item in sub.items:
                    if _mentions(item.context_expr, name):
                        releases.append((sub, True))
            elif isinstance(sub, ast.Call):
                full = resolve(sub.func, imports) or ""
                argvals = list(sub.args) + \
                    [kw.value for kw in sub.keywords]
                recv = _recv_base(sub)
                if recv and (recv == name or
                             recv.startswith(name + ".")) and \
                        isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr in _RELEASE_METHODS:
                    releases.append(
                        (sub, _on_exception_path(sub, parents, fn)))
                elif full in _RELEASE_FUNCS and \
                        any(_mentions(a, name) for a in argvals):
                    releases.append(
                        (sub, _on_exception_path(sub, parents, fn)))
                elif full in _CLEANUP_REGISTRARS and \
                        any(_mentions(a, name) for a in argvals):
                    return "exception-safe"
                elif isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr in _ESCAPE_STASH_METHODS and \
                        any(_mentions(a, name) for a in sub.args):
                    escapes = True
                elif any(_mentions(a, name) for a in sub.args):
                    for h in _helper_bodies(sub, methods, defs):
                        if _helper_releases_params(h):
                            releases.append(
                                (sub, _on_exception_path(
                                    sub, parents, fn)))
                            break
        if any(safe for _, safe in releases):
            return "exception-safe"
        if escapes:
            return "escapes"
        return "straight-line" if releases else "none"

    def check(self, tree: ast.Module, src: str,
              path: str) -> Iterable[Finding]:
        imports = import_map(tree)
        parents = build_parents(tree)
        defs = _collect_defs(tree)
        for node in module_nodes(tree):
            if not isinstance(node, ast.Call):
                continue
            full = resolve(node.func, imports) or ""
            kind = _ACQUIRE_CTORS.get(full)
            if kind is None:
                continue
            p = parents.get(node)
            if isinstance(p, ast.withitem):
                continue    # context manager owns the release
            if isinstance(p, (ast.Call, ast.Return, ast.Yield,
                              ast.Await)):
                continue    # ownership transferred to callee/caller
            if isinstance(p, ast.Attribute):
                gp = parents.get(p)
                if p.attr in _RELEASE_METHODS and \
                        isinstance(gp, ast.Call):
                    continue    # Popen(...).wait() — consumed in place
                yield self.finding(
                    path, node,
                    f"{full}() acquires a {kind} that is dereferenced "
                    f"without keeping a handle — nothing can release "
                    f"it; bind it and release it on all paths")
                continue
            if isinstance(p, ast.Expr):
                yield self.finding(
                    path, node,
                    f"{full}() result discarded: the {kind} it "
                    f"acquires can never be released — bind it and "
                    f"release it on all paths (try/finally or a with "
                    f"block), or suppress with why the leak is the "
                    f"design")
                continue
            if not isinstance(p, (ast.Assign, ast.AnnAssign)):
                continue
            targets = p.targets if isinstance(p, ast.Assign) \
                else [p.target]
            names: List[str] = []
            owned_elsewhere = False
            for t in targets:
                if isinstance(t, ast.Name):
                    names.append(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    names.extend(el.id for el in t.elts
                                 if isinstance(el, ast.Name))
                elif isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    cls = _class_of(node, parents)
                    if cls is not None and \
                            not _class_releases(cls, t.attr, imports):
                        yield self.finding(
                            path, node,
                            f"self.{t.attr} holds a {kind} acquired "
                            f"here but no method of {cls.name} "
                            f"releases it — add a close()/shutdown "
                            f"path, or suppress with why the resource "
                            f"lives for the process")
                    owned_elsewhere = True
                else:
                    owned_elsewhere = True  # subscript/attr: escapes
            fn = _enclosing_function(node, parents)
            if not names or fn is None:
                # module-level binding: a process-lifetime singleton
                # (and owned_elsewhere targets were handled above)
                del owned_elsewhere
                continue
            cls = _class_of(node, parents)
            methods = _methods(cls) if cls is not None else {}
            for name in names:
                verdict = self._local_lifecycle(
                    name, fn, parents, imports, methods, defs)
                if verdict in ("exception-safe", "escapes"):
                    continue
                if verdict == "straight-line":
                    yield self.finding(
                        path, node,
                        f"{kind} '{name}' ({full}()) is released only "
                        f"on the straight-line path — an exception "
                        f"between acquire and release leaks it; wrap "
                        f"the release in try/finally or a with block, "
                        f"or suppress with why the path cannot raise")
                else:
                    yield self.finding(
                        path, node,
                        f"{kind} '{name}' ({full}()) is never "
                        f"released in {getattr(fn, 'name', '<fn>')}() "
                        f"and does not escape to a longer-lived owner "
                        f"— release it on all paths, or suppress with "
                        f"why the leak is bounded")


# ---------------------------------------------------------------------------
# SGL017 proc-env-contract
# ---------------------------------------------------------------------------

#: the fault/observability vars a spawned child MUST NOT inherit: a
#: parent fault plan re-firing inside the child is the double-fire
#: chaos bug class PR 18 fixed by convention (supervisor._child_env)
_SCRUB_VARS = ("SINGA_FAULTS", "SINGA_FAULTS_SEED", "SINGA_OBS")


def _is_scrub_key(value: object) -> bool:
    return isinstance(value, str) and (
        value in _SCRUB_VARS or value.startswith("SINGA_FAULTS"))


def _env_receiver(expr: ast.AST) -> Optional[str]:
    """Dotted name of an environment-mapping receiver (``os.environ``,
    a local ``env`` dict) — the name-based half of the write ban."""
    d = dotted_name(expr)
    if d is None:
        return None
    leaf = d.split(".")[-1]
    return d if leaf in ("environ", "env") else None


def _scrubbed_in(fn: ast.AST,
                 recv: Optional[str] = None) -> Set[str]:
    """Var names popped/deleted in ``fn`` — through the literal form
    (``env.pop("SINGA_OBS", None)``), the loop form (``for k in
    ("SINGA_FAULTS", ...): env.pop(k, None)`` — supervisor's actual
    seam), and ``del env["..."]``.  ``recv`` restricts to one
    receiver name."""
    loop_vars: Dict[str, Set[str]] = {}
    for sub in ast.walk(fn):
        if isinstance(sub, ast.For) and \
                isinstance(sub.target, ast.Name) and \
                isinstance(sub.iter, (ast.Tuple, ast.List)):
            vals = {e.value for e in sub.iter.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)}
            loop_vars.setdefault(sub.target.id, set()).update(vals)
    out: Set[str] = set()
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Call) and \
                isinstance(sub.func, ast.Attribute) and \
                sub.func.attr == "pop" and sub.args:
            r = dotted_name(sub.func.value)
            if recv is not None and r != recv:
                continue
            a0 = sub.args[0]
            if isinstance(a0, ast.Constant) and \
                    isinstance(a0.value, str):
                out.add(a0.value)
            elif isinstance(a0, ast.Name) and a0.id in loop_vars:
                out.update(loop_vars[a0.id])
        elif isinstance(sub, ast.Delete):
            for t in sub.targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.slice, ast.Constant) and \
                        isinstance(t.slice.value, str):
                    if recv is not None and \
                            dotted_name(t.value) != recv:
                        continue
                    out.add(t.slice.value)
    return out


def _is_scrub_seam(fn: Optional[ast.AST]) -> bool:
    """The designated seam: a function that pops ALL the scrub vars
    (``_Fabric._child_env``) may also write fault vars into the env it
    is building — that is what the seam is FOR."""
    return fn is not None and set(_SCRUB_VARS) <= _scrubbed_in(fn)


@register
class ChildEnvContractRule(Rule):
    code = "SGL017"
    name = "proc-env-contract"
    description = ("subprocess.Popen must pass env= built through a "
                   "scrub seam that pops SINGA_FAULTS, "
                   "SINGA_FAULTS_SEED and SINGA_OBS before the child "
                   "starts (a parent fault plan double-fires in the "
                   "child otherwise), and no code outside such a seam "
                   "may write those vars into an environment mapping")

    def _env_scrubs(self, expr: ast.AST, node: ast.Call,
                    parents: Dict[ast.AST, ast.AST],
                    defs: Dict[str, List[ast.FunctionDef]]
                    ) -> Set[str]:
        """The scrub-var set provably popped on the way to this
        ``env=`` value: a helper call (``env=self._child_env()``), or
        a local name with in-function pops / helper assignment."""
        if isinstance(expr, ast.Dict):
            if any(k is None for k in expr.keys):
                return set()    # **spread: contents unknown
            # built from scratch — nothing inherited; an explicit
            # scrub-var key still reads as unscrubbed (the child
            # receives it)
            present = {k.value for k in expr.keys
                       if isinstance(k, ast.Constant)
                       and _is_scrub_key(k.value)}
            return set(_SCRUB_VARS) - present
        if isinstance(expr, ast.Call):
            if isinstance(expr.func, ast.Name) and \
                    expr.func.id == "dict" and not expr.args:
                return set(_SCRUB_VARS)    # dict(K=..): from scratch
            body = self._callee_body(expr, node, parents, defs)
            return _scrubbed_in(body) if body is not None else set()
        if isinstance(expr, ast.Name):
            fn = _enclosing_function(node, parents)
            if fn is None:
                return set()
            out = _scrubbed_in(fn, recv=expr.id)
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Assign) and \
                        isinstance(sub.value, ast.Call) and any(
                            isinstance(t, ast.Name) and t.id == expr.id
                            for t in sub.targets):
                    body = self._callee_body(sub.value, node,
                                             parents, defs)
                    if body is not None:
                        out |= _scrubbed_in(body)
            return out
        return set()

    def _callee_body(self, call: ast.Call, site: ast.AST,
                     parents: Dict[ast.AST, ast.AST],
                     defs: Dict[str, List[ast.FunctionDef]]
                     ) -> Optional[ast.AST]:
        m = _self_method(call.func)
        if m is not None:
            cls = _class_of(site, parents)
            if cls is not None:
                return _methods(cls).get(m)
            return None
        if isinstance(call.func, ast.Name) and call.func.id in defs:
            return defs[call.func.id][0]
        return None

    def check(self, tree: ast.Module, src: str,
              path: str) -> Iterable[Finding]:
        imports = import_map(tree)
        parents = build_parents(tree)
        defs = _collect_defs(tree)
        for node in module_nodes(tree):
            if isinstance(node, ast.Call):
                full = resolve(node.func, imports) or ""
                if full == "subprocess.Popen":
                    env_kw = next((kw for kw in node.keywords
                                   if kw.arg == "env"), None)
                    if env_kw is None or (
                            isinstance(env_kw.value, ast.Constant)
                            and env_kw.value.value is None):
                        yield self.finding(
                            path, node,
                            f"subprocess.Popen without a scrubbed "
                            f"env=: the child inherits the parent's "
                            f"environment including "
                            f"{'/'.join(_SCRUB_VARS)}, so a parent "
                            f"fault plan double-fires in the child — "
                            f"build env through the scrub seam")
                        continue
                    missing = [v for v in _SCRUB_VARS
                               if v not in self._env_scrubs(
                                   env_kw.value, node, parents, defs)]
                    if missing:
                        yield self.finding(
                            path, node,
                            f"child env passed to subprocess.Popen "
                            f"does not scrub {', '.join(missing)} — "
                            f"pop them in the env-building seam "
                            f"before the child starts, or suppress "
                            f"with why inheritance is safe")
                elif full == "os.putenv" and node.args and \
                        isinstance(node.args[0], ast.Constant) and \
                        _is_scrub_key(node.args[0].value) and \
                        not _is_scrub_seam(
                            _enclosing_function(node, parents)):
                    yield self.finding(
                        path, node,
                        f"os.putenv({node.args[0].value!r}, ...) "
                        f"outside the child-env scrub seam: fault/"
                        f"obs vars may only be written where all of "
                        f"{'/'.join(_SCRUB_VARS)} are popped first")
                elif isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "setdefault" and \
                        node.args and \
                        isinstance(node.args[0], ast.Constant) and \
                        _is_scrub_key(node.args[0].value) and \
                        _env_receiver(node.func.value) is not None \
                        and not _is_scrub_seam(
                            _enclosing_function(node, parents)):
                    yield self.finding(
                        path, node,
                        f"writes {node.args[0].value} into "
                        f"{_env_receiver(node.func.value)} outside "
                        f"the child-env scrub seam — the designated "
                        f"seam (which pops {'/'.join(_SCRUB_VARS)}) "
                        f"is the only place fault/obs vars may be "
                        f"set")
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) and \
                            isinstance(t.slice, ast.Constant) and \
                            _is_scrub_key(t.slice.value) and \
                            _env_receiver(t.value) is not None and \
                            not _is_scrub_seam(
                                _enclosing_function(node, parents)):
                        yield self.finding(
                            path, node,
                            f"writes {t.slice.value} into "
                            f"{_env_receiver(t.value)} outside the "
                            f"child-env scrub seam — the designated "
                            f"seam (which pops "
                            f"{'/'.join(_SCRUB_VARS)}) is the only "
                            f"place fault/obs vars may be set")


# ---------------------------------------------------------------------------
# SGL016 rpc-protocol conformance (a cross-file audit, not a per-module
# rule: the dispatch table, the call sites, and the deadline table live
# in different files — and the call-site scan includes tests/)
# ---------------------------------------------------------------------------

def _dict_op(d: ast.Dict) -> Optional[str]:
    for k, v in zip(d.keys, d.values):
        if isinstance(k, ast.Constant) and k.value == "op" and \
                isinstance(v, ast.Constant) and \
                isinstance(v.value, str):
            return v.value
    return None


def _codec_findings(path: str, tree: ast.Module) -> List[Finding]:
    """Magic/version literal skew between a wire codec's encode and
    decode sides (modules defining both an ``encode_*`` and a
    ``decode_*`` top-level function)."""
    fns = [n for n in tree.body
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    enc = [f for f in fns if f.name.startswith("encode")]
    dec = [f for f in fns if f.name.startswith("decode")]
    if not enc or not dec:
        return []
    bytes_consts: Dict[str, bytes] = {}
    version_consts: Dict[str, int] = {}
    for n in tree.body:
        if isinstance(n, ast.Assign) and \
                isinstance(n.value, ast.Constant):
            for t in n.targets:
                if not isinstance(t, ast.Name):
                    continue
                if isinstance(n.value.value, bytes):
                    bytes_consts[t.id] = n.value.value
                elif isinstance(n.value.value, int) and \
                        "VERSION" in t.id.upper():
                    version_consts[t.id] = n.value.value

    def magics(side: List[ast.AST]) -> Set[bytes]:
        out: Set[bytes] = set()
        for f in side:
            for sub in ast.walk(f):
                if isinstance(sub, ast.Constant) and \
                        isinstance(sub.value, bytes) and sub.value:
                    out.add(sub.value)
                elif isinstance(sub, ast.Name) and \
                        sub.id in bytes_consts:
                    out.add(bytes_consts[sub.id])
        return out

    def versions(side: List[ast.AST]) -> Set[int]:
        out: Set[int] = set()
        for f in side:
            for sub in ast.walk(f):
                if isinstance(sub, ast.Name) and \
                        sub.id in version_consts:
                    out.add(version_consts[sub.id])
                elif isinstance(sub, ast.Compare):
                    sides = [sub.left] + list(sub.comparators)
                    named = any("version" in (dotted_name(s) or "")
                                .lower() for s in sides)
                    if named:
                        out.update(
                            s.value for s in sides
                            if isinstance(s, ast.Constant)
                            and isinstance(s.value, int))
        return out

    findings: List[Finding] = []
    em, dm = magics(enc), magics(dec)
    if em and dm and not (em & dm):
        findings.append(Finding(
            path, dec[0].lineno, dec[0].col_offset, "SGL016",
            f"codec magic skew: encode writes {sorted(em)} but decode "
            f"accepts {sorted(dm)} — every frame one side produces, "
            f"the other rejects; share one module-level constant"))
    ev, dv = versions(enc), versions(dec)
    if ev and dv and not (ev & dv):
        findings.append(Finding(
            path, dec[0].lineno, dec[0].col_offset, "SGL016",
            f"codec wire-version skew: encode stamps {sorted(ev)} but "
            f"decode accepts {sorted(dv)} — every frame one side "
            f"produces, the other rejects; share one module-level "
            f"constant"))
    return findings


def protocol_findings(paths: Optional[Iterable[str]] = None,
                      root: Optional[str] = None) -> List[Finding]:
    """The SGL016 cross-check: worker dispatch vs. call sites vs. the
    deadline table, plus per-module codec magic/version skew.  [] when
    the three views of the protocol agree exactly (or no worker
    dispatch table exists in the scanned trees)."""
    root = root or _REPO_ROOT
    if paths is None:
        paths = [os.path.join(root, t) for t in PROTOCOL_TREES
                 if os.path.isdir(os.path.join(root, t))]
    handled: Dict[str, Tuple[str, ast.AST]] = {}
    called: Dict[str, Tuple[str, ast.AST]] = {}
    timeouts: Dict[str, Tuple[str, ast.AST]] = {}
    timeout_anchor: Optional[Tuple[str, ast.AST]] = None
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        parsed = parse_file(path)
        if parsed is None:
            continue
        tree, _src = parsed
        findings.extend(_codec_findings(path, tree))
        worker_classes = [
            n for n in module_nodes(tree) if isinstance(n, ast.ClassDef)
            and sum(m.startswith("_op_") for m in _methods(n)) >= 2]
        for cls in worker_classes:
            for m, fn in _methods(cls).items():
                if m.startswith("_op_"):
                    handled.setdefault(m[len("_op_"):], (path, fn))
        for node in module_nodes(tree):
            if worker_classes and isinstance(node, ast.Compare) and \
                    isinstance(node.left, ast.Name) and \
                    node.left.id == "op":
                # inline dispatch (`if op == "shutdown": ...`)
                for comp in node.comparators:
                    if isinstance(comp, ast.Constant) and \
                            isinstance(comp.value, str):
                        handled.setdefault(comp.value, (path, node))
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("call", "send") and \
                    node.args and isinstance(node.args[0], ast.Dict):
                op = _dict_op(node.args[0])
                if op is not None:
                    called.setdefault(op, (path, node))
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Dict) and any(
                        isinstance(t, ast.Name) and
                        t.id == "_OP_TIMEOUTS" for t in node.targets):
                timeout_anchor = (path, node)
                for k in node.value.keys:
                    if isinstance(k, ast.Constant) and \
                            isinstance(k.value, str):
                        timeouts.setdefault(k.value, (path, node))
    if not handled:
        return sorted(findings,
                      key=lambda f: (f.path, f.line, f.message))
    for op in sorted(set(handled) - set(called)):
        p, n = handled[op]
        findings.append(Finding(
            p, n.lineno, n.col_offset, "SGL016",
            f"RPC op '{op}' is handled by the worker dispatch table "
            f"but never sent by any supervisor/tool/test call site — "
            f"dead protocol surface; remove the handler or add the "
            f"caller"))
    for op in sorted(set(called) - set(handled)):
        p, n = called[op]
        findings.append(Finding(
            p, n.lineno, n.col_offset, "SGL016",
            f"RPC op '{op}' is sent at this call site but no worker "
            f"handler (_op_{op} or inline dispatch) exists — the "
            f"worker answers it with an unknown-op error at runtime"))
    if timeout_anchor is not None:
        tp, tn = timeout_anchor
        for op in sorted(set(handled) - set(timeouts)):
            findings.append(Finding(
                tp, tn.lineno, tn.col_offset, "SGL016",
                f"RPC op '{op}' has no _OP_TIMEOUTS deadline entry — "
                f"a hung worker turns that call into an unbounded "
                f"stall; add a deadline row"))
        for op in sorted(set(timeouts) - set(handled)):
            findings.append(Finding(
                tp, tn.lineno, tn.col_offset, "SGL016",
                f"_OP_TIMEOUTS entry '{op}' names an op no worker "
                f"handles — stale deadline row; remove it or restore "
                f"the handler"))
    return sorted(findings, key=lambda f: (f.path, f.line, f.message))


# ---------------------------------------------------------------------------
# process-model discovery (the SGL019 baseline's content)
# ---------------------------------------------------------------------------

def _has_reap(fn: ast.AST, sync: Dict[str, str],
              methods: Dict[str, ast.FunctionDef],
              defs: Dict[str, List[ast.FunctionDef]]) -> bool:
    """A reap (``.wait()``/``.join()``) is reachable inside ``fn`` —
    directly, or one self-helper/local-def level down (``_reap()``)."""

    def direct(body: ast.AST) -> bool:
        for sub in ast.walk(body):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr in ("wait", "join"):
                recv = dotted_name(sub.func.value)
                if recv is None or recv in sync:
                    continue
                if sub.func.attr == "join" and sub.args:
                    continue    # str.join / os.path.join
                return True
        return False

    if direct(fn):
        return True
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Call):
            for h in _helper_bodies(sub, methods, defs):
                if direct(h):
                    return True
    return False


def _module_proc(tree: ast.Module,
                 relpath: str) -> Dict[str, Dict[str, str]]:
    """The four model sections for one parsed module.  Keys are
    ``<relpath>::<scope>`` — file + enclosing scope (dotted through
    closures, so the respawner's ``ProcRouter._respawn.respawn`` is
    distinct) — deliberately line-free so the baseline survives
    unrelated edits; multiple facts in one scope join with ``+``."""
    imports = import_map(tree)
    parents = build_parents(tree)
    defs = _collect_defs(tree)
    sync = _sync_vars(tree, imports)
    sec: Dict[str, Dict[str, Set[str]]] = {s: {} for s in _SECTIONS}

    def add(section: str, node: ast.AST, tag: str) -> None:
        key = f"{relpath}::{_scope_name(node, parents)}"
        sec[section].setdefault(key, set()).add(tag)

    def kill_tag(node: ast.AST, sig: str) -> str:
        fn = _enclosing_function(node, parents)
        if fn is None:
            return f"{sig}!noreap"
        cls = _class_of(node, parents)
        methods = _methods(cls) if cls is not None else {}
        return sig if _has_reap(fn, sync, methods, defs) \
            else f"{sig}!noreap"

    for node in module_nodes(tree):
        if not isinstance(node, ast.Call):
            continue
        full = resolve(node.func, imports) or ""
        attr = node.func.attr \
            if isinstance(node.func, ast.Attribute) else None
        recv = _recv_base(node)
        if full == "subprocess.Popen":
            add("roots", node, "popen")
        elif full.rsplit(".", 1)[-1] == "Process" and \
                "multiprocessing" in full:
            add("roots", node, "mp-process")
        elif attr == "spawn_many":
            add("roots", node, "spawn-call")
        elif full == "os.kill":
            sig = "SIG?"
            if len(node.args) >= 2:
                d = dotted_name(node.args[1]) or ""
                if d.rsplit(".", 1)[-1].startswith("SIG"):
                    sig = d.rsplit(".", 1)[-1]
            add("signals", node, kill_tag(node, sig))
        elif attr == "kill" and recv is not None and recv != "os":
            add("signals", node, kill_tag(node, "SIGKILL"))
        elif attr == "terminate" and recv is not None:
            add("signals", node, kill_tag(node, "SIGTERM"))
        elif attr == "wait" and recv is not None and \
                recv not in sync:
            add("reaps", node, "wait")
        elif attr == "join" and not node.args and \
                recv is not None and recv not in sync:
            add("reaps", node, "join")
        elif attr in ("remove", "pop") and recv is not None and \
                "procs" in recv.split("."):
            add("reaps", node, "ledger")
        elif full == "socket.socket":
            add("sockets", node, "socket")
        elif full == "socket.socketpair":
            add("sockets", node, "socketpair")
        elif attr == "accept" and not node.args:
            add("sockets", node, "accept")
    return {s: {k: "+".join(sorted(v)) for k, v in sec[s].items()}
            for s in _SECTIONS}


def model_hash(model: Dict) -> str:
    """Content hash of the model's sections — recorded in the baseline
    header so a hand-edited model.json fails the gate loudly and the
    ``--update-baselines`` diff stays the only write path."""
    payload = json.dumps({s: model.get(s, {}) for s in _SECTIONS},
                         sort_keys=True, separators=(",", ":"))
    return hashlib.blake2b(payload.encode(),
                           digest_size=8).hexdigest()


def discover_model(paths: Optional[Iterable[str]] = None,
                   root: Optional[str] = None) -> Dict:
    """The tree's process model: every spawn site, signal send, reap
    site, and socket with its scope key.  Uses the framework parse
    cache, so in a bare full audit (where the static rules already
    parsed everything) discovery re-parses nothing."""
    root = root or _REPO_ROOT
    if paths is None:
        paths = [os.path.join(root, t) for t in DEFAULT_TREES]
    sections: Dict[str, Dict[str, str]] = {s: {} for s in _SECTIONS}
    for path in iter_python_files(paths):
        parsed = parse_file(path)
        if parsed is None:
            continue
        tree, _src = parsed
        rel = os.path.relpath(path, start=root).replace(os.sep, "/")
        mod = _module_proc(tree, rel)
        for s in _SECTIONS:
            sections[s].update(mod[s])
    model: Dict = {"schema": PROC_SCHEMA}
    for s in _SECTIONS:
        model[s] = dict(sorted(sections[s].items()))
    model["hash"] = model_hash(model)
    return model


# ---------------------------------------------------------------------------
# the baseline gate (SGL019) + the reviewed-update flow
# ---------------------------------------------------------------------------

#: per-section diff wording: (label, why a NEW entry needs review, why
#: a VANISHED entry needs review)
_SECTION_WORDING = {
    "roots": ("process root",
              "a new spawn site needs human review: check its reap "
              "path and child-env scrub",
              "removed or renamed spawn site (or a discovery "
              "regression)"),
    "signals": ("signal send",
                "a new kill/terminate path needs human review: "
                "'!noreap' means no reap is reachable from it",
                "removed or renamed kill site"),
    "reaps": ("reap site",
              "a new reap path should correspond to a spawn or kill "
              "that needs it",
              "a spawn or kill whose reap vanished leaks zombie "
              "processes"),
    "sockets": ("socket site",
                "a new socket/accept path widens the wire surface",
                "removed or renamed socket site"),
}


def gate_findings(model: Optional[Dict] = None,
                  baseline_path: Optional[str] = None,
                  paths: Optional[Iterable[str]] = None,
                  root: Optional[str] = None) -> List[Finding]:
    """Diff the discovered process model against the committed
    baseline; [] = the mesh is exactly what was last reviewed."""
    baseline_path = baseline_path or MODEL_PATH
    if model is None:
        model = discover_model(paths, root=root)
    base, err = _load_baseline(baseline_path)
    if base is None:
        what = "no committed process-model baseline" \
            if err == "missing" \
            else f"unreadable process-model baseline ({err})"
        return [Finding(baseline_path, 1, 0, "SGL019",
                        f"{what} — every spawn, signal, reap, and "
                        f"socket site must be a reviewed baseline "
                        f"entry; {_UPDATE_HINT}")]
    if base.get("schema") != model.get("schema"):
        return [Finding(baseline_path, 1, 0, "SGL019",
                        f"process-model baseline schema "
                        f"{base.get('schema')!r} does not match the "
                        f"auditor's {model.get('schema')!r} — "
                        f"{_UPDATE_HINT}")]
    if base.get("hash") != model_hash(base):
        return [Finding(baseline_path, 1, 0, "SGL019",
                        f"process-model baseline hash "
                        f"{base.get('hash')!r} does not match its own "
                        f"sections — the committed model.json was "
                        f"hand-edited; the reviewed-diff flow is the "
                        f"only write path: {_UPDATE_HINT}")]
    findings: List[Finding] = []
    for s in _SECTIONS:
        label, why_new, why_gone = _SECTION_WORDING[s]
        bsec, msec = base.get(s, {}), model[s]
        for key in sorted(set(msec) - set(bsec)):
            f, line = _root_file_line(key)
            findings.append(Finding(
                f, line, 0, "SGL019",
                f"NEW {label} {key} ({msec[key]}) is not in the "
                f"committed process model — {why_new}, then "
                f"{_UPDATE_HINT}"))
        for key in sorted(set(bsec) - set(msec)):
            findings.append(Finding(
                baseline_path, 1, 0, "SGL019",
                f"{label} {key} ({bsec[key]}) is in the committed "
                f"model but was not discovered — {why_gone}; "
                f"{_UPDATE_HINT}"))
        for key in sorted(set(bsec) & set(msec)):
            if bsec[key] != msec[key]:
                f, line = _root_file_line(key)
                findings.append(Finding(
                    f, line, 0, "SGL019",
                    f"{label} {key} changed: {bsec[key]} -> "
                    f"{msec[key]} — a reap or signal appearing or "
                    f"vanishing on a process path is exactly what "
                    f"needs review; {_UPDATE_HINT}"))
    return sorted(findings, key=lambda f: (f.path, f.line, f.message))


def audit_findings(root: Optional[str] = None) -> List[Finding]:
    """Everything the ``--proc`` mode gates: the SGL019 model diff
    plus the SGL016 protocol cross-check."""
    out = gate_findings(root=root) + protocol_findings(root=root)
    return sorted(out, key=lambda f: (f.path, f.line, f.message))


def update_model_baseline(model: Optional[Dict] = None,
                          baseline_path: Optional[str] = None,
                          paths: Optional[Iterable[str]] = None,
                          root: Optional[str] = None) -> str:
    """Write the discovered model (hash included) as the new committed
    baseline and return the human-readable diff — the reviewed
    artifact of an intentional process-mesh change (same flow as the
    conc/HLO baselines)."""
    baseline_path = baseline_path or MODEL_PATH
    if model is None:
        model = discover_model(paths, root=root)
    base, _err = _load_baseline(baseline_path)
    base = base or {}
    lines: List[str] = []
    for s in _SECTIONS:
        label = s[:-1]    # roots -> root, signals -> signal, ...
        bsec, msec = base.get(s, {}), model[s]
        for key in sorted(set(msec) - set(bsec)):
            lines.append(f"+ {label} {key}: {msec[key]}")
        for key in sorted(set(bsec) - set(msec)):
            lines.append(f"- {label} {key}: {bsec[key]}")
        for key in sorted(set(bsec) & set(msec)):
            if bsec[key] != msec[key]:
                lines.append(f"~ {label} {key}: {bsec[key]} -> "
                             f"{msec[key]}")
    if not lines:
        lines.append("process model unchanged")
    os.makedirs(os.path.dirname(baseline_path), exist_ok=True)
    with open(baseline_path, "w", encoding="utf-8") as f:
        json.dump(model, f, indent=2, sort_keys=True)
        f.write("\n")
    return "\n".join(lines)
