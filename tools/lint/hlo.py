"""hloaudit — the compiled-program invariant gate (ROADMAP item 5).

singalint's AST rules guard the *Python* half of this repo's
invariants; the performance truth of a TPU-native framework lives in
what XLA actually emitted — fusion decisions dominate achieved
throughput ("Operator Fusion in XLA", arXiv:2301.13062) and schedules
have to be audited at the compiled-program level (FADiff,
arXiv:2511.22348).  This module turns the hand-rolled one-off
assertions ("jit cache size == 2", "'all-reduce' in compiled_hlo()")
into a general regression gate:

1. **lower** the flagship programs — the Llama train step (fused
   CE-chunk loss; single-device and 2-way data-parallel variants) and
   the serve engine's prefill-chunk / decode-over-block-tables — to
   *optimized* HLO text on the CPU backend with tiny configs (no chips
   needed; ``ServeEngine.lower_programs()`` and the graph executor's
   ``CapturedGraph.compiled`` are the hooks);
2. **summarize** each module structurally: fusion count and kinds, op
   histogram, collective ops and whether they sit inside a loop body
   (the overlap path), while/remat bodies, entry parameter count, and
   donation aliasing (``input_output_alias`` — the KV arena and
   optimizer-state donations);
3. **diff** the summaries against committed per-program baselines under
   ``tools/lint/data/hlo/``, failing loudly (exit 1) with a named
   finding per drifted metric — a new op splitting the CE-chunk fusion,
   a collective migrating out of the loop body, a lost donation.

Intentional changes are one reviewed command:
``python -m tools.lint --hlo --update-baselines`` rewrites the
baselines and prints a human-readable metric diff for the PR.

A baseline file may carry ``"suppress": {"HLO006": "<reason>"}`` to
waive one metric for one program — the reason is REQUIRED (an empty
one is itself a finding, HLO000), mirroring the singalint suppression
contract.

Everything jax lives behind function-local imports: importing this
module (e.g. for :func:`assert_program_count` in tests) must stay as
cheap as importing the AST rules.
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

from .framework import Finding

__all__ = ["assert_program_count", "summarize_hlo", "diff_summaries",
           "gate_findings", "lower_flagship_texts", "lower_train_step",
           "update_baselines", "load_baselines", "audit_payload",
           "hlo_main", "BASELINE_DIR", "FLAGSHIP_PROGRAMS", "HLO_CODES",
           "SUMMARY_SCHEMA"]

_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

#: committed per-program baselines live here, one JSON file per program
BASELINE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "data", "hlo")

#: the audited programs, in lowering order.  train_step is the flagship
#: decoder's compiled step (fused CE-chunk loss — the lax.scan while
#: body the gate protects); train_step_dp2 is the same step under a
#: 2-way 'data' mesh with DistOpt, which is what puts real all-reduce
#: ops into the module so collective count/placement are non-vacuous;
#: train_step_dp2_int8 is that DP step with
#: ``DistOpt(compression="int8_ring")`` — error-feedback int8 ring
#: gradient sync, whose committed COST005 wire_bytes baseline proves
#: (and permanently gates) the >=3x wire reduction vs train_step_dp2's
#: f32 collectives; prefill_chunk / decode are the serve engine's
#: exactly-two programs; verify is the SPECULATIVE engine's third
#: program (serve/spec.py: k+1 draft propose steps + one k+1-token
#: target verify in a single dispatch, both arenas donated — lowered
#: from a self-speculation engine at spec_k=2, which carries the same
#: structure as any draft at the audited tiny config); handoff_gather
#: is the engine's optional program for the disaggregated tier's KV
#: handoff source (one slot's dense per-layer view through its
#: block-table row; no donation by design, so a failed handoff leaves
#: the source arena valid); decode_int8 is the decode step over an
#: int8 KV arena (serve/mem.py: QuantKV block pools, quantize-on-
#: scatter / dequantize-on-gather inside the paged primitives) —
#: its committed COST003 hbm_bytes baseline proves (and permanently
#: gates) the KV-traffic drop vs decode's f32 arena that is the whole
#: point of the int8 tier.
FLAGSHIP_PROGRAMS = ("train_step", "train_step_dp2",
                     "train_step_dp2_int8", "prefill_chunk", "decode",
                     "verify", "handoff_gather", "decode_int8")

#: summary format version — bump on incompatible metric changes; a
#: baseline with another version fails the gate (HLO001) instead of
#: diffing garbage
SUMMARY_SCHEMA = 1

#: finding codes, one per metric (the "named finding per drifted
#: metric" contract) — enumerated by ``--list-rules``
HLO_CODES = {
    "HLO000": ("suppression-hygiene", "a baseline 'suppress' entry "
               "without a reason, or naming an unknown metric code, is "
               "itself a finding and cannot be waived"),
    "HLO001": ("program-set", "every audited program has a committed, "
               "parseable, same-schema baseline — and every baseline "
               "has a lowered program"),
    "HLO002": ("fusion", "fusion count and kind histogram match the "
               "baseline (a new op splitting the CE-chunk fusion lands "
               "here)"),
    "HLO003": ("collective", "collective op count and opcode set match "
               "the baseline"),
    "HLO004": ("collective-placement", "collectives inside loop bodies "
               "stay there (a collective migrating off the overlap "
               "path lands here)"),
    "HLO005": ("donation", "input/output buffer aliasing "
               "(donate_argnums: the KV arena, params/opt state) is "
               "not lost"),
    "HLO006": ("op-histogram", "the module's opcode histogram matches "
               "the baseline"),
    "HLO007": ("while-loop", "while/remat body count matches the "
               "baseline (the CE-chunk scan, remat replays)"),
    "HLO008": ("interface", "entry-computation parameter count matches "
               "the baseline"),
}

#: HLO opcodes that are cross-device collectives
_COLLECTIVE_OPS = frozenset({
    "all-reduce", "all-reduce-start", "all-reduce-done",
    "all-gather", "all-gather-start", "all-gather-done",
    "reduce-scatter", "collective-permute", "collective-permute-start",
    "collective-permute-done", "all-to-all", "collective-broadcast",
})


# ---------------------------------------------------------------------------
# the shared jit-cache helper (no jax import needed)
# ---------------------------------------------------------------------------

def assert_program_count(obj, expected) -> None:
    """Assert the compiled-program count of an engine or jitted
    function(s) — the ONE implementation of the serve two-program
    contract, shared by tests/test_serve.py, tests/test_faults.py and
    this gate (an engine that silently recompiles would drift every
    HLO metric at once; an assertion names the drift immediately).

    ``obj`` may be a ServeEngine (``compiled_counts()``), a sequence of
    jitted functions, or one jitted function; ``expected`` is the
    matching tuple (or int for a single function)."""
    if hasattr(obj, "compiled_counts"):
        actual: object = tuple(obj.compiled_counts())
        expected = tuple(expected)
    elif isinstance(obj, (tuple, list)):
        actual = tuple(f._cache_size() for f in obj)
        expected = tuple(expected)
    else:
        actual = obj._cache_size()
        expected = int(expected)
    assert actual == expected, (
        f"compiled-program count drifted: expected {expected}, got "
        f"{actual} — a new input shape/dtype leaked into a jitted "
        f"program (the no-recompile contract; see docs/serving.md)")


# ---------------------------------------------------------------------------
# HLO text -> structural summary
# ---------------------------------------------------------------------------

_COMP_HEADER_RE = re.compile(
    r"^(ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->")
_INSTR_RE = re.compile(
    r"^\s+(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(.*)$")
_OPCODE_RE = re.compile(r"\s([a-z][a-z0-9\-]*)\(")
_CALLED_RE = re.compile(
    r"(?:calls|body|condition|to_apply|branch_computations|"
    r"true_computation|false_computation)=\{?%?([\w.\-]+)")
_FUSION_KIND_RE = re.compile(r"\bkind=(\w+)")
_WHILE_BODY_RE = re.compile(r"\bwhile\(.*\bbody=%?([\w.\-]+)")


def _alias_count(text: str) -> int:
    """Number of aliased (donated) outputs in the module header's
    ``input_output_alias={ {out}: (arg, {}, may-alias), ... }``."""
    m = re.search(r"input_output_alias=\{", text)
    if m is None:
        return 0
    i, depth, start = m.end() - 1, 0, m.end() - 1
    while i < len(text):
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
            if depth == 0:
                break
        i += 1
    return text[start:i].count("-alias")


def summarize_hlo(text: str, program: str) -> Dict:
    """Parse one optimized-HLO module's text into the structural
    summary the gate diffs.  Purely textual — no jax."""
    comps: Dict[str, List[str]] = {}          # computation -> opcodes
    called: Dict[str, List[str]] = {}         # computation -> callees
    entry: Optional[str] = None
    cur: Optional[str] = None
    while_bodies: List[str] = []
    fusion_kinds: Dict[str, int] = {}

    for line in text.splitlines():
        if line and not line[0].isspace():
            mh = _COMP_HEADER_RE.match(line)
            if mh:
                cur = mh.group(2)
                comps.setdefault(cur, [])
                if mh.group(1):
                    entry = cur
                continue
        mi = _INSTR_RE.match(line)
        if mi is None or cur is None:
            continue
        rhs = mi.group(1)
        mo = _OPCODE_RE.search(" " + rhs)
        if mo is None:
            continue
        op = mo.group(1)
        comps[cur].append(op)
        for mc in _CALLED_RE.finditer(rhs):
            called.setdefault(cur, []).append(mc.group(1))
        if op == "fusion":
            mk = _FUSION_KIND_RE.search(rhs)
            kind = mk.group(1) if mk else "unknown"
            fusion_kinds[kind] = fusion_kinds.get(kind, 0) + 1
        if op == "while":
            mw = _WHILE_BODY_RE.search(rhs)
            if mw:
                while_bodies.append(mw.group(1))

    # computations reachable from a while body = "inside the loop"
    in_loop: set = set()
    frontier = list(while_bodies)
    while frontier:
        c = frontier.pop()
        if c in in_loop:
            continue
        in_loop.add(c)
        frontier.extend(called.get(c, []))

    histogram: Dict[str, int] = {}
    coll_by_op: Dict[str, int] = {}
    coll_in_loop = 0
    for comp, ops in comps.items():
        for op in ops:
            histogram[op] = histogram.get(op, 0) + 1
            if op in _COLLECTIVE_OPS:
                coll_by_op[op] = coll_by_op.get(op, 0) + 1
                if comp in in_loop:
                    coll_in_loop += 1

    entry_params = (comps.get(entry, []).count("parameter")
                    if entry is not None else 0)
    return {
        "schema": SUMMARY_SCHEMA,
        "program": program,
        "entry_params": entry_params,
        "donated_outputs": _alias_count(text),
        "fusions": {"total": sum(fusion_kinds.values()),
                    "kinds": dict(sorted(fusion_kinds.items()))},
        "while_loops": histogram.get("while", 0),
        "collectives": {"total": sum(coll_by_op.values()),
                        "by_op": dict(sorted(coll_by_op.items())),
                        "in_loop_body": coll_in_loop},
        "op_histogram": dict(sorted(histogram.items())),
    }


# ---------------------------------------------------------------------------
# summary diff -> findings
# ---------------------------------------------------------------------------

def _histogram_drift(base: Dict[str, int],
                     cur: Dict[str, int]) -> List[str]:
    """Human fragments for opcode-set and count changes, worst first."""
    out = []
    for op in sorted(set(cur) - set(base)):
        out.append(f"new op {op!r} (x{cur[op]})")
    for op in sorted(set(base) - set(cur)):
        out.append(f"op {op!r} vanished (was x{base[op]})")
    for op in sorted(set(base) & set(cur)):
        if base[op] != cur[op]:
            out.append(f"{op}: {base[op]} -> {cur[op]}")
    return out


def _baseline_suppressions(baseline: Dict, path: str, codes: Dict,
                           hygiene_code: str) -> Tuple[set, List[Finding]]:
    """Waived metric codes of one baseline, plus hygiene findings for
    waivers without a reason / naming unknown codes (the hygiene code —
    HLO000 or COST000 — which, like SGL000, cannot itself be waived).
    ONE implementation of the baseline-waiver contract, shared by the
    structural gate and the cost gate (tools/lint/cost.py)."""
    sup = baseline.get("suppress", {})
    waived: set = set()
    bad: List[Finding] = []
    for code, reason in sorted(sup.items() if isinstance(sup, dict) else ()):
        if code not in codes or code == hygiene_code:
            bad.append(Finding(path, 1, 0, hygiene_code,
                               f"baseline waives unknown metric code "
                               f"{code!r} (known: "
                               f"{', '.join(sorted(codes))})"))
        elif not (isinstance(reason, str) and reason.strip()):
            bad.append(Finding(path, 1, 0, hygiene_code,
                               f"baseline waiver of {code} carries no "
                               f"reason — an unexplained waiver is the "
                               f"silent drift this gate exists to stop"))
        else:
            waived.add(code)
    return waived, bad


def _suppressions_of(baseline: Dict, path: str) -> Tuple[set, List[Finding]]:
    return _baseline_suppressions(baseline, path, HLO_CODES, "HLO000")


def diff_summaries(program: str, baseline: Dict, current: Dict,
                   path: str) -> List[Finding]:
    """Named finding per drifted metric of one program."""
    waived, findings = _suppressions_of(baseline, path)

    def fnd(code: str, msg: str) -> None:
        if code in waived:
            return
        findings.append(Finding(path, 1, 0, code,
                                f"[{program}] {msg} — if intentional, "
                                f"re-baseline with 'python -m tools.lint "
                                f"--hlo --update-baselines'"))

    if baseline.get("schema") != current.get("schema"):
        findings.append(Finding(
            path, 1, 0, "HLO001",
            f"[{program}] baseline summary schema "
            f"{baseline.get('schema')!r} does not match the auditor's "
            f"{current.get('schema')!r} — regenerate with "
            f"--update-baselines"))
        return findings

    bf, cf = baseline.get("fusions", {}), current.get("fusions", {})
    if bf.get("total") != cf.get("total") or \
            bf.get("kinds") != cf.get("kinds"):
        fnd("HLO002",
            f"fusion structure drifted: {bf.get('total')} fusions "
            f"{bf.get('kinds')} -> {cf.get('total')} fusions "
            f"{cf.get('kinds')} (an op falling out of a fusion — e.g. "
            f"a defused CE chunk — lands here)")

    bc = baseline.get("collectives", {})
    cc = current.get("collectives", {})
    if bc.get("total") != cc.get("total") or \
            bc.get("by_op") != cc.get("by_op"):
        fnd("HLO003",
            f"collective ops drifted: {bc.get('by_op')} -> "
            f"{cc.get('by_op')}")
    if bc.get("in_loop_body") != cc.get("in_loop_body"):
        fnd("HLO004",
            f"collective placement drifted: {bc.get('in_loop_body')} "
            f"inside loop bodies -> {cc.get('in_loop_body')} (a "
            f"collective migrated {'out of' if (cc.get('in_loop_body') or 0) < (bc.get('in_loop_body') or 0) else 'into'} "
            f"the loop/overlap path)")

    if baseline.get("donated_outputs") != current.get("donated_outputs"):
        b, c = baseline.get("donated_outputs"), current.get("donated_outputs")
        fnd("HLO005",
            f"donation aliasing drifted: {b} aliased outputs -> {c}"
            f"{' (a donation was LOST: the arena/state now copies every dispatch)' if (c or 0) < (b or 0) else ''}")

    drift = _histogram_drift(baseline.get("op_histogram", {}),
                             current.get("op_histogram", {}))
    if drift:
        shown = "; ".join(drift[:8])
        more = len(drift) - 8
        fnd("HLO006",
            f"op histogram drifted ({len(drift)} opcode(s)): {shown}"
            f"{f'; ... {more} more' if more > 0 else ''}")

    if baseline.get("while_loops") != current.get("while_loops"):
        fnd("HLO007",
            f"while/remat body count drifted: "
            f"{baseline.get('while_loops')} -> "
            f"{current.get('while_loops')}")

    if baseline.get("entry_params") != current.get("entry_params"):
        fnd("HLO008",
            f"entry parameter count drifted: "
            f"{baseline.get('entry_params')} -> "
            f"{current.get('entry_params')}")
    return findings


# ---------------------------------------------------------------------------
# baselines on disk
# ---------------------------------------------------------------------------

def _baseline_path(program: str, baseline_dir: str) -> str:
    return os.path.join(baseline_dir, f"{program}.json")


def load_baselines_dir(baseline_dir: str, code: str,
                       what: str = "baseline"
                       ) -> Tuple[Dict[str, Dict], List[Finding]]:
    """All committed baselines of one family (structure or cost), plus
    program-set findings for unreadable files.  A missing DIRECTORY is
    not a finding here — the gate reports per-program misses so the
    message can name the program.  ONE implementation shared by both
    gate families so a fix to this path cannot miss one of them."""
    out: Dict[str, Dict] = {}
    bad: List[Finding] = []
    if not os.path.isdir(baseline_dir):
        return out, bad
    for name in sorted(os.listdir(baseline_dir)):
        if not name.endswith(".json"):
            continue
        path = os.path.join(baseline_dir, name)
        try:
            with open(path, encoding="utf-8") as f:
                out[name[:-len(".json")]] = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            bad.append(Finding(path, 1, 0, code,
                               f"unreadable {what}: {e}"))
    return out, bad


def gate_findings_dir(summaries: Dict[str, Dict], baseline_dir: str,
                      code: str, what: str, diff_fn,
                      review_hint: str) -> List[Finding]:
    """The shared program-set gate core: diff each lowered program
    against its committed baseline via ``diff_fn``, and make misses
    loud in BOTH directions (no baseline / stale baseline) under the
    family's program-set ``code``."""
    baselines, findings = load_baselines_dir(baseline_dir, code, what)
    for program, summary in summaries.items():
        path = _baseline_path(program, baseline_dir)
        base = baselines.get(program)
        if base is None:
            findings.append(Finding(
                path, 1, 0, code,
                f"[{program}] no committed {what} — run 'python -m "
                f"tools.lint --hlo --update-baselines' and review the "
                f"{review_hint} it writes"))
            continue
        findings.extend(diff_fn(program, base, summary, path))
    for program in sorted(set(baselines) - set(summaries)):
        findings.append(Finding(
            _baseline_path(program, baseline_dir), 1, 0, code,
            f"[{program}] {what} exists but the program was not "
            f"lowered — renamed/removed program, or a partial audit; "
            f"delete the stale {what} or fix the lowering"))
    return sorted(findings, key=lambda f: (f.path, f.code))


def update_baselines_dir(summaries: Dict[str, Dict], baseline_dir: str,
                         code: str, what: str, diff_fn, describe,
                         unchanged_label: str) -> str:
    """The shared ``--update-baselines`` core: write the summaries as
    the new baselines (preserving each program's ``suppress`` block,
    pruning stale programs loudly) and return the human-readable metric
    diff — the reviewed artifact of an intentional change."""
    os.makedirs(baseline_dir, exist_ok=True)
    old, _bad = load_baselines_dir(baseline_dir, code, what)
    lines: List[str] = []
    for program, summary in summaries.items():
        path = _baseline_path(program, baseline_dir)
        base = old.get(program)
        if base is None:
            lines.append(f"{program}: NEW {what} ({describe(summary)})")
        else:
            drifted = diff_fn(program, base, summary, path)
            if drifted:
                lines.append(f"{program}:")
                lines.extend(f"  {f.code} {f.message}" for f in drifted)
            else:
                lines.append(f"{program}: {unchanged_label}")
            sup = base.get("suppress")
            if sup:
                summary = dict(summary, suppress=sup)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(summary, f, indent=2, sort_keys=True)
            f.write("\n")
    for program in sorted(set(old) - set(summaries)):
        os.remove(_baseline_path(program, baseline_dir))
        lines.append(f"{program}: {what} REMOVED (program no longer "
                     f"lowered)")
    return "\n".join(lines)


def load_baselines(baseline_dir: Optional[str] = None
                   ) -> Tuple[Dict[str, Dict], List[Finding]]:
    """The structural family's committed baselines (HLO001 findings for
    unreadable files)."""
    return load_baselines_dir(baseline_dir or BASELINE_DIR, "HLO001")


def gate_findings(summaries: Dict[str, Dict],
                  baseline_dir: Optional[str] = None) -> List[Finding]:
    """Diff lowered summaries against the committed baselines; the
    gate's whole verdict as findings ([] = clean)."""
    return gate_findings_dir(summaries, baseline_dir or BASELINE_DIR,
                             "HLO001", "baseline", diff_summaries,
                             "summary")


def update_baselines(summaries: Dict[str, Dict],
                     baseline_dir: Optional[str] = None) -> str:
    """Write the summaries as the new structural baselines; see
    :func:`update_baselines_dir`."""
    return update_baselines_dir(
        summaries, baseline_dir or BASELINE_DIR, "HLO001", "baseline",
        diff_summaries,
        lambda s: (f"{s['fusions']['total']} fusions, "
                   f"{s['collectives']['total']} collectives, "
                   f"{s['while_loops']} while loops, "
                   f"{s['donated_outputs']} donated outputs"),
        "unchanged")


def audit_payload(summaries: Dict[str, Dict],
                  findings: Iterable[Finding],
                  cost_summaries: Optional[Dict[str, Dict]] = None) -> Dict:
    """The ``hlo_audit`` record payload (obs.schema): the drift-history
    quantities that accumulate in runs/records.jsonl next to the perf
    trajectory.  With ``cost_summaries`` (tools/lint/cost.py — the
    normal full-audit case), the payload carries the extended cost
    numerics too: total flops / HBM / wire bytes, the max per-program
    peak, and the per-program feature rows the autotuner consumes."""
    payload = {
        "programs": len(summaries),
        "drifted": len(list(findings)),
        "fusions": sum(s["fusions"]["total"] for s in summaries.values()),
        "collectives": sum(s["collectives"]["total"]
                           for s in summaries.values()),
        "while_loops": sum(s["while_loops"] for s in summaries.values()),
    }
    if cost_summaries is not None:
        # omitted entirely when the cost pass did not run: a record
        # with literal-zero flops would read as a measurement, and the
        # schema's required-field check then rejects the append loudly
        cs = cost_summaries
        payload["flops"] = sum(s["flops"] for s in cs.values())
        payload["hbm_bytes"] = sum(s["hbm_bytes"] for s in cs.values())
        payload["wire_bytes"] = sum(s["wire_bytes"] for s in cs.values())
        payload["peak_bytes"] = max(
            (s["peak_bytes"] for s in cs.values()), default=0)
        payload["cost_per_program"] = {
            name: {"flops": s["flops"], "hbm_bytes": s["hbm_bytes"],
                   "peak_bytes": s["peak_bytes"],
                   "wire_bytes": s["wire_bytes"]}
            for name, s in sorted(cs.items())}
    return payload


# ---------------------------------------------------------------------------
# lowering the flagship programs (jax from here down)
# ---------------------------------------------------------------------------

def _ensure_cpu_backend() -> None:
    """Pin the virtual-CPU platform (the canonical recipe — this
    image's sitecustomize force-registers the TPU plugin).  8 devices
    to match tests/conftest.py exactly, so baselines generated by the
    CLI and checked under pytest see the same platform."""
    import sys
    if _REPO_ROOT not in sys.path:
        sys.path.insert(0, _REPO_ROOT)
    from singa_tpu.utils.virtcpu import pin_virtual_cpu
    if not pin_virtual_cpu(8):
        raise RuntimeError(
            "the HLO audit needs the virtual-CPU backend but another "
            "JAX backend is already initialized in this process — run "
            "it in a fresh process (python -m tools.lint --hlo)")
    import jax
    # conftest.py sets this for every test process; the audit must
    # lower the same programs the tests see
    jax.config.update("jax_default_matmul_precision", "highest")


def lower_train_step(dp: bool = False, fused_loss: bool = True,
                     ce_chunk: Optional[int] = None,
                     compression: Optional[str] = None) -> str:
    """Optimized-HLO text of the flagship (tiny-config) compiled train
    step: Llama + fused CE-chunk loss + SGD, through the real graph
    executor — so the audited module IS the module training runs.  With
    ``dp``, the same step under a 2-way 'data' mesh with DistOpt (the
    in-graph gradient all-reduce); ``compression="int8_ring"`` (implies
    the DP variant's mesh) swaps the f32 all-reduces for the
    error-feedback int8 ring — the train_step_dp2_int8 program whose
    committed wire_bytes baseline enforces the byte win.
    ``fused_loss=False`` builds the deliberately-defused variant the
    regression tests feed the gate; ``ce_chunk`` overrides
    ``fused_loss_chunk`` (the cost-gate tests lower a many-chunk
    variant to prove flops/HBM drift is caught)."""
    _ensure_cpu_backend()
    import numpy as np
    from singa_tpu import models, opt, parallel, tensor

    tensor.set_seed(0)
    np.random.seed(0)
    # ONE transformer block: XLA compile time scales with instruction
    # count (layer count — measured 3x the gate latency at tiny()'s two
    # blocks), and one block already carries every audited structure:
    # the fused CE-chunk scan, attention/FFN fusions, params/opt-state
    # donation, and the DP gradient all-reduces.  The serve programs
    # keep tiny()'s two layers — the repeated per-layer paging pattern
    # is itself an audited structure there.
    cfg = models.LlamaConfig.tiny()
    cfg.num_layers = 1
    cfg.fused_loss = fused_loss
    if ce_chunk is not None:
        cfg.fused_loss_chunk = ce_chunk
    saved_mesh = parallel.current_mesh()
    dp = dp or compression is not None
    try:
        if dp:
            parallel.set_mesh(parallel.make_mesh({"data": 2}))
        else:
            parallel.set_mesh(None)
        m = models.Llama(cfg)
        m.set_optimizer(opt.DistOpt(opt.SGD(lr=0.01, momentum=0.9),
                                    compression=compression)
                        if dp else opt.SGD(lr=0.01, momentum=0.9))
        ids = tensor.from_numpy(np.zeros((2, 16), np.int32))
        m.compile([ids], is_train=True, use_graph=True)
        m.train_step(ids)
        return m.graph.compiled_hlo()
    finally:
        parallel.set_mesh(saved_mesh)


def _lower_serve_programs(want_verify: bool = True,
                          want_int8: bool = True) -> Dict[str, str]:
    """Optimized-HLO texts of the serve engine's exactly-two programs
    plus the optional handoff gather (tiny Llama, 2 slots) via
    ``ServeEngine.lower_programs()`` — and, from a SECOND, speculative
    engine (self-speculation draft at spec_k=2), the ``verify``
    program, and from a THIRD engine with ``kv_dtype="int8"``, the
    ``decode_int8`` program.  The plain engine stays the source of the
    prefill/decode/handoff baselines (a spec engine's prefill also
    writes the draft arena, and an int8 engine's programs carry QuantKV
    arena leaves — different audited modules), and each extra engine
    contributes exactly its one extra flagship program, so each is
    still lowered exactly once."""
    _ensure_cpu_backend()
    import numpy as np
    from singa_tpu import models, tensor
    from singa_tpu.serve import ServeEngine

    tensor.set_seed(0)
    np.random.seed(0)
    m = models.Llama(models.LlamaConfig.tiny())
    m.eval()
    m.compile([tensor.from_numpy(np.zeros((1, 4), np.int32))],
              is_train=False, use_graph=False)
    eng = ServeEngine(m, num_slots=2, max_len=16, block_size=8)
    texts = {name: lowered.compile().as_text()
             for name, lowered in eng.lower_programs().items()}
    # lowering must never have touched the engine's own executables
    assert_program_count(eng, (0, 0))
    if want_verify:
        spec_eng = ServeEngine(m, num_slots=2, max_len=16, block_size=8,
                               draft_model=m, spec_k=2)
        lowered = spec_eng.lower_programs(names=("verify",))
        texts["verify"] = lowered["verify"].compile().as_text()
        assert spec_eng.spec_compiled_counts() == (0, 0, 0, 0)
    if want_int8:
        q_eng = ServeEngine(m, num_slots=2, max_len=16, block_size=8,
                            kv_dtype="int8")
        lowered = q_eng.lower_programs(names=("decode",))
        texts["decode_int8"] = lowered["decode"].compile().as_text()
        assert_program_count(q_eng, (0, 0))
    return texts


def lower_flagship_texts(programs: Optional[Iterable[str]] = None
                         ) -> Dict[str, str]:
    """Optimized-HLO text per flagship program (CPU backend, tiny
    configs).  ``programs`` restricts the set — the test fixture lowers
    everything once and shares it."""
    wanted = tuple(programs) if programs is not None else FLAGSHIP_PROGRAMS
    unknown = set(wanted) - set(FLAGSHIP_PROGRAMS)
    if unknown:
        raise ValueError(f"unknown program(s): {sorted(unknown)} "
                         f"(known: {FLAGSHIP_PROGRAMS})")
    texts: Dict[str, str] = {}
    if "train_step" in wanted:
        texts["train_step"] = lower_train_step()
    if "train_step_dp2" in wanted:
        texts["train_step_dp2"] = lower_train_step(dp=True)
    if "train_step_dp2_int8" in wanted:
        texts["train_step_dp2_int8"] = lower_train_step(
            compression="int8_ring")
    serve_names = ("prefill_chunk", "decode", "verify", "handoff_gather",
                   "decode_int8")
    if any(name in wanted for name in serve_names):
        serve = _lower_serve_programs(
            want_verify="verify" in wanted,
            want_int8="decode_int8" in wanted)
        for name in serve_names:
            if name in wanted:
                texts[name] = serve[name]
    return {name: texts[name] for name in wanted}


def flagship_summaries(programs: Optional[Iterable[str]] = None,
                       texts: Optional[Dict[str, str]] = None
                       ) -> Dict[str, Dict]:
    """Structural summary per flagship program.  Pass already-lowered
    ``texts`` to reuse a lowering (the cost gate shares ONE lowering
    pass with this gate — lower once, audit twice)."""
    if texts is None:
        texts = lower_flagship_texts(programs)
    return {name: summarize_hlo(text, name) for name, text in texts.items()}


# ---------------------------------------------------------------------------
# CLI body (shared by `python -m tools.lint --hlo` and tools/hlo_audit.py)
# ---------------------------------------------------------------------------

def hlo_main(update: bool = False, json_out: bool = False,
             baseline_dir: Optional[str] = None,
             structure: bool = True, cost_gate: bool = True,
             cost_baseline_dir: Optional[str] = None,
             static_findings: Optional[List[Finding]] = None) -> int:
    """Lower ONCE, then audit twice: the structural gate (fusions,
    collectives, donation — HLO00x) and the cost gate (flops, HBM
    traffic, peak memory, wire bytes — COST00x, tools/lint/cost.py)
    both summarize the SAME lowered texts.  ``structure``/``cost_gate``
    select the halves (``--select hlo`` / ``--select cost``); with
    ``update``, both baseline families are rewritten with a
    human-readable metric diff.  Exit codes follow the lint front door:
    0 clean, 1 findings.  ``static_findings`` merges the bare full
    audit's static results into the single ``json_out`` document (the
    --json contract: stdout is ONE parseable object); drift history
    reaches runs/records.jsonl via bench.py, which runs this CLI with
    --json in a pinned-CPU subprocess and appends the ``hlo`` payload."""
    from .framework import render_human, render_json
    from . import cost

    texts = lower_flagship_texts()
    summaries = flagship_summaries(texts=texts) if structure else {}
    cost_summaries = cost.cost_summaries(texts) if cost_gate else None
    if update:
        parts = []
        if structure:
            parts.append(update_baselines(summaries, baseline_dir))
        if cost_gate:
            parts.append(cost.update_cost_baselines(
                cost_summaries, cost_baseline_dir))
        print("\n".join(parts))
        print(f"hlo_audit: baselines updated under "
              f"{baseline_dir or BASELINE_DIR}"
              + (f" and {cost_baseline_dir or cost.COST_BASELINE_DIR}"
                 if cost_gate else "")
              + " — review the diff above")
        return 0
    findings = gate_findings(summaries, baseline_dir) if structure else []
    if cost_gate:
        findings = findings + cost.cost_gate_findings(
            cost_summaries, cost_baseline_dir)
    if json_out:
        doc = json.loads(render_json(list(static_findings or []) +
                                     findings))
        doc["hlo"] = audit_payload(summaries, findings, cost_summaries)
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        # same rendering as the static rules; only the banner differs
        print(render_human(findings).replace("singalint:", "hlo_audit:"))
    return 1 if findings else 0
