"""Dynamic-audit implementations behind the ``tools.lint`` front door.

``python -m tools.lint --records [ROOT]`` and ``--ckpt DIR`` run the
same checks the standalone CLIs (``tools/record_check.py``,
``tools/ckpt_fsck.py``) expose — those files are now thin shims over
this module, so the audit logic has exactly one home and the linter is
the single entry point for "is this tree/record-store/checkpoint-dir
sound?".

Imports of ``singa_tpu`` happen lazily inside the functions: the static
rules must stay runnable (and fast) on machines where jax is absent or
slow to initialize.
"""

from __future__ import annotations

import glob
import json
import os
import sys
from typing import List, Optional, Tuple

_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))


def _ensure_repo_on_path() -> None:
    if _REPO_ROOT not in sys.path:
        sys.path.insert(0, _REPO_ROOT)


def _load_json(path: str):
    try:
        with open(path) as f:
            return json.load(f), None
    except json.JSONDecodeError as e:
        return None, f"{path}: not valid JSON ({e.msg} at line {e.lineno})"
    except OSError as e:
        return None, f"{path}: unreadable ({e})"


def check_records_root(root: str) -> List[str]:
    """Validate every committed telemetry record under ``root`` against
    the obs schema; returns error strings ([] = all valid).

    Covers ``tpu_session*.json`` / ``*_session.json`` (session docs, v1
    strict / legacy structural), ``BENCH_*.json`` / ``MULTICHIP_*.json``
    (driver records) and ``runs/records.jsonl`` (the RunRecord store:
    every line strictly valid, no duplicate keys)."""
    _ensure_repo_on_path()
    from singa_tpu.obs import record as obs_record
    from singa_tpu.obs import schema

    errors: List[str] = []

    def run(validator, path):
        doc, err = _load_json(path)
        if err:
            errors.append(err)
            return
        errors.extend(schema.collect_errors(validator, doc, path))

    for path in sorted(glob.glob(os.path.join(root, "tpu_session*.json"))):
        run(schema.validate_session_doc, path)
    for path in sorted(glob.glob(os.path.join(root, "*_session.json"))):
        if os.path.basename(path).startswith("tpu_session"):
            continue  # already covered by the pattern above
        run(schema.validate_session_doc, path)
    for path in sorted(glob.glob(os.path.join(root, "BENCH_*.json"))):
        run(schema.validate_bench_doc, path)
    for path in sorted(glob.glob(os.path.join(root, "MULTICHIP_*.json"))):
        run(schema.validate_multichip_doc, path)

    store = os.path.join(root, obs_record.DEFAULT_STORE)
    if os.path.exists(store):
        errors.extend(obs_record.RunRecord(store).validate())
        errors.extend(_check_flight_refs(store))
        errors.extend(_check_perf_attr(store))
    errors.extend(_check_incident_dumps(root))
    errors.extend(_check_autotune(root, store))
    return errors


def _check_perf_attr(store: str) -> List[str]:
    """Every committed ``perf_attr`` entry's program keys must be a
    subset of ``hlo.FLAGSHIP_PROGRAMS`` (the schema checks row shape;
    a key the cost model never lowered has no modeled side, so its
    'achieved fraction' would be a join against nothing — exactly the
    unfalsifiable number this record kind exists to ban)."""
    _ensure_repo_on_path()
    from singa_tpu.obs import record as obs_record
    from singa_tpu.obs import schema

    from .hlo import FLAGSHIP_PROGRAMS

    errors: List[str] = []
    try:
        entries = obs_record.RunRecord(store).entries()
    except schema.SchemaError:
        return []          # the store lint above already reported it
    for e in entries:
        if e["kind"] != "perf_attr":
            continue
        stray = sorted(set((e.get("payload") or {}).get("programs", {}))
                       - set(FLAGSHIP_PROGRAMS))
        if stray:
            errors.append(
                f"{store}: {e['run_id']}: perf_attr program key(s) "
                f"{stray} are not flagship programs (known: "
                f"{list(FLAGSHIP_PROGRAMS)})")
    return errors


def _check_autotune(root: str, store: str,
                    table: Optional[str] = None) -> List[str]:
    """The autotune layer's record hygiene (ISSUE 14): every committed
    ``autotune_sweep`` entry's knob NAMES must be registered in
    ``singa_tpu.autotune.knobs.KNOBS`` (the schema checks shape; a
    typo'd knob would otherwise fit a predictor on noise), and the
    committed best-config table — when one exists — must validate
    against the current schema version AND cite only run_ids that
    exist in the store (a best point must reference its measured
    evidence; a stale-version table fails loudly instead of silently
    steering configs).  ``table`` overrides the committed location so
    ``tools.autotune check --table`` can vet a CANDIDATE table against
    the same store before it is committed."""
    _ensure_repo_on_path()
    from singa_tpu.autotune import knobs as at_knobs
    from singa_tpu.autotune import table as at_table
    from singa_tpu.obs import record as obs_record
    from singa_tpu.obs import schema

    errors: List[str] = []
    run_ids: Optional[set] = None
    if os.path.exists(store):
        try:
            entries = obs_record.RunRecord(store).entries()
        except schema.SchemaError:
            # the store lint above already reported it; run_ids stays
            # None so the table check below does not pile spurious
            # 'cites a run_id which does not exist' errors on top of
            # the one real store error
            entries = []
        else:
            run_ids = {e["run_id"] for e in entries}
        for e in entries:
            if e["kind"] != "autotune_sweep":
                continue
            p = e["payload"]
            ctx = f"{store}: {e['run_id']}"
            errors.extend(at_knobs.validate_knobs(
                p.get("domain"), p.get("knobs"), ctx=ctx))

    table = table or os.path.join(root, at_table.DEFAULT_TABLE)
    if os.path.exists(table):
        doc, err = _load_json(table)
        if err:
            errors.append(err)
        else:
            errors.extend(at_table.validate_table(
                doc, ctx=table, store_run_ids=run_ids))
    return errors


def _check_flight_refs(store: str) -> List[str]:
    """Every ``flight_ref`` carried by a store entry must point at an
    existing, parseable flight dump (path relative to the store's
    directory) — a ref into nothing would strand the postmortem the
    whole flight-recorder machinery exists to serve."""
    _ensure_repo_on_path()
    from singa_tpu.obs import record as obs_record
    from singa_tpu.obs import schema
    from tools import obsq

    errors: List[str] = []
    try:
        entries = obs_record.RunRecord(store).entries()
    except schema.SchemaError:
        return []          # the store lint above already reported it
    store_dir = os.path.dirname(os.path.abspath(store))
    for e in entries:
        ref = (e.get("payload") or {}).get("flight_ref")
        if not isinstance(ref, str) or not ref:
            continue
        path = os.path.join(store_dir, ref)
        if not os.path.exists(path):
            errors.append(f"{store}: {e['run_id']}: flight_ref {ref!r} "
                          f"points at a missing dump file")
            continue
        try:
            obsq.load_events(path)
        except ValueError as exc:
            errors.append(f"{store}: {e['run_id']}: flight_ref {ref!r}: "
                          f"{exc}")
    return errors


def _check_incident_dumps(root: str) -> List[str]:
    """Every committed flight dump under ``runs/incidents/`` must parse
    as an event-per-line file (partial/truncated dumps fail here, not
    in a postmortem)."""
    _ensure_repo_on_path()
    from tools import obsq

    errors: List[str] = []
    for path in sorted(glob.glob(os.path.join(root, "runs", "incidents",
                                              "*.jsonl"))):
        try:
            obsq.load_events(path)
        except ValueError as exc:
            errors.append(str(exc))
    return errors


def fsck_ckpt_dir(directory: str) -> Tuple[List[str], List[str]]:
    """Audit one checkpoint directory against the commit-marker
    contract; returns (errors, warnings).

    The checks ARE the loader's checks — ``AsyncCheckpointManager.
    verify`` for the marker/size/sha contract and ``utils.checkpoint``'s
    decode + manifest enforcement — so the auditor and the restore path
    can never disagree about what "intact" means."""
    _ensure_repo_on_path()
    from singa_tpu.train import ckpt as train_ckpt
    from singa_tpu.utils import checkpoint

    errors: List[str] = []
    warns: List[str] = []
    if not os.path.isdir(directory):
        return [f"{directory}: not a directory"], []
    for tmp in glob.glob(os.path.join(directory, "*.tmp")):
        warns.append(f"{tmp}: stray temp file (interrupted write)")

    mgr = train_ckpt.AsyncCheckpointManager(directory)
    steps = mgr.steps()
    committed = {mgr.path(s) for s in steps}
    for marker in glob.glob(os.path.join(directory, "ckpt_*.npz"
                                         + train_ckpt.COMMIT_SUFFIX)):
        path = marker[:-len(train_ckpt.COMMIT_SUFFIX)]
        if path not in committed:
            # steps() couldn't parse the name, so restore can't see it
            errors.append(f"{marker}: unparsable marker name (invisible "
                          f"to restore)")
            committed.add(path)

    for step in steps:
        path = mgr.path(step)
        try:
            mgr.verify(step)
        except train_ckpt.CheckpointCorrupt as e:
            errors.append(str(e))
            continue
        # committed and byte-intact: the payload must also decode and
        # self-agree (array manifest vs members, opt moments vs slots)
        try:
            arrays, aux = checkpoint.load_arrays(path)
            checkpoint.check_opt_manifest(arrays, aux)
        except Exception as e:
            errors.append(f"{path}: committed but undecodable "
                          f"({type(e).__name__}: {e})")

    npzs = set(glob.glob(os.path.join(directory, "ckpt_*.npz")))
    for path in sorted(npzs - committed):
        warns.append(f"{path}: no commit marker (uncommitted — ignored "
                     f"at load)")
    return errors, warns


def records_main(root: str) -> int:
    """CLI body shared by ``tools.lint --records`` and the
    ``record_check.py`` shim: 0 = all valid, 1 = named errors printed."""
    root = os.path.abspath(root)
    errors = check_records_root(root)
    if errors:
        for e in errors:
            print(f"record_check: {e}", file=sys.stderr)
        print(f"record_check: {len(errors)} error(s) in {root}",
              file=sys.stderr)
        return 1
    print(f"record_check: all records valid in {root}")
    return 0


def ckpt_main(dirs: List[str]) -> int:
    """CLI body shared by ``tools.lint --ckpt`` and the
    ``ckpt_fsck.py`` shim: 0 = every committed checkpoint intact
    (warnings allowed), 1 = errors printed one per line."""
    all_errors: List[str] = []
    for d in dirs:
        errors, warns = fsck_ckpt_dir(os.path.abspath(d))
        for w in warns:
            print(f"ckpt_fsck: warning: {w}", file=sys.stderr)
        all_errors.extend(errors)
    if all_errors:
        for e in all_errors:
            print(f"ckpt_fsck: {e}", file=sys.stderr)
        print(f"ckpt_fsck: {len(all_errors)} error(s)", file=sys.stderr)
        return 1
    print("ckpt_fsck: all committed checkpoints intact")
    return 0
