"""``python -m tools.lint`` — the one audit front door.

Static (default)::

    python -m tools.lint singa_tpu tools          # lint trees/files
    python -m tools.lint --json singa_tpu         # machine-readable
    python -m tools.lint --select SGL005 singa_tpu
    python -m tools.lint --list-rules

Dynamic audits (same checks the old standalone CLIs ran)::

    python -m tools.lint --records [ROOT]         # telemetry records
    python -m tools.lint --ckpt DIR [DIR ...]     # checkpoint fsck

Exit codes: 0 clean, 1 findings/errors, 2 usage error.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from . import RULES, render_human, render_json, run_paths
from . import audit

#: ``--records`` with no value means "the repo root" — a sentinel the
#: user cannot type, so an explicit ``--records .`` still means cwd
_RECORDS_DEFAULT = "\0repo-root"


def _list_rules() -> str:
    lines = ["singalint rules:"]
    for code, cls in RULES.items():
        lines.append(f"  {code}  {cls.name:<17} {cls.description}")
    lines.append("  SGL000 suppression-hygiene  a '# singalint: "
                 "disable=CODE' without a reason, or naming an unknown "
                 "code, is itself a finding and cannot be suppressed")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="singalint: AST invariant linter + dynamic audits")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to lint (static rules)")
    parser.add_argument("--json", action="store_true",
                        help="emit findings as JSON")
    parser.add_argument("--select", metavar="CODES",
                        help="comma-separated rule codes to run "
                             "(default: all)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalogue and exit")
    parser.add_argument("--records", nargs="?", const=_RECORDS_DEFAULT,
                        metavar="ROOT", default=None,
                        help="validate telemetry records under ROOT "
                             "(default: repo root) instead of linting")
    parser.add_argument("--ckpt", nargs="+", metavar="DIR", default=None,
                        help="fsck checkpoint directories instead of "
                             "linting")
    args = parser.parse_args(argv)

    if args.list_rules:
        print(_list_rules())
        return 0
    if args.records is not None and args.ckpt is not None:
        parser.error("--records and --ckpt are separate audit modes")
    if (args.records is not None or args.ckpt is not None) and args.paths:
        parser.error("audit modes take no lint paths — run the static "
                     "lint as a separate invocation")
    if args.records is not None:
        root = (audit._REPO_ROOT if args.records == _RECORDS_DEFAULT
                else args.records)
        return audit.records_main(root)
    if args.ckpt is not None:
        return audit.ckpt_main(args.ckpt)

    if not args.paths:
        parser.error("no paths given (or use --list-rules / --records / "
                     "--ckpt)")
    codes = None
    if args.select:
        codes = [c.strip() for c in args.select.split(",") if c.strip()]
        unknown = [c for c in codes if c not in RULES]
        if unknown:
            parser.error(f"unknown rule code(s): {', '.join(unknown)} "
                         f"(see --list-rules)")
    try:
        findings = run_paths(args.paths, codes)
    except ValueError as e:
        # a typo'd or renamed path must not read as "clean"
        parser.error(str(e))
    print(render_json(findings) if args.json else render_human(findings))
    return 1 if findings else 0


if __name__ == "__main__":
    # die silently when the consumer closes the pipe (… | head)
    import signal
    if hasattr(signal, "SIGPIPE"):
        signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    raise SystemExit(main())
