"""``python -m tools.lint`` — the one audit front door.

Static (explicit paths)::

    python -m tools.lint singa_tpu tools          # lint trees/files
    python -m tools.lint --json singa_tpu         # machine-readable
    python -m tools.lint --select SGL005 singa_tpu
    python -m tools.lint --list-rules

Full audit (no paths, no mode flags): static rules over the repo's own
trees (``singa_tpu``, ``tools``), the concurrency thread-model gate
(conclint, ``tools/lint/conc.py``), the process-mesh gate (proclint,
``tools/lint/proc.py``), AND the compiled-program gates — HLO
structure (hloaudit) plus cost/memory (hlocost), off ONE shared
lowering::

    python -m tools.lint

Dynamic audits (same checks the old standalone CLIs ran)::

    python -m tools.lint --records [ROOT]         # telemetry records
    python -m tools.lint --ckpt DIR [DIR ...]     # checkpoint fsck
    python -m tools.lint --hlo                    # structure + cost gates
    python -m tools.lint --hlo --update-baselines # reviewed re-baseline
    python -m tools.lint --conc                   # thread-model gate
    python -m tools.lint --conc --update-baselines  # reviewed re-model
    python -m tools.lint --proc                   # process-mesh gate
    python -m tools.lint --proc --update-baselines  # reviewed re-model
    python -m tools.lint --perf PATH              # runtime-attribution
    python -m tools.lint --perf PATH --update-baselines  # sentinel

``--select`` filters audit modes too (``--select hlo``,
``--select cost``, ``--select conc``, ``--select records``, or mixed
with SGL codes in the full audit).

Exit codes: 0 clean, 1 findings/errors, 2 usage error.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from . import RULES, render_human, render_json, run_paths
from . import audit

#: ``--records`` with no value means "the repo root" — a sentinel the
#: user cannot type, so an explicit ``--records .`` still means cwd
_RECORDS_DEFAULT = "\0repo-root"

#: the dynamic-audit modes --select/--list-rules enumerate alongside
#: the SGL rules; ckpt needs its DIR argument so it is flag-only
_AUDIT_MODES = {
    "records": "validate telemetry records (sessions, BENCH/MULTICHIP "
               "docs, runs/records.jsonl) — also via --records [ROOT]",
    "ckpt": "checkpoint-directory fsck (commit markers, manifests) — "
            "via --ckpt DIR [DIR ...] only, it needs the directory",
    "conc": "concurrency thread-model gate (conclint): diff the "
            "discovered thread roots + cross-thread attribute table "
            "against tools/lint/data/conc/model.json — also via "
            "--conc (re-baseline with --conc --update-baselines)",
    "proc": "process-mesh gate (proclint): diff the discovered spawn/"
            "signal/reap/socket model against tools/lint/data/proc/"
            "model.json AND cross-check the worker RPC dispatch table "
            "vs. call sites vs. _OP_TIMEOUTS — also via --proc "
            "(re-baseline with --proc --update-baselines)",
    "hlo": "compiled-program structural gate: lower the flagship train/"
           "prefill/decode programs and diff fusions, collectives, "
           "donation vs tools/lint/data/hlo/ — also via --hlo (which "
           "runs the cost gate too, off ONE shared lowering)",
    "cost": "compiled-program cost gate (hlocost): flops, HBM traffic, "
            "peak live memory, collective wire bytes vs "
            "tools/lint/data/hlo/cost/ — shares the hlo mode's lowering",
    "perf": "runtime-attribution sentinel (perfattr): box-robust "
            "invariants of a perf_attr payload (completeness, p50 "
            "ranking, decode/prefill ratio, achieved-fraction sanity) "
            "vs tools/lint/data/perf/sentinel.json — via --perf PATH "
            "only, it needs the payload dump (re-baseline with "
            "--perf PATH --update-baselines)",
}

#: the trees the bare full-audit invocation lints (repo-relative) —
#: the same set the tier-1 repo-is-clean gate pins
_DEFAULT_TREES = ("singa_tpu", "tools")


def _list_rules() -> str:
    from .conc import CONC_GATE_CODES
    from .cost import COST_CODES
    from .proc import PROC_GATE_CODES
    from .framework import RETIRED_CODES
    from .hlo import HLO_CODES
    lines = ["singalint rules:"]
    for code, cls in RULES.items():
        lines.append(f"  {code}  {cls.name:<17} {cls.description}")
    lines.append("  SGL000 suppression-hygiene  a '# singalint: "
                 "disable=CODE' without a reason, or naming an unknown "
                 "code, is itself a finding and cannot be suppressed")
    for code, successor in sorted(RETIRED_CODES.items()):
        lines.append(f"  {code}  (retired)          superseded by "
                     f"{successor}; a disable={code} suppression fails "
                     f"loudly with a migration hint")
    lines.append("conc gate finding codes (the committed thread-model "
                 "baseline, tools/lint/conc.py; re-baseline via "
                 "--conc --update-baselines):")
    for code, (name, desc) in CONC_GATE_CODES.items():
        lines.append(f"  {code}  {name:<21} {desc}")
    lines.append("proc gate finding codes (the committed process-model "
                 "baseline + RPC-protocol cross-check, "
                 "tools/lint/proc.py; re-baseline via "
                 "--proc --update-baselines):")
    for code, (name, desc) in PROC_GATE_CODES.items():
        lines.append(f"  {code}  {name:<21} {desc}")
    lines.append("audit modes (run via their flag, or --select MODE):")
    for mode, desc in _AUDIT_MODES.items():
        lines.append(f"  {mode:<7} {desc}")
    lines.append("hlo gate finding codes (named finding per drifted "
                 "metric; waive per-baseline via a 'suppress' entry "
                 "with a reason):")
    for code, (name, desc) in HLO_CODES.items():
        lines.append(f"  {code}  {name:<21} {desc}")
    lines.append("cost gate finding codes (relative tolerance per "
                 "metric; same per-baseline waiver contract):")
    for code, (name, desc) in COST_CODES.items():
        lines.append(f"  {code}  {name:<21} {desc}")
    from .perf import PERF_CODES
    lines.append("perf gate finding codes (runtime-attribution "
                 "sentinel: box-robust invariants, never "
                 "milliseconds; same waiver contract):")
    for code, (name, desc) in PERF_CODES.items():
        lines.append(f"  {code}  {name:<21} {desc}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="singalint: AST invariant linter + dynamic audits "
                    "(records, ckpt, hlo); bare invocation runs the "
                    "full audit: static rules + the HLO gate")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to lint (static "
                             "rules); omit everything for the full "
                             "audit (static + HLO gate)")
    parser.add_argument("--json", action="store_true",
                        help="emit findings as JSON")
    parser.add_argument("--select", metavar="CODES",
                        help="comma-separated rule codes and/or audit "
                             "modes (records, hlo) to run "
                             "(default: all)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule + audit-mode catalogue "
                             "and exit")
    parser.add_argument("--records", nargs="?", const=_RECORDS_DEFAULT,
                        metavar="ROOT", default=None,
                        help="validate telemetry records under ROOT "
                             "(default: repo root) instead of linting")
    parser.add_argument("--ckpt", nargs="+", metavar="DIR", default=None,
                        help="fsck checkpoint directories instead of "
                             "linting")
    parser.add_argument("--hlo", action="store_true",
                        help="run the compiled-program gates (structure "
                             "AND cost, off one shared lowering) against "
                             "tools/lint/data/hlo/ baselines")
    parser.add_argument("--conc", action="store_true",
                        help="run the concurrency thread-model gate "
                             "(conclint) against "
                             "tools/lint/data/conc/model.json")
    parser.add_argument("--proc", action="store_true",
                        help="run the process-mesh gate (proclint): "
                             "spawn/signal/reap/socket model vs "
                             "tools/lint/data/proc/model.json, plus "
                             "the RPC-protocol cross-check")
    parser.add_argument("--perf", metavar="PATH", default=None,
                        help="gate a perf_attr payload dump (bench.py "
                             "--serve --perf-attr PATH) against the "
                             "committed runtime-attribution sentinel "
                             "tools/lint/data/perf/sentinel.json")
    parser.add_argument("--update-baselines", action="store_true",
                        help="rewrite the committed baselines, printing "
                             "a human-readable diff to review: with "
                             "--conc the thread model; otherwise the "
                             "HLO structure + cost baselines (implies "
                             "--hlo)")
    args = parser.parse_args(argv)

    if args.list_rules:
        print(_list_rules())
        return 0
    if args.update_baselines and not (args.conc or args.perf
                                      or args.proc):
        args.hlo = True
    mode_flags = [f for f, on in (("--records", args.records is not None),
                                  ("--ckpt", args.ckpt is not None),
                                  ("--hlo", args.hlo),
                                  ("--conc", args.conc),
                                  ("--proc", args.proc),
                                  ("--perf", args.perf is not None)) if on]
    if len(mode_flags) > 1:
        parser.error(f"{' and '.join(mode_flags)} are separate audit "
                     f"modes")
    if mode_flags and args.paths:
        parser.error("audit modes take no lint paths — run the static "
                     "lint as a separate invocation")

    # --select: SGL codes and/or audit-mode names
    codes = None
    selected_modes: List[str] = []
    if args.select:
        raw = [c.strip() for c in args.select.split(",") if c.strip()]
        selected_modes = [c for c in raw if c in _AUDIT_MODES]
        codes = [c for c in raw if c in RULES]
        unknown = [c for c in raw if c not in RULES
                   and c not in _AUDIT_MODES]
        if unknown:
            from .framework import RETIRED_CODES
            retired = [f"{c} was retired — use {RETIRED_CODES[c]}"
                       for c in unknown if c in RETIRED_CODES]
            parser.error(f"unknown rule code(s)/mode(s): "
                         f"{', '.join(unknown)} (see --list-rules"
                         + (f"; {'; '.join(retired)}" if retired else "")
                         + ")")
        if "ckpt" in selected_modes:
            parser.error("the ckpt audit needs its directories — run "
                         "it as --ckpt DIR [DIR ...]")
        if "perf" in selected_modes:
            parser.error("the perf sentinel needs its payload dump — "
                         "run it as --perf PATH")
        if selected_modes and (args.paths or mode_flags):
            parser.error("--select with audit-mode names applies to "
                         "the bare full-audit invocation only")

    if args.records is not None:
        root = (audit._REPO_ROOT if args.records == _RECORDS_DEFAULT
                else args.records)
        return audit.records_main(root)
    if args.ckpt is not None:
        return audit.ckpt_main(args.ckpt)
    if args.perf is not None:
        from .perf import perf_main
        try:
            return perf_main(args.perf, update=args.update_baselines,
                             json_out=args.json)
        except RuntimeError as e:
            parser.error(str(e))
    if args.conc:
        from . import conc
        if args.update_baselines:
            print(conc.update_model_baseline())
            print(f"conclint: thread-model baseline updated at "
                  f"{conc.MODEL_PATH} — review the diff above")
            return 0
        findings = conc.gate_findings()
        print(render_json(findings) if args.json
              else render_human(findings).replace("singalint:",
                                                  "conclint:"))
        return 1 if findings else 0
    if args.proc:
        from . import proc
        if args.update_baselines:
            print(proc.update_model_baseline())
            print(f"proclint: process-model baseline updated at "
                  f"{proc.MODEL_PATH} — review the diff above")
            return 0
        findings = proc.audit_findings()
        print(render_json(findings) if args.json
              else render_human(findings).replace("singalint:",
                                                  "proclint:"))
        return 1 if findings else 0
    if args.hlo:
        from .hlo import hlo_main
        try:
            return hlo_main(update=args.update_baselines,
                            json_out=args.json)
        except RuntimeError as e:
            parser.error(str(e))

    if not args.paths:
        # the full audit: static rules over the repo trees + the
        # concurrency thread-model gate (conclint) + the process-mesh
        # gate (proclint) + the compiled-program gates (or the
        # --select'ed subset) — the structure and cost gates always
        # share ONE lowering pass, and the conc/proc gates reuse the
        # static pass's parse cache
        run_static = codes is None or bool(codes)
        run_hlo = not args.select or "hlo" in selected_modes
        run_cost = not args.select or "cost" in selected_modes
        run_conc = not args.select or "conc" in selected_modes
        run_proc = not args.select or "proc" in selected_modes
        run_records = "records" in selected_modes
        rc = 0
        findings = []
        if run_static:
            trees = [os.path.join(audit._REPO_ROOT, t)
                     for t in _DEFAULT_TREES]
            try:
                findings = run_paths(trees, codes)
            except ValueError as e:
                parser.error(str(e))
        if run_conc:
            from . import conc
            findings = sorted(
                findings + conc.gate_findings(),
                key=lambda f: (f.path, f.line, f.col, f.code))
        if run_proc:
            from . import proc
            findings = sorted(
                findings + proc.audit_findings(),
                key=lambda f: (f.path, f.line, f.col, f.code))
        if run_static or run_conc or run_proc:
            # with --json AND a gate half, the static findings merge
            # into the gate's single document — stdout must stay ONE
            # parseable JSON object
            if not (args.json and (run_hlo or run_cost)):
                print(render_json(findings) if args.json
                      else render_human(findings))
            rc = max(rc, 1 if findings else 0)
        if run_records:
            rc = max(rc, audit.records_main(audit._REPO_ROOT))
        if run_hlo or run_cost:
            from .hlo import hlo_main
            try:
                rc = max(rc, hlo_main(
                    json_out=args.json, structure=run_hlo,
                    cost_gate=run_cost,
                    static_findings=findings if args.json else None))
            except RuntimeError as e:
                parser.error(str(e))
        return rc

    try:
        findings = run_paths(args.paths, codes)
    except ValueError as e:
        # a typo'd or renamed path must not read as "clean"
        parser.error(str(e))
    print(render_json(findings) if args.json else render_human(findings))
    return 1 if findings else 0


if __name__ == "__main__":
    # die silently when the consumer closes the pipe (… | head)
    import signal
    if hasattr(signal, "SIGPIPE"):
        signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    raise SystemExit(main())
