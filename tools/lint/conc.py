"""conclint — the whole-program concurrency audit (ISSUE 15).

The stack serves and trains through a real host-side thread mesh —
Heartbeat monitors, the async checkpoint writer, ``ServeEngine``
recovery via ``threading.Event``, the flight recorder's broadcast
registry, signal handlers in ``train/preempt.py`` — but until this
module the only static guard was SGL004's shallow "unguarded ``self.*``
write in a thread-target method" check.  conclint turns the invariants
the chaos tests probe dynamically into a static gate, the same
"committed baseline + named finding + reviewed diff" shape hloaudit
gave the compiled programs:

1. **thread-root discovery** — an AST pass that registers every
   concurrency domain: ``threading.Thread(target=...)``, Heartbeat
   ``on_failure=`` callbacks (including conditional ``a if c else b``
   forms), ``executor.submit(...)`` targets, ``signal.signal(...)``
   handlers, and the ``obs.trace.capture()``/``attach()`` hand-off
   seams.  Reuses the per-parse module cache (PR 5) and the framework's
   parse cache, so the bare repo-wide run parses each file once.
2. **a shared-state classifier** (rule **SGL010**, superseding SGL004):
   for every class that spawns a concurrency domain, each ``self.*``
   attribute its background-reachable methods touch is classified
   *lock-guarded* (SGL004's whole-segment guard recognizer),
   *mediated* (the attribute is itself an Event/Condition/Lock/queue),
   *init-only* (written nowhere but ``__init__`` — immutable after
   construction), or *unguarded*.  Unguarded background WRITES are
   findings (the SGL004 behavior), and — new — unguarded background
   READS of an attribute that has a lock-guarded access elsewhere in
   the class are findings too: a read outside the lock that every
   writer takes can observe torn or stale state.
3. **a lock-order graph** across call edges with cycle detection
   (**SGL011** deadlock), **SGL012** blocking-under-lock
   (``time.sleep``, ``jax.device_get``/``block_until_ready``, file
   ``open``, ``os.fsync``, ``.join()``/``.result()`` while a lock is
   held — one helper level deep), and **SGL013**
   ``Event.wait``/``Condition.wait`` without a timeout or enclosing
   predicate loop.
4. **a committed thread-model baseline**
   (``tools/lint/data/conc/model.json``): the discovered roots +
   shared-state table.  The gate (**SGL014**) diffs the tree's model
   against the committed one, so a NEW thread root or a newly
   cross-thread attribute becomes a loud, human-reviewed diff — run
   ``python -m tools.lint --conc --update-baselines`` and review what
   it prints — instead of silent drift.

Scope limits (same contract as the other rules, documented in
docs/static-analysis.md): analysis is module-local and name-based.  The
guard recognizer matches whole name segments (``self._lock``,
``state_lock``; ``self._clock`` does not guard); mediation is
recognized by ``self.x = threading.Event()``-shaped assignments; a lock
passed in from outside the class, dynamic dispatch, and cross-module
call chains are invisible by design — the forced-interleaving stress
tests cover the runtime half.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .framework import Finding, Rule, register, iter_python_files, \
    parse_file
from .rules import (_class_of, _is_guard_name, _lock_guarded, _methods,
                    _module_cache, _self_method, build_parents,
                    dotted_name, import_map, module_nodes)

__all__ = ["discover_model", "gate_findings", "update_model_baseline",
           "MODEL_PATH", "CONC_SCHEMA", "CONC_GATE_CODES",
           "DEFAULT_TREES"]

_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

#: the committed thread-model baseline — the reviewed record of every
#: concurrency domain and cross-thread attribute in the audited trees
MODEL_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "data", "conc", "model.json")

#: model format version — bump on incompatible shape changes; a
#: baseline with another version fails the gate instead of diffing
#: garbage (same contract as the HLO summary schema)
CONC_SCHEMA = 1

#: the trees the thread model covers — the same set the bare full
#: audit lints (tools/lint/__main__._DEFAULT_TREES)
DEFAULT_TREES = ("singa_tpu", "tools")

#: the baseline gate's finding code, enumerated by --list-rules next to
#: the HLO/COST families (it is a gate code, not a per-module rule)
CONC_GATE_CODES = {
    "SGL014": ("thread-model", "the discovered thread roots and "
               "cross-thread attribute table match the committed "
               "baseline tools/lint/data/conc/model.json — a new "
               "concurrency domain or newly shared attribute fails "
               "loudly until '--conc --update-baselines' is run and "
               "the diff reviewed"),
}

#: synchronization primitives whose attribute assignment marks an
#: attribute as *mediated*: raw reads/method calls on it are the safe
#: cross-thread protocol, not a race
_SYNC_CTORS = frozenset({
    "Event", "Condition", "Lock", "RLock", "Semaphore",
    "BoundedSemaphore", "Barrier", "Queue", "SimpleQueue", "LifoQueue",
    "PriorityQueue", "deque",
})

#: calls that block the calling thread (SGL012's set): holding a lock
#: across one stalls every contending thread for the full duration
_BLOCKING_CALLS = {
    "time.sleep": "time.sleep()",
    "jax.device_get": "jax.device_get() (device->host transfer)",
    "jax.block_until_ready": "jax.block_until_ready()",
    "os.fsync": "os.fsync()",
    "open": "open() (file I/O)",
}


# ---------------------------------------------------------------------------
# shared per-class concurrency analysis (cached on the parsed module)
# ---------------------------------------------------------------------------

def _callback_targets(expr: ast.AST) -> List[str]:
    """``self.<m>`` method names an expression may call back into —
    follows conditional forms (``self._a if flag else self._b``) and
    boolean fallbacks (``self._cb or default``), because that is how
    ServeEngine wires its Heartbeat callback."""
    out: List[str] = []
    m = _self_method(expr)
    if m:
        out.append(m)
    elif isinstance(expr, ast.IfExp):
        out.extend(_callback_targets(expr.body))
        out.extend(_callback_targets(expr.orelse))
    elif isinstance(expr, ast.BoolOp):
        for v in expr.values:
            out.extend(_callback_targets(v))
    return out


def _local_def_name(expr: ast.AST,
                    defs: Dict[str, List[ast.FunctionDef]]) -> Optional[str]:
    """Bare name resolving to a function defined in this module (the
    ``Thread(target=probe)`` local-closure form) — a plain variable
    (e.g. a prompt array passed to ``engine.submit``) is NOT a root."""
    if isinstance(expr, ast.Name) and expr.id in defs:
        return expr.id
    return None


def _bg_entries(cls: ast.ClassDef,
                imports: Dict[str, str]) -> Dict[str, str]:
    """method name -> how it reaches a concurrency domain."""
    bg: Dict[str, str] = {}
    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        full = _resolve(node.func, imports)
        fname = dotted_name(node.func) or ""
        if full in ("threading.Thread", "Thread") or \
                full.endswith(".Thread"):
            for kw in node.keywords:
                if kw.arg == "target":
                    for m in _callback_targets(kw.value):
                        bg.setdefault(m, "threading.Thread target")
        elif fname.endswith(".submit") and node.args:
            for m in _callback_targets(node.args[0]):
                bg.setdefault(m, "executor.submit target")
        elif full.rsplit(".", 1)[-1] == "Heartbeat":
            for kw in node.keywords:
                if kw.arg == "on_failure":
                    for m in _callback_targets(kw.value):
                        bg.setdefault(m, "Heartbeat on_failure callback")
        elif full == "signal.signal" and len(node.args) >= 2:
            for m in _callback_targets(node.args[1]):
                bg.setdefault(m, "signal handler")
    return bg


def _resolve(node: ast.AST, imports: Dict[str, str]) -> str:
    from .rules import resolve
    return resolve(node, imports) or ""


def _reachable_closure(methods: Dict[str, ast.FunctionDef],
                       bg: Dict[str, str]) -> Dict[str, str]:
    """Transitive closure of ``self.<m>()`` calls from the background
    entry points — deeper than SGL001/SGL008's one level, because a
    writer thread's work is routinely two hops from its submit target
    (``_write_traced -> _write -> _commit`` in train/ckpt.py)."""
    reach: Dict[str, str] = {m: how for m, how in bg.items()
                             if m in methods}
    frontier = list(reach)
    while frontier:
        m = frontier.pop()
        for node in ast.walk(methods[m]):
            if isinstance(node, ast.Call):
                h = _self_method(node.func)
                if h and h in methods and h not in reach:
                    reach[h] = f"called from {m}() ({reach[m]})" \
                        if "called from" not in reach[m] \
                        else reach[m]
                    frontier.append(h)
    return reach


def _mediated_attrs(cls: ast.ClassDef,
                    imports: Dict[str, str]) -> Set[str]:
    """Attributes assigned a synchronization primitive anywhere in the
    class (``self._stop = threading.Event()``)."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign) or \
                not isinstance(node.value, ast.Call):
            continue
        ctor = _resolve(node.value.func, imports)
        if ctor.rsplit(".", 1)[-1] not in _SYNC_CTORS:
            continue
        for t in node.targets:
            d = dotted_name(t)
            if d and d.startswith("self.") and d.count(".") == 1:
                out.add(d.split(".", 1)[1])
    return out


def _attr_accesses(body: ast.AST) -> List[Tuple[ast.AST, str, bool]]:
    """(node, attr, is_write) for every plain ``self.<attr>`` touched
    in ``body`` — method calls (``self.helper()``) are excluded by the
    caller via the class's method table.  A bare ``self.x: T``
    annotation stores nothing and is neither read nor write."""
    bare_ann: Set[int] = {
        id(n.target) for n in ast.walk(body)
        if isinstance(n, ast.AnnAssign) and n.value is None}
    out: List[Tuple[ast.AST, str, bool]] = []
    for node in ast.walk(body):
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self" and id(node) not in bare_ann:
            is_write = isinstance(node.ctx, (ast.Store, ast.Del))
            out.append((node, node.attr, is_write))
    return out


def _class_conc(tree: ast.Module, cls: ast.ClassDef,
                imports: Dict[str, str],
                parents: Dict[ast.AST, ast.AST]) -> Dict:
    """The per-class concurrency facts every conc rule (and the model
    discovery) shares — computed once per parse via the module cache."""
    cache = _module_cache(tree).setdefault("conc_classes", {})
    if id(cls) in cache:
        return cache[id(cls)]
    methods = _methods(cls)
    bg = _bg_entries(cls, imports)
    reach = _reachable_closure(methods, bg)
    mediated = _mediated_attrs(cls, imports)
    init = methods.get("__init__")
    init_nodes: Set[int] = {id(n) for n in ast.walk(init)} \
        if init is not None else set()

    # every access in every method: attr -> facts
    written_outside_init: Set[str] = set()
    guarded_anywhere: Set[str] = set()
    for mname, body in methods.items():
        for node, attr, is_write in _attr_accesses(body):
            if attr in methods:
                continue
            if is_write and id(node) not in init_nodes:
                written_outside_init.add(attr)
            if _lock_guarded(node, parents, body):
                guarded_anywhere.add(attr)

    info = {"methods": methods, "bg": bg, "reach": reach,
            "mediated": mediated,
            "written_outside_init": written_outside_init,
            "guarded_anywhere": guarded_anywhere}
    cache[id(cls)] = info
    return info


# ---------------------------------------------------------------------------
# SGL010 conc-shared-state (supersedes SGL004 thread-seam)
# ---------------------------------------------------------------------------

@register
class SharedStateRule(Rule):
    code = "SGL010"
    name = "conc-shared-state"
    description = ("attributes shared with a concurrency domain "
                   "(Thread target, executor.submit, Heartbeat "
                   "on_failure, signal handler — transitive self.* "
                   "call closure) must be lock-guarded or "
                   "Event/queue-mediated: unguarded background writes, "
                   "and unguarded background reads of attributes with "
                   "lock-guarded accesses elsewhere, are findings "
                   "(supersedes the retired SGL004)")

    def check(self, tree: ast.Module, src: str,
              path: str) -> Iterable[Finding]:
        imports = import_map(tree)
        parents = build_parents(tree)
        for cls in [n for n in module_nodes(tree)
                    if isinstance(n, ast.ClassDef)]:
            info = _class_conc(tree, cls, imports, parents)
            if not info["reach"]:
                continue
            methods = info["methods"]
            for m, how in info["reach"].items():
                body = methods[m]
                reported: Set[Tuple[int, int]] = set()
                for node, attr, is_write in _attr_accesses(body):
                    if attr in methods or attr in info["mediated"]:
                        continue
                    if _lock_guarded(node, parents, body):
                        continue
                    key = (node.lineno, node.col_offset)
                    if key in reported:
                        continue
                    if is_write:
                        reported.add(key)
                        yield self.finding(
                            path, node,
                            f"write to self.{attr} in "
                            f"{cls.name}.{m}(), which runs "
                            f"concurrently with the main thread "
                            f"({how}), is not lock-guarded — guard "
                            f"it, mediate it through an Event/queue, "
                            f"or suppress with the reason it is safe")
                    elif attr in info["guarded_anywhere"]:
                        reported.add(key)
                        yield self.finding(
                            path, node,
                            f"unguarded read of self.{attr} in "
                            f"{cls.name}.{m}() ({how}): other "
                            f"accesses of self.{attr} in this class "
                            f"take a lock, so this read can observe "
                            f"torn or stale state — take the same "
                            f"lock, or suppress with why the race is "
                            f"benign")


# ---------------------------------------------------------------------------
# SGL011 lock-order — cycle detection over the acquisition graph
# ---------------------------------------------------------------------------

def _lock_id(expr: ast.AST, cls: Optional[ast.ClassDef]) -> Optional[str]:
    """Canonical id of a guard-named context expression: ``self._lock``
    inside class C becomes ``C._lock`` so acquisitions in different
    methods of one class correlate; module-level names stay as-is."""
    d = dotted_name(expr)
    if not d or not _is_guard_name(d):
        return None
    if d.startswith("self.") and cls is not None:
        return f"{cls.name}.{d[len('self.'):]}"
    return d


def _with_guards(node: ast.With,
                 cls: Optional[ast.ClassDef]) -> List[str]:
    return [lid for item in node.items
            for lid in [_lock_id(item.context_expr, cls)]
            if lid is not None]


def _helper_bodies(call: ast.Call, methods: Dict[str, ast.FunctionDef],
                   defs: Dict[str, List[ast.FunctionDef]]
                   ) -> List[ast.FunctionDef]:
    """One level of callee bodies for a call made while a lock is held:
    same-class ``self.helper()`` and locally-defined bare-name
    functions."""
    name = dotted_name(call.func)
    if name is None:
        return []
    if name.startswith("self.") and name.count(".") == 1:
        h = methods.get(name.split(".", 1)[1])
        return [h] if h is not None else []
    if "." not in name and name in defs:
        return [defs[name][0]]
    return []


@register
class LockOrderRule(Rule):
    code = "SGL011"
    name = "conc-lock-order"
    description = ("lock acquisition order must be acyclic across the "
                   "module's call edges (one helper level): thread A "
                   "holding L1 wanting L2 while thread B holds L2 "
                   "wanting L1 is a deadlock, not a slowdown")

    def check(self, tree: ast.Module, src: str,
              path: str) -> Iterable[Finding]:
        from .rules import _collect_defs
        parents = build_parents(tree)
        defs = _collect_defs(tree)
        # edges: held lock -> acquired-while-held lock, with a witness
        edges: Dict[Tuple[str, str], ast.AST] = {}

        def note_inner(outer: List[str], body: ast.AST,
                       cls: Optional[ast.ClassDef],
                       follow_helpers: bool) -> None:
            for sub in ast.walk(body):
                if isinstance(sub, ast.With):
                    for inner in _with_guards(sub, cls):
                        for o in outer:
                            if o != inner:
                                edges.setdefault((o, inner), sub)
                elif follow_helpers and isinstance(sub, ast.Call):
                    methods = _methods(cls) if cls is not None else {}
                    for h in _helper_bodies(sub, methods, defs):
                        note_inner(outer, h, _class_of(h, parents),
                                   follow_helpers=False)

        for node in module_nodes(tree):
            if not isinstance(node, ast.With):
                continue
            cls = _class_of(node, parents)
            held = _with_guards(node, cls)
            if not held:
                continue
            # a multi-item `with a, b:` acquires left to right — those
            # ARE ordered acquisitions, same as textual nesting
            for i, outer in enumerate(held):
                for inner in held[i + 1:]:
                    if outer != inner:
                        edges.setdefault((outer, inner), node)
            for stmt in node.body:
                note_inner(held, stmt, cls, follow_helpers=True)

        # cycle detection (DFS) over the module-wide acquisition graph
        graph: Dict[str, List[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, []).append(b)
        reported: Set[Tuple[str, str]] = set()
        for start in sorted(graph):
            stack = [(start, [start])]
            while stack:
                cur, chain = stack.pop()
                for nxt in sorted(graph.get(cur, [])):
                    if nxt == start:
                        key = tuple(sorted((start, cur)))
                        if key in reported:
                            continue
                        reported.add(key)
                        witness = edges[(cur, start)]
                        cycle = " -> ".join(chain + [start])
                        yield self.finding(
                            path, witness,
                            f"lock-order cycle: {cycle} — two threads "
                            f"taking these locks in opposite order "
                            f"deadlock; pick one global order and "
                            f"stick to it")
                    elif nxt not in chain:
                        stack.append((nxt, chain + [nxt]))


# ---------------------------------------------------------------------------
# SGL012 blocking-under-lock
# ---------------------------------------------------------------------------

@register
class BlockingUnderLockRule(Rule):
    code = "SGL012"
    name = "conc-blocking-under-lock"
    description = ("no blocking call (time.sleep, jax.device_get/"
                   "block_until_ready, open/os.fsync file I/O, "
                   ".join()/.result() waits) while holding a lock — "
                   "one helper level deep; every contending thread "
                   "stalls for the full duration — or suppress with "
                   "why the stall is the design")

    def _blocking(self, node: ast.Call,
                  imports: Dict[str, str]) -> Optional[str]:
        from .rules import resolve
        full = resolve(node.func, imports) or ""
        if full in _BLOCKING_CALLS:
            return _BLOCKING_CALLS[full]
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("join", "result") and not node.args:
            # zero positional args: thread.join(timeout=...) /
            # future.result() — a positional arg means str.join(parts)
            if dotted_name(node.func) is not None:
                return f"{dotted_name(node.func)}() " \
                       f"({'thread join' if node.func.attr == 'join' else 'future wait'})"
        return None

    def check(self, tree: ast.Module, src: str,
              path: str) -> Iterable[Finding]:
        from .rules import _collect_defs
        imports = import_map(tree)
        parents = build_parents(tree)
        defs = _collect_defs(tree)
        reported: Set[Tuple[int, int]] = set()

        def scan(body: ast.AST, lock: str, cls, via: Optional[str],
                 follow: bool):
            for sub in ast.walk(body):
                if not isinstance(sub, ast.Call):
                    continue
                shown = self._blocking(sub, imports)
                if shown is not None:
                    key = (sub.lineno, sub.col_offset)
                    if key in reported:
                        continue
                    reported.add(key)
                    chain = f" (reached via {via}())" if via else ""
                    yield self.finding(
                        path, sub,
                        f"blocking call {shown} while holding "
                        f"{lock}{chain}: every thread contending the "
                        f"lock stalls for the full duration — move it "
                        f"outside the guarded region, or suppress "
                        f"with why the stall is the design")
                elif follow:
                    methods = _methods(cls) if cls is not None else {}
                    for h in _helper_bodies(sub, methods, defs):
                        hname = dotted_name(sub.func)
                        yield from scan(h, lock, _class_of(h, parents),
                                        hname, follow=False)

        for node in module_nodes(tree):
            if not isinstance(node, ast.With):
                continue
            cls = _class_of(node, parents)
            held = _with_guards(node, cls)
            if not held:
                continue
            for stmt in node.body:
                yield from scan(stmt, held[0], cls, None, follow=True)


# ---------------------------------------------------------------------------
# SGL013 wait-predicate
# ---------------------------------------------------------------------------

def _sync_vars(tree: ast.Module, imports: Dict[str, str]
               ) -> Dict[str, str]:
    """name (``self.x`` or bare local/module name) -> primitive kind
    ('Event' or 'Condition') for every ``= threading.Event()``-shaped
    assignment in the module."""
    from .rules import resolve
    out: Dict[str, str] = {}
    for node in module_nodes(tree):
        if not isinstance(node, ast.Assign) or \
                not isinstance(node.value, ast.Call):
            continue
        ctor = (resolve(node.value.func, imports) or "").rsplit(".", 1)[-1]
        if ctor not in ("Event", "Condition"):
            continue
        for t in node.targets:
            d = dotted_name(t)
            if d:
                out[d] = ctor
    return out


@register
class WaitPredicateRule(Rule):
    code = "SGL013"
    name = "conc-wait-predicate"
    description = ("Event.wait() must carry a timeout (a dead setter "
                   "wedges the waiter forever), and Condition.wait() "
                   "must sit inside a while predicate loop (wakeups "
                   "are spurious and racy by spec)")

    def check(self, tree: ast.Module, src: str,
              path: str) -> Iterable[Finding]:
        imports = import_map(tree)
        parents = build_parents(tree)
        sync = _sync_vars(tree, imports)
        if not sync:
            return
        for node in module_nodes(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "wait"):
                continue
            recv = dotted_name(node.func.value)
            kind = sync.get(recv or "")
            if kind is None:
                continue
            if kind == "Event":
                has_timeout = bool(node.args) or any(
                    kw.arg == "timeout" for kw in node.keywords)
                if not has_timeout:
                    yield self.finding(
                        path, node,
                        f"{recv}.wait() without a timeout: if the "
                        f"setter thread dies (the exact failure this "
                        f"stack's watchdogs exist for) the waiter "
                        f"wedges forever — pass a timeout and "
                        f"re-check, or suppress with why the setter "
                        f"cannot die")
            else:  # Condition
                cur = parents.get(node)
                in_while = False
                while cur is not None and not isinstance(
                        cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Module)):
                    if isinstance(cur, ast.While):
                        in_while = True
                        break
                    cur = parents.get(cur)
                if not in_while:
                    yield self.finding(
                        path, node,
                        f"{recv}.wait() outside a while predicate "
                        f"loop: condition wakeups are spurious by "
                        f"spec — wrap it in 'while not <predicate>: "
                        f"cond.wait(...)'")


# ---------------------------------------------------------------------------
# thread-model discovery (the baseline's content)
# ---------------------------------------------------------------------------

def _scope_name(node: ast.AST, parents: Dict[ast.AST, ast.AST]) -> str:
    """Dotted enclosing-scope name (``Class.method`` / ``func`` /
    ``<module>``) — the stable half of a root's key."""
    chain: List[str] = []
    cur: Optional[ast.AST] = node
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            chain.append(cur.name)
        cur = parents.get(cur)
    return ".".join(reversed(chain)) or "<module>"


def _module_roots(tree: ast.Module, relpath: str) -> Dict[str, str]:
    """root key -> kind for one parsed module.

    Keys are ``<relpath>::<scope>.<target>`` — file + enclosing scope +
    the callable that runs on (or hands context to) the other domain —
    deliberately line-free so the baseline survives unrelated edits."""
    from .rules import _collect_defs, resolve
    imports = import_map(tree)
    parents = build_parents(tree)
    defs = _collect_defs(tree)
    roots: Dict[str, str] = {}

    def add(node: ast.AST, target: str, kind: str) -> None:
        cls = _class_of(node, parents)
        scope = cls.name if cls is not None else \
            _scope_name(node, parents)
        roots[f"{relpath}::{scope}.{target}"] = kind

    def add_targets(node: ast.AST, expr: ast.AST, kind: str) -> None:
        for m in _callback_targets(expr):
            add(node, m, kind)
        local = _local_def_name(expr, defs)
        if local is not None:
            add(node, local, kind)

    for node in module_nodes(tree):
        if not isinstance(node, ast.Call):
            continue
        full = resolve(node.func, imports) or ""
        fname = dotted_name(node.func) or ""
        if full in ("threading.Thread", "Thread") or \
                full.endswith(".Thread"):
            for kw in node.keywords:
                if kw.arg == "target":
                    add_targets(node, kw.value, "thread")
        elif fname.endswith(".submit") and node.args:
            add_targets(node, node.args[0], "executor")
        elif full.rsplit(".", 1)[-1] == "Heartbeat":
            for kw in node.keywords:
                if kw.arg == "on_failure":
                    add_targets(node, kw.value, "heartbeat")
        elif full == "signal.signal" and len(node.args) >= 2:
            add_targets(node, node.args[1], "signal")
        elif full.endswith("trace.capture"):
            add(node, "<capture>", "trace-capture")
        elif full.endswith("trace.attach"):
            add(node, "<attach>", "trace-attach")
    return roots


def _module_shared(tree: ast.Module, relpath: str) -> Dict[str, str]:
    """shared-attribute key -> classification for one parsed module:
    every ``self.*`` attribute touched by a background-reachable method
    of a class that spawns a concurrency domain."""
    imports = import_map(tree)
    parents = build_parents(tree)
    shared: Dict[str, str] = {}
    for cls in [n for n in module_nodes(tree)
                if isinstance(n, ast.ClassDef)]:
        info = _class_conc(tree, cls, imports, parents)
        if not info["reach"]:
            continue
        methods = info["methods"]
        attrs: Dict[str, List[Tuple[ast.AST, bool, str]]] = {}
        for m in info["reach"]:
            body = methods[m]
            for node, attr, is_write in _attr_accesses(body):
                if attr in methods:
                    continue
                attrs.setdefault(attr, []).append(
                    (node, is_write,
                     "guarded" if _lock_guarded(node, parents, body)
                     else "bare"))
        for attr, accesses in attrs.items():
            if attr in info["mediated"]:
                cl = "mediated"
            elif attr not in info["written_outside_init"] and \
                    not any(w for _, w, _ in accesses):
                cl = "init-only"
            elif all(g == "guarded" for _, _, g in accesses) and \
                    attr in info["guarded_anywhere"]:
                cl = "lock-guarded"
            else:
                cl = "unguarded"
            shared[f"{relpath}::{cls.name}.{attr}"] = cl
    return shared


def discover_model(paths: Optional[Iterable[str]] = None,
                   root: Optional[str] = None) -> Dict:
    """The tree's thread model: every concurrency root and every
    cross-thread class attribute with its guard classification.  Uses
    the framework parse cache, so in a bare full audit (where the
    static rules already parsed everything) discovery re-parses
    nothing."""
    root = root or _REPO_ROOT
    if paths is None:
        paths = [os.path.join(root, t) for t in DEFAULT_TREES]
    roots: Dict[str, str] = {}
    shared: Dict[str, str] = {}
    for path in iter_python_files(paths):
        parsed = parse_file(path)
        if parsed is None:
            continue
        tree, _src = parsed
        rel = os.path.relpath(path, start=root).replace(os.sep, "/")
        roots.update(_module_roots(tree, rel))
        shared.update(_module_shared(tree, rel))
    return {"schema": CONC_SCHEMA,
            "roots": dict(sorted(roots.items())),
            "shared": dict(sorted(shared.items()))}


# ---------------------------------------------------------------------------
# the baseline gate (SGL014) + the reviewed-update flow
# ---------------------------------------------------------------------------

def _load_baseline(path: str) -> Tuple[Optional[Dict], Optional[str]]:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f), None
    except FileNotFoundError:
        return None, "missing"
    except (OSError, json.JSONDecodeError) as e:
        return None, str(e)


def _root_file_line(key: str) -> Tuple[str, int]:
    """Finding anchor for a model key (``<relpath>::...``): the source
    file when it still exists, line 1 (keys are deliberately
    line-free)."""
    rel = key.split("::", 1)[0]
    path = os.path.join(_REPO_ROOT, rel)
    return (path if os.path.exists(path) else rel), 1


def gate_findings(model: Optional[Dict] = None,
                  baseline_path: Optional[str] = None,
                  paths: Optional[Iterable[str]] = None,
                  root: Optional[str] = None) -> List[Finding]:
    """Diff the discovered thread model against the committed baseline;
    [] = the mesh is exactly what was last reviewed."""
    baseline_path = baseline_path or MODEL_PATH
    if model is None:
        model = discover_model(paths, root=root)
    base, err = _load_baseline(baseline_path)
    hint = ("run 'python -m tools.lint --conc --update-baselines' and "
            "review the diff it prints")
    if base is None:
        what = "no committed thread-model baseline" if err == "missing" \
            else f"unreadable thread-model baseline ({err})"
        return [Finding(baseline_path, 1, 0, "SGL014",
                        f"{what} — every concurrency domain must be a "
                        f"reviewed baseline entry; {hint}")]
    if base.get("schema") != model.get("schema"):
        return [Finding(baseline_path, 1, 0, "SGL014",
                        f"thread-model baseline schema "
                        f"{base.get('schema')!r} does not match the "
                        f"auditor's {model.get('schema')!r} — {hint}")]
    findings: List[Finding] = []
    broots, mroots = base.get("roots", {}), model["roots"]
    for key in sorted(set(mroots) - set(broots)):
        f, line = _root_file_line(key)
        findings.append(Finding(
            f, line, 0, "SGL014",
            f"NEW thread root {key} ({mroots[key]}) is not in the "
            f"committed thread model — a new concurrency domain needs "
            f"human review: check its shared state, then {hint}"))
    for key in sorted(set(broots) - set(mroots)):
        findings.append(Finding(
            baseline_path, 1, 0, "SGL014",
            f"thread root {key} ({broots[key]}) is in the committed "
            f"model but was not discovered — removed or renamed root "
            f"(or a discovery regression); {hint}"))
    for key in sorted(set(broots) & set(mroots)):
        if broots[key] != mroots[key]:
            f, line = _root_file_line(key)
            findings.append(Finding(
                f, line, 0, "SGL014",
                f"thread root {key} changed kind: "
                f"{broots[key]} -> {mroots[key]}; {hint}"))
    bshared, mshared = base.get("shared", {}), model["shared"]
    for key in sorted(set(mshared) - set(bshared)):
        f, line = _root_file_line(key)
        findings.append(Finding(
            f, line, 0, "SGL014",
            f"attribute {key} became cross-thread "
            f"({mshared[key]}) and is not in the committed "
            f"shared-state table — review its guarding, then {hint}"))
    for key in sorted(set(bshared) - set(mshared)):
        findings.append(Finding(
            baseline_path, 1, 0, "SGL014",
            f"shared attribute {key} ({bshared[key]}) is in the "
            f"committed table but no longer cross-thread; {hint}"))
    for key in sorted(set(bshared) & set(mshared)):
        if bshared[key] != mshared[key]:
            f, line = _root_file_line(key)
            findings.append(Finding(
                f, line, 0, "SGL014",
                f"shared attribute {key} changed classification: "
                f"{bshared[key]} -> {mshared[key]} — a guard "
                f"appearing or vanishing is exactly what needs "
                f"review; {hint}"))
    return sorted(findings, key=lambda f: (f.path, f.line, f.message))


def update_model_baseline(model: Optional[Dict] = None,
                          baseline_path: Optional[str] = None,
                          paths: Optional[Iterable[str]] = None,
                          root: Optional[str] = None) -> str:
    """Write the discovered model as the new committed baseline and
    return the human-readable diff — the reviewed artifact of an
    intentional concurrency change (same flow as the HLO baselines)."""
    baseline_path = baseline_path or MODEL_PATH
    if model is None:
        model = discover_model(paths, root=root)
    base, _err = _load_baseline(baseline_path)
    base = base or {"roots": {}, "shared": {}}
    lines: List[str] = []
    for label, bsec, msec in (("root", base.get("roots", {}),
                               model["roots"]),
                              ("shared", base.get("shared", {}),
                               model["shared"])):
        for key in sorted(set(msec) - set(bsec)):
            lines.append(f"+ {label} {key}: {msec[key]}")
        for key in sorted(set(bsec) - set(msec)):
            lines.append(f"- {label} {key}: {bsec[key]}")
        for key in sorted(set(bsec) & set(msec)):
            if bsec[key] != msec[key]:
                lines.append(f"~ {label} {key}: {bsec[key]} -> "
                             f"{msec[key]}")
    if not lines:
        lines.append("thread model unchanged")
    os.makedirs(os.path.dirname(baseline_path), exist_ok=True)
    with open(baseline_path, "w", encoding="utf-8") as f:
        json.dump(model, f, indent=2, sort_keys=True)
        f.write("\n")
    return "\n".join(lines)
