"""Print the zoo vision models' exact traced FLOP counts
(singa_tpu.utils.flops) next to the published reference numbers.

This audit caught the r1-r4 ResNet bench feeding NCHW images into the
NHWC zoo (the "ResNet-50" being benchmarked computed 0.83 GFLOP/image
instead of 4.1).  tests/test_flops.py pins the corrected counts.

Usage: python tools/flops_count.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np


def main():
    from singa_tpu import models, tensor
    from singa_tpu.utils.flops import model_forward_flops

    tensor.set_seed(0)
    np.random.seed(0)

    # (name, model, NHWC shape, published fwd GFLOP/image (2 FLOPs per MAC))
    cases = (
        ("resnet50@224", models.resnet50(num_classes=1000,
                                         cifar_stem=False),
         (1, 224, 224, 3), 8.18),
        ("resnet18-cifar@32", models.resnet18(num_classes=10,
                                              cifar_stem=True),
         (1, 32, 32, 3), 1.11),
        ("vgg11@32", models.vgg11(num_classes=10), (1, 32, 32, 3), 0.31),
    )
    for name, m, shape, pub in cases:
        x = tensor.from_numpy(np.random.randn(*shape).astype(np.float32))
        m.compile([x], is_train=False, use_graph=False)
        f = model_forward_flops(m, x)
        print(f"{name}: forward {f/1e9:.3f} GFLOP/image "
              f"(published ~{pub}; train ~= 3x = {3*f/1e9:.2f})", flush=True)


if __name__ == "__main__":
    main()
