"""Probe 5b: calibrate sustained matmul rate with LLAMA-SHAPED matmuls
(probe 5's square 4096^3 scan chain sustained only ~9 TFLOP/s while the
real llama step sustains ~92 — either square chains hit a tunnel/
virtualization pathology or the llama numerator is wrong; probe 5b + a
traced-jaxpr FLOP count of the train step settle which).

  lmhead16   16 x (16384x768 @ 768x32000) chained   12.88 TFLOP/program
  proj64     64 x (16384x768 @ 768x768)  chained     1.24 TFLOP/program
  sq1024x64  64 x (1024^3) scan chain                0.14 TFLOP (count
             vs size discrimination for the probe-5 anomaly)

All fns reduce to a scalar in-program; true host-fetch fence.

Usage: nohup setsid python tools/dispatch_probe5b.py > /tmp/probe5b.out 2>&1 &
"""
from __future__ import annotations

import os
import statistics
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def fetch(x):
    return np.asarray(x).ravel()[0]


def bench(tag, f, args, flops, reps=5):
    fetch(f(*args))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fetch(f(*args))
        ts.append(time.perf_counter() - t0)
    dt = statistics.median(ts)
    print(f"{tag:12s} {dt*1e3:9.2f} ms  {flops/dt/1e12:7.1f} TFLOP/s "
          f"(min {min(ts)*1e3:.2f} max {max(ts)*1e3:.2f})", flush=True)


def main():
    print("device:", jax.devices()[0], flush=True)
    rng = np.random.RandomState(0)
    B, D, V = 16384, 768, 32000
    x = jnp.asarray(rng.randn(B, D).astype(np.float32) / 28,
                    jnp.bfloat16)
    w_head = jnp.asarray(rng.randn(D, V).astype(np.float32) / 28,
                         jnp.bfloat16)
    w_back = jnp.asarray(rng.randn(V, D).astype(np.float32) / 180,
                         jnp.bfloat16)
    w_proj = jnp.asarray(rng.randn(D, D).astype(np.float32) / 28,
                         jnp.bfloat16)

    def lmhead16(x, wh, wb):
        c = x
        for _ in range(8):
            y = (c @ wh).astype(jnp.bfloat16)     # (B, V)
            c = (y @ wb).astype(jnp.bfloat16)     # (B, D)
        return c.astype(jnp.float32).sum()

    fl = 8 * (2.0 * B * D * V + 2.0 * B * V * D)
    bench("lmhead16", jax.jit(lmhead16), (x, w_head, w_back), fl)

    def proj64(x, w):
        def body(c, _):
            return (c @ w).astype(jnp.bfloat16), None
        return lax.scan(body, x, None, length=64)[0] \
            .astype(jnp.float32).sum()

    bench("proj64", jax.jit(proj64), (x, w_proj), 64 * 2.0 * B * D * D)

    s = jnp.asarray(rng.randn(1024, 1024).astype(np.float32) / 32,
                    jnp.bfloat16)

    def sq1024x64(a):
        def body(c, _):
            return (c @ a).astype(jnp.bfloat16), None
        return lax.scan(body, a, None, length=64)[0] \
            .astype(jnp.float32).sum()

    bench("sq1024x64", jax.jit(sq1024x64), (s,), 64 * 2.0 * 1024 ** 3)


if __name__ == "__main__":
    main()
