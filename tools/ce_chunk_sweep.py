"""CE-chunk sweep on chip: the fused-loss lax.scan runs 16384/chunk
iterations and this platform taxes each ~1 ms (probe 5b), so bigger
chunks should buy back most of that tax.

Usage: nohup setsid python tools/ce_chunk_sweep.py > /tmp/ce_sweep.out 2>&1 &
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np


def main():
    import jax

    from singa_tpu import device, models, opt, tensor
    from singa_tpu.utils.timing import windowed_steps

    device.set_default_device(device.create_tpu_device())
    for chunk in (512, 2048, 4096, 8192, 16384):
        tensor.set_seed(0)
        np.random.seed(0)
        cfg = models.LlamaConfig.small()
        cfg.fused_loss = True
        cfg.fused_loss_chunk = chunk
        m = models.Llama(cfg)
        m.set_optimizer(opt.SGD(lr=0.01, momentum=0.9))
        ids = tensor.from_numpy(np.random.randint(
            0, cfg.vocab_size, (16, 1024)).astype(np.int32))
        t0 = time.perf_counter()
        m.compile([ids], is_train=True, use_graph=True)
        out = m.train_step(ids)
        np.asarray(out[-1].data)
        t_compile = time.perf_counter() - t0

        holder = {}

        def one():
            holder["out"] = m.train_step(ids)
            return holder["out"][-1].data

        dt, stats = windowed_steps(one, windows=3, window_len=8, warmup=1)
        print(f"chunk {chunk:5d}: {dt*1e3:7.2f} ms/step "
              f"({16384/dt:,.0f} tok/s)  compile {t_compile:.1f}s  "
              f"windows {stats['window_ms']}", flush=True)
        del m, holder


if __name__ == "__main__":
    main()
