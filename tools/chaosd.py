"""Seeded chaos-campaign driver for the multi-process serve tier.

``tools.loadgen --mp-smoke`` proves the tier serves; this driver
proves it SURVIVES.  A campaign is a deterministic sequence of
disruptive events — worker SIGKILLs (crash), worker SIGSTOPs (hang: the
process exists but stops answering), supervisor-side fault plans
(``serve.handoff`` errors, ``serve.transport`` torn frames), and
elastic resizes — fired mid-stream against a live proc tier under
open-loop Poisson load, with the standing invariants re-asserted after
every event:

* **bitwise** — every stream completes and matches the single-engine
  reference token for token (kills and hangs replay on survivors, the
  respawned worker adopts at a step boundary; none of it may change
  one sampled token);
* **program sets fixed** — no worker's jit cache grew past one entry
  per program (chaos must never recompile);
* **no orphan processes** — every process the fabric ever spawned is
  either an adopted pool member or reaped (``poll() is not None``);
* **flight refs resolve** — every incident committed to the record
  store points at a dump file that exists.

Determinism contract: the event schedule is a pure function of the
seed (blake2b over ``(seed, field, event index)`` — the same
derivation discipline as :class:`~singa_tpu.faults.plan.FaultPlan`),
so :func:`plan_events` recomputed from a committed ``chaos_campaign``
record's ``seed``/``events`` fields reproduces exactly the kills /
hangs / fault plans / resizes the record claims (the frozen-record
assertion in tests/test_net.py).  Wall-clock timing is NOT part of the
contract — arrivals are Poisson and detection latency varies — but
the event composition and every token of every stream are.

    python -m tools.chaosd --seed 19 --events 6      # full campaign
    python -m tools.chaosd --smoke                   # CI: 1 kill + 1 hang
    python -m tools.loadgen --chaos-campaign --seed 19

The smoke flavor is ``tools/ci_gate.sh``'s chaos stage: a fixed
forced schedule (one SIGKILL, one SIGSTOP) against a 2-process 1:1
tier — the cheapest run that still exercises death detection, hang
detection, replay, and respawn-adoption end to end.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import signal
import sys
import time
from typing import Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: what a campaign may do to the tier, in schedule-derivation order
EVENT_KINDS = ("kill", "hang", "fault", "resize")

#: the supervisor-side fault plans a ``fault`` event cycles through —
#: all REQUEST-PRESERVING seams (the router replays; streams stay
#: bitwise), which is exactly why they belong under load
FAULT_PLANS = (
    "serve.handoff=error:p=0.4",
    "serve.transport=torn_frame:at=1",
    "serve.handoff=hang:p=0.2,delay=0.05",
)

#: snappy RPC deadlines for chaos runs: hang DETECTION is the thing
#: under test, so a wedged worker must be declared dead in seconds
#: (the production defaults in supervisor._OP_TIMEOUTS trade latency
#: for tolerance of loaded hosts)
CHAOS_OP_TIMEOUTS = {"heartbeat": 2.0, "health": 5.0, "tick": 8.0,
                     "handoff": 10.0}
#: a fresh worker's first ticks still pay a jit compile — keep the
#: escalated budget honest even in chaos runs
CHAOS_COMPILE_TIMEOUT_S = 120.0

#: engine shape every campaign worker (and the reference engine) uses;
#: max_len covers shared prefix (16) + longest private suffix (16) +
#: largest output budget (8)
ENGINE_KW = dict(num_slots=4, max_len=48, block_size=8)
_PROMPT_LENS = (6, 10, 16)
_NEW_TOKENS = (4, 8)

SMOKE_SEED = 7


def _det_u32(seed: int, *parts) -> int:
    """Deterministic u32 from (seed, parts) — blake2b like
    ``FaultPlan._det_uniform``, stable across processes and
    PYTHONHASHSEED."""
    text = ":".join([str(int(seed))] + [str(p) for p in parts])
    h = hashlib.blake2b(text.encode(), digest_size=8).digest()
    return int.from_bytes(h[:4], "big")


def plan_events(seed: int, n_events: int) -> List[dict]:
    """The campaign's event schedule — a PURE function of the seed, so
    a committed record's schedule is recomputable forever."""
    events = []
    for i in range(n_events):
        kind = EVENT_KINDS[_det_u32(seed, "kind", i) % len(EVENT_KINDS)]
        ev = {"i": i, "kind": kind}
        if kind in ("kill", "hang"):
            ev["role"] = ("prefill",
                          "decode")[_det_u32(seed, "role", i) % 2]
        elif kind == "fault":
            ev["plan"] = FAULT_PLANS[_det_u32(seed, "plan", i)
                                     % len(FAULT_PLANS)]
        else:
            ev["decode"] = 1 + _det_u32(seed, "nd", i) % 2
        events.append(ev)
    return events


def composition(events: List[dict]) -> Dict[str, int]:
    """Event counts by kind — what a ``chaos_campaign`` record's
    kills/hangs/fault_plans/resizes fields must equal for its seed."""
    out = {k: 0 for k in EVENT_KINDS}
    for ev in events:
        out[ev["kind"]] += 1
    return out


# -- event firing ------------------------------------------------------------

def _victim(tier, role: str, seed: int, i: int, *,
            warmed_only: bool = False):
    """Deterministically pick a target worker of ``role`` (falls back
    to the other pool if that role has no alive worker — a campaign
    event never no-ops just because an earlier event emptied a pool).
    ``warmed_only`` restricts to workers past their compile-warmup
    ticks, so a SIGSTOP is detected on the fast steady-state deadline
    rather than the compile-escalated one."""
    from singa_tpu.serve.net import supervisor as sup

    pools = [tier.prefill if role == "prefill" else tier.decode,
             tier.decode if role == "prefill" else tier.prefill]
    for pool in pools:
        alive = sorted([w for w in pool if w.alive],
                       key=lambda w: w.name)
        if warmed_only:
            alive = [w for w in alive
                     if w.ok_ticks >= sup._WARMUP_TICKS]
        if alive:
            return alive[_det_u32(seed, "victim", i) % len(alive)]
    return None


def _fire(tier, ev: dict, seed: int) -> bool:
    """Fire one schedule event against the live tier.  Returns False
    when the event has no target YET (hang with no warmed victim) —
    the phase loop retries on a later step."""
    kind = ev["kind"]
    if kind == "kill":
        w = _victim(tier, ev["role"], seed, ev["i"])
        if w is None:
            return False
        # raw SIGKILL on the worker process — the supervisor learns of
        # it the hard way (socket error on the next RPC), which is the
        # crash path production would see
        w.proc.kill()
        return True
    if kind == "hang":
        w = _victim(tier, ev["role"], seed, ev["i"], warmed_only=True)
        if w is None or w.pid is None:
            return False
        # SIGSTOP: the process EXISTS but stops answering — only the
        # liveness layer (per-op deadlines / heartbeat probes) can
        # tell this apart from a healthy-but-slow worker
        os.kill(w.pid, signal.SIGSTOP)
        return True
    if kind == "resize":
        tier.resize(n_decode=ev["decode"])
        return True
    raise ValueError(f"unfireable event kind {kind!r}")


# -- invariants --------------------------------------------------------------

def _settle(tier, timeout_s: float = 240.0) -> dict:
    """Step the tier until self-healing has converged: no spawn in
    flight, nothing staged, and every role either back at its target
    size or given up on by the breaker.  Returns the final
    ``heal_state`` snapshot."""
    deadline = time.monotonic() + timeout_s
    while True:
        tier.step()
        hs = tier.heal_state()
        busy = (any(hs["spawning"].values())
                or any(hs["staged"].values()))
        sized = all(hs["breaker"][r]
                    or hs["alive"][r] >= hs["target"][r]
                    for r in ("prefill", "decode"))
        if not busy and sized:
            return hs
        if time.monotonic() > deadline:
            raise RuntimeError(
                f"tier did not settle within {timeout_s:.0f}s: {hs}")
        time.sleep(0.05)


def check_invariants(tier, store: Optional[str]) -> List[str]:
    """The standing invariants asserted after every event (call only
    on a SETTLED tier).  Returns human-readable violations; empty
    means the tier held."""
    problems: List[str] = []
    # program sets fixed: chaos must never have recompiled anything
    for w in tier.workers():
        if not w.alive:
            continue
        rep, _ = w.call({"op": "health"})
        comp = rep.get("compiles") or ()
        if any(int(c) > 1 for c in comp):
            problems.append(
                f"{w.name}: jit cache grew to {list(comp)} "
                f"(program set not fixed)")
        if int(rep.get("handoff_compiles") or 0) > 1:
            problems.append(
                f"{w.name}: handoff program recompiled "
                f"({rep['handoff_compiles']} cache entries)")
    # no orphan processes: everything the fabric ever spawned is an
    # adopted pool member or reaped
    live = {w.proc.pid for w in tier.workers() if w.alive}
    for p in tier.fabric.procs:
        if p.pid not in live and p.poll() is None:
            problems.append(f"orphan worker process pid={p.pid} "
                            f"(alive but not in any pool)")
    # every committed incident's flight_ref resolves to a dump file
    if store and os.path.exists(store):
        base = os.path.dirname(os.path.abspath(store))
        with open(store, encoding="utf-8") as f:
            for ln, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    entry = json.loads(line)
                except ValueError:
                    problems.append(f"{store}:{ln}: unparseable record")
                    continue
                ref = (entry.get("payload") or {}).get("flight_ref")
                if ref and not os.path.exists(os.path.join(base, ref)):
                    problems.append(
                        f"{store}:{ln}: flight_ref {ref!r} does not "
                        f"resolve")
    return problems


# -- the campaign ------------------------------------------------------------

def _ref_streams(model, workloads: List[list]) -> List[List[List[int]]]:
    """Per-phase reference token streams from ONE in-process engine —
    the bitwise ground truth every tier stream is held to."""
    from singa_tpu.serve import ServeEngine

    eng = ServeEngine(model, **ENGINE_KW)
    try:
        refs = []
        for wl in workloads:
            phase = []
            for a in wl:
                h = eng.submit(a.prompt, max_new_tokens=a.max_new)
                while not h.done:
                    eng.step()
                phase.append(list(h.tokens))
            refs.append(phase)
        return refs
    finally:
        eng.close()


def run_campaign(seed: int, n_events: int, *, per_phase: int = 4,
                 rate: float = 30.0, n_prefill: int = 1,
                 n_decode: int = 2, store: Optional[str] = None,
                 forced_events: Optional[List[dict]] = None,
                 breaker_k: int = 10,
                 phase_wall_s: float = 300.0) -> dict:
    """Run one seeded campaign; returns ``{"ok": bool, "payload": ...,
    "problems": [...]}`` where ``payload`` is the (schema-valid)
    ``chaos_campaign`` record body.  ``forced_events`` overrides the
    seeded schedule (the CI smoke pins 1 kill + 1 hang); the committed
    record still carries the seed, and the schedule-vs-record
    assertion only applies to seeded runs."""
    from singa_tpu import faults
    from singa_tpu.faults.plan import FaultPlan
    from singa_tpu.obs import flight as obs_flight
    from singa_tpu.obs import record as obs_record
    from singa_tpu.serve import ProcRouter, QueueFull, build_proc_pools
    from tools.loadgen import _build_model, build_workload

    events = (forced_events if forced_events is not None
              else plan_events(seed, n_events))
    model = _build_model()
    vocab = int(model.cfg.vocab_size)
    # phase 0 is event-free warmup (compiles land, caches settle),
    # then one phase per event
    workloads = [build_workload(per_phase, rate,
                                _det_u32(seed, "wl", i) % (1 << 16),
                                prompt_lens=_PROMPT_LENS,
                                new_tokens=_NEW_TOKENS, vocab=vocab)
                 for i in range(len(events) + 1)]
    refs = _ref_streams(model, workloads)

    pw, dw = build_proc_pools(
        "tools.loadgen:_build_model", n_prefill, n_decode,
        record_store=store, op_timeouts=CHAOS_OP_TIMEOUTS,
        compile_timeout_s=CHAOS_COMPILE_TIMEOUT_S, **ENGINE_KW)
    tier = ProcRouter(pw, dw, record_store=store,
                      run_id=obs_record.new_run_id("chaosd"),
                      heartbeat_every_s=1.0, respawn_backoff_s=0.25,
                      breaker_k=breaker_k)

    counters = {k: 0 for k in EVENT_KINDS}
    requests = completed = 0
    bitwise_ok = True
    problems: List[str] = []

    def phase(idx: int, ev: Optional[dict]) -> None:
        nonlocal requests, completed, bitwise_ok
        arrivals, want = workloads[idx], refs[idx]
        plan_installed = False
        if ev is not None and ev["kind"] == "fault":
            faults.uninstall()
            faults.install(FaultPlan.parse(ev["plan"],
                                           seed=seed + ev["i"]))
            plan_installed = True
            counters["fault"] += 1
        fired = ev is None or plan_installed
        handles: list = []
        i = 0
        t0 = time.monotonic()
        try:
            while True:
                now = time.monotonic() - t0
                while i < len(arrivals) and arrivals[i].at_s <= now:
                    try:
                        handles.append(tier.submit(
                            arrivals[i].prompt,
                            max_new_tokens=arrivals[i].max_new))
                    except QueueFull:
                        break       # still due — retried next round
                    i += 1
                if not fired and handles and tier.pending:
                    # mid-stream, by construction: requests are in
                    # flight when the event lands
                    if _fire(tier, ev, seed):
                        counters[ev["kind"]] += 1
                        fired = True
                if tier.pending:
                    tier.step()
                elif i < len(arrivals):
                    time.sleep(min(arrivals[i].at_s - now, 0.05))
                else:
                    break
                if time.monotonic() - t0 > phase_wall_s:
                    raise RuntimeError(
                        f"phase {idx} exceeded {phase_wall_s:.0f}s")
        finally:
            if plan_installed:
                faults.uninstall()
        # a hang that never found a warmed victim mid-phase fires now,
        # against the settling tier (streams already complete)
        while not fired:
            tier.step()
            if _fire(tier, ev, seed):
                counters[ev["kind"]] += 1
                fired = True
            if time.monotonic() - t0 > phase_wall_s:
                raise RuntimeError(
                    f"phase {idx}: event {ev} never became fireable")
        _settle(tier)
        requests += len(arrivals)
        for h, ref in zip(handles, want):
            done = h.finish_reason in ("eos", "length")
            completed += 1 if done else 0
            if not done or list(h.tokens) != ref:
                bitwise_ok = False
                problems.append(
                    f"phase {idx} req {h.qid}: "
                    + ("did not complete "
                       f"({h.finish_reason}, {h.error})" if not done
                       else "stream diverged from the single-engine "
                            "reference"))
        problems.extend(check_invariants(tier, store))

    try:
        phase(0, None)
        for n, ev in enumerate(events):
            phase(n + 1, ev)
    finally:
        tier.close()
    # the tier is down: its processes must ALL be gone now
    for p in tier.fabric.procs:
        if p.poll() is None:
            problems.append(f"post-close orphan pid={p.pid}")
    flight_ref = obs_flight.dump_for_store(
        tier.flight, "serve.respawn", store,
        f"chaos campaign seed={seed} summary")
    payload = {
        "seed": int(seed),
        "events": len(events),
        "kills": counters["kill"],
        "hangs": counters["hang"],
        "fault_plans": counters["fault"],
        "resizes": counters["resize"],
        "respawns": int(tier.metrics.respawns),
        "reroutes": int(tier.metrics.reroutes),
        "worker_deaths": int(tier.metrics.worker_deaths),
        "requests": int(requests),
        "completed": int(completed),
        "bitwise_ok": bool(bitwise_ok),
    }
    if flight_ref:
        payload["flight_ref"] = flight_ref
    ok = bitwise_ok and not problems and completed == requests
    if store:
        import jax
        platform = jax.default_backend()
        dev = jax.devices()[0]
        entry = obs_record.new_entry(
            "chaos_campaign", platform, platform != "tpu",
            getattr(dev, "device_kind", "") or platform,
            run_id=obs_record.new_run_id("chaos"), payload=payload)
        obs_record.RunRecord(store).append(entry)
    return {"ok": bool(ok), "payload": payload, "problems": problems}


def smoke(store: Optional[str] = None) -> int:
    """The CI chaos stage: fixed schedule (1 SIGKILL + 1 SIGSTOP, both
    aimed at the decode role) against a 2-process 1:1 tier.  Streams
    bitwise, both deaths detected, both respawns adopted, no orphans —
    or a nonzero exit."""
    forced = [{"i": 0, "kind": "kill", "role": "decode"},
              {"i": 1, "kind": "hang", "role": "decode"}]
    res = run_campaign(SMOKE_SEED, len(forced), per_phase=3,
                       n_prefill=1, n_decode=1, store=store,
                       forced_events=forced)
    p = res["payload"]
    fails = list(res["problems"])
    if not p["bitwise_ok"]:
        fails.append("streams diverged from the single-engine "
                     "reference")
    if p["worker_deaths"] < 2:
        fails.append(f"expected 2 worker deaths (1 kill + 1 hang), "
                     f"observed {p['worker_deaths']}")
    if p["respawns"] < 2:
        fails.append(f"expected 2 respawns adopted, observed "
                     f"{p['respawns']}")
    if fails:
        for f in fails:
            print(f"chaos-smoke: FAIL — {f}", file=sys.stderr)
        return 1
    print(f"chaos-smoke: OK — 1 kill + 1 hang against a 2-process "
          f"tier: {p['completed']}/{p['requests']} streams bitwise, "
          f"{p['respawns']} respawns adopted, "
          f"{p['reroutes']} reroutes, no orphans")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="seeded chaos campaign against a live "
                    "multi-process serve tier (kills, hangs, fault "
                    "plans, resizes under Poisson load; bitwise / "
                    "program-set / no-orphan / flight-ref invariants "
                    "asserted after every event)")
    ap.add_argument("--seed", type=int, default=19)
    ap.add_argument("--events", type=int, default=6,
                    help="schedule length (one load phase per event, "
                         "plus an event-free warmup phase)")
    ap.add_argument("--per-phase", type=int, default=4,
                    help="Poisson arrivals per phase")
    ap.add_argument("--rate", type=float, default=30.0,
                    help="offered arrivals/s within a phase")
    ap.add_argument("--prefill", type=int, default=1)
    ap.add_argument("--decode", type=int, default=2)
    ap.add_argument("--store", default=None,
                    help="record store path (default: "
                         "runs/records.jsonl; incidents + the "
                         "chaos_campaign summary land here)")
    ap.add_argument("--no-record", action="store_true")
    ap.add_argument("--smoke", action="store_true",
                    help="CI mode: fixed 1-kill + 1-hang schedule "
                         "against a 1:1 tier (no record store unless "
                         "--store)")
    args = ap.parse_args(argv)
    store = (None if args.no_record
             else args.store
             or os.path.join(_REPO, "runs", "records.jsonl"))
    if args.smoke:
        return smoke(store=args.store if args.store else None)
    res = run_campaign(args.seed, args.events,
                       per_phase=args.per_phase, rate=args.rate,
                       n_prefill=args.prefill, n_decode=args.decode,
                       store=store)
    print(json.dumps(res["payload"], indent=2))
    if res["problems"]:
        for p in res["problems"]:
            print(f"chaosd: INVARIANT VIOLATION — {p}",
                  file=sys.stderr)
        return 1
    print(f"chaosd: OK — seed {args.seed}: {res['payload']['events']} "
          f"events ({res['payload']['kills']} kills, "
          f"{res['payload']['hangs']} hangs, "
          f"{res['payload']['fault_plans']} fault plans, "
          f"{res['payload']['resizes']} resizes), "
          f"{res['payload']['respawns']} respawns, every stream "
          f"bitwise", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
