"""Open-loop traffic generator for the paged serving engine.

Closed-loop drivers (submit, wait, submit) let a slow server set its
own pace and hide queueing collapse; an OPEN-loop generator arrives on
its own clock — Poisson inter-arrivals at a configured rate, mixed
prompt/output lengths, a tenant mix whose requests share per-tenant
system prompts — so scheduler and paging changes are judged on what
production cares about: p99 TTFT, tokens/s, and how gracefully load is
shed when the offered rate exceeds capacity.

    python -m tools.loadgen --rate 20 --requests 80 --deadline 10
    SINGA_FAULTS="serve.decode=error:every=40" python -m tools.loadgen ...

The run drives ``ServeEngine.step()`` directly (arrivals are submitted
the tick their timestamp passes; ``QueueFull`` rejections count as
overload outcomes, not errors) and reports SLO percentiles from the
engine's obs histograms.  The headline lands in the run-record store as
a ``serve_load`` entry (``obs/schema.py``; linted by ``python -m
tools.lint --records``) with the offered/completed/shed/rejected
counts and TTFT p50/p99 — and the whole thing is runnable under a
``SINGA_FAULTS`` chaos plan, where the resilience claim is simply "the
engine finished the run" (every fired fault shows up in the detail).

Importable: :func:`build_workload` + :func:`run_load` are used by
tests/test_serve.py against a prebuilt engine (the CLI builds its own
model on CPU).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@dataclass
class _Arrival:
    at_s: float
    prompt: np.ndarray
    max_new: int
    tenant: int


def build_workload(n_requests: int, rate_rps: float, seed: int, *,
                   prompt_lens: Sequence[int] = (6, 10, 16, 24),
                   new_tokens: Sequence[int] = (4, 8, 16),
                   tenants: int = 3, shared_len: int = 16,
                   vocab: int = 256) -> List[_Arrival]:
    """A reproducible open-loop trace: Poisson arrivals at ``rate_rps``,
    prompts drawn as ``tenant system prefix (shared_len tokens) +
    private suffix (prompt_lens mix)``, output budgets from
    ``new_tokens``.  ``tenants=0`` or ``shared_len=0`` disables
    sharing (every prompt fully private)."""
    rng = np.random.RandomState(seed)
    at = np.cumsum(rng.exponential(1.0 / rate_rps, n_requests))
    prefixes = [rng.randint(0, vocab, (shared_len,)).astype(np.int32)
                for _ in range(tenants)] if tenants and shared_len else []
    out = []
    for i in range(n_requests):
        tenant = int(rng.randint(0, tenants)) if prefixes else -1
        suffix = rng.randint(
            0, vocab,
            (int(prompt_lens[rng.randint(0, len(prompt_lens))]),)
        ).astype(np.int32)
        prompt = (np.concatenate([prefixes[tenant], suffix])
                  if prefixes else suffix)
        out.append(_Arrival(float(at[i]), prompt,
                            int(new_tokens[rng.randint(0,
                                                       len(new_tokens))]),
                            tenant))
    return out


def run_load(engine, workload: List[_Arrival], *,
             deadline_s: Optional[float] = None,
             eos_id: Optional[int] = None,
             max_wall_s: float = 300.0) -> dict:
    """Drive ``engine`` through ``workload`` open-loop and return the
    ``serve_load`` payload (plus a ``detail`` sub-dict that is NOT part
    of the schema contract).  Never raises on overload outcomes —
    ``QueueFull`` is a counted result; only an engine CRASH (the thing
    chaos runs assert cannot happen) propagates."""
    from singa_tpu.serve import QueueFull

    handles = []
    n = len(workload)
    i = 0
    t0 = time.monotonic()
    while True:
        now = time.monotonic() - t0
        while i < n and workload[i].at_s <= now:
            try:
                handles.append(engine.submit(
                    workload[i].prompt,
                    max_new_tokens=workload[i].max_new,
                    deadline_s=deadline_s, eos_id=eos_id))
            except QueueFull:
                handles.append(None)       # counted via metrics.rejected
            i += 1
        if engine.pending:
            engine.step()
        elif i < n:
            # idle gap before the next arrival: sleep it off instead of
            # spinning (open loop — we must not pull arrivals early)
            time.sleep(min(workload[i].at_s - now, 0.05))
        else:
            break
        if now > max_wall_s:
            break
    wall = time.monotonic() - t0
    snap = engine.metrics.snapshot()
    done = [h for h in handles if h is not None]
    completed = sum(1 for h in done
                    if h.finish_reason in ("eos", "length"))
    tokens = sum(len(h.tokens) for h in done)
    ttft = snap["ttft_ms"] or {}
    payload = {
        "requests": n,
        "completed": completed,
        "shed": int(snap["evicted"].get("shed", 0)),
        "rejected": int(snap["rejected"]),
        "tokens_per_s": round(tokens / wall, 1) if wall else 0.0,
        "ttft_p50_ms": round(ttft.get("p50", 0.0), 3),
        "ttft_p99_ms": round(ttft.get("p99", 0.0), 3),
    }
    payload["detail"] = {
        "wall_s": round(wall, 3),
        "generated_tokens": tokens,
        "deadline_evicted": int(snap["evicted"].get("deadline", 0)),
        "quarantined": int(snap["quarantined"]),
        "preempted": int(snap["preempted"]),
        "recoveries": int(snap["recoveries"]),
        "prefix_hits": int(snap["prefix_hits"]),
        "prefix_hit_tokens": int(snap["prefix_hit_tokens"]),
        "retries": dict(snap["retries"]),
        "token_p50_ms": round((snap["token_ms"] or {}).get("p50", 0.0),
                              3),
    }
    return payload


def append_record(payload: dict, store: Optional[str] = None) -> str:
    """Write the headline (schema-required fields + numeric extras;
    the ``detail`` sub-dict stays out of the durable record) as a
    ``serve_load`` entry.  Returns the store path."""
    import jax

    from singa_tpu.obs import record as obs_record
    from singa_tpu.obs import schema

    body = {k: v for k, v in payload.items() if k != "detail"}
    body.update({k: v for k, v in payload["detail"].items()
                 if isinstance(v, (int, float))})
    platform = jax.default_backend()
    dev = jax.devices()[0]
    entry = obs_record.new_entry(
        "serve_load", platform, platform != "tpu",
        getattr(dev, "device_kind", "") or platform,
        run_id=obs_record.new_run_id("load"), payload=body)
    schema.validate_entry(entry)           # fail before touching disk
    store = store or os.path.join(_REPO, obs_record.DEFAULT_STORE)
    obs_record.RunRecord(store).append(entry)
    return store


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="open-loop Poisson traffic through the paged "
                    "serving engine (SLO readout + serve_load record)")
    ap.add_argument("--requests", type=int, default=60)
    ap.add_argument("--rate", type=float, default=20.0,
                    help="offered arrivals/s (push past capacity to "
                         "study overload)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--tenants", type=int, default=3,
                    help="tenant count for the shared-prefix mix "
                         "(0 = no sharing)")
    ap.add_argument("--shared-prefix", type=int, default=16,
                    help="system-prompt tokens shared per tenant")
    ap.add_argument("--deadline", type=float, default=30.0,
                    help="per-request SLO deadline (s); drives "
                         "shedding under overload")
    ap.add_argument("--num-slots", type=int, default=8)
    ap.add_argument("--max-len", type=int, default=64)
    ap.add_argument("--block-size", type=int, default=8)
    ap.add_argument("--num-blocks", type=int, default=None)
    ap.add_argument("--no-share", action="store_true",
                    help="disable prefix-cache sharing in the engine")
    ap.add_argument("--store", default=None,
                    help="run-record store path (default: "
                         "runs/records.jsonl)")
    ap.add_argument("--no-record", action="store_true")
    args = ap.parse_args(argv)

    from singa_tpu import models, tensor
    from singa_tpu.obs import record as obs_record
    from singa_tpu.serve import ServeEngine

    # one resolved store for BOTH record producers: the engine's
    # incident entries (quarantine/recovery under chaos) and the final
    # serve_load headline — otherwise a default-args chaos soak would
    # silently drop its incident evidence
    store = (None if args.no_record else
             args.store or os.path.join(_REPO, obs_record.DEFAULT_STORE))

    tensor.set_seed(0)
    m = models.Llama(models.LlamaConfig.tiny())
    m.eval()
    m.compile([tensor.from_numpy(np.zeros((1, 4), np.int32))],
              is_train=False, use_graph=False)
    eng = ServeEngine(m, args.num_slots, args.max_len,
                      block_size=args.block_size,
                      num_blocks=args.num_blocks,
                      share_prefix=not args.no_share,
                      backoff_base=0.005, backoff_max=0.05,
                      # a chaos soak may recover many times; the
                      # engine-default budget of 2 is tuned for unit
                      # scenarios, not sustained injection
                      max_recoveries=100,
                      record_store=store)
    wl = build_workload(args.requests, args.rate, args.seed,
                        tenants=args.tenants,
                        shared_len=args.shared_prefix,
                        vocab=m.cfg.vocab_size)
    payload = run_load(eng, wl, deadline_s=args.deadline)
    print(json.dumps(payload, indent=2))
    if store is not None:
        append_record(payload, store)
        print(f"# serve_load entry appended to {store}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
