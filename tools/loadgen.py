"""Open-loop traffic generator for the paged serving engine.

Closed-loop drivers (submit, wait, submit) let a slow server set its
own pace and hide queueing collapse; an OPEN-loop generator arrives on
its own clock — Poisson inter-arrivals at a configured rate, mixed
prompt/output lengths, a tenant mix whose requests share per-tenant
system prompts — so scheduler and paging changes are judged on what
production cares about: p99 TTFT, tokens/s, and how gracefully load is
shed when the offered rate exceeds capacity.

    python -m tools.loadgen --rate 20 --requests 80 --deadline 10
    SINGA_FAULTS="serve.decode=error:every=40" python -m tools.loadgen ...

    # disaggregated tier (ISSUE 12): N prefill + M decode workers
    # behind the SLO-aware Router, and the independent-scaling sweep —
    # one serve_load record per N:M point, same Poisson workload
    python -m tools.loadgen --prefill-workers 3 --decode-workers 1
    python -m tools.loadgen --ratio-sweep 3:1,2:2,1:3 --rate 40
    python -m tools.loadgen --disagg-smoke     # CI: tier == engine

    # speculative decoding (ISSUE 13): verify-k through a
    # self-speculation draft; --spec-compare commits the plain-vs-spec
    # serve_load pair (shared spec_pair_id, interleaved-median trials)
    python -m tools.loadgen --spec-k 4 --new-tokens 32
    python -m tools.loadgen --spec-compare --num-slots 1 --spec-k 7
    python -m tools.loadgen --spec-smoke       # CI: spec == generate()

    # multi-process tier (ISSUE 18): every worker a ServeEngine in its
    # own OS process behind the serve.net wire (framed RPC + digest-
    # checked KV handoff codec); records stamp the transport trio,
    # `procs` and `host_cores` (a 1-core box serializes the workers —
    # the record says so instead of faking a scaling win), and
    # `mp_sweep_id` (NOT sweep_id: the in-process ratio-direction
    # assertion in tests/test_disagg.py must not adopt mp points)
    python -m tools.loadgen --procs --prefill-workers 1 --decode-workers 2
    python -m tools.loadgen --procs --ratio-sweep 2:1,1:2 --rate 40
    python -m tools.loadgen --mp-smoke         # CI: mp tier == engine

The run drives ``ServeEngine.step()`` directly (arrivals are submitted
the tick their timestamp passes; ``QueueFull`` rejections count as
overload outcomes, not errors) and reports SLO percentiles from the
engine's obs histograms.  The headline lands in the run-record store as
a ``serve_load`` entry (``obs/schema.py``; linted by ``python -m
tools.lint --records``) with the offered/completed/shed/rejected
counts and TTFT p50/p99 — and the whole thing is runnable under a
``SINGA_FAULTS`` chaos plan, where the resilience claim is simply "the
engine finished the run" (every fired fault shows up in the detail).

Importable: :func:`build_workload` + :func:`run_load` are used by
tests/test_serve.py against a prebuilt engine (the CLI builds its own
model on CPU).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@dataclass
class _Arrival:
    at_s: float
    prompt: np.ndarray
    max_new: int
    tenant: int


def build_workload(n_requests: int, rate_rps: float, seed: int, *,
                   prompt_lens: Sequence[int] = (6, 10, 16, 24),
                   new_tokens: Sequence[int] = (4, 8, 16),
                   tenants: int = 3, shared_len: int = 16,
                   vocab: int = 256) -> List[_Arrival]:
    """A reproducible open-loop trace: Poisson arrivals at ``rate_rps``,
    prompts drawn as ``tenant system prefix (shared_len tokens) +
    private suffix (prompt_lens mix)``, output budgets from
    ``new_tokens``.  ``tenants=0`` or ``shared_len=0`` disables
    sharing (every prompt fully private)."""
    rng = np.random.RandomState(seed)
    at = np.cumsum(rng.exponential(1.0 / rate_rps, n_requests))
    prefixes = [rng.randint(0, vocab, (shared_len,)).astype(np.int32)
                for _ in range(tenants)] if tenants and shared_len else []
    out = []
    for i in range(n_requests):
        tenant = int(rng.randint(0, tenants)) if prefixes else -1
        suffix = rng.randint(
            0, vocab,
            (int(prompt_lens[rng.randint(0, len(prompt_lens))]),)
        ).astype(np.int32)
        prompt = (np.concatenate([prefixes[tenant], suffix])
                  if prefixes else suffix)
        out.append(_Arrival(float(at[i]), prompt,
                            int(new_tokens[rng.randint(0,
                                                       len(new_tokens))]),
                            tenant))
    return out


def run_load(engine, workload: List[_Arrival], *,
             deadline_s: Optional[float] = None,
             eos_id: Optional[int] = None,
             max_wall_s: float = 300.0,
             pass_tenant: bool = False) -> dict:
    """Drive ``engine`` through ``workload`` open-loop and return the
    ``serve_load`` payload (plus a ``detail`` sub-dict that is NOT part
    of the schema contract).  Never raises on overload outcomes —
    ``QueueFull`` is a counted result; only an engine CRASH (the thing
    chaos runs assert cannot happen) propagates.

    ``engine`` may equally be a :class:`singa_tpu.serve.Router` (a
    disaggregated tier — same submit/step/pending/metrics surface);
    the payload then additionally carries the per-pool tier fields
    (``engine.tier_stats()``, linted as schema
    ``_SERVE_TIER_FIELDS``).  ``pass_tenant`` forwards each arrival's
    tenant id to ``submit(tenant=...)`` so per-tenant quotas are
    exercised (Router only — a plain engine has no tenant door).

    An injected ``serve.router`` fault at the door is a counted
    outcome like ``QueueFull`` (``detail.router_faults``) — the chaos
    contract is that only an engine CRASH aborts the harness, and the
    routing site's documented behavior is 'surfaces to the submitter
    like a routing outage'."""
    from singa_tpu.faults import InjectedFault
    from singa_tpu.serve import QueueFull

    handles = []
    router_faults = 0
    n = len(workload)
    i = 0
    t0 = time.monotonic()
    while True:
        now = time.monotonic() - t0
        while i < n and workload[i].at_s <= now:
            kw = {"tenant": f"t{workload[i].tenant}"} \
                if pass_tenant and workload[i].tenant >= 0 else {}
            try:
                handles.append(engine.submit(
                    workload[i].prompt,
                    max_new_tokens=workload[i].max_new,
                    deadline_s=deadline_s, eos_id=eos_id, **kw))
            except QueueFull:
                handles.append(None)       # counted via metrics.rejected
            except InjectedFault:
                handles.append(None)       # a chaos-plan routing outage
                router_faults += 1
            i += 1
        if engine.pending:
            engine.step()
        elif i < n:
            # idle gap before the next arrival: sleep it off instead of
            # spinning (open loop — we must not pull arrivals early)
            time.sleep(min(workload[i].at_s - now, 0.05))
        else:
            break
        if now > max_wall_s:
            break
    wall = time.monotonic() - t0
    snap = engine.metrics.snapshot()
    # stamp the architecture key (ISSUE 14): the autotuner's spec_k
    # picker matches records to a (model, platform) strictly, so a
    # pair measured on one architecture can never decide another's k
    served_model = getattr(engine, "model", None)
    model_key = None
    if served_model is not None:
        from singa_tpu.autotune import table as autotune_table
        model_key = autotune_table.model_key(served_model)
    done = [h for h in handles if h is not None]
    completed = sum(1 for h in done
                    if h.finish_reason in ("eos", "length"))
    tokens = sum(len(h.tokens) for h in done)
    ttft = snap["ttft_ms"] or {}
    payload = {
        "requests": n,
        "completed": completed,
        "shed": int(snap["evicted"].get("shed", 0)),
        "rejected": int(snap["rejected"]),
        "tokens_per_s": round(tokens / wall, 1) if wall else 0.0,
        "ttft_p50_ms": round(ttft.get("p50", 0.0), 3),
        "ttft_p99_ms": round(ttft.get("p99", 0.0), 3),
    }
    if model_key is not None:
        payload["model"] = model_key
    if snap.get("accept_rate") is not None:
        # speculative engine/tier: the pair joins the headline (schema
        # both-or-neither contract, _SPEC_FIELDS) — accept rate plus the
        # tokens-per-dispatch density the spec path exists to raise
        payload["accept_rate"] = round(snap["accept_rate"], 4)
        payload["tokens_per_dispatch"] = round(
            snap["tokens_per_dispatch"] or 0.0, 3)
    pool = getattr(engine, "pool", None)
    if pool is not None and getattr(pool, "spill", None) is not None:
        # spill-tier engine: the trio joins the headline as a unit
        # (schema all-or-nothing contract, _SERVE_SPILL_FIELDS)
        payload["spilled_blocks"] = int(snap.get("spilled_blocks", 0))
        payload["prefetch_hits"] = int(snap.get("prefetch_hits", 0))
        payload["prefetch_wait_ms"] = round(
            float(snap.get("prefetch_wait_ms", 0.0)), 3)
    payload["detail"] = {
        "wall_s": round(wall, 3),
        "generated_tokens": tokens,
        "deadline_evicted": int(snap["evicted"].get("deadline", 0)),
        "quarantined": int(snap["quarantined"]),
        "preempted": int(snap["preempted"]),
        "recoveries": int(snap["recoveries"]),
        "prefix_hits": int(snap["prefix_hits"]),
        "prefix_hit_tokens": int(snap["prefix_hit_tokens"]),
        "retries": dict(snap["retries"]),
        "token_p50_ms": round((snap["token_ms"] or {}).get("p50", 0.0),
                              3),
        "router_faults": router_faults,
        "spec_rounds": int(snap.get("spec_rounds", 0)),
        "spec_fallbacks": int(snap.get("spec_fallbacks", 0)),
    }
    tier = getattr(engine, "tier_stats", None)
    if tier is not None:
        # a disaggregated Router: the per-pool quartet joins the
        # headline (schema both-or-neither contract) and the tier-only
        # diagnostics stay in detail
        payload.update(tier())
        payload["detail"]["reroutes"] = int(snap.get("reroutes", 0))
        payload["detail"]["worker_deaths"] = int(
            snap.get("worker_deaths", 0))
        payload["detail"]["handoff_p50_ms"] = round(
            (snap.get("handoff_ms") or {}).get("p50", 0.0), 3)
    return payload


def append_record(payload: dict, store: Optional[str] = None,
                  prefix: str = "load") -> str:
    """Write the headline (schema-required fields + numeric extras;
    the ``detail`` sub-dict stays out of the durable record) as a
    ``serve_load`` entry.  Returns the store path.

    ``prefix`` must DIFFER between two appends from the same process in
    the same second: the store keys entries by ``(run_id, platform,
    smoke)`` and ``new_run_id``'s timestamp has second resolution, so
    back-to-back same-prefix appends (the --spec-compare pair) would
    silently overwrite each other."""
    import jax

    from singa_tpu.obs import record as obs_record
    from singa_tpu.obs import schema

    body = {k: v for k, v in payload.items() if k != "detail"}
    body.update({k: v for k, v in payload["detail"].items()
                 if isinstance(v, (int, float))})
    platform = jax.default_backend()
    dev = jax.devices()[0]
    entry = obs_record.new_entry(
        "serve_load", platform, platform != "tpu",
        getattr(dev, "device_kind", "") or platform,
        run_id=obs_record.new_run_id(prefix), payload=body)
    schema.validate_entry(entry)           # fail before touching disk
    store = store or os.path.join(_REPO, obs_record.DEFAULT_STORE)
    obs_record.RunRecord(store).append(entry)
    return store


def _attr_source_engine(target):
    """The ServeEngine whose lowered programs model ``target``'s
    dispatches: the engine itself, or — for a disaggregated tier — the
    first decode worker's engine (decode workers carry the draft, so
    their program set is the tier's superset)."""
    if hasattr(target, "lower_programs"):
        return target
    router = getattr(target, "_router", target)
    for pool in (getattr(router, "decode", None),
                 getattr(router, "prefill", None)):
        if pool:
            # a ProcRouter's pools hold WorkerProc handles — the
            # engines live in other processes, so there is nothing to
            # attribute against here (each child keeps its own ledger)
            return getattr(pool[0], "engine", None)
    return None


def _emit_perf_attr(led, target, window_s: float,
                    dump_path: Optional[str],
                    store: Optional[str]) -> None:
    """Join the run's attribution ledger against the cost model of the
    driven engine's own lowered programs (ISSUE 16); dump to
    ``dump_path`` when given and append a ``perf_attr`` record when a
    store is resolved.  Never fatal — attribution must not turn a
    completed load run into a failure."""
    if dump_path is None and store is None:
        return
    try:
        import jax

        from singa_tpu.obs import attr as obs_attr
        from singa_tpu.obs import record as obs_record
        from tools.lint.perf import engine_features

        src = _attr_source_engine(target)
        if src is None:
            raise RuntimeError("no engine exposes lower_programs")
        payload = obs_attr.attribution_payload(
            led.snapshot(), engine_features(src), window_s)
        if dump_path:
            with open(dump_path, "w", encoding="utf-8") as f:
                json.dump(payload, f, indent=1, sort_keys=True)
            print(f"# perf_attr payload written to {dump_path}",
                  file=sys.stderr)
        if store is not None:
            platform = jax.default_backend()
            dev = jax.devices()[0]
            entry = obs_record.new_entry(
                "perf_attr", platform, platform != "tpu",
                getattr(dev, "device_kind", "") or platform,
                run_id=obs_record.new_run_id("perfattr"),
                payload=payload)
            obs_record.RunRecord(store).append(entry)
            print(f"# perf_attr entry appended to {store}",
                  file=sys.stderr)
    except Exception as e:  # noqa: BLE001
        print(f"# perf_attr emission failed: {type(e).__name__}: {e}",
              file=sys.stderr)


def _spec_kwargs(spec_k, model):
    """The ServeEngine speculative kwargs for ``--spec-k`` — ONE place
    parameterizes every engine/tier/template builder (self-speculation
    draft; a template built differently from its workers would only
    surface at programs= validation time)."""
    return {"draft_model": model, "spec_k": spec_k} if spec_k else {}


def _build_model():
    from singa_tpu import models, tensor
    tensor.set_seed(0)
    m = models.Llama(models.LlamaConfig.tiny())
    m.eval()
    m.compile([tensor.from_numpy(np.zeros((1, 4), np.int32))],
              is_train=False, use_graph=False)
    return m


def _resolve_serve_knobs(args, model) -> dict:
    """Fill ``args.num_slots`` / ``args.block_size`` /
    ``args.spill_blocks`` from the committed best-config table
    (``singa_tpu.autotune.table``) when the CLI left them at their None
    defaults.  Precedence is the autotuner's contract: an explicit flag
    always wins; else the table's entry for this (model, platform);
    else the registry's hand-carried constants
    (``autotune.knobs.DEFAULTS`` — ONE source of truth), announced
    loudly once.  The registry stores ``spill_blocks`` as a number with
    0 = off; the engine constructor wants None for off, so 0 maps
    back."""
    import jax

    from singa_tpu.autotune import table as autotune_table

    knobs = autotune_table.resolve(
        "serve", autotune_table.model_key(model), jax.default_backend(),
        {"num_slots": args.num_slots, "block_size": args.block_size,
         "spill_blocks": getattr(args, "spill_blocks", None)})
    args.num_slots = int(knobs["num_slots"])
    args.block_size = int(knobs["block_size"])
    if getattr(args, "spill_blocks", None) is None:
        spill = int(knobs.get("spill_blocks", 0) or 0)
        args.spill_blocks = spill if spill > 0 else None
    return {"num_slots": args.num_slots,
            "block_size": args.block_size}


def _build_tier(model, n_prefill: int, n_decode: int, args, store,
                template=None):
    """A Router over N + M same-config workers (sharing ``template``'s
    compiled programs when given, so a ratio sweep compiles once).
    With ``--spec-k`` the whole tier carries the (self-speculation)
    draft — prefill workers write both arenas, decode workers verify."""
    from singa_tpu.serve import Router, build_pools

    spec = _spec_kwargs(getattr(args, "spec_k", 0), model)
    pw, dw = build_pools(model, n_prefill, n_decode, template=template,
                         num_slots=args.num_slots, max_len=args.max_len,
                         block_size=args.block_size,
                         num_blocks=args.num_blocks,
                         share_prefix=not args.no_share,
                         max_queue=args.max_queue,
                         backoff_base=0.005, backoff_max=0.05,
                         max_recoveries=100, record_store=store, **spec)
    return Router(pw, dw, tenant_quota=args.tenant_quota,
                  record_store=store)


def parse_ratios(spec: str) -> List[tuple]:
    """``"3:1,2:2,1:3"`` -> [(3, 1), (2, 2), (1, 3)] — the N:M
    prefill:decode points a ratio sweep runs (each must have >= 1
    worker per pool)."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        try:
            n, m = part.split(":")
            n, m = int(n), int(m)
        except ValueError:
            raise ValueError(
                f"--ratio-sweep: expected N:M points like '3:1,1:3', "
                f"got {part!r}")
        if n < 1 or m < 1:
            raise ValueError(f"--ratio-sweep: each pool needs >= 1 "
                             f"worker, got {part!r}")
        out.append((n, m))
    if not out:
        raise ValueError("--ratio-sweep: no points")
    return out


def disagg_smoke() -> int:
    """The CI gate's disagg stage: a tiny 1:1 tier serves 8 requests
    with greedy streams asserted IDENTICAL to a single-engine
    ServeEngine run (and the first one to ``generate()``) — the
    handoff path's end-to-end correctness as one cheap command
    (``python -m tools.loadgen --disagg-smoke``)."""
    from singa_tpu.serve import Router, ServeEngine, build_pools

    m = _build_model()
    rng = np.random.RandomState(11)
    prompts = [rng.randint(0, m.cfg.vocab_size, (int(n),)).astype(np.int32)
               for n in (4, 6, 9, 12, 5, 7, 10, 8)]
    eng = ServeEngine(m, num_slots=4, max_len=32, block_size=8)
    ref = [eng.submit(p, max_new_tokens=6) for p in prompts]
    eng.run_until_idle()
    ref_toks = [h.tokens for h in ref]
    gen = m.generate(prompts[0][None], max_new_tokens=6)[0,
                                                         prompts[0].size:]
    if list(map(int, gen)) != ref_toks[0]:
        print("disagg-smoke: FAIL — single engine drifted from "
              "generate()", file=sys.stderr)
        return 1
    pw, dw = build_pools(m, 1, 1, template=eng, num_slots=4, max_len=32,
                         block_size=8)
    tier = Router(pw, dw)
    got = [tier.submit(p, max_new_tokens=6) for p in prompts]
    tier.run_until_idle()
    got_toks = [h.tokens for h in got]
    if got_toks != ref_toks:
        for i, (a, b) in enumerate(zip(ref_toks, got_toks)):
            if a != b:
                print(f"disagg-smoke: FAIL — request {i} diverged: "
                      f"engine={a} tier={b}", file=sys.stderr)
        return 1
    handoffs = tier.metrics.handoffs
    print(f"disagg-smoke: OK — {len(prompts)} streams identical "
          f"through a 1:1 tier ({handoffs} handoffs)")
    return 0


def spec_smoke() -> int:
    """The CI gate's speculative-decoding stage: the same 8 prompts
    decoded three ways — ``generate()``, a plain engine, and a
    self-speculation engine (draft == target, spec_k=3) — must produce
    IDENTICAL greedy streams, and self-speculation must accept every
    proposal (the identity end of the correctness envelope; the
    adversarial end lives in tests/test_spec.py).  One cheap command:
    ``python -m tools.loadgen --spec-smoke``."""
    from singa_tpu.serve import ServeEngine

    m = _build_model()
    rng = np.random.RandomState(13)
    prompts = [rng.randint(0, m.cfg.vocab_size, (int(n),)).astype(np.int32)
               for n in (4, 6, 9, 12, 5, 7, 10, 8)]
    plain = ServeEngine(m, num_slots=4, max_len=32, block_size=8)
    ref = [plain.submit(p, max_new_tokens=6) for p in prompts]
    plain.run_until_idle()
    ref_toks = [h.tokens for h in ref]
    gen = m.generate(prompts[0][None], max_new_tokens=6)[0,
                                                         prompts[0].size:]
    if list(map(int, gen)) != ref_toks[0]:
        print("spec-smoke: FAIL — plain engine drifted from generate()",
              file=sys.stderr)
        return 1
    spec = ServeEngine(m, num_slots=4, max_len=32, block_size=8,
                       draft_model=m, spec_k=3)
    got = [spec.submit(p, max_new_tokens=6) for p in prompts]
    spec.run_until_idle()
    got_toks = [h.tokens for h in got]
    if got_toks != ref_toks:
        for i, (a, b) in enumerate(zip(ref_toks, got_toks)):
            if a != b:
                print(f"spec-smoke: FAIL — request {i} diverged: "
                      f"plain={a} spec={b}", file=sys.stderr)
        return 1
    snap = spec.metrics.snapshot()
    if snap["accept_rate"] != 1.0:
        print(f"spec-smoke: FAIL — self-speculation accept_rate "
              f"{snap['accept_rate']} != 1.0 (the draft IS the target; "
              f"anything rejected means the verify window diverged "
              f"from sequential decode)", file=sys.stderr)
        return 1
    print(f"spec-smoke: OK — {len(prompts)} streams identical "
          f"(generate == plain == spec_k=3), accept_rate 1.0, "
          f"{snap['tokens_per_dispatch']:.2f} tokens/dispatch")
    return 0


def spill_smoke() -> int:
    """The CI gate's spill-tier stage: a deliberately shrunk arena
    (num_blocks=9) with a host spill store serves a shared-prefix
    request, churns the arena until the cold prefix blocks are evicted
    to the spill tier, then re-hits the prefix so the blocks are
    restored.  Asserts both shared-prefix streams are IDENTICAL to
    ``generate()``, that blocks actually spilled, and that the prefix
    re-hit was served from the spill store — one cheap command
    (``python -m tools.loadgen --spill-smoke``)."""
    from singa_tpu.serve import ServeEngine

    m = _build_model()
    rng = np.random.RandomState(17)
    shared = rng.randint(0, m.cfg.vocab_size, (16,)).astype(np.int32)
    tails = [rng.randint(0, m.cfg.vocab_size, (4,)).astype(np.int32)
             for _ in range(2)]
    prompts = [np.concatenate([shared, t]) for t in tails]
    refs = [list(map(int, m.generate(p[None], max_new_tokens=6)
                     [0, p.size:])) for p in prompts]
    # shrunk arena: the churn requests below need 3+ blocks each and
    # run two-at-a-time, so with only 9 physical blocks the LRU must
    # evict the first request's cold shared-prefix blocks — into the
    # spill store instead of oblivion
    eng = ServeEngine(m, num_slots=2, max_len=32, block_size=8,
                      num_blocks=9, spill_blocks=16)
    h1 = eng.submit(prompts[0], max_new_tokens=6)
    eng.run_until_idle()
    for _ in range(4):
        q = rng.randint(0, m.cfg.vocab_size, (20,)).astype(np.int32)
        eng.submit(q, max_new_tokens=4)
    eng.run_until_idle()
    # prefix re-hit: the shared blocks come back from the spill store
    h2 = eng.submit(prompts[1], max_new_tokens=6)
    eng.run_until_idle()
    got = [h1.tokens, h2.tokens]
    if got != refs:
        for i, (a, b) in enumerate(zip(refs, got)):
            if a != b:
                print(f"spill-smoke: FAIL — request {i} diverged: "
                      f"generate={a} spill={b}", file=sys.stderr)
        return 1
    snap = eng.metrics.snapshot()
    if snap["spilled_blocks"] < 1:
        print("spill-smoke: FAIL — the shrunk arena never spilled a "
              "block (arena sizing drifted?)", file=sys.stderr)
        return 1
    if snap["prefetch_hits"] < 1:
        print("spill-smoke: FAIL — blocks spilled but no prefix re-hit "
              "was served from the spill store", file=sys.stderr)
        return 1
    print(f"spill-smoke: OK — streams identical to generate() through "
          f"a 9-block arena, {snap['spilled_blocks']} blocks spilled, "
          f"{snap['prefetch_hits']} restored "
          f"({snap['prefetch_wait_ms']:.1f} ms total prefetch wait)")
    return 0


def _build_proc_tier(n_prefill: int, n_decode: int, args, store,
                     policy=None):
    """A ProcRouter over N + M worker PROCESSES (ISSUE 18): each worker
    re-builds this module's ``_build_model`` in its own interpreter
    (deterministic — seed 0, same tiny config) and compiles its own
    program set; KV handoffs travel the digest-checked wire codec
    instead of a same-process device copy."""
    from singa_tpu.serve import ProcRouter, build_proc_pools

    pw, dw = build_proc_pools(
        "tools.loadgen:_build_model", n_prefill, n_decode,
        num_slots=args.num_slots, max_len=args.max_len,
        block_size=args.block_size, num_blocks=args.num_blocks,
        share_prefix=not args.no_share, max_queue=args.max_queue,
        record_store=store, self_spec_k=args.spec_k)
    return ProcRouter(pw, dw, record_store=store, policy=policy)


def _stamp_mp(payload: dict, tier, n_procs: int) -> None:
    """The multi-process provenance a ``--procs`` record carries: the
    transport trio (schema ``_SERVE_TRANSPORT_FIELDS``), the worker
    process count, and the host's core count — ``host_cores`` is what
    lets a reader (and the frozen-record assertion in tests) judge
    whether the tokens/s number COULD have scaled with processes, or
    whether a 1-core box serialized them."""
    payload.update(tier.transport_stats())
    if tier.model_key:
        payload["model"] = tier.model_key
    payload["procs"] = int(n_procs)
    payload["host_cores"] = int(os.cpu_count() or 1)


def mp_smoke() -> int:
    """The CI gate's multi-process stage: a 2-process 1:1 tier (each
    worker a ServeEngine in its own OS process behind the serve.net
    RPC) serves 6 requests with greedy streams asserted IDENTICAL to a
    single in-process engine — spawn, framed RPC, the digest-checked KV
    wire codec, and donated-scatter injection end-to-end as one cheap
    command (``python -m tools.loadgen --mp-smoke``)."""
    from singa_tpu.serve import ServeEngine

    m = _build_model()
    rng = np.random.RandomState(19)
    prompts = [rng.randint(0, m.cfg.vocab_size, (int(n),)).astype(np.int32)
               for n in (4, 6, 9, 12, 5, 10)]
    eng = ServeEngine(m, num_slots=4, max_len=32, block_size=8)
    ref = [eng.submit(p, max_new_tokens=6) for p in prompts]
    eng.run_until_idle()
    ref_toks = [h.tokens for h in ref]
    eng.close()

    class _Args:
        num_slots, max_len, block_size = 4, 32, 8
        num_blocks, max_queue, spec_k = None, None, 0
        no_share = False

    tier = _build_proc_tier(1, 1, _Args(), None)
    try:
        got = [tier.submit(p, max_new_tokens=6) for p in prompts]
        tier.run_until_idle()
        got_toks = [h.tokens for h in got]
        handoffs = tier.metrics.handoffs
        wire = tier.metrics.wire_bytes
    finally:
        tier.close()
    if got_toks != ref_toks:
        for i, (a, b) in enumerate(zip(ref_toks, got_toks)):
            if a != b:
                print(f"mp-smoke: FAIL — request {i} diverged across "
                      f"the process boundary: engine={a} tier={b}",
                      file=sys.stderr)
        return 1
    if handoffs < 1:
        print("mp-smoke: FAIL — a 1:1 tier completed without a single "
              "KV handoff (the wire path was never exercised)",
              file=sys.stderr)
        return 1
    print(f"mp-smoke: OK — {len(prompts)} streams identical through a "
          f"2-process 1:1 tier ({handoffs} KV handoffs, {wire} bytes "
          f"over the wire)")
    return 0


def spec_compare(args, store, trials: int = 3) -> int:
    """``--spec-compare``: the SAME Poisson workload through a plain
    engine and a self-speculation verify-k engine (the PR 12-era
    baseline vs ISSUE 13), one ``serve_load`` record each, paired by a
    shared ``spec_pair_id`` — the committed pair is the frozen evidence
    tier-1 asserts the end-to-end tokens/s win from
    (tests/test_spec.py, same contract as the ratio-sweep records).

    Trials are INTERLEAVED (plain, spec, plain, spec, ...) and each
    side records its median-tokens/s run: single back-to-back passes on
    a shared CPU box drift by more than the effect under measurement,
    and an interleaved median is evidence where an A-then-B pair is
    weather."""
    from singa_tpu.obs import record as obs_record
    from singa_tpu.serve import ServeEngine
    from singa_tpu.serve.metrics import ServeMetrics

    m = _build_model()
    _resolve_serve_knobs(args, m)
    new_tokens = tuple(int(t) for t in args.new_tokens.split(",")
                       if t.strip())
    prompt_lens = tuple(int(t) for t in args.prompt_lens.split(",")
                        if t.strip())
    pair_id = obs_record.new_run_id("specpair")
    variants = (0, args.spec_k or 3)
    engines = {}
    for spec_k in variants:
        spec = _spec_kwargs(spec_k, m)
        eng = ServeEngine(m, args.num_slots, args.max_len,
                          block_size=args.block_size,
                          num_blocks=args.num_blocks,
                          share_prefix=not args.no_share,
                          max_queue=args.max_queue,
                          backoff_base=0.005, backoff_max=0.05,
                          max_recoveries=100, record_store=store, **spec)
        # warm the programs so neither side pays a mid-run compile
        eng.submit(build_workload(1, 1.0, args.seed + 1,
                                  vocab=m.cfg.vocab_size)[0].prompt,
                   max_new_tokens=2)
        eng.run_until_idle()
        engines[spec_k] = eng
    runs = {spec_k: [] for spec_k in variants}
    for trial in range(max(1, trials)):
        for spec_k in variants:
            eng = engines[spec_k]
            eng.metrics = ServeMetrics(flight=eng.flight)
            wl = build_workload(args.requests, args.rate, args.seed,
                                prompt_lens=prompt_lens,
                                new_tokens=new_tokens,
                                tenants=args.tenants,
                                shared_len=args.shared_prefix,
                                vocab=m.cfg.vocab_size)
            runs[spec_k].append(run_load(eng, wl,
                                         deadline_s=args.deadline))
    rows = []
    for seq, spec_k in enumerate(variants):
        ordered = sorted(runs[spec_k], key=lambda p: p["tokens_per_s"])
        payload = ordered[len(ordered) // 2]       # median trial
        payload["spec_pair_id"] = pair_id
        payload["spec_seq"] = seq
        payload["spec_k"] = spec_k
        payload["spec_trials"] = len(ordered)
        rows.append(payload)
        print(f"# {'spec_k=' + str(spec_k) if spec_k else 'plain'}  "
              f"tokens/s={payload['tokens_per_s']} (median of "
              f"{len(ordered)})  ttft_p99={payload['ttft_p99_ms']} ms"
              + (f"  accept_rate={payload['accept_rate']}"
                 f"  tokens/dispatch={payload['tokens_per_dispatch']}"
                 if spec_k else ""), file=sys.stderr)
        print(json.dumps(payload, indent=2))
        if store is not None:
            append_record(payload, store,
                          prefix=f"load-spec{spec_k}")
    plain_tps, spec_tps = (r["tokens_per_s"] for r in rows)
    print(f"# spec vs plain tokens/s: {spec_tps} vs {plain_tps} "
          f"({spec_tps / plain_tps:.2f}x, pair {pair_id})",
          file=sys.stderr)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="open-loop Poisson traffic through the paged "
                    "serving engine or a disaggregated prefill/decode "
                    "tier (SLO readout + serve_load record)")
    ap.add_argument("--requests", type=int, default=60)
    ap.add_argument("--rate", type=float, default=20.0,
                    help="offered arrivals/s (push past capacity to "
                         "study overload)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--tenants", type=int, default=3,
                    help="tenant count for the shared-prefix mix "
                         "(0 = no sharing)")
    ap.add_argument("--shared-prefix", type=int, default=16,
                    help="system-prompt tokens shared per tenant")
    ap.add_argument("--deadline", type=float, default=30.0,
                    help="per-request SLO deadline (s); drives "
                         "shedding under overload")
    ap.add_argument("--new-tokens", default="4,8,16",
                    help="comma-separated generation-budget mix drawn "
                         "per request (generation-heavy mixes sharpen "
                         "the decode-side of a ratio sweep)")
    ap.add_argument("--prompt-lens", default="6,10,16,24",
                    help="comma-separated private-suffix prompt-length "
                         "mix (short prompts + long generations isolate "
                         "the decode path a --spec-k comparison is "
                         "about)")
    ap.add_argument("--num-slots", type=int, default=None,
                    help="decode-batch slots (default: the committed "
                         "best-config table's value for this model+"
                         "platform, else 8)")
    ap.add_argument("--max-queue", type=int, default=None,
                    help="admission-queue capacity (default: the "
                         "engine's 2*num_slots)")
    ap.add_argument("--max-len", type=int, default=64)
    ap.add_argument("--block-size", type=int, default=None,
                    help="paged-KV block size (default: the committed "
                         "best-config table's value for this model+"
                         "platform, else 8)")
    ap.add_argument("--num-blocks", type=int, default=None)
    ap.add_argument("--no-share", action="store_true",
                    help="disable prefix-cache sharing in the engine")
    ap.add_argument("--store", default=None,
                    help="run-record store path (default: "
                         "runs/records.jsonl)")
    ap.add_argument("--no-record", action="store_true")
    ap.add_argument("--perf-attr", default=None, metavar="PATH",
                    help="dump the runtime-attribution payload "
                         "(ISSUE 16: per-program dispatch times joined "
                         "against the analytic cost model) to PATH; "
                         "a perf_attr record is appended whenever "
                         "recording is on")
    ap.add_argument("--prefill-workers", type=int, default=0,
                    help="disaggregated tier: prefill pool size "
                         "(with --decode-workers; 0 = single engine)")
    ap.add_argument("--decode-workers", type=int, default=0,
                    help="disaggregated tier: decode pool size")
    ap.add_argument("--tenant-quota", type=int, default=None,
                    help="per-tenant in-flight quota at the tier door "
                         "(Router only)")
    ap.add_argument("--ratio-sweep", default=None, metavar="N:M,...",
                    help="run the SAME workload through each "
                         "prefill:decode ratio (e.g. '3:1,2:2,1:3'), "
                         "emitting one serve_load record per point — "
                         "the independent-scaling measurement")
    ap.add_argument("--disagg-smoke", action="store_true",
                    help="CI smoke: 1:1 tier streams asserted "
                         "identical to a single engine (8 requests); "
                         "exits non-zero on divergence")
    ap.add_argument("--procs", action="store_true",
                    help="run the tier MULTI-PROCESS (serve.net): each "
                         "worker a ServeEngine in its own OS process, "
                         "KV handoffs over the digest-checked wire "
                         "codec; records stamp the transport trio plus "
                         "procs/host_cores provenance")
    ap.add_argument("--elastic-max", type=int, default=0,
                    help="with --procs: cap for an ElasticPolicy that "
                         "grows/shrinks the pools at runtime from "
                         "backpressure signals (0 = fixed pools)")
    ap.add_argument("--mp-smoke", action="store_true",
                    help="CI smoke: 2-process 1:1 tier streams "
                         "asserted identical to a single in-process "
                         "engine (6 requests, >=1 wire handoff); "
                         "exits non-zero on divergence")
    ap.add_argument("--spec-k", type=int, default=0,
                    help="speculative decoding: propose/verify k "
                         "tokens per round through a self-speculation "
                         "draft (0 = plain decode)")
    ap.add_argument("--spec-compare", action="store_true",
                    help="run the SAME workload through a plain and a "
                         "speculative engine, one serve_load record "
                         "each paired by spec_pair_id — the committed "
                         "tokens/s-win evidence")
    ap.add_argument("--spec-smoke", action="store_true",
                    help="CI smoke: self-speculation streams asserted "
                         "identical to generate() and a plain engine, "
                         "accept rate asserted 1.0; exits non-zero on "
                         "divergence")
    ap.add_argument("--spill-smoke", action="store_true",
                    help="CI smoke: shrunk arena + host spill store; "
                         "streams asserted identical to a roomy "
                         "engine, with blocks spilled AND a prefix "
                         "re-hit served from the spill store; exits "
                         "non-zero on divergence")
    ap.add_argument("--chaos-campaign", action="store_true",
                    help="delegate to tools.chaosd: a seeded "
                         "deterministic campaign of worker kills/"
                         "hangs, fault plans and resizes against a "
                         "live multi-process tier under this Poisson "
                         "load shape, committing a chaos_campaign "
                         "record (see python -m tools.chaosd --help "
                         "for the full knob set)")
    ap.add_argument("--chaos-events", type=int, default=6,
                    help="with --chaos-campaign: schedule length")
    ap.add_argument("--kv-dtype", default=None,
                    choices=("f32", "int8"),
                    help="KV arena storage format (plain engine only; "
                         "int8 = quantize-on-scatter blocks with "
                         "per-position scales)")
    ap.add_argument("--spill-blocks", type=int, default=None,
                    help="host spill-store capacity in blocks (plain "
                         "engine only; default: no spill tier)")
    args = ap.parse_args(argv)

    if args.disagg_smoke:
        return disagg_smoke()
    if args.mp_smoke:
        return mp_smoke()
    if args.spec_smoke:
        return spec_smoke()
    if args.spill_smoke:
        return spill_smoke()
    if args.chaos_campaign:
        from tools import chaosd
        cargv = ["--seed", str(args.seed),
                 "--events", str(args.chaos_events),
                 "--rate", str(args.rate)]
        if args.prefill_workers:
            cargv += ["--prefill", str(args.prefill_workers)]
        if args.decode_workers:
            cargv += ["--decode", str(args.decode_workers)]
        if args.store:
            cargv += ["--store", args.store]
        if args.no_record:
            cargv += ["--no-record"]
        return chaosd.main(cargv)
    if args.spec_k < 0:
        ap.error("--spec-k must be >= 0")
    if ((args.kv_dtype or args.spill_blocks) and
            (args.prefill_workers or args.decode_workers or
             args.ratio_sweep or args.spec_compare or args.procs)):
        ap.error("--kv-dtype/--spill-blocks drive a plain engine — "
                 "not a tier, sweep, or --spec-compare")
    if args.procs and args.spec_compare:
        ap.error("--spec-compare is an in-process A/B (interleaved "
                 "trials on shared engines) — it has no --procs mode")
    if args.procs and not (args.ratio_sweep or
                           (args.prefill_workers and
                            args.decode_workers)):
        ap.error("--procs needs a tier: --prefill-workers/"
                 "--decode-workers or --ratio-sweep")
    if args.procs and args.tenant_quota is not None:
        ap.error("--tenant-quota is the in-process Router's door — "
                 "the multi-process tier has no per-tenant quota yet")
    if args.elastic_max and not args.procs:
        ap.error("--elastic-max resizes worker PROCESSES — it needs "
                 "--procs")

    from singa_tpu.obs import record as obs_record
    from singa_tpu.serve import ServeEngine

    # one resolved store for BOTH record producers: the engine's
    # incident entries (quarantine/recovery under chaos) and the final
    # serve_load headline — otherwise a default-args chaos soak would
    # silently drop its incident evidence
    store = (None if args.no_record else
             args.store or os.path.join(_REPO, obs_record.DEFAULT_STORE))

    if args.spec_compare:
        return spec_compare(args, store)

    m = _build_model()
    _resolve_serve_knobs(args, m)
    new_tokens = tuple(int(t) for t in args.new_tokens.split(",")
                       if t.strip())
    prompt_lens = tuple(int(t) for t in args.prompt_lens.split(",")
                        if t.strip())

    if args.ratio_sweep and args.procs:
        points = parse_ratios(args.ratio_sweep)
        # no template sharing across process boundaries: every point
        # spawns fresh workers that each compile their own program set
        # (the per-point spawn+compile cost is the price of real
        # process isolation, and it stays OUT of run_load's wall)
        sweep_id = obs_record.new_run_id("mpsweep")
        rows = []
        for i, (n, mdec) in enumerate(points):
            tier = _build_proc_tier(n, mdec, args, store)
            try:
                wl = build_workload(args.requests, args.rate, args.seed,
                                    prompt_lens=prompt_lens,
                                    new_tokens=new_tokens,
                                    tenants=args.tenants,
                                    shared_len=args.shared_prefix,
                                    vocab=m.cfg.vocab_size)
                payload = run_load(tier, wl, deadline_s=args.deadline)
                _stamp_mp(payload, tier, n + mdec)
            finally:
                tier.close()
            # mp_sweep_id, NOT sweep_id: the in-process ratio-direction
            # assertion (tests/test_disagg.py) groups by sweep_id and
            # must never adopt points measured across process
            # boundaries on an unknown core budget
            payload["mp_sweep_id"] = sweep_id
            payload["mp_sweep_seq"] = i
            rows.append((n, mdec, payload))
            print(f"# mp ratio {n}:{mdec} ({n + mdec} procs, "
                  f"{payload['host_cores']} cores)  "
                  f"ttft_p99={payload['ttft_p99_ms']} ms  "
                  f"tokens/s={payload['tokens_per_s']}  "
                  f"handoffs={payload['handoffs']}  "
                  f"wire_bytes={payload['handoff_wire_bytes']}",
                  file=sys.stderr)
            print(json.dumps(payload, indent=2))
            if store is not None:
                append_record(payload, store, prefix=f"mpload{i}")
        if store is not None:
            print(f"# {len(rows)} serve_load entries (mp sweep "
                  f"{sweep_id}) appended to {store}", file=sys.stderr)
        return 0

    if args.ratio_sweep:
        points = parse_ratios(args.ratio_sweep)
        # every point's tier shares ONE template engine's compiled
        # programs, so the sweep pays one compile no matter how many
        # ratios it visits — and a shared sweep_id groups the points
        # for the direction assertion in tests/test_disagg.py.  The
        # template must carry the same draft/spec_k the workers get:
        # programs= sharing validates draft identity
        spec = _spec_kwargs(args.spec_k, m)
        template = ServeEngine(m, args.num_slots, args.max_len,
                               block_size=args.block_size,
                               num_blocks=args.num_blocks,
                               share_prefix=not args.no_share, **spec)
        # warm every program (incl. the lazily-compiled handoff
        # gather) through a throwaway 1:1 tier, so the first sweep
        # point does not pay a mid-run compile the others skip
        warm = _build_tier(m, 1, 1, args, None, template=template)
        warm.submit(build_workload(1, 1.0, args.seed + 1,
                                   vocab=m.cfg.vocab_size)[0].prompt,
                    max_new_tokens=2)
        warm.run_until_idle()
        sweep_id = obs_record.new_run_id("sweep")
        rows = []
        for i, (n, mdec) in enumerate(points):
            tier = _build_tier(m, n, mdec, args, store,
                               template=template)
            wl = build_workload(args.requests, args.rate, args.seed,
                                prompt_lens=prompt_lens,
                                new_tokens=new_tokens,
                                tenants=args.tenants,
                                shared_len=args.shared_prefix,
                                vocab=m.cfg.vocab_size)
            payload = run_load(tier, wl, deadline_s=args.deadline,
                               pass_tenant=args.tenant_quota is not None)
            payload["sweep_id"] = sweep_id
            payload["sweep_seq"] = i
            rows.append((n, mdec, payload))
            print(f"# ratio {n}:{mdec}  ttft_p99={payload['ttft_p99_ms']}"
                  f" ms  tokens/s={payload['tokens_per_s']}  "
                  f"handoffs={payload['handoffs']}", file=sys.stderr)
            print(json.dumps(payload, indent=2))
            if store is not None:
                append_record(payload, store)
        if store is not None:
            print(f"# {len(rows)} serve_load entries (sweep {sweep_id}) "
                  f"appended to {store}", file=sys.stderr)
        return 0

    if args.prefill_workers or args.decode_workers:
        if args.prefill_workers < 1 or args.decode_workers < 1:
            ap.error("a tier needs --prefill-workers >= 1 AND "
                     "--decode-workers >= 1")
        if args.procs:
            policy = None
            if args.elastic_max:
                from singa_tpu.serve import ElasticPolicy
                policy = ElasticPolicy(max_total=args.elastic_max)
            eng = _build_proc_tier(args.prefill_workers,
                                   args.decode_workers, args, store,
                                   policy=policy)
        else:
            eng = _build_tier(m, args.prefill_workers,
                              args.decode_workers, args, store)
    else:
        if args.tenant_quota is not None:
            ap.error("--tenant-quota needs a tier "
                     "(--prefill-workers/--decode-workers) — a plain "
                     "engine has no tenant door")
        spec = _spec_kwargs(args.spec_k, m)
        eng = ServeEngine(m, args.num_slots, args.max_len,
                          block_size=args.block_size,
                          num_blocks=args.num_blocks,
                          share_prefix=not args.no_share,
                          max_queue=args.max_queue,
                          backoff_base=0.005, backoff_max=0.05,
                          # a chaos soak may recover many times; the
                          # engine-default budget of 2 is tuned for unit
                          # scenarios, not sustained injection
                          max_recoveries=100,
                          record_store=store,
                          kv_dtype=args.kv_dtype,
                          spill_blocks=args.spill_blocks, **spec)
    wl = build_workload(args.requests, args.rate, args.seed,
                        prompt_lens=prompt_lens,
                        new_tokens=new_tokens,
                        tenants=args.tenants,
                        shared_len=args.shared_prefix,
                        vocab=m.cfg.vocab_size)
    # runtime-attribution ledger (ISSUE 16) around the driven window
    from singa_tpu.obs import attr as obs_attr
    led = obs_attr.install()
    payload = run_load(eng, wl, deadline_s=args.deadline,
                       pass_tenant=args.tenant_quota is not None)
    obs_attr.uninstall()
    if args.procs:
        _stamp_mp(payload, eng,
                  args.prefill_workers + args.decode_workers)
        eng.close()
    print(json.dumps(payload, indent=2))
    if store is not None:
        append_record(payload, store,
                      prefix="mpload" if args.procs else "load")
        print(f"# serve_load entry appended to {store}", file=sys.stderr)
    if not args.procs:
        # attribution is per-process: the supervisor dispatches no XLA
        # programs of its own, so an mp run's ledger here is empty —
        # each worker keeps its own
        _emit_perf_attr(led, eng, payload["detail"]["wall_s"],
                        args.perf_attr, store)
    return 0


if __name__ == "__main__":
    sys.exit(main())
