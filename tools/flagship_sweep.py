"""Flagship-config sweep: honest-MFU (traced-FLOPs numerator) of
larger single-chip Llama configs.  The 110M `small` config has weak
arithmetic intensity (dim 768); a right-sized config keeps the MXU
busier per HBM byte.

Usage: nohup setsid python tools/flagship_sweep.py > /tmp/flagship.out 2>&1 &
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np


def main():
    from singa_tpu import device, models, opt, tensor
    from singa_tpu.utils.metrics import peak_flops
    from singa_tpu.utils.timing import windowed_steps

    device.set_default_device(device.create_tpu_device())
    peak = peak_flops("TPU v5 lite")

    cases = [
        ("small-768x12 b16", dict(vocab_size=32000, dim=768, num_layers=12,
                                  num_heads=12, num_kv_heads=4,
                                  ffn_dim=2048, max_position=2048), 16),
        ("mid-1536x16 b8", dict(vocab_size=32000, dim=1536, num_layers=16,
                                num_heads=16, num_kv_heads=8,
                                ffn_dim=4096, max_position=2048), 8),
        ("big-2048x16 b8", dict(vocab_size=32000, dim=2048, num_layers=16,
                                num_heads=16, num_kv_heads=8,
                                ffn_dim=5632, max_position=2048), 8),
        ("big-2048x24 b8", dict(vocab_size=32000, dim=2048, num_layers=24,
                                num_heads=16, num_kv_heads=8,
                                ffn_dim=5632, max_position=2048), 8),
    ]
    T = 1024
    for name, kw, B in cases:
        try:
            tensor.set_seed(0)
            np.random.seed(0)
            cfg = models.LlamaConfig(**kw)
            cfg.fused_loss = True
            m = models.Llama(cfg)
            m.set_optimizer(opt.SGD(lr=0.01, momentum=0.9))
            ids = tensor.from_numpy(np.random.randint(
                0, cfg.vocab_size, (B, T)).astype(np.int32))
            t0 = time.perf_counter()
            m.compile([ids], is_train=True, use_graph=True)
            out = m.train_step(ids)
            np.asarray(out[-1].data)
            t_compile = time.perf_counter() - t0

            holder = {}

            def one():
                holder["out"] = m.train_step(ids)
                return holder["out"][-1].data

            dt, stats = windowed_steps(one, windows=3, window_len=8,
                                       warmup=1)
            n = m.num_params()
            n_emb = cfg.vocab_size * cfg.dim     # tok_emb gather, no FLOPs
            fl_tok = (6 * (n - n_emb) + 12 * cfg.num_layers * cfg.dim * T
                      + 2 * cfg.dim * cfg.vocab_size)
            fl = fl_tok * B * T
            print(f"{name:18s} params {n/1e6:6.1f}M  {dt*1e3:8.2f} ms/step "
                  f"{B*T/dt:9,.0f} tok/s  MFU(hon) {fl/dt/peak:.4f}  "
                  f"compile {t_compile:.0f}s  windows {stats['window_ms']}",
                  flush=True)
            del m, holder
        except Exception as e:  # noqa: BLE001
            print(f"{name:18s} FAILED {type(e).__name__}: "
                  f"{str(e).splitlines()[0][:160]}", flush=True)


if __name__ == "__main__":
    main()
