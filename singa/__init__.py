"""`singa` compatibility alias — the frozen Python surface of
BASELINE.json:5 ("The Python singa.model API and the sonnx ONNX importer
run unmodified ... with a one-line device change").  All implementation
lives in singa_tpu."""

import sys as _sys

import singa_tpu as _impl
from singa_tpu import (autograd, device, graph, layer, model, opt,  # noqa: F401
                       ops, parallel, proto, tensor, utils)

__version__ = _impl.__version__

# make `import singa.tensor` style imports resolve to the impl modules
for _name in ("device", "proto", "tensor", "autograd", "layer", "model",
              "opt", "graph", "ops", "parallel", "utils"):
    _sys.modules[f"singa.{_name}"] = getattr(_impl, _name)


def __getattr__(name):
    if name in ("sonnx", "models"):
        mod = getattr(_impl, name)
        _sys.modules[f"singa.{name}"] = mod
        return mod
    raise AttributeError(name)
