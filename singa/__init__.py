"""`singa` compatibility alias — the frozen Python surface of
BASELINE.json:5 ("The Python singa.model API and the sonnx ONNX importer
run unmodified ... with a one-line device change").  All implementation
lives in singa_tpu."""

import sys as _sys

import singa_tpu as _impl
from singa_tpu import (autograd, device, graph, layer, model, opt,  # noqa: F401
                       ops, parallel, proto, tensor, utils)

__version__ = _impl.__version__

# make `import singa.tensor` style imports resolve to the impl modules
for _name in ("device", "proto", "tensor", "autograd", "layer", "model",
              "opt", "graph", "ops", "parallel", "utils"):
    _sys.modules[f"singa.{_name}"] = getattr(_impl, _name)


def __getattr__(name):
    if name in ("sonnx", "models"):
        mod = getattr(_impl, name)
        _sys.modules[f"singa.{name}"] = mod
        return mod
    raise AttributeError(name)


class _AliasFinder:
    """Make `import singa.sonnx` / `import singa.models` (and any
    submodule underneath, e.g. `singa.sonnx.backend`) resolve to the
    SAME module objects as their singa_tpu counterparts: plain import
    statements bypass module __getattr__, and without this finder the
    path-based machinery would re-execute the source files as duplicate
    modules (distinct classes, diverged registries)."""

    _PREFIXES = ("singa.sonnx", "singa.models")

    def find_spec(self, fullname, path=None, target=None):
        if fullname in self._PREFIXES or any(
                fullname.startswith(p + ".") for p in self._PREFIXES):
            import importlib
            import importlib.util
            mod = importlib.import_module(
                "singa_tpu." + fullname.split(".", 1)[1])
            return importlib.util.spec_from_loader(
                fullname, _AliasLoader(mod))
        return None


class _AliasLoader:
    def __init__(self, mod):
        self._mod = mod

    def create_module(self, spec):
        # remember the real identity: the import system is about to
        # stamp the alias spec onto this (shared) module object
        self._orig = (self._mod.__spec__, self._mod.__loader__)
        return self._mod

    def exec_module(self, module):
        # restore the true __spec__/__loader__ so importlib.reload and
        # introspection keep working on the singa_tpu module
        module.__spec__, module.__loader__ = self._orig


# BEFORE PathFinder: singa.sonnx's __path__ points at the real
# singa_tpu/sonnx directory, so the path machinery would happily
# re-execute submodule files as duplicates if consulted first
_sys.meta_path.insert(0, _AliasFinder())
