"""Structural ONNX model validation — a pure-python subset of
``onnx.checker.check_model`` that runs where the official wheel is not
installed (this build image; VERDICT r4 item 9).

Validates the rules the official checker enforces for the graphs this
framework produces and consumes:

  * model: ir_version set, at least one opset_import, graph present;
  * graph SSA: every node input resolves to a graph input, an
    initializer, or an EARLIER node's output; no value name is defined
    twice; every graph output is defined;
  * nodes: non-empty op_type; empty-string inputs allowed (ONNX's
    "optional absent" convention);
  * initializers: known dtype, raw_data length == prod(dims) *
    itemsize when raw encoding is used;
  * attributes: a name, and a consistent type/value pairing (at most
    one value family populated; declared type matches it when set);
    sub-graph attributes (If/Loop) are checked recursively with outer
    scope visible (ONNX scoping rule).

This is deliberately NOT a replacement for the official checker in
CI — tests/test_sonnx_external.py keeps the ``onnx``-wheel legs,
which validate against the reference implementation when the wheel is
present.  Here the same structural assertions run everywhere, so a
malformed export can never ride a skipped test into a release.
"""

from __future__ import annotations

from math import prod

from .proto import (AttributeProto, GraphProto, ModelProto, TensorProto,
                    _TP2NP)

__all__ = ["CheckError", "check_model", "check_graph"]


class CheckError(ValueError):
    """A structural validation failure (mirrors onnx.checker's
    ValidationError role)."""


def _fail(msg: str) -> None:
    raise CheckError(msg)


# attribute value families: (field, AttributeProto type enum, is_repeated)
_ATTR_FAMILIES = (
    ("f", AttributeProto.FLOAT, False),
    ("i", AttributeProto.INT, False),
    ("s", AttributeProto.STRING, False),
    ("t", AttributeProto.TENSOR, False),
    ("g", AttributeProto.GRAPH, False),
    ("floats", AttributeProto.FLOATS, True),
    ("ints", AttributeProto.INTS, True),
    ("strings", AttributeProto.STRINGS, True),
    ("tensors", AttributeProto.TENSORS, True),
    ("graphs", AttributeProto.GRAPHS, True),
)


def _check_attribute(a: AttributeProto, node_name: str,
                     outer_scope: set) -> None:
    if not a.name:
        _fail(f"node {node_name!r}: attribute without a name")
    populated = []
    for field, enum, rep in _ATTR_FAMILIES:
        v = getattr(a, field, None)
        if rep:
            if v:
                populated.append((field, enum))
        else:
            # scalar fields: proto3 default (0 / empty) is
            # indistinguishable from set — rely on the declared type
            # when present, else detect non-default
            if field in ("t", "g"):
                if v is not None:
                    populated.append((field, enum))
            elif v:
                populated.append((field, enum))
    declared = a.type or 0
    if len(populated) > 1:
        # the official checker rejects multi-family attributes whether
        # or not a type is declared — a declared type matching ONE of
        # the families must not launder the extra payload through
        _fail(f"node {node_name!r}: attribute {a.name!r} has multiple "
              f"value families {populated}"
              + (f" (declared type {declared})" if declared
                 else " and no type"))
    if declared:
        matches = [e for _f, e in populated]
        if populated and declared not in matches:
            # scalar zero values legitimately vanish; only complain
            # when a DIFFERENT family is populated
            _fail(f"node {node_name!r}: attribute {a.name!r} declares "
                  f"type {declared} but carries {populated}")
    # recurse into sub-graphs with the outer scope visible
    if a.g is not None:
        check_graph(a.g, outer_scope=outer_scope)
    for g in a.graphs or ():
        check_graph(g, outer_scope=outer_scope)


def _check_initializer(t: TensorProto, graph_name: str) -> None:
    if not t.name:
        _fail(f"graph {graph_name!r}: initializer without a name")
    dt = t.data_type or TensorProto.FLOAT
    np_dt = _TP2NP.get(dt)
    if np_dt is None:
        _fail(f"initializer {t.name!r}: unknown data_type {dt}")
    n = prod(t.dims) if t.dims else 1
    if t.raw_data:
        expect = n * np_dt.itemsize
        if len(t.raw_data) != expect:
            _fail(f"initializer {t.name!r}: raw_data is "
                  f"{len(t.raw_data)} bytes, dims {list(t.dims)} x "
                  f"{np_dt} needs {expect}")
    else:
        typed = (t.float_data or t.int32_data or t.int64_data
                 or t.double_data or t.uint64_data or t.string_data)
        if typed and len(typed) not in (n, 0):
            _fail(f"initializer {t.name!r}: {len(typed)} typed values "
                  f"for dims {list(t.dims)}")


def check_graph(g: GraphProto, outer_scope: set | None = None) -> None:
    name = g.name or "<unnamed>"
    defined = set(outer_scope or ())
    for vi in g.input or ():
        if not vi.name:
            _fail(f"graph {name!r}: graph input without a name")
        defined.add(vi.name)
    for init in g.initializer or ():
        _check_initializer(init, name)
        defined.add(init.name)
    for i, node in enumerate(g.node or ()):
        label = node.name or f"#{i}({node.op_type})"
        if not node.op_type:
            _fail(f"graph {name!r}: node {label!r} has no op_type")
        for inp in node.input or ():
            if inp and inp not in defined:
                _fail(f"graph {name!r}: node {label!r} input {inp!r} is "
                      f"not a graph input, initializer, or earlier "
                      f"node output (SSA violation)")
        for a in node.attribute or ():
            _check_attribute(a, label, defined)
        for out in node.output or ():
            if not out:
                continue
            if out in defined:
                _fail(f"graph {name!r}: value {out!r} defined twice "
                      f"(SSA violation at node {label!r})")
            defined.add(out)
    for vo in g.output or ():
        if vo.name and vo.name not in defined:
            _fail(f"graph {name!r}: graph output {vo.name!r} is never "
                  f"produced")


def check_model(m: ModelProto) -> None:
    """Validate `m` structurally; raises CheckError on the first
    violation, returns None when the model passes (the official
    checker's contract)."""
    if not m.ir_version:
        _fail("model has no ir_version")
    if not m.opset_import:
        _fail("model has no opset_import")
    for op in m.opset_import:
        if op.version in (None, 0):
            _fail(f"opset_import for domain {op.domain!r} has no version")
    if m.graph is None:
        _fail("model has no graph")
    check_graph(m.graph)
