"""Self-contained ONNX protobuf support (no `onnx` pip dependency).

Capability parity: the reference's `sonnx` module rides the `onnx` python
package for ModelProto / GraphProto / helper builders (BASELINE.json:5,9
— "the sonnx ONNX importer", BERT-base + GPT-2 workloads).  This image
has no `onnx` wheel and the bundled protoc (3.21) emits gencode the
protobuf-6.x runtime rejects, so we implement the subset of the ONNX
protobuf schema we need directly against the protobuf *wire format*
(varint / 64-bit / length-delimited / 32-bit records).  Field numbers
below match onnx/onnx.proto exactly, so files produced here open in
netron/onnxruntime and real exported .onnx files load here.

Public surface mirrors `onnx` + `onnx.helper` + `onnx.numpy_helper`:
    ModelProto, GraphProto, NodeProto, TensorProto, AttributeProto, ...
    make_node, make_graph, make_model, make_tensor, make_tensor_value_info
    to_array, from_array, load, save, load_model_from_string
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

try:  # bf16 numpy dtype ships with jax
    import ml_dtypes
    _BF16 = np.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover
    ml_dtypes = None
    _BF16 = None

__all__ = [
    "TensorProto", "AttributeProto", "ValueInfoProto", "NodeProto",
    "ModelProto", "GraphProto", "TypeProto", "TensorShapeProto",
    "OperatorSetIdProto",
    "make_node", "make_graph", "make_model", "make_tensor",
    "make_tensor_value_info", "make_attribute",
    "to_array", "from_array", "load", "save", "load_model_from_string",
    "tensor_dtype_to_np_dtype", "np_dtype_to_tensor_dtype",
]


# ---------------------------------------------------------------------------
# wire-format primitives
# ---------------------------------------------------------------------------

_WT_VARINT, _WT_I64, _WT_LEN, _WT_I32 = 0, 1, 2, 5

_VARINT_KINDS = ("int64", "int32", "uint64", "enum")


def _enc_varint(buf: bytearray, n: int) -> None:
    if n < 0:
        n += 1 << 64  # two's-complement int64 on the wire
    while True:
        b = n & 0x7F
        n >>= 7
        buf.append(b | (0x80 if n else 0))
        if not n:
            break


def _dec_varint(data: bytes, pos: int) -> Tuple[int, int]:
    res = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        res |= (b & 0x7F) << shift
        if not (b & 0x80):
            return res, pos
        shift += 7


def _signed(v: int, kind: str) -> int:
    if kind in ("int64", "int32", "enum") and v >= 1 << 63:
        v -= 1 << 64
    return v


def _enc_tag(buf: bytearray, num: int, wt: int) -> None:
    _enc_varint(buf, (num << 3) | wt)


def _enc_len_delim(buf: bytearray, num: int, payload: bytes) -> None:
    _enc_tag(buf, num, _WT_LEN)
    _enc_varint(buf, len(payload))
    buf += payload


# ---------------------------------------------------------------------------
# generic message base
# ---------------------------------------------------------------------------

class Message:
    """Tiny protobuf message: subclasses declare FIELDS =
    {field_number: (attr_name, kind, repeated)} where kind is a scalar
    kind string or a Message subclass."""

    FIELDS: Dict[int, Tuple[str, Any, bool]] = {}

    def __init__(self, **kw):
        for _num, (name, _kind, rep) in self.FIELDS.items():
            setattr(self, name, [] if rep else None)
        for k, v in kw.items():
            if k not in {n for (n, _k, _r) in self.FIELDS.values()}:
                raise AttributeError(f"{type(self).__name__} has no field {k!r}")
            setattr(self, k, v)

    # -- encode ---------------------------------------------------------------
    def SerializeToString(self) -> bytes:
        buf = bytearray()
        for num in sorted(self.FIELDS):
            name, kind, rep = self.FIELDS[num]
            val = getattr(self, name)
            if val is None or (rep and len(val) == 0):
                continue
            vals = val if rep else [val]
            if isinstance(kind, type) and issubclass(kind, Message):
                for v in vals:
                    _enc_len_delim(buf, num, v.SerializeToString())
            elif kind in _VARINT_KINDS:
                if rep:  # packed
                    inner = bytearray()
                    for v in vals:
                        _enc_varint(inner, int(v))
                    _enc_len_delim(buf, num, bytes(inner))
                else:
                    _enc_tag(buf, num, _WT_VARINT)
                    _enc_varint(buf, int(vals[0]))
            elif kind == "float":
                if rep:
                    _enc_len_delim(buf, num, struct.pack(f"<{len(vals)}f", *vals))
                else:
                    _enc_tag(buf, num, _WT_I32)
                    buf += struct.pack("<f", vals[0])
            elif kind == "double":
                if rep:
                    _enc_len_delim(buf, num, struct.pack(f"<{len(vals)}d", *vals))
                else:
                    _enc_tag(buf, num, _WT_I64)
                    buf += struct.pack("<d", vals[0])
            elif kind == "string":
                for v in vals:
                    _enc_len_delim(buf, num, v.encode("utf-8") if isinstance(v, str) else bytes(v))
            elif kind == "bytes":
                for v in vals:
                    _enc_len_delim(buf, num, bytes(v))
            else:  # pragma: no cover
                raise TypeError(f"unknown field kind {kind}")
        return bytes(buf)

    # -- decode ---------------------------------------------------------------
    @classmethod
    def FromString(cls, data: bytes) -> "Message":
        msg = cls()
        pos, end = 0, len(data)
        while pos < end:
            tag, pos = _dec_varint(data, pos)
            num, wt = tag >> 3, tag & 0x7
            spec = cls.FIELDS.get(num)
            if spec is None:
                pos = _skip(data, pos, wt)
                continue
            name, kind, rep = spec
            if isinstance(kind, type) and issubclass(kind, Message):
                ln, pos = _dec_varint(data, pos)
                sub = kind.FromString(data[pos:pos + ln])
                pos += ln
                if rep:
                    getattr(msg, name).append(sub)
                else:
                    setattr(msg, name, sub)
            elif kind in _VARINT_KINDS:
                if wt == _WT_LEN:  # packed
                    ln, pos = _dec_varint(data, pos)
                    stop = pos + ln
                    lst = getattr(msg, name) if rep else None
                    while pos < stop:
                        v, pos = _dec_varint(data, pos)
                        v = _signed(v, kind)
                        if rep:
                            lst.append(v)
                        else:
                            setattr(msg, name, v)
                else:
                    v, pos = _dec_varint(data, pos)
                    v = _signed(v, kind)
                    if rep:
                        getattr(msg, name).append(v)
                    else:
                        setattr(msg, name, v)
            elif kind in ("float", "double"):
                fmt, size, wtyp = (("<f", 4, _WT_I32) if kind == "float"
                                   else ("<d", 8, _WT_I64))
                if wt == _WT_LEN:  # packed
                    ln, pos = _dec_varint(data, pos)
                    n = ln // size
                    vals = struct.unpack(f"<{n}{fmt[-1]}", data[pos:pos + ln])
                    pos += ln
                    if rep:
                        getattr(msg, name).extend(vals)
                    elif vals:
                        setattr(msg, name, vals[-1])
                else:
                    (v,) = struct.unpack(fmt, data[pos:pos + size])
                    pos += size
                    if rep:
                        getattr(msg, name).append(v)
                    else:
                        setattr(msg, name, v)
            elif kind in ("string", "bytes"):
                ln, pos = _dec_varint(data, pos)
                raw = data[pos:pos + ln]
                pos += ln
                v = raw.decode("utf-8") if kind == "string" else raw
                if rep:
                    getattr(msg, name).append(v)
                else:
                    setattr(msg, name, v)
            else:  # pragma: no cover
                raise TypeError(f"unknown field kind {kind}")
        return msg

    def ParseFromString(self, data: bytes) -> None:
        """Protobuf in-place parse idiom: mutates self (unlike the
        classmethod FromString, which returns a new message)."""
        parsed = type(self).FromString(data)
        for _num, (name, _kind, _rep) in self.FIELDS.items():
            setattr(self, name, getattr(parsed, name))

    def __repr__(self):
        parts = []
        for _num, (name, _kind, rep) in sorted(self.FIELDS.items()):
            v = getattr(self, name)
            if v is None or (rep and not v):
                continue
            s = f"[{len(v)} items]" if rep and len(v) > 3 else repr(v)
            parts.append(f"{name}={s}")
        return f"{type(self).__name__}({', '.join(parts)})"


def _skip(data: bytes, pos: int, wt: int) -> int:
    if wt == _WT_VARINT:
        _, pos = _dec_varint(data, pos)
    elif wt == _WT_I64:
        pos += 8
    elif wt == _WT_LEN:
        ln, pos = _dec_varint(data, pos)
        pos += ln
    elif wt == _WT_I32:
        pos += 4
    else:
        raise ValueError(f"cannot skip wire type {wt}")
    return pos


# ---------------------------------------------------------------------------
# ONNX messages — field numbers match onnx/onnx.proto
# ---------------------------------------------------------------------------

class TensorProto(Message):
    # DataType enum values (onnx.proto TensorProto.DataType)
    UNDEFINED, FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64 = range(8)
    STRING, BOOL, FLOAT16, DOUBLE, UINT32, UINT64 = 8, 9, 10, 11, 12, 13
    COMPLEX64, COMPLEX128, BFLOAT16 = 14, 15, 16

    FIELDS = {
        1: ("dims", "int64", True),
        2: ("data_type", "int32", False),
        4: ("float_data", "float", True),
        5: ("int32_data", "int32", True),
        6: ("string_data", "bytes", True),
        7: ("int64_data", "int64", True),
        8: ("name", "string", False),
        9: ("raw_data", "bytes", False),
        10: ("double_data", "double", True),
        11: ("uint64_data", "uint64", True),
        12: ("doc_string", "string", False),
    }


class TensorShapeProto(Message):
    class Dimension(Message):
        FIELDS = {
            1: ("dim_value", "int64", False),
            2: ("dim_param", "string", False),
            3: ("denotation", "string", False),
        }

    FIELDS = {1: ("dim", Dimension, True)}


class TypeProto(Message):
    class Tensor(Message):
        FIELDS = {
            1: ("elem_type", "int32", False),
            2: ("shape", TensorShapeProto, False),
        }

    FIELDS = {1: ("tensor_type", Tensor, False), 6: ("denotation", "string", False)}


class ValueInfoProto(Message):
    FIELDS = {
        1: ("name", "string", False),
        2: ("type", TypeProto, False),
        3: ("doc_string", "string", False),
    }


class AttributeProto(Message):
    # AttributeType enum
    UNDEFINED, FLOAT, INT, STRING, TENSOR, GRAPH = range(6)
    FLOATS, INTS, STRINGS, TENSORS, GRAPHS = 6, 7, 8, 9, 10

    FIELDS = {
        1: ("name", "string", False),
        2: ("f", "float", False),
        3: ("i", "int64", False),
        4: ("s", "bytes", False),
        5: ("t", TensorProto, False),
        7: ("floats", "float", True),
        8: ("ints", "int64", True),
        9: ("strings", "bytes", True),
        10: ("tensors", TensorProto, True),
        13: ("doc_string", "string", False),
        20: ("type", "enum", False),
        21: ("ref_attr_name", "string", False),
    }
    # field 6/11 (g/graphs: GraphProto) registered after GraphProto exists


class NodeProto(Message):
    FIELDS = {
        1: ("input", "string", True),
        2: ("output", "string", True),
        3: ("name", "string", False),
        4: ("op_type", "string", False),
        5: ("attribute", AttributeProto, True),
        6: ("doc_string", "string", False),
        7: ("domain", "string", False),
    }


class GraphProto(Message):
    FIELDS = {
        1: ("node", NodeProto, True),
        2: ("name", "string", False),
        5: ("initializer", TensorProto, True),
        10: ("doc_string", "string", False),
        11: ("input", ValueInfoProto, True),
        12: ("output", ValueInfoProto, True),
        13: ("value_info", ValueInfoProto, True),
    }


# close the recursion: AttributeProto.g / .graphs
AttributeProto.FIELDS[6] = ("g", GraphProto, False)
AttributeProto.FIELDS[11] = ("graphs", GraphProto, True)


class OperatorSetIdProto(Message):
    FIELDS = {
        1: ("domain", "string", False),
        2: ("version", "int64", False),
    }


class StringStringEntryProto(Message):
    FIELDS = {
        1: ("key", "string", False),
        2: ("value", "string", False),
    }


class ModelProto(Message):
    FIELDS = {
        1: ("ir_version", "int64", False),
        2: ("producer_name", "string", False),
        3: ("producer_version", "string", False),
        4: ("domain", "string", False),
        5: ("model_version", "int64", False),
        6: ("doc_string", "string", False),
        7: ("graph", GraphProto, False),
        8: ("opset_import", OperatorSetIdProto, True),
        14: ("metadata_props", StringStringEntryProto, True),
    }


# ---------------------------------------------------------------------------
# dtype mapping + numpy_helper
# ---------------------------------------------------------------------------

_TP2NP = {
    TensorProto.FLOAT: np.dtype(np.float32),
    TensorProto.UINT8: np.dtype(np.uint8),
    TensorProto.INT8: np.dtype(np.int8),
    TensorProto.UINT16: np.dtype(np.uint16),
    TensorProto.INT16: np.dtype(np.int16),
    TensorProto.INT32: np.dtype(np.int32),
    TensorProto.INT64: np.dtype(np.int64),
    TensorProto.BOOL: np.dtype(np.bool_),
    TensorProto.FLOAT16: np.dtype(np.float16),
    TensorProto.DOUBLE: np.dtype(np.float64),
    TensorProto.UINT32: np.dtype(np.uint32),
    TensorProto.UINT64: np.dtype(np.uint64),
}
if _BF16 is not None:
    _TP2NP[TensorProto.BFLOAT16] = _BF16
_NP2TP = {v: k for k, v in _TP2NP.items()}


def tensor_dtype_to_np_dtype(tp: int) -> np.dtype:
    return _TP2NP[tp]


def np_dtype_to_tensor_dtype(dt) -> int:
    dt = np.dtype(dt)
    if dt not in _NP2TP:
        raise TypeError(f"no ONNX dtype for numpy {dt}")
    return _NP2TP[dt]


def to_array(t: TensorProto) -> np.ndarray:
    """TensorProto → numpy (onnx.numpy_helper.to_array parity)."""
    dt = _TP2NP[t.data_type or TensorProto.FLOAT]
    dims = tuple(t.dims)
    if t.raw_data:
        a = np.frombuffer(t.raw_data, dtype=dt.newbyteorder("<")).astype(dt)
        return a.reshape(dims)
    if t.data_type == TensorProto.FLOAT and t.float_data:
        return np.asarray(t.float_data, np.float32).reshape(dims)
    if t.data_type == TensorProto.DOUBLE and t.double_data:
        return np.asarray(t.double_data, np.float64).reshape(dims)
    if t.data_type in (TensorProto.INT64,) and t.int64_data:
        return np.asarray(t.int64_data, np.int64).reshape(dims)
    if t.data_type in (TensorProto.UINT64,) and t.uint64_data:
        return np.asarray(t.uint64_data, np.uint64).reshape(dims)
    if t.data_type in (TensorProto.FLOAT16, TensorProto.BFLOAT16) and t.int32_data:
        raw = np.asarray(t.int32_data, np.int32).astype(np.uint16)
        return raw.view(dt).reshape(dims)
    if t.int32_data:  # int32 and narrower ints ride int32_data
        return np.asarray(t.int32_data, np.int32).astype(dt).reshape(dims)
    return np.zeros(dims, dt)


def from_array(a: np.ndarray, name: str = "") -> TensorProto:
    """numpy → TensorProto via raw_data (onnx.numpy_helper.from_array).
    (np.asarray, not ascontiguousarray: the latter promotes 0-d to 1-d,
    and .tobytes() below already copies non-contiguous input.)"""
    a = np.asarray(a)
    t = TensorProto()
    t.name = name
    t.dims = list(a.shape)
    t.data_type = np_dtype_to_tensor_dtype(a.dtype)
    t.raw_data = a.astype(a.dtype.newbyteorder("<"), copy=False).tobytes()
    return t


# ---------------------------------------------------------------------------
# helper builders (onnx.helper parity)
# ---------------------------------------------------------------------------

def make_attribute(name: str, value: Any) -> AttributeProto:
    a = AttributeProto(name=name)
    if isinstance(value, np.ndarray):
        value = from_array(value)
    if isinstance(value, bool):
        a.type, a.i = AttributeProto.INT, int(value)
    elif isinstance(value, (int, np.integer)):
        a.type, a.i = AttributeProto.INT, int(value)
    elif isinstance(value, (float, np.floating)):
        a.type, a.f = AttributeProto.FLOAT, float(value)
    elif isinstance(value, str):
        a.type, a.s = AttributeProto.STRING, value.encode("utf-8")
    elif isinstance(value, bytes):
        a.type, a.s = AttributeProto.STRING, value
    elif isinstance(value, TensorProto):
        a.type, a.t = AttributeProto.TENSOR, value
    elif isinstance(value, GraphProto):
        a.type, a.g = AttributeProto.GRAPH, value
    elif isinstance(value, (list, tuple)):
        if len(value) == 0 or isinstance(value[0], (int, np.integer)):
            a.type = AttributeProto.INTS
            a.ints = [int(v) for v in value]
        elif isinstance(value[0], (float, np.floating)):
            a.type = AttributeProto.FLOATS
            a.floats = [float(v) for v in value]
        elif isinstance(value[0], str):
            a.type = AttributeProto.STRINGS
            a.strings = [v.encode("utf-8") for v in value]
        elif isinstance(value[0], TensorProto):
            a.type = AttributeProto.TENSORS
            a.tensors = list(value)
        else:
            raise TypeError(f"bad attribute list element {type(value[0])}")
    else:
        raise TypeError(f"bad attribute value {type(value)}")
    return a


def attribute_value(a: AttributeProto) -> Any:
    t = a.type or 0
    if t == AttributeProto.FLOAT:
        return float(a.f if a.f is not None else 0.0)
    if t == AttributeProto.INT:
        return int(a.i if a.i is not None else 0)
    if t == AttributeProto.STRING:
        return (a.s or b"").decode("utf-8", "replace")
    if t == AttributeProto.TENSOR:
        return to_array(a.t)
    if t == AttributeProto.GRAPH:
        return a.g
    if t == AttributeProto.FLOATS:
        return [float(v) for v in a.floats]
    if t == AttributeProto.INTS:
        return [int(v) for v in a.ints]
    if t == AttributeProto.STRINGS:
        return [v.decode("utf-8", "replace") for v in a.strings]
    if t == AttributeProto.TENSORS:
        return [to_array(v) for v in a.tensors]
    raise ValueError(f"unsupported attribute type {t}")


def make_node(op_type: str, inputs: Sequence[str], outputs: Sequence[str],
              name: Optional[str] = None, domain: str = "",
              **attrs) -> NodeProto:
    n = NodeProto(op_type=op_type)
    n.input = list(inputs)
    n.output = list(outputs)
    if name:
        n.name = name
    if domain:
        n.domain = domain
    n.attribute = [make_attribute(k, v) for k, v in sorted(attrs.items())
                   if v is not None]
    return n


def make_tensor_value_info(name: str, elem_type: int,
                           shape: Optional[Sequence] = None) -> ValueInfoProto:
    vi = ValueInfoProto(name=name)
    tt = TypeProto.Tensor(elem_type=elem_type)
    if shape is not None:
        sp = TensorShapeProto()
        for d in shape:
            dim = TensorShapeProto.Dimension()
            if isinstance(d, str):
                dim.dim_param = d
            elif d is not None:
                dim.dim_value = int(d)
            sp.dim.append(dim)
        tt.shape = sp
    vi.type = TypeProto(tensor_type=tt)
    return vi


def make_tensor(name: str, data_type: int, dims: Sequence[int],
                vals) -> TensorProto:
    np_dt = _TP2NP[data_type]
    return from_array(np.asarray(vals, dtype=np_dt).reshape(tuple(dims)), name)


def make_graph(nodes: Sequence[NodeProto], name: str,
               inputs: Sequence[ValueInfoProto],
               outputs: Sequence[ValueInfoProto],
               initializer: Optional[Sequence[TensorProto]] = None,
               value_info: Optional[Sequence[ValueInfoProto]] = None) -> GraphProto:
    g = GraphProto(name=name)
    g.node = list(nodes)
    g.input = list(inputs)
    g.output = list(outputs)
    g.initializer = list(initializer or [])
    g.value_info = list(value_info or [])
    return g


def make_model(graph: GraphProto, opset_version: int = 18,
               producer_name: str = "singa_tpu",
               ir_version: int = 8) -> ModelProto:
    m = ModelProto(ir_version=ir_version, producer_name=producer_name)
    m.graph = graph
    m.opset_import = [OperatorSetIdProto(domain="", version=opset_version)]
    return m


def load_model_from_string(data: bytes) -> ModelProto:
    return ModelProto.FromString(data)


def load(path: str) -> ModelProto:
    with open(path, "rb") as f:
        return ModelProto.FromString(f.read())


def save(model: ModelProto, path: str) -> None:
    with open(path, "wb") as f:
        f.write(model.SerializeToString())
