"""singa_tpu.sonnx — ONNX interchange (reference `sonnx`, BASELINE.json:5,9).

Frozen API parity surface:
    sonnx.prepare(onnx_model, device)  -> backend rep; rep.run(inputs)
    sonnx.to_onnx(model, inputs)       -> ModelProto export
    sonnx.load / sonnx.save            -> file IO
plus the `onnx`-compatible proto/helper layer in `sonnx.proto` (this
image has no onnx wheel; the codec is self-contained — see proto.py).

TPU-first: an imported graph is a `model.Model`, so `compile()` captures
it into one XLA module; float initializers are trainable, making the
import training-capable (BERT-base / GPT-2 fine-tuning, BASELINE.json:9).
"""

from . import proto
from .checker import CheckError, check_graph, check_model
from .backend import SingaBackend, SingaRep, prepare, supported_ops
from .export import export, to_onnx
from .proto import (AttributeProto, GraphProto, ModelProto, NodeProto,
                    TensorProto, from_array, load, load_model_from_string,
                    make_graph, make_model, make_node, make_tensor,
                    make_tensor_value_info, save, to_array)

__all__ = [
    "prepare", "SingaBackend", "SingaRep", "supported_ops",
    "check_model", "check_graph", "CheckError",
    "to_onnx", "export", "load", "save", "load_model_from_string",
    "proto", "ModelProto", "GraphProto", "NodeProto", "TensorProto",
    "AttributeProto", "make_node", "make_graph", "make_model",
    "make_tensor", "make_tensor_value_info", "to_array", "from_array",
]
