"""sonnx export — singa_tpu model → ONNX ModelProto.

Capability parity: the reference's `sonnx.to_onnx` export path
(BASELINE.json:5 "the sonnx ONNX importer" — import+export is the
interchange surface; SURVEY.md §5 checkpoint/interchange).  Mechanism:
run one forward pass with every `autograd.Operator.__call__` recorded
(a real tape with output identity, so multi-output ops export
correctly), then map each recorded op to ONNX node(s).

Layout note: our conv/pool/batchnorm compute in NHWC (the TPU/MXU
layout); ONNX spec ops are NCHW, so export wraps them in Transpose
pairs and stores conv weights transposed HWIO→OIHW.  Reimporting with
`sonnx.prepare` cancels the transposes inside one XLA fusion.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import autograd
from ..tensor import Tensor
from . import proto
from .proto import TensorProto, make_model, make_node, make_tensor_value_info

__all__ = ["to_onnx", "export", "save"]


@contextlib.contextmanager
def _record_ops():
    """Temporarily wrap Operator.__call__ to log (op, inputs, outputs)."""
    orig = autograd.Operator.__call__
    tape: List[Tuple[Any, Tuple[Tensor, ...], Tuple[Tensor, ...]]] = []

    def wrapped(self, *inputs):
        out = orig(self, *inputs)
        outs = out if isinstance(out, tuple) else (out,)
        tape.append((self, inputs, outs))
        return out

    autograd.Operator.__call__ = wrapped
    try:
        yield tape
    finally:
        autograd.Operator.__call__ = orig


class _Exporter:
    def __init__(self):
        self.nodes: List[proto.NodeProto] = []
        self.initializers: List[TensorProto] = []
        self.names: Dict[int, str] = {}      # id(Tensor) -> graph name
        self._counter = 0
        self._used: set = set()

    # -- naming ---------------------------------------------------------------
    def fresh(self, hint: str = "t") -> str:
        self._counter += 1
        name = f"{hint}_{self._counter}"
        while name in self._used:
            self._counter += 1
            name = f"{hint}_{self._counter}"
        self._used.add(name)
        return name

    def name_of(self, t: Tensor) -> str:
        n = self.names.get(id(t))
        if n is None:
            # leaf never seen: a captured constant — emit as initializer
            n = self.fresh("const")
            self.names[id(t)] = n
            self.initializers.append(proto.from_array(np.asarray(t.data), n))
        return n

    def bind(self, t: Tensor, name: str) -> None:
        self.names[id(t)] = name
        self._used.add(name)

    def add_init(self, arr: np.ndarray, hint: str) -> str:
        n = self.fresh(hint)
        self.initializers.append(proto.from_array(np.asarray(arr), n))
        return n

    def emit(self, op_type: str, ins: Sequence[str], outs: Sequence[str],
             **attrs) -> None:
        self.nodes.append(make_node(op_type, ins, outs,
                                    name=self.fresh(op_type.lower()), **attrs))


# ---------------------------------------------------------------------------
# per-op export rules: fn(ex, op, in_names, out_tensors) -> None
# (out_tensors already have names bound via ex.names)
# ---------------------------------------------------------------------------

_EXPORT: Dict[type, Callable] = {}


def _exports(*op_classes):
    def deco(fn):
        for c in op_classes:
            _EXPORT[c] = fn
        return fn
    return deco


def _outn(ex, outs):
    return [ex.names[id(o)] for o in outs]


_SIMPLE = {
    autograd.Add: "Add", autograd.Sub: "Sub", autograd.Mul: "Mul",
    autograd.Div: "Div", autograd.Pow: "Pow", autograd.Neg: "Neg",
    autograd.Abs: "Abs", autograd.Exp: "Exp", autograd.Log: "Log",
    autograd.Sqrt: "Sqrt", autograd.Erf: "Erf", autograd.Matmul: "MatMul",
    autograd.ReLU: "Relu", autograd.Sigmoid: "Sigmoid",
    autograd.Tanh: "Tanh", autograd.Softplus: "Softplus",
    # breadth ops (r3): 1:1 ONNX node types
    autograd.Sin: "Sin", autograd.Cos: "Cos", autograd.Tan: "Tan",
    autograd.Asin: "Asin", autograd.Acos: "Acos", autograd.Atan: "Atan",
    autograd.Sinh: "Sinh", autograd.Cosh: "Cosh",
    autograd.Asinh: "Asinh", autograd.Acosh: "Acosh",
    autograd.Atanh: "Atanh", autograd.Ceil: "Ceil",
    autograd.Floor: "Floor", autograd.Round: "Round",
    autograd.Sign: "Sign", autograd.Reciprocal: "Reciprocal",
    autograd.Minimum: "Min", autograd.Maximum: "Max",
    autograd.Equal: "Equal", autograd.Greater: "Greater",
    autograd.GreaterEqual: "GreaterOrEqual", autograd.Less: "Less",
    autograd.LessEqual: "LessOrEqual", autograd.LogicalAnd: "And",
    autograd.LogicalOr: "Or", autograd.LogicalXor: "Xor",
    autograd.LogicalNot: "Not", autograd.SELU: "Selu",
    autograd.PReLU: "PRelu", autograd.Mish: "Mish",
    autograd.HardSwish: "HardSwish",
}


@_exports(*_SIMPLE)
def _e_simple(ex, op, ins, outs):
    ex.emit(_SIMPLE[type(op)], ins, _outn(ex, outs))


@_exports(autograd.Gelu)
def _e_gelu(ex, op, ins, outs):
    approx = "tanh" if getattr(op, "approximate", True) else "none"
    ex.emit("Gelu", ins, _outn(ex, outs), approximate=approx)


@_exports(autograd.Mod)
def _e_mod(ex, op, ins, outs):
    dt = np.dtype(outs[0].dtype)
    if np.issubdtype(dt, np.integer):
        # ONNX integer Mod (fmod=0) is floor-mod: matches jnp.mod
        ex.emit("Mod", ins, _outn(ex, outs), fmod=0)
        return
    # float: ONNX Mod only offers C-fmod (sign of dividend), but the
    # native op is floor-mod (jnp.mod, sign of divisor) — decompose
    # a - floor(a/b)*b, which is dtype-agnostic and sign-correct
    a, b = ins
    q = ex.fresh("mod_div")
    ex.emit("Div", [a, b], [q])
    fl = ex.fresh("mod_floor")
    ex.emit("Floor", [q], [fl])
    prod = ex.fresh("mod_prod")
    ex.emit("Mul", [fl, b], [prod])
    ex.emit("Sub", [a, prod], _outn(ex, outs))


@_exports(autograd.HardSigmoid)
def _e_hardsigmoid(ex, op, ins, outs):
    ex.emit("HardSigmoid", ins, _outn(ex, outs),
            alpha=float(op.alpha), beta=float(op.beta))


@_exports(autograd.Tile)
def _e_tile(ex, op, ins, outs):
    # ONNX Tile requires len(repeats) == input rank. jnp.tile left-pads
    # short reps with 1s (match that); long reps promote the input's
    # rank, which ONNX Tile can't express without a reshape.
    x_rank = len(ex.cur_in_tensors[0].shape)
    reps = list(op.reps)
    if len(reps) > x_rank:
        raise ValueError(
            "sonnx export: Tile with more reps than input rank has no "
            "ONNX equivalent; reshape the input first")
    reps = [1] * (x_rank - len(reps)) + reps
    r = ex.add_init(np.asarray(reps, np.int64), "repeats")
    ex.emit("Tile", [ins[0], r], _outn(ex, outs))


@_exports(autograd.Expand)
def _e_expand(ex, op, ins, outs):
    shp = ex.add_init(np.asarray(op.shape, np.int64), "shape")
    ex.emit("Expand", [ins[0], shp], _outn(ex, outs))


@_exports(autograd.CumSum)
def _e_cumsum(ex, op, ins, outs):
    ax = ex.add_init(np.asarray(op.axis, np.int64), "axis")
    ex.emit("CumSum", [ins[0], ax], _outn(ex, outs))


@_exports(autograd.SiLU)
def _e_silu(ex, op, ins, outs):
    mid = ex.fresh("sig")
    ex.emit("Sigmoid", ins, [mid])
    ex.emit("Mul", [ins[0], mid], _outn(ex, outs))


@_exports(autograd.Rsqrt)
def _e_rsqrt(ex, op, ins, outs):
    mid = ex.fresh("sqrt")
    ex.emit("Sqrt", ins, [mid])
    ex.emit("Reciprocal", [mid], _outn(ex, outs))


@_exports(autograd.LeakyReLU)
def _e_leaky(ex, op, ins, outs):
    ex.emit("LeakyRelu", ins, _outn(ex, outs), alpha=float(op.slope))


@_exports(autograd.Elu)
def _e_elu(ex, op, ins, outs):
    ex.emit("Elu", ins, _outn(ex, outs), alpha=float(op.alpha))


@_exports(autograd.Softmax)
def _e_softmax(ex, op, ins, outs):
    ex.emit("Softmax", ins, _outn(ex, outs), axis=int(op.axis))


@_exports(autograd.LogSoftmax)
def _e_logsoftmax(ex, op, ins, outs):
    ex.emit("LogSoftmax", ins, _outn(ex, outs), axis=int(op.axis))


@_exports(autograd.Cast)
def _e_cast(ex, op, ins, outs):
    to = proto.np_dtype_to_tensor_dtype(np.dtype(op.dtype))
    ex.emit("Cast", ins, _outn(ex, outs), to=to)


@_exports(autograd.Clip)
def _e_clip(ex, op, ins, outs):
    dt = np.dtype(outs[0].dtype)           # dtype only: no host copy
    lo = ex.add_init(np.asarray(op.lo, dt), "clip_min")
    hi = ex.add_init(np.asarray(op.hi, dt), "clip_max")
    ex.emit("Clip", [ins[0], lo, hi], _outn(ex, outs))


@_exports(autograd.Linear)
def _e_linear(ex, op, ins, outs):
    x_nd = len(op._x.shape)
    if x_nd == 2:
        if op.has_bias:
            ex.emit("Gemm", ins, _outn(ex, outs))
        else:
            ex.emit("MatMul", ins[:2], _outn(ex, outs))
        return
    mm = ex.fresh("mm") if op.has_bias else _outn(ex, outs)[0]
    ex.emit("MatMul", ins[:2], [mm])
    if op.has_bias:
        ex.emit("Add", [mm, ins[2]], _outn(ex, outs))


@_exports(autograd.AddBias)
def _e_addbias(ex, op, ins, outs):
    x_nd = len(outs[0].shape)
    shape = [1] * x_nd
    shape[op.axis] = -1
    sh = ex.add_init(np.asarray(shape, np.int64), "shape")
    mid = ex.fresh("b_rs")
    ex.emit("Reshape", [ins[1], sh], [mid])
    ex.emit("Add", [ins[0], mid], _outn(ex, outs))


@_exports(autograd.Einsum)
def _e_einsum(ex, op, ins, outs):
    ex.emit("Einsum", ins, _outn(ex, outs), equation=op.subscripts)


@_exports(autograd.Reshape, autograd.Flatten, autograd.Squeeze,
          autograd.Unsqueeze)
def _e_reshape(ex, op, ins, outs):
    # all four are bijective reshapes; output shape is static at export
    sh = ex.add_init(np.asarray(outs[0].shape, np.int64), "shape")
    ex.emit("Reshape", [ins[0], sh], _outn(ex, outs))


@_exports(autograd.Transpose)
def _e_transpose(ex, op, ins, outs):
    perm = op.axes
    if perm is None:
        perm = tuple(reversed(range(len(outs[0].shape))))
    ex.emit("Transpose", ins, _outn(ex, outs), perm=list(perm))


@_exports(autograd.Cat)
def _e_cat(ex, op, ins, outs):
    ex.emit("Concat", ins, _outn(ex, outs), axis=int(op.axis))


@_exports(autograd.Stack)
def _e_stack(ex, op, ins, outs):
    axis = int(op.axis)
    mids = []
    ax_init = ex.add_init(np.asarray([axis], np.int64), "axes")
    for i in ins:
        m = ex.fresh("unsq")
        ex.emit("Unsqueeze", [i, ax_init], [m])
        mids.append(m)
    ex.emit("Concat", mids, _outn(ex, outs), axis=axis)


@_exports(autograd.Split)
def _e_split(ex, op, ins, outs):
    axis = int(op.axis)
    if isinstance(op.parts, int):
        total = sum(o.shape[axis] for o in outs)
        parts = [total // op.parts] * op.parts
    else:
        parts = list(op.parts)
    sp = ex.add_init(np.asarray(parts, np.int64), "split")
    ex.emit("Split", [ins[0], sp], _outn(ex, outs), axis=axis)


@_exports(autograd.Gather)
def _e_gather(ex, op, ins, outs):
    idx = ex.add_init(np.asarray(op.indices, np.int64), "indices")
    ex.emit("Gather", [ins[0], idx], _outn(ex, outs), axis=int(op.axis))


@_exports(autograd.Embedding)
def _e_embedding(ex, op, ins, outs):
    ex.emit("Gather", [ins[0], ins[1]], _outn(ex, outs), axis=0)


@_exports(autograd.Index)
def _e_index(ex, op, ins, outs):
    idx = op.idx if isinstance(op.idx, tuple) else (op.idx,)
    if not all(isinstance(s, (slice, int)) for s in idx):
        raise NotImplementedError(
            "ONNX export of advanced (array) indexing is unsupported")
    in_shape = op._shape
    starts, ends, axes, steps = [], [], [], []
    squeeze_axes = []
    for a, s in enumerate(idx):
        if isinstance(s, int):
            starts.append(s)
            ends.append(s + 1 if s != -1 else np.iinfo(np.int64).max)
            axes.append(a)
            steps.append(1)
            squeeze_axes.append(a)
            continue
        if s == slice(None):
            continue
        step = 1 if s.step is None else s.step
        i64 = np.iinfo(np.int64)
        # open bounds flip sentinels under negative step (ONNX Slice spec)
        starts.append((i64.max if step < 0 else 0) if s.start is None else s.start)
        ends.append((i64.min if step < 0 else i64.max) if s.stop is None else s.stop)
        axes.append(a)
        steps.append(step)
    del in_shape
    outn = _outn(ex, outs)
    target = outn[0] if not squeeze_axes else ex.fresh("sliced")
    if starts:
        ex.emit("Slice",
                [ins[0],
                 ex.add_init(np.asarray(starts, np.int64), "starts"),
                 ex.add_init(np.asarray(ends, np.int64), "ends"),
                 ex.add_init(np.asarray(axes, np.int64), "axes"),
                 ex.add_init(np.asarray(steps, np.int64), "steps")],
                [target])
    else:
        ex.emit("Identity", [ins[0]], [target])
    if squeeze_axes:
        sq = ex.add_init(np.asarray(squeeze_axes, np.int64), "axes")
        ex.emit("Squeeze", [target, sq], outn)


@_exports(autograd.Pad)
def _e_pad(ex, op, ins, outs):
    pw = op.pad_width
    pads = [p[0] for p in pw] + [p[1] for p in pw]
    pn = ex.add_init(np.asarray(pads, np.int64), "pads")
    dt = np.asarray(outs[0].data).dtype
    cv = ex.add_init(np.asarray(op.value, dt), "pad_value")
    ex.emit("Pad", [ins[0], pn, cv], _outn(ex, outs))


@_exports(autograd.Where)
def _e_where(ex, op, ins, outs):
    import warnings
    warnings.warn(
        "sonnx export: the Where condition evaluated at trace time is "
        "frozen into the graph as a constant; input-dependent masks will "
        "not vary in the exported model.", stacklevel=2)
    cond = ex.add_init(np.asarray(op.cond, np.bool_), "cond")
    ex.emit("Where", [cond, ins[0], ins[1]], _outn(ex, outs))


@_exports(autograd.Dropout)
def _e_dropout(ex, op, ins, outs):
    ex.emit("Identity", ins, _outn(ex, outs))  # export = inference graph


def _reduce_common(ex, op, ins, outs, op_type):
    axes = op.axis
    outn = _outn(ex, outs)
    inputs = [ins[0]]
    if axes is not None:
        ax = [axes] if isinstance(axes, int) else list(axes)
        inputs.append(ex.add_init(np.asarray(ax, np.int64), "axes"))
    ex.emit(op_type, inputs, outn, keepdims=int(bool(op.keepdims)))


@_exports(autograd.ReduceSum)
def _e_rsum(ex, op, ins, outs):
    _reduce_common(ex, op, ins, outs, "ReduceSum")


@_exports(autograd.ReduceMean)
def _e_rmean(ex, op, ins, outs):
    _reduce_common(ex, op, ins, outs, "ReduceMean")


@_exports(autograd.ReduceMax)
def _e_rmax(ex, op, ins, outs):
    _reduce_common(ex, op, ins, outs, "ReduceMax")


@_exports(autograd.ReduceMin)
def _e_rmin(ex, op, ins, outs):
    _reduce_common(ex, op, ins, outs, "ReduceMin")


@_exports(autograd.LayerNorm)
def _e_layernorm(ex, op, ins, outs):
    ex.emit("LayerNormalization", ins, _outn(ex, outs),
            axis=-1, epsilon=float(op.eps))


@_exports(autograd.RMSNorm)
def _e_rmsnorm(ex, op, ins, outs):
    # decompose: y = x * rsqrt(mean(x^2) + eps) * gamma  (portable ONNX)
    x, gamma = ins
    sq = ex.fresh("sq")
    ex.emit("Mul", [x, x], [sq])
    mean = ex.fresh("ms")
    ax = ex.add_init(np.asarray([-1], np.int64), "axes")
    ex.emit("ReduceMean", [sq, ax], [mean], keepdims=1)
    dt = np.asarray(outs[0].data).dtype
    epsn = ex.add_init(np.asarray(op.eps, np.float32 if dt == np.float32 else dt), "eps")
    shifted = ex.fresh("ms_eps")
    ex.emit("Add", [mean, epsn], [shifted])
    rt = ex.fresh("sqrt")
    ex.emit("Sqrt", [shifted], [rt])
    normed = ex.fresh("normed")
    ex.emit("Div", [x, rt], [normed])
    ex.emit("Mul", [normed, gamma], _outn(ex, outs))


def _nhwc_in(ex, name):
    out = ex.fresh("nchw")
    ex.emit("Transpose", [name], [out], perm=[0, 3, 1, 2])
    return out


def _nhwc_out(ex, nchw_name, final_name):
    ex.emit("Transpose", [nchw_name], [final_name], perm=[0, 2, 3, 1])


@_exports(autograd.Conv2d)
def _e_conv(ex, op, ins, outs):
    if isinstance(op.padding, str):
        pads = None
        auto_pad = "SAME_UPPER" if op.padding == "SAME" else "VALID"
    else:
        (pt, pb), (pl, pr) = op.padding
        pads = [pt, pl, pb, pr]
        auto_pad = None
    # weight initializer was stored HWIO (our layout) — re-emit as OIHW
    x_nchw = _nhwc_in(ex, ins[0])
    w_t = ex.fresh("w_oihw")
    ex.emit("Transpose", [ins[1]], [w_t], perm=[3, 2, 0, 1])
    conv_in = [x_nchw, w_t]
    y_nchw = ex.fresh("conv_out")
    attrs = dict(strides=list(op.stride), dilations=list(op.dilation),
                 group=int(op.groups))
    if pads is not None:
        attrs["pads"] = pads
    else:
        attrs["auto_pad"] = auto_pad
    ex.emit("Conv", conv_in, [y_nchw], **attrs)
    if len(ins) > 2:  # bias was added inside our fused conv
        y_b = ex.fresh("conv_bias")
        shp = ex.add_init(np.asarray([1, -1, 1, 1], np.int64), "shape")
        b_r = ex.fresh("b_r")
        ex.emit("Reshape", [ins[2], shp], [b_r])
        ex.emit("Add", [y_nchw, b_r], [y_b])
        y_nchw = y_b
    _nhwc_out(ex, y_nchw, _outn(ex, outs)[0])


@_exports(autograd.MaxPool2d, autograd.AvgPool2d)
def _e_pool(ex, op, ins, outs):
    is_max = isinstance(op, autograd.MaxPool2d)
    p = int(op.padding)
    x_nchw = _nhwc_in(ex, ins[0])
    y_nchw = ex.fresh("pool_out")
    attrs = dict(kernel_shape=list(op.kernel), strides=list(op.stride),
                 pads=[p, p, p, p])
    if not is_max:
        # our AvgPool2d always divides by the full kernel area
        attrs["count_include_pad"] = 1
    ex.emit("MaxPool" if is_max else "AveragePool", [x_nchw], [y_nchw],
            **attrs)
    _nhwc_out(ex, y_nchw, _outn(ex, outs)[0])


@_exports(autograd.BatchNorm)
def _e_batchnorm(ex, op, ins, outs):
    # ins: x (NHWC), gamma, beta, mean, var
    x_nchw = _nhwc_in(ex, ins[0])
    y_nchw = ex.fresh("bn_out")
    ex.emit("BatchNormalization",
            [x_nchw, ins[1], ins[2], ins[3], ins[4]], [y_nchw],
            epsilon=float(op.eps))
    _nhwc_out(ex, y_nchw, _outn(ex, outs)[0])


def _register_scan_rnn_rule():
    """layer.RNN / layer.LSTM (generic _ScanRNNOp) → real ONNX RNN/LSTM
    nodes.  Layout conversion happens in-graph (Transpose/Split/Concat
    of the weight initializers — runtimes constant-fold them):
      ours: x (B,T,D); Wx (D,G*H); Wh (H,G*H); b (G*H,), LSTM gate
      order i,f,g,o.  ONNX: X (T,B,D); W (1,G*H,D); R (1,G*H,H);
      B (1,2*G*H) with zero recurrence bias; LSTM gate order i,o,f,c."""
    from ..layer import _ScanRNNOp

    @_exports(_ScanRNNOp)
    def _e_scan_rnn(ex, op, ins, outs):
        kind, H = op.kind, op.hidden
        if kind not in ("RNN", "LSTM"):
            raise ValueError(
                f"cannot export generic _ScanRNNOp (kind={kind!r}); "
                "only layer.RNN / layer.LSTM cells map onto ONNX nodes")
        G = 4 if kind == "LSTM" else 1
        ax0 = ex.add_init(np.asarray([0], np.int64), "ax0")
        # explicit split sizes: valid in opset 13 through 18+ (a bare
        # 4-output Split without them is rejected at opset 18); only
        # LSTM reorders gates, so only it emits the initializer
        gate_splits = (ex.add_init(np.full((4,), H, np.int64), "gsplit")
                       if kind == "LSTM" else None)

        def to_onnx_weight(name, hint):
            t = ex.fresh(hint)
            ex.emit("Transpose", [name], [t], perm=[1, 0])  # (G*H, in)
            if kind == "LSTM":
                parts = [ex.fresh(f"{hint}_g{i}") for i in range(4)]
                ex.emit("Split", [t, gate_splits], parts, axis=0)
                ro = ex.fresh(f"{hint}_iofc")
                # ours [i, f, g, o] -> ONNX [i, o, f, c(=g)]
                ex.emit("Concat", [parts[0], parts[3], parts[1],
                                   parts[2]], [ro], axis=0)
                t = ro
            u = ex.fresh(f"{hint}_d")
            ex.emit("Unsqueeze", [t, ax0], [u])             # (1, G*H, in)
            return u

        w = to_onnx_weight(ins[1], "rnn_w")
        r = to_onnx_weight(ins[2], "rnn_r")
        lstm_ins = [None, w, r]
        if len(ins) > 3:
            b = ex.fresh("rnn_b")
            if kind == "LSTM":
                parts = [ex.fresh(f"rnn_b_g{i}") for i in range(4)]
                ex.emit("Split", [ins[3], gate_splits], parts, axis=0)
                ro = ex.fresh("rnn_b_iofc")
                ex.emit("Concat", [parts[0], parts[3], parts[1],
                                   parts[2]], [ro], axis=0)
                src = ro
            else:
                src = ins[3]
            # recurrence-bias zeros in the traced activation dtype
            # (bf16/f16 models would otherwise emit a mixed-type Concat)
            zeros = ex.add_init(
                np.zeros((G * H,), np.dtype(outs[0].dtype)), "rb0")
            ex.emit("Concat", [src, zeros], [b], axis=0)    # (2*G*H,)
            bu = ex.fresh("rnn_b_d")
            ex.emit("Unsqueeze", [b, ax0], [bu])            # (1, 2*G*H)
            lstm_ins.append(bu)

        xt = ex.fresh("x_tbd")
        ex.emit("Transpose", [ins[0]], [xt], perm=[1, 0, 2])
        lstm_ins[0] = xt
        y = ex.fresh("rnn_y")                               # (T, 1, B, H)
        ex.emit(kind, lstm_ins, [y], hidden_size=int(H))
        sq = ex.fresh("rnn_y_sq")
        ax1 = ex.add_init(np.asarray([1], np.int64), "ax1")
        ex.emit("Squeeze", [y, ax1], [sq])                  # (T, B, H)
        ex.emit("Transpose", [sq], _outn(ex, outs), perm=[1, 0, 2])


_register_scan_rnn_rule()


def _register_sdpa_rule():
    """Fused attention (singa_tpu.ops.attention.SDPA) → portable ONNX:
    head-transposed MatMul / Mul(scale) / Where(mask) / Softmax / MatMul.
    GQA (kv heads K < H) is expressed by tiling kv heads to H via
    Unsqueeze+Expand+Reshape, which ONNX runtimes fold."""
    from ..ops.attention import SDPA

    @_exports(SDPA)
    def _e_sdpa(ex, op, ins, outs):
        import math
        q_t, k_t, v_t = ex.cur_in_tensors
        B, Tq, H, D = q_t.shape
        Tk, K = k_t.shape[1], k_t.shape[2]
        scale = op.scale or (1.0 / math.sqrt(D))
        kn, vn = ins[1], ins[2]
        if K != H:  # tile kv heads up to H
            for src, tag in ((kn, "k"), (vn, "v")):
                u = ex.fresh(f"{tag}_unsq")
                ex.emit("Unsqueeze",
                        [src, ex.add_init(np.asarray([3], np.int64), "axes")],
                        [u])
                e = ex.fresh(f"{tag}_exp")
                ex.emit("Expand",
                        [u, ex.add_init(
                            np.asarray([B, Tk, K, H // K, D], np.int64),
                            "shape")], [e])
                r = ex.fresh(f"{tag}_rep")
                ex.emit("Reshape",
                        [e, ex.add_init(np.asarray([B, Tk, H, D], np.int64),
                                        "shape")], [r])
                if tag == "k":
                    kn = r
                else:
                    vn = r
        qh = ex.fresh("qh")
        ex.emit("Transpose", [ins[0]], [qh], perm=[0, 2, 1, 3])  # B,H,Tq,D
        kT = ex.fresh("kT")
        ex.emit("Transpose", [kn], [kT], perm=[0, 2, 3, 1])      # B,H,D,Tk
        raw = ex.fresh("scores_raw")
        ex.emit("MatMul", [qh, kT], [raw])
        # constants in the traced activation dtype (same pattern as
        # _e_clip) so bf16/f16 exports don't emit type-mismatched nodes
        act_dt = np.dtype(outs[0].dtype)   # dtype only: no host copy
        try:
            neg_val = np.finfo(act_dt).min
        except ValueError:            # np.finfo can't read ml_dtypes bf16
            import ml_dtypes
            neg_val = ml_dtypes.finfo(act_dt).min
        scores = ex.fresh("scores")
        ex.emit("Mul", [raw, ex.add_init(np.asarray(scale, act_dt),
                                         "scale")], [scores])
        neg = ex.add_init(np.asarray(neg_val, act_dt), "neg_inf")
        if op.causal:
            cm = np.tril(np.ones((Tq, Tk), np.bool_), k=Tk - Tq)
            cmn = ex.add_init(cm, "causal_mask")
            masked = ex.fresh("masked")
            ex.emit("Where", [cmn, scores, neg], [masked])
            scores = masked
        if op.mask is not None:
            import warnings
            warnings.warn(
                "sonnx export: the attention mask passed at trace time is "
                "frozen into the exported graph as a constant (trace-time "
                "constant folding). Export without attention_mask if the "
                "mask varies per batch.", stacklevel=2)
            mn = ex.add_init(np.asarray(op.mask, np.bool_), "attn_mask")
            masked = ex.fresh("masked")
            ex.emit("Where", [mn, scores, neg], [masked])
            scores = masked
        probs = ex.fresh("probs")
        ex.emit("Softmax", [scores], [probs], axis=-1)
        vh = ex.fresh("vh")
        ex.emit("Transpose", [vn], [vh], perm=[0, 2, 1, 3])      # B,H,Tk,D
        ctx = ex.fresh("ctx")
        ex.emit("MatMul", [probs, vh], [ctx])                    # B,H,Tq,D
        ex.emit("Transpose", [ctx], _outn(ex, outs), perm=[0, 2, 1, 3])


_register_sdpa_rule()


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def to_onnx(model, inputs: Sequence, name: Optional[str] = None,
            opset_version: int = 18) -> proto.ModelProto:
    """Trace `model(*inputs)` and build an ONNX ModelProto.

    `inputs` — example Tensors (shapes become the graph signature).
    The model runs in eval mode; params become initializers."""
    from ..device import get_default_device

    was_training = autograd.is_training()
    autograd.set_training(False)
    try:
        ts = []
        dev = getattr(model, "device_", None) or get_default_device()
        for x in inputs:
            ts.append(x if isinstance(x, Tensor)
                      else Tensor(data=np.asarray(x), device=dev))
        with _record_ops() as tape:
            out = model(*ts) if len(ts) > 1 else model(ts[0])
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
    finally:
        autograd.set_training(was_training)

    ex = _Exporter()
    # bind params first so they keep their model names
    graph_inputs = []
    for i, t in enumerate(ts):
        in_name = f"input_{i}"
        ex.bind(t, in_name)
        graph_inputs.append(make_tensor_value_info(
            in_name, proto.np_dtype_to_tensor_dtype(np.asarray(t.data).dtype),
            list(t.shape)))
    param_map = {}
    if hasattr(model, "get_params"):
        for pname, p in model.get_params().items():
            if id(p) not in ex.names:
                ex.bind(p, pname)
                param_map[pname] = p
                ex.initializers.append(
                    proto.from_array(np.asarray(p.data), pname))
    if hasattr(model, "_get_buffers"):
        for sname, s in model._get_buffers().items():
            if id(s) not in ex.names:
                ex.bind(s, sname)
                ex.initializers.append(
                    proto.from_array(np.asarray(s.data), sname))

    # name every tape output, then emit in recorded (topological) order
    needed = _live_ops(tape, outs)
    for op, op_ins, op_outs in needed:
        for o in op_outs:
            if id(o) not in ex.names:
                ex.bind(o, ex.fresh("t"))
    for op, op_ins, op_outs in needed:
        rule = _EXPORT.get(type(op))
        if rule is None:
            raise NotImplementedError(
                f"no ONNX export rule for autograd.{type(op).__name__}")
        in_names = [ex.name_of(t) for t in op_ins]
        ex.cur_in_tensors = op_ins  # rules that need input shapes read this
        rule(ex, op, in_names, op_outs)

    graph_outputs = []
    for i, o in enumerate(outs):
        oname = ex.names.get(id(o))
        if oname is None:  # output is a direct input/param passthrough
            oname = ex.name_of(o)
        graph_outputs.append(make_tensor_value_info(
            oname, proto.np_dtype_to_tensor_dtype(np.asarray(o.data).dtype),
            list(o.shape)))

    g = proto.make_graph(ex.nodes, name or getattr(model, "name", "singa_model"),
                         graph_inputs, graph_outputs, ex.initializers)
    return make_model(g, opset_version=opset_version)


def _live_ops(tape, outs):
    """Keep only ops on a path to the requested outputs (dead-code prune:
    e.g. metric branches recorded during the trace)."""
    live = {id(o) for o in outs}
    keep = []
    for op, op_ins, op_outs in reversed(tape):
        if any(id(o) in live for o in op_outs):
            keep.append((op, op_ins, op_outs))
            for t in op_ins:
                live.add(id(t))
    return list(reversed(keep))


def export(model, inputs: Sequence, path: str, **kw) -> proto.ModelProto:
    m = to_onnx(model, inputs, **kw)
    proto.save(m, path)
    return m


save = proto.save
