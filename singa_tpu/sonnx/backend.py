"""sonnx import backend — ONNX graph → singa_tpu autograd execution.

Capability parity: the reference's `sonnx.prepare(onnx_model, device)`
returning a backend rep whose `.run(inputs)` replays the graph through
`singa.autograd` operators (BASELINE.json:9 — ONNX BERT-base / GPT-2
inference; SURVEY.md §3.4 import call stack).  TPU-first design: every
handler maps an ONNX node onto autograd Operators (differentiable, so
imported models are *training-capable*) or pure-jnp ops; a `SingaRep`
is a `model.Model`, so `compile()` captures the whole imported graph
into one XLA module exactly like a hand-written model.

Static-shape discipline (XLA): shape-computation chains
(Shape → Gather/Concat/... → Reshape/Expand/Slice) are *partially
evaluated on the host* — `Shape` yields a concrete numpy vector because
tensor shapes are static under jit, and any node whose inputs are all
host constants folds at import time.  Data-dependent shapes (NonZero
etc.) are rejected with a clear error rather than silently miscompiled.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from .. import autograd
from .. import model as model_mod
from ..device import get_default_device
from ..tensor import Tensor
from . import proto
from .proto import (AttributeProto, GraphProto, ModelProto, NodeProto,
                    TensorProto, attribute_value, to_array)

__all__ = ["prepare", "SingaBackend", "SingaRep", "supported_ops"]


# ---------------------------------------------------------------------------
# value lanes: host constants (numpy — shape math, folded at trace time)
# vs device tensors (autograd Tensors — the compute lane)
# ---------------------------------------------------------------------------

_HostVal = (np.ndarray, np.generic, int, float, bool)


def _is_host(v) -> bool:
    return isinstance(v, _HostVal)


def _host(v) -> np.ndarray:
    return np.asarray(v)


def _require_host(v, node: NodeProto, what: str) -> np.ndarray:
    if not _is_host(v):
        raise ValueError(
            f"ONNX node {node.op_type} ({node.name}): {what} must be a "
            f"compile-time constant — XLA requires static shapes; a "
            f"data-dependent value reached a shape position")
    return _host(v)


class _Ctx:
    def __init__(self, device, opset: int, training: bool,
                 consumed: Optional[set] = None):
        self.device = device
        self.opset = opset
        self.training = training
        # names read by downstream nodes / graph outputs — used to reject
        # requests for aux outputs we don't compute (norm stats etc.)
        self.consumed = consumed or set()

    def tensor(self, v, requires_grad=False) -> Tensor:
        if isinstance(v, Tensor):
            return v
        return Tensor(data=jnp.asarray(v), device=self.device,
                      requires_grad=requires_grad)


def _attrs(node: NodeProto) -> Dict[str, Any]:
    return {a.name: attribute_value(a) for a in node.attribute}


class _JnpOp(autograd.Operator):
    """Wrap a pure jnp function as an autograd Operator: backward comes
    free from jax.vjp, so imported graphs stay differentiable."""

    def __init__(self, fn: Callable):
        super().__init__()
        self.fn = fn

    def fwd(self, *arrays):
        return self.fn(*arrays)


def _apply(ctx: _Ctx, fn: Callable, *vals):
    """Run `fn` on mixed host/tensor values through the autograd tape."""
    ts = [ctx.tensor(v) for v in vals]
    return _JnpOp(fn)(*ts)


# ---------------------------------------------------------------------------
# handler registry
# ---------------------------------------------------------------------------

_HANDLERS: Dict[str, Callable] = {}


def handles(*op_types: str):
    def deco(fn):
        for t in op_types:
            _HANDLERS[t] = fn
        return fn
    return deco


def supported_ops() -> List[str]:
    return sorted(_HANDLERS)


# -- elementwise unary -------------------------------------------------------

_UNARY = {
    "Relu": (autograd.relu, lambda a: np.maximum(a, 0)),
    "Sigmoid": (autograd.sigmoid, lambda a: 1 / (1 + np.exp(-a))),
    "Tanh": (autograd.tanh, np.tanh),
    "Exp": (autograd.exp, np.exp),
    "Log": (autograd.log, np.log),
    "Sqrt": (autograd.sqrt, np.sqrt),
    "Abs": (autograd.abs, np.abs),
    "Neg": (autograd.neg, np.negative),
    "Erf": (autograd.erf, None),
    "Floor": (autograd.floor, np.floor),
    "Ceil": (autograd.ceil, np.ceil),
    "Round": (autograd.round, np.round),
    "Sign": (autograd.sign, np.sign),
    "Reciprocal": (autograd.reciprocal, lambda a: 1.0 / a),
    "Softplus": (autograd.softplus, None),
    "Not": (autograd.logical_not, np.logical_not),
    "Identity": (lambda t: t, lambda a: a),
    # trig/hyperbolic family: differentiable native operators (r3)
    "Sin": (autograd.sin, np.sin), "Cos": (autograd.cos, np.cos),
    "Tan": (autograd.tan, np.tan), "Asin": (autograd.asin, np.arcsin),
    "Acos": (autograd.acos, np.arccos), "Atan": (autograd.atan, np.arctan),
    "Sinh": (autograd.sinh, np.sinh), "Cosh": (autograd.cosh, np.cosh),
    "Asinh": (autograd.asinh, np.arcsinh),
    "Acosh": (autograd.acosh, np.arccosh),
    "Atanh": (autograd.atanh, np.arctanh),
    "HardSwish": (autograd.hardswish, None),
    "Mish": (autograd.mish, None),
}


@handles(*_UNARY)
def _h_unary(ctx, node, attrs, ins):
    t_fn, np_fn = _UNARY[node.op_type]
    (x,) = ins
    if _is_host(x) and np_fn is not None:
        return [np_fn(_host(x))]
    return [t_fn(ctx.tensor(x))]


# -- elementwise binary / variadic ------------------------------------------

def _onnx_div_jnp(a, b):
    """ONNX Div: C-style truncating division for integer operands
    (torch exports chunk/shape arithmetic as int64 Div; true division
    would leak floats into downstream Slice/Reshape bounds)."""
    a, b = jnp.asarray(a), jnp.asarray(b)
    if (jnp.issubdtype(a.dtype, jnp.integer)
            and jnp.issubdtype(b.dtype, jnp.integer)):
        return jnp.sign(a) * jnp.sign(b) * (jnp.abs(a) // jnp.abs(b))
    return jnp.divide(a, b)


def _onnx_div_np(a, b):
    a, b = np.asarray(a), np.asarray(b)
    if (np.issubdtype(a.dtype, np.integer)
            and np.issubdtype(b.dtype, np.integer)):
        return np.sign(a) * np.sign(b) * (np.abs(a) // np.abs(b))
    return np.divide(a, b)


_BINARY = {
    "Add": (jnp.add, np.add),
    "Sub": (jnp.subtract, np.subtract),
    "Mul": (jnp.multiply, np.multiply),
    "Div": (_onnx_div_jnp, _onnx_div_np),
    "Pow": (jnp.power, np.power),
    "Equal": (jnp.equal, np.equal),
    "Greater": (jnp.greater, np.greater),
    "GreaterOrEqual": (jnp.greater_equal, np.greater_equal),
    "Less": (jnp.less, np.less),
    "LessOrEqual": (jnp.less_equal, np.less_equal),
    "And": (jnp.logical_and, np.logical_and),
    "Or": (jnp.logical_or, np.logical_or),
    "Xor": (jnp.logical_xor, np.logical_xor),
}


@handles(*_BINARY)
def _h_binary(ctx, node, attrs, ins):
    j_fn, np_fn = _BINARY[node.op_type]
    a, b = ins
    if _is_host(a) and _is_host(b):
        return [np_fn(_host(a), _host(b))]
    return [_apply(ctx, j_fn, a, b)]


@handles("Mod")
def _h_mod(ctx, node, attrs, ins):
    # fmod=1 -> C fmod (sign of dividend); fmod=0 -> floor-mod
    fmod = bool(attrs.get("fmod", 0))
    j_fn = jnp.fmod if fmod else jnp.mod
    np_fn = np.fmod if fmod else np.mod
    a, b = ins
    if _is_host(a) and _is_host(b):
        return [np_fn(_host(a), _host(b))]
    return [_apply(ctx, j_fn, a, b)]


@handles("Min", "Max", "Sum", "Mean")
def _h_variadic(ctx, node, attrs, ins):
    j_fn = {"Min": jnp.minimum, "Max": jnp.maximum,
            "Sum": jnp.add, "Mean": jnp.add}[node.op_type]
    if all(_is_host(v) for v in ins):
        np_fn = {"Min": np.minimum, "Max": np.maximum,
                 "Sum": np.add, "Mean": np.add}[node.op_type]
        out = _host(ins[0])
        for v in ins[1:]:
            out = np_fn(out, _host(v))
        if node.op_type == "Mean":
            out = out / len(ins)
        return [out]
    out = ctx.tensor(ins[0])
    for v in ins[1:]:
        out = _apply(ctx, j_fn, out, v)
    if node.op_type == "Mean":
        out = _apply(ctx, lambda a: a / len(ins), out)
    return [out]


@handles("Clip")
def _h_clip(ctx, node, attrs, ins):
    x = ins[0]
    lo = attrs.get("min")
    hi = attrs.get("max")
    if len(ins) > 1 and ins[1] is not None:
        lo = float(_require_host(ins[1], node, "min"))
    if len(ins) > 2 and ins[2] is not None:
        hi = float(_require_host(ins[2], node, "max"))
    lo = -np.inf if lo is None else lo
    hi = np.inf if hi is None else hi
    if _is_host(x):
        return [np.clip(_host(x), lo, hi)]
    return [autograd.clip(ctx.tensor(x), lo, hi)]


@handles("LeakyRelu")
def _h_leaky(ctx, node, attrs, ins):
    return [autograd.leakyrelu(ctx.tensor(ins[0]), attrs.get("alpha", 0.01))]


@handles("Elu")
def _h_elu(ctx, node, attrs, ins):
    return [autograd.elu(ctx.tensor(ins[0]), attrs.get("alpha", 1.0))]


@handles("Selu")
def _h_selu(ctx, node, attrs, ins):
    alpha = attrs.get("alpha", 1.6732632)
    gamma = attrs.get("gamma", 1.050701)
    return [_apply(ctx, lambda a: gamma * jnp.where(a > 0, a, alpha * (jnp.exp(a) - 1)),
                   ins[0])]


@handles("HardSigmoid")
def _h_hardsigmoid(ctx, node, attrs, ins):
    alpha = attrs.get("alpha", 0.2)
    beta = attrs.get("beta", 0.5)
    return [_apply(ctx, lambda a: jnp.clip(alpha * a + beta, 0, 1), ins[0])]


@handles("Gelu")
def _h_gelu(ctx, node, attrs, ins):
    approx = attrs.get("approximate", "none")
    if isinstance(approx, bytes):
        approx = approx.decode()
    # ONNX default is the exact erf form; only approximate="tanh" maps
    # to the tanh approximation
    return [autograd.gelu(ctx.tensor(ins[0]), approximate=(approx == "tanh"))]


@handles("PRelu")
def _h_prelu(ctx, node, attrs, ins):
    return [_apply(ctx, lambda a, s: jnp.where(a > 0, a, s * a), ins[0], ins[1])]


def _softmax_like(ctx, node, attrs, ins, fn):
    x = ctx.tensor(ins[0])
    if ctx.opset >= 13:
        return [fn(x, attrs.get("axis", -1))]
    # opset 1-12: coerce to 2-D — flatten dims [axis:] and normalize over
    # the whole flattened block jointly
    axis = attrs.get("axis", 1)
    shape = x.shape
    nd = len(shape)
    axis = axis % nd
    lead = int(np.prod(shape[:axis])) if axis > 0 else 1
    flat = autograd.reshape(x, (lead, -1))
    return [autograd.reshape(fn(flat, -1), shape)]


@handles("Softmax")
def _h_softmax(ctx, node, attrs, ins):
    return _softmax_like(ctx, node, attrs, ins, autograd.softmax)


@handles("LogSoftmax")
def _h_logsoftmax(ctx, node, attrs, ins):
    return _softmax_like(ctx, node, attrs, ins, autograd.log_softmax)


# -- matmul family -----------------------------------------------------------

@handles("MatMul")
def _h_matmul(ctx, node, attrs, ins):
    return [autograd.matmul(ctx.tensor(ins[0]), ctx.tensor(ins[1]))]


@handles("Gemm")
def _h_gemm(ctx, node, attrs, ins):
    alpha = attrs.get("alpha", 1.0)
    beta = attrs.get("beta", 1.0)
    ta, tb = attrs.get("transA", 0), attrs.get("transB", 0)

    def gemm(a, b, *c):
        a2 = a.T if ta else a
        b2 = b.T if tb else b
        y = alpha * jnp.matmul(a2, b2)
        if c:
            y = y + beta * c[0]
        return y

    return [_apply(ctx, gemm, *[v for v in ins if v is not None])]


@handles("Einsum")
def _h_einsum(ctx, node, attrs, ins):
    return [autograd.einsum(attrs["equation"], *[ctx.tensor(v) for v in ins])]


# -- shape lane --------------------------------------------------------------

@handles("Shape")
def _h_shape(ctx, node, attrs, ins):
    shape = np.asarray(_shape_of(ins[0]), np.int64)
    start = attrs.get("start", 0)
    end = attrs.get("end")
    return [shape[start:end]]


def _shape_of(v):
    return tuple(_host(v).shape) if _is_host(v) else tuple(v.shape)


@handles("Size")
def _h_size(ctx, node, attrs, ins):
    return [np.asarray(int(np.prod(_shape_of(ins[0]))), np.int64)]


@handles("Constant")
def _h_constant(ctx, node, attrs, ins):
    if "value" in attrs:
        return [attrs["value"]]
    for k in ("value_float", "value_int"):
        if k in attrs:
            return [np.asarray(attrs[k])]
    for k in ("value_floats", "value_ints"):
        if k in attrs:
            return [np.asarray(attrs[k])]
    raise ValueError("Constant node without a value attribute")


@handles("ConstantOfShape")
def _h_constant_of_shape(ctx, node, attrs, ins):
    shape = tuple(int(d) for d in _require_host(ins[0], node, "shape").reshape(-1))
    val = attrs.get("value")
    if val is None:
        val = np.zeros((1,), np.float32)
    return [np.full(shape, np.asarray(val).reshape(-1)[0])]


@handles("Cast")
def _h_cast(ctx, node, attrs, ins):
    dt = proto.tensor_dtype_to_np_dtype(int(attrs["to"]))
    (x,) = ins
    if _is_host(x):
        return [_host(x).astype(dt)]
    return [autograd.cast(ctx.tensor(x), jnp.dtype(dt))]


@handles("CastLike")
def _h_castlike(ctx, node, attrs, ins):
    x, like = ins
    dt = _host(like).dtype if _is_host(like) else like.dtype
    if _is_host(x):
        return [_host(x).astype(dt)]
    return [autograd.cast(ctx.tensor(x), dt)]


@handles("Reshape")
def _h_reshape(ctx, node, attrs, ins):
    x = ins[0]
    target = [int(d) for d in _require_host(ins[1], node, "shape").reshape(-1)]
    allowzero = attrs.get("allowzero", 0)
    cur = _shape_of(x)
    shape = []
    for i, d in enumerate(target):
        if d == 0 and not allowzero:
            shape.append(cur[i])
        else:
            shape.append(d)
    if _is_host(x):
        return [_host(x).reshape(shape)]
    return [autograd.reshape(ctx.tensor(x), shape)]


@handles("Transpose")
def _h_transpose(ctx, node, attrs, ins):
    perm = attrs.get("perm")
    (x,) = ins
    if _is_host(x):
        return [np.transpose(_host(x), perm)]
    return [autograd.transpose(ctx.tensor(x), perm)]


@handles("Flatten")
def _h_flatten(ctx, node, attrs, ins):
    axis = attrs.get("axis", 1)
    shape = _shape_of(ins[0])
    if axis < 0:
        axis += len(shape)
    lead = int(np.prod(shape[:axis])) if axis > 0 else 1
    return [autograd.reshape(ctx.tensor(ins[0]), (lead, -1))]


def _axes_arg(node, attrs, ins, idx, opset) -> Optional[List[int]]:
    """axes moved from attribute to input at opset 13 — accept both."""
    if len(ins) > idx and ins[idx] is not None:
        return [int(a) for a in _require_host(ins[idx], node, "axes").reshape(-1)]
    if "axes" in attrs:
        return [int(a) for a in attrs["axes"]]
    return None


@handles("Squeeze")
def _h_squeeze(ctx, node, attrs, ins):
    axes = _axes_arg(node, attrs, ins, 1, ctx.opset)
    x = ins[0]
    if _is_host(x):
        return [np.squeeze(_host(x), tuple(axes) if axes else None)]
    ax = tuple(axes) if axes else None
    return [autograd.squeeze(ctx.tensor(x), ax)]


@handles("Unsqueeze")
def _h_unsqueeze(ctx, node, attrs, ins):
    axes = _axes_arg(node, attrs, ins, 1, ctx.opset)
    x = ins[0]
    if _is_host(x):
        out = _host(x)
        ndim_out = out.ndim + len(axes)
        for a in sorted(a % ndim_out for a in axes):
            out = np.expand_dims(out, a)
        return [out]
    t = ctx.tensor(x)
    ndim_out = len(t.shape) + len(axes)
    return [autograd.unsqueeze(t, sorted(a % ndim_out for a in axes))]


@handles("Concat")
def _h_concat(ctx, node, attrs, ins):
    axis = attrs["axis"]
    if all(_is_host(v) for v in ins):
        return [np.concatenate([_host(v) for v in ins], axis=axis)]
    return [autograd.cat([ctx.tensor(v) for v in ins], axis)]


@handles("Split")
def _h_split(ctx, node, attrs, ins):
    axis = attrs.get("axis", 0)
    parts = None
    if len(ins) > 1 and ins[1] is not None:
        parts = [int(v) for v in _require_host(ins[1], node, "split").reshape(-1)]
    elif "split" in attrs:
        parts = [int(v) for v in attrs["split"]]
    n_out = len(node.output)
    t = ctx.tensor(ins[0])
    if parts is None:
        size = t.shape[axis]
        num = attrs.get("num_outputs", n_out)
        base = -(-size // num)  # ceil-div; last chunk smaller (maybe 0)
        parts = [base] * (num - 1) + [size - base * (num - 1)]
        if parts[-1] < 0:
            raise ValueError(
                f"Split: axis size {size} cannot fill {num} outputs of "
                f"chunk {base}")
    outs = autograd.split(t, parts, axis)
    return list(outs)


@handles("Slice")
def _h_slice(ctx, node, attrs, ins):
    x = ins[0]
    nd = len(_shape_of(x))
    if ctx.opset >= 10 or len(ins) > 1:
        starts = _require_host(ins[1], node, "starts").reshape(-1)
        ends = _require_host(ins[2], node, "ends").reshape(-1)
        axes = (_require_host(ins[3], node, "axes").reshape(-1)
                if len(ins) > 3 and ins[3] is not None
                else np.arange(len(starts)))
        steps = (_require_host(ins[4], node, "steps").reshape(-1)
                 if len(ins) > 4 and ins[4] is not None
                 else np.ones(len(starts), np.int64))
    else:
        starts = np.asarray(attrs["starts"])
        ends = np.asarray(attrs["ends"])
        axes = np.asarray(attrs.get("axes", list(range(len(starts)))))
        steps = np.ones(len(starts), np.int64)
    slices = [slice(None)] * nd
    int_max = np.iinfo(np.int64).max
    for s, e, a, st in zip(starts, ends, axes, steps):
        s, e, st = int(s), int(e), int(st)
        a = int(a) % nd
        # INT64_MAX / INT64_MIN are ONNX's "to the end" sentinels
        s_ = None if s in (int_max, -int_max - 1) else s
        e_ = None if e in (int_max, -int_max - 1) else e
        slices[a] = slice(s_, e_, st)
    slices = tuple(slices)
    if _is_host(x):
        return [_host(x)[slices]]
    return [autograd.index(ctx.tensor(x), slices)]


@handles("Gather")
def _h_gather(ctx, node, attrs, ins):
    axis = attrs.get("axis", 0)
    data, idx = ins
    if _is_host(data) and _is_host(idx):
        return [np.take(_host(data), _host(idx).astype(np.int64), axis=axis)]
    iv = _host(idx).astype(np.int64) if _is_host(idx) else idx.data
    return [autograd.gather(ctx.tensor(data), axis, iv)]


@handles("GatherElements")
def _h_gather_elements(ctx, node, attrs, ins):
    axis = attrs.get("axis", 0)
    idx = ins[1]
    iv = _host(idx).astype(np.int64) if _is_host(idx) else idx.data
    return [_apply(ctx, lambda d: jnp.take_along_axis(d, jnp.asarray(iv), axis=axis),
                   ins[0])]


@handles("Expand")
def _h_expand(ctx, node, attrs, ins):
    target = tuple(int(d) for d in _require_host(ins[1], node, "shape").reshape(-1))
    cur = _shape_of(ins[0])
    out_shape = np.broadcast_shapes(cur, target)
    if _is_host(ins[0]):
        return [np.broadcast_to(_host(ins[0]), out_shape)]
    return [_apply(ctx, lambda a: jnp.broadcast_to(a, out_shape), ins[0])]


@handles("Tile")
def _h_tile(ctx, node, attrs, ins):
    reps = tuple(int(d) for d in _require_host(ins[1], node, "repeats").reshape(-1))
    if _is_host(ins[0]):
        return [np.tile(_host(ins[0]), reps)]
    return [_apply(ctx, lambda a: jnp.tile(a, reps), ins[0])]


@handles("Range")
def _h_range(ctx, node, attrs, ins):
    s, l, d = (_require_host(v, node, "range arg") for v in ins)
    return [np.arange(s.item(), l.item(), d.item())]


@handles("Where")
def _h_where(ctx, node, attrs, ins):
    cond, a, b = ins
    if all(_is_host(v) for v in ins):
        return [np.where(_host(cond), _host(a), _host(b))]
    cv = _host(cond) if _is_host(cond) else cond
    return [autograd.where(cv, ctx.tensor(a), ctx.tensor(b))]


@handles("Trilu")
def _h_trilu(ctx, node, attrs, ins):
    upper = attrs.get("upper", 1)
    k = int(_require_host(ins[1], node, "k")) if len(ins) > 1 and ins[1] is not None else 0
    fn = (lambda a: jnp.triu(a, k)) if upper else (lambda a: jnp.tril(a, k))
    if _is_host(ins[0]):
        return [np.triu(_host(ins[0]), k) if upper else np.tril(_host(ins[0]), k)]
    return [_apply(ctx, fn, ins[0])]


# -- recurrent ops (LSTM/GRU/RNN) -------------------------------------------
# ONNX layout=0 tensors: X (T, B, I); W (D, G*H, I); R (D, G*H, H);
# B (D, 2*G*H) = W-bias ++ R-bias; initial_h/c (D, B, H); outputs
# Y (T, D, B, H), Y_h/Y_c (D, B, H).  The time loop is lax.scan with the
# input projection hoisted out (one big (T*B, I)x(I, G*H) matmul feeds
# the MXU; only the (B, H)x(H, G*H) recurrence stays sequential), and
# the whole cell is a pure jnp function so jax.vjp keeps imported
# recurrent graphs trainable.

_RNN_ACT = {"Sigmoid": jax.nn.sigmoid, "Tanh": jnp.tanh,
            "Relu": jax.nn.relu, "Affine": None}


def _rnn_common(node, attrs, ins, default_acts):
    """Shared decode/validation; returns (H, D, direction, acts, clip)."""
    H = int(attrs["hidden_size"])
    direction = attrs.get("direction", b"forward")
    if isinstance(direction, bytes):
        direction = direction.decode()
    if direction not in ("forward", "reverse", "bidirectional"):
        raise ValueError(f"{node.op_type}: bad direction {direction!r}")
    D = 2 if direction == "bidirectional" else 1
    if int(attrs.get("layout", 0)) != 0:
        raise ValueError(f"{node.op_type}: layout=1 is not supported")
    acts = attrs.get("activations")
    if acts:
        acts = [a.decode() if isinstance(a, bytes) else a for a in acts]
        for a in acts:
            if a not in _RNN_ACT or _RNN_ACT[a] is None:
                raise ValueError(f"{node.op_type}: activation {a!r} "
                                 "unsupported")
        want = len(default_acts) * D
        if len(acts) != want:
            raise ValueError(
                f"{node.op_type}: activations lists {len(acts)} names, "
                f"expected {want} ({len(default_acts)} per direction)")
    else:
        acts = default_acts * D
    clip = float(attrs["clip"]) if "clip" in attrs else None
    seq_lens = ins[4] if len(ins) > 4 else None
    if seq_lens is not None:
        sl = _require_host(seq_lens, node, "sequence_lens").reshape(-1)
        T = ins[0].shape[0] if hasattr(ins[0], "shape") else None
        if not np.all(sl == sl[0]) or (T is not None and sl[0] != T):
            raise ValueError(
                f"{node.op_type}: sequence_lens {sl.tolist()} != full "
                f"length {T} are not supported (ONNX requires zero "
                "padding + per-row final states, which need dynamic "
                "shapes)")
    if node.op_type == "LSTM" and len(ins) > 7 and ins[7] is not None:
        raise ValueError("LSTM: peephole weights (P) are not supported")
    return H, D, direction, acts, clip


def _rnn_scan(op_type, x, w, r, b, h0, c0, H, D, direction, acts, clip,
              linear_before_reset=0):
    """Pure jnp: run the recurrence; returns (Y, Y_h[, Y_c])."""
    T, Bs, _ = x.shape
    n_g = {"LSTM": 4, "GRU": 3, "RNN": 1}[op_type]
    acts_per_dir = {"LSTM": 3, "GRU": 2, "RNN": 1}[op_type]
    outs, hs, cs = [], [], []
    for d in range(D):
        rev = (direction == "reverse") or (d == 1)
        xd = x[::-1] if rev else x
        wd, rd = w[d], r[d]                       # (G*H, I), (G*H, H)
        bd = b[d] if b is not None else jnp.zeros((2 * n_g * H,), x.dtype)
        wb, rb = bd[:n_g * H], bd[n_g * H:]
        da = acts[d * acts_per_dir:(d + 1) * acts_per_dir]
        f_act = _RNN_ACT[da[0]]
        g_act = _RNN_ACT[da[1]] if len(da) > 1 else None
        h_act = _RNN_ACT[da[2]] if len(da) > 2 else None
        hd0 = h0[d]
        cd0 = c0[d] if c0 is not None else None

        def cl(v):
            return jnp.clip(v, -clip, clip) if clip is not None else v

        if op_type == "LSTM":
            pre = xd @ wd.T + wb + rb             # (T, Bs, 4H)

            def step(carry, px):
                h, c = carry
                g = cl(px + h @ rd.T)
                i = f_act(g[..., 0:H])
                o = f_act(g[..., H:2 * H])
                f = f_act(g[..., 2 * H:3 * H])
                cand = g_act(g[..., 3 * H:4 * H])
                c2 = f * c + i * cand
                h2 = o * h_act(c2)
                return (h2, c2), h2

            (hT, cT), ys = jax.lax.scan(step, (hd0, cd0), pre)
            cs.append(cT)
        elif op_type == "GRU":
            pre = xd @ wd.T + wb                  # (T, Bs, 3H)
            rb_h = rb[2 * H:3 * H]
            rd_h = rd[2 * H:3 * H]

            if linear_before_reset:
                # all three recurrent projections use un-gated h: one
                # fused (Bs,H)x(H,3H) matmul per step
                def step(h, px):
                    hr = h @ rd.T + rb            # (Bs, 3H)
                    z = f_act(cl(px[..., 0:H] + hr[..., 0:H]))
                    rr = f_act(cl(px[..., H:2 * H] + hr[..., H:2 * H]))
                    hh = g_act(cl(px[..., 2 * H:] + rr * hr[..., 2 * H:]))
                    h2 = (1 - z) * hh + z * h
                    return h2, h2
            else:
                # z/r fuse on un-gated h; the candidate needs (r*h)
                rd_zr = rd[0:2 * H]
                rb_zr = rb[0:2 * H]

                def step(h, px):
                    hzr = h @ rd_zr.T + rb_zr     # (Bs, 2H)
                    z = f_act(cl(px[..., 0:H] + hzr[..., 0:H]))
                    rr = f_act(cl(px[..., H:2 * H] + hzr[..., H:]))
                    hh = g_act(cl(px[..., 2 * H:] + (rr * h) @ rd_h.T
                                  + rb_h))
                    h2 = (1 - z) * hh + z * h
                    return h2, h2

            hT, ys = jax.lax.scan(step, hd0, pre)
        else:  # RNN
            pre = xd @ wd.T + wb + rb             # (T, Bs, H)

            def step(h, px):
                h2 = f_act(cl(px + h @ rd.T))
                return h2, h2

            hT, ys = jax.lax.scan(step, hd0, pre)
        if rev:
            ys = ys[::-1]
        outs.append(ys)
        hs.append(hT)
    Y = jnp.stack(outs, axis=1)                   # (T, D, Bs, H)
    Yh = jnp.stack(hs, axis=0)                    # (D, Bs, H)
    if op_type == "LSTM":
        return Y, Yh, jnp.stack(cs, axis=0)
    return Y, Yh


def _h_recurrent(ctx, node, attrs, ins, default_acts):
    H, D, direction, acts, clip = _rnn_common(node, attrs, ins,
                                              default_acts)
    lbr = int(attrs.get("linear_before_reset", 0))
    X, W, R = ins[0], ins[1], ins[2]
    Bb = ins[3] if len(ins) > 3 else None
    h0 = ins[5] if len(ins) > 5 else None
    c0 = ins[6] if len(ins) > 6 else None
    has_b, has_h, has_c = (Bb is not None, h0 is not None, c0 is not None)
    present = [v for v in (X, W, R, Bb, h0, c0) if v is not None]

    def fn(*arrs):
        it = iter(arrs)
        x, w, r = next(it), next(it), next(it)
        b = next(it) if has_b else None
        Bs = x.shape[1]
        hh = next(it) if has_h else jnp.zeros((D, Bs, H), x.dtype)
        cc = (next(it) if has_c else jnp.zeros((D, Bs, H), x.dtype)) \
            if node.op_type == "LSTM" else None
        return _rnn_scan(node.op_type, x, w, r, b, hh, cc, H, D,
                         direction, acts, clip, lbr)

    outs = _apply(ctx, fn, *present)
    return list(outs)[:max(1, len(node.output))]


@handles("LSTM")
def _h_lstm(ctx, node, attrs, ins):
    return _h_recurrent(ctx, node, attrs, ins,
                        ["Sigmoid", "Tanh", "Tanh"])


@handles("GRU")
def _h_gru(ctx, node, attrs, ins):
    return _h_recurrent(ctx, node, attrs, ins, ["Sigmoid", "Tanh"])


@handles("RNN")
def _h_rnn(ctx, node, attrs, ins):
    return _h_recurrent(ctx, node, attrs, ins, ["Tanh"])


@handles("OneHot")
def _h_onehot(ctx, node, attrs, ins):
    axis = attrs.get("axis", -1)
    depth = int(_require_host(ins[1], node, "depth"))
    values = _require_host(ins[2], node, "values").reshape(-1)
    off, on = values[0], values[1]

    def onehot(idx):
        oh = jax.nn.one_hot(idx.astype(jnp.int32), depth, axis=axis)
        return oh * (on - off) + off

    return [_apply(ctx, onehot, ins[0])]


@handles("CumSum")
def _h_cumsum(ctx, node, attrs, ins):
    axis = int(_require_host(ins[1], node, "axis"))
    return [_apply(ctx, lambda a: jnp.cumsum(a, axis=axis), ins[0])]


@handles("Pad")
def _h_pad(ctx, node, attrs, ins):
    mode = attrs.get("mode", "constant")
    if len(ins) > 1 and ins[1] is not None:
        pads = [int(v) for v in _require_host(ins[1], node, "pads").reshape(-1)]
        cval = float(_require_host(ins[2], node, "value")) if len(ins) > 2 and ins[2] is not None else 0.0
    else:
        pads = [int(v) for v in attrs["pads"]]
        cval = attrs.get("value", 0.0)
    nd = len(pads) // 2
    pw = [(pads[i], pads[i + nd]) for i in range(nd)]
    if mode != "constant":
        return [_apply(ctx, lambda a: jnp.pad(a, pw, mode=mode), ins[0])]
    return [autograd.pad(ctx.tensor(ins[0]), pw, cval)]


# -- reductions --------------------------------------------------------------

_REDUCE = {
    "ReduceSum": autograd.reduce_sum,
    "ReduceMean": autograd.reduce_mean,
    "ReduceMax": autograd.reduce_max,
    "ReduceMin": autograd.reduce_min,
}


@handles(*_REDUCE, "ReduceProd", "ReduceL2")
def _h_reduce(ctx, node, attrs, ins):
    keepdims = bool(attrs.get("keepdims", 1))
    axes = _axes_arg(node, attrs, ins, 1, ctx.opset)
    if axes is None and attrs.get("noop_with_empty_axes", 0):
        return [ctx.tensor(ins[0])]
    ax = tuple(axes) if axes is not None else None
    if node.op_type == "ReduceProd":
        return [_apply(ctx, lambda a: jnp.prod(a, axis=ax, keepdims=keepdims), ins[0])]
    if node.op_type == "ReduceL2":
        return [_apply(ctx, lambda a: jnp.sqrt(jnp.sum(a * a, axis=ax, keepdims=keepdims)),
                       ins[0])]
    return [_REDUCE[node.op_type](ctx.tensor(ins[0]), ax, keepdims)]


@handles("ArgMax", "ArgMin")
def _h_argmax(ctx, node, attrs, ins):
    axis = attrs.get("axis", 0)
    keepdims = bool(attrs.get("keepdims", 1))
    fn = jnp.argmax if node.op_type == "ArgMax" else jnp.argmin

    def arg(a):
        out = fn(a, axis=axis).astype(jnp.int64)
        return jnp.expand_dims(out, axis) if keepdims else out

    return [_apply(ctx, arg, ins[0])]


# -- NN ops ------------------------------------------------------------------

@handles("Conv")
def _h_conv(ctx, node, attrs, ins):
    """ONNX Conv is NCHW/OIHW; our MXU path is NHWC/HWIO
    (autograd.Conv2d) — transpose in, convolve, transpose out; XLA
    cancels back-to-back transposes between stacked convs."""
    x = ctx.tensor(ins[0])
    w = ctx.tensor(ins[1])
    b = ctx.tensor(ins[2]) if len(ins) > 2 and ins[2] is not None else None
    spatial = len(x.shape) - 2
    one_d = spatial == 1
    if one_d:  # lift 1-D conv to H=1 2-D
        x = autograd.unsqueeze(x, 2)   # N C 1 W
        w = autograd.unsqueeze(w, 2)   # O I 1 K
        spatial = 2
    if spatial != 2:
        raise ValueError(f"Conv: only 1-D/2-D supported, got {spatial}-D")
    strides = list(attrs.get("strides", [1] * spatial))
    dil = list(attrs.get("dilations", [1] * spatial))
    groups = attrs.get("group", 1)
    if one_d:
        strides = [1] + strides if len(strides) == 1 else strides
        dil = [1] + dil if len(dil) == 1 else dil
    in_sp = x.shape[2:]
    k_sp = w.shape[2:]
    eff_k = [(k - 1) * d + 1 for k, d in zip(k_sp, dil)]
    auto = attrs.get("auto_pad", "NOTSET")
    if auto in ("NOTSET", ""):
        pads_attr = list(attrs.get("pads", [0] * (2 * spatial)))
        if one_d and len(pads_attr) == 2:
            pads_attr = [0, pads_attr[0], 0, pads_attr[1]]
        pads = [(pads_attr[i], pads_attr[i + spatial]) for i in range(spatial)]
    elif auto == "VALID":
        pads = [(0, 0)] * spatial
    else:
        pads = []
        for i in range(spatial):
            rem = in_sp[i] % strides[i]
            total = max(0, eff_k[i] - (rem if rem else strides[i]))
            lo, hi = total // 2, total - total // 2
            pads.append((lo, hi) if auto == "SAME_UPPER" else (hi, lo))
    xh = autograd.transpose(x, (0, 2, 3, 1))          # NCHW -> NHWC
    wh = autograd.transpose(w, (2, 3, 1, 0))          # OIHW -> HWIO
    y = autograd.conv2d(xh, wh, None, stride=tuple(strides), padding=pads,
                        groups=groups, dilation=tuple(dil))
    y = autograd.transpose(y, (0, 3, 1, 2))           # NHWC -> NCHW
    if b is not None:
        y = autograd.add_bias(y, b, axis=1)
    if one_d:
        y = autograd.squeeze(y, 2)
    return [y]


@handles("MaxPool", "AveragePool")
def _h_pool(ctx, node, attrs, ins):
    x = ctx.tensor(ins[0])
    if len(x.shape) != 4:
        raise ValueError("MaxPool/AveragePool: 2-D only")
    kernel = tuple(attrs["kernel_shape"])
    strides = tuple(attrs.get("strides", kernel))
    pads = list(attrs.get("pads", [0, 0, 0, 0]))
    if attrs.get("ceil_mode", 0):
        raise ValueError("pool ceil_mode=1 not supported (static shapes)")
    if pads[0] != pads[2] or pads[1] != pads[3]:
        raise ValueError("asymmetric pool padding not supported")
    if pads[0] != pads[1]:
        raise ValueError("non-square pool padding not supported")
    p = pads[0]
    xh = autograd.transpose(x, (0, 2, 3, 1))
    if node.op_type == "MaxPool":
        y = autograd.max_pool2d(xh, kernel, strides, p)
    elif attrs.get("count_include_pad", 0) or p == 0:
        y = autograd.avg_pool2d(xh, kernel, strides, p)
    else:
        # ONNX default count_include_pad=0: denominator excludes padding
        def avg_excl_pad(xv):  # NHWC
            pw = ((0, 0), (p, p), (p, p), (0, 0))
            win = (1,) + kernel + (1,)
            st = (1,) + strides + (1,)
            s = jax.lax.reduce_window(xv, 0.0, jax.lax.add, win, st, pw)
            cnt = jax.lax.reduce_window(jnp.ones_like(xv), 0.0, jax.lax.add,
                                        win, st, pw)
            return s / cnt

        y = _apply(ctx, avg_excl_pad, xh)
    return [autograd.transpose(y, (0, 3, 1, 2))]


@handles("GlobalAveragePool")
def _h_gap(ctx, node, attrs, ins):
    x = ctx.tensor(ins[0])
    sp = tuple(range(2, len(x.shape)))
    return [autograd.reduce_mean(x, sp, keepdims=True)]


@handles("GlobalMaxPool")
def _h_gmp(ctx, node, attrs, ins):
    x = ctx.tensor(ins[0])
    sp = tuple(range(2, len(x.shape)))
    return [autograd.reduce_max(x, sp, keepdims=True)]


def _reject_consumed_aux(ctx, node):
    used = [n for n in node.output[1:] if n and n in ctx.consumed]
    if used:
        raise NotImplementedError(
            f"{node.op_type}: auxiliary outputs {used} are consumed by the "
            f"graph but this importer only computes the primary output "
            f"(training-graph stats are not supported)")


@handles("BatchNormalization")
def _h_batchnorm(ctx, node, attrs, ins):
    eps = attrs.get("epsilon", 1e-5)
    x, scale, bias, mean, var = (ctx.tensor(v) for v in ins[:5])

    def bn(xv, s, b, m, v):
        shp = (1, -1) + (1,) * (xv.ndim - 2)  # channel axis 1 (NCHW)
        return ((xv - m.reshape(shp)) * jax.lax.rsqrt(v.reshape(shp) + eps)
                * s.reshape(shp) + b.reshape(shp))

    y = _JnpOp(bn)(x, scale, bias, mean, var)
    # training-mode extra outputs (running stats) are not produced; the
    # importer targets inference graphs (training uses singa.layer.BatchNorm2d)
    _reject_consumed_aux(ctx, node)
    return [y] + [None] * (len(node.output) - 1)


@handles("LayerNormalization")
def _h_layernorm(ctx, node, attrs, ins):
    eps = attrs.get("epsilon", 1e-5)
    axis = attrs.get("axis", -1)
    x = ctx.tensor(ins[0])
    scale = ctx.tensor(ins[1])
    bias = ctx.tensor(ins[2]) if len(ins) > 2 and ins[2] is not None else None
    nd = len(x.shape)
    ax = axis % nd
    axes = tuple(range(ax, nd))

    def ln(xv, s, *b):
        mu = jnp.mean(xv, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(xv - mu), axis=axes, keepdims=True)
        y = (xv - mu) * jax.lax.rsqrt(var + eps) * s
        if b:
            y = y + b[0]
        return y

    args = (x, scale) + ((bias,) if bias is not None else ())
    y = _JnpOp(ln)(*args)
    _reject_consumed_aux(ctx, node)  # Mean/InvStdDev outputs not computed
    return [y] + [None] * (len(node.output) - 1)


@handles("InstanceNormalization")
def _h_instancenorm(ctx, node, attrs, ins):
    eps = attrs.get("epsilon", 1e-5)

    def inorm(xv, s, b):
        axes = tuple(range(2, xv.ndim))
        mu = jnp.mean(xv, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(xv - mu), axis=axes, keepdims=True)
        shp = (1, -1) + (1,) * (xv.ndim - 2)
        return (xv - mu) * jax.lax.rsqrt(var + eps) * s.reshape(shp) + b.reshape(shp)

    return [_apply(ctx, inorm, *ins[:3])]


@handles("Dropout")
def _h_dropout(ctx, node, attrs, ins):
    x = ctx.tensor(ins[0])
    ratio = attrs.get("ratio", 0.5)
    if len(ins) > 1 and ins[1] is not None:
        ratio = float(_require_host(ins[1], node, "ratio"))
    train = False
    if len(ins) > 2 and ins[2] is not None:
        train = bool(_require_host(ins[2], node, "training_mode"))
    y = autograd.dropout(x, ratio) if (train and ctx.training) else x
    outs = [y]
    if len(node.output) > 1:
        outs.append(np.ones(x.shape, np.bool_))
    return outs


# ---------------------------------------------------------------------------
# the backend rep
# ---------------------------------------------------------------------------

class SingaRep(model_mod.Model):
    """An imported ONNX graph as a singa model.

    `run(inputs)` mirrors the reference backend-rep surface; because this
    is a `model.Model`, `compile()` + graph mode captures the whole
    imported network into a single XLA module, and float initializers are
    trainable params (training-capable import)."""

    def __init__(self, model_proto: ModelProto, device=None,
                 init_inputs: Optional[Sequence] = None, name: str = "onnx"):
        super().__init__(name=name)
        self.proto_model = model_proto
        g = model_proto.graph
        if g is None:
            raise ValueError("ModelProto has no graph")
        self.onnx_graph = g
        self.device_ = device or get_default_device()
        self.opset = 18
        for op in model_proto.opset_import:
            if (op.domain or "") == "":
                self.opset = int(op.version or 18)
        # initializers → params (float ⇒ trainable) / constants
        self._consts: Dict[str, Any] = {}
        self._param_alias: Dict[str, str] = {}
        for init in g.initializer:
            arr = to_array(init)
            # 0-d float initializers are scale/eps constants, not weights
            if np.issubdtype(arr.dtype, np.floating) and arr.ndim > 0:
                pname = _sanitize(init.name)
                t = Tensor(data=jnp.asarray(arr), device=self.device_,
                           requires_grad=True, stores_grad=True,
                           name=pname)
                self.register_param(pname, t)
                self._param_alias[init.name] = pname
            else:
                self._consts[init.name] = arr
        init_names = ({i.name for i in g.initializer})
        self.input_names = [vi.name for vi in g.input if vi.name not in init_names]
        self.output_names = [vi.name for vi in g.output]
        self._consumed = set(self.output_names)
        for n in g.node:
            self._consumed.update(i for i in n.input if i)
        unsupported = sorted({n.op_type for n in g.node if n.op_type not in _HANDLERS})
        if unsupported:
            raise NotImplementedError(
                f"unsupported ONNX ops: {unsupported}; supported: "
                f"{supported_ops()}")

    # -- execution ------------------------------------------------------------
    def forward(self, *inputs):
        if len(inputs) != len(self.input_names):
            raise ValueError(
                f"expected {len(self.input_names)} inputs "
                f"{self.input_names}, got {len(inputs)}")
        ctx = _Ctx(self.device_, self.opset, autograd.is_training(),
                   self._consumed)
        env: Dict[str, Any] = dict(self._consts)
        for onnx_name, pname in self._param_alias.items():
            env[onnx_name] = self._params[pname]
        for name, v in zip(self.input_names, inputs):
            env[name] = v if isinstance(v, Tensor) else ctx.tensor(np.asarray(v))
        for node in self.onnx_graph.node:
            ins = [env[i] if i else None for i in node.input]
            outs = _HANDLERS[node.op_type](ctx, node, _attrs(node), ins)
            for name, v in zip(node.output, outs):
                if name and v is not None:
                    env[name] = v
        outs = []
        for name in self.output_names:
            v = env[name]
            outs.append(v if isinstance(v, Tensor) else ctx.tensor(v))
        return outs[0] if len(outs) == 1 else tuple(outs)

    def run(self, inputs: Sequence) -> List[Tensor]:
        """Reference backend-rep surface: list in, list of Tensors out."""
        out = self(*inputs)
        return list(out) if isinstance(out, tuple) else [out]


def _sanitize(name: str) -> str:
    out = "".join(c if (c.isalnum() or c == "_") else "_" for c in name)
    return out or "param"


class SingaBackend:
    """onnx-backend-style entry (reference `sonnx.SingaBackend`)."""

    @staticmethod
    def supports_device(device: str) -> bool:
        return True

    @staticmethod
    def prepare(model_proto: ModelProto, device=None, **kwargs) -> SingaRep:
        return SingaRep(model_proto, device=device, **kwargs)


def prepare(model_proto: Union[ModelProto, bytes, str], device=None,
            **kwargs) -> SingaRep:
    """Import an ONNX model (path / bytes / ModelProto) for execution +
    training on singa_tpu (reference sonnx.prepare, SURVEY.md §3.4)."""
    if isinstance(model_proto, (bytes, bytearray)):
        model_proto = ModelProto.FromString(bytes(model_proto))
    elif isinstance(model_proto, str):
        model_proto = proto.load(model_proto)
    return SingaBackend.prepare(model_proto, device=device, **kwargs)
