"""singa_tpu.autograd — tape-based reverse-mode autodiff over XLA math.

Capability parity: the reference's ``singa.autograd`` (~90 Operator
classes with explicit forward/backward and a tape; BASELINE.json:5 "the
Graph/Scheduler that buffers singa.autograd ops").  TPU-first design:

* Every ``Operator.fwd`` is a *pure jnp function* — so an eager call runs
  via XLA eagerly, and the same Python code traced under ``jax.jit``
  (see singa_tpu.model graph mode) captures forward + backward + update
  into ONE XLA HLO module, which is the north-star execution model.
* ``backward()`` walks the creator graph in reverse topological order —
  the tape IS the captured graph; in graph mode the tape is rebuilt per
  trace, then frozen inside the compiled executable.
* Hand-written backwards for the hot/simple ops; everything else uses
  ``jax.vjp`` of the op's pure ``fwd`` — identical semantics, and XLA
  DCEs unused residuals in eval mode.

No torch anywhere; no data-dependent Python control flow inside ops.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import tensor as tensor_mod
from .tensor import Tensor

__all__ = [
    "training", "set_training", "is_training", "Operator", "backward",
    "grad_of", "add", "sub", "mul", "div", "neg", "pow", "abs", "exp",
    "log", "sqrt", "rsqrt", "cast", "clip", "matmul", "einsum", "reshape",
    "transpose", "flatten", "squeeze", "unsqueeze", "cat", "stack",
    "split", "index", "gather", "embedding", "relu", "sigmoid", "tanh",
    "gelu", "silu", "softplus", "leakyrelu", "elu", "softmax",
    "log_softmax", "dropout", "reduce_sum", "reduce_mean", "reduce_max",
    "reduce_min", "cross_entropy", "softmax_cross_entropy", "mse_loss",
    "nll_loss", "binary_cross_entropy", "conv2d", "max_pool2d",
    "avg_pool2d", "batchnorm", "layernorm", "rmsnorm", "linear",
    "add_bias", "pad", "cossim", "where", "erf",
]

# global train/eval flag (reference: autograd.training)
training: bool = False


def set_training(flag: bool) -> None:
    global training
    training = bool(flag)


def is_training() -> bool:
    return training


class _TrainingScope:
    def __init__(self, flag):
        self.flag = flag

    def __enter__(self):
        self.prev = training
        set_training(self.flag)

    def __exit__(self, *a):
        set_training(self.prev)


def train_mode():
    return _TrainingScope(True)


def eval_mode():
    return _TrainingScope(False)


# ---------------------------------------------------------------------------
# Operator base
# ---------------------------------------------------------------------------

class Operator:
    """A differentiable op: node in the captured graph.

    Subclasses either
      * define ``fwd(self, *arrays) -> array`` (pure jnp) and inherit the
        jax.vjp-derived backward, or
      * override ``forward``/``backward`` for a hand-written rule.
    """

    # comparisons/logical ops set this False: integer/bool outputs take
    # no gradient and must never enter the tape
    differentiable = True

    def __init__(self):
        self.src: List[Tuple[Tensor, bool]] = []   # (input tensor, needs grad)
        self.requires_grad = False
        self._vjp: Optional[Callable] = None

    # -- to be provided by subclasses ---------------------------------------
    def fwd(self, *arrays):  # pragma: no cover - overridden
        raise NotImplementedError

    def forward(self, *arrays):
        if self.requires_grad:
            out, self._vjp = jax.vjp(self.fwd, *arrays)
            return out
        return self.fwd(*arrays)

    def backward(self, dy):
        return self._vjp(dy)

    # -- native CPU dispatch (tensor_math_cpp parity) ------------------------
    # Ops that define `native_fwd` run through csrc/tensor_math_cpp.cc when
    # the input device is CppCPU(use_native=True) and inputs are concrete
    # f32 host arrays.  Ops relying on the default jax.vjp backward only
    # dispatch natively when no gradient is required (the vjp pairing needs
    # the jnp forward); hand-written-backward ops dispatch in training too.
    def _native_candidate(self, inputs, arrays) -> bool:
        if not inputs or not hasattr(self, "native_fwd"):
            return False
        dev = inputs[0].device
        if not getattr(dev, "use_native", False):
            return False
        from . import _core
        if not _core.available():
            return False
        import jax as _jax
        for a in arrays:
            if isinstance(a, _jax.core.Tracer) or a.dtype != np.float32:
                return False
        if type(self).backward is Operator.backward and self.requires_grad:
            return False  # default-vjp backward needs the jnp forward
        return True

    # -- tape machinery ------------------------------------------------------
    def __call__(self, *inputs: Tensor):
        arrays = []
        for x in inputs:
            if not isinstance(x, Tensor):
                raise TypeError(f"{type(self).__name__} got non-Tensor input {type(x)}")
            arrays.append(x.data)
        self.requires_grad = (training and self.differentiable
                              and any(x.requires_grad for x in inputs))
        out = None
        if self._native_candidate(inputs, arrays):
            out = self.native_fwd(*[np.asarray(a) for a in arrays])
            if out is not None:
                out = jnp.asarray(out)
        if out is None:
            out = self.forward(*arrays)
        if self.requires_grad:
            self.src = [(x, x.requires_grad) for x in inputs]
        dev = inputs[0].device if inputs else None
        creator = self if self.requires_grad else None
        if isinstance(out, tuple):
            return tuple(Tensor(data=o, device=dev, requires_grad=self.requires_grad,
                                creator=creator) for o in out)
        return Tensor(data=out, device=dev, requires_grad=self.requires_grad,
                      creator=creator)


def _unbroadcast(g, shape):
    """Reduce gradient ``g`` back to ``shape`` after numpy broadcasting."""
    if tuple(g.shape) == tuple(shape):
        return g
    # sum leading broadcast dims
    extra = g.ndim - len(shape)
    if extra > 0:
        g = jnp.sum(g, axis=tuple(range(extra)))
    # sum dims that were 1
    axes = tuple(i for i, s in enumerate(shape) if s == 1 and g.shape[i] != 1)
    if axes:
        g = jnp.sum(g, axis=axes, keepdims=True)
    return g


# ---------------------------------------------------------------------------
# reverse pass
# ---------------------------------------------------------------------------

def backward(y: Tensor, dy: Optional[Any] = None):
    """Reverse-topological walk of the creator graph from ``y``.

    Returns a list of (param_tensor, grad_tensor) for every reachable leaf
    with ``stores_grad=True``; also sets ``leaf.grad``.  Mirrors the
    reference's ``autograd.backward`` contract.
    """
    if y.creator is None:
        return []
    if dy is None:
        dy = jnp.ones_like(y.data)
    elif isinstance(dy, Tensor):
        dy = dy.data

    # topological order over ops via DFS
    order: List[Operator] = []
    seen = set()

    def visit(op: Operator):
        if id(op) in seen:
            return
        seen.add(id(op))
        for (t, needs) in op.src:
            if needs and t.creator is not None:
                visit(t.creator)
        order.append(op)

    visit(y.creator)

    # accumulate output-grads per tensor id
    grads: Dict[int, Any] = {id(y): dy}
    tensors: Dict[int, Tensor] = {id(y): y}
    # map op -> its output tensor ids handled implicitly: each Tensor holds
    # its creator, so walk ops in reverse and pull grads of their outputs.
    # We track output grads keyed by tensor identity.
    out_of: Dict[int, List[Tensor]] = {}
    for op in order:
        for (t, needs) in op.src:
            tensors[id(t)] = t

    results = []
    for op in reversed(order):
        # gather grad(s) of this op's output(s)
        g_out = _collect_op_output_grad(op, grads)
        if g_out is None:
            continue
        # incoming cotangents must match the op's output dtype: mixed-
        # precision boundaries (e.g. BatchNorm's f32 statistics feeding a
        # bf16 conv) otherwise hand jax.vjp an f32 dy for a bf16 output
        dts = getattr(op, "_out_dtypes", None)
        if dts is not None:
            if isinstance(g_out, tuple):
                g_out = tuple(g if g is None or g.dtype == d else g.astype(d)
                              for g, d in zip(g_out, dts))
            elif g_out.dtype != dts[0]:
                g_out = g_out.astype(dts[0])
        gs = op.backward(g_out)
        if not isinstance(gs, (tuple, list)):
            gs = (gs,)
        for (t, needs), g in zip(op.src, gs):
            if not needs or g is None:
                continue
            tid = id(t)
            if tid in grads:
                grads[tid] = grads[tid] + g
            else:
                grads[tid] = g

    for tid, t in tensors.items():
        if t.stores_grad and tid in grads:
            gt = Tensor(data=grads[tid], device=t.device, requires_grad=False)
            t.grad = gt
            results.append((t, gt))
    return results


def _collect_op_output_grad(op: Operator, grads: Dict[int, Any]):
    # Tensors referencing this op as creator are its outputs; we stored the
    # grads keyed by the tensor id, which we find via the _outputs hook set
    # below. For single-output ops (the overwhelming majority) the output
    # tensor registered its id at creation time via grads lookup by the
    # caller; to keep this O(1) we stash output ids on the op.
    ids = getattr(op, "_out_ids", None)
    if ids is None:
        return None
    gs = [grads.get(i) for i in ids]
    if all(g is None for g in gs):
        return None
    # multi-output: missing grads become zeros of recorded shape
    if len(gs) == 1:
        return gs[0]
    shapes = op._out_shapes
    dtypes = op._out_dtypes
    return tuple(g if g is not None else jnp.zeros(s, d)
                 for g, s, d in zip(gs, shapes, dtypes))


# hook output registration into Operator.__call__ (kept separate for clarity)
_orig_call = Operator.__call__


def _call_with_registration(self, *inputs):
    out = _orig_call(self, *inputs)
    if self.requires_grad:
        outs = out if isinstance(out, tuple) else (out,)
        self._out_ids = [id(o) for o in outs]
        self._out_shapes = [o.data.shape for o in outs]
        self._out_dtypes = [o.data.dtype for o in outs]
        # keep outputs alive for the duration of the tape walk: ids are only
        # valid while the tensors exist
        self._outs_ref = outs
    return out


Operator.__call__ = _call_with_registration


def grad_of(t: Tensor) -> Optional[Tensor]:
    return t.grad


# ---------------------------------------------------------------------------
# elementwise arithmetic (hand-written backwards)
# ---------------------------------------------------------------------------

class Add(Operator):
    def forward(self, a, b):
        self._sa, self._sb = a.shape, b.shape
        return jnp.add(a, b)

    def native_fwd(self, a, b):
        if a.shape != b.shape:
            return None  # broadcast handled by the jnp path
        self._sa = self._sb = a.shape
        from . import _core
        return _core.add(a, b)

    def backward(self, dy):
        return _unbroadcast(dy, self._sa), _unbroadcast(dy, self._sb)


class Sub(Operator):
    def forward(self, a, b):
        self._sa, self._sb = a.shape, b.shape
        return jnp.subtract(a, b)

    def backward(self, dy):
        return _unbroadcast(dy, self._sa), _unbroadcast(-dy, self._sb)


class Mul(Operator):
    def forward(self, a, b):
        self._a, self._b = a, b
        return jnp.multiply(a, b)

    def native_fwd(self, a, b):
        if a.shape != b.shape:
            return None
        self._a, self._b = a, b
        from . import _core
        return _core.mul(a, b)

    def backward(self, dy):
        return (_unbroadcast(dy * self._b, self._a.shape),
                _unbroadcast(dy * self._a, self._b.shape))


class Div(Operator):
    def forward(self, a, b):
        self._a, self._b = a, b
        return jnp.divide(a, b)

    def backward(self, dy):
        ga = dy / self._b
        gb = -dy * self._a / (self._b * self._b)
        return _unbroadcast(ga, self._a.shape), _unbroadcast(gb, self._b.shape)


class Neg(Operator):
    def forward(self, a):
        return -a

    def backward(self, dy):
        return (-dy,)


class Pow(Operator):
    def __init__(self, p):
        super().__init__()
        self.p = p

    def forward(self, a):
        self._a = a
        return jnp.power(a, self.p)

    def backward(self, dy):
        return (dy * self.p * jnp.power(self._a, self.p - 1),)


class Abs(Operator):
    def forward(self, a):
        self._a = a
        return jnp.abs(a)

    def backward(self, dy):
        return (dy * jnp.sign(self._a),)


class Exp(Operator):
    def forward(self, a):
        self._y = jnp.exp(a)
        return self._y

    def backward(self, dy):
        return (dy * self._y,)


class Log(Operator):
    def forward(self, a):
        self._a = a
        return jnp.log(a)

    def backward(self, dy):
        return (dy / self._a,)


class Sqrt(Operator):
    def forward(self, a):
        self._y = jnp.sqrt(a)
        return self._y

    def backward(self, dy):
        return (dy * 0.5 / self._y,)


class Rsqrt(Operator):
    def fwd(self, a):
        return jax.lax.rsqrt(a)


class Cast(Operator):
    def __init__(self, dtype):
        super().__init__()
        self.dtype = dtype

    def forward(self, a):
        self._from = a.dtype
        return a.astype(self.dtype)

    def backward(self, dy):
        return (dy.astype(self._from),)


class Clip(Operator):
    def __init__(self, lo, hi):
        super().__init__()
        self.lo, self.hi = lo, hi

    def forward(self, a):
        self._mask = ((a >= self.lo) & (a <= self.hi))
        return jnp.clip(a, self.lo, self.hi)

    def backward(self, dy):
        return (dy * self._mask.astype(dy.dtype),)


class Erf(Operator):
    def fwd(self, a):
        return jax.lax.erf(a)


def add(a, b):
    return Add()(a, _as_t(b, a))


def sub(a, b):
    return Sub()(a, _as_t(b, a))


def mul(a, b):
    return Mul()(a, _as_t(b, a))


def div(a, b):
    return Div()(a, _as_t(b, a))


def neg(a):
    return Neg()(a)


def pow(a, p):
    return Pow(p)(a)


def abs(a):
    return Abs()(a)


def exp(a):
    return Exp()(a)


def log(a):
    return Log()(a)


def sqrt(a):
    return Sqrt()(a)


def rsqrt(a):
    return Rsqrt()(a)


def cast(a, dtype):
    return Cast(dtype)(a)


def clip(a, lo, hi):
    return Clip(lo, hi)(a)


def erf(a):
    return Erf()(a)


def _as_t(x, like: Tensor) -> Tensor:
    if isinstance(x, Tensor):
        return x
    return Tensor(data=jnp.asarray(x, dtype=like.dtype), device=like.device,
                  requires_grad=False)


# ---------------------------------------------------------------------------
# matmul / einsum / linear — MXU territory: keep batched, let XLA tile
# ---------------------------------------------------------------------------

class Matmul(Operator):
    def forward(self, a, b):
        self._a, self._b = a, b
        return jnp.matmul(a, b)

    def native_fwd(self, a, b):
        if a.ndim != 2 or b.ndim != 2:
            return None
        self._a, self._b = a, b
        from . import _core
        return _core.gemm(a, b)

    def backward(self, dy):
        a, b = self._a, self._b
        ga = jnp.matmul(dy, jnp.swapaxes(b, -1, -2))
        gb = jnp.matmul(jnp.swapaxes(a, -1, -2), dy)
        return _unbroadcast(ga, a.shape), _unbroadcast(gb, b.shape)


class Einsum(Operator):
    def __init__(self, subscripts):
        super().__init__()
        self.subscripts = subscripts

    def fwd(self, *arrays):
        return jnp.einsum(self.subscripts, *arrays)


class Linear(Operator):
    """y = x @ W (+ b). Fused affine — one MXU call + bias fusion."""

    def __init__(self, has_bias: bool):
        super().__init__()
        self.has_bias = has_bias

    def forward(self, x, w, *b):
        self._x, self._w = x, w
        y = jnp.matmul(x, w)
        if self.has_bias:
            y = y + b[0]
        return y

    def native_fwd(self, x, w, *b):
        if x.ndim != 2:
            return None
        self._x, self._w = x, w
        from . import _core
        y = _core.gemm(x, w)
        if self.has_bias:
            y += b[0]
        return y

    def backward(self, dy):
        x, w = self._x, self._w
        gx = jnp.matmul(dy, w.T)
        lead = int(np.prod(x.shape[:-1]))
        gw = jnp.matmul(x.reshape(lead, x.shape[-1]).T,
                        dy.reshape(lead, dy.shape[-1]))
        if self.has_bias:
            gb = jnp.sum(dy.reshape(lead, dy.shape[-1]), axis=0)
            return gx, gw, gb
        return gx, gw


def matmul(a, b):
    return Matmul()(a, b)


def einsum(subscripts, *ts):
    return Einsum(subscripts)(*ts)


def linear(x, w, b=None):
    if b is None:
        return Linear(False)(x, w)
    return Linear(True)(x, w, b)


class AddBias(Operator):
    def __init__(self, axis=1):
        super().__init__()
        self.axis = axis

    def forward(self, x, b):
        shape = [1] * x.ndim
        shape[self.axis] = b.shape[0]
        self._xnd = x.ndim
        return x + b.reshape(shape)

    def backward(self, dy):
        axes = tuple(i for i in range(self._xnd) if i != self.axis)
        return dy, jnp.sum(dy, axis=axes)


def add_bias(x, b, axis=1):
    return AddBias(axis)(x, b)


# ---------------------------------------------------------------------------
# shape ops
# ---------------------------------------------------------------------------

class Reshape(Operator):
    def __init__(self, shape):
        super().__init__()
        self.shape = tuple(shape)

    def forward(self, a):
        self._orig = a.shape
        return a.reshape(self.shape)

    def backward(self, dy):
        return (dy.reshape(self._orig),)


class Transpose(Operator):
    def __init__(self, axes=None):
        super().__init__()
        self.axes = tuple(axes) if axes is not None else None

    def forward(self, a):
        if self.axes is None:
            self._inv = None
            return a.T
        self._inv = tuple(np.argsort(self.axes))
        return jnp.transpose(a, self.axes)

    def backward(self, dy):
        if self._inv is None:
            return (dy.T,)
        return (jnp.transpose(dy, self._inv),)


class Flatten(Operator):
    def __init__(self, start_axis=0):
        super().__init__()
        self.start_axis = start_axis

    def forward(self, a):
        self._orig = a.shape
        s = self.start_axis
        lead = a.shape[:s]
        return a.reshape(lead + (-1,))

    def backward(self, dy):
        return (dy.reshape(self._orig),)


class Squeeze(Operator):
    def __init__(self, axis=None):
        super().__init__()
        self.axis = axis

    def forward(self, a):
        self._orig = a.shape
        return jnp.squeeze(a, self.axis)

    def backward(self, dy):
        return (dy.reshape(self._orig),)


class Unsqueeze(Operator):
    def __init__(self, axis):
        super().__init__()
        self.axis = axis

    def forward(self, a):
        self._orig = a.shape
        ax = self.axis if isinstance(self.axis, (list, tuple)) else [self.axis]
        out = a
        for x in sorted(ax):
            out = jnp.expand_dims(out, x)
        return out

    def backward(self, dy):
        return (dy.reshape(self._orig),)


class Cat(Operator):
    def __init__(self, axis):
        super().__init__()
        self.axis = axis

    def forward(self, *arrays):
        self._sizes = [a.shape[self.axis] for a in arrays]
        return jnp.concatenate(arrays, axis=self.axis)

    def backward(self, dy):
        splits = np.cumsum(self._sizes)[:-1].tolist()
        return tuple(jnp.split(dy, splits, axis=self.axis))


class Stack(Operator):
    def __init__(self, axis):
        super().__init__()
        self.axis = axis

    def forward(self, *arrays):
        return jnp.stack(arrays, axis=self.axis)

    def backward(self, dy):
        parts = jnp.split(dy, dy.shape[self.axis], axis=self.axis)
        return tuple(jnp.squeeze(p, self.axis) for p in parts)


class Split(Operator):
    def __init__(self, parts, axis):
        super().__init__()
        self.parts, self.axis = parts, axis

    def forward(self, a):
        if isinstance(self.parts, int):
            return tuple(jnp.split(a, self.parts, axis=self.axis))
        splits = np.cumsum(self.parts)[:-1].tolist()
        return tuple(jnp.split(a, splits, axis=self.axis))

    def backward(self, dys):
        return (jnp.concatenate(list(dys), axis=self.axis),)


class Index(Operator):
    def __init__(self, idx):
        super().__init__()
        self.idx = idx

    def forward(self, a):
        self._shape, self._dtype = a.shape, a.dtype
        return a[self.idx]

    def backward(self, dy):
        z = jnp.zeros(self._shape, self._dtype)
        return (z.at[self.idx].add(dy),)


class Gather(Operator):
    def __init__(self, axis, indices):
        super().__init__()
        self.axis = axis
        self.indices = jnp.asarray(indices)

    def forward(self, a):
        self._shape, self._dtype = a.shape, a.dtype
        return jnp.take(a, self.indices, axis=self.axis)

    def backward(self, dy):
        z = jnp.zeros(self._shape, self._dtype)
        idx = [slice(None)] * len(self._shape)
        idx[self.axis] = self.indices
        return (z.at[tuple(idx)].add(dy),)


class Embedding(Operator):
    """Row lookup: out[i] = table[ids[i]]. ids are int, non-differentiable."""

    def forward(self, table, ids):
        self._n, self._d = table.shape
        self._ids = ids
        self._dtype = table.dtype
        return jnp.take(table, ids, axis=0)

    def backward(self, dy):
        z = jnp.zeros((self._n, self._d), self._dtype)
        return (z.at[self._ids].add(dy), None)


class Pad(Operator):
    def __init__(self, pad_width, value=0.0):
        super().__init__()
        self.pad_width = pad_width
        self.value = value

    def forward(self, a):
        self._orig = a.shape
        return jnp.pad(a, self.pad_width, constant_values=self.value)

    def backward(self, dy):
        slices = tuple(slice(p[0], p[0] + s)
                       for p, s in zip(self.pad_width, self._orig))
        return (dy[slices],)


def reshape(a, shape):
    return Reshape(shape)(a)


def transpose(a, axes=None):
    return Transpose(axes)(a)


def flatten(a, start_axis=0):
    return Flatten(start_axis)(a)


def squeeze(a, axis=None):
    return Squeeze(axis)(a)


def unsqueeze(a, axis):
    return Unsqueeze(axis)(a)


def cat(ts, axis=0):
    return Cat(axis)(*ts)


def stack(ts, axis=0):
    return Stack(axis)(*ts)


def split(a, parts, axis=0):
    return Split(parts, axis)(a)


def index(a, idx):
    return Index(idx)(a)


def gather(a, axis, indices):
    return Gather(axis, indices)(a)


def embedding(table, ids):
    if not isinstance(ids, Tensor):
        ids = Tensor(data=jnp.asarray(ids), device=table.device, requires_grad=False)
    return Embedding()(table, ids)


def pad(a, pad_width, value=0.0):
    return Pad(pad_width, value)(a)


# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------

class ReLU(Operator):
    def forward(self, a):
        self._mask = a > 0
        return jnp.where(self._mask, a, 0)

    def native_fwd(self, a):
        self._mask = a > 0
        from . import _core
        return _core.relu(a)

    def backward(self, dy):
        return (jnp.where(self._mask, dy, 0),)


class Sigmoid(Operator):
    def forward(self, a):
        self._y = jax.nn.sigmoid(a)
        return self._y

    def native_fwd(self, a):
        from . import _core
        self._y = _core.sigmoid(a)
        return self._y

    def backward(self, dy):
        return (dy * self._y * (1 - self._y),)


class Tanh(Operator):
    def forward(self, a):
        self._y = jnp.tanh(a)
        return self._y

    def native_fwd(self, a):
        from . import _core
        self._y = _core.tanh(a)
        return self._y

    def backward(self, dy):
        return (dy * (1 - self._y * self._y),)


class Gelu(Operator):
    """GELU; approximate=True is the tanh form (GPT-2's gelu_new),
    False the exact erf form (BERT, ONNX Gelu default)."""

    def __init__(self, approximate: bool = True):
        super().__init__()
        self.approximate = approximate

    def fwd(self, a):
        return jax.nn.gelu(a, approximate=self.approximate)


class SiLU(Operator):
    def fwd(self, a):
        return jax.nn.silu(a)


class Softplus(Operator):
    def fwd(self, a):
        return jax.nn.softplus(a)


class LeakyReLU(Operator):
    def __init__(self, slope=0.01):
        super().__init__()
        self.slope = slope

    def forward(self, a):
        self._mask = a > 0
        return jnp.where(self._mask, a, self.slope * a)

    def backward(self, dy):
        return (jnp.where(self._mask, dy, self.slope * dy),)


class Elu(Operator):
    def __init__(self, alpha=1.0):
        super().__init__()
        self.alpha = alpha

    def fwd(self, a):
        return jax.nn.elu(a, self.alpha)


class Softmax(Operator):
    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def native_fwd(self, a):
        if self.axis not in (-1, a.ndim - 1):
            return None
        from . import _core
        self._y = _core.softmax(a)
        return self._y

    def forward(self, a):
        self._y = jax.nn.softmax(a, axis=self.axis)
        return self._y

    def backward(self, dy):
        y = self._y
        inner = jnp.sum(dy * y, axis=self.axis, keepdims=True)
        return (y * (dy - inner),)


class LogSoftmax(Operator):
    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, a):
        self._y = jax.nn.log_softmax(a, axis=self.axis)
        return self._y

    def backward(self, dy):
        soft = jnp.exp(self._y)
        return (dy - soft * jnp.sum(dy, axis=self.axis, keepdims=True),)


class Dropout(Operator):
    def __init__(self, p, key):
        super().__init__()
        self.p = p
        self.key = key

    def forward(self, a):
        if not training or self.p <= 0.0:
            self._mask = None
            return a
        keep = 1.0 - self.p
        self._mask = jax.random.bernoulli(self.key, keep, a.shape)
        self._scale = 1.0 / keep
        return jnp.where(self._mask, a * self._scale, 0)

    def backward(self, dy):
        if self._mask is None:
            return (dy,)
        return (jnp.where(self._mask, dy * self._scale, 0),)


class Where(Operator):
    def __init__(self, cond):
        super().__init__()
        self.cond = cond

    def forward(self, a, b):
        self._sa, self._sb = a.shape, b.shape
        return jnp.where(self.cond, a, b)

    def backward(self, dy):
        return (_unbroadcast(jnp.where(self.cond, dy, 0), self._sa),
                _unbroadcast(jnp.where(self.cond, 0, dy), self._sb))


def relu(a):
    return ReLU()(a)


def sigmoid(a):
    return Sigmoid()(a)


def tanh(a):
    return Tanh()(a)


def gelu(a, approximate: bool = True):
    return Gelu(approximate)(a)


def silu(a):
    return SiLU()(a)


def softplus(a):
    return Softplus()(a)


def leakyrelu(a, slope=0.01):
    return LeakyReLU(slope)(a)


def elu(a, alpha=1.0):
    return Elu(alpha)(a)


def softmax(a, axis=-1):
    return Softmax(axis)(a)


def log_softmax(a, axis=-1):
    return LogSoftmax(axis)(a)


def dropout(a, p=0.5, key=None):
    if not is_training() or p <= 0.0:
        return a          # identity in eval: don't burn (or trace) a key
    if key is None:
        key = tensor_mod._next_key()
    return Dropout(p, key)(a)


def where(cond, a, b):
    cv = cond.data if isinstance(cond, Tensor) else cond
    return Where(cv)(a, _as_t(b, a))


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------

class ReduceSum(Operator):
    def __init__(self, axis, keepdims):
        super().__init__()
        self.axis, self.keepdims = axis, keepdims

    def forward(self, a):
        self._shape = a.shape
        return jnp.sum(a, axis=self.axis, keepdims=self.keepdims)

    def backward(self, dy):
        return (_bcast_reduce_grad(dy, self._shape, self.axis, self.keepdims),)


class ReduceMean(Operator):
    def __init__(self, axis, keepdims):
        super().__init__()
        self.axis, self.keepdims = axis, keepdims

    def forward(self, a):
        self._shape = a.shape
        n = np.prod(a.shape) if self.axis is None else np.prod(
            [a.shape[i] for i in _norm_axes(self.axis, a.ndim)])
        self._n = float(n)
        return jnp.mean(a, axis=self.axis, keepdims=self.keepdims)

    def backward(self, dy):
        return (_bcast_reduce_grad(dy, self._shape, self.axis, self.keepdims) / self._n,)


class ReduceMax(Operator):
    def __init__(self, axis, keepdims):
        super().__init__()
        self.axis, self.keepdims = axis, keepdims

    def fwd(self, a):
        return jnp.max(a, axis=self.axis, keepdims=self.keepdims)


class ReduceMin(Operator):
    def __init__(self, axis, keepdims):
        super().__init__()
        self.axis, self.keepdims = axis, keepdims

    def fwd(self, a):
        return jnp.min(a, axis=self.axis, keepdims=self.keepdims)


def _norm_axes(axis, ndim):
    if isinstance(axis, int):
        axis = (axis,)
    return tuple(a % ndim for a in axis)


def _bcast_reduce_grad(dy, shape, axis, keepdims):
    if axis is None:
        return jnp.broadcast_to(dy, shape)
    if not keepdims:
        for a in sorted(_norm_axes(axis, len(shape))):
            dy = jnp.expand_dims(dy, a)
    return jnp.broadcast_to(dy, shape)


def reduce_sum(a, axis=None, keepdims=False):
    return ReduceSum(axis, keepdims)(a)


def reduce_mean(a, axis=None, keepdims=False):
    return ReduceMean(axis, keepdims)(a)


def reduce_max(a, axis=None, keepdims=False):
    return ReduceMax(axis, keepdims)(a)


def reduce_min(a, axis=None, keepdims=False):
    return ReduceMin(axis, keepdims)(a)


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

class SoftmaxCrossEntropy(Operator):
    """Fused logits->loss with the classic (p - t)/N backward.

    Targets: int class ids (any leading batch dims) or one-hot/probs.
    """

    def forward(self, logits, target):
        self._dtype = logits.dtype
        self._shape = logits.shape
        V = logits.shape[-1]
        # softmax in f32 regardless of compute dtype (bf16 logits with a
        # 100k vocab lose the loss signal otherwise)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        self._n = float(np.prod(logits.shape[:-1]))
        self._p = jnp.exp(logp)
        if jnp.issubdtype(target.dtype, jnp.integer):
            # gather the target log-prob — never materialize a (N, V) one-hot.
            # out-of-range ids (e.g. -1 padding labels) are ignored: zero
            # loss AND zero gradient for those rows.
            tgt = target.reshape(-1)
            self._valid = (tgt >= 0) & (tgt < V)
            self._tgt = jnp.clip(tgt, 0, V - 1)
            picked = jnp.take_along_axis(logp.reshape(-1, V),
                                         self._tgt[:, None], axis=-1)[:, 0]
            return -jnp.sum(jnp.where(self._valid, picked, 0.0)) / self._n
        self._tgt = None
        self._t = target.astype(jnp.float32)
        return -jnp.sum(self._t * logp) / self._n

    def backward(self, dy):
        V = self._shape[-1]
        if self._tgt is not None:
            n = self._tgt.shape[0]
            g = self._p.reshape(-1, V).at[jnp.arange(n), self._tgt].add(-1.0)
            g = jnp.where(self._valid[:, None], g, 0.0)
        else:
            g = self._p.reshape(-1, V) - self._t.reshape(-1, V)
        g = (dy * g / self._n).reshape(self._shape).astype(self._dtype)
        return (g, None)


class FusedLinearCrossEntropy(Operator):
    """lm-head matmul + softmax-CE fused with row chunking: the (n, V)
    logits are never materialized.  Forward maps over row chunks keeping
    only the per-row logsumexp; backward recomputes each chunk's logits
    under lax.scan, accumulating dW in f32.  Peak activation memory
    drops from O(n*V) to O(chunk*V) (≈1 GB -> 64 MB for the bench
    Llama at 32k vocab), at the cost of one extra lm-head matmul in
    backward — the classic memory-lean large-vocab loss on TPU.

    Semantics match SoftmaxCrossEntropy(matmul(h, W), tgt) for INTEGER
    class-id targets (the only kind supported here — one-hot/probability
    targets are rejected): softmax in f32, mean over ALL rows,
    out-of-range ids (e.g. -1 padding) contribute zero loss and zero
    gradient."""

    def __init__(self, chunk_rows: int = 512):
        super().__init__()
        self.chunk = int(chunk_rows)

    def forward(self, h, w, target):
        if not jnp.issubdtype(target.dtype, jnp.integer):
            raise TypeError(
                "fused_linear_cross_entropy needs integer class-id "
                f"targets, got dtype {target.dtype}; use "
                "softmax_cross_entropy(matmul(h, w), target) for "
                "one-hot/probability targets")
        n, d = h.shape
        V = w.shape[-1]
        self._hdtype, self._wdtype = h.dtype, w.dtype
        c = min(self.chunk, n)
        nch = -(-n // c)
        pad = nch * c - n
        tgt = target.reshape(-1)
        valid = (tgt >= 0) & (tgt < V)
        tgtc = jnp.clip(tgt, 0, V - 1).astype(jnp.int32)
        if pad:
            h = jnp.concatenate([h, jnp.zeros((pad, d), h.dtype)], 0)
            valid = jnp.concatenate(
                [valid, jnp.zeros((pad,), valid.dtype)], 0)
            tgtc = jnp.concatenate([tgtc, jnp.zeros((pad,), tgtc.dtype)], 0)
        wc = w.astype(h.dtype) if w.dtype != h.dtype else w
        hch = h.reshape(nch, c, d)
        tch = tgtc.reshape(nch, c)

        def chunk_fwd(args):
            hc, tc = args
            lg = jnp.dot(hc, wc, preferred_element_type=jnp.float32)
            lse = jax.scipy.special.logsumexp(lg, axis=-1)
            zt = jnp.take_along_axis(lg, tc[:, None], axis=-1)[:, 0]
            return lse, zt

        lse, zt = jax.lax.map(chunk_fwd, (hch, tch))
        self._n = float(n)
        self._save = (hch, wc, tch, valid.reshape(nch, c), lse)
        self._meta = (n, d, V, c, nch)
        delta = jnp.where(valid, (lse - zt).reshape(-1), 0.0)
        return jnp.sum(delta) / self._n

    def backward(self, dy):
        hch, wc, tch, vch, lsech = self._save
        n, d, V, c, nch = self._meta
        scale = dy / self._n

        def step(dw_acc, args):
            hc, tc, vc, lsec = args
            lg = jnp.dot(hc, wc, preferred_element_type=jnp.float32)
            p = jnp.exp(lg - lsec[:, None])
            g = p.at[jnp.arange(c), tc].add(-1.0)
            g = (jnp.where(vc[:, None], g, 0.0) * scale).astype(hc.dtype)
            dw_acc = dw_acc + jnp.dot(hc.T, g,
                                      preferred_element_type=jnp.float32)
            dh = jnp.dot(g, wc.T, preferred_element_type=jnp.float32)
            return dw_acc, dh.astype(hc.dtype)

        dw0 = jnp.zeros((d, V), jnp.float32)
        dw, dhch = jax.lax.scan(step, dw0, (hch, tch, vch, lsech))
        dh = dhch.reshape(nch * c, d)[:n]
        return (dh.astype(self._hdtype), dw.astype(self._wdtype), None)


def fused_linear_cross_entropy(h, w, target, chunk_rows: int = 512):
    """Chunked fused `softmax_cross_entropy(matmul(h, w), target)` that
    never materializes the (n, V) logits (FusedLinearCrossEntropy)."""
    target = _as_int_or_t(target, h)
    return FusedLinearCrossEntropy(chunk_rows)(h, w, target)


class MSELoss(Operator):
    def forward(self, x, t):
        self._d = x - t
        self._n = float(np.prod(x.shape))
        return jnp.sum(self._d * self._d) / self._n

    def backward(self, dy):
        g = dy * 2.0 * self._d / self._n
        return (g, -g)


class BinaryCrossEntropy(Operator):
    def fwd(self, p, t):
        eps = 1e-7
        p = jnp.clip(p, eps, 1 - eps)
        return -jnp.mean(t * jnp.log(p) + (1 - t) * jnp.log(1 - p))


class NLLLoss(Operator):
    """Negative log-likelihood over log-probabilities + int targets."""

    def forward(self, logp, target):
        n = float(np.prod(target.shape))
        onehot = jax.nn.one_hot(target, logp.shape[-1], dtype=logp.dtype)
        self._t, self._n = onehot, n
        return -jnp.sum(onehot * logp) / n

    def backward(self, dy):
        return (-dy * self._t / self._n, None)


def softmax_cross_entropy(logits, target):
    target = _as_int_or_t(target, logits)
    return SoftmaxCrossEntropy()(logits, target)


# the reference exposes this op pair under both names
cross_entropy = softmax_cross_entropy


def mse_loss(x, t):
    return MSELoss()(x, _as_t(t, x))


def binary_cross_entropy(p, t):
    return BinaryCrossEntropy()(p, _as_t(t, p))


def nll_loss(logp, target):
    return NLLLoss()(logp, _as_int_or_t(target, logp))


def _as_int_or_t(x, like):
    if isinstance(x, Tensor):
        return x
    arr = jnp.asarray(x)
    return Tensor(data=arr, device=like.device, requires_grad=False)


class CosSim(Operator):
    def fwd(self, a, b):
        num = jnp.sum(a * b, axis=-1)
        den = jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1)
        return num / (den + 1e-8)


def cossim(a, b):
    return CosSim()(a, b)


# ---------------------------------------------------------------------------
# conv / pool / norm — NHWC layout (TPU-native; reference lineage is NCHW,
# we accept NCHW at the layer level and transpose once at the edge)
# ---------------------------------------------------------------------------

class Conv2d(Operator):
    """2-D convolution via lax.conv_general_dilated in NHWC/HWIO — the
    layout XLA:TPU maps straight onto the MXU."""

    def __init__(self, stride, padding, groups=1, dilation=1):
        super().__init__()
        self.stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
        self.dilation = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
        if isinstance(padding, str):
            self.padding = padding.upper()
        elif isinstance(padding, int):
            self.padding = [(padding, padding), (padding, padding)]
        else:
            self.padding = [tuple(p) if isinstance(p, (tuple, list)) else (p, p)
                            for p in padding]
        self.groups = groups

    def native_fwd(self, x, w, *b):
        # inference-only native conv (training uses the jnp/vjp path)
        if self.groups != 1 or self.dilation != (1, 1):
            return None
        if isinstance(self.padding, str):
            return None
        (pt, pb), (pl, pr) = self.padding
        if pt != pb or pl != pr:
            return None
        from . import _core
        y = _core.conv2d_nhwc(x, w, self.stride, (pt, pl))
        if b:
            y = y + b[0]
        return y

    def fwd(self, x, w, *b):
        # no preferred_element_type: the MXU already accumulates bf16
        # convs in f32 internally, and requesting an f32 output makes the
        # vjp transpose mix bf16 primals with f32 cotangents (TypeError)
        y = jax.lax.conv_general_dilated(
            x, w, window_strides=self.stride, padding=self.padding,
            rhs_dilation=self.dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.groups,
        )
        if b:
            y = y + b[0]
        return y.astype(x.dtype)


class MaxPool2d(Operator):
    def __init__(self, kernel, stride, padding=0):
        super().__init__()
        self.kernel = (kernel, kernel) if isinstance(kernel, int) else tuple(kernel)
        self.stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
        self.padding = padding

    def fwd(self, x):  # NHWC
        pads = ((0, 0), (self.padding, self.padding),
                (self.padding, self.padding), (0, 0))
        return jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max,
            (1,) + self.kernel + (1,), (1,) + self.stride + (1,), pads)


class AvgPool2d(Operator):
    def __init__(self, kernel, stride, padding=0, count_include_pad=True):
        super().__init__()
        self.kernel = (kernel, kernel) if isinstance(kernel, int) else tuple(kernel)
        self.stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
        self.padding = padding

    def fwd(self, x):
        pads = ((0, 0), (self.padding, self.padding),
                (self.padding, self.padding), (0, 0))
        s = jax.lax.reduce_window(
            x, 0.0, jax.lax.add,
            (1,) + self.kernel + (1,), (1,) + self.stride + (1,), pads)
        return s / float(self.kernel[0] * self.kernel[1])


class BatchNorm(Operator):
    """Training-mode batchnorm over NHWC (reduce N,H,W). Running stats are
    updated OUTSIDE the op (layer owns them as state) so the op stays pure.
    """

    def __init__(self, eps):
        super().__init__()
        self.eps = eps

    def fwd(self, x, gamma, beta, mean, var):
        xf = x.astype(jnp.float32)
        inv = jax.lax.rsqrt(var.astype(jnp.float32) + self.eps)
        return ((xf - mean) * inv * gamma + beta).astype(x.dtype)


class LayerNorm(Operator):
    def __init__(self, eps=1e-5):
        super().__init__()
        self.eps = eps

    def fwd(self, x, gamma, beta):
        # stats in f32 (bf16 mean/var loses precision), output in x dtype;
        # f32 master gamma/beta are cast so they don't re-promote bf16
        xf = x.astype(jnp.float32)
        mu = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
        y = (xf - mu) * jax.lax.rsqrt(var + self.eps) * gamma + beta
        return y.astype(x.dtype)


class RMSNorm(Operator):
    def __init__(self, eps=1e-6):
        super().__init__()
        self.eps = eps

    def fwd(self, x, gamma):
        # norm in f32 for stability, output in input dtype (llama-style)
        xf = x.astype(jnp.float32)
        ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
        return (xf * jax.lax.rsqrt(ms + self.eps) * gamma).astype(x.dtype)


def conv2d(x, w, b=None, stride=1, padding=0, groups=1, dilation=1):
    op = Conv2d(stride, padding, groups, dilation)
    if b is None:
        return op(x, w)
    return op(x, w, b)


def max_pool2d(x, kernel, stride=None, padding=0):
    return MaxPool2d(kernel, stride or kernel, padding)(x)


def avg_pool2d(x, kernel, stride=None, padding=0):
    return AvgPool2d(kernel, stride or kernel, padding)(x)


def batchnorm(x, gamma, beta, mean, var, eps=1e-5):
    return BatchNorm(eps)(x, gamma, beta, mean, var)


def layernorm(x, gamma, beta, eps=1e-5):
    return LayerNorm(eps)(x, gamma, beta)


def rmsnorm(x, gamma, eps=1e-6):
    return RMSNorm(eps)(x, gamma)


# ---------------------------------------------------------------------------
# breadth ops toward the reference lineage's ~90-operator surface
# (SURVEY.md §2.2 row 6; VERDICT r2 item 10).  fwd-only definitions
# inherit the jax.vjp backward; comparison/logical ops are marked
# non-differentiable so their integer/bool outputs never enter the tape.
# ---------------------------------------------------------------------------

class Sin(Operator):
    def fwd(self, a):
        return jnp.sin(a)


class Cos(Operator):
    def fwd(self, a):
        return jnp.cos(a)


class Tan(Operator):
    def fwd(self, a):
        return jnp.tan(a)


class Asin(Operator):
    def fwd(self, a):
        return jnp.arcsin(a)


class Acos(Operator):
    def fwd(self, a):
        return jnp.arccos(a)


class Atan(Operator):
    def fwd(self, a):
        return jnp.arctan(a)


class Sinh(Operator):
    def fwd(self, a):
        return jnp.sinh(a)


class Cosh(Operator):
    def fwd(self, a):
        return jnp.cosh(a)


class Asinh(Operator):
    def fwd(self, a):
        return jnp.arcsinh(a)


class Acosh(Operator):
    def fwd(self, a):
        return jnp.arccosh(a)


class Atanh(Operator):
    def fwd(self, a):
        return jnp.arctanh(a)


class Ceil(Operator):
    def fwd(self, a):
        return jnp.ceil(a)


class Floor(Operator):
    def fwd(self, a):
        return jnp.floor(a)


class Round(Operator):
    def fwd(self, a):
        return jnp.round(a)


class Sign(Operator):
    def fwd(self, a):
        return jnp.sign(a)


class Reciprocal(Operator):
    def fwd(self, a):
        return 1.0 / a


class Minimum(Operator):
    def fwd(self, a, b):
        return jnp.minimum(a, b)


class Maximum(Operator):
    def fwd(self, a, b):
        return jnp.maximum(a, b)


class Mod(Operator):
    differentiable = False

    def fwd(self, a, b):
        return jnp.mod(a, b)


class Equal(Operator):
    differentiable = False

    def fwd(self, a, b):
        return a == b


class Greater(Operator):
    differentiable = False

    def fwd(self, a, b):
        return a > b


class GreaterEqual(Operator):
    differentiable = False

    def fwd(self, a, b):
        return a >= b


class Less(Operator):
    differentiable = False

    def fwd(self, a, b):
        return a < b


class LessEqual(Operator):
    differentiable = False

    def fwd(self, a, b):
        return a <= b


class LogicalAnd(Operator):
    differentiable = False

    def fwd(self, a, b):
        return jnp.logical_and(a, b)


class LogicalOr(Operator):
    differentiable = False

    def fwd(self, a, b):
        return jnp.logical_or(a, b)


class LogicalXor(Operator):
    differentiable = False

    def fwd(self, a, b):
        return jnp.logical_xor(a, b)


class LogicalNot(Operator):
    differentiable = False

    def fwd(self, a):
        return jnp.logical_not(a)


class PReLU(Operator):
    """Parametric ReLU: slope is a LEARNED tensor input (second arg)."""

    def fwd(self, a, slope):
        return jnp.where(a > 0, a, slope * a)


class SELU(Operator):
    def fwd(self, a):
        return jax.nn.selu(a)


class HardSigmoid(Operator):
    def __init__(self, alpha=0.2, beta=0.5):
        super().__init__()
        self.alpha, self.beta = alpha, beta

    def fwd(self, a):
        return jnp.clip(self.alpha * a + self.beta, 0.0, 1.0)


class HardSwish(Operator):
    def fwd(self, a):
        return a * jnp.clip(a / 6.0 + 0.5, 0.0, 1.0)


class Mish(Operator):
    def fwd(self, a):
        return a * jnp.tanh(jax.nn.softplus(a))


class Tile(Operator):
    def __init__(self, reps):
        super().__init__()
        self.reps = tuple(reps) if hasattr(reps, "__len__") else (reps,)

    def fwd(self, a):
        return jnp.tile(a, self.reps)


class Repeat(Operator):
    def __init__(self, repeats, axis):
        super().__init__()
        self.repeats, self.axis = repeats, axis

    def fwd(self, a):
        return jnp.repeat(a, self.repeats, axis=self.axis)


class TensorDot(Operator):
    def __init__(self, axes):
        super().__init__()
        self.axes = axes

    def fwd(self, a, b):
        return jnp.tensordot(a, b, axes=self.axes)


class Expand(Operator):
    def __init__(self, shape):
        super().__init__()
        self.shape = tuple(shape)

    def fwd(self, a):
        return jnp.broadcast_to(a, self.shape)


class OneHot(Operator):
    differentiable = False

    def __init__(self, depth, axis=-1, dtype=jnp.float32):
        super().__init__()
        self.depth, self.axis, self.dtype = depth, axis, dtype

    def fwd(self, ids):
        return jax.nn.one_hot(ids, self.depth, axis=self.axis,
                              dtype=self.dtype)


class CumSum(Operator):
    def __init__(self, axis=0):
        super().__init__()
        self.axis = axis

    def fwd(self, a):
        return jnp.cumsum(a, axis=self.axis)


class ReduceProd(Operator):
    def __init__(self, axis=None, keepdims=False):
        super().__init__()
        self.axis, self.keepdims = axis, keepdims

    def fwd(self, a):
        return jnp.prod(a, axis=self.axis, keepdims=self.keepdims)


class Shape(Operator):
    differentiable = False

    def fwd(self, a):
        # int32: jax truncates int64 (and warns) unless x64 is enabled —
        # keep the output dtype environment-independent
        return jnp.asarray(a.shape, jnp.int32)


def sin(a): return Sin()(a)
def cos(a): return Cos()(a)
def tan(a): return Tan()(a)
def asin(a): return Asin()(a)
def acos(a): return Acos()(a)
def atan(a): return Atan()(a)
def sinh(a): return Sinh()(a)
def cosh(a): return Cosh()(a)
def asinh(a): return Asinh()(a)
def acosh(a): return Acosh()(a)
def atanh(a): return Atanh()(a)
def ceil(a): return Ceil()(a)
def floor(a): return Floor()(a)
def round(a): return Round()(a)   # noqa: A001 - reference op name
def sign(a): return Sign()(a)
def reciprocal(a): return Reciprocal()(a)
def minimum(a, b): return Minimum()(a, _as_t(b, a))
def maximum(a, b): return Maximum()(a, _as_t(b, a))
def mod(a, b): return Mod()(a, _as_t(b, a))
def equal(a, b): return Equal()(a, _as_t(b, a))
def greater(a, b): return Greater()(a, _as_t(b, a))
def greater_equal(a, b): return GreaterEqual()(a, _as_t(b, a))
def less(a, b): return Less()(a, _as_t(b, a))
def less_equal(a, b): return LessEqual()(a, _as_t(b, a))
def logical_and(a, b): return LogicalAnd()(a, _as_t(b, a))
def logical_or(a, b): return LogicalOr()(a, _as_t(b, a))
def logical_xor(a, b): return LogicalXor()(a, _as_t(b, a))
def logical_not(a): return LogicalNot()(a)
def prelu(a, slope): return PReLU()(a, slope)
def selu(a): return SELU()(a)
def hardsigmoid(a, alpha=0.2, beta=0.5): return HardSigmoid(alpha, beta)(a)
def hardswish(a): return HardSwish()(a)
def mish(a): return Mish()(a)
def tile(a, reps): return Tile(reps)(a)
def repeat(a, repeats, axis=None): return Repeat(repeats, axis)(a)
def tensordot(a, b, axes=2): return TensorDot(axes)(a, _as_t(b, a))
def expand(a, shape): return Expand(shape)(a)
def onehot(ids, depth, axis=-1): return OneHot(depth, axis)(ids)
def cumsum(a, axis=0): return CumSum(axis)(a)
def reduce_prod(a, axis=None, keepdims=False):
    return ReduceProd(axis, keepdims)(a)
def shape_of(a): return Shape()(a)


__all__ += [
    "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh", "asinh",
    "acosh", "atanh", "ceil", "floor", "round", "sign", "reciprocal",
    "minimum", "maximum", "mod", "equal", "greater", "greater_equal",
    "less", "less_equal", "logical_and", "logical_or", "logical_xor",
    "logical_not", "prelu", "selu", "hardsigmoid", "hardswish", "mish",
    "tile", "expand", "onehot", "cumsum", "reduce_prod", "shape_of",
    "repeat", "tensordot",
]
