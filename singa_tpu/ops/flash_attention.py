"""Flash attention — blockwise Pallas TPU kernel (forward + backward).

Online-softmax attention with O(block) VMEM: K/V stream through the
innermost grid dimension one (BLOCK_K, D) tile at a time while the
(running max, denominator, f32 accumulator) persist in VMEM scratch, so
sequence length is bounded by HBM, not VMEM — the long-context half of
the single-chip design (cross-chip sequence scaling is
ops.ring_attention).  Structure follows FlashAttention-2; backward
recomputes score tiles from the saved logsumexp with separate dQ and
dK/dV kernels.

TPU mapping (pallas_guide.md): QK^T and PV tiles ride the MXU via
jnp.dot(..., preferred_element_type=f32); tiles live in VMEM; causal
skips fully-masked tiles with pl.when; GQA maps G query heads onto one
kv head in the BlockSpec index map so grouped (Llama-3) attention needs
no head replication in HBM.  Causal masking is bottom-right aligned
(qpos + Tk - Tq >= kpos), matching the XLA reference for Tq != Tk
(KV-cached decoding).

Falls back to the XLA-fused reference for shapes the kernel does not
tile (T not a multiple of 128, tiny head dims) and off-TPU; interpret
mode runs the same kernels on CPU for tests.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "flash_attention_with_lse"]

_NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


def _causal_ids(qi, kj, block_q, block_k, off):
    qpos = qi * block_q + off + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    kpos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return qpos, kpos


# ---------------------------------------------------------------------------
# forward: grid (B, H, nq, nkv); kv streams innermost; acc/m/l in scratch
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale, causal, block_q, block_k, off, window=None):
    qi, kj = pl.program_id(2), pl.program_id(3)
    nkv = pl.num_programs(3)

    @pl.when(kj == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: skip tiles where even the last q row precedes the first
    # key; window: also skip tiles entirely below the band (every key
    # older than first-query-pos - W)
    live = True
    if causal:
        live = (qi * block_q + block_q - 1 + off) >= kj * block_k
    if window is not None:
        live = live & (kj * block_k + block_k - 1
                       > qi * block_q + off - window)

    @pl.when(live)
    def _():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal or window is not None:
            qpos, kpos = _causal_ids(qi, kj, block_q, block_k, off)
            if causal:
                s = jnp.where(qpos >= kpos, s, _NEG_INF)
            if window is not None:
                s = jnp.where(kpos > qpos - window, s, _NEG_INF)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.dot(p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = m_new

    @pl.when(kj == nkv - 1)
    def _():
        l = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)
        # lse carried as (bq, 1): Mosaic requires the trailing two block
        # dims be (mult-of-8, mult-of-128 | full-dim), which (bq, 1) over
        # a (B, H, Tq, 1) array satisfies and (1, bq) over (B, H, Tq)
        # does not (the v5e ValueError from BENCH_r02).
        lse_ref[0, 0] = m_ref[:] + jnp.log(l)


# ---------------------------------------------------------------------------
# backward: dQ streams kv innermost; dK/dV streams q innermost
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc, *, scale, causal, block_q, block_k, off,
                   window=None):
    qi, kj = pl.program_id(2), pl.program_id(3)
    nkv = pl.num_programs(3)

    @pl.when(kj == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    live = True
    if causal:
        live = (qi * block_q + block_q - 1 + off) >= kj * block_k
    if window is not None:
        live = live & (kj * block_k + block_k - 1
                       > qi * block_q + off - window)

    @pl.when(live)
    def _():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse)
        if causal or window is not None:
            qpos, kpos = _causal_ids(qi, kj, block_q, block_k, off)
            if causal:
                p = jnp.where(qpos >= kpos, p, 0.0)
            if window is not None:
                p = jnp.where(kpos > qpos - window, p, 0.0)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_acc[:] = dq_acc[:] + jnp.dot(ds, k,
                                        preferred_element_type=jnp.float32)

    @pl.when(kj == nkv - 1)
    def _():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    block_q, block_k, off, window=None):
    kj, qi = pl.program_id(2), pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    live = True
    if causal:
        live = (qi * block_q + block_q - 1 + off) >= kj * block_k
    if window is not None:
        live = live & (kj * block_k + block_k - 1
                       > qi * block_q + off - window)

    @pl.when(live)
    def _():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse)                              # (BQ, BK)
        if causal or window is not None:
            qpos, kpos = _causal_ids(qi, kj, block_q, block_k, off)
            if causal:
                p = jnp.where(qpos >= kpos, p, 0.0)
            if window is not None:
                p = jnp.where(kpos > qpos - window, p, 0.0)
        dv_acc[:] = dv_acc[:] + jnp.dot(p.T, do,
                                        preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_acc[:] = dk_acc[:] + jnp.dot(ds.T, q,
                                        preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call drivers over (B, H, T, D) layout
# ---------------------------------------------------------------------------

def _block_sizes(seq_q, seq_k):
    """Tile sizes for the kernel grid.  SINGA_FLASH_BLOCK="bq,bk"
    overrides for tuning (each must divide its sequence length and be a
    multiple of 128; invalid overrides fall back to the default)."""
    import os
    override = os.environ.get("SINGA_FLASH_BLOCK")
    if override:
        try:
            bq, bk = (int(v) for v in override.split(","))
            if (bq % 128 == 0 and bk % 128 == 0 and bq > 0 and bk > 0
                    and seq_q % bq == 0 and seq_k % bk == 0):
                return bq, bk
        except ValueError:
            pass
    # 512 tiles measured fastest on v5e (r4 sweep: 189 ms/step vs
    # 254 ms at 256 for the bench Llama — 4x fewer grid steps amortize
    # per-step grid overhead; VMEM comfortably fits 512x64 q/k/v tiles)
    def best(seq):
        for b in (512, 256, 128):
            if seq % b == 0:
                return b
        return 128
    return best(seq_q), best(seq_k)


def _fwd(q, k, v, causal, scale, interpret, window=None):
    B, H, Tq, D = q.shape
    K, Tk = k.shape[1], k.shape[2]
    G = H // K
    bq, bk = _block_sizes(Tq, Tk)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=bq, block_k=bk, off=Tk - Tq,
                               window=window)
    o, lse = pl.pallas_call(
        kernel,
        grid=(B, H, Tq // bq, Tk // bk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, D),
                         lambda b, h, i, j, G=G: (b, h // G, j, 0)),
            pl.BlockSpec((1, 1, bk, D),
                         lambda b, h, i, j, G=G: (b, h // G, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Tq, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


def _bwd(q, k, v, o, lse, do, causal, scale, interpret, dlse=None,
         window=None):
    B, H, Tq, D = q.shape
    K, Tk = k.shape[1], k.shape[2]
    G = H // K
    bq, bk = _block_sizes(Tq, Tk)
    off = Tk - Tq
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
                    keepdims=True)                        # (B, H, Tq, 1)
    if dlse is not None:
        # lse cotangent folds into delta: ds = p * (dp - delta + dlse)
        # (∂lse_i/∂s_ij = p_ij), so delta_eff = delta - dlse
        delta = delta - dlse.reshape(B, H, Tq, 1).astype(jnp.float32)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, off=off, window=window),
        grid=(B, H, Tq // bq, Tk // bk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, D),
                         lambda b, h, i, j, G=G: (b, h // G, j, 0)),
            pl.BlockSpec((1, 1, bk, D),
                         lambda b, h, i, j, G=G: (b, h // G, j, 0)),
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, Tq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dk/dv per *query* head (grid over H), reduced over each GQA group
    # outside the kernel — avoids cross-program accumulation
    dk_p, dv_p = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, off=off, window=window),
        grid=(B, H, Tk // bk, Tq // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, j, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, D),
                         lambda b, h, j, i, G=G: (b, h // G, j, 0)),
            pl.BlockSpec((1, 1, bk, D),
                         lambda b, h, j, i, G=G: (b, h // G, j, 0)),
            pl.BlockSpec((1, 1, bq, D), lambda b, h, j, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, j, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, j, i: (b, h, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j, i: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j, i: (b, h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Tk, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Tk, D), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    if G > 1:
        dk = dk_p.reshape(B, K, G, Tk, D).sum(axis=2).astype(k.dtype)
        dv = dv_p.reshape(B, K, G, Tk, D).sum(axis=2).astype(v.dtype)
    else:
        dk, dv = dk_p, dv_p
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-vjp core in (B, H, T, D) layout
# ---------------------------------------------------------------------------

def _flash_core(q, k, v, causal, scale, interpret, window=None):
    """o-only view over the (o, lse) core; the lse cotangent is zeros,
    which _bwd folds in for free (delta - 0)."""
    return _flash_core_lse(q, k, v, causal, scale, interpret, window)[0]


# -- (o, lse) core: also the building block for cross-chip ring attention --

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_core_lse(q, k, v, causal, scale, interpret, window=None):
    return _fwd(q, k, v, causal, scale, interpret, window)


def _flash_core_lse_fwd(q, k, v, causal, scale, interpret, window=None):
    o, lse = _fwd(q, k, v, causal, scale, interpret, window)
    return (o, lse), (q, k, v, o, lse)


def _flash_core_lse_bwd(causal, scale, interpret, window, res, cots):
    q, k, v, o, lse = res
    do, dlse = cots
    return _bwd(q, k, v, o, lse, do, causal, scale, interpret, dlse=dlse,
                window=window)


_flash_core_lse.defvjp(_flash_core_lse_fwd, _flash_core_lse_bwd)


def flash_attention_with_lse(q, k, v, causal: bool = False,
                             scale: float = None, interpret: bool = None):
    """(B, H, T, D)-layout flash attention returning (o, lse) with lse
    differentiable — the per-block primitive ring attention combines
    across chips (lse (B, H, Tq, 1) f32).  No XLA fallback: shapes that
    don't tile raise (a silent fallback here would skip tail rows)."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    if not _tileable(Tq, Tk, D) or H % k.shape[1] != 0:
        raise ValueError(
            f"flash_attention_with_lse needs tiling shapes "
            f"(T % 128 == 0, D >= 32, D % 8 == 0); got Tq={Tq}, Tk={Tk}, "
            f"D={D}, H={H}, K={k.shape[1]}")
    scale = scale or (1.0 / math.sqrt(q.shape[-1]))
    if interpret is None:
        interpret = not _on_tpu()
    return _flash_core_lse(q, k, v, bool(causal), float(scale),
                           bool(interpret))


def _tileable(Tq, Tk, D) -> bool:
    return Tq % 128 == 0 and Tk % 128 == 0 and D >= 32 and D % 8 == 0


def flash_attention(q, k, v, causal: bool = False, scale: float = None,
                    interpret: bool = None, window: int = None):
    """(B, T, H, D) attention; k/v may have fewer heads (GQA, H % K == 0)
    or a longer sequence (KV cache; causal is bottom-right aligned).
    `window`: Mistral-style sliding window — banded tiles below the
    band are skipped entirely (requires causal=True).

    Uses the Pallas kernel when shapes tile onto the hardware, else the
    XLA-fused reference (same math, O(T^2) logits)."""
    from .attention import _sdpa_reference

    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True (the band is "
                             "causal by definition)")
        if window < 1:
            raise ValueError(
                f"window must be >= 1, got {window} (0 would mask every "
                "key; use window=None for full causal attention)")
    scale = scale or (1.0 / math.sqrt(q.shape[-1]))
    B, Tq, H, D = q.shape
    Tk, K = k.shape[1], k.shape[2]
    if not _tileable(Tq, Tk, D) or H % K != 0:
        if window is not None:
            from .attention import _banded_reference
            return _banded_reference(q, k, v, window, scale)
        return _sdpa_reference(q, k, v, causal, None, scale)
    if interpret is None:
        interpret = not _on_tpu()
    # (B, T, H, D) -> (B, H, T, D) for contiguous per-head tiles
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    o = _flash_core(qh, kh, vh, causal, float(scale), bool(interpret),
                    None if window is None else int(window))
    return jnp.swapaxes(o, 1, 2)
