"""Flash attention — blockwise attention kernel (Pallas TPU).

Milestone note: the Pallas kernel lands with the transformer-model
milestone; until then this module provides the same signature backed by
the XLA-fused reference computation so callers never break.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

__all__ = ["flash_attention"]


def flash_attention(q, k, v, causal: bool = False, scale: float = None):
    from .attention import _sdpa_reference
    scale = scale or (1.0 / math.sqrt(q.shape[-1]))
    return _sdpa_reference(q, k, v, causal, None, scale)
