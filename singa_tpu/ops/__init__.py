"""singa_tpu.ops — fused / hand-tuned ops for the TPU hot path.

Where XLA fusion suffices we use plain jnp (it usually does); Pallas
kernels live here for the ops where it doesn't (attention — SURVEY.md
§7.2 step 7).
"""

from . import attention
from .attention import attention as fused_attention
from .rope import (apply_rope, llama31_rope_scaling,
                   rope_frequencies)
from .ring_attention import ring_attention, ring_attention_local

__all__ = ["attention", "fused_attention", "apply_rope", "rope_frequencies",
           "llama31_rope_scaling",
           "ring_attention", "ring_attention_local"]
