"""Mixture-of-Experts with expert parallelism (the 'expert' mesh axis).

Not a reference capability (SURVEY.md §2.3: the reference's only
strategy is DP) — this is the TPU-native extension that completes the
framework's parallelism axes (dp/tp/sp/pp/ep).  Formulation follows the
GShard/Switch static-shape recipe, which is what XLA partitions well:

  * router: (N, D) -> (N, E) logits -> top-1 gate with a static expert
    capacity C = ceil(cf * N / E);
  * dispatch: two equivalent token-movement formulations sharing one
    router (`_route`): gather/SCATTER into the (E, C, D) buffers
    (O(k*N*D) memory ops — the single-chip default; the one-hot
    einsums cost O(cf*k*N^2*D) MAC, quadratic in tokens, and were the
    whole 0.16-MFU story on chip in r4) and the one-hot EINSUM form
    (the EP default: GSPMD partitions it into all-to-alls over ICI).
    NO dynamic shapes in either; dropped tokens (over capacity) pass
    through with zero expert contribution;
  * expert compute: (E, C, D) batched einsums over stacked expert
    weights, leading E axis sharded over the 'expert' mesh axis;
  * combine: gate-weighted gather back to (N, D).

Everything is pure jnp (fwd differentiates via jax.vjp), so the whole
MoE layer compiles into the model's single step module like any other
op; router load-balance auxiliary loss follows Switch (mean fraction *
mean probability per expert).
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = ["moe_dispatch", "moe_forward", "load_balance_loss"]


def moe_dispatch(logits, capacity: int, k: int = 1):
    """Top-k routing with static capacity (k=1: Switch; k=2: GShard).

    logits: (N, E).  Returns (combine (N, E, C) f32, probs (N, E),
    onehot (N, E) of the FIRST choice — the balance loss follows the
    primary assignment).  combine[n, e, c] is token n's gate weight at
    slot c of expert e (0 everywhere else; 0 for dropped assignments).
    Gates renormalize over the k selected experts; capacity slots fill
    rank-major (every token's first choice outranks any second choice,
    the GShard priority)."""
    N, E = logits.shape
    # one router for both dispatch formulations (_route): identical
    # softmax/top-k/gating/rank-major slot positions as the scatter path
    e_flat, gate_flat, pos, keep, probs, onehot = _route(logits, capacity, k)
    oh = jax.nn.one_hot(e_flat, E, dtype=jnp.float32)  # (k*N, E)
    slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                          dtype=jnp.float32)           # (k*N, C)
    contrib = (oh * (gate_flat * keep)[:, None])[:, :, None] \
        * slot[:, None, :]                             # (k*N, E, C)
    combine = jnp.sum(contrib.reshape(k, N, E, capacity), axis=0)
    return combine, probs, onehot


def load_balance_loss(probs, onehot):
    """Switch aux loss: E * sum_e mean_n(frac_e) * mean_n(prob_e)."""
    E = probs.shape[-1]
    frac = jnp.mean(onehot, axis=0)
    prob = jnp.mean(probs, axis=0)
    return E * jnp.sum(frac * prob)


def _route(logits, capacity: int, k: int):
    """Shared top-k routing state, rank-major (GShard priority: every
    token's first choice outranks any second choice for a slot).

    Returns (e_flat (k*N,) expert ids, gate_flat (k*N,) f32 gates,
    pos (k*N,) slot index within the expert, keep (k*N,) bool,
    probs (N, E), onehot (N, E) of the first choice)."""
    N, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    topv, topi = jax.lax.top_k(probs, k)               # (N, k)
    gates = topv if k == 1 else \
        topv / jnp.sum(topv, axis=-1, keepdims=True)
    e_flat = topi.T.reshape(-1)                        # rank-major (k*N,)
    oh = jax.nn.one_hot(e_flat, E, dtype=jnp.float32)
    pos = jnp.sum(jnp.cumsum(oh, axis=0) * oh - oh, axis=-1)  # (k*N,)
    keep = pos < capacity
    onehot = jax.nn.one_hot(topi[:, 0], E, dtype=jnp.float32)
    return e_flat, gates.T.reshape(-1), pos, keep, probs, onehot


_warned_auto_trace = False


def _warn_auto_under_trace(x, resolved: str) -> None:
    """dispatch_mode='auto' resolved the global mesh while tracing: the
    choice is baked into this jit cache entry and will NOT re-resolve if
    the mesh changes later (the cache is not keyed on the mesh global).
    Warn ONCE per process so raw-jit users learn to pass an explicit
    mode; the model executor re-traces per compile and is fine."""
    global _warned_auto_trace
    if _warned_auto_trace or not isinstance(x, jax.core.Tracer):
        return
    _warned_auto_trace = True
    import warnings
    warnings.warn(
        f"moe_forward(dispatch_mode='auto') resolved to {resolved!r} at "
        "trace time from the global mesh; the jit cache is not keyed on "
        "that global, so a later set_mesh() will NOT re-route already-"
        "jitted callers.  Pass dispatch_mode='scatter'/'einsum' "
        "explicitly when jitting moe_forward directly around mesh "
        "changes.", stacklevel=3)


def _expert_ffn(buf, w_in, w_out, w_gate):
    """(E, C, D) expert buffers -> (E, C, D) outputs (relu or SwiGLU)."""
    up = jnp.einsum("ecd,edh->ech", buf, w_in.astype(buf.dtype))
    if w_gate is not None:
        h = jax.nn.silu(jnp.einsum("ecd,edh->ech", buf,
                                   w_gate.astype(buf.dtype))) * up
    else:
        h = jax.nn.relu(up)
    return jnp.einsum("ech,ehd->ecd", h, w_out.astype(buf.dtype))


def moe_forward(x, router_w, w_in, w_out, capacity_factor: float = 1.25,
                return_aux: bool = False, top_k: int = 1, w_gate=None,
                dispatch_mode: str = "auto"):
    """Top-k MoE FFN over flattened tokens (k=1 Switch, k=2 GShard).

    x: (..., D); router_w: (D, E); w_in: (E, D, H); w_out: (E, H, D).
    Expert e computes relu(x @ w_in[e]) @ w_out[e] — or, with `w_gate`
    (E, D, H) given, the SwiGLU form silu(x @ w_gate[e]) * (x @
    w_in[e]) @ w_out[e] (Mixtral-style experts).  Shard the stacked
    weights' leading axis over the 'expert' mesh axis (SHARD_RULES)
    for EP.

    dispatch_mode:
      * 'scatter' — gather/scatter token movement: O(k*N*D) memory ops
        into the (E, C, D) buffers and back.  Default off-mesh: the
        one-hot einsums below cost O(cf*k*N^2*D) MAC each — quadratic
        in token count and pure overhead (r4 on-chip MoE MFU 0.1585;
        scatter dispatch removed the einsums' N^2 term, r5).
      * 'einsum' — GShard one-hot dispatch/combine einsums.  Default
        when an 'expert' mesh axis is live: GSPMD partitions einsums
        over E into all-to-alls cleanly, which is the EP wire format.
      * 'auto' — scatter without an EP axis, einsum with one.
        CAVEAT: 'auto' reads the global `parallel.mesh.current_mesh()`
        AT TRACE TIME, and the jit cache is NOT keyed on that global —
        a function jitted before the 'expert' mesh is installed stays
        cached on the scatter path (numerics identical; the einsum
        all-to-all wire format is what's silently missed).  The model
        executor re-traces per compile so it is unaffected, but code
        that jits `moe_forward` directly around mesh changes should
        pass an explicit mode (the `MoE` layer forwards its
        `dispatch_mode` argument for exactly this).  A one-time warning
        fires when 'auto' resolves under a trace.

    Both modes share `_route` (identical routing, gating, capacity
    drops) and are equivalence-tested against each other."""
    orig_shape = x.shape
    D = orig_shape[-1]
    xf = x.reshape(-1, D)
    N = xf.shape[0]
    E = router_w.shape[-1]
    # capacity covers the k-fold assignment load at the same factor
    capacity = max(1, math.ceil(capacity_factor * top_k * N / E))

    logits = xf.astype(jnp.float32) @ router_w.astype(jnp.float32)
    if dispatch_mode == "auto":
        from ..parallel import mesh as mesh_mod
        m = mesh_mod.current_mesh()
        ep = m is not None and m.shape.get("expert", 1) > 1
        dispatch_mode = "einsum" if ep else "scatter"
        _warn_auto_under_trace(x, dispatch_mode)

    if dispatch_mode == "scatter":
        e_flat, gate_flat, pos, keep, probs, onehot = _route(
            logits, capacity, top_k)
        # dropped assignments write out of bounds -> mode='drop' elides
        pos_i = jnp.where(keep, pos, capacity).astype(jnp.int32)
        tok = jnp.tile(jnp.arange(N), top_k)
        xs = xf[tok]                                   # (k*N, D)
        buf = jnp.zeros((E, capacity, D), xf.dtype) \
            .at[e_flat, pos_i].set(xs, mode="drop")
        y = _expert_ffn(buf, w_in, w_out, w_gate)      # (E, C, D)
        # combine: gather each assignment's expert output, gate, sum k
        w = (gate_flat * keep).astype(xf.dtype)
        out_a = y[e_flat, jnp.clip(pos_i, 0, capacity - 1)] * w[:, None]
        out = jnp.sum(out_a.reshape(top_k, N, D), axis=0)
    else:
        combine, probs, onehot = moe_dispatch(logits, capacity, top_k)
        dispatch = (combine > 0).astype(xf.dtype)      # (N, E, C)
        # dispatch tokens into per-expert buffers: (E, C, D)
        buf = jnp.einsum("nec,nd->ecd", dispatch, xf)
        y = _expert_ffn(buf, w_in, w_out, w_gate)
        # gate-weighted combine back to tokens
        out = jnp.einsum("nec,ecd->nd", combine.astype(xf.dtype), y)
    out = out.astype(xf.dtype).reshape(orig_shape)
    if return_aux:
        return out, load_balance_loss(probs, onehot)
    return out
