"""Mixture-of-Experts with expert parallelism (the 'expert' mesh axis).

Not a reference capability (SURVEY.md §2.3: the reference's only
strategy is DP) — this is the TPU-native extension that completes the
framework's parallelism axes (dp/tp/sp/pp/ep).  Formulation follows the
GShard/Switch static-shape recipe, which is what XLA partitions well:

  * router: (N, D) -> (N, E) logits -> top-1 gate with a static expert
    capacity C = ceil(cf * N / E);
  * dispatch: a one-hot (N, E, C) combine tensor built with cumsum
    position indexing — NO dynamic shapes, dropped tokens (over
    capacity) pass through with zero expert contribution;
  * expert compute: (E, C, D) batched einsums over stacked expert
    weights — sharding the leading E axis over the 'expert' mesh axis
    turns the dispatch/combine einsums into XLA all-to-alls over ICI;
  * combine: gate-weighted gather back to (N, D).

Everything is pure jnp (fwd differentiates via jax.vjp), so the whole
MoE layer compiles into the model's single step module like any other
op; router load-balance auxiliary loss follows Switch (mean fraction *
mean probability per expert).
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = ["moe_dispatch", "moe_forward", "load_balance_loss"]


def moe_dispatch(logits, capacity: int, k: int = 1):
    """Top-k routing with static capacity (k=1: Switch; k=2: GShard).

    logits: (N, E).  Returns (combine (N, E, C) f32, probs (N, E),
    onehot (N, E) of the FIRST choice — the balance loss follows the
    primary assignment).  combine[n, e, c] is token n's gate weight at
    slot c of expert e (0 everywhere else; 0 for dropped assignments).
    Gates renormalize over the k selected experts; capacity slots fill
    rank-major (every token's first choice outranks any second choice,
    the GShard priority)."""
    N, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    topv, topi = jax.lax.top_k(probs, k)               # (N, k)
    # Switch (k=1) gates with the RAW top probability (router gradient
    # flows through the gate); GShard (k>1) renormalizes over the k
    # selected experts
    gates = topv if k == 1 else \
        topv / jnp.sum(topv, axis=-1, keepdims=True)
    # rank-major flattening: (k*N, E); cumsum gives globally consistent
    # slot positions with rank-0 assignments filling first
    oh = jax.nn.one_hot(topi.T.reshape(-1), E, dtype=jnp.float32)
    pos = jnp.sum(jnp.cumsum(oh, axis=0) * oh - oh, axis=-1)  # (k*N,)
    keep = pos < capacity
    slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                          dtype=jnp.float32)           # (k*N, C)
    gate_flat = gates.T.reshape(-1)                    # (k*N,)
    contrib = (oh * (gate_flat * keep)[:, None])[:, :, None] \
        * slot[:, None, :]                             # (k*N, E, C)
    combine = jnp.sum(contrib.reshape(k, N, E, capacity), axis=0)
    onehot = jax.nn.one_hot(topi[:, 0], E, dtype=jnp.float32)
    return combine, probs, onehot


def load_balance_loss(probs, onehot):
    """Switch aux loss: E * sum_e mean_n(frac_e) * mean_n(prob_e)."""
    E = probs.shape[-1]
    frac = jnp.mean(onehot, axis=0)
    prob = jnp.mean(probs, axis=0)
    return E * jnp.sum(frac * prob)


def moe_forward(x, router_w, w_in, w_out, capacity_factor: float = 1.25,
                return_aux: bool = False, top_k: int = 1, w_gate=None):
    """Top-k MoE FFN over flattened tokens (k=1 Switch, k=2 GShard).

    x: (..., D); router_w: (D, E); w_in: (E, D, H); w_out: (E, H, D).
    Expert e computes relu(x @ w_in[e]) @ w_out[e] — or, with `w_gate`
    (E, D, H) given, the SwiGLU form silu(x @ w_gate[e]) * (x @
    w_in[e]) @ w_out[e] (Mixtral-style experts).  Shard the stacked
    weights' leading axis over the 'expert' mesh axis (SHARD_RULES)
    for EP."""
    orig_shape = x.shape
    D = orig_shape[-1]
    xf = x.reshape(-1, D)
    N = xf.shape[0]
    E = router_w.shape[-1]
    # capacity covers the k-fold assignment load at the same factor
    capacity = max(1, math.ceil(capacity_factor * top_k * N / E))

    logits = xf.astype(jnp.float32) @ router_w.astype(jnp.float32)
    combine, probs, onehot = moe_dispatch(logits, capacity, top_k)
    dispatch = (combine > 0).astype(xf.dtype)          # (N, E, C)
    # dispatch tokens into per-expert buffers: (E, C, D)
    buf = jnp.einsum("nec,nd->ecd", dispatch, xf)
    up = jnp.einsum("ecd,edh->ech", buf, w_in.astype(xf.dtype))
    if w_gate is not None:
        h = jax.nn.silu(jnp.einsum("ecd,edh->ech", buf,
                                   w_gate.astype(xf.dtype))) * up
    else:
        h = jax.nn.relu(up)
    y = jnp.einsum("ech,ehd->ecd", h, w_out.astype(xf.dtype))
    # gate-weighted combine back to tokens
    out = jnp.einsum("nec,ecd->nd", combine.astype(xf.dtype), y)
    out = out.reshape(orig_shape)
    if return_aux:
        return out, load_balance_loss(probs, onehot)
    return out
