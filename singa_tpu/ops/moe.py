"""Mixture-of-Experts with expert parallelism (the 'expert' mesh axis).

Not a reference capability (SURVEY.md §2.3: the reference's only
strategy is DP) — this is the TPU-native extension that completes the
framework's parallelism axes (dp/tp/sp/pp/ep).  Formulation follows the
GShard/Switch static-shape recipe, which is what XLA partitions well:

  * router: (N, D) -> (N, E) logits -> top-1 gate with a static expert
    capacity C = ceil(cf * N / E);
  * dispatch: a one-hot (N, E, C) combine tensor built with cumsum
    position indexing — NO dynamic shapes, dropped tokens (over
    capacity) pass through with zero expert contribution;
  * expert compute: (E, C, D) batched einsums over stacked expert
    weights — sharding the leading E axis over the 'expert' mesh axis
    turns the dispatch/combine einsums into XLA all-to-alls over ICI;
  * combine: gate-weighted gather back to (N, D).

Everything is pure jnp (fwd differentiates via jax.vjp), so the whole
MoE layer compiles into the model's single step module like any other
op; router load-balance auxiliary loss follows Switch (mean fraction *
mean probability per expert).
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = ["moe_dispatch", "moe_forward", "load_balance_loss"]


def moe_dispatch(logits, capacity: int):
    """Top-1 routing with static capacity.

    logits: (N, E).  Returns (combine (N, E, C) f32, gate (N,), aux
    tensors for the balance loss).  combine[n, e, c] is the gate weight
    of token n at slot c of expert e (0 everywhere else; 0 for dropped
    tokens)."""
    N, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate = jnp.max(probs, axis=-1)                     # (N,)
    expert = jnp.argmax(probs, axis=-1)                # (N,)
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)   # (N, E)
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0    # (N, E), -1 elsewhere
    pos_in_expert = jnp.sum(pos * onehot, axis=-1)     # (N,)
    keep = pos_in_expert < capacity
    slot = jax.nn.one_hot(pos_in_expert.astype(jnp.int32), capacity,
                          dtype=jnp.float32)
    combine = (onehot * (gate * keep)[:, None])[:, :, None] * slot[:, None, :]
    return combine, probs, onehot


def load_balance_loss(probs, onehot):
    """Switch aux loss: E * sum_e mean_n(frac_e) * mean_n(prob_e)."""
    E = probs.shape[-1]
    frac = jnp.mean(onehot, axis=0)
    prob = jnp.mean(probs, axis=0)
    return E * jnp.sum(frac * prob)


def moe_forward(x, router_w, w_in, w_out, capacity_factor: float = 1.25,
                return_aux: bool = False):
    """Top-1 MoE FFN over flattened tokens.

    x: (..., D); router_w: (D, E); w_in: (E, D, H); w_out: (E, H, D).
    Expert e computes relu(x @ w_in[e]) @ w_out[e].  Shard w_in/w_out's
    leading axis over the 'expert' mesh axis (SHARD_RULES) for EP."""
    orig_shape = x.shape
    D = orig_shape[-1]
    xf = x.reshape(-1, D)
    N = xf.shape[0]
    E = router_w.shape[-1]
    capacity = max(1, math.ceil(capacity_factor * N / E))

    logits = xf.astype(jnp.float32) @ router_w.astype(jnp.float32)
    combine, probs, onehot = moe_dispatch(logits, capacity)
    dispatch = (combine > 0).astype(xf.dtype)          # (N, E, C)
    # dispatch tokens into per-expert buffers: (E, C, D)
    buf = jnp.einsum("nec,nd->ecd", dispatch, xf)
    h = jax.nn.relu(jnp.einsum("ecd,edh->ech", buf, w_in.astype(xf.dtype)))
    y = jnp.einsum("ech,ehd->ecd", h, w_out.astype(xf.dtype))
    # gate-weighted combine back to tokens
    out = jnp.einsum("nec,ecd->nd", combine.astype(xf.dtype), y)
    out = out.reshape(orig_shape)
    if return_aux:
        return out, load_balance_loss(probs, onehot)
    return out
