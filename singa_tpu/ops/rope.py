"""Rotary position embeddings (RoPE) — needed for GPT-NeoX/Llama families
(BASELINE.json:11 stretch config)."""

from __future__ import annotations

import functools

import jax.numpy as jnp

from .. import autograd
from ..tensor import Tensor

__all__ = ["rope_frequencies", "apply_rope"]


@functools.lru_cache(maxsize=32)
def rope_frequencies(head_dim: int, max_len: int, theta: float = 10000.0):
    """Precompute (cos, sin) tables of shape (max_len, head_dim//2).

    Cached so every attention layer of a model shares one table pair
    instead of baking per-layer copies into the compiled module."""
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)
    return jnp.cos(freqs), jnp.sin(freqs)


def _rope_fn(x, cos, sin):
    # x: (B, T, H, D); tables sliced to T
    T = x.shape[1]
    c = cos[:T][None, :, None, :]
    s = sin[:T][None, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
    return out.astype(x.dtype)


class Rope(autograd.Operator):
    def __init__(self, cos, sin):
        super().__init__()
        self.cos, self.sin = cos, sin

    def fwd(self, x):
        return _rope_fn(x, self.cos, self.sin)


def apply_rope(x, cos, sin):
    if isinstance(x, Tensor):
        return Rope(cos, sin)(x)
    return _rope_fn(x, cos, sin)
