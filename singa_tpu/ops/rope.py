"""Rotary position embeddings (RoPE) — needed for GPT-NeoX/Llama families
(BASELINE.json:11 stretch config)."""

from __future__ import annotations

import functools

import jax.numpy as jnp

from .. import autograd
from ..tensor import Tensor

__all__ = ["rope_frequencies", "apply_rope", "llama31_rope_scaling"]


def llama31_rope_scaling(inv_freq, scale_factor: float = 8.0,
                         low_freq_factor: float = 1.0,
                         high_freq_factor: float = 4.0,
                         original_max_position: int = 8192):
    """Llama-3.1-style frequency-dependent NTK interpolation: long
    wavelengths (beyond the original context) are divided by
    `scale_factor`, short wavelengths pass through, and the band in
    between blends linearly — extends the usable context by
    ~scale_factor without retraining the short-range behavior."""
    wavelen = 2.0 * jnp.pi / inv_freq
    low_bound = original_max_position / low_freq_factor    # long waves
    high_bound = original_max_position / high_freq_factor  # short waves
    # smooth in (0,1): 0 at the long-wave bound, 1 at the short-wave one
    smooth = (original_max_position / wavelen - low_freq_factor) / (
        high_freq_factor - low_freq_factor)
    scaled = jnp.where(
        wavelen > low_bound, inv_freq / scale_factor,
        jnp.where(wavelen < high_bound, inv_freq,
                  (1 - smooth) * inv_freq / scale_factor + smooth * inv_freq))
    return scaled


@functools.lru_cache(maxsize=32)
def rope_frequencies(head_dim: int, max_len: int, theta: float = 10000.0,
                     rope_scaling: float = 0.0,
                     rope_original_max_position: int = 8192):
    """Precompute (cos, sin) tables of shape (max_len, head_dim//2).

    Cached so every attention layer of a model shares one table pair
    instead of baking per-layer copies into the compiled module.

    rope_scaling > 0 applies Llama-3.1-style frequency-dependent
    interpolation with that scale factor (context extension);
    `rope_original_max_position` is the PRETRAINED context window the
    interpolation bands are anchored to."""
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    if rope_scaling and rope_scaling > 0.0:
        inv = llama31_rope_scaling(
            inv, scale_factor=float(rope_scaling),
            original_max_position=int(rope_original_max_position))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)
    return jnp.cos(freqs), jnp.sin(freqs)


def _rope_fn(x, cos, sin, offset=0):
    # x: (B, T, H, D); tables sliced to [offset, offset+T).  `offset` may
    # be a traced scalar (KV-cached decoding) — dynamic_slice keeps the
    # compiled decode step position-independent — or a traced (B,)
    # vector (continuous-batching decode, serve.engine): row b reads
    # table rows [offset[b], offset[b]+T), so every slot rotates at its
    # own position inside ONE compiled step.
    import jax
    T = x.shape[1]
    if getattr(offset, "ndim", 0):
        idx = offset[:, None] + jnp.arange(T)[None, :]       # (B, T)
        c = jnp.take(cos, idx, axis=0)[:, :, None, :]        # (B, T, 1, D/2)
        s = jnp.take(sin, idx, axis=0)[:, :, None, :]
        x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
        out = jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
        return out.astype(x.dtype)
    if isinstance(offset, int) and offset == 0:
        c, s = cos[:T], sin[:T]
    else:
        c = jax.lax.dynamic_slice_in_dim(cos, offset, T, axis=0)
        s = jax.lax.dynamic_slice_in_dim(sin, offset, T, axis=0)
    c = c[None, :, None, :]
    s = s[None, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
    return out.astype(x.dtype)


class Rope(autograd.Operator):
    def __init__(self, cos, sin, offset=0):
        super().__init__()
        self.cos, self.sin, self.offset = cos, sin, offset

    def fwd(self, x):
        return _rope_fn(x, self.cos, self.sin, self.offset)


def apply_rope(x, cos, sin, offset=0):
    if isinstance(x, Tensor):
        return Rope(cos, sin, offset)(x)
    return _rope_fn(x, cos, sin, offset)
