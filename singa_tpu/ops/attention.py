"""Scaled-dot-product attention as an autograd Operator.

Two lowerings behind one API:
  * `_sdpa_reference` — plain jnp einsum/softmax; XLA fuses this well for
    short sequences, and it is the correctness oracle on CPU.
  * the Pallas flash-attention kernel (singa_tpu.ops.flash_attention) —
    blockwise O(T) memory for long sequences on TPU.
Selection is by sequence length + platform; both are jit-traceable so the
choice is static at capture time.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from .. import autograd
from ..tensor import Tensor

__all__ = ["attention", "sdpa", "banded_attention", "banded_sdpa"]

# sequences at least this long route to the flash kernel on TPU
_FLASH_MIN_LEN = 512


def _sdpa_reference(q, k, v, causal: bool, mask, scale: float):
    # q: (B, T, H, D); k/v: (B, T, K, D) with K | H (grouped-query attention
    # when K < H — Llama-3 style).  Head dim kept last for MXU-friendly
    # einsums; the group axis stays folded into one batched matmul.
    H, K = q.shape[2], k.shape[2]
    if K != H:
        G = H // K
        q = q.reshape(q.shape[:2] + (K, G, q.shape[-1]))
        logits = jnp.einsum("bqkgd,bskd->bkgqs", q, k) * scale
    else:
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    extra = logits.ndim - 2  # leading axes before (Tq, Tk)
    if causal:
        Tq, Tk = q.shape[1], k.shape[1]
        cm = jnp.tril(jnp.ones((Tq, Tk), bool), k=Tk - Tq)
        cm = cm[(None,) * extra]
        logits = jnp.where(cm, logits, jnp.finfo(logits.dtype).min)
    if mask is not None:
        m = jnp.asarray(mask)
        if K != H and m.ndim == 4:
            # user masks address (B, H|1, Tq|1, Ts); grouped logits are
            # (B, K, G, Tq, Ts) — split the head axis so broadcasting can't
            # silently land the batch dim on the kv-head axis
            if m.shape[1] == H:
                m = m.reshape(m.shape[0], K, G, *m.shape[2:])
            else:
                m = m[:, :, None]
        logits = jnp.where(m, logits, jnp.finfo(logits.dtype).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    if K != H:
        out = jnp.einsum("bkgqs,bskd->bqkgd", probs, v)
        return out.reshape(out.shape[:2] + (H, out.shape[-1]))
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _use_flash(q, k=None) -> bool:
    import os
    if os.environ.get("SINGA_DISABLE_FLASH"):
        return False
    if q.shape[1] < _FLASH_MIN_LEN:
        return False
    if k is not None and q.shape[2] % k.shape[2] != 0:
        return False  # non-grouping head ratio: einsum reference path
    platform = jax.devices()[0].platform
    return platform in ("tpu", "axon")


class SDPA(autograd.Operator):
    def __init__(self, causal: bool, mask, scale: Optional[float]):
        super().__init__()
        self.causal = causal
        self.mask = mask
        self.scale = scale

    def fwd(self, q, k, v):
        scale = self.scale or (1.0 / math.sqrt(q.shape[-1]))
        if self.mask is None and _use_flash(q, k):
            from .flash_attention import flash_attention
            return flash_attention(q, k, v, causal=self.causal, scale=scale)
        return _sdpa_reference(q, k, v, self.causal, self.mask, scale)


def attention(q: Tensor, k: Tensor, v: Tensor, causal: bool = False,
              mask: Optional[Tensor] = None,
              scale: Optional[float] = None) -> Tensor:
    """(B, T, H, D) attention with optional causal/explicit mask."""
    m = mask.data if isinstance(mask, Tensor) else mask
    return SDPA(causal, m, scale)(q, k, v)


def sdpa(q, k, v, causal=False, mask=None, scale=None):
    """Raw-array entry point used by models bypassing the tape."""
    scale = scale or (1.0 / math.sqrt(q.shape[-1]))
    if mask is None and _use_flash(q, k):
        from .flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal, scale=scale)
    return _sdpa_reference(q, k, v, causal, mask, scale)


# ---------------------------------------------------------------------------
# chunked banded (sliding-window) attention — O(T * W) memory
# ---------------------------------------------------------------------------

def _banded_reference(q, k, v, window: int, scale: float):
    """Oracle: full (Tq, Tk) band mask through _sdpa_reference
    (bottom-right aligned when Tk > Tq, matching the causal
    convention)."""
    Tq, Tk = q.shape[1], k.shape[1]
    qpos = jnp.arange(Tq)[:, None] + (Tk - Tq)
    kpos = jnp.arange(Tk)[None, :]
    band = (kpos <= qpos) & (kpos > qpos - window)
    return _sdpa_reference(q, k, v, False, band[None, None], scale)


def pick_band_chunk(T: int, window: int) -> Optional[int]:
    """Largest divisor of T up to ~the window (capped at 512) — the
    chunk size that keeps (C, C+W) score tiles small.  None when only a
    degenerate chunk (< 8) divides T: the k/v duplication of tiny
    chunks would cost more than the full masked path."""
    cap = max(16, min(window, 512))
    c = next(c for c in range(min(cap, T), 0, -1) if T % c == 0)
    return c if c >= 8 else None


def banded_sdpa(q, k, v, window: int, scale: Optional[float] = None,
                chunk: Optional[int] = None):
    """Sliding-window attention (query t attends keys in (t-W, t])
    computed in query chunks so only (chunk, chunk+W) score tiles ever
    materialize — O(T*W) memory instead of the O(T^2) masked path, on
    any backend, in pure jnp (so jax.vjp differentiates it).

    The relative band is identical for every interior chunk: chunk i's
    queries [iC, iC+C) need keys [iC-W+1, iC+C), a width-(C+W-1) slice
    of k/v left-padded by W so edge chunks clamp cleanly; padded keys
    fall outside the band mask.  vmap over chunks keeps everything one
    fused program."""
    T = q.shape[1]
    scale = scale or (1.0 / math.sqrt(q.shape[-1]))
    W = int(window)
    if chunk is None:
        chunk = pick_band_chunk(T, W)
        if chunk is None:
            raise ValueError(
                f"no usable chunk divides T={T} (all divisors < 8); "
                "use the masked path instead")
    C = int(chunk)
    if T % C:
        raise ValueError(f"seq len {T} must divide by chunk {C}")
    n = T // C
    span = C + W                                    # keys per chunk
    # left-pad keys/values by W (zeros; masked out below)
    kp = jnp.pad(k, ((0, 0), (W, 0), (0, 0), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (W, 0), (0, 0), (0, 0)))
    qc = q.reshape(q.shape[0], n, C, *q.shape[2:])  # (B, n, C, H, D)
    starts = jnp.arange(n) * C                      # chunk i keys start
    kc = jax.vmap(lambda s: jax.lax.dynamic_slice_in_dim(kp, s, span, 1),
                  out_axes=1)(starts)               # (B, n, span, K, D)
    vc = jax.vmap(lambda s: jax.lax.dynamic_slice_in_dim(vp, s, span, 1),
                  out_axes=1)(starts)
    # relative positions are chunk-invariant: query c (0..C-1) sits at
    # absolute offset c; key j (0..span-1) at absolute offset j - W.
    # band: 0 <= (c + W - j) < W  i.e.  c < j <= c + W ... in padded
    # coords: key abs = j - W, query abs = c; causal j - W <= c and
    # within-window j - W > c - W  =>  c < j <= c + W
    cpos = jnp.arange(C)[:, None]
    jpos = jnp.arange(span)[None, :]
    band = (jpos <= cpos + W) & (jpos > cpos)       # (C, span)
    # first chunk's left-pad keys are already outside the band only
    # when j > c holds... padded keys have j < W and represent
    # negative absolute positions; for chunk 0 they must be masked:
    # absolute key pos = starts[i] + j - W >= 0  =>  j >= W - starts[i]
    valid0 = jpos[None] >= (W - starts)[:, None, None]  # (n, 1, span)
    mask = band[None] & valid0                      # (n, C, span)

    def one_chunk(qi, ki, vi, mi):
        return _sdpa_reference(qi, ki, vi, False, mi[None, None], scale)

    out = jax.vmap(one_chunk, in_axes=(1, 1, 1, 0), out_axes=1)(
        qc, kc, vc, mask)                           # (B, n, C, H, D)
    return out.reshape(q.shape)


class BandedSDPA(autograd.Operator):
    """Backend selection mirrors SDPA: the Pallas banded kernel on TPU
    (below-band tiles skipped entirely), the chunked jnp path
    elsewhere, the full-mask reference for degenerate chunkings."""

    def __init__(self, window: int, scale: Optional[float],
                 chunk: Optional[int]):
        super().__init__()
        self.window = window
        self.scale = scale
        self.chunk = chunk

    def fwd(self, q, k, v):
        scale = self.scale or (1.0 / math.sqrt(q.shape[-1]))
        W = self.window
        if self.chunk is None and _use_flash(q, k):
            from .flash_attention import flash_attention
            # falls back to the banded reference internally when the
            # shape doesn't tile
            return flash_attention(q, k, v, causal=True, scale=scale,
                                   window=W)
        if self.chunk is None and pick_band_chunk(q.shape[1], W) is None:
            return _banded_reference(q, k, v, W, scale)
        return banded_sdpa(q, k, v, W, scale, self.chunk)


def banded_attention(q: Tensor, k: Tensor, v: Tensor, window: int,
                     scale: Optional[float] = None,
                     chunk: Optional[int] = None) -> Tensor:
    """Tape entry point for chunked sliding-window attention."""
    return BandedSDPA(window, scale, chunk)(q, k, v)
