"""Scaled-dot-product attention as an autograd Operator.

Two lowerings behind one API:
  * `_sdpa_reference` — plain jnp einsum/softmax; XLA fuses this well for
    short sequences, and it is the correctness oracle on CPU.
  * the Pallas flash-attention kernel (singa_tpu.ops.flash_attention) —
    blockwise O(T) memory for long sequences on TPU.
Selection is by sequence length + platform; both are jit-traceable so the
choice is static at capture time.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from .. import autograd
from ..tensor import Tensor

__all__ = ["attention", "sdpa"]

# sequences at least this long route to the flash kernel on TPU
_FLASH_MIN_LEN = 512


def _sdpa_reference(q, k, v, causal: bool, mask, scale: float):
    # q: (B, T, H, D); k/v: (B, T, K, D) with K | H (grouped-query attention
    # when K < H — Llama-3 style).  Head dim kept last for MXU-friendly
    # einsums; the group axis stays folded into one batched matmul.
    H, K = q.shape[2], k.shape[2]
    if K != H:
        G = H // K
        q = q.reshape(q.shape[:2] + (K, G, q.shape[-1]))
        logits = jnp.einsum("bqkgd,bskd->bkgqs", q, k) * scale
    else:
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    extra = logits.ndim - 2  # leading axes before (Tq, Tk)
    if causal:
        Tq, Tk = q.shape[1], k.shape[1]
        cm = jnp.tril(jnp.ones((Tq, Tk), bool), k=Tk - Tq)
        cm = cm[(None,) * extra]
        logits = jnp.where(cm, logits, jnp.finfo(logits.dtype).min)
    if mask is not None:
        m = jnp.asarray(mask)
        if K != H and m.ndim == 4:
            # user masks address (B, H|1, Tq|1, Ts); grouped logits are
            # (B, K, G, Tq, Ts) — split the head axis so broadcasting can't
            # silently land the batch dim on the kv-head axis
            if m.shape[1] == H:
                m = m.reshape(m.shape[0], K, G, *m.shape[2:])
            else:
                m = m[:, :, None]
        logits = jnp.where(m, logits, jnp.finfo(logits.dtype).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    if K != H:
        out = jnp.einsum("bkgqs,bskd->bqkgd", probs, v)
        return out.reshape(out.shape[:2] + (H, out.shape[-1]))
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _use_flash(q, k=None) -> bool:
    import os
    if os.environ.get("SINGA_DISABLE_FLASH"):
        return False
    if q.shape[1] < _FLASH_MIN_LEN:
        return False
    if k is not None and q.shape[2] % k.shape[2] != 0:
        return False  # non-grouping head ratio: einsum reference path
    platform = jax.devices()[0].platform
    return platform in ("tpu", "axon")


class SDPA(autograd.Operator):
    def __init__(self, causal: bool, mask, scale: Optional[float]):
        super().__init__()
        self.causal = causal
        self.mask = mask
        self.scale = scale

    def fwd(self, q, k, v):
        scale = self.scale or (1.0 / math.sqrt(q.shape[-1]))
        if self.mask is None and _use_flash(q, k):
            from .flash_attention import flash_attention
            return flash_attention(q, k, v, causal=self.causal, scale=scale)
        return _sdpa_reference(q, k, v, self.causal, self.mask, scale)


def attention(q: Tensor, k: Tensor, v: Tensor, causal: bool = False,
              mask: Optional[Tensor] = None,
              scale: Optional[float] = None) -> Tensor:
    """(B, T, H, D) attention with optional causal/explicit mask."""
    m = mask.data if isinstance(mask, Tensor) else mask
    return SDPA(causal, m, scale)(q, k, v)


def sdpa(q, k, v, causal=False, mask=None, scale=None):
    """Raw-array entry point used by models bypassing the tape."""
    scale = scale or (1.0 / math.sqrt(q.shape[-1]))
    if mask is None and _use_flash(q, k):
        from .flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal, scale=scale)
    return _sdpa_reference(q, k, v, causal, mask, scale)
