"""Scaled-dot-product attention as an autograd Operator.

Two lowerings behind one API:
  * `_sdpa_reference` — plain jnp einsum/softmax; XLA fuses this well for
    short sequences, and it is the correctness oracle on CPU.
  * the Pallas flash-attention kernel (singa_tpu.ops.flash_attention) —
    blockwise O(T) memory for long sequences on TPU.
Selection is by sequence length + platform; both are jit-traceable so the
choice is static at capture time.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from .. import autograd
from ..tensor import Tensor

__all__ = ["attention", "sdpa"]

# sequences at least this long route to the flash kernel on TPU
_FLASH_MIN_LEN = 512


def _sdpa_reference(q, k, v, causal: bool, mask, scale: float):
    # q,k,v: (B, T, H, D) — keep head dim last for MXU-friendly einsums
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        Tq, Tk = q.shape[1], k.shape[1]
        cm = jnp.tril(jnp.ones((Tq, Tk), bool), k=Tk - Tq)
        logits = jnp.where(cm[None, None], logits, jnp.finfo(logits.dtype).min)
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _use_flash(q) -> bool:
    if q.shape[1] < _FLASH_MIN_LEN:
        return False
    platform = jax.devices()[0].platform
    return platform in ("tpu", "axon")


class SDPA(autograd.Operator):
    def __init__(self, causal: bool, mask, scale: Optional[float]):
        super().__init__()
        self.causal = causal
        self.mask = mask
        self.scale = scale

    def fwd(self, q, k, v):
        scale = self.scale or (1.0 / math.sqrt(q.shape[-1]))
        if self.mask is None and _use_flash(q):
            from .flash_attention import flash_attention
            return flash_attention(q, k, v, causal=self.causal, scale=scale)
        return _sdpa_reference(q, k, v, self.causal, self.mask, scale)


def attention(q: Tensor, k: Tensor, v: Tensor, causal: bool = False,
              mask: Optional[Tensor] = None,
              scale: Optional[float] = None) -> Tensor:
    """(B, T, H, D) attention with optional causal/explicit mask."""
    m = mask.data if isinstance(mask, Tensor) else mask
    return SDPA(causal, m, scale)(q, k, v)


def sdpa(q, k, v, causal=False, mask=None, scale=None):
    """Raw-array entry point used by models bypassing the tape."""
    scale = scale or (1.0 / math.sqrt(q.shape[-1]))
    if mask is None and _use_flash(q):
        from .flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal, scale=scale)
    return _sdpa_reference(q, k, v, causal, mask, scale)
