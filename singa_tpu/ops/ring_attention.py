"""Ring attention — cross-chip sequence/context parallelism.

Long-context scaling (task directive; beyond the reference, which never
scales sequence length past one device): the sequence axis of Q/K/V is
sharded over the 'seq' mesh axis; each device holds one block and K/V
blocks rotate around the ring via `lax.ppermute` while a numerically
stable online-softmax accumulates output blocks (blockwise attention in
the FlashAttention/RingAttention style).  Communication rides ICI
neighbor links — each step overlaps the block matmul with the next
block's transfer, which is exactly what the TPU torus is shaped for.

Two entry points:
  * ``ring_attention_local``   — raw per-shard function, for use inside
    an existing shard_map region;
  * ``ring_attention``         — autograd Operator on global Tensors;
    wraps itself in shard_map over the installed mesh (composes with
    the GSPMD-jitted training step), falling back to fused SDPA when
    no 'seq' axis is installed.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .. import autograd
from ..tensor import Tensor

__all__ = ["ring_attention", "ring_attention_local"]

_NEG = float(jnp.finfo(jnp.float32).min)


def ring_attention_local(q, k, v, axis: str = "seq", causal: bool = True,
                         scale: Optional[float] = None,
                         use_flash: Optional[bool] = None):
    """Blockwise ring attention on per-shard blocks (inside shard_map).

    q: (B, T_local, H, D); k/v: (B, T_local, K, D).  The einsum path
    requires full heads (K == H; repeat kv heads before the ring); the
    flash path handles grouped-query K < H natively — KV blocks rotate
    un-replicated, cutting ring ICI bytes and HBM by H/K (3x for
    Llama-3's 12q/4kv).

    use_flash: compute each block's attention with the Pallas flash
    kernel (ops.flash_attention_with_lse) instead of materializing the
    (B, H, Tl, Tl) f32 logits — SP x flash composition.  None = auto
    (TPU, tileable shapes, SINGA_DISABLE_FLASH unset)."""
    gqa = k.shape[2] != q.shape[2]
    if gqa and (k.shape[2] == 0 or q.shape[2] % k.shape[2] != 0):
        raise ValueError(
            f"q heads ({q.shape[2]}) must be a multiple of kv heads "
            f"({k.shape[2]})")
    scale = scale or (1.0 / math.sqrt(q.shape[-1]))
    if use_flash is None:
        use_flash = _flash_ring_auto(q.shape[1], q.shape[3])
    if use_flash:
        return _ring_local_flash(q, k, v, axis, causal, scale)
    if gqa:
        raise ValueError("the einsum ring needs matching q/kv heads; "
                         "repeat kv heads before the ring (the flash "
                         "path handles GQA natively)")
    S = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    B, Tl, H, D = q.shape
    qf = q.astype(jnp.float32)

    o0 = jnp.zeros((B, H, Tl, D), jnp.float32)
    m0 = jnp.full((B, H, Tl), _NEG, jnp.float32)
    l0 = jnp.zeros((B, H, Tl), jnp.float32)
    perm = [(r, (r + 1) % S) for r in range(S)]

    q_pos = idx * Tl + jnp.arange(Tl)

    def accumulate(o, m, l, k_blk, v_blk, src):
        logits = jnp.einsum("bqhd,bkhd->bhqk", qf,
                            k_blk.astype(jnp.float32)) * scale
        if causal:
            k_pos = src * Tl + jnp.arange(Tl)
            keep = q_pos[:, None] >= k_pos[None, :]          # (Tq, Tk)
            logits = jnp.where(keep[None, None], logits, _NEG)
            pmask = keep[None, None].astype(jnp.float32)
        else:
            pmask = None
        m_new = jnp.maximum(m, logits.max(axis=-1))
        p = jnp.exp(logits - m_new[..., None])
        if pmask is not None:
            p = p * pmask  # kill exp(0)=1 residue of fully-masked rows
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1)
        o = o * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32))
        return o, m_new, l

    def step(carry, s):
        o, m, l, k_blk, v_blk = carry
        # kick off the next block's transfer before the compute that uses
        # the current block — the permute doesn't depend on the matmuls, so
        # XLA overlaps ICI transfer with MXU work within the iteration
        k_next = lax.ppermute(k_blk, axis, perm)
        v_next = lax.ppermute(v_blk, axis, perm)
        src = (idx - s) % S  # rank that produced the block we now hold
        o, m, l = accumulate(o, m, l, k_blk, v_blk, src)
        return (o, m, l, k_next, v_next), None

    if S > 1:
        (o, m, l, k_last, v_last), _ = lax.scan(
            step, (o0, m0, l0, k, v), jnp.arange(S - 1))
    else:
        o, m, l, k_last, v_last = o0, m0, l0, k, v
    # final held block needs no further rotation — S-1 permutes total
    o, m, l = accumulate(o, m, l, k_last, v_last, (idx - (S - 1)) % S)
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)  # (B, Tl, H, D)


def _flash_ring_auto(Tl: int, D: int) -> bool:
    """Auto predicate for flash ring blocks: on TPU with tileable local
    shapes, unless SINGA_DISABLE_FLASH.  SINGA_RING_FLASH=1/0 overrides
    the platform check (still requires tileable shapes) — lets CPU tests
    and drives exercise the interpret-mode flash ring."""
    import os

    from .flash_attention import _on_tpu, _tileable
    if not _tileable(Tl, Tl, D):
        return False
    if os.environ.get("SINGA_DISABLE_FLASH"):
        return False        # the ablation switch always wins
    force = os.environ.get("SINGA_RING_FLASH")
    if force == "1":
        return True
    if force == "0":
        return False
    return _on_tpu()


def _ring_local_flash(q, k, v, axis: str, causal: bool, scale: float):
    """Per-block flash attention (o, lse) combined across the ring with
    a numerically-stable cross-block logsumexp merge.  Under causal
    masking, block s=0 is the diagonal (standard causal flash); rotated
    blocks are either fully visible (source rank < this rank) or fully
    masked (weight 0) — no per-element mask tensors at all."""
    from .flash_attention import flash_attention_with_lse

    S = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    perm = [(r, (r + 1) % S) for r in range(S)]
    qh = jnp.swapaxes(q, 1, 2)                      # (B, H, Tl, D)

    def block(k_blk, v_blk, block_causal):
        kh = jnp.swapaxes(k_blk, 1, 2)
        vh = jnp.swapaxes(v_blk, 1, 2)
        o_b, lse_b = flash_attention_with_lse(qh, kh, vh,
                                              causal=block_causal,
                                              scale=scale)
        return o_b.astype(jnp.float32), lse_b[..., 0]   # (B,H,Tl,D),(B,H,Tl)

    def merge(o, m, l, o_b, lse_b, s):
        # after s rotations we hold rank (idx - s)'s block: under causal
        # masking it is fully visible iff idx >= s, else entirely in the
        # future (weight 0) — no per-element mask tensors at all
        if causal:
            lse_b = jnp.where(idx >= s, lse_b, _NEG)
        m_new = jnp.maximum(m, lse_b)
        alpha = jnp.exp(m - m_new)
        w = jnp.exp(lse_b - m_new)
        return (o * alpha[..., None] + o_b * w[..., None], m_new,
                l * alpha + w)

    if S > 1:
        # kick off the first rotation before the diagonal's compute so
        # ICI transfer overlaps MXU work (same trick as the einsum path)
        k_cur = lax.ppermute(k, axis, perm)
        v_cur = lax.ppermute(v, axis, perm)

    # diagonal block: standard causal flash on the locally-held K/V
    o, m = block(k, v, causal)
    l = jnp.ones_like(m)                            # sum exp(s - lse) = 1

    if S > 1:
        def step(carry, s):
            o, m, l, k_blk, v_blk = carry
            k_next = lax.ppermute(k_blk, axis, perm)
            v_next = lax.ppermute(v_blk, axis, perm)
            o_b, lse_b = block(k_blk, v_blk, False)
            o, m, l = merge(o, m, l, o_b, lse_b, s)
            return (o, m, l, k_next, v_next), None

        if S > 2:
            (o, m, l, k_cur, v_cur), _ = lax.scan(
                step, (o, m, l, k_cur, v_cur), jnp.arange(1, S - 1))
        # final held block needs no further rotation — S-1 permutes total
        o_b, lse_b = block(k_cur, v_cur, False)
        o, m, l = merge(o, m, l, o_b, lse_b, S - 1)
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)  # (B, Tl, H, D)


class _RingSDPA(autograd.Operator):
    def __init__(self, mesh, specs, axis, causal, scale, use_flash=None):
        super().__init__()
        self.mesh, self.specs = mesh, specs
        self.axis, self.causal, self.scale = axis, causal, scale
        self.use_flash = use_flash

    def fwd(self, q, k, v):
        # operands are always tracers here: ring_attention routes concrete
        # (eager) calls to the fused SDPA path before building this op
        body = partial(ring_attention_local, axis=self.axis,
                       causal=self.causal, scale=self.scale,
                       use_flash=self.use_flash)
        sharded = jax.shard_map(body, mesh=self.mesh, in_specs=self.specs,
                                out_specs=self.specs[0], check_vma=False)
        return sharded(q, k, v)


def ring_attention(q: Tensor, k: Tensor, v: Tensor, causal: bool = True,
                   scale: Optional[float] = None, axis: str = "seq",
                   data_axis: Optional[str] = None,
                   model_axis: str = "model") -> Tensor:
    """Global-tensor ring attention over the installed mesh's `axis`.

    Falls back to the fused SDPA op when no seq axis is installed, so
    models can call this unconditionally.  `data_axis` defaults to the
    executor-installed batch axis (mesh.current_data_axis), so a DistOpt
    with a custom axis name keeps batch sharding inside the ring.  When
    the mesh has a tensor-parallel `model_axis` that divides the head
    count, heads stay sharded over it through the shard_map boundary —
    each TP group computes only its own heads."""
    from ..parallel import mesh as mesh_mod
    from . import attention as attn_ops

    mesh = mesh_mod.current_mesh()
    if mesh is None or axis not in mesh.shape or mesh.shape[axis] == 1 \
            or q.shape[1] % mesh.shape[axis] != 0:
        return attn_ops.attention(q, k, v, causal=causal, scale=scale)
    if not isinstance(q.data, jax.core.Tracer):
        # eager call (compile()'s param-materializing dry-run): same math
        # via the fused path; the ring only engages inside the compiled
        # step where operands are global tracers
        return attn_ops.attention(q, k, v, causal=causal, scale=scale)
    # the flash-engagement decision is computed ONCE here and threaded
    # through _RingSDPA into ring_attention_local, so the global
    # replication choice and the local block path can never disagree
    use_flash = _flash_ring_auto(q.shape[1] // mesh.shape[axis], q.shape[3])
    tp = mesh.shape.get(model_axis, 1)
    if k.shape[2] != q.shape[2]:
        # GQA: the flash block path consumes grouped KV natively (ring
        # ICI bytes and HBM drop by H/K) — but only skip the head
        # replication when it does not cost tensor-parallel head
        # sharding (tp must divide the GROUPED kv head count too,
        # else every TP rank would compute all heads redundantly)
        flash_gqa = (use_flash and q.shape[2] % k.shape[2] == 0
                     and (tp <= 1 or q.shape[2] % tp != 0
                          or k.shape[2] % tp == 0))
        if not flash_gqa:
            rep = q.shape[2] // k.shape[2]
            k = _repeat_heads(k, rep)
            v = _repeat_heads(v, rep)
    P = mesh_mod.P
    if data_axis is None:
        data_axis = mesh_mod.current_data_axis()
    dspec = (data_axis if data_axis in mesh.shape
             and q.shape[0] % mesh.shape[data_axis] == 0 else None)
    hspec = (model_axis if model_axis in mesh.shape
             and mesh.shape[model_axis] > 1
             and q.shape[2] % mesh.shape[model_axis] == 0
             and k.shape[2] % mesh.shape[model_axis] == 0 else None)
    spec = P(dspec, axis, hspec)
    return _RingSDPA(mesh, (spec, spec, spec), axis, causal, scale,
                     use_flash=use_flash)(q, k, v)


class _RepeatHeads(autograd.Operator):
    def __init__(self, rep):
        super().__init__()
        self.rep = rep

    def fwd(self, x):
        # (B, T, K, D) -> (B, T, K*rep, D), repeat-interleave to match the
        # grouped-query (K, G) head layout
        return jnp.repeat(x, self.rep, axis=2)


def _repeat_heads(x: Tensor, rep: int) -> Tensor:
    return _RepeatHeads(rep)(x)
