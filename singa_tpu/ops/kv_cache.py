"""KV-cache primitives for autoregressive decoding (VERDICT r2 item 4;
SURVEY.md §7.3.5 — GPT-2 generation with dynamic shapes is hostile to
XLA, so the TPU-native formulation is a *static* cache: preallocated
(B, S_max, K, D) buffers updated in place with dynamic_update_slice and
an explicit validity mask, so every decode step reuses ONE compiled
module regardless of how many tokens have been generated).

Prefill attends within the prompt via the regular attention stack (the
Pallas flash kernel when the shape tiles); decode steps (Tq=1) are
bandwidth-bound matvecs where flash has nothing to win, so they run the
masked-reference path against the full cache.

**Int8 KV blocks** (ISSUE 17): a paged arena may store its blocks as
:class:`QuantKV` — int8 codes plus a per-position f32 scale (one scale
per (K, D) slab, i.e. a ``(block_size,)`` scale vector per block).
Every gather/scatter primitive below branches on ``isinstance(ck,
QuantKV)`` at TRACE time: quantize-on-scatter / dequantize-on-gather
are fixed-shape elementwise ops folded into the same programs, so an
int8 arena compiles the same fixed program set as a full-precision one
(one jit entry per program, asserted in tests) while its decode
dispatch streams ~4x fewer KV bytes through HBM (the hlocost
``decode_int8`` flagship baseline is the committed evidence).  The
scale granularity is per POSITION, not per block, because
``scatter_token_kv``/``scatter_tokens_kv`` write partial blocks — a
single per-block scalar would force requantizing the block's existing
content whenever a new token's amax grew past it.
"""

from __future__ import annotations

import math
from typing import List, Tuple

import jax
import jax.numpy as jnp

__all__ = ["init_cache", "update_cache", "cached_sdpa",
           "gather_block_kv", "scatter_block_kv", "scatter_token_kv",
           "scatter_tokens_kv", "QuantKV", "quantize_kv",
           "dequantize_kv"]

#: int8 code range: symmetric, -127..127 (the -128 code is unused so
#: quantization commutes with negation and the scale maps amax -> 127)
_QMAX = 127.0
#: scale floor so an all-zero (K, D) slab quantizes to exact zeros
#: instead of dividing by zero (dequantized value stays exactly 0.0)
_SCALE_FLOOR = 1e-30


@jax.tree_util.register_pytree_node_class
class QuantKV:
    """One int8-quantized KV pool: ``q`` int8 codes with the pool's
    layout (``(num_blocks, block_size, K, D)``) and ``scale`` f32 of
    shape ``(num_blocks, block_size, 1, 1)`` — dequantized value is
    ``q * scale``.  A registered pytree, so it flows through jit
    arguments, donation and ``jax.tree`` utilities exactly like the
    plain arrays it replaces; ``.shape``/``.dtype`` mirror ``q`` so
    shape-reading call sites (``ck.shape[1]``) need no branch."""

    __slots__ = ("q", "scale")

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.q.dtype

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def __repr__(self):
        return f"QuantKV(q={self.q.shape}, scale={self.scale.shape})"


def quantize_kv(x):
    """Quantize ``x`` (..., K, D) to (int8 codes, f32 scales): one
    symmetric absmax scale per leading index (per position), shape
    (..., 1, 1).  Fixed-shape elementwise math — folds into whatever
    program performs the scatter."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=(-2, -1), keepdims=True)
    scale = jnp.maximum(amax / _QMAX, _SCALE_FLOOR)
    q = jnp.clip(jnp.round(xf / scale), -_QMAX, _QMAX).astype(jnp.int8)
    return q, scale


def dequantize_kv(q, scale):
    """Inverse of :func:`quantize_kv` (f32 out)."""
    return q.astype(jnp.float32) * scale


def init_cache(num_layers: int, batch: int, max_len: int, num_kv_heads: int,
               head_dim: int, dtype=jnp.float32) -> List[Tuple]:
    """Per-layer (k, v) buffers of shape (B, S_max, K, D)."""
    shape = (batch, max_len, num_kv_heads, head_dim)
    return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
            for _ in range(num_layers)]


def update_cache(ck, cv, k_new, v_new, pos):
    """Write k/v for positions [pos, pos+T) into the cache (functional).

    `pos` may be a traced scalar — decode steps compile once and slide —
    or a traced (B,) vector (continuous batching, serve.engine): row b's
    new keys land at its own positions [pos[b], pos[b]+T), so slots at
    different generation depths share ONE compiled decode step."""
    if getattr(pos, "ndim", 0):
        def row(c, n, p):
            return jax.lax.dynamic_update_slice_in_dim(c, n, p, axis=0)
        return (jax.vmap(row)(ck, k_new.astype(ck.dtype), pos),
                jax.vmap(row)(cv, v_new.astype(cv.dtype), pos))
    ck = jax.lax.dynamic_update_slice_in_dim(ck, k_new.astype(ck.dtype),
                                             pos, axis=1)
    cv = jax.lax.dynamic_update_slice_in_dim(cv, v_new.astype(cv.dtype),
                                             pos, axis=1)
    return ck, cv


def gather_block_kv(ck, cv, table):
    """Gather a contiguous per-request view out of a paged block arena.

    ``ck``/``cv``: (num_blocks, block_size, K, D) block pools.
    ``table``: (B, max_blocks) int32 block table — row b's logical block
    i lives in physical block ``table[b, i]``.  Returns dense
    (B, max_blocks * block_size, K, D) views.  The gather is a
    fixed-shape ``jnp.take`` on the leading axis, so the paged arena
    rides ONE compiled program no matter which physical blocks a
    request holds (stale/unallocated table entries read garbage that
    the attention ``limit`` mask makes unreachable).  A :class:`QuantKV`
    arena gathers codes AND scales through the same take and
    dequantizes in-program — the dense view is f32 either way the
    attention math sees it."""
    B, M = table.shape
    bs = ck.shape[1]

    def dense(c):
        g = jnp.take(c, table.reshape(-1), axis=0)        # (B*M, bs, K, D)
        return g.reshape((B, M * bs) + c.shape[2:])

    if isinstance(ck, QuantKV):
        return (dense(ck.q).astype(jnp.float32) * dense(ck.scale),
                dense(cv.q).astype(jnp.float32) * dense(cv.scale))
    return dense(ck), dense(cv)


def scatter_block_kv(ck, cv, block, k_blk, v_blk):
    """Write one block's worth of k/v back into the paged arena.

    ``block`` is a traced int32 scalar physical block id; ``k_blk`` /
    ``v_blk`` are (block_size, K, D).  The chunked-prefill counterpart
    of :func:`gather_block_kv` — a fixed-shape scatter at a dynamic
    leading index, one compiled shape for every block."""
    if isinstance(ck, QuantKV):
        kq, ks = quantize_kv(k_blk)
        vq, vs = quantize_kv(v_blk)
        return (QuantKV(ck.q.at[block].set(kq),
                        ck.scale.at[block].set(ks)),
                QuantKV(cv.q.at[block].set(vq),
                        cv.scale.at[block].set(vs)))
    return (ck.at[block].set(k_blk.astype(ck.dtype)),
            cv.at[block].set(v_blk.astype(cv.dtype)))


def scatter_token_kv(ck, cv, block, offset, k_tok, v_tok):
    """Write ONE position's k/v per batch row into the paged arena.

    ``block``/``offset``: (B,) int32 vectors — row b's token lands at
    ``[block[b], offset[b]]``.  ``k_tok``/``v_tok``: (B, K, D).  The
    decode-over-block-tables counterpart of :func:`update_cache`'s
    per-row vector path; rows sharing a target (inactive slots
    redirected to the null block) resolve arbitrarily, which is safe
    because the null block is never inside any row's validity window."""
    if isinstance(ck, QuantKV):
        kq, ks = quantize_kv(k_tok)
        vq, vs = quantize_kv(v_tok)
        return (QuantKV(ck.q.at[block, offset].set(kq),
                        ck.scale.at[block, offset].set(ks)),
                QuantKV(cv.q.at[block, offset].set(vq),
                        cv.scale.at[block, offset].set(vs)))
    return (ck.at[block, offset].set(k_tok.astype(ck.dtype)),
            cv.at[block, offset].set(v_tok.astype(cv.dtype)))


def scatter_tokens_kv(ck, cv, blocks, offsets, k_toks, v_toks):
    """Write a per-row WINDOW of positions into the paged arena.

    ``blocks``/``offsets``: (B, T) int32 — row b's window token t lands
    at ``[blocks[b, t], offsets[b, t]]``.  ``k_toks``/``v_toks``:
    (B, T, K, D).  The speculative verify-k counterpart of
    :func:`scatter_token_kv`: one verify dispatch writes k+1 positions
    per slot (the pending token plus the k proposals), and rejected
    positions are rolled back by TRUNCATING the slot's ``pos``/attention
    ``limit`` — the stale entries past the new limit are unreachable,
    exactly like any stale block content.  Rows sharing a target
    (inactive slots redirected to the null block for every window
    position) resolve arbitrarily, which is safe for the same reason."""
    if isinstance(ck, QuantKV):
        kq, ks = quantize_kv(k_toks)
        vq, vs = quantize_kv(v_toks)
        return (QuantKV(ck.q.at[blocks, offsets].set(kq),
                        ck.scale.at[blocks, offsets].set(ks)),
                QuantKV(cv.q.at[blocks, offsets].set(vq),
                        cv.scale.at[blocks, offsets].set(vs)))
    return (ck.at[blocks, offsets].set(k_toks.astype(ck.dtype)),
            cv.at[blocks, offsets].set(v_toks.astype(cv.dtype)))


def cached_sdpa(q, ck, cv, limit, scale: float = None, mask=None,
                window: int = None):
    """Attention of q (B, T, H, D) against the full cache (B, S, K, D),
    masked to cache positions < `limit` plus bottom-right-aligned
    causality inside the query block (query t attends cache positions
    <= limit - T + t).  `limit` may be a scalar or a (B,) vector of
    per-row limits (continuous batching: every slot attends its own
    prefix inside one compiled step).  GQA (H % K == 0) and the grouped
    einsums are delegated to attention._sdpa_reference — one attention
    math, two entry points.  `mask`: optional (B, 1|H, 1|T, S) boolean
    padding mask ANDed with the validity window.  `window`:
    Mistral-style sliding window — each query also ignores cache
    positions more than `window - 1` behind it."""
    from .attention import _sdpa_reference
    T = q.shape[1]
    S = ck.shape[1]
    scale = scale or (1.0 / math.sqrt(q.shape[-1]))
    kpos = jnp.arange(S)[None, None, None, :]           # (1, 1, 1, S)
    lim = jnp.asarray(limit)
    lim = lim.reshape((-1, 1, 1, 1)) if lim.ndim else lim
    qpos = lim - T + jnp.arange(T)[None, None, :, None]  # (B|1, 1, T, 1)
    valid = kpos <= qpos                                 # (B|1, 1, T, S)
    if window is not None:
        valid = jnp.logical_and(valid, kpos > qpos - window)
    if mask is not None:
        valid = jnp.logical_and(valid, mask)
    return _sdpa_reference(q, ck, cv, False, valid, scale)
