"""KV-cache primitives for autoregressive decoding (VERDICT r2 item 4;
SURVEY.md §7.3.5 — GPT-2 generation with dynamic shapes is hostile to
XLA, so the TPU-native formulation is a *static* cache: preallocated
(B, S_max, K, D) buffers updated in place with dynamic_update_slice and
an explicit validity mask, so every decode step reuses ONE compiled
module regardless of how many tokens have been generated).

Prefill attends within the prompt via the regular attention stack (the
Pallas flash kernel when the shape tiles); decode steps (Tq=1) are
bandwidth-bound matvecs where flash has nothing to win, so they run the
masked-reference path against the full cache.
"""

from __future__ import annotations

import math
from typing import List, Tuple

import jax
import jax.numpy as jnp

__all__ = ["init_cache", "update_cache", "cached_sdpa"]


def init_cache(num_layers: int, batch: int, max_len: int, num_kv_heads: int,
               head_dim: int, dtype=jnp.float32) -> List[Tuple]:
    """Per-layer (k, v) buffers of shape (B, S_max, K, D)."""
    shape = (batch, max_len, num_kv_heads, head_dim)
    return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
            for _ in range(num_layers)]


def update_cache(ck, cv, k_new, v_new, pos):
    """Write k/v for positions [pos, pos+T) into the cache (functional).

    `pos` may be a traced scalar — decode steps compile once and slide —
    or a traced (B,) vector (continuous batching, serve.engine): row b's
    new keys land at its own positions [pos[b], pos[b]+T), so slots at
    different generation depths share ONE compiled decode step."""
    if getattr(pos, "ndim", 0):
        def row(c, n, p):
            return jax.lax.dynamic_update_slice_in_dim(c, n, p, axis=0)
        return (jax.vmap(row)(ck, k_new.astype(ck.dtype), pos),
                jax.vmap(row)(cv, v_new.astype(cv.dtype), pos))
    ck = jax.lax.dynamic_update_slice_in_dim(ck, k_new.astype(ck.dtype),
                                             pos, axis=1)
    cv = jax.lax.dynamic_update_slice_in_dim(cv, v_new.astype(cv.dtype),
                                             pos, axis=1)
    return ck, cv


def cached_sdpa(q, ck, cv, limit, scale: float = None, mask=None,
                window: int = None):
    """Attention of q (B, T, H, D) against the full cache (B, S, K, D),
    masked to cache positions < `limit` plus bottom-right-aligned
    causality inside the query block (query t attends cache positions
    <= limit - T + t).  `limit` may be a scalar or a (B,) vector of
    per-row limits (continuous batching: every slot attends its own
    prefix inside one compiled step).  GQA (H % K == 0) and the grouped
    einsums are delegated to attention._sdpa_reference — one attention
    math, two entry points.  `mask`: optional (B, 1|H, 1|T, S) boolean
    padding mask ANDed with the validity window.  `window`:
    Mistral-style sliding window — each query also ignores cache
    positions more than `window - 1` behind it."""
    from .attention import _sdpa_reference
    T = q.shape[1]
    S = ck.shape[1]
    scale = scale or (1.0 / math.sqrt(q.shape[-1]))
    kpos = jnp.arange(S)[None, None, None, :]           # (1, 1, 1, S)
    lim = jnp.asarray(limit)
    lim = lim.reshape((-1, 1, 1, 1)) if lim.ndim else lim
    qpos = lim - T + jnp.arange(T)[None, None, :, None]  # (B|1, 1, T, 1)
    valid = kpos <= qpos                                 # (B|1, 1, T, S)
    if window is not None:
        valid = jnp.logical_and(valid, kpos > qpos - window)
    if mask is not None:
        valid = jnp.logical_and(valid, mask)
    return _sdpa_reference(q, ck, cv, False, valid, scale)
