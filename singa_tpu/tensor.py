"""Tensor: n-d array with device placement and autograd hooks.

Capability parity: the reference's ``singa::Tensor`` + per-device math
dispatch tables (BASELINE.json:5 — "Tensor math dispatches to XLA instead
of tensor_math_cuda").  TPU-first design: a Tensor *wraps* an immutable
``jax.Array`` (or a tracer while a step is being captured) and re-binds it
on in-place ops — functionalization-by-rebinding, which is what lets the
imperative SINGA API trace cleanly into a single XLA module (SURVEY.md
section 7.3 item 2).

Module-level functions mirror the reference's ``singa.tensor`` namespace
(from_numpy, to_numpy, add, mul, matmul, reshape, ...).  Differentiable
math routes through singa_tpu.autograd so the tape sees it; raw
(non-differentiable) helpers operate on ``.data`` directly.
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import device as device_mod
from .device import Device

__all__ = [
    "Tensor", "from_numpy", "to_numpy", "from_raw", "zeros", "ones",
    "zeros_like", "ones_like", "full", "arange", "eye", "gaussian",
    "uniform", "bernoulli", "set_seed", "add", "sub", "mul", "div",
    "matmul", "mult", "reshape", "transpose", "flatten", "squeeze",
    "unsqueeze", "concatenate", "stack", "split", "abs", "exp", "log",
    "sqrt", "pow", "square", "sign", "tanh", "sigmoid", "relu", "sum",
    "mean", "max", "min", "argmax", "argmin", "clip", "einsum",
    "copy_data_to_from", "default_float", "sum_all",
    "softmax", "lt", "le", "gt", "ge", "eq",
    "eltwise_mult", "axpy", "add_column", "add_row", "sum_columns",
    "sum_rows", "tensordot", "batchmatmul", "repeat", "ceil", "floor",
    "round",
]

# lazy: creating a PRNGKey initializes the JAX backend, and importing
# singa_tpu must not force that (e.g. the axon TPU tunnel can take tens
# of seconds to come up when the user only wants CPU)
_rng_key = None


def set_seed(seed: int) -> None:
    global _rng_key
    _rng_key = jax.random.PRNGKey(int(seed))


def _next_key():
    global _rng_key
    if _rng_key is None:
        _rng_key = jax.random.PRNGKey(0)
    _rng_key, sub = jax.random.split(_rng_key)
    return sub


def default_float(dev: Optional[Device]) -> np.dtype:
    return (dev or device_mod.get_default_device()).default_dtype


class Tensor:
    """SINGA-style tensor.

    Attributes mirroring the reference surface:
      * ``device``       — owning Device
      * ``requires_grad``— participates in autograd
      * ``stores_grad``  — is a leaf parameter whose grad is materialized
      * ``creator``      — the autograd Operator that produced it (tape edge)
    """

    __slots__ = ("data", "device", "requires_grad", "stores_grad",
                 "creator", "name", "_grad")
    __array_priority__ = 100  # numpy defers to us in mixed expressions

    def __init__(self, shape: Optional[Sequence[int]] = None,
                 device: Optional[Device] = None, dtype=None,
                 data=None, requires_grad: bool = True,
                 stores_grad: bool = False, creator=None,
                 name: Optional[str] = None):
        self.device = device or device_mod.get_default_device()
        if data is None:
            if shape is None:
                raise ValueError("Tensor needs shape or data")
            dtype = dtype or self.device.default_dtype
            data = jnp.zeros(tuple(shape), dtype=dtype)
        else:
            if isinstance(data, Tensor):
                data = data.data
            elif isinstance(data, np.ndarray):
                data = jnp.asarray(data, dtype=dtype) if dtype else jnp.asarray(data)
            elif not isinstance(data, jnp.ndarray) and not _is_tracer(data):
                data = jnp.asarray(data, dtype=dtype)
            if dtype is not None and data.dtype != np.dtype(dtype) and not _is_tracer(data):
                data = data.astype(dtype)
        self.data = data
        self.requires_grad = requires_grad
        self.stores_grad = stores_grad
        self.creator = creator
        self.name = name
        self._grad = None

    # -- shape/dtype ---------------------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self.data.shape)

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def ndim(self) -> int:
        return self.data.ndim

    @property
    def size(self) -> int:
        return int(np.prod(self.data.shape)) if self.data.shape else 1

    def nDim(self) -> int:  # noqa: N802 — reference casing
        return self.ndim

    def Size(self) -> int:  # noqa: N802
        return self.size

    @property
    def T(self) -> "Tensor":
        from . import autograd
        return autograd.transpose(self)

    @property
    def grad(self) -> Optional["Tensor"]:
        return self._grad

    @grad.setter
    def grad(self, g) -> None:
        self._grad = g

    # -- device movement / conversion ---------------------------------------
    def to_device(self, dev: Device) -> "Tensor":
        """In-place device move (reference semantics)."""
        if not _is_tracer(self.data):
            self.data = dev.put(self.data)
        self.device = dev
        return self

    def as_type(self, dtype) -> "Tensor":
        from . import autograd
        return autograd.cast(self, dtype)

    def astype(self, dtype) -> "Tensor":
        return self.as_type(dtype)

    def to_numpy(self) -> np.ndarray:
        return np.asarray(self.data)

    def __array__(self, dtype=None, copy=None) -> np.ndarray:
        """numpy interop: np.asarray(t) fetches the buffer in one
        device->host copy.  Without this, numpy falls back to
        element-wise __getitem__ — thousands of autograd slice dispatches
        for one conversion (the generate()-with-Tensor-prompt hang)."""
        if copy is False:
            raise ValueError(
                "a device-backed Tensor cannot be converted to numpy "
                "without a copy (np.asarray(..., copy=False))")
        a = np.asarray(self.data)
        if dtype is not None:
            a = a.astype(dtype, copy=False)
        if copy:
            # honor the NumPy 2 contract: copy=True must return a fresh
            # WRITABLE array (np.asarray of a jax.Array can be a
            # read-only zero-copy view)
            a = np.array(a, copy=True)
        return a

    def numpy(self) -> np.ndarray:
        return self.to_numpy()

    def item(self):
        return self.to_numpy().item()

    def clone(self) -> "Tensor":
        return Tensor(data=self.data, device=self.device,
                      requires_grad=self.requires_grad,
                      stores_grad=self.stores_grad, name=self.name)

    def detach(self) -> "Tensor":
        return Tensor(data=self.data, device=self.device,
                      requires_grad=False, stores_grad=False)

    # -- in-place fills (leaf initialization; not differentiated) ------------
    def set_value(self, x) -> "Tensor":
        self.data = jnp.full(self.shape, x, dtype=self.dtype)
        return self

    def gaussian(self, mean: float = 0.0, std: float = 1.0) -> "Tensor":
        self.data = (mean + std * jax.random.normal(
            _next_key(), self.shape, dtype=jnp.float32)).astype(self.dtype)
        return self

    def uniform(self, low: float = 0.0, high: float = 1.0) -> "Tensor":
        self.data = jax.random.uniform(
            _next_key(), self.shape, dtype=jnp.float32,
            minval=low, maxval=high).astype(self.dtype)
        return self

    def bernoulli(self, p: float) -> "Tensor":
        self.data = jax.random.bernoulli(
            _next_key(), p, self.shape).astype(self.dtype)
        return self

    def copy_from(self, src: Union["Tensor", np.ndarray]) -> "Tensor":
        src_data = src.data if isinstance(src, Tensor) else jnp.asarray(src)
        self.data = src_data.reshape(self.shape).astype(self.dtype)
        return self

    def copy_from_numpy(self, np_array: np.ndarray) -> "Tensor":
        return self.copy_from(np_array)

    # -- shape ops (differentiable, route through autograd) ------------------
    def reshape(self, shape) -> "Tensor":
        from . import autograd
        return autograd.reshape(self, shape)

    def view(self, *shape) -> "Tensor":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = shape[0]
        return self.reshape(shape)

    def transpose(self, axes=None) -> "Tensor":
        from . import autograd
        return autograd.transpose(self, axes)

    def flatten(self, start_axis: int = 0) -> "Tensor":
        from . import autograd
        return autograd.flatten(self, start_axis)

    def squeeze(self, axis=None) -> "Tensor":
        from . import autograd
        return autograd.squeeze(self, axis)

    def sum(self, axis=None, keepdims=False) -> "Tensor":
        from . import autograd
        return autograd.reduce_sum(self, axis, keepdims)

    def mean(self, axis=None, keepdims=False) -> "Tensor":
        from . import autograd
        return autograd.reduce_mean(self, axis, keepdims)

    # -- arithmetic (differentiable) -----------------------------------------
    def __add__(self, other):
        from . import autograd
        return autograd.add(self, _wrap(other, self))

    __radd__ = __add__

    def __sub__(self, other):
        from . import autograd
        return autograd.sub(self, _wrap(other, self))

    def __rsub__(self, other):
        from . import autograd
        return autograd.sub(_wrap(other, self), self)

    def __mul__(self, other):
        from . import autograd
        return autograd.mul(self, _wrap(other, self))

    __rmul__ = __mul__

    def __truediv__(self, other):
        from . import autograd
        return autograd.div(self, _wrap(other, self))

    def __rtruediv__(self, other):
        from . import autograd
        return autograd.div(_wrap(other, self), self)

    def __matmul__(self, other):
        from . import autograd
        return autograd.matmul(self, other)

    def __pow__(self, p):
        from . import autograd
        return autograd.pow(self, p)

    def __neg__(self):
        from . import autograd
        return autograd.neg(self)

    # in-place variants rebind .data (functionalization-by-rebinding)
    def __iadd__(self, other):
        out = self.__add__(other)
        self.data, self.creator = out.data, out.creator
        return self

    def __isub__(self, other):
        out = self.__sub__(other)
        self.data, self.creator = out.data, out.creator
        return self

    def __imul__(self, other):
        out = self.__mul__(other)
        self.data, self.creator = out.data, out.creator
        return self

    def __itruediv__(self, other):
        out = self.__truediv__(other)
        self.data, self.creator = out.data, out.creator
        return self

    # comparisons: non-differentiable masks
    def __lt__(self, other):
        return _cmp(self, other, jnp.less)

    def __le__(self, other):
        return _cmp(self, other, jnp.less_equal)

    def __gt__(self, other):
        return _cmp(self, other, jnp.greater)

    def __ge__(self, other):
        return _cmp(self, other, jnp.greater_equal)

    def __getitem__(self, idx):
        from . import autograd
        return autograd.index(self, idx)

    def __len__(self) -> int:
        return self.shape[0] if self.shape else 0

    def __repr__(self) -> str:
        tag = "tracer" if _is_tracer(self.data) else "array"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype}, "
                f"device={self.device.name}, {tag})")


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _wrap(x, like: Tensor) -> Tensor:
    if isinstance(x, Tensor):
        return x
    return Tensor(data=jnp.asarray(x, dtype=like.dtype), device=like.device,
                  requires_grad=False)


def _cmp(a: Tensor, b, op) -> Tensor:
    bv = b.data if isinstance(b, Tensor) else b
    return Tensor(data=op(a.data, bv).astype(a.dtype), device=a.device,
                  requires_grad=False)


# ---------------------------------------------------------------------------
# module-level constructors (singa.tensor namespace parity)
# ---------------------------------------------------------------------------

def from_numpy(np_array: np.ndarray, dev: Optional[Device] = None) -> Tensor:
    dev = dev or device_mod.get_default_device()
    arr = jnp.asarray(np_array)
    return Tensor(data=dev.put(arr), device=dev, requires_grad=False)


def to_numpy(t: Tensor) -> np.ndarray:
    return t.to_numpy()


def from_raw(jax_array, dev: Optional[Device] = None, **kw) -> Tensor:
    return Tensor(data=jax_array, device=dev or device_mod.get_default_device(), **kw)


def zeros(shape, dev=None, dtype=None) -> Tensor:
    dev = dev or device_mod.get_default_device()
    return Tensor(data=jnp.zeros(shape, dtype=dtype or dev.default_dtype), device=dev)


def ones(shape, dev=None, dtype=None) -> Tensor:
    dev = dev or device_mod.get_default_device()
    return Tensor(data=jnp.ones(shape, dtype=dtype or dev.default_dtype), device=dev)


def full(shape, value, dev=None, dtype=None) -> Tensor:
    dev = dev or device_mod.get_default_device()
    return Tensor(data=jnp.full(shape, value, dtype=dtype or dev.default_dtype), device=dev)


def zeros_like(t: Tensor) -> Tensor:
    return Tensor(data=jnp.zeros_like(t.data), device=t.device)


def ones_like(t: Tensor) -> Tensor:
    return Tensor(data=jnp.ones_like(t.data), device=t.device)


def arange(start, stop=None, step=1, dev=None, dtype=None) -> Tensor:
    dev = dev or device_mod.get_default_device()
    return Tensor(data=jnp.arange(start, stop, step, dtype=dtype), device=dev)


def eye(n, dev=None, dtype=None) -> Tensor:
    dev = dev or device_mod.get_default_device()
    return Tensor(data=jnp.eye(n, dtype=dtype or dev.default_dtype), device=dev)


def gaussian(shape, mean=0.0, std=1.0, dev=None, dtype=None) -> Tensor:
    return Tensor(shape, dev, dtype).gaussian(mean, std)


def uniform(shape, low=0.0, high=1.0, dev=None, dtype=None) -> Tensor:
    return Tensor(shape, dev, dtype).uniform(low, high)


def bernoulli(shape, p, dev=None, dtype=None) -> Tensor:
    return Tensor(shape, dev, dtype).bernoulli(p)


def copy_data_to_from(dst: Tensor, src: Tensor, size: Optional[int] = None) -> None:
    dst.copy_from(src)


# ---------------------------------------------------------------------------
# module-level math: differentiable wrappers over autograd
# ---------------------------------------------------------------------------

def _ag():
    from . import autograd
    return autograd


def add(a, b):
    return _ag().add(a, b)


def sub(a, b):
    return _ag().sub(a, b)


def mul(a, b):
    return _ag().mul(a, b)


def mult(a, b):
    """Reference semantics: `tensor.mult` is MATRIX multiplication
    (GEMM/GEMV); the elementwise product is `eltwise_mult`."""
    return _ag().matmul(a, b)


def eltwise_mult(a, b):
    return _ag().mul(a, b)


def axpy(alpha: float, x: Tensor, y: Tensor) -> Tensor:
    """y += alpha * x in the reference's in-place style (rebinds y's
    buffer; returns y).  BLAS semantics: shapes must match exactly."""
    if tuple(x.shape) != tuple(y.shape):
        raise ValueError(f"axpy shape mismatch: x {x.shape} vs y {y.shape}")
    y.data = (y.data + alpha * x.data).astype(y.dtype)
    return y


def add_column(v: Tensor, m: Tensor) -> Tensor:
    """Add column vector v to every column of matrix m (in place)."""
    if m.ndim != 2 or v.size != m.shape[0]:
        raise ValueError(
            f"add_column needs v of length rows(m): v {v.shape}, m {m.shape}")
    m.data = (m.data + v.data.reshape(-1, 1)).astype(m.dtype)
    return m


def add_row(v: Tensor, m: Tensor) -> Tensor:
    """Add row vector v to every row of matrix m (in place)."""
    if m.ndim != 2 or v.size != m.shape[1]:
        raise ValueError(
            f"add_row needs v of length cols(m): v {v.shape}, m {m.shape}")
    m.data = (m.data + v.data.reshape(1, -1)).astype(m.dtype)
    return m


def sum_columns(m: Tensor) -> Tensor:
    """Sum over columns: (r, c) -> (r,)."""
    return _ag().reduce_sum(m, axis=1)


def sum_rows(m: Tensor) -> Tensor:
    """Sum over rows: (r, c) -> (c,)."""
    return _ag().reduce_sum(m, axis=0)


def tensordot(a, b, axes=2):
    return _ag().tensordot(a, b, axes)


def batchmatmul(a, b):
    """Batched matmul over leading dims (reference name)."""
    return _ag().matmul(a, b)


def repeat(t, repeats, axis=None):
    return _ag().repeat(t, repeats, axis)


def ceil(t):
    return _ag().ceil(t)


def floor(t):
    return _ag().floor(t)


def round(t):  # noqa: A001 - reference op name
    return _ag().round(t)


def div(a, b):
    return _ag().div(a, b)


def matmul(a, b):
    return _ag().matmul(a, b)


def einsum(subscripts, *ts):
    return _ag().einsum(subscripts, *ts)


def reshape(t, shape):
    return _ag().reshape(t, shape)


def transpose(t, axes=None):
    return _ag().transpose(t, axes)


def flatten(t, start_axis=0):
    return _ag().flatten(t, start_axis)


def squeeze(t, axis=None):
    return _ag().squeeze(t, axis)


def unsqueeze(t, axis):
    return _ag().unsqueeze(t, axis)


def concatenate(ts, axis=0):
    return _ag().cat(ts, axis)


def stack(ts, axis=0):
    return _ag().stack(ts, axis)


def split(t, parts, axis=0):
    return _ag().split(t, parts, axis)


def abs(t):
    return _ag().abs(t)


def exp(t):
    return _ag().exp(t)


def log(t):
    return _ag().log(t)


def sqrt(t):
    return _ag().sqrt(t)


def square(t):
    return _ag().mul(t, t)


def pow(t, p):
    return _ag().pow(t, p)


def sign(t):
    return Tensor(data=jnp.sign(t.data), device=t.device, requires_grad=False)


def tanh(t):
    return _ag().tanh(t)


def sigmoid(t):
    return _ag().sigmoid(t)


def relu(t):
    return _ag().relu(t)


def softmax(t, axis=-1):
    return _ag().softmax(t, axis)


def sum(t, axis=None, keepdims=False):
    return _ag().reduce_sum(t, axis, keepdims)


def sum_all(t):
    return float(jnp.sum(t.data))


def mean(t, axis=None, keepdims=False):
    return _ag().reduce_mean(t, axis, keepdims)


def max(t, axis=None, keepdims=False):
    return _ag().reduce_max(t, axis, keepdims)


def min(t, axis=None, keepdims=False):
    return _ag().reduce_min(t, axis, keepdims)


def argmax(t, axis=-1):
    return Tensor(data=jnp.argmax(t.data, axis=axis), device=t.device,
                  requires_grad=False)


def argmin(t, axis=-1):
    return Tensor(data=jnp.argmin(t.data, axis=axis), device=t.device,
                  requires_grad=False)


def clip(t, lo, hi):
    return _ag().clip(t, lo, hi)


def lt(a, b):
    return a < b


def le(a, b):
    return a <= b


def gt(a, b):
    return a > b


def ge(a, b):
    return a >= b


def eq(a, b):
    bv = b.data if isinstance(b, Tensor) else b
    return Tensor(data=(a.data == bv).astype(a.dtype), device=a.device,
                  requires_grad=False)
