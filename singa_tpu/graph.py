"""Graph capture bookkeeping — the Python face of the Graph/Scheduler
(capability parity: BASELINE.json:5 "the Graph/Scheduler that buffers
singa.autograd ops compiles the captured computational graph into a
single XLA HLO module").

In this framework the *capture* is a jax trace of the user's imperative
``train_one_batch`` and the *schedule* is XLA's — but we keep a real
graph object: the closed jaxpr (op list, topological order) plus the
lowered/compiled artifacts, so users can inspect what was captured, dump
HLO, and get cost analysis (FLOPs → MFU accounting, BASELINE.json:5
"≥45% MFU" target).  The native C++ scheduler (csrc/scheduler.cc) is fed
from this same captured graph for host-side execution planning.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

__all__ = ["CapturedGraph", "Schedule", "reset_graph"]


class CapturedGraph:
    """A captured training/eval step: jaxpr + lowered + compiled handles."""

    def __init__(self, name: str, jaxpr=None, lowered=None, compiled=None,
                 jaxpr_thunk=None):
        self.name = name
        self._jaxpr = jaxpr
        self._jaxpr_thunk = jaxpr_thunk
        self.lowered = lowered
        self.compiled = compiled

    @property
    def jaxpr(self):
        if self._jaxpr is None and self._jaxpr_thunk is not None:
            self._jaxpr = self._jaxpr_thunk()
            self._jaxpr_thunk = None
        return self._jaxpr

    # -- introspection --------------------------------------------------------
    @property
    def num_ops(self) -> int:
        if self.jaxpr is not None:
            return _count_eqns(self.jaxpr.jaxpr)
        # fall back to counting HLO instructions in the lowered module
        txt = self.hlo_text()
        return sum(1 for line in txt.splitlines() if " = " in line)

    def op_types(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        if self.jaxpr is not None:
            _collect_ops(self.jaxpr.jaxpr, out)
        return out

    def hlo_text(self) -> str:
        if self.lowered is None:
            return ""
        return self.lowered.as_text()

    def compiled_hlo(self) -> str:
        if self.compiled is None:
            return ""
        try:
            return self.compiled.as_text()
        except Exception:
            return ""

    def cost_analysis(self) -> Dict[str, Any]:
        """XLA cost analysis of the compiled module (flops, bytes)."""
        if self.compiled is None:
            return {}
        from .obs import events as obs_events
        try:
            with obs_events.span("graph.cost_analysis", graph=self.name):
                ca = self.compiled.cost_analysis()
            if isinstance(ca, list):
                ca = ca[0] if ca else {}
            return dict(ca)
        except Exception:
            return {}

    def flops(self) -> float:
        return float(self.cost_analysis().get("flops", 0.0))

    def memory_analysis(self) -> Dict[str, Any]:
        if self.compiled is None:
            return {}
        try:
            ma = self.compiled.memory_analysis()
            return {k: getattr(ma, k) for k in dir(ma) if not k.startswith("_")}
        except Exception:
            return {}

    def save_hlo(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.hlo_text())

    # -- native scheduler bridge ---------------------------------------------
    def schedule(self):
        """Feed the captured op graph to the native C++ scheduler
        (csrc/scheduler.cc): deterministic topological order + first-fit
        arena plan for a serial host replay.  Returns a Schedule with
        .order, .arena_bytes, .num_nodes — the reference Graph/Scheduler's
        introspection surface, TPU-side scheduling stays XLA's."""
        from . import _core
        from .obs import events as obs_events
        if not _core.available():
            raise RuntimeError("native core unavailable")
        cj = self.jaxpr
        if cj is None:
            raise RuntimeError("no jaxpr captured for this graph")
        jaxpr = cj.jaxpr
        with obs_events.span("graph.schedule", graph=self.name,
                             eqns=len(jaxpr.eqns)):
            ng = _core.NativeGraph()
            buf_ids = {}

            def bid(v):
                key = id(v)
                if key not in buf_ids:
                    buf_ids[key] = len(buf_ids)
                return buf_ids[key]

            for v in jaxpr.invars:
                bid(v)
            for eqn in jaxpr.eqns:
                # Literals carry .val; Vars don't — version-stable check
                ins = [bid(v) for v in eqn.invars if not hasattr(v, "val")]
                outs = [bid(v) for v in eqn.outvars]
                sizes = [int(np.prod(v.aval.shape)) * v.aval.dtype.itemsize
                         for v in eqn.outvars]
                ng.add_node(eqn.primitive.name, ins, outs, sizes)
            # sink node: jaxpr outputs are read after the last eqn, so
            # their buffers must stay live to the end of the plan (replay
            # returns arena views of them)
            sink_ins = [bid(v) for v in jaxpr.outvars
                        if not hasattr(v, "val")]
            if sink_ins:
                ng.add_node("__sink__", sink_ins, [], [], 0)
            order = ng.toposort()
            arena, offsets = ng.plan_memory()
            return Schedule(order=order, arena_bytes=arena,
                            num_nodes=ng.num_nodes, buffer_offsets=offsets,
                            closed_jaxpr=cj, var_buf=buf_ids)

    def __repr__(self):
        return f"<CapturedGraph {self.name}: {self.num_ops} ops>"


class Schedule:
    """Native-planned execution schedule: deterministic topological order
    plus the first-fit arena plan from csrc/scheduler.cc — and a host
    REPLAY that consumes both (SURVEY.md §5: the scheduler's
    single-threaded deterministic replay mode).  Replay executes the
    captured jaxpr eqn-by-eqn in the planned order, writes f32 results
    into their planned arena offsets (so an unsound liveness plan
    corrupts outputs and fails the equivalence tests), and dispatches
    the hot elementwise/GEMM primitives to the native csrc kernels."""

    def __init__(self, order, arena_bytes, num_nodes, buffer_offsets,
                 closed_jaxpr=None, var_buf=None):
        self.order = order
        self.arena_bytes = arena_bytes
        self.num_nodes = num_nodes
        self.buffer_offsets = buffer_offsets
        self.closed_jaxpr = closed_jaxpr
        self.var_buf = var_buf or {}
        self.native_hits = 0

    def replay(self, *args, use_native: bool = True):
        """Serial host execution of the captured graph in planned order.

        `args` match the jaxpr invars (flattened). Returns the flat
        output list. Single-threaded and deterministic by construction —
        the race-detection story for the host path."""
        import jax.numpy as jnp

        from . import _core

        cj = self.closed_jaxpr
        if cj is None:
            raise RuntimeError("schedule has no captured jaxpr")
        jaxpr = cj.jaxpr
        if len(args) != len(jaxpr.invars):
            raise ValueError(f"replay needs {len(jaxpr.invars)} args, "
                             f"got {len(args)}")
        native_ok = use_native and _core.available()
        arena = (np.zeros(self.arena_bytes, np.uint8)
                 if self.arena_bytes else None)
        env = {}
        for v, c in zip(jaxpr.constvars, cj.consts):
            env[id(v)] = c
        for v, a in zip(jaxpr.invars, args):
            env[id(v)] = np.asarray(a)

        def read(v):
            if hasattr(v, "val"):
                return v.val
            return env[id(v)]

        def place(v, value):
            """Store an output, into its planned arena slot when f32."""
            aval = v.aval
            off = self.buffer_offsets.get(self.var_buf.get(id(v)))
            if (arena is not None and off is not None
                    and aval.dtype == np.float32 and aval.shape):
                n = int(np.prod(aval.shape))
                view = np.frombuffer(arena, np.float32, count=n,
                                     offset=off).reshape(aval.shape)
                view[...] = np.asarray(value, np.float32)
                env[id(v)] = view
            else:
                env[id(v)] = np.asarray(value)

        self.native_hits = 0
        for idx in self.order:
            if idx >= len(jaxpr.eqns):
                continue              # liveness sink node, nothing to run
            eqn = jaxpr.eqns[idx]
            vals = [read(v) for v in eqn.invars]
            outs = self._native_eqn(eqn, vals) if native_ok else None
            if outs is None:
                subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
                res = eqn.primitive.bind(
                    *subfuns, *[jnp.asarray(v) for v in vals], **bind_params)
                outs = list(res) if eqn.primitive.multiple_results else [res]
            else:
                self.native_hits += 1
            for v, o in zip(eqn.outvars, outs):
                place(v, o)
        # copy at the boundary: outputs must not be aliases into the
        # (possibly large, mutable) shared arena
        return [np.array(read(v)) for v in jaxpr.outvars]

    @staticmethod
    def _native_eqn(eqn, vals):
        """Dispatch an eqn to csrc kernels; None -> no native lowering."""
        from . import _core
        name = eqn.primitive.name
        if any(not isinstance(v, np.ndarray) or v.dtype != np.float32
               for v in vals):
            return None
        if name in ("add", "sub", "mul", "div") and len(vals) == 2 \
                and vals[0].shape == vals[1].shape and vals[0].shape:
            return [getattr(_core, name)(vals[0], vals[1])]
        if name == "exp" and vals[0].shape:
            return [_core.exp(vals[0])]
        if name == "tanh" and vals[0].shape:
            return [_core.tanh(vals[0])]
        if name == "logistic" and vals[0].shape:
            return [_core.sigmoid(vals[0])]
        if name == "dot_general":
            dn = eqn.params["dimension_numbers"]
            # plain (m,k)@(k,n) f32 — native f32 FMA gemm matches any XLA
            # CPU precision setting for f32 inputs
            if (dn == (((1,), (0,)), ((), ()))
                    and vals[0].ndim == 2 and vals[1].ndim == 2
                    and np.dtype(eqn.params.get("preferred_element_type")
                                 or np.float32) == np.float32):
                return [_core.gemm(vals[0], vals[1])]
        return None

    def __repr__(self):
        return (f"<Schedule nodes={self.num_nodes} "
                f"arena={self.arena_bytes}B>")


def _count_eqns(jaxpr) -> int:
    n = len(jaxpr.eqns)
    for eq in jaxpr.eqns:
        for sub in _sub_jaxprs(eq):
            n += _count_eqns(sub)
    return n


def _collect_ops(jaxpr, out: Dict[str, int]) -> None:
    for eq in jaxpr.eqns:
        out[eq.primitive.name] = out.get(eq.primitive.name, 0) + 1
        for sub in _sub_jaxprs(eq):
            _collect_ops(sub, out)


def _sub_jaxprs(eqn):
    for v in eqn.params.values():
        if hasattr(v, "jaxpr"):
            yield v.jaxpr
        elif isinstance(v, (list, tuple)):
            for x in v:
                if hasattr(x, "jaxpr"):
                    yield x.jaxpr


def reset_graph(device=None) -> None:
    """Drop captured graphs so the next step re-captures (reference
    Device.ResetGraph). Models track their own executors; this clears the
    process-wide registry."""
    from . import model as model_mod
    model_mod._invalidate_all_graphs()
