"""FaultSpec / FaultPlan — deterministic, seeded fault schedules.

A plan is a list of specs, each binding one registered injection site
(:mod:`singa_tpu.faults.sites`) to one fault kind and one trigger rule.
Trigger decisions are pure functions of ``(seed, site, spec index,
call index)`` — no wall clock, no global RNG — so a chaos run replays
bit-identically under the same plan, which is what lets the chaos
suite assert token-identical serving output against a fault-free run.

Fault kinds:

* ``error``      — raise :class:`InjectedFault` (a ``RuntimeError``):
                   the transient-failure shape every retry path in the
                   repo catches;
* ``hang``       — sleep ``delay_s`` inside the site: long enough
                   relative to a Heartbeat timeout, this exercises hang
                   detection and the recovery paths behind it;
* ``torn_write`` — truncate the file named by the site's ``path``
                   context (checkpoint torn-write simulation);
* ``torn_frame`` — truncate the BYTES payload flowing past the site
                   (wire torn-frame simulation, applied by
                   :func:`faults.tear` — the in-memory analogue of
                   ``torn_write`` for transport seams);
* ``nan``        — replace float array values flowing past the site
                   with NaN (applied by :func:`faults.corrupt`).

Env syntax (parsed by :meth:`FaultPlan.parse`, activated at import by
``SINGA_FAULTS``; seed via ``SINGA_FAULTS_SEED``)::

    SINGA_FAULTS="serve.decode=error:every=3,times=2;serve.prefill=hang:at=1,delay=0.5"

i.e. ``;``-separated specs of ``<site>=<kind>[:key=val[,key=val...]]``
with keys ``at`` (1-based call index), ``every`` (every Kth call),
``p`` (probability per call, seeded-deterministic), ``times`` (cap on
fires), ``delay`` (hang seconds).
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Dict, List, Optional, Tuple

from . import sites as _sites

__all__ = ["KINDS", "InjectedFault", "FaultSpec", "FaultPlan"]

KINDS = ("error", "hang", "torn_write", "torn_frame", "nan")


class InjectedFault(RuntimeError):
    """The transient error the injector raises for kind ``error`` — a
    plain RuntimeError subclass, so it takes exactly the retry paths a
    real transient dispatch failure would."""


def _det_uniform(seed: int, site: str, spec_idx: int, n: int) -> float:
    """Deterministic uniform in [0, 1): stable across processes and
    PYTHONHASHSEED (blake2b, not hash())."""
    h = hashlib.blake2b(f"{seed}:{site}:{spec_idx}:{n}".encode(),
                        digest_size=8).digest()
    return int.from_bytes(h, "big") / float(1 << 64)


class FaultSpec:
    """One (site, kind, trigger) rule.  Exactly one of ``at`` /
    ``every`` / ``p`` selects calls (none given = every call); ``times``
    caps total fires (defaults to 1 for ``at``, unlimited otherwise)."""

    __slots__ = ("site", "kind", "at", "every", "p", "times", "delay_s")

    def __init__(self, site: str, kind: str, *, at: Optional[int] = None,
                 every: Optional[int] = None, p: Optional[float] = None,
                 times: Optional[int] = None, delay_s: float = 0.25):
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r} "
                             f"(known: {KINDS})")
        if not _sites.is_known(site):
            raise ValueError(
                f"unknown injection site {site!r} (registered: "
                f"{sorted(_sites.SITES)})")
        if kind not in _sites.supported_kinds(site):
            raise ValueError(
                f"site {site!r} does not support kind {kind!r} "
                f"(supports: {_sites.supported_kinds(site)})")
        ntrig = sum(v is not None for v in (at, every, p))
        if ntrig > 1:
            raise ValueError("at / every / p are mutually exclusive "
                             f"(got at={at}, every={every}, p={p})")
        if at is not None and at < 1:
            raise ValueError(f"at is a 1-based call index, got {at}")
        if every is not None and every < 1:
            raise ValueError(f"every must be >= 1, got {every}")
        if p is not None and not 0.0 <= p <= 1.0:
            raise ValueError(f"p must be in [0, 1], got {p}")
        if times is not None and times < 1:
            raise ValueError(f"times must be >= 1, got {times}")
        if delay_s < 0:
            raise ValueError(f"delay_s must be >= 0, got {delay_s}")
        self.site = site
        self.kind = kind
        self.at = at
        self.every = every
        self.p = p
        self.times = times if times is not None else (
            1 if at is not None else None)
        self.delay_s = float(delay_s)

    def triggers(self, seed: int, spec_idx: int, n: int) -> bool:
        """Pure trigger decision for the site's ``n``-th call (1-based);
        the ``times`` cap is the plan's job (it owns the fire count)."""
        if self.at is not None:
            return n == self.at
        if self.every is not None:
            return n % self.every == 0
        if self.p is not None:
            return _det_uniform(seed, self.site, spec_idx, n) < self.p
        return True

    def __repr__(self) -> str:
        trig = (f"at={self.at}" if self.at is not None
                else f"every={self.every}" if self.every is not None
                else f"p={self.p}" if self.p is not None else "always")
        return (f"FaultSpec({self.site}={self.kind}:{trig}"
                f"{f',times={self.times}' if self.times else ''})")


class FaultPlan:
    """A seeded set of :class:`FaultSpec` rules plus the mutable firing
    state (per-site call counters, per-spec fire counts, a log of every
    fired fault).  Activate with ``faults.active(plan)`` (context
    manager) or ``faults.install(plan)``; an EMPTY plan is the
    site-call-count probe the overhead tests use — it fires nothing but
    still counts every ``fire()``/``corrupt()`` that reaches it."""

    def __init__(self, specs: Optional[List[FaultSpec]] = None,
                 seed: int = 0):
        self.specs: List[FaultSpec] = list(specs or [])
        self.seed = int(seed)
        self.calls: Dict[str, int] = {}      # site -> calls observed
        self.fired: List[Dict[str, Any]] = []  # log of fired faults
        self._fires: Dict[int, int] = {}     # spec idx -> fires so far
        self._lock = threading.Lock()

    # -- construction ------------------------------------------------------
    @classmethod
    def parse(cls, text: str, seed: int = 0) -> "FaultPlan":
        """Parse the ``SINGA_FAULTS`` syntax (see module docstring)."""
        specs = []
        for part in text.split(";"):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(
                    f"bad fault spec {part!r}: expected "
                    f"<site>=<kind>[:key=val,...]")
            site, rhs = part.split("=", 1)
            kind, _, opts = rhs.partition(":")
            kw: Dict[str, Any] = {}
            for opt in filter(None, (o.strip() for o in opts.split(","))):
                if "=" not in opt:
                    raise ValueError(f"bad fault option {opt!r} in "
                                     f"{part!r}: expected key=val")
                k, v = opt.split("=", 1)
                k = k.strip()
                if k in ("at", "every", "times"):
                    kw[k] = int(v)
                elif k == "p":
                    kw[k] = float(v)
                elif k == "delay":
                    kw["delay_s"] = float(v)
                else:
                    raise ValueError(
                        f"unknown fault option {k!r} in {part!r} "
                        f"(known: at, every, p, times, delay)")
            specs.append(FaultSpec(site.strip(), kind.strip(), **kw))
        return cls(specs, seed=seed)

    # -- firing state ------------------------------------------------------
    def match(self, site: str, kinds: Tuple[str, ...],
              count: bool = True) -> List[Tuple[int, FaultSpec]]:
        """Advance ``site``'s call counter (when ``count``) and return
        the (spec_idx, spec) pairs of the given kinds that fire on this
        call, respecting each spec's ``times`` cap."""
        with self._lock:
            if count:
                n = self.calls[site] = self.calls.get(site, 0) + 1
            else:
                n = self.calls.get(site, 0)
            out = []
            for i, s in enumerate(self.specs):
                if s.site != site or s.kind not in kinds:
                    continue
                if s.times is not None and self._fires.get(i, 0) >= s.times:
                    continue
                if s.triggers(self.seed, i, n):
                    self._fires[i] = self._fires.get(i, 0) + 1
                    self.fired.append({"site": site, "kind": s.kind,
                                       "call": n, "spec": i})
                    out.append((i, s))
            return out

    def fire_count(self, site: Optional[str] = None) -> int:
        """Fired faults so far (optionally for one site)."""
        with self._lock:
            return len([f for f in self.fired
                        if site is None or f["site"] == site])

    def __repr__(self) -> str:
        return (f"FaultPlan(seed={self.seed}, specs={self.specs}, "
                f"fired={len(self.fired)})")
