"""singa_tpu.faults — deterministic fault injection (chaos testing).

Every failure path this repo claims to survive — train-step retry,
torn-checkpoint fallback, serve-engine quarantine and arena recovery,
hang detection — is exercisable through NAMED injection sites wired at
the real failure seams (:mod:`.sites`), driven by a seeded
:class:`~singa_tpu.faults.plan.FaultPlan` (:mod:`.plan`).  The chaos
tests in ``tests/test_faults.py`` replace the ad-hoc monkeypatching
that previously stood in for failures.

Usage::

    from singa_tpu import faults
    plan = faults.FaultPlan([
        faults.FaultSpec("serve.decode", "error", every=3, times=2),
        faults.FaultSpec("serve.prefill", "hang", at=2, delay_s=1.0),
    ], seed=42)
    with faults.active(plan):
        engine.run_until_idle()
    assert plan.fire_count() == 3

or from the environment (no code changes)::

    SINGA_FAULTS="train.step=error:every=50" python train.py

Design contract (asserted in tests):

* **zero overhead when off** — :func:`fire`/:func:`corrupt` are a
  single module-global ``None`` check when no plan is active; sites
  live OUTSIDE jit, so activating a plan never changes compiled-program
  cache keys, and with no plan active no obs event is ever emitted.
* **deterministic** — trigger decisions are pure functions of
  ``(seed, site, spec index, call index)``; a chaos run replays
  bit-identically.
* **observable** — every fired fault emits a ``fault.injected``
  counter through :mod:`singa_tpu.obs.events` (site, kind, call).
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Optional

from .plan import KINDS, FaultPlan, FaultSpec, InjectedFault
from .sites import SITES

__all__ = ["KINDS", "SITES", "FaultPlan", "FaultSpec", "InjectedFault",
           "fire", "corrupt", "tear", "active", "install", "uninstall",
           "get_active"]

_active: Optional[FaultPlan] = None


def get_active() -> Optional[FaultPlan]:
    return _active


def install(plan: Optional[FaultPlan]) -> None:
    """Make ``plan`` the process-wide active plan (None deactivates).
    Prefer the :func:`active` context manager in tests."""
    global _active
    _active = plan


def uninstall() -> None:
    install(None)


@contextlib.contextmanager
def active(plan: FaultPlan):
    """Activate ``plan`` for the dynamic extent of the block."""
    global _active
    if _active is not None:
        raise RuntimeError("a FaultPlan is already active — nested "
                           "activation would make firing ambiguous")
    _active = plan
    try:
        yield plan
    finally:
        _active = None


def _emit(site: str, kind: str, call: int) -> None:
    from ..obs import events, flight
    # attr is fault_kind, not kind: event attrs merge into the sink
    # line, and a bare "kind" would clobber the event's own kind field
    events.counter("fault.injected", 1, site=site, fault_kind=kind,
                   call=call)
    # every FIRED fault also lands in the live flight-recorder rings
    # (ServeEngine / TrainRunner), so an incident dump's timeline shows
    # the injected fault next to the retries/quarantine it caused; the
    # no-fault path never reaches here (zero-overhead contract)
    flight.broadcast("counter", "fault.injected", site=site,
                     fault_kind=kind, call=call)


def fire(site: str, **ctx: Any) -> None:
    """The injection hook: a no-op unless an active plan says this call
    of ``site`` faults.  Kind ``error`` raises :class:`InjectedFault`,
    ``hang`` sleeps the spec's ``delay_s`` (so a Heartbeat watching the
    caller fires), ``torn_write`` truncates the file at ``ctx['path']``.
    When several specs fire on the same call, hangs and truncations are
    applied first and an error is raised last."""
    plan = _active
    if plan is None:
        return
    hits = plan.match(site, ("error", "hang", "torn_write"))
    if not hits:
        return
    err: Optional[FaultSpec] = None
    for _, spec in hits:
        _emit(site, spec.kind, plan.calls.get(site, 0))
        if spec.kind == "hang":
            time.sleep(spec.delay_s)
        elif spec.kind == "torn_write":
            _truncate(ctx.get("path"))
        else:
            err = spec
    if err is not None:
        raise InjectedFault(
            f"injected transient fault at {site} "
            f"(call {plan.calls.get(site, 0)}, ctx {ctx or '{}'})")


def corrupt(site: str, value: Any) -> Any:
    """NaN-corruption hook: returns ``value`` unchanged unless a ``nan``
    spec fires, in which case every float array in it is replaced with
    NaNs.  Does not advance the site's call counter — by convention a
    ``nan``-capable site calls :func:`fire` first (pre-dispatch) and
    ``corrupt`` on the same logical call's output."""
    plan = _active
    if plan is None:
        return value
    hits = plan.match(site, ("nan",), count=False)
    if not hits:
        return value
    for _, spec in hits:
        _emit(site, spec.kind, plan.calls.get(site, 0))
    return _nanify(value)


def tear(site: str, data: bytes) -> bytes:
    """Torn-frame hook for byte payloads: returns ``data`` unchanged
    unless a ``torn_frame`` spec fires, in which case only the first
    half survives — the in-memory analogue of a ``torn_write`` for
    transport seams, where the payload is bytes on a wire rather than
    a file on disk.  Does not advance the site's call counter — by
    convention a transport site calls :func:`fire` first (pre-send)
    and ``tear`` on the same logical call's payload, mirroring the
    :func:`corrupt` convention."""
    plan = _active
    if plan is None:
        return data
    hits = plan.match(site, ("torn_frame",), count=False)
    if not hits:
        return data
    for _, spec in hits:
        _emit(site, spec.kind, plan.calls.get(site, 0))
    return data[:len(data) // 2]


def _truncate(path: Optional[str]) -> None:
    """Tear a file the way an interrupted write would: keep the first
    half, drop the rest.  (A site offering torn_write passes ``path``.)"""
    if not path or not os.path.exists(path):
        return
    size = os.path.getsize(path)
    if size < 2:
        return
    with open(path, "r+b") as f:
        f.truncate(size // 2)


def _nanify(value: Any) -> Any:
    import numpy as np

    def one(x):
        dt = getattr(x, "dtype", None)
        if dt is None or not np.issubdtype(np.dtype(dt), np.floating):
            return x
        if isinstance(x, np.ndarray):
            return np.full_like(x, np.nan)
        try:
            import jax.numpy as jnp
            return jnp.full_like(x, jnp.nan)
        except Exception:
            return x

    try:
        import jax
        return jax.tree.map(one, value)
    except Exception:
        return one(value)


def _init_from_env() -> None:
    text = os.environ.get("SINGA_FAULTS")
    if not text:
        return
    seed = int(os.environ.get("SINGA_FAULTS_SEED", "0") or 0)
    # a malformed plan must fail LOUDLY: the whole point of env
    # activation is a chaos run — silently injecting nothing would
    # report "survived" without ever being tested
    install(FaultPlan.parse(text, seed=seed))


_init_from_env()
