"""Injection-site registry: the named seams where faults can be fired.

A *site* is a host-side hook (`faults.fire(name, ...)` or
`faults.corrupt(name, value)`) placed at a failure seam the robustness
machinery claims to survive.  The registry is the contract between the
chaos tests and the code under test: a :class:`~singa_tpu.faults.plan.
FaultPlan` naming an unregistered site fails at construction (catching
typos before a chaos run silently injects nothing), and
``docs/robustness.md`` renders this table as the user-facing list.

Every site fires host-side Python — a fired fault never becomes part
of a compiled program, so activating a plan cannot change
compiled-program cache keys (asserted in tests/test_faults.py via the
serve engine's jit cache sizes).  All sites except ``comm.collective``
also fire outside tracing, once per runtime call; ``comm.collective``
necessarily fires at TRACE time (see its entry below for what that
means for ``at=``/``every=`` triggers).
"""

from __future__ import annotations

from typing import Dict, Tuple

__all__ = ["SITES", "INCIDENT_SITES", "supported_kinds", "is_known",
           "is_incident_site"]

#: site name -> (description, kinds the site supports).
#: ``error``/``hang`` are raised/slept by :func:`faults.fire` before the
#: guarded operation dispatches; ``torn_write`` truncates the file named
#: by the site's ``path`` context; ``torn_frame`` truncates the bytes
#: payload flowing past the site (applied by :func:`faults.tear`);
#: ``nan`` is applied by :func:`faults.corrupt` to the value flowing
#: PAST the site.
SITES: Dict[str, Tuple[str, Tuple[str, ...]]] = {
    "device.execute": (
        "compiled step-graph dispatch (model step executor); nan "
        "corrupts the step outputs (loss) after a clean dispatch",
        ("error", "hang", "nan")),
    "comm.collective": (
        "collective staging in parallel.communicator (allreduce / "
        "allgather / reduce_scatter / ppermute / broadcast); fires "
        "host-side at staging, so an injected error surfaces at trace "
        "time like a failed collective launch.  Collectives are "
        "in-graph ops: the site counts graph (re)traces, NOT "
        "executions — at=/every= triggers count traces, and a plan "
        "activated after warmup injects nothing until something "
        "retraces",
        ("error", "hang")),
    "ckpt.write": (
        "checkpoint serialization in train.ckpt (before the npz is "
        "written); an injected error surfaces through "
        "AsyncCheckpointManager.wait() exactly like ENOSPC",
        ("error", "hang")),
    "ckpt.torn": (
        "after the commit marker lands (ctx: path) — torn_write "
        "truncates the committed npz, simulating a crash/bit-rot torn "
        "file that the sha-checked restore path must skip",
        ("torn_write",)),
    "serve.prefill": (
        "serve engine prefill-into-slot dispatch (per admission)",
        ("error", "hang")),
    "serve.decode": (
        "serve engine decode-over-block-tables dispatch (per tick)",
        ("error", "hang")),
    "serve.block_alloc": (
        "paged KV arena block allocation (admission reserve and "
        "decode-time growth); fires BEFORE the host-side allocation, "
        "so refcounts/tables are untouched — an injected error at "
        "admission quarantines the request, mid-stream (growth) it "
        "escalates to an arena rebuild that reconstructs block tables "
        "and refcounts",
        ("error", "hang")),
    "serve.verify": (
        "speculative verify-round dispatch (draft propose-k + target "
        "verify-k over the paged arena, serve/spec.py); fires BEFORE "
        "the jitted call, so the donated arenas survive — an injected "
        "error past the retry budget makes THAT tick fall back to "
        "plain decode instead of wedging the slot or rebuilding the "
        "arena: the accepted stream is unchanged (plain decode is the "
        "same target argmax), only the draft cache takes a gap that "
        "can lower later accept rates",
        ("error", "hang")),
    "serve.handoff": (
        "disaggregated-tier KV block handoff (the Router moving a "
        "finished prefill's blocks from a prefill worker to a decode "
        "worker); fires BEFORE extraction, so an injected error models "
        "a worker dying mid-handoff with the source arena's host state "
        "intact — the router re-routes: the request re-prefills from "
        "prompt (+ tokens so far) on a prefill worker and its greedy "
        "stream is unchanged",
        ("error", "hang")),
    "serve.spill": (
        "KV spill tier seams (serve/mem.py, both directions): the "
        "spill WRITE when an evicted refcount-0 prefix block's bytes "
        "are copied to the host store, and the prefetch READ when a "
        "prefix hit restores a spilled block into a free physical "
        "block; fires BEFORE either copy, so an injected error only "
        "DEGRADES — the block dies unspilled / the prefix re-prefills, "
        "exactly the pre-spill behavior — streams stay bitwise "
        "identical and the fault lands as a serve.spill incident with "
        "a flight dump",
        ("error", "hang")),
    "serve.router": (
        "disaggregated-tier routing decision (per Router.submit, "
        "before a prefill worker is chosen); an injected error "
        "surfaces to the submitter like a routing outage — requests "
        "already inside the tier are unaffected",
        ("error", "hang")),
    "serve.transport": (
        "multi-process KV wire transport (serve/net: every framed "
        "handoff payload, send and receive side); fires BEFORE the "
        "bytes move, and torn_frame truncates the serialized package "
        "mid-wire (faults.tear on the payload).  NEVER retried in "
        "place: the codec's digest check rejects a torn frame before "
        "inject, the supervisor treats any transport fault as a dead "
        "handoff and re-routes the request via replay (prompt + tokens "
        "so far on a surviving prefill worker), so streams stay "
        "bitwise and a torn transfer is never injected",
        ("error", "hang", "torn_frame")),
    "serve.resize": (
        "elastic pool resize (serve/net supervisor, before a grow "
        "spawn or drain-shrink mutates the tier); an injected error "
        "aborts THAT resize cleanly — the worker set, in-flight "
        "streams and admission are untouched, and the autoscaler "
        "simply re-evaluates on a later round (no quarantine: resizes "
        "are idempotent tier-shape goals, not per-request work)",
        ("error", "hang")),
    "serve.respawn": (
        "self-healing respawn decision (serve/net supervisor, before "
        "a replacement worker spawn is scheduled for a dead one); an "
        "injected error makes THAT attempt fail — it counts toward "
        "the capped exponential backoff and is retried at a later "
        "step boundary — and a hang delays the decision; the tier is "
        "otherwise untouched (the dead worker's requests already "
        "replayed on survivors before respawn runs)",
        ("error", "hang")),
    "train.step": (
        "TrainRunner's retried step region (the shared injector the "
        "train retry/backoff path is exercised through)",
        ("error", "hang")),
    "data.next": (
        "DataLoader batch draw; nan corrupts the float parts of the "
        "yielded batch",
        ("error", "hang", "nan")),
}


#: subsystem seams that appear in incident records / flight-recorder
#: dumps but are NOT injection sites (nothing fires there — they name
#: where the SYSTEM acted, not where a fault was injected):
#: ``serve.arena`` (arena rebuild/recovery), ``serve.crashloop`` (the
#: respawn circuit breaker giving up on a role after K deaths in a
#: window — the tier degrades to survivors), ``train.fatal`` (retry
#: exhaustion / checkpoint-write failure), ``train.hung`` (heartbeat
#: hang abort).  ``FlightRecorder.dump`` accepts SITES plus these;
#: singalint SGL009 enforces the same union statically so a typo'd dump
#: site cannot silently never dump.
INCIDENT_SITES: Tuple[str, ...] = ("serve.arena", "serve.crashloop",
                                   "train.fatal", "train.hung")


def is_known(site: str) -> bool:
    return site in SITES


def is_incident_site(site: str) -> bool:
    """Valid name for an incident record / flight dump: any injection
    site, or one of the recovery/fatal seams in INCIDENT_SITES."""
    return site in SITES or site in INCIDENT_SITES


def supported_kinds(site: str) -> Tuple[str, ...]:
    return SITES[site][1] if site in SITES else ()
