"""Binding layer to the native runtime (csrc/ → libsinga_core.so).

Parity role: the reference's generated binding layer between the Python
surface and the C++ core (SURVEY.md §2.2 row 5).  Two bindings share
one C API:

  * ``singa_core_ext`` — a CPython C-API extension (csrc/py_ext.cc)
    using the buffer protocol for zero-copy argument passing; preferred
    for the hot kernels when built;
  * ctypes over the shared library — always available as the fallback
    and the binding for handle-based components (scheduler, loader,
    pool).

Builds both on demand with the csrc/Makefile if missing.
"""

from __future__ import annotations

import ctypes as C
import os
import subprocess
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libsinga_core.so")
_CSRC = os.path.abspath(os.path.join(_HERE, "..", "..", "csrc"))


def _find_so():
    """The dev build writes libsinga_core.so (csrc/Makefile); installed
    packages carry a cpython-suffixed name from setuptools — either is
    a plain shared object for ctypes.  The exact Makefile name wins (the
    dev rebuild flow keeps working); among suffixed hits, newest mtime
    wins (a stale binary from another interpreter must not shadow a
    fresh one)."""
    if os.path.exists(_SO):
        return _SO
    import glob
    hits = glob.glob(os.path.join(_HERE, "libsinga_core*.so"))
    return max(hits, key=os.path.getmtime) if hits else None

_lib: Optional[C.CDLL] = None
_load_error: Optional[str] = None
_ext = None          # the CPython extension module, when importable


def ext():
    """The C-API extension binding, or None (ctypes remains)."""
    global _ext
    if _ext is None and lib() is not None:   # lib() builds csrc on demand
        _ext = _load_ext() or False
    return _ext or None


def _load_ext():
    import glob
    import importlib.util

    paths = glob.glob(os.path.join(_HERE, "singa_core_ext*.so"))
    if not paths:
        # best-effort build; failure (e.g. no Python dev headers) leaves
        # the ctypes binding in charge
        try:
            subprocess.run(["make", "-C", _CSRC, "ext"], check=True,
                           capture_output=True, timeout=300)
        except Exception:
            return None
        paths = glob.glob(os.path.join(_HERE, "singa_core_ext*.so"))
        if not paths:
            return None
    # prefer the current interpreter's ABI tag, else newest mtime — a
    # stale .so from another interpreter must not get tried first and
    # latch _ext = False
    import sysconfig
    tag = sysconfig.get_config_var("EXT_SUFFIX") or ""
    exact = [p for p in paths if p.endswith(tag)]
    best = exact[0] if exact else max(paths, key=os.path.getmtime)
    spec = importlib.util.spec_from_file_location("singa_core_ext", best)
    if spec is None or spec.loader is None:
        return None
    try:
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    except Exception:
        return None


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _CSRC], check=True,
                       capture_output=True, timeout=300)
        return os.path.exists(_SO)
    except Exception:
        return False


def lib() -> Optional[C.CDLL]:
    """The loaded native library, or None if unavailable (callers must
    degrade to the pure-JAX path)."""
    global _lib, _load_error
    if _lib is not None:
        return _lib
    if _load_error is not None:
        return None
    so = _find_so()
    if so is None:
        if not _build():
            _load_error = "build failed"
            return None
        so = _SO

    def _try(path):
        l = C.CDLL(path)
        _declare(l)
        return l

    try:
        _lib = _try(so)
        return _lib
    except (OSError, AttributeError) as e:
        # OSError: wrong-arch binary; AttributeError: a stale .so
        # predating a newer sg_* symbol.  A glob-found stale file must
        # not block the dev rebuild path: try `make` + the exact name
        # before giving up on the native core
        if so != _SO and _build():
            try:
                _lib = _try(_SO)
                return _lib
            except (OSError, AttributeError) as e2:
                e = e2
        _load_error = str(e)
        return None


def available() -> bool:
    return lib() is not None


i64 = C.c_int64
f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")


def _declare(l: C.CDLL) -> None:
    l.sg_version.restype = C.c_char_p
    l.sg_gemm.argtypes = [f32p, f32p, f32p, i64, i64, i64,
                          C.c_int, C.c_int, C.c_float, C.c_float]
    for name in ("sg_add", "sg_sub", "sg_mul", "sg_div"):
        getattr(l, name).argtypes = [f32p, f32p, f32p, i64]
    for name in ("sg_relu", "sg_sigmoid", "sg_tanh", "sg_exp"):
        getattr(l, name).argtypes = [f32p, f32p, i64]
    l.sg_relu_grad.argtypes = [f32p, f32p, f32p, i64]
    l.sg_softmax.argtypes = [f32p, f32p, i64, i64]
    l.sg_sum.argtypes = [f32p, f32p, i64]
    l.sg_axpy.argtypes = [C.c_float, f32p, f32p, i64]
    l.sg_scale.argtypes = [C.c_float, f32p, i64]
    l.sg_conv2d_nhwc.argtypes = [f32p, f32p, f32p] + [i64] * 11
    l.sg_sgd_update.argtypes = [f32p, f32p, C.c_void_p,
                                C.c_float, C.c_float, C.c_float, i64]
    l.sg_graph_new.restype = i64
    l.sg_graph_free.argtypes = [i64]
    l.sg_graph_add_node.restype = i64
    l.sg_graph_add_node.argtypes = [i64, C.c_char_p, i64p, i64, i64p, i64,
                                    i64p, i64]
    l.sg_graph_toposort.restype = i64
    l.sg_graph_toposort.argtypes = [i64, i64p, i64]
    l.sg_graph_plan_memory.restype = i64
    l.sg_graph_plan_memory.argtypes = [i64, i64p, i64]
    l.sg_graph_num_nodes.restype = i64
    l.sg_graph_num_nodes.argtypes = [i64]
    l.sg_graph_total_flops.restype = i64
    l.sg_graph_total_flops.argtypes = [i64]
    l.sg_loader_new.restype = i64
    l.sg_loader_new.argtypes = [f32p, C.c_void_p, i64, i64, i64,
                                C.c_int, C.c_uint64, C.c_int, C.c_int, C.c_int]
    l.sg_loader_next.restype = i64
    l.sg_loader_next.argtypes = [i64, f32p, C.c_void_p]
    l.sg_loader_free.argtypes = [i64]
    l.sg_loader_batches_per_epoch.restype = i64
    l.sg_loader_batches_per_epoch.argtypes = [i64]
    l.sg_pool_alloc.restype = C.c_void_p
    l.sg_pool_alloc.argtypes = [C.c_size_t]
    l.sg_pool_free.argtypes = [C.c_void_p]
    l.sg_pool_bytes_in_use.restype = C.c_size_t
    l.sg_pool_bytes_reserved.restype = C.c_size_t
    # PJRT touchpoint (pjrt_device.cc) — OPTIONAL: the Makefile skips
    # it when the official pjrt_c_api.h is absent, and its absence must
    # not take down the rest of the native core
    if not hasattr(l, "sg_pjrt_load"):
        return
    cp = C.c_char_p
    l.sg_pjrt_load.restype = i64
    l.sg_pjrt_load.argtypes = [cp, C.c_int, C.c_char_p, i64]
    l.sg_pjrt_api_version.restype = i64
    l.sg_pjrt_api_version.argtypes = [i64, C.POINTER(C.c_int32),
                                      C.POINTER(C.c_int32)]
    l.sg_pjrt_init_error.argtypes = [i64, C.c_char_p, i64]
    l.sg_pjrt_attr_count.restype = i64
    l.sg_pjrt_attr_count.argtypes = [i64]
    l.sg_pjrt_attr_get.restype = C.c_int
    l.sg_pjrt_attr_get.argtypes = [i64, i64, C.c_char_p, i64, C.c_char_p, i64]
    l.sg_pjrt_client_create.restype = i64
    l.sg_pjrt_client_create.argtypes = [i64, C.c_char_p, i64]
    l.sg_pjrt_client_device_count.restype = i64
    l.sg_pjrt_client_device_count.argtypes = [i64]
    l.sg_pjrt_client_platform.argtypes = [i64, C.c_char_p, i64]
    l.sg_pjrt_device_desc.argtypes = [i64, i64, C.c_char_p, i64]
    l.sg_pjrt_client_destroy.argtypes = [i64]
    l.sg_pjrt_unload.argtypes = [i64]


def version() -> str:
    l = lib()
    return l.sg_version().decode() if l else "unavailable"


# ---------------------------------------------------------------------------
# numpy-level wrappers (tensor_math_cpp dispatch surface)
# ---------------------------------------------------------------------------

# dispatch instrumentation: counts PUBLIC kernel-wrapper calls (gemm,
# add, relu, ...) — proof that csrc kernels are exercised
stats = {"calls": 0}


def reset_stats() -> None:
    stats["calls"] = 0


def _count() -> None:
    stats["calls"] += 1


def _c(a):
    return np.ascontiguousarray(a, dtype=np.float32)


def gemm(a: np.ndarray, b: np.ndarray, transa=False, transb=False,
         alpha=1.0) -> np.ndarray:
    l = lib()
    _count()
    a, b = _c(a), _c(b)
    m = a.shape[1] if transa else a.shape[0]
    k = a.shape[0] if transa else a.shape[1]
    n = b.shape[0] if transb else b.shape[1]
    out = np.zeros((m, n), np.float32)
    e = ext()
    if e is not None and alpha == 1.0:
        e.gemm(a, b, out, m, k, n, bool(transa), bool(transb))
    else:
        l.sg_gemm(a, b, out, m, k, n, int(transa), int(transb), alpha, 0.0)
    return out


def _binary(name):
    ext_name = name[3:]                      # sg_add -> add

    def fn(a, b):
        l = lib()
        _count()
        a, b = _c(a), _c(b)
        out = np.empty_like(a)
        e = ext()
        if e is not None:
            getattr(e, ext_name)(a.reshape(-1), b.reshape(-1),
                                 out.reshape(-1))
        else:
            getattr(l, name)(a, b, out, a.size)
        return out
    return fn


add = _binary("sg_add")
sub = _binary("sg_sub")
mul = _binary("sg_mul")
div = _binary("sg_div")


def _unary(name):
    ext_name = name[3:]

    def fn(a):
        l = lib()
        _count()
        a = _c(a)
        out = np.empty_like(a)
        e = ext()
        if e is not None:
            getattr(e, ext_name)(a.reshape(-1), out.reshape(-1))
        else:
            getattr(l, name)(a, out, a.size)
        return out
    return fn


relu = _unary("sg_relu")
sigmoid = _unary("sg_sigmoid")
tanh = _unary("sg_tanh")
exp = _unary("sg_exp")


def relu_grad(a, dy):
    l = lib()
    _count()
    a, dy = _c(a), _c(dy)
    out = np.empty_like(a)
    l.sg_relu_grad(a, dy, out, a.size)
    return out


def softmax(a):
    l = lib()
    _count()
    a = _c(a)
    rows = int(np.prod(a.shape[:-1])) if a.ndim > 1 else 1
    out = np.empty_like(a)
    l.sg_softmax(a.reshape(rows, -1), out.reshape(rows, -1), rows, a.shape[-1])
    return out


def array_sum(a) -> float:
    l = lib()
    _count()
    a = _c(a)
    out = np.zeros(1, np.float32)
    l.sg_sum(a.reshape(-1), out, a.size)
    return float(out[0])


def conv2d_nhwc(x, w, stride=(1, 1), padding=(0, 0)):
    l = lib()
    _count()
    x, w = _c(x), _c(w)
    N, H, W_, Cin = x.shape
    KH, KW, _, OC = w.shape
    sh, sw = stride
    ph, pw = padding
    OH = (H + 2 * ph - KH) // sh + 1
    OW = (W_ + 2 * pw - KW) // sw + 1
    y = np.zeros((N, OH, OW, OC), np.float32)
    l.sg_conv2d_nhwc(x, w, y, N, H, W_, Cin, KH, KW, OC, sh, sw, ph, pw)
    return y


def sgd_update(param: np.ndarray, grad: np.ndarray,
               mom: Optional[np.ndarray], lr, momentum=0.0, weight_decay=0.0):
    l = lib()
    _count()
    assert param.dtype == np.float32 and param.flags["C_CONTIGUOUS"]
    mom_p = mom.ctypes.data_as(C.c_void_p) if mom is not None else None
    l.sg_sgd_update(param, _c(grad), mom_p, lr, momentum, weight_decay,
                    param.size)


# ---------------------------------------------------------------------------
# scheduler wrapper
# ---------------------------------------------------------------------------

class NativeGraph:
    """Handle on a native scheduler graph (topo sort + memory planning)."""

    def __init__(self):
        l = lib()
        if l is None:
            raise RuntimeError("native core unavailable")
        self._l = l
        self.h = l.sg_graph_new()
        self._nbufs = 0

    def add_node(self, name: str, in_bufs, out_bufs, out_sizes, flops=0) -> int:
        ib = np.asarray(in_bufs, np.int64)
        ob = np.asarray(out_bufs, np.int64)
        sz = np.asarray(out_sizes, np.int64)
        self._nbufs = max([self._nbufs] + [int(b) + 1 for b in list(ib) + list(ob)])
        return int(self._l.sg_graph_add_node(
            self.h, name.encode(), ib, len(ib), ob, len(ob), sz, int(flops)))

    def toposort(self):
        n = int(self._l.sg_graph_num_nodes(self.h))
        out = np.zeros(n, np.int64)
        r = int(self._l.sg_graph_toposort(self.h, out, n))
        if r < 0:
            raise ValueError("cycle in graph")
        return out.tolist()

    def plan_memory(self):
        """Returns (arena_bytes, {buf_id: offset})."""
        offsets = np.full(self._nbufs, -1, np.int64)
        arena = int(self._l.sg_graph_plan_memory(self.h, offsets, self._nbufs))
        return arena, {i: int(o) for i, o in enumerate(offsets) if o >= 0}

    @property
    def num_nodes(self) -> int:
        return int(self._l.sg_graph_num_nodes(self.h))

    @property
    def total_flops(self) -> int:
        return int(self._l.sg_graph_total_flops(self.h))

    def __del__(self):
        try:
            self._l.sg_graph_free(self.h)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# data loader wrapper
# ---------------------------------------------------------------------------

class NativeLoader:
    def __init__(self, x: np.ndarray, y: Optional[np.ndarray], batch: int,
                 shuffle=True, seed=0, drop_last=False, workers=2, prefetch=4):
        l = lib()
        if l is None:
            raise RuntimeError("native core unavailable")
        self._l = l
        self.x = np.ascontiguousarray(x.reshape(len(x), -1), np.float32)
        self.y = (np.ascontiguousarray(y, np.int32) if y is not None else None)
        self.sample_shape = x.shape[1:]
        self.batch = batch
        self.stride = self.x.shape[1]
        yp = self.y.ctypes.data_as(C.c_void_p) if self.y is not None else None
        self.h = l.sg_loader_new(self.x, yp, len(x), self.stride, batch,
                                 int(shuffle), seed, int(drop_last),
                                 workers, prefetch)
        if self.h < 0:
            raise ValueError("bad loader args")
        self._xbuf = np.empty((batch, self.stride), np.float32)
        self._ybuf = np.empty(batch, np.int32)

    def next(self):
        yb = self._ybuf.ctypes.data_as(C.c_void_p) if self.y is not None else None
        n = int(self._l.sg_loader_next(self.h, self._xbuf, yb))
        if n <= 0:
            raise StopIteration
        x = self._xbuf[:n].reshape((n,) + self.sample_shape).copy()
        y = self._ybuf[:n].copy() if self.y is not None else None
        return x, y

    @property
    def batches_per_epoch(self) -> int:
        return int(self._l.sg_loader_batches_per_epoch(self.h))

    def close(self):
        if self.h is not None:
            self._l.sg_loader_free(self.h)
            self.h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
