"""The autotuner's knob registry — the ONE catalogue of tunable
configuration the repo actually exposes (ISSUE 14).

Every knob here already exists as a constructor argument or config
field somewhere in the codebase; the registry's job is to make the set
closed and checkable.  A sweep record naming a knob that is not in
:data:`KNOBS` fails ``python -m tools.lint --records`` loudly — a
typo'd knob name would otherwise fit a predictor on a column of noise
and commit a best-config table nothing consumes (the autotune flavor
of the r5 silent-truncation failure mode).

Two domains, mirroring the two serving/training entry points the sweep
driver (``singa_tpu.autotune.sweep``) drives:

* ``train`` — ``batch`` (global batch size through the compiled train
  step), ``ce_chunk`` (``LlamaConfig.fused_loss_chunk``, the fused
  lm-head+CE lax.scan chunk), ``int8_ring`` (``DistOpt(compression=
  "int8_ring")`` on the DP mesh, 0/1).
* ``serve`` — ``num_slots`` / ``block_size`` (the paged-arena shape
  every ``ServeEngine`` compiles against), ``spec_k`` (the speculative
  verify-k window; 0 = plain decode), ``spill_blocks`` (the host-RAM
  KV spill store capacity; 0 = off), ``pool_ratio`` (the decode share
  of the disaggregated worker budget the serve.net elastic policy
  steers toward).

Knob values are stored as NUMBERS in records and in the best-config
table (booleans as 0/1) so the predictor's feature vector needs no
per-knob encoding rules.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, Iterable, List, Tuple

__all__ = ["KNOBS", "DEFAULTS", "OBJECTIVES", "validate_knobs",
           "grid_points", "KnobError"]

#: domain -> knob name -> one-line description.  Kept a module-level
#: literal so tooling can enumerate it without importing jax.
KNOBS: Dict[str, Dict[str, str]] = {
    "train": {
        "batch": "global batch size through the compiled train step",
        "ce_chunk": "fused lm-head+CE chunk rows "
                    "(LlamaConfig.fused_loss_chunk)",
        "int8_ring": "DistOpt gradient-sync compression on the DP mesh "
                     "(0 = f32 ring, 1 = error-feedback int8_ring)",
    },
    "serve": {
        "num_slots": "ServeEngine decode-batch slot count (arena rows)",
        "block_size": "paged-KV block size in tokens (arena granularity)",
        "spec_k": "speculative verify-k window (0 = plain decode)",
        "spill_blocks": "host-RAM KV spill store capacity in blocks "
                        "(ServeEngine spill_blocks; 0 = spill off)",
        "pool_ratio": "decode share of the disaggregated worker budget "
                      "(serve.net elastic target; 0.5 = even split)",
    },
}

#: the hand-carried constants each consumer falls back to when no
#: best-config table is committed — today's behavior, preserved exactly
#: (bench.py's CPU serve config; loadgen's CLI defaults; DP2 train).
DEFAULTS: Dict[str, Dict[str, float]] = {
    "train": {"batch": 4, "ce_chunk": 512, "int8_ring": 0},
    "serve": {"num_slots": 8, "block_size": 8, "spec_k": 0,
              "spill_blocks": 0, "pool_ratio": 0.5},
}

#: domain -> (objective payload field, direction).  The sweep driver
#: measures it, the fit picks the argbest, the table commits it.
OBJECTIVES: Dict[str, Tuple[str, str]] = {
    "train": ("step_ms", "min"),
    "serve": ("tokens_per_s", "max"),
}


class KnobError(ValueError):
    """An unknown domain or knob name — always loud, never coerced."""


def validate_knobs(domain: str, knobs: Any,
                   ctx: str = "knobs") -> List[str]:
    """Error strings ([] = valid): ``domain`` must be registered,
    ``knobs`` a non-empty dict whose keys are registered knob names for
    that domain and whose values are numeric (bools rejected — a knob
    accidentally recorded as ``True`` must not fit as a measurement)."""
    errors: List[str] = []
    if domain not in KNOBS:
        return [f"{ctx}: unknown autotune domain {domain!r} "
                f"(registered: {sorted(KNOBS)})"]
    if not isinstance(knobs, dict) or not knobs:
        return [f"{ctx}: knobs must be a non-empty object, got "
                f"{knobs!r}"]
    for name, value in knobs.items():
        if name not in KNOBS[domain]:
            errors.append(
                f"{ctx}: unknown {domain} knob {name!r} (registered: "
                f"{sorted(KNOBS[domain])})")
        elif not isinstance(value, (int, float)) or isinstance(value, bool):
            errors.append(f"{ctx}: knob {name!r} must be numeric, got "
                          f"{value!r}")
    return errors


def require_knobs(domain: str, knobs: Any, ctx: str = "knobs") -> None:
    """:func:`validate_knobs`, raising :class:`KnobError` on the first
    problem — the fail-loudly entry the sweep driver and predictor use."""
    errors = validate_knobs(domain, knobs, ctx)
    if errors:
        raise KnobError(errors[0])


def grid_points(domain: str,
                grid: Dict[str, Iterable[Any]]) -> List[Dict[str, Any]]:
    """The cartesian product of ``grid`` as a list of knob dicts, in
    deterministic (sorted-knob, given-value) order.  Every knob name is
    validated up front."""
    if not grid:
        raise KnobError(f"{domain} sweep: empty knob grid")
    names = sorted(grid)
    for name in names:
        require_knobs(domain, {name: 0}, ctx=f"{domain} sweep grid")
    value_lists = [list(grid[name]) for name in names]
    for name, values in zip(names, value_lists):
        if not values:
            raise KnobError(f"{domain} sweep grid: knob {name!r} has no "
                            f"values")
    return [dict(zip(names, combo))
            for combo in itertools.product(*value_lists)]
