"""The committed best-config table (ISSUE 14): what the autotuner
PROVED, in a form bench.py / ServeEngine / tools/loadgen.py consult by
default.

One JSON document at ``tools/autotune/data/best.json`` (same
committed-artifact flow as the HLO gate baselines under
``tools/lint/data/hlo/`` — re-generated via ``python -m tools.autotune
fit --update-best`` and reviewed in the PR diff, never hand-edited):

.. code-block:: json

    {"schema_version": 1,
     "configs": {
       "serve/llama-d64-L2/cpu": {
         "knobs": {"num_slots": 8, "block_size": 8, "spec_k": 7},
         "objective_name": "tokens_per_s", "objective": 123.4,
         "sweep_id": "atsweep-...", "run_id": "at-...-3",
         "loo_rel_err": 0.12,
         "spec_evidence": {"pair_id": "specpair-...",
                           "accept_rate": 1.0,
                           "tokens_per_dispatch": 7.8,
                           "run_id": "load-spec7-..."}}}}

Resolution precedence, everywhere a consumer asks (:func:`resolve`):

1. an EXPLICIT kwarg/CLI value always wins — the autotuner advises, it
   never overrides an operator;
2. else the committed table's entry for ``(domain, model, platform)``;
3. else the hand-carried constant the consumer shipped with (exactly
   today's behavior), announced LOUDLY ONCE per process per reason —
   a missing table must be visible, not a silent regression to
   pre-autotuner constants.

Every ``run_id`` the table cites must exist in ``runs/records.jsonl``
(``python -m tools.lint --records`` enforces it), and a table whose
``schema_version`` trails the current obs schema fails validation
loudly — a stale table silently steering production configs is the
failure mode the version stamp exists to prevent.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List, Optional

from ..obs import schema as obs_schema
from . import knobs as _knobs

__all__ = ["DEFAULT_TABLE", "table_path", "load_table", "validate_table",
           "config_key", "model_key", "best_knobs", "resolve",
           "resolve_spec_k", "pick_spec_k", "update_table",
           "SPEC_K_FALLBACK"]

_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

#: committed location, repo-relative (a data-only directory next to
#: tools/autotune.py — no __init__.py, so `import tools.autotune` still
#: resolves to the CLI module)
DEFAULT_TABLE = os.path.join("tools", "autotune", "data", "best.json")

#: env override for tests and ad-hoc tables
ENV_TABLE = "SINGA_AUTOTUNE_TABLE"

#: the hand-carried constant ServeEngine(spec_k=None) falls back to
#: when no table entry decides k (the value every committed spec run
#: to date used as its default)
SPEC_K_FALLBACK = 3

#: warn-once registry: one stderr line per distinct reason per process
_WARNED: set = set()


def _warn_once(reason: str) -> None:
    if reason in _WARNED:
        return
    _WARNED.add(reason)
    print(f"autotune: {reason}", file=sys.stderr)


def table_path(path: Optional[str] = None) -> str:
    """Resolve the table location: explicit arg > ``SINGA_AUTOTUNE_TABLE``
    env > the committed repo default."""
    if path:
        return path
    env = os.environ.get(ENV_TABLE)
    if env:
        return env
    return os.path.join(_REPO_ROOT, DEFAULT_TABLE)


def config_key(domain: str, model: str, platform: str) -> str:
    return f"{domain}/{model}/{platform}"


def model_key(model: Any) -> str:
    """Deterministic per-architecture identity for table keys: class
    name plus the width/depth that shape every compiled program.  Two
    models with the same key compile the same programs, which is the
    granularity the table's knobs apply at."""
    cfg = getattr(model, "cfg", None)
    name = type(model).__name__.lower()
    dim = getattr(cfg, "dim", None)
    layers = getattr(cfg, "num_layers", None)
    if isinstance(dim, int) and isinstance(layers, int):
        return f"{name}-d{dim}-L{layers}"
    return name


def validate_table(doc: Any, ctx: str = "best.json",
                   store_run_ids: Optional[set] = None) -> List[str]:
    """Error strings ([] = valid).  Checks shape, the schema-version
    staleness guard, knob-name reality per entry, and — when the
    caller supplies the store's run_id set — that every cited record
    exists (``python -m tools.lint --records`` passes it)."""
    errors: List[str] = []
    if not isinstance(doc, dict):
        return [f"{ctx}: expected an object, got {type(doc).__name__}"]
    ver = doc.get("schema_version")
    if ver != obs_schema.SCHEMA_VERSION:
        return [f"{ctx}: schema_version {ver!r} does not match the "
                f"current obs schema {obs_schema.SCHEMA_VERSION} — the "
                f"table is stale; re-run `python -m tools.autotune fit "
                f"--update-best` against a fresh sweep"]
    configs = doc.get("configs")
    if not isinstance(configs, dict) or not configs:
        return [f"{ctx}: 'configs' must be a non-empty object, got "
                f"{configs!r}"]
    for key, entry in sorted(configs.items()):
        c = f"{ctx}: configs[{key!r}]"
        parts = str(key).split("/")
        if len(parts) != 3 or not all(parts):
            errors.append(f"{c}: key must be 'domain/model/platform'")
            continue
        domain = parts[0]
        if not isinstance(entry, dict):
            errors.append(f"{c}: expected an object")
            continue
        errors.extend(_knobs.validate_knobs(domain, entry.get("knobs"),
                                            ctx=c))
        for field in ("objective_name", "sweep_id", "run_id"):
            v = entry.get(field)
            if not isinstance(v, str) or not v:
                errors.append(f"{c}: {field!r} must be a non-empty "
                              f"string, got {v!r}")
        for field in ("objective", "loo_rel_err"):
            v = entry.get(field)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                errors.append(f"{c}: {field!r} must be numeric, got "
                              f"{v!r}")
        ev = entry.get("spec_evidence")
        if ev is not None:
            if not isinstance(ev, dict) or not isinstance(
                    ev.get("run_id"), str) or not ev.get("run_id"):
                errors.append(f"{c}: 'spec_evidence' must carry the "
                              f"winning record's 'run_id'")
        if store_run_ids is not None:
            cited = [entry.get("run_id")]
            if isinstance(ev, dict):
                cited.append(ev.get("run_id"))
            for rid in cited:
                if isinstance(rid, str) and rid and \
                        rid not in store_run_ids:
                    errors.append(
                        f"{c}: cites run_id {rid!r} which does not "
                        f"exist in the record store — a best point "
                        f"must reference its measured evidence")
    return errors


def load_table(path: Optional[str] = None, *,
               required: bool = False) -> Optional[Dict[str, Any]]:
    """Parse + validate the table.  Missing file: None (or raise when
    ``required``).  An INVALID table always raises — consumers must
    fall back only on absence, never on quiet corruption."""
    p = table_path(path)
    if not os.path.exists(p):
        if required:
            raise FileNotFoundError(
                f"autotune: no best-config table at {p} — run "
                f"`python -m tools.autotune sweep` then `fit "
                f"--update-best`")
        return None
    with open(p, encoding="utf-8") as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"{p}: not valid JSON ({e.msg} at line "
                             f"{e.lineno})") from e
    errors = validate_table(doc, ctx=p)
    if errors:
        raise ValueError("; ".join(errors))
    return doc


def best_knobs(domain: str, model: str, platform: str,
               path: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """The committed knob dict for ``(domain, model, platform)``, or
    None — with the loud-once fallback announcements the resolution
    contract promises."""
    doc = load_table(path)
    if doc is None:
        _warn_once(f"no best-config table at {table_path(path)}; "
                   f"{domain} consumers fall back to built-in defaults")
        return None
    entry = doc["configs"].get(config_key(domain, model, platform))
    if entry is None:
        _warn_once(f"best-config table has no entry for "
                   f"{config_key(domain, model, platform)}; falling "
                   f"back to built-in defaults")
        return None
    return dict(entry["knobs"])


def resolve(domain: str, model: str, platform: str,
            explicit: Dict[str, Any],
            defaults: Optional[Dict[str, Any]] = None,
            path: Optional[str] = None) -> Dict[str, Any]:
    """One resolved knob dict: ``explicit`` (non-None values) beats the
    table beats ``defaults`` (falling back to the registry's
    :data:`~singa_tpu.autotune.knobs.DEFAULTS`).  The returned dict
    covers exactly the union of the inputs' knob names."""
    base = dict(_knobs.DEFAULTS.get(domain, {}))
    if defaults:
        base.update(defaults)
    table = best_knobs(domain, model, platform, path) or {}
    out: Dict[str, Any] = {}
    for name in sorted(set(base) | set(table)
                       | {k for k, v in explicit.items()
                          if v is not None}):
        if explicit.get(name) is not None:
            out[name] = explicit[name]
        elif name in table:
            out[name] = table[name]
        else:
            out[name] = base[name]
    return out


def resolve_spec_k(model: Any, platform: Optional[str] = None,
                   path: Optional[str] = None) -> int:
    """The verify-k window for ``ServeEngine(draft_model=..,
    spec_k=None)``: the table's committed ``spec_k`` for this (model,
    platform) when it decides speculation is worth it (k >= 1), else
    :data:`SPEC_K_FALLBACK` — announced once.  The caller already
    chose TO speculate by passing a draft model; the table only picks
    HOW DEEP."""
    if platform is None:
        import jax
        platform = jax.default_backend()
    knobs = best_knobs("serve", model_key(model), platform, path) or {}
    k = knobs.get("spec_k")
    if isinstance(k, (int, float)) and not isinstance(k, bool) and \
            int(k) >= 1:
        return int(k)
    if k is not None:
        _warn_once(f"best-config table advises spec_k={int(k)} (no "
                   f"speculation win) for {model_key(model)}/{platform} "
                   f"but a draft_model was supplied; using the "
                   f"fallback spec_k={SPEC_K_FALLBACK}")
    else:
        _warn_once(f"no committed spec_k for {model_key(model)}/"
                   f"{platform}; using the fallback spec_k="
                   f"{SPEC_K_FALLBACK}")
    return SPEC_K_FALLBACK


def pick_spec_k(entries: List[Dict[str, Any]], platform: str,
                model: Optional[str] = None
                ) -> Optional[Dict[str, Any]]:
    """The ROADMAP item-2b wire-up: choose ``spec_k`` from committed
    ``accept_rate`` / ``tokens_per_dispatch`` record fields, per
    (model, platform).

    Scans ``--spec-compare`` pair records (``serve_load`` entries
    sharing a ``spec_pair_id``): a speculative side qualifies only
    when it BEAT its paired plain run on tokens/s — dispatch density
    alone is not a win if wall-clock lost.  Among qualifying ks the
    LARGEST tokens/s win over its own paired plain run wins (the
    serve domain's declared objective; the ratio rather than raw
    tokens/s because different pairs may have run different
    workloads) — ``accept_rate`` / ``tokens_per_dispatch`` are the
    qualifying evidence carried into ``spec_evidence``, not the
    ranking metric.  With ``model`` set the match is STRICT: only
    records stamped with that payload ``model`` key count
    (pre-ISSUE-14 records carry no stamp and are skipped — a pair
    measured on one architecture must never decide another's k).
    Returns ``{"spec_k", "accept_rate", "tokens_per_dispatch",
    "tokens_per_s_win", "run_id", "pair_id"}`` or None when no
    committed pair shows a win."""
    pairs: Dict[str, List[Dict[str, Any]]] = {}
    for e in entries:
        if e.get("kind") != "serve_load" or e.get("platform") != platform:
            continue
        p = e.get("payload") or {}
        if model is not None and p.get("model") != model:
            continue
        if p.get("spec_pair_id"):
            pairs.setdefault(p["spec_pair_id"], []).append(e)
    best: Optional[Dict[str, Any]] = None
    for pair_id, group in sorted(pairs.items()):
        plain = [e for e in group
                 if not e["payload"].get("spec_k")]
        spec = [e for e in group
                if e["payload"].get("spec_k")
                and "accept_rate" in e["payload"]
                and "tokens_per_dispatch" in e["payload"]]
        if not plain or not spec:
            continue
        plain_tps = max(float(e["payload"]["tokens_per_s"])
                        for e in plain)
        for e in spec:
            p = e["payload"]
            if float(p["tokens_per_s"]) <= plain_tps:
                continue
            cand = {"spec_k": int(p["spec_k"]),
                    "accept_rate": float(p["accept_rate"]),
                    "tokens_per_dispatch":
                        float(p["tokens_per_dispatch"]),
                    "tokens_per_s_win":
                        float(p["tokens_per_s"]) / plain_tps,
                    "run_id": e["run_id"], "pair_id": pair_id}
            if best is None or cand["tokens_per_s_win"] > \
                    best["tokens_per_s_win"]:
                best = cand
    return best


def update_table(key: str, entry: Dict[str, Any],
                 path: Optional[str] = None) -> str:
    """Insert/replace one config entry (the ``fit --update-best``
    flow) and atomically rewrite the table.  Returns the path.

    A STALE or invalid existing table is discarded (announced) and
    rebuilt fresh rather than raised on: ``fit --update-best`` is the
    documented remedy the stale-table error points at, so it must be
    able to run — and after a schema bump every entry in the old doc
    is stale by definition (the version stamp is document-level),
    so each domain re-fits from its own sweep records."""
    p = table_path(path)
    doc = None
    if os.path.exists(p):
        try:
            doc = load_table(p)
        except ValueError as e:
            _warn_once(f"discarding invalid best-config table at {p} "
                       f"({e}); rebuilding from this fit")
    if doc is None:
        doc = {"schema_version": obs_schema.SCHEMA_VERSION,
               "configs": {}}
    doc["configs"][key] = entry
    errors = validate_table(doc, ctx=p)
    if errors:
        raise ValueError("; ".join(errors))
    os.makedirs(os.path.dirname(os.path.abspath(p)), exist_ok=True)
    tmp = f"{p}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, p)
    return p
