"""singa_tpu.autotune — the record-driven autotuner (ISSUE 14).

Closes the loop ROADMAP item 4 names: the obs record store already
holds analytic per-program cost features (``tools.lint.cost.
cost_features()``, appended on every bench run) and a measured bench/
serve trajectory; this package turns them into config decisions —

* :mod:`~singa_tpu.autotune.knobs` — the closed registry of tunable
  knobs per domain (train: batch / ce_chunk / int8_ring; serve:
  num_slots / block_size / spec_k) and their hand-carried defaults;
* :mod:`~singa_tpu.autotune.sweep` — knob points -> ``autotune_sweep``
  records under one ``sweep_id`` (+ the ``point = -1`` fit record);
* :mod:`~singa_tpu.autotune.predictor` — deterministic ridge /
  nearest-neighbor fit with an exact leave-one-out error report;
* :mod:`~singa_tpu.autotune.table` — the committed best-config table
  (``tools/autotune/data/best.json``) that bench.py, ServeEngine and
  tools/loadgen.py consult by default (explicit values always win; a
  missing table falls back to today's constants, loudly once).

Front door: ``python -m tools.autotune`` (sweep / fit / best / check /
smoke).  Everything here is host-only — no jax import at package
import time.
"""

from . import knobs, predictor, sweep, table  # noqa: F401
from .knobs import DEFAULTS, KNOBS, OBJECTIVES, KnobError  # noqa: F401
from .predictor import Predictor, best_point, fit_points  # noqa: F401
from .table import (best_knobs, load_table, model_key,  # noqa: F401
                    pick_spec_k, resolve, resolve_spec_k)

__all__ = ["knobs", "predictor", "sweep", "table", "KNOBS", "DEFAULTS",
           "OBJECTIVES", "KnobError", "Predictor", "fit_points",
           "best_point", "model_key", "best_knobs", "resolve",
           "resolve_spec_k", "pick_spec_k", "load_table"]
