"""Record-fitted performance predictor (ISSUE 14).

"A Learned Performance Model for TPUs" (arXiv:2008.01040) shows
record-fitted predictors beating analytic cost models for exactly the
config-choice problem this module serves — but its GNN needs a corpus
this repo does not have.  What the repo DOES have is a small, exact
feature vector per sweep point: the knob values themselves plus the
analytic ``tools.lint.cost.cost_features()`` quantities measured off
the point's own lowering (wire bytes per int8_ring setting, etc.).  At
this scale the right learner is a closed-form one:

* **ridge regression** over standardized (knob + analytic-feature)
  columns — deterministic (``numpy.linalg.solve`` on a fixed design
  matrix; no iterative optimizer, no seed), zero new dependencies, and
  its leave-one-out error is cheap enough to compute exactly;
* **nearest-neighbor** lookup as the companion: on a measured point it
  returns the measurement itself, which is the honest answer when the
  query IS in the store.

Trustworthiness is a NUMBER, not a vibe: :func:`fit_points` returns a
leave-one-out relative-error report alongside the predictor, the fit
record commits it to the store (``loo_rel_err``), and a tier-1 test
bounds it on the frozen committed records.  Failure modes are loud:
an empty point set, an unknown knob name, or ragged knob keys raise
immediately with the offending name — a predictor silently fit on
garbage would launder noise into the committed best-config table.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import knobs as _knobs

__all__ = ["Predictor", "fit_points", "best_point", "point_vector"]


def _check_points(domain: str,
                  points: Sequence[Dict[str, Any]]) -> Tuple[List[str],
                                                             List[str]]:
    """Validate a sweep-point list and return the (knob_names,
    feature_names) column order shared by every point."""
    if not points:
        raise ValueError(
            f"autotune predictor: no {domain!r} sweep points to fit — "
            f"run `python -m tools.autotune sweep` first (empty store)")
    first_knobs = sorted(points[0].get("knobs", {}))
    feature_names = sorted(points[0].get("features", {}) or {})
    for i, p in enumerate(points):
        _knobs.require_knobs(domain, p.get("knobs"),
                             ctx=f"sweep point {i}")
        if sorted(p["knobs"]) != first_knobs:
            raise ValueError(
                f"autotune predictor: sweep point {i} knobs "
                f"{sorted(p['knobs'])} differ from point 0's "
                f"{first_knobs} — a ragged sweep cannot share one "
                f"design matrix")
        if sorted(p.get("features", {}) or {}) != feature_names:
            raise ValueError(
                f"autotune predictor: sweep point {i} features differ "
                f"from point 0's {feature_names}")
        y = p.get("objective")
        if not isinstance(y, (int, float)) or isinstance(y, bool):
            raise ValueError(f"autotune predictor: sweep point {i} has "
                             f"no numeric objective (got {y!r})")
    return first_knobs, feature_names


def point_vector(point: Dict[str, Any], knob_names: Sequence[str],
                 feature_names: Sequence[str]) -> np.ndarray:
    """One point's raw (unstandardized) feature row, knob columns then
    analytic-feature columns, in the fit's fixed order."""
    vals = [float(point["knobs"][k]) for k in knob_names]
    feats = point.get("features", {}) or {}
    vals += [float(feats[f]) for f in feature_names]
    return np.asarray(vals, dtype=np.float64)


class Predictor:
    """A fitted ridge model over one (domain, model, platform) sweep.

    Holds the standardization constants and the measured points, so
    :meth:`predict` answers for unseen knob settings and
    :meth:`nearest` returns the closest MEASURED point (normalized
    L2 over the same columns) when the honest answer is a lookup."""

    def __init__(self, domain: str, knob_names: List[str],
                 feature_names: List[str], mean: np.ndarray,
                 scale: np.ndarray, weights: np.ndarray, bias: float,
                 points: List[Dict[str, Any]]):
        self.domain = domain
        self.knob_names = knob_names
        self.feature_names = feature_names
        self._mean = mean
        self._scale = scale
        self._weights = weights
        self._bias = bias
        self.points = points

    def _row(self, knobs: Dict[str, Any],
             features: Optional[Dict[str, Any]] = None) -> np.ndarray:
        _knobs.require_knobs(self.domain, knobs, ctx="predict")
        missing = [k for k in self.knob_names if k not in knobs]
        if missing:
            raise ValueError(f"autotune predictor: predict() missing "
                             f"fitted knob(s) {missing}")
        point = {"knobs": knobs, "features": features or {}}
        if sorted(point["features"]) != self.feature_names:
            raise ValueError(
                f"autotune predictor: predict() features "
                f"{sorted(point['features'])} do not match the fitted "
                f"columns {self.feature_names}")
        raw = point_vector(point, self.knob_names, self.feature_names)
        return (raw - self._mean) / self._scale

    def predict(self, knobs: Dict[str, Any],
                features: Optional[Dict[str, Any]] = None) -> float:
        """Ridge estimate of the objective at ``knobs`` (+ analytic
        ``features`` when the fit used any)."""
        return float(self._row(knobs, features) @ self._weights
                     + self._bias)

    def nearest(self, knobs: Dict[str, Any],
                features: Optional[Dict[str, Any]] = None
                ) -> Dict[str, Any]:
        """The measured point closest to ``knobs`` in standardized
        space — exact on any point that was actually swept."""
        row = self._row(knobs, features)
        best_i, best_d = 0, float("inf")
        for i, p in enumerate(self.points):
            raw = point_vector(p, self.knob_names, self.feature_names)
            d = float(np.sum(((raw - self._mean) / self._scale - row)
                             ** 2))
            if d < best_d:
                best_i, best_d = i, d
        return self.points[best_i]


def _standardize(X: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(mean, scale) per column; zero-variance columns get scale 1 so
    they standardize to a constant 0 and contribute nothing (an
    analytic feature that never varies across the sweep — e.g. flops
    at fixed shapes — is carried but inert, by construction)."""
    mean = X.mean(axis=0)
    std = X.std(axis=0)
    scale = np.where(std > 0, std, 1.0)
    return mean, scale


def _ridge(Xs: np.ndarray, y: np.ndarray,
           l2: float) -> Tuple[np.ndarray, float]:
    yc = y - y.mean()
    n_cols = Xs.shape[1]
    A = Xs.T @ Xs + l2 * np.eye(n_cols)
    w = np.linalg.solve(A, Xs.T @ yc)
    return w, float(y.mean())


def fit_points(domain: str, points: Sequence[Dict[str, Any]], *,
               l2: float = 1e-2
               ) -> Tuple[Predictor, Dict[str, Any]]:
    """Fit the ridge predictor and compute its exact leave-one-out
    report: ``{"loo_rel_err": mean, "loo_rel_err_max": max, "n": N}``.

    With fewer than 3 points LOO is meaningless; the report then
    carries ``loo_rel_err = 1.0`` (maximally untrustworthy) rather
    than a flattering NaN — a 2-point smoke sweep must never look
    better calibrated than the committed 6-point one."""
    pts = list(points)
    knob_names, feature_names = _check_points(domain, pts)
    X = np.stack([point_vector(p, knob_names, feature_names)
                  for p in pts])
    y = np.asarray([float(p["objective"]) for p in pts],
                   dtype=np.float64)
    mean, scale = _standardize(X)
    Xs = (X - mean) / scale
    w, b = _ridge(Xs, y, l2)
    pred = Predictor(domain, knob_names, feature_names, mean, scale,
                     w, b, pts)

    n = len(pts)
    if n < 3:
        report = {"loo_rel_err": 1.0, "loo_rel_err_max": 1.0, "n": n}
        return pred, report
    rel_errs: List[float] = []
    idx = np.arange(n)
    for i in range(n):
        keep = idx != i
        m_i, s_i = _standardize(X[keep])
        w_i, b_i = _ridge((X[keep] - m_i) / s_i, y[keep], l2)
        est = float((X[i] - m_i) / s_i @ w_i + b_i)
        denom = max(abs(y[i]), 1e-12)
        rel_errs.append(abs(est - y[i]) / denom)
    report = {"loo_rel_err": float(np.mean(rel_errs)),
              "loo_rel_err_max": float(np.max(rel_errs)), "n": n}
    return pred, report


def best_point(domain: str,
               points: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """The MEASURED argbest point under the domain's objective
    direction — what the committed table records (the predictor ranks
    unmeasured candidates; the table never claims more than what was
    measured)."""
    pts = list(points)
    _check_points(domain, pts)
    _, direction = _knobs.OBJECTIVES[domain]
    key = lambda p: float(p["objective"])  # noqa: E731 - local sort key
    return (min if direction == "min" else max)(pts, key=key)
