"""Sweep driver: knob points -> measured ``autotune_sweep`` records.

This module is the record-store half of the autotuner loop.  It takes
a list of knob dicts and a ``measure`` callable (the actual
bench/loadgen glue lives in ``tools/autotune.py``, so ``singa_tpu``
never imports ``tools``), runs each point, and appends ONE validated
``autotune_sweep`` entry per point under a shared ``sweep_id`` — the
same append-only, schema-linted store every other telemetry producer
uses, so ``python -m tools.obsq diff --sweep <id>`` and ``python -m
tools.lint --records`` work on sweeps for free.

The fit step reads the points back (:func:`sweep_points_from_store`),
fits the predictor, and appends a FIT record — same kind, same
``sweep_id``, ``point = -1`` — carrying the leave-one-out error
report, so the committed store holds both the measurements and the
number that says how much to trust interpolating between them.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..obs import record as obs_record
from . import knobs as _knobs

__all__ = ["new_sweep_id", "append_point", "append_fit", "run_sweep",
           "sweep_points_from_store", "FIT_POINT"]

#: the fit record's ``point`` index — measurement points are >= 0
FIT_POINT = -1


def new_sweep_id() -> str:
    return obs_record.new_run_id("atsweep")


def _entry(store_path: str, payload: Dict[str, Any], platform: str,
           device: str, smoke: bool) -> Dict[str, Any]:
    entry = obs_record.new_entry(
        "autotune_sweep", platform, smoke, device,
        run_id=obs_record.new_run_id("at"), payload=payload)
    obs_record.RunRecord(store_path).append(entry)
    return entry


def append_point(store_path: str, *, domain: str, model: str,
                 platform: str, device: str, sweep_id: str, point: int,
                 knobs: Dict[str, Any], objective: float,
                 smoke: bool = True,
                 features: Optional[Dict[str, Any]] = None,
                 extra: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
    """Append one measured sweep point (validated on the way in)."""
    _knobs.require_knobs(domain, knobs, ctx=f"{domain} sweep point")
    objective_name, _ = _knobs.OBJECTIVES[domain]
    payload: Dict[str, Any] = {
        "domain": domain, "model": model,
        "objective_name": objective_name, "sweep_id": sweep_id,
        "point": int(point), "objective": float(objective),
        "knobs": dict(knobs),
    }
    if features:
        payload["features"] = {k: float(v)
                               for k, v in sorted(features.items())}
    if extra:
        payload.update(extra)
    return _entry(store_path, payload, platform, device, smoke)


def append_fit(store_path: str, *, domain: str, model: str,
               platform: str, device: str, sweep_id: str,
               best: Dict[str, Any], report: Dict[str, Any],
               smoke: bool = True,
               spec_evidence: Optional[Dict[str, Any]] = None
               ) -> Dict[str, Any]:
    """Append the fit-summary record (``point = FIT_POINT``): the
    measured argbest knobs + objective, and the predictor's
    leave-one-out report — the committed trustworthiness number the
    acceptance tests bound."""
    objective_name, _ = _knobs.OBJECTIVES[domain]
    payload: Dict[str, Any] = {
        "domain": domain, "model": model,
        "objective_name": objective_name, "sweep_id": sweep_id,
        "point": FIT_POINT,
        "objective": float(best["objective"]),
        "knobs": dict(best["knobs"]),
        "loo_rel_err": float(report["loo_rel_err"]),
        "loo_rel_err_max": float(report["loo_rel_err_max"]),
        "n_points": int(report["n"]),
    }
    if spec_evidence:
        payload["spec_k_evidence_run"] = str(spec_evidence["run_id"])
    return _entry(store_path, payload, platform, device, smoke)


def run_sweep(domain: str, model: str,
              points: Sequence[Dict[str, Any]],
              measure: Callable[[Dict[str, Any]],
                                Tuple[float, Dict[str, Any]]],
              store_path: str, *, platform: str, device: str,
              smoke: bool = True, sweep_id: Optional[str] = None,
              log: Optional[Callable[[str], None]] = None
              ) -> Tuple[str, List[Dict[str, Any]]]:
    """Measure every knob point and append its record; returns
    ``(sweep_id, entries)``.

    ``measure(knobs)`` returns ``(objective, features)`` — features
    may be ``{}``.  A point that RAISES aborts the sweep loudly (a
    partial sweep is still a valid record group; the fit step sees
    exactly the points that were measured), but knob validation
    happens for ALL points up front so a typo'd grid never burns
    minutes measuring before failing."""
    pts = list(points)
    if not pts:
        raise _knobs.KnobError(f"{domain} sweep: no points")
    for i, knobs in enumerate(pts):
        _knobs.require_knobs(domain, knobs, ctx=f"{domain} sweep "
                                                f"point {i}")
    sid = sweep_id or new_sweep_id()
    entries: List[Dict[str, Any]] = []
    for i, knobs in enumerate(pts):
        objective, features = measure(knobs)
        entries.append(append_point(
            store_path, domain=domain, model=model, platform=platform,
            device=device, sweep_id=sid, point=i, knobs=knobs,
            objective=objective, smoke=smoke, features=features))
        if log is not None:
            log(f"point {i + 1}/{len(pts)} {knobs} -> "
                f"{_knobs.OBJECTIVES[domain][0]}={objective:.3f}")
    return sid, entries


def sweep_points_from_store(store_path: str, domain: str,
                            model: Optional[str] = None,
                            platform: Optional[str] = None,
                            sweep_id: Optional[str] = None
                            ) -> Tuple[str, List[Dict[str, Any]],
                                       Optional[Dict[str, Any]]]:
    """Read one sweep group back: ``(sweep_id, point payloads in point
    order, fit payload or None)``.  With no ``sweep_id`` the NEWEST
    matching group (by append order) is used.  No matching records is
    loud — an empty store must not fit an empty predictor."""
    groups: Dict[str, List[Dict[str, Any]]] = {}
    order: List[str] = []
    for e in obs_record.RunRecord(store_path).entries():
        if e["kind"] != "autotune_sweep":
            continue
        p = e["payload"]
        if p["domain"] != domain:
            continue
        if model is not None and p["model"] != model:
            continue
        if platform is not None and e["platform"] != platform:
            continue
        sid = p["sweep_id"]
        if sid not in groups:
            groups[sid] = []
            order.append(sid)
        # the entry-level identity rides along so a later fit record
        # can stamp the SAME device as the points it summarizes
        groups[sid].append({**p, "run_id": e["run_id"],
                            "device": e["device"]})
    if sweep_id is None:
        if not order:
            raise LookupError(
                f"no {domain!r} autotune_sweep records"
                + (f" for model {model!r}" if model else "")
                + f" in {store_path} — run `python -m tools.autotune "
                  f"sweep` first")
        sweep_id = order[-1]
    elif sweep_id not in groups:
        raise LookupError(f"no autotune_sweep records with sweep_id "
                          f"{sweep_id!r} in {store_path}")
    rows = groups[sweep_id]
    fit = next((r for r in rows if r["point"] == FIT_POINT), None)
    pts = sorted((r for r in rows if r["point"] >= 0),
                 key=lambda r: r["point"])
    return sweep_id, pts, fit
