"""Request/step-scoped trace contexts (ISSUE 11).

A *trace* ties every telemetry event a subsystem emits while working on
one logical unit — a serve request, a training run — to one id, without
threading that id through every call signature.  The id rides a
:mod:`contextvars` context variable: ``ServeEngine`` activates a
request's trace around its admission/prefill/delivery sections,
``TrainRunner`` activates its run id around the step loop, and
:mod:`singa_tpu.obs.events` stamps the active ``trace`` (plus ``span``/
``parent`` ids for spans, so spans nest) into every emitted line.  The
flight recorder (:mod:`singa_tpu.obs.flight`) stamps the same id into
its in-memory ring, which is how an incident dump reconstructs exactly
the poisoned request's timeline.

Thread rules (the part contextvars do NOT do automatically):

* a ``threading.Thread`` starts with an EMPTY context — it never
  inherits the spawner's trace by accident, so two threads cannot leak
  span parentage into each other's traces;
* a worker that SHOULD carry the spawner's trace (the checkpoint
  background writer: its ``train.ckpt.write`` span belongs to the step
  that snapshotted) captures it with :func:`capture` on the spawning
  thread and re-enters it with :func:`attach` on the worker;
* a watchdog that observes the whole process rather than one unit
  (``utils.failure.Heartbeat``'s monitor thread) deliberately runs
  trace-less — its events are engine-scoped, not request-scoped
  (documented there).

Zero-overhead contract: reading/activating a context is a few hundred
nanoseconds of pure Python and allocates nothing persistent; when no
telemetry consumer is installed, nothing downstream even reads it.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import os
from typing import Iterator, Optional, Tuple

__all__ = ["new_trace_id", "current", "current_trace_id",
           "current_span_id", "activate", "capture", "attach",
           "new_span_id"]

#: (trace_id, parent_span_id) of the active trace, or None outside one.
#: One ContextVar holding a tuple, so readers pay a single .get().
_STATE: contextvars.ContextVar[Optional[Tuple[str, Optional[int]]]] = \
    contextvars.ContextVar("singa_obs_trace", default=None)

_trace_seq = itertools.count()
_span_seq = itertools.count(1)


def new_trace_id(prefix: str = "tr") -> str:
    """A process-unique trace id (``<prefix>-<pid>-<seq>``).  Callers
    with a naturally-unique id (a run_id, ``run_id/r<rid>``) should use
    that instead — ids exist to be greppable."""
    return f"{prefix}-{os.getpid()}-{next(_trace_seq)}"


def new_span_id() -> int:
    """Process-unique span id (monotonic int; uniqueness is per process,
    which is the scope a trace file covers)."""
    return next(_span_seq)


def current() -> Optional[Tuple[str, Optional[int]]]:
    """The active ``(trace_id, parent_span_id)``, or None."""
    return _STATE.get()


def current_trace_id() -> Optional[str]:
    ctx = _STATE.get()
    return ctx[0] if ctx is not None else None


def current_span_id() -> Optional[int]:
    ctx = _STATE.get()
    return ctx[1] if ctx is not None else None


@contextlib.contextmanager
def activate(trace_id: str,
             parent_span: Optional[int] = None) -> Iterator[str]:
    """Make ``trace_id`` the active trace for the dynamic extent of the
    block.  Nested activations shadow (and restore) the outer trace —
    e.g. a per-request section inside an engine-level span."""
    token = _STATE.set((trace_id, parent_span))
    try:
        yield trace_id
    finally:
        _STATE.reset(token)


def capture() -> Optional[Tuple[str, Optional[int]]]:
    """Snapshot the active context for hand-off to a worker thread
    (:func:`attach` on the other side).  Returns None outside a trace —
    attaching None is a documented no-op, so capture/attach pairs are
    safe unconditionally."""
    return _STATE.get()


@contextlib.contextmanager
def attach(ctx: Optional[Tuple[str, Optional[int]]]) -> Iterator[None]:
    """Re-enter a context captured on another thread (the checkpoint
    writer inheriting the saving step's trace).  ``attach(None)`` is a
    no-op block."""
    if ctx is None:
        yield
        return
    token = _STATE.set(ctx)
    try:
        yield
    finally:
        _STATE.reset(token)


def _push_span(span_id: int):
    """Used by ``events._Span``: keep the trace, re-parent children to
    ``span_id``.  Returns the reset token (None when no trace is
    active)."""
    ctx = _STATE.get()
    if ctx is None:
        return None
    return _STATE.set((ctx[0], span_id))


def _pop_span(token) -> None:
    if token is not None:
        _STATE.reset(token)
