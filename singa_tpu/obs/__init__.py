"""singa_tpu.obs — durable run records + structured telemetry.

The observability subsystem (ISSUE 1):

* :mod:`~singa_tpu.obs.schema` — versioned field contracts for every
  committed telemetry artifact; ``require()`` gives consumers
  named-field errors instead of KeyError.
* :mod:`~singa_tpu.obs.record` — :class:`RunRecord`, the append-only
  JSONL store of bench/session runs keyed by
  ``(run_id, platform, smoke)`` with atomic write-temp-then-rename;
  smoke/CPU entries can never overwrite or shadow on-chip entries.
* :mod:`~singa_tpu.obs.events` — ``trace_span`` / ``counter`` /
  ``gauge`` with a JSONL sink and optional ``jax.profiler``
  annotation passthrough, wired into the compiled-step, collective,
  and grad-sync hot paths.
* :mod:`~singa_tpu.obs.trace` — contextvar-carried request/step trace
  contexts (ISSUE 11): every event emitted inside an active trace is
  stamped with its id, spans nest, and worker threads inherit (or
  explicitly drop) the spawner's context.
* :mod:`~singa_tpu.obs.flight` — :class:`FlightRecorder`, the bounded
  in-memory incident ring dumped to ``runs/incidents/`` (and referenced
  from ``incident``/``train_run`` records via ``flight_ref``) when a
  fault fires through to quarantine/recovery/fatal.
* :mod:`~singa_tpu.obs.attr` — the runtime-attribution ledger
  (ISSUE 16): per-program dispatch timing at the jitted call seams,
  joined against the analytic cost model into ``perf_attr`` records
  and gated by the PERF00x sentinel (tools/lint/perf.py).

``tools/obsq.py`` is the query layer over all three (timeline
rendering, trace-derived SLO recomputation, record trajectories).  See
docs/observability.md for the schema and the smoke-vs-chip protection
rule.
"""

from . import attr, events, flight, record, schema, trace
from .events import (configure, counter, gauge, histogram,
                     histogram_summary, reset_histograms, span, trace_span)
from .flight import FlightRecorder
from .record import RunRecord, is_onchip_session_doc, new_entry, new_run_id
from .schema import SCHEMA_VERSION, SchemaError, require

__all__ = ["schema", "record", "events", "trace", "flight", "attr",
           "FlightRecorder", "RunRecord", "SchemaError",
           "SCHEMA_VERSION", "require", "new_entry", "new_run_id",
           "is_onchip_session_doc", "configure", "counter", "gauge",
           "span", "trace_span", "histogram", "histogram_summary",
           "reset_histograms"]
