"""Structured telemetry events: spans, counters, gauges.

A thin host-side event layer over the hot paths (compiled-step
dispatch, XLA compiles, collective staging, grad sync).  Disabled by
default and engineered so the disabled path costs one attribute check —
`span()` returns a shared no-op context manager and `counter()/gauge()`
return immediately — because `Model.train_step` calls into here every
step.

Enable with either:

* ``SINGA_OBS=/path/to/events.jsonl`` in the environment (one JSON
  object per line), or programmatically ``events.configure(path=...)``;
* ``SINGA_OBS_XPROF=1`` to additionally wrap spans in
  ``jax.profiler.TraceAnnotation`` so they show up on the XProf/
  TensorBoard timeline next to the device trace.

Semantics worth knowing before reading the numbers:

* **span durations are host-side wall clock.**  JAX dispatch is async:
  a span around a compiled step measures time-to-dispatch (plus any
  blocking fetch the caller does inside), not device time.  Device
  time comes from ``utils.timing`` (true-fenced windows) or the XProf
  trace — spans tell you *what ran when* and catch multi-second stalls
  (compiles, tunnel weather), they are not an MFU instrument.
* **collective counters fire at trace time.**  ``comm.*.bytes``
  counters are emitted while XLA traces the step — once per compile,
  not once per execution — because the collectives themselves are
  in-graph ops.  They record the *staged* payload sizes (what the
  wire will carry every step), which is the quantity the parallel
  layer's bandwidth accounting needs.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import warnings
from typing import Any, Dict, Optional

from . import trace

__all__ = ["JsonlSink", "configure", "enabled", "get_sink", "span",
           "trace_span", "counter", "gauge", "histogram",
           "histogram_summary", "reset_histograms"]


class JsonlSink:
    """Append events to a JSONL file (thread-safe, line-buffered).

    ``max_bytes`` (or ``SINGA_OBS_MAX_BYTES``; default off) bounds the
    file: when the next line would cross the limit the current file is
    atomically renamed to ``<path>.1`` (replacing the previous rollover)
    and a fresh file is opened — a loadgen/chaos soak holds at most
    ``2 * max_bytes`` of event data on disk instead of growing without
    bound."""

    def __init__(self, path: str, max_bytes: Optional[int] = None):
        self.path = path
        if max_bytes is not None and int(max_bytes) < 0:
            raise ValueError(
                f"max_bytes must be >= 0 (0/None disables rotation), "
                f"got {max_bytes}")
        self.max_bytes = int(max_bytes) if max_bytes else None
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._f = open(path, "a")
        self._size = self._f.tell()
        self._lock = threading.Lock()

    def emit(self, event: Dict[str, Any]) -> None:
        line = json.dumps(event, sort_keys=True, default=_jsonable)
        with self._lock:
            if self._f.closed:
                return
            try:
                if (self.max_bytes is not None and self._size
                        and self._size + len(line) + 1 > self.max_bytes):
                    self._rotate()
                self._f.write(line + "\n")
                self._f.flush()
                self._size += len(line) + 1
            except (OSError, ValueError):
                # disk full / fd gone mid-run: telemetry degrades, the
                # training loop it instruments must never die for it
                try:
                    self._f.close()
                except OSError:
                    pass

    def _rotate(self) -> None:
        """Size-based rollover (caller holds the lock): close, atomic
        ``os.replace`` to ``<path>.1`` (clobbering the previous roll),
        reopen fresh — every retained line lives in a complete file."""
        self._f.close()
        os.replace(self.path, self.path + ".1")
        self._f = open(self.path, "a")  # singalint: disable=SGL012 the sink lock exists to serialize file writers; rollover I/O under it is the design, bounded to one reopen per max_bytes of events
        self._size = 0

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


def _jsonable(v):
    try:
        return float(v)
    except (TypeError, ValueError):
        return repr(v)


_sink: Optional[JsonlSink] = None
_annotate = False
#: serializes sink swaps: two concurrent configure() calls would both
#: read the same ``old`` and one replaced sink would never be closed
_config_lock = threading.Lock()


def configure(sink: Optional[JsonlSink] = None, path: Optional[str] = None,
              annotate: Optional[bool] = None,
              max_bytes: Optional[int] = None) -> None:
    """Install/replace the event sink and/or the XProf annotation flag.

    ``configure()`` with no arguments disables the JSONL sink (closing
    the old one) and leaves annotation untouched.  ``max_bytes``
    applies to a sink built from ``path`` (size-based rollover to
    ``<path>.1``; ``SINGA_OBS_MAX_BYTES`` in the environment).

    Safe to call while other threads emit: emitters snapshot the sink
    reference once per event (see ``_emit``), and a swapped-out sink's
    ``emit`` degrades to a no-op once closed."""
    if path is not None:
        sink = JsonlSink(path, max_bytes=max_bytes)
    global _sink, _annotate
    with _config_lock:
        old = _sink
        _sink = sink
        if annotate is not None:
            _annotate = bool(annotate)
    if old is not None and old is not sink:
        old.close()


def _init_from_env() -> None:
    path = os.environ.get("SINGA_OBS")
    if path:
        max_bytes: Optional[int] = None
        raw = os.environ.get("SINGA_OBS_MAX_BYTES")
        if raw:
            try:
                max_bytes = int(raw)
            except ValueError:
                warnings.warn(f"SINGA_OBS_MAX_BYTES={raw!r} is not an "
                              f"integer; sink rotation disabled",
                              stacklevel=2)
            if max_bytes is not None and max_bytes < 0:
                # a bad limit must degrade to "no rotation", never kill
                # the sink itself (JsonlSink would raise ValueError)
                warnings.warn(f"SINGA_OBS_MAX_BYTES={raw!r} is negative; "
                              f"sink rotation disabled", stacklevel=2)
                max_bytes = None
        try:
            configure(path=path, max_bytes=max_bytes)
        except (OSError, ValueError):
            # unwritable path / bad limit must never break training
            pass
    if os.environ.get("SINGA_OBS_XPROF") == "1":
        configure(sink=_sink, annotate=True)


def enabled() -> bool:
    """Cheap hot-path check: is any telemetry consumer installed?"""
    return _sink is not None or _annotate


def get_sink() -> Optional[JsonlSink]:
    return _sink


def _emit(kind: str, name: str, attrs: Dict[str, Any]) -> None:
    # SNAPSHOT the module global exactly once: a concurrent
    # configure() can swap (or clear) the sink between a check and a
    # use, and the pre-fix double read of ``_sink`` crashed the
    # emitting thread with AttributeError — telemetry taking down the
    # step loop it instruments (forced-interleaving regression test in
    # tests/test_obs.py).  Emitting into the just-replaced sink is
    # fine: its emit() is a silent no-op once closed.
    sink = _sink
    if sink is None:
        return
    ev = {"t": time.time(), "kind": kind, "name": name}  # singalint: disable=SGL005 event timestamps must correlate across hosts/files; durations use the monotonic clocks in span()
    # request/step attribution (ISSUE 11): every event emitted inside
    # an active obs.trace context carries its trace id — how obsq
    # reconstructs one request's timeline out of an interleaved stream
    tid = trace.current_trace_id()
    if tid is not None and "trace" not in attrs:
        ev["trace"] = tid
    ev.update(attrs)
    sink.emit(ev)


def counter(name: str, value, **attrs) -> None:
    """A monotonically-accumulating quantity (bytes moved, steps run)."""
    if _sink is not None:
        attrs["value"] = value
        _emit("counter", name, attrs)


def gauge(name: str, value, **attrs) -> None:
    """A point-in-time level (loss, queue depth, HBM headroom)."""
    if _sink is not None:
        attrs["value"] = value
        _emit("gauge", name, attrs)


#: bounded per-name sample buffer: count/sum/min/max stay exact beyond
#: this; percentiles are computed over a deterministic ring of the most
#: recent _HIST_CAP observations (no RNG — reproducible summaries)
_HIST_CAP = 4096


class _Hist:
    __slots__ = ("count", "total", "vmin", "vmax", "samples")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")
        self.samples: list = []

    def observe(self, v: float) -> None:
        self.count += 1
        self.total += v
        self.vmin = min(self.vmin, v)
        self.vmax = max(self.vmax, v)
        if len(self.samples) < _HIST_CAP:
            self.samples.append(v)
        else:
            self.samples[(self.count - 1) % _HIST_CAP] = v

    def summary(self) -> Optional[Dict[str, Any]]:
        """{count, sum, mean, min, max, p50, p90, p99}, or None when
        nothing was observed yet.

        Determinism/approximation contract (regression-tested in
        tests/test_obs.py): count/sum/mean/min/max are exact over every
        observation.  Percentiles are nearest-rank over the retained
        ring — observation ``i`` (0-based) lives in slot
        ``i % _HIST_CAP``, so once the ring has wrapped it holds
        exactly the most recent ``_HIST_CAP`` observations and the same
        insertion order always reproduces the same summary (no RNG, no
        reservoir).  While ``count <= _HIST_CAP`` the percentiles are
        exact; beyond that they are the exact nearest-rank quantiles of
        the most recent window (rank resolution ``1/_HIST_CAP``), which
        can differ from the all-time quantile only by however much the
        stream drifted outside that window — for latency SLOs the
        recent window is the quantity of interest anyway."""
        if not self.count:
            return None
        vals = sorted(self.samples)
        return {"count": self.count, "sum": self.total,
                "mean": self.total / self.count, "min": self.vmin,
                "max": self.vmax,
                "p50": _percentile(vals, 50.0),
                "p90": _percentile(vals, 90.0),
                "p99": _percentile(vals, 99.0)}


_hists: Dict[str, _Hist] = {}
_hist_lock = threading.Lock()


def histogram(name: str, value, **attrs) -> None:
    """One observation of a distribution (a latency, a queue wait).

    Unlike counter/gauge, histograms ALWAYS aggregate in-process —
    cheaply (one list append under a lock) — because their consumers
    (serve.metrics TTFT/per-token percentiles, the serve_throughput
    bench) need summaries even when no JSONL sink is installed.  With a
    sink, each observation is additionally emitted as a
    ``{"kind": "hist", "name": ..., "value": ...}`` line."""
    v = float(value)
    with _hist_lock:
        h = _hists.get(name)
        if h is None:
            h = _hists[name] = _Hist()
        h.observe(v)
    if _sink is not None:
        attrs["value"] = v
        _emit("hist", name, attrs)


def _percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over the retained samples."""
    i = min(len(sorted_vals) - 1, max(0, int(round(
        q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[i]


def histogram_summary(name: str) -> Optional[Dict[str, Any]]:
    """{count, sum, mean, min, max, p50, p90, p99} for ``name``, or
    None when nothing was observed.  count/sum/min/max are exact over
    every observation; percentiles come from the retained ring (the
    most recent ``_HIST_CAP`` samples)."""
    with _hist_lock:
        h = _hists.get(name)
        return h.summary() if h is not None else None


def reset_histograms(name: Optional[str] = None) -> None:
    """Drop one histogram's aggregates (or all of them) — a bench run
    isolating its own window calls this before the measured phase."""
    with _hist_lock:
        if name is None:
            _hists.clear()
        else:
            _hists.pop(name, None)


class _NullCtx:
    """Shared no-op context manager for the disabled fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullCtx()


class _Span:
    __slots__ = ("name", "attrs", "_t0", "_ann", "_sid", "_parent",
                 "_tok")

    def __init__(self, name: str, attrs: Dict[str, Any]):
        self.name = name
        self.attrs = attrs
        self._t0 = 0.0
        self._ann = None
        self._sid = None
        self._parent = None
        self._tok = None

    def __enter__(self):
        if _annotate:
            try:
                import jax
                self._ann = jax.profiler.TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:  # profiler optional; never break the step
                self._ann = None
        # inside an active trace, spans nest: this span takes a span id,
        # records the current parent, and becomes the parent for any
        # span opened within its extent (contextvar push, popped on
        # exit) — no id threading through call signatures
        ctx = trace.current()
        if ctx is not None:
            self._parent = ctx[1]
            self._sid = trace.new_span_id()
            self._tok = trace._push_span(self._sid)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        trace._pop_span(self._tok)
        if self._ann is not None:
            with contextlib.suppress(Exception):
                self._ann.__exit__(exc_type, exc, tb)
        attrs = self.attrs
        attrs["dur_ms"] = round(dur * 1e3, 3)
        if self._sid is not None:
            attrs["span"] = self._sid
            if self._parent is not None:
                attrs["parent"] = self._parent
        if exc_type is not None:
            attrs["error"] = exc_type.__name__
        _emit("span", self.name, attrs)
        return False


def span(name: str, **attrs):
    """Context manager timing a host-side region.

        with events.span("graph.compile", graph="llama.train"):
            compiled = lowered.compile()

    Emits ``{"kind": "span", "name": ..., "dur_ms": ...}`` to the sink
    and (with SINGA_OBS_XPROF=1) annotates the XProf timeline.  Returns
    a shared no-op context when telemetry is disabled."""
    if _sink is None and not _annotate:
        return _NULL
    return _Span(name, attrs)


#: alias matching the subsystem spec (`trace_span` in ISSUE.md)
trace_span = span

_init_from_env()
