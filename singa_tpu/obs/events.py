"""Structured telemetry events: spans, counters, gauges.

A thin host-side event layer over the hot paths (compiled-step
dispatch, XLA compiles, collective staging, grad sync).  Disabled by
default and engineered so the disabled path costs one attribute check —
`span()` returns a shared no-op context manager and `counter()/gauge()`
return immediately — because `Model.train_step` calls into here every
step.

Enable with either:

* ``SINGA_OBS=/path/to/events.jsonl`` in the environment (one JSON
  object per line), or programmatically ``events.configure(path=...)``;
* ``SINGA_OBS_XPROF=1`` to additionally wrap spans in
  ``jax.profiler.TraceAnnotation`` so they show up on the XProf/
  TensorBoard timeline next to the device trace.

Semantics worth knowing before reading the numbers:

* **span durations are host-side wall clock.**  JAX dispatch is async:
  a span around a compiled step measures time-to-dispatch (plus any
  blocking fetch the caller does inside), not device time.  Device
  time comes from ``utils.timing`` (true-fenced windows) or the XProf
  trace — spans tell you *what ran when* and catch multi-second stalls
  (compiles, tunnel weather), they are not an MFU instrument.
* **collective counters fire at trace time.**  ``comm.*.bytes``
  counters are emitted while XLA traces the step — once per compile,
  not once per execution — because the collectives themselves are
  in-graph ops.  They record the *staged* payload sizes (what the
  wire will carry every step), which is the quantity the parallel
  layer's bandwidth accounting needs.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, Optional

__all__ = ["JsonlSink", "configure", "enabled", "get_sink", "span",
           "trace_span", "counter", "gauge", "histogram",
           "histogram_summary", "reset_histograms"]


class JsonlSink:
    """Append events to a JSONL file (thread-safe, line-buffered)."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._f = open(path, "a")
        self._lock = threading.Lock()

    def emit(self, event: Dict[str, Any]) -> None:
        line = json.dumps(event, sort_keys=True, default=_jsonable)
        with self._lock:
            if self._f.closed:
                return
            try:
                self._f.write(line + "\n")
                self._f.flush()
            except (OSError, ValueError):
                # disk full / fd gone mid-run: telemetry degrades, the
                # training loop it instruments must never die for it
                try:
                    self._f.close()
                except OSError:
                    pass

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


def _jsonable(v):
    try:
        return float(v)
    except (TypeError, ValueError):
        return repr(v)


_sink: Optional[JsonlSink] = None
_annotate = False


def configure(sink: Optional[JsonlSink] = None, path: Optional[str] = None,
              annotate: Optional[bool] = None) -> None:
    """Install/replace the event sink and/or the XProf annotation flag.

    ``configure()`` with no arguments disables the JSONL sink (closing
    the old one) and leaves annotation untouched."""
    global _sink, _annotate
    old = _sink
    if path is not None:
        sink = JsonlSink(path)
    _sink = sink
    if annotate is not None:
        _annotate = bool(annotate)
    if old is not None and old is not _sink:
        old.close()


def _init_from_env() -> None:
    path = os.environ.get("SINGA_OBS")
    if path:
        try:
            configure(path=path)
        except OSError:  # unwritable path must never break training
            pass
    if os.environ.get("SINGA_OBS_XPROF") == "1":
        configure(sink=_sink, annotate=True)


def enabled() -> bool:
    """Cheap hot-path check: is any telemetry consumer installed?"""
    return _sink is not None or _annotate


def get_sink() -> Optional[JsonlSink]:
    return _sink


def _emit(kind: str, name: str, attrs: Dict[str, Any]) -> None:
    if _sink is None:
        return
    ev = {"t": time.time(), "kind": kind, "name": name}  # singalint: disable=SGL005 event timestamps must correlate across hosts/files; durations use the monotonic clocks in span()
    ev.update(attrs)
    _sink.emit(ev)


def counter(name: str, value, **attrs) -> None:
    """A monotonically-accumulating quantity (bytes moved, steps run)."""
    if _sink is not None:
        attrs["value"] = value
        _emit("counter", name, attrs)


def gauge(name: str, value, **attrs) -> None:
    """A point-in-time level (loss, queue depth, HBM headroom)."""
    if _sink is not None:
        attrs["value"] = value
        _emit("gauge", name, attrs)


#: bounded per-name sample buffer: count/sum/min/max stay exact beyond
#: this; percentiles are computed over a deterministic ring of the most
#: recent _HIST_CAP observations (no RNG — reproducible summaries)
_HIST_CAP = 4096


class _Hist:
    __slots__ = ("count", "total", "vmin", "vmax", "samples")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")
        self.samples: list = []

    def observe(self, v: float) -> None:
        self.count += 1
        self.total += v
        self.vmin = min(self.vmin, v)
        self.vmax = max(self.vmax, v)
        if len(self.samples) < _HIST_CAP:
            self.samples.append(v)
        else:
            self.samples[(self.count - 1) % _HIST_CAP] = v

    def summary(self) -> Optional[Dict[str, Any]]:
        """{count, sum, mean, min, max, p50, p90, p99}, or None when
        nothing was observed yet."""
        if not self.count:
            return None
        vals = sorted(self.samples)
        return {"count": self.count, "sum": self.total,
                "mean": self.total / self.count, "min": self.vmin,
                "max": self.vmax,
                "p50": _percentile(vals, 50.0),
                "p90": _percentile(vals, 90.0),
                "p99": _percentile(vals, 99.0)}


_hists: Dict[str, _Hist] = {}
_hist_lock = threading.Lock()


def histogram(name: str, value, **attrs) -> None:
    """One observation of a distribution (a latency, a queue wait).

    Unlike counter/gauge, histograms ALWAYS aggregate in-process —
    cheaply (one list append under a lock) — because their consumers
    (serve.metrics TTFT/per-token percentiles, the serve_throughput
    bench) need summaries even when no JSONL sink is installed.  With a
    sink, each observation is additionally emitted as a
    ``{"kind": "hist", "name": ..., "value": ...}`` line."""
    v = float(value)
    with _hist_lock:
        h = _hists.get(name)
        if h is None:
            h = _hists[name] = _Hist()
        h.observe(v)
    if _sink is not None:
        attrs["value"] = v
        _emit("hist", name, attrs)


def _percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over the retained samples."""
    i = min(len(sorted_vals) - 1, max(0, int(round(
        q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[i]


def histogram_summary(name: str) -> Optional[Dict[str, Any]]:
    """{count, sum, mean, min, max, p50, p90, p99} for ``name``, or
    None when nothing was observed.  count/sum/min/max are exact over
    every observation; percentiles come from the retained ring (the
    most recent ``_HIST_CAP`` samples)."""
    with _hist_lock:
        h = _hists.get(name)
        return h.summary() if h is not None else None


def reset_histograms(name: Optional[str] = None) -> None:
    """Drop one histogram's aggregates (or all of them) — a bench run
    isolating its own window calls this before the measured phase."""
    with _hist_lock:
        if name is None:
            _hists.clear()
        else:
            _hists.pop(name, None)


class _NullCtx:
    """Shared no-op context manager for the disabled fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullCtx()


class _Span:
    __slots__ = ("name", "attrs", "_t0", "_ann")

    def __init__(self, name: str, attrs: Dict[str, Any]):
        self.name = name
        self.attrs = attrs
        self._t0 = 0.0
        self._ann = None

    def __enter__(self):
        if _annotate:
            try:
                import jax
                self._ann = jax.profiler.TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:  # profiler optional; never break the step
                self._ann = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        if self._ann is not None:
            with contextlib.suppress(Exception):
                self._ann.__exit__(exc_type, exc, tb)
        attrs = self.attrs
        attrs["dur_ms"] = round(dur * 1e3, 3)
        if exc_type is not None:
            attrs["error"] = exc_type.__name__
        _emit("span", self.name, attrs)
        return False


def span(name: str, **attrs):
    """Context manager timing a host-side region.

        with events.span("graph.compile", graph="llama.train"):
            compiled = lowered.compile()

    Emits ``{"kind": "span", "name": ..., "dur_ms": ...}`` to the sink
    and (with SINGA_OBS_XPROF=1) annotates the XProf timeline.  Returns
    a shared no-op context when telemetry is disabled."""
    if _sink is None and not _annotate:
        return _NULL
    return _Span(name, attrs)


#: alias matching the subsystem spec (`trace_span` in ISSUE.md)
trace_span = span

_init_from_env()
