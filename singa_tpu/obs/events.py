"""Structured telemetry events: spans, counters, gauges.

A thin host-side event layer over the hot paths (compiled-step
dispatch, XLA compiles, collective staging, grad sync).  Disabled by
default and engineered so the disabled path costs one attribute check —
`span()` returns a shared no-op context manager and `counter()/gauge()`
return immediately — because `Model.train_step` calls into here every
step.

Enable with either:

* ``SINGA_OBS=/path/to/events.jsonl`` in the environment (one JSON
  object per line), or programmatically ``events.configure(path=...)``;
* ``SINGA_OBS_XPROF=1`` to additionally wrap spans in
  ``jax.profiler.TraceAnnotation`` so they show up on the XProf/
  TensorBoard timeline next to the device trace.

Semantics worth knowing before reading the numbers:

* **span durations are host-side wall clock.**  JAX dispatch is async:
  a span around a compiled step measures time-to-dispatch (plus any
  blocking fetch the caller does inside), not device time.  Device
  time comes from ``utils.timing`` (true-fenced windows) or the XProf
  trace — spans tell you *what ran when* and catch multi-second stalls
  (compiles, tunnel weather), they are not an MFU instrument.
* **collective counters fire at trace time.**  ``comm.*.bytes``
  counters are emitted while XLA traces the step — once per compile,
  not once per execution — because the collectives themselves are
  in-graph ops.  They record the *staged* payload sizes (what the
  wire will carry every step), which is the quantity the parallel
  layer's bandwidth accounting needs.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, Optional

__all__ = ["JsonlSink", "configure", "enabled", "get_sink", "span",
           "trace_span", "counter", "gauge"]


class JsonlSink:
    """Append events to a JSONL file (thread-safe, line-buffered)."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._f = open(path, "a")
        self._lock = threading.Lock()

    def emit(self, event: Dict[str, Any]) -> None:
        line = json.dumps(event, sort_keys=True, default=_jsonable)
        with self._lock:
            if self._f.closed:
                return
            try:
                self._f.write(line + "\n")
                self._f.flush()
            except (OSError, ValueError):
                # disk full / fd gone mid-run: telemetry degrades, the
                # training loop it instruments must never die for it
                try:
                    self._f.close()
                except OSError:
                    pass

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


def _jsonable(v):
    try:
        return float(v)
    except (TypeError, ValueError):
        return repr(v)


_sink: Optional[JsonlSink] = None
_annotate = False


def configure(sink: Optional[JsonlSink] = None, path: Optional[str] = None,
              annotate: Optional[bool] = None) -> None:
    """Install/replace the event sink and/or the XProf annotation flag.

    ``configure()`` with no arguments disables the JSONL sink (closing
    the old one) and leaves annotation untouched."""
    global _sink, _annotate
    old = _sink
    if path is not None:
        sink = JsonlSink(path)
    _sink = sink
    if annotate is not None:
        _annotate = bool(annotate)
    if old is not None and old is not _sink:
        old.close()


def _init_from_env() -> None:
    path = os.environ.get("SINGA_OBS")
    if path:
        try:
            configure(path=path)
        except OSError:  # unwritable path must never break training
            pass
    if os.environ.get("SINGA_OBS_XPROF") == "1":
        configure(sink=_sink, annotate=True)


def enabled() -> bool:
    """Cheap hot-path check: is any telemetry consumer installed?"""
    return _sink is not None or _annotate


def get_sink() -> Optional[JsonlSink]:
    return _sink


def _emit(kind: str, name: str, attrs: Dict[str, Any]) -> None:
    if _sink is None:
        return
    ev = {"t": time.time(), "kind": kind, "name": name}
    ev.update(attrs)
    _sink.emit(ev)


def counter(name: str, value, **attrs) -> None:
    """A monotonically-accumulating quantity (bytes moved, steps run)."""
    if _sink is not None:
        attrs["value"] = value
        _emit("counter", name, attrs)


def gauge(name: str, value, **attrs) -> None:
    """A point-in-time level (loss, queue depth, HBM headroom)."""
    if _sink is not None:
        attrs["value"] = value
        _emit("gauge", name, attrs)


class _NullCtx:
    """Shared no-op context manager for the disabled fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullCtx()


class _Span:
    __slots__ = ("name", "attrs", "_t0", "_ann")

    def __init__(self, name: str, attrs: Dict[str, Any]):
        self.name = name
        self.attrs = attrs
        self._t0 = 0.0
        self._ann = None

    def __enter__(self):
        if _annotate:
            try:
                import jax
                self._ann = jax.profiler.TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:  # profiler optional; never break the step
                self._ann = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        if self._ann is not None:
            with contextlib.suppress(Exception):
                self._ann.__exit__(exc_type, exc, tb)
        attrs = self.attrs
        attrs["dur_ms"] = round(dur * 1e3, 3)
        if exc_type is not None:
            attrs["error"] = exc_type.__name__
        _emit("span", self.name, attrs)
        return False


def span(name: str, **attrs):
    """Context manager timing a host-side region.

        with events.span("graph.compile", graph="llama.train"):
            compiled = lowered.compile()

    Emits ``{"kind": "span", "name": ..., "dur_ms": ...}`` to the sink
    and (with SINGA_OBS_XPROF=1) annotates the XProf timeline.  Returns
    a shared no-op context when telemetry is disabled."""
    if _sink is None and not _annotate:
        return _NULL
    return _Span(name, attrs)


#: alias matching the subsystem spec (`trace_span` in ISSUE.md)
trace_span = span

_init_from_env()
