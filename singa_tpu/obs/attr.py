"""Runtime attribution (ISSUE 16): the per-program perf ledger.

The repo's perf story has an analytic half (tools/lint/cost.py models
every flagship program's flops/HBM/roofline from its optimized HLO) and
a measured half (obs spans wrap the jitted dispatches) — but until this
module nothing attributed measured *seconds* to compiled *programs*, so
a 2x dispatch regression that leaves the HLO byte-identical sailed
through every gate.  The ledger closes that seam:

* every jitted dispatch — the train step (``model._StepExecutor``),
  the serve engine's prefill/decode/verify/handoff
  (``ServeEngine._dispatch``), DistOpt's eager grad-sync — is timed
  host-side with ``time.perf_counter`` around the already-existing call
  seam (OUTSIDE jit: singalint SGL001 treats ``obs.attr.*`` as impure,
  so a timer migrating inside a jit root is a lint finding);
* observations accumulate per program key as exact
  count/total/min/max plus the bounded-ring nearest-rank percentile
  estimator the event layer already provides
  (:class:`singa_tpu.obs.events._Hist` — same determinism contract);
* :func:`attribution_payload` joins a snapshot against the analytic
  per-program features (``tools.lint.cost.cost_features()``) into the
  schema-linted ``perf_attr`` record payload: achieved FLOP/s, achieved
  HBM GB/s, and the achieved-roofline fraction per program.

Zero-overhead-when-off contract (regression-tested like the fault
layer's): the instrumented seams read the module-global ledger ONCE per
dispatch; with no ledger installed that read is the entire cost — no
``perf_counter`` call, no allocation, no event.  Installation is
explicit (:func:`install`), never ambient.

The dispatch seams are host-side wall clock around an *asynchronous*
dispatch: under jax's async dispatch a noted duration is
time-to-dispatch plus whatever device work the caller's next host sync
forces.  Every instrumented seam here sits on a path whose caller
blocks on the result before the next dispatch (the serve tick consumes
logits; the train loop fetches loss), so in practice the ledger sees
per-dispatch wall time — but absolute numbers are box-dependent, which
is exactly why the PERF00x gate (tools/lint/perf.py) asserts rankings
and ratios, never milliseconds.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from .events import _Hist

__all__ = ["Ledger", "install", "uninstall", "get", "note",
           "attribution_payload", "NOMINAL_FLOPS_PER_S",
           "NOMINAL_HBM_BYTES_PER_S"]

#: the reference roofline the achieved fraction is computed against:
#: deliberately generous single-core-class ceilings (1 TFLOP/s, 100
#: GB/s) so the fraction reads as "share of a nominal box" and stays
#: below 1 on any host this repo's CPU smoke runs on.  The absolute
#: value is NOT gated (box speed varies); the PERF005 sanity bound only
#: rejects fractions that are non-positive or beyond the committed
#: ceiling — the signature of a broken clock or a garbage join, not of
#: a slow machine.
NOMINAL_FLOPS_PER_S = 1.0e12
NOMINAL_HBM_BYTES_PER_S = 100.0e9


class Ledger:
    """Per-program dispatch-time accumulator.

    One :class:`~singa_tpu.obs.events._Hist` per program key: exact
    count/total/min/max over every observation, nearest-rank p50/p99
    over the bounded ring (deterministic — same observation order,
    same summary).  Thread-safe: serve engines tick from worker
    threads (disagg Router), so :meth:`note` takes the ledger lock the
    same way the event layer's histogram registry does."""

    __slots__ = ("_hists", "_lock", "installed_at")

    def __init__(self):
        self._hists: Dict[str, _Hist] = {}
        self._lock = threading.Lock()
        #: ``perf_counter`` stamp of :func:`install` — the enclosing
        #: window's start, so ``window_s`` in the record payload is the
        #: ledger's own lifetime unless the caller measures a tighter one
        self.installed_at: Optional[float] = None

    def note(self, program: str, dur_s: float) -> None:
        """One dispatch of ``program`` took ``dur_s`` seconds."""
        with self._lock:
            h = self._hists.get(program)
            if h is None:
                h = self._hists[program] = _Hist()
            h.observe(float(dur_s))

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """``{program: {count, total_s, min_s, max_s, p50_s, p99_s}}``
        — count/total/min/max exact, percentiles from the retained
        ring (see ``_Hist.summary`` for the determinism contract)."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            items = list(self._hists.items())
        for program, h in items:
            s = h.summary()
            if s is None:
                continue
            out[program] = {"count": s["count"], "total_s": s["sum"],
                            "min_s": s["min"], "max_s": s["max"],
                            "p50_s": s["p50"], "p99_s": s["p99"]}
        return out

    def reset(self) -> None:
        """Drop every accumulated program (a bench isolating its
        measured window calls this, then re-stamps the window)."""
        with self._lock:
            self._hists.clear()
        self.installed_at = time.perf_counter()


#: the module-global the dispatch seams read ONCE per call — ``None``
#: (the default) means the seam costs a single global load and nothing
#: else: no clock read, no allocation (the overhead-honesty test pins
#: this with an allocation probe)
_LEDGER: Optional[Ledger] = None


def install(ledger: Optional[Ledger] = None) -> Ledger:
    """Install ``ledger`` (or a fresh one) as the process-wide
    attribution target and return it.  Re-installing replaces the
    previous ledger (the old one keeps its accumulated state — callers
    that snapshot after uninstall still see their window)."""
    global _LEDGER
    led = ledger if ledger is not None else Ledger()
    led.installed_at = time.perf_counter()
    _LEDGER = led
    return led


def uninstall() -> Optional[Ledger]:
    """Remove the installed ledger (returning it, so the caller can
    snapshot the closed window); the dispatch seams fall back to the
    zero-overhead path."""
    global _LEDGER
    led = _LEDGER
    _LEDGER = None
    return led


def get() -> Optional[Ledger]:
    """The installed ledger, or None."""
    return _LEDGER


def note(program: str, dur_s: float) -> None:
    """Module-level note: forwards to the installed ledger, no-op
    without one.  Instrumented seams should instead snapshot
    ``attr.get()`` BEFORE their ``perf_counter`` read so the off path
    never touches the clock — this helper is for call sites where a
    duration already exists for other reasons."""
    led = _LEDGER
    if led is not None:
        led.note(program, dur_s)


def _achieved(row: Dict[str, float], feat: Dict[str, Any]
              ) -> Dict[str, float]:
    """The measured-vs-modeled join for one program: achieved FLOP/s
    and HBM bytes/s from the mean dispatch time, and the
    achieved-roofline fraction — the analytic minimum time (compute or
    memory bound, whichever dominates at the nominal box) over the
    measured mean.  Pure arithmetic on the snapshot row and the
    feature row, so a frozen record re-derives bit-equal."""
    mean_s = row["total_s"] / row["count"]
    flops = float(feat.get("flops", 0) or 0)
    hbm = float(feat.get("hbm_bytes", 0) or 0)
    modeled_min_s = max(flops / NOMINAL_FLOPS_PER_S,
                        hbm / NOMINAL_HBM_BYTES_PER_S)
    return {
        "modeled_flops": flops,
        "modeled_hbm_bytes": hbm,
        "achieved_flops_per_s": flops / mean_s if mean_s > 0 else 0.0,
        "achieved_hbm_gbps": hbm / mean_s / 1e9 if mean_s > 0 else 0.0,
        "achieved_flops_frac": (modeled_min_s / mean_s
                                if mean_s > 0 else 0.0),
    }


def attribution_payload(snapshot: Dict[str, Dict[str, float]],
                        features: Dict[str, Dict[str, Any]],
                        window_s: float) -> Dict[str, Any]:
    """The ``perf_attr`` record payload (obs.schema): every snapshot
    program that has an analytic feature row, joined.

    Programs WITHOUT a feature row (an eval step, the eager grad-sync
    key) are dropped — the schema requires program keys to be a subset
    of the flagship set, and a program the cost model never lowered has
    no modeled side to reconcile; they stay visible in the live view
    (``python -m tools.obsq attr``).  ``attributed_s`` sums the
    *included* programs' totals against the caller's enclosing
    ``window_s``, so the completeness invariant (PERF002) reads
    directly off the record."""
    programs: Dict[str, Dict[str, float]] = {}
    attributed = 0.0
    for name in sorted(snapshot):
        if name not in features:
            continue
        row = dict(snapshot[name])
        row.update(_achieved(snapshot[name], features[name]))
        programs[name] = row
        attributed += snapshot[name]["total_s"]
    return {
        "window_s": float(window_s),
        "attributed_s": attributed,
        "attributed_frac": (attributed / window_s
                            if window_s > 0 else 0.0),
        "programs": programs,
    }
