"""Run-record schema (singa_tpu.obs): versioned field contracts for the
telemetry artifacts this repo commits.

Why this exists: round 5 lost its on-chip evidence because the record
files had no contract — a CPU smoke session silently overwrote the
on-chip `tpu_session.json`, and the README generator then crashed with a
raw ``KeyError: 'batch'`` against the record actually committed
(VERDICT.md).  Every consumer of a record now goes through
:func:`require`, so a missing field fails loudly with its *name* and the
context it was needed in, and :func:`validate_entry` checks whole
entries so a stale or truncated record is caught at write/lint time.

Three record shapes are covered:

* **v1 entries** — what :class:`singa_tpu.obs.record.RunRecord` stores:
  one JSON object per run, keyed by ``(run_id, platform, smoke)``, with
  ``schema_version`` stamped.  Strictly validated.
* **legacy session docs** — pre-v1 ``tpu_session.json`` (a bare
  ``{"stages": ..., "device": ...}`` object).  Structurally validated;
  grandfathered fields are not retro-required AT LINT TIME (the
  committed r4 record predates the schema and cannot be re-measured
  off-chip, so ``tools/record_check.py`` keeps CI green on it).
  Consumers are a different story: a tool that QUOTES a field still
  ``require()``s it and fails loudly — ``readme_perf_table.py``
  exiting 2 with "stage 'resnet50': missing required field 'batch'"
  against the r4 record is by design (the README table needs a fresh
  on-chip session; silently dropping the row would be the r5 silent-
  truncation failure mode again).
* **driver bench records** — ``BENCH_rNN.json`` /
  ``MULTICHIP_rNN.json`` written by the round driver.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

__all__ = ["SCHEMA_VERSION", "SchemaError", "require", "validate_entry",
           "validate_stage", "validate_session_doc", "validate_bench_doc",
           "validate_multichip_doc", "validate_serve_payload",
           "validate_serve_load_payload", "validate_train_run_payload",
           "validate_incident_payload", "validate_chaos_campaign_payload",
           "validate_hlo_audit_payload",
           "validate_autotune_sweep_payload", "validate_perf_attr_payload",
           "validate_wire_byte_fields", "validate_flight_ref",
           "validate_serve_tier_fields", "validate_spec_fields",
           "validate_serve_spill_fields", "validate_serve_arena_fields",
           "validate_serve_transport_fields", "entry_key"]

#: bump when entry fields change incompatibly; validators dispatch on it
SCHEMA_VERSION = 1

_KINDS = ("session", "bench", "serve_throughput", "serve_load",
          "train_run", "incident", "hlo_audit", "autotune_sweep",
          "perf_attr", "chaos_campaign")

#: required numeric payload fields of a serve_throughput entry — the
#: serving bench's headline quantities (tools/record_check.py lints
#: committed serving records against these alongside the training ones)
_SERVE_FIELDS = ("tokens_per_s", "speedup_vs_sequential", "ttft_p50_ms",
                 "ttft_p99_ms", "requests")

#: required numeric payload fields of a serve_load entry — what one
#: tools/loadgen.py open-loop traffic run commits: the offered load,
#: how much of it survived, the SLO percentiles, and the overload
#: outcomes (shed + rejected), so scheduler/paging changes are judged
#: on p99 TTFT and tokens/s under overload rather than on unit tests
_SERVE_LOAD_FIELDS = ("requests", "completed", "shed", "rejected",
                      "tokens_per_s", "ttft_p50_ms", "ttft_p99_ms")

#: the disaggregated-tier pool fields (tools/loadgen.py driving a
#: serve.disagg Router): how the tier was shaped (worker counts per
#: pool), how many KV handoffs crossed it, and the handoff p99 wait
#: (prefill-finish -> decode-inject, decode-capacity queueing
#: included).  OPTIONAL on serve_load payloads — a single-engine run
#: has no pools — but a record carrying ANY of them must carry ALL,
#: numeric (a ratio-sweep point whose worker counts went missing could
#: not support the independent-scaling claim the sweep exists to make)
_SERVE_TIER_FIELDS = ("prefill_workers", "decode_workers", "handoffs",
                      "handoff_p99_ms")

#: the speculative-decoding pair (ServeEngine(draft_model=, spec_k=) /
#: tools/loadgen.py --spec-k / bench.py --serve): the draft accept rate
#: and the delivered tokens per per-slot program dispatch (1.0 for a
#: plain engine by definition).  OPTIONAL on serve_load AND
#: serve_throughput payloads — but a record carrying EITHER must carry
#: BOTH, numeric (an accept rate with no dispatch-density evidence, or
#: vice versa, cannot support the tokens-per-dispatch claim
#: speculation exists to make)
_SPEC_FIELDS = ("accept_rate", "tokens_per_dispatch")

#: the KV spill-tier trio (ServeEngine(spill_blocks=) /
#: tools/loadgen.py --spill-blocks): evicted prefix blocks spilled to
#: host RAM, spilled blocks restored on prefix hits, and the cumulative
#: host-side restore wait.  OPTIONAL on serve_load payloads — a run
#: with no spill tier has nothing to report — but a record carrying ANY
#: of them must carry ALL, numeric (spill pressure with no restore
#: evidence, or hits with no wait cost, cannot support the
#: TTFT-on-re-hit claim the tier exists to make)
_SERVE_SPILL_FIELDS = ("spilled_blocks", "prefetch_hits",
                       "prefetch_wait_ms")

#: the multi-process transport trio (tools/loadgen.py --procs driving a
#: serve.net ProcRouter): KV bytes the handoff wire actually carried,
#: the p99 serialize+deserialize cost per handoff, and how many elastic
#: pool resizes the run performed.  OPTIONAL on serve_load payloads —
#: an in-process tier has no wire — but a record carrying ANY of them
#: must carry ALL, numeric (a multi-process tokens/s claim without its
#: wire-cost evidence cannot support the handoff-over-sockets story;
#: see docs/serving.md, "Multi-process serving")
_SERVE_TRANSPORT_FIELDS = ("handoff_wire_bytes", "handoff_ser_ms_p99",
                           "resizes")

#: the KV-arena memory-hierarchy compare (bench.py --serve
#: --arena-compare): peak measured concurrency of an f32 paged arena
#: and of an int8 QuantKV arena holding the SAME HBM byte budget (both
#: byte totals on the record), against the fixed-arena slot ceiling
#: that budget buys.  OPTIONAL on serve_throughput payloads — the
#: plain serving bench has no quantized arena — but a record carrying
#: ANY of the int8-side fields (``_SERVE_ARENA_TRIGGERS``) must carry
#: ALL FIVE, numeric: a quantized peak without the equal-bytes
#: evidence (or without the f32 peak it beats) cannot support the
#: concurrency-per-byte claim the int8 tier exists to make (see
#: docs/serving.md, "KV memory hierarchy").  The fixed/paged pair
#: alone stays valid — that is the PR 6 paged-vs-fixed compare, which
#: predates the int8 tier.
_SERVE_ARENA_FIELDS = ("fixed_max_concurrent", "paged_peak_concurrent",
                       "quant_peak_concurrent", "arena_bytes_f32",
                       "arena_bytes_int8")
_SERVE_ARENA_TRIGGERS = ("quant_peak_concurrent", "arena_bytes_f32",
                         "arena_bytes_int8")

#: required numeric payload fields of a train_run entry — what the
#: training orchestrator (singa_tpu.train.TrainRunner) commits for
#: every run: how far it got, how long it took, how many checkpoints
#: it landed, and where it resumed from (-1 = fresh start)
_TRAIN_RUN_FIELDS = ("steps", "wall_s", "ckpt_count", "resumed_from")

#: the gradient-sync wire-byte pair (DistOpt compression="int8_ring" /
#: bench.py --quantized): per-participant bytes the wire actually
#: carried vs what f32 collectives would have cost.  OPTIONAL on
#: train_run and bench payloads — but a record carrying either must
#: carry BOTH as numerics (a lone "compressed" number with no f32
#: reference cannot support a reduction claim), linted exactly like the
#: required fields
_WIRE_BYTE_FIELDS = ("wire_bytes_compressed", "wire_bytes_f32_equiv")

#: required numeric payload fields of an hlo_audit entry — one run of
#: the compiled-program invariant gates (tools/lint/hlo.py structure +
#: tools/lint/cost.py cost): how many flagship programs were lowered,
#: how many findings drifted, the aggregate structural quantities
#: (fusions, collectives, while loops) AND the analytic cost numerics
#: (total flops / HBM traffic / collective wire bytes, max per-program
#: peak live bytes) whose trajectory the drift history tracks next to
#: the perf records — the bench trajectory accumulates cost history
#: for the record-driven autotuner (ROADMAP item 4).  The cost fields
#: joined the required set WITHOUT a SCHEMA_VERSION bump because no
#: committed store anywhere carried an hlo_audit entry yet (verified at
#: the time of the change) — were one to exist, this would need the
#: version dance instead
_HLO_AUDIT_FIELDS = ("programs", "drifted", "fusions", "collectives",
                     "while_loops", "flops", "hbm_bytes", "peak_bytes",
                     "wire_bytes")

#: required payload fields of an autotune_sweep entry — one measured
#: point (point >= 0) or the fit summary (point == -1) of a knob sweep
#: (singa_tpu.autotune.sweep): which domain/model the sweep tuned,
#: which sweep group the point belongs to, what was measured.  The
#: ``knobs`` dict is structurally validated here (non-empty, numeric
#: values); knob-NAME reality against the registry is the dynamic
#: audit's job (``python -m tools.lint --records`` imports
#: singa_tpu.autotune.knobs), keeping this module free of an
#: autotune import cycle.  A fit record must carry ``loo_rel_err`` —
#: a committed best config without its trustworthiness number is a
#: vibe, which is exactly what ISSUE 14 bans
_AUTOTUNE_STR_FIELDS = ("domain", "model", "objective_name", "sweep_id")
_AUTOTUNE_NUM_FIELDS = ("objective", "point")
_AUTOTUNE_DOMAINS = ("train", "serve")

#: required numeric payload fields of a perf_attr entry (ISSUE 16) —
#: the enclosing measured window and how much of it the ledger
#: attributed to programs; ``programs`` itself is validated
#: per-program (``_PERF_ATTR_PROGRAM_FIELDS``)
_PERF_ATTR_FIELDS = ("window_s", "attributed_s", "attributed_frac")

#: required numerics per program of a perf_attr payload: the exact
#: dispatch count/total, the ring percentiles, and the
#: achieved-roofline fraction joined from the analytic cost model
#: (singa_tpu.obs.attr.attribution_payload) — a program row missing
#: its achieved fraction is a clock with no model to reconcile
#: against, which is the gap this record kind exists to close
_PERF_ATTR_PROGRAM_FIELDS = ("count", "total_s", "p50_s", "p99_s",
                             "achieved_flops_frac")

#: required string payload fields of an incident entry — one fired
#: fault or recovery action (singa_tpu.faults / ServeEngine resilience):
#: which seam (site), what happened there (fault), what the system did
#: about it (outcome); ``ref`` (step or request id) and numeric
#: ``retries`` are validated separately in validate_incident_payload
_INCIDENT_STR_FIELDS = ("site", "fault", "outcome")

#: required numeric payload fields of a chaos_campaign entry — one
#: seeded chaos campaign against a live multi-process tier
#: (tools/chaosd.py, ISSUE 19): the seed that makes the event sequence
#: reproducible, the event counts by kind (kills / hangs / injected
#: fault plans / resizes), what the self-healing layer did about them
#: (respawns adopted, requests rerouted, worker deaths declared), and
#: the traffic served across it all.  ``bitwise_ok`` — every stream
#: matched its single-engine reference — is validated separately as a
#: STRICT bool (the campaign's headline claim must never lint as a
#: numeric measurement, nor a number as the claim)
_CHAOS_CAMPAIGN_FIELDS = ("seed", "events", "kills", "hangs",
                          "fault_plans", "resizes", "respawns",
                          "reroutes", "worker_deaths", "requests",
                          "completed")


class SchemaError(ValueError):
    """A record failed validation.  ``field`` names the offending field
    so consumers/CI report *what* is missing, never a raw KeyError."""

    def __init__(self, message: str, field: Optional[str] = None):
        super().__init__(message)
        self.field = field


def require(mapping: Any, field: str, ctx: str = "record") -> Any:
    """Named-field access: ``mapping[field]`` that raises
    :class:`SchemaError` ("<ctx>: missing required field '<field>'")
    instead of KeyError, and rejects non-dict containers loudly."""
    if not isinstance(mapping, dict):
        raise SchemaError(f"{ctx}: expected an object with field "
                          f"{field!r}, got {type(mapping).__name__}",
                          field=field)
    if field not in mapping:
        raise SchemaError(f"{ctx}: missing required field {field!r} "
                          f"(present: {sorted(mapping)})", field=field)
    return mapping[field]


def _expect(cond: bool, msg: str, field: Optional[str] = None) -> None:
    if not cond:
        raise SchemaError(msg, field=field)


def entry_key(entry: Dict[str, Any]) -> Tuple[str, str, bool]:
    """The store key: ``(run_id, platform, smoke)``."""
    return (str(require(entry, "run_id", "entry")),
            str(require(entry, "platform", "entry")),
            bool(require(entry, "smoke", "entry")))


def validate_stage(name: str, stage: Any, ctx: str = "record") -> None:
    """One session stage: exactly one of ``skipped``, ``ok: true`` (with
    optional ``s``/``result``), or ``ok: false`` + ``error``."""
    c = f"{ctx}: stage {name!r}"
    _expect(isinstance(stage, dict),
            f"{c}: expected an object, got {type(stage).__name__}")
    if stage.get("skipped"):
        return
    ok = require(stage, "ok", c)
    _expect(isinstance(ok, bool), f"{c}: 'ok' must be a bool, got {ok!r}",
            field="ok")
    if not ok:
        err = require(stage, "error", c)
        _expect(isinstance(err, str) and err,
                f"{c}: failed stage needs a non-empty 'error' string",
                field="error")


def validate_entry(entry: Any, ctx: str = "entry") -> None:
    """Strict validation of a v1 store entry."""
    _expect(isinstance(entry, dict),
            f"{ctx}: expected an object, got {type(entry).__name__}")
    ver = require(entry, "schema_version", ctx)
    _expect(ver == SCHEMA_VERSION,
            f"{ctx}: schema_version {ver!r} is not the supported "
            f"{SCHEMA_VERSION}", field="schema_version")
    run_id = require(entry, "run_id", ctx)
    _expect(isinstance(run_id, str) and run_id,
            f"{ctx}: 'run_id' must be a non-empty string, got {run_id!r}",
            field="run_id")
    kind = require(entry, "kind", ctx)
    _expect(kind in _KINDS,
            f"{ctx}: 'kind' must be one of {_KINDS}, got {kind!r}",
            field="kind")
    platform = require(entry, "platform", ctx)
    _expect(isinstance(platform, str) and platform,
            f"{ctx}: 'platform' must be a non-empty string, got "
            f"{platform!r}", field="platform")
    smoke = require(entry, "smoke", ctx)
    _expect(isinstance(smoke, bool),
            f"{ctx}: 'smoke' must be a bool, got {smoke!r}", field="smoke")
    device = require(entry, "device", ctx)
    _expect(isinstance(device, str),
            f"{ctx}: 'device' must be a string, got {device!r}",
            field="device")
    created = require(entry, "created_at", ctx)
    _expect(isinstance(created, (int, float)) and not isinstance(
        created, bool),
            f"{ctx}: 'created_at' must be a unix timestamp, got "
            f"{created!r}", field="created_at")
    if kind == "session":
        stages = require(entry, "stages", ctx)
        _expect(isinstance(stages, dict),
                f"{ctx}: 'stages' must be an object, got "
                f"{type(stages).__name__}", field="stages")
        for sname, stage in stages.items():
            validate_stage(sname, stage, ctx)
    else:
        payload = require(entry, "payload", ctx)
        _expect(isinstance(payload, dict),
                f"{ctx}: 'payload' must be an object, got "
                f"{type(payload).__name__}", field="payload")
        if kind == "serve_throughput":
            validate_serve_payload(payload, f"{ctx}: serve payload")
        elif kind == "serve_load":
            validate_serve_load_payload(payload,
                                        f"{ctx}: serve_load payload")
        elif kind == "train_run":
            validate_train_run_payload(payload, f"{ctx}: train_run payload")
        elif kind == "incident":
            validate_incident_payload(payload, f"{ctx}: incident payload")
        elif kind == "hlo_audit":
            validate_hlo_audit_payload(payload, f"{ctx}: hlo_audit payload")
        elif kind == "autotune_sweep":
            validate_autotune_sweep_payload(
                payload, f"{ctx}: autotune_sweep payload")
        elif kind == "perf_attr":
            validate_perf_attr_payload(payload,
                                       f"{ctx}: perf_attr payload")
        elif kind == "chaos_campaign":
            validate_chaos_campaign_payload(
                payload, f"{ctx}: chaos_campaign payload")
        elif kind == "bench":
            validate_wire_byte_fields(payload, f"{ctx}: bench payload")


def _require_numeric_fields(payload: Any, fields: Tuple[str, ...],
                            ctx: str) -> None:
    """One definition of "a numeric payload field" for every kind that
    carries headline quantities (bools are NOT numbers here — a record
    field accidentally set to True must not lint as a measurement)."""
    for f in fields:
        v = require(payload, f, ctx)
        _expect(isinstance(v, (int, float)) and not isinstance(v, bool),
                f"{ctx}: {f!r} must be numeric, got {v!r}", field=f)


def validate_serve_payload(payload: Any, ctx: str = "serve payload") -> None:
    """The serving bench's headline quantities: every field in
    ``_SERVE_FIELDS`` present and numeric (a serving record with a
    missing TTFT percentile is the r5 silent-truncation failure mode
    wearing a new hat).  The optional speculative-decoding pair
    (``_SPEC_FIELDS``) and the optional KV-arena compare group
    (``_SERVE_ARENA_FIELDS``) are linted whenever any of them
    appear."""
    _require_numeric_fields(payload, _SERVE_FIELDS, ctx)
    validate_spec_fields(payload, ctx)
    validate_serve_arena_fields(payload, ctx)


def validate_serve_arena_fields(payload: Any,
                                ctx: str = "payload") -> None:
    """The optional KV-arena memory-hierarchy compare: a payload
    carrying ANY of the int8-side fields (``_SERVE_ARENA_TRIGGERS``)
    must carry all five of ``_SERVE_ARENA_FIELDS``, numeric — a
    quantized concurrency peak stripped of its equal-bytes evidence
    (or of the f32 peak it is measured against) cannot support the
    concurrency-per-byte claim the int8 KV tier exists to make.  The
    PR 6 fixed/paged pair on its own is NOT a trigger."""
    if not isinstance(payload, dict):
        return
    if any(f in payload for f in _SERVE_ARENA_TRIGGERS):
        _require_numeric_fields(payload, _SERVE_ARENA_FIELDS, ctx)


def validate_serve_load_payload(payload: Any,
                                ctx: str = "serve_load payload") -> None:
    """One loadgen traffic run's outcome: every field in
    ``_SERVE_LOAD_FIELDS`` present and numeric — an overload run whose
    shed/rejected counts went missing would let 'survived the chaos
    run' masquerade as 'served every request'.  The optional
    disaggregated-tier pool fields (``_SERVE_TIER_FIELDS``), the
    optional speculative-decoding pair (``_SPEC_FIELDS``), the optional
    KV spill-tier trio (``_SERVE_SPILL_FIELDS``) and the optional
    multi-process transport trio (``_SERVE_TRANSPORT_FIELDS``) are
    linted whenever any of them appear."""
    _require_numeric_fields(payload, _SERVE_LOAD_FIELDS, ctx)
    validate_serve_tier_fields(payload, ctx)
    validate_spec_fields(payload, ctx)
    validate_serve_spill_fields(payload, ctx)
    validate_serve_transport_fields(payload, ctx)


def validate_serve_spill_fields(payload: Any,
                                ctx: str = "payload") -> None:
    """The optional KV spill-tier trio: a payload carrying ANY of
    ``_SERVE_SPILL_FIELDS`` must carry all three, numeric — spill
    pressure without restore evidence (or hits without their wait
    cost) cannot support the TTFT-on-re-hit claim the spill tier
    exists to make (see docs/serving.md, "KV memory hierarchy")."""
    if not isinstance(payload, dict):
        return
    if any(f in payload for f in _SERVE_SPILL_FIELDS):
        _require_numeric_fields(payload, _SERVE_SPILL_FIELDS, ctx)


def validate_serve_transport_fields(payload: Any,
                                    ctx: str = "payload") -> None:
    """The optional multi-process transport trio: a payload carrying
    ANY of ``_SERVE_TRANSPORT_FIELDS`` must carry all three, numeric —
    a multi-process throughput point whose wire-byte or serialization
    evidence went missing cannot support the KV-handoff-over-sockets
    claim the transport exists to make (see docs/serving.md,
    "Multi-process serving")."""
    if not isinstance(payload, dict):
        return
    if any(f in payload for f in _SERVE_TRANSPORT_FIELDS):
        _require_numeric_fields(payload, _SERVE_TRANSPORT_FIELDS, ctx)


def validate_spec_fields(payload: Any, ctx: str = "payload") -> None:
    """The optional speculative-decoding pair: a payload carrying
    EITHER of ``_SPEC_FIELDS`` must carry both, numeric — an accept
    rate without its tokens-per-dispatch consequence (or vice versa)
    cannot support the dispatch-density claim speculation exists to
    make (see docs/serving.md, "Speculative decoding")."""
    if not isinstance(payload, dict):
        return
    if any(f in payload for f in _SPEC_FIELDS):
        _require_numeric_fields(payload, _SPEC_FIELDS, ctx)


def validate_serve_tier_fields(payload: Any, ctx: str = "payload") -> None:
    """The optional disaggregated-tier pool quartet: a payload carrying
    ANY of ``_SERVE_TIER_FIELDS`` must carry all four, numeric — a
    worker-ratio point without its handoff evidence (or vice versa)
    cannot support the independent-scaling claim (see
    docs/serving.md, "Disaggregated tier")."""
    if not isinstance(payload, dict):
        return
    if any(f in payload for f in _SERVE_TIER_FIELDS):
        _require_numeric_fields(payload, _SERVE_TIER_FIELDS, ctx)


def validate_wire_byte_fields(payload: Any, ctx: str = "payload") -> None:
    """The optional gradient-sync wire-byte pair: a payload carrying
    EITHER of ``_WIRE_BYTE_FIELDS`` must carry both, numeric — a
    compressed byte count without its f32-equivalent reference (or vice
    versa) cannot support the reduction claim the pair exists to make."""
    if not isinstance(payload, dict):
        return
    if any(f in payload for f in _WIRE_BYTE_FIELDS):
        _require_numeric_fields(payload, _WIRE_BYTE_FIELDS, ctx)


def validate_flight_ref(payload: Any, ctx: str = "payload") -> None:
    """The optional flight-recorder dump reference (ISSUE 11): when an
    incident/train_run payload carries ``flight_ref`` it must be a
    non-empty string — the dump path relative to the record store's
    directory.  A ref that exists but is empty/mistyped would point the
    postmortem at nothing; ``python -m tools.lint --records``
    additionally checks the referenced file exists and parses."""
    if not isinstance(payload, dict) or "flight_ref" not in payload:
        return
    v = payload["flight_ref"]
    _expect(isinstance(v, str) and bool(v),
            f"{ctx}: 'flight_ref' must be a non-empty string (dump path "
            f"relative to the record store), got {v!r}",
            field="flight_ref")


def validate_train_run_payload(payload: Any,
                               ctx: str = "train_run payload") -> None:
    """The orchestrator's run outcome: every field in
    ``_TRAIN_RUN_FIELDS`` present and numeric, so a run that aborted
    mid-write can never masquerade as a complete record; the optional
    wire-byte pair (``wire_bytes_compressed`` / ``wire_bytes_f32_equiv``,
    quantized-sync runs) and the optional ``flight_ref`` (fatal/hung
    runs dump their flight ring) are linted whenever they appear."""
    _require_numeric_fields(payload, _TRAIN_RUN_FIELDS, ctx)
    validate_wire_byte_fields(payload, ctx)
    validate_flight_ref(payload, ctx)


def validate_hlo_audit_payload(payload: Any,
                               ctx: str = "hlo_audit payload") -> None:
    """One compiled-program audit run: every field in
    ``_HLO_AUDIT_FIELDS`` present and numeric — a drift-history entry
    whose counts went missing could not answer 'when did the fusion
    count change' later, which is the entire point of keeping it."""
    _require_numeric_fields(payload, _HLO_AUDIT_FIELDS, ctx)


def validate_autotune_sweep_payload(payload: Any,
                                    ctx: str = "autotune_sweep payload"
                                    ) -> None:
    """One autotune sweep point or fit summary: the string quartet
    (``domain``/``model``/``objective_name``/``sweep_id``) non-empty
    with a registered domain, ``objective``/``point`` numeric, and a
    non-empty all-numeric ``knobs`` object.  A fit record (``point ==
    -1``) must additionally carry its numeric ``loo_rel_err`` — the
    predictor's committed trustworthiness; a measurement point
    carrying one by accident is equally rejected (it would read as a
    calibration claim no fit produced)."""
    for f in _AUTOTUNE_STR_FIELDS:
        v = require(payload, f, ctx)
        _expect(isinstance(v, str) and v,
                f"{ctx}: {f!r} must be a non-empty string, got {v!r}",
                field=f)
    _expect(payload["domain"] in _AUTOTUNE_DOMAINS,
            f"{ctx}: 'domain' must be one of {_AUTOTUNE_DOMAINS}, got "
            f"{payload['domain']!r}", field="domain")
    _require_numeric_fields(payload, _AUTOTUNE_NUM_FIELDS, ctx)
    knobs = require(payload, "knobs", ctx)
    _expect(isinstance(knobs, dict) and bool(knobs),
            f"{ctx}: 'knobs' must be a non-empty object, got {knobs!r}",
            field="knobs")
    for name, value in knobs.items():
        _expect(isinstance(value, (int, float))
                and not isinstance(value, bool),
                f"{ctx}: knob {name!r} must be numeric, got {value!r}",
                field="knobs")
    features = payload.get("features")
    if features is not None:
        _expect(isinstance(features, dict),
                f"{ctx}: 'features' must be an object, got "
                f"{features!r}", field="features")
        for name, value in features.items():
            _expect(isinstance(value, (int, float))
                    and not isinstance(value, bool),
                    f"{ctx}: feature {name!r} must be numeric, got "
                    f"{value!r}", field="features")
    if int(payload["point"]) == -1:
        _require_numeric_fields(payload, ("loo_rel_err",), ctx)
    else:
        _expect("loo_rel_err" not in payload,
                f"{ctx}: 'loo_rel_err' belongs to the fit record "
                f"(point == -1), not a measurement point",
                field="loo_rel_err")


def validate_perf_attr_payload(payload: Any,
                               ctx: str = "perf_attr payload") -> None:
    """One runtime-attribution window (ISSUE 16): the measured window
    and attributed totals numeric, and a non-empty ``programs`` object
    whose every row carries ``_PERF_ATTR_PROGRAM_FIELDS`` numeric — a
    ledger row whose count or achieved fraction went missing could not
    support the measured-vs-modeled reconciliation later, which is the
    record's entire reason to exist.  Program-key REALITY (subset of
    the flagship set the cost model lowers) is the dynamic audit's job
    (``python -m tools.lint --records`` imports tools.lint.hlo),
    keeping this module free of a tools import."""
    _require_numeric_fields(payload, _PERF_ATTR_FIELDS, ctx)
    programs = require(payload, "programs", ctx)
    _expect(isinstance(programs, dict) and bool(programs),
            f"{ctx}: 'programs' must be a non-empty object, got "
            f"{programs!r}", field="programs")
    for name, row in programs.items():
        _expect(isinstance(name, str) and name,
                f"{ctx}: program keys must be non-empty strings, got "
                f"{name!r}", field="programs")
        _require_numeric_fields(row, _PERF_ATTR_PROGRAM_FIELDS,
                                f"{ctx}: program {name!r}")


def validate_incident_payload(payload: Any,
                              ctx: str = "incident payload") -> None:
    """One fired fault / recovery action in the durable store: ``site``
    (injection-site or subsystem seam), ``fault`` (what fired), and
    ``outcome`` (``retried`` / ``quarantined`` / ``recovered`` /
    ``unrecoverable`` / ...) as non-empty strings; ``ref`` — the step or
    request id the incident is about (string or number); ``retries`` —
    how many attempts were burned, numeric, so postmortems can
    aggregate retry pressure without re-parsing prose."""
    for f in _INCIDENT_STR_FIELDS:
        v = require(payload, f, ctx)
        _expect(isinstance(v, str) and v,
                f"{ctx}: {f!r} must be a non-empty string, got {v!r}",
                field=f)
    ref = require(payload, "ref", ctx)
    _expect(isinstance(ref, (str, int, float)) and not isinstance(ref, bool),
            f"{ctx}: 'ref' must be a step/request id (string or number), "
            f"got {ref!r}", field="ref")
    _require_numeric_fields(payload, ("retries",), ctx)
    validate_flight_ref(payload, ctx)


def validate_chaos_campaign_payload(
        payload: Any, ctx: str = "chaos_campaign payload") -> None:
    """One seeded chaos campaign's invariant summary (tools/chaosd.py):
    every count in ``_CHAOS_CAMPAIGN_FIELDS`` present and numeric, plus
    ``bitwise_ok`` as a STRICT bool — the campaign's headline claim
    ("every stream across every kill/hang/resize matched its
    single-engine reference bit for bit") must be a verdict, not a
    number that happens to be truthy.  A campaign record whose seed or
    event counts went missing could not be re-derived and re-asserted
    from the frozen record, which is the determinism contract the
    driver exists to honor (docs/robustness.md, "Self-healing")."""
    _require_numeric_fields(payload, _CHAOS_CAMPAIGN_FIELDS, ctx)
    ok = require(payload, "bitwise_ok", ctx)
    _expect(isinstance(ok, bool),
            f"{ctx}: 'bitwise_ok' must be a bool, got {ok!r}",
            field="bitwise_ok")
    validate_flight_ref(payload, ctx)


def validate_session_doc(doc: Any, ctx: str = "session record") -> None:
    """A session document: a v1 entry (when ``schema_version`` is
    stamped) or a legacy ``tpu_session.json`` (structural check only —
    grandfathered records cannot be re-measured without a chip)."""
    _expect(isinstance(doc, dict),
            f"{ctx}: expected an object, got {type(doc).__name__}")
    if "schema_version" in doc:
        validate_entry(doc, ctx)
        return
    stages = require(doc, "stages", ctx)
    _expect(isinstance(stages, dict),
            f"{ctx}: 'stages' must be an object, got "
            f"{type(stages).__name__}", field="stages")
    for sname, stage in stages.items():
        validate_stage(sname, stage, ctx)


def validate_bench_doc(doc: Any, ctx: str = "bench record") -> None:
    """A driver ``BENCH_rNN.json``: run metadata + the parsed headline.

    ``parsed`` may be null — that honestly records a round whose
    headline never made it into the driver's tail capture (r01/r03).
    When present it must be a complete numeric headline."""
    _expect(isinstance(doc, dict),
            f"{ctx}: expected an object, got {type(doc).__name__}")
    for f in ("n", "cmd", "rc", "tail"):
        require(doc, f, ctx)
    parsed = require(doc, "parsed", ctx)
    if parsed is None:
        return
    c = f"{ctx}: 'parsed' headline"
    for f in ("metric", "value", "unit", "vs_baseline"):
        require(parsed, f, c)
    val = parsed["value"]
    _expect(isinstance(val, (int, float)) and not isinstance(val, bool),
            f"{c}: 'value' must be numeric, got {val!r}", field="value")


def validate_multichip_doc(doc: Any, ctx: str = "multichip record") -> None:
    """A driver ``MULTICHIP_rNN.json`` smoke result."""
    _expect(isinstance(doc, dict),
            f"{ctx}: expected an object, got {type(doc).__name__}")
    for f in ("n_devices", "ok", "rc"):
        require(doc, f, ctx)


def collect_errors(validator, doc, ctx: str) -> List[str]:
    """Run a validator, returning [] or the error messages (never raises
    — for lint-style reporting over many files)."""
    try:
        validator(doc, ctx)
        return []
    except SchemaError as e:
        return [str(e)]
