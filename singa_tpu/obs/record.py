"""RunRecord — a durable, schema-versioned, append-only run-record store.

One JSONL file; each line is one immutable v1 entry (see
``singa_tpu.obs.schema``) keyed by ``(run_id, platform, smoke)``.  The
invariants this class enforces are exactly the ones whose absence lost
the round-5 on-chip evidence:

* **append-only** — writing never rewrites other runs' lines: existing
  lines are carried to the new file *byte-for-byte*.  The only in-place
  operation allowed is a run superseding ITS OWN entry (same full key),
  which is how a session persists incrementally after every stage.
* **smoke can never clobber chip** — the key includes ``smoke``, so a
  smoke entry structurally cannot replace an on-chip line; and
  :meth:`latest` never returns a smoke entry unless the caller asked
  for smoke explicitly, so smoke runs can't *shadow* on-chip records
  for consumers either.
* **atomic durability** — every write goes to a temp file in the same
  directory, is fsync'ed, then ``os.replace``d over the store, so a
  crash mid-write leaves the previous store intact, never a truncated
  one.
* **fail loudly** — entries are validated on the way in and on the way
  out; a malformed line names its line number and field instead of
  surfacing as a KeyError four rounds later.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import time
from typing import Any, Dict, List, Optional

from . import schema

__all__ = ["RunRecord", "new_entry", "new_run_id", "is_onchip_session_doc",
           "DEFAULT_STORE"]

#: store location relative to a repo root
DEFAULT_STORE = os.path.join("runs", "records.jsonl")


#: per-process uniquifier: wallclock has SECOND resolution, so two ids
#: minted by the same process in the same second (a loadgen sweep whose
#: points finish in under a second, the --spec-compare pair) would
#: collide — and the store treats an equal (run_id, platform, smoke)
#: key as self-supersede, silently replacing the earlier entry
_RUN_SEQ = itertools.count()


def new_run_id(prefix: str = "run") -> str:
    """Collision-resistant id: wallclock + pid + per-process sequence
    (the sequence is what makes two same-second ids from one process
    distinct — see ``_RUN_SEQ``)."""
    return (f"{prefix}-{time.strftime('%Y%m%d-%H%M%S')}-{os.getpid()}"
            f"-{next(_RUN_SEQ)}")


def new_entry(kind: str, platform: str, smoke: bool, device: str,
              run_id: Optional[str] = None, *,
              stages: Optional[Dict[str, Any]] = None,
              payload: Optional[Dict[str, Any]] = None,
              extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Assemble (and validate) a v1 entry."""
    entry: Dict[str, Any] = {
        "schema_version": schema.SCHEMA_VERSION,
        "run_id": run_id or new_run_id(kind),
        "kind": kind,
        "platform": platform,
        "smoke": bool(smoke),
        "device": device,
        "created_at": time.time(),  # singalint: disable=SGL005 created_at is a cross-host-correlatable timestamp in the durable record, not a duration
    }
    if kind == "session":
        entry["stages"] = stages if stages is not None else {}
    else:
        entry["payload"] = payload if payload is not None else {}
    if extra:
        entry.update(extra)
    schema.validate_entry(entry)
    return entry


def _dumps(entry: Dict[str, Any]) -> str:
    return json.dumps(entry, sort_keys=True, separators=(",", ":"))


def _atomic_write(path: str, text: str) -> None:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, f".{os.path.basename(path)}.tmp.{os.getpid()}")
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


@contextlib.contextmanager
def _store_lock(path: str):
    """Exclusive advisory lock serializing read-modify-rename cycles:
    concurrent appenders (bench.py vs a session's incremental _finish)
    must not lose each other's lines.  Sidecar lock file, because the
    store itself is replaced by rename.  Falls back to unlocked on
    platforms without fcntl."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    try:
        import fcntl
    except ImportError:  # pragma: no cover - non-posix
        yield
        return
    with open(os.path.join(d, f".{os.path.basename(path)}.lock"), "w") as lf:
        fcntl.flock(lf.fileno(), fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(lf.fileno(), fcntl.LOCK_UN)


class RunRecord:
    """The append-only store over one JSONL file."""

    def __init__(self, path: str):
        self.path = path

    # -- reading ----------------------------------------------------------
    def raw_lines(self) -> List[str]:
        """The file's lines verbatim (no trailing newlines), [] when the
        store doesn't exist yet."""
        if not os.path.exists(self.path):
            return []
        with open(self.path) as f:
            return [ln for ln in f.read().splitlines() if ln.strip()]

    def entries(self) -> List[Dict[str, Any]]:
        """All entries in file order.  Malformed lines raise SchemaError
        naming the line number."""
        out = []
        for i, ln in enumerate(self.raw_lines(), 1):
            try:
                e = json.loads(ln)
            except json.JSONDecodeError as exc:
                raise schema.SchemaError(
                    f"{self.path}:{i}: not valid JSON ({exc.msg})") from exc
            schema.validate_entry(e, ctx=f"{self.path}:{i}")
            out.append(e)
        return out

    def validate(self) -> List[str]:
        """Lint the whole store: every line parses + validates, and no
        two lines share a key.  Returns error strings ([] when clean)."""
        errors: List[str] = []
        seen: Dict[tuple, int] = {}
        for i, ln in enumerate(self.raw_lines(), 1):
            ctx = f"{self.path}:{i}"
            try:
                e = json.loads(ln)
            except json.JSONDecodeError as exc:
                errors.append(f"{ctx}: not valid JSON ({exc.msg})")
                continue
            try:
                schema.validate_entry(e, ctx=ctx)
                key = schema.entry_key(e)
            except schema.SchemaError as exc:
                errors.append(str(exc))
                continue
            if key in seen:
                errors.append(f"{ctx}: duplicate key {key} "
                              f"(first at line {seen[key]})")
            else:
                seen[key] = i
        return errors

    def latest(self, kind: Optional[str] = None,
               platform: Optional[str] = None,
               smoke: bool = False) -> Optional[Dict[str, Any]]:
        """Newest matching entry, or None.

        Smoke entries are returned ONLY when ``smoke=True`` was asked
        for — a smoke run can never shadow an on-chip record."""
        best = None
        for e in self.entries():
            if bool(e["smoke"]) != bool(smoke):
                continue
            if kind is not None and e["kind"] != kind:
                continue
            if platform is not None and e["platform"] != platform:
                continue
            if best is None or e["created_at"] >= best["created_at"]:
                best = e
        return best

    # -- writing ----------------------------------------------------------
    def append(self, entry: Dict[str, Any]) -> None:
        """Validate + durably append ``entry``.

        If a line with the SAME full key ``(run_id, platform, smoke)``
        exists, it is superseded in place (a run checkpointing itself);
        every other line is preserved byte-for-byte.  Keys differing in
        any component — including ``smoke`` — always append a new line,
        so a smoke entry structurally cannot overwrite an on-chip one.

        The read-modify-rename cycle runs under an exclusive file lock
        so concurrent appenders cannot lose each other's lines."""
        schema.validate_entry(entry)
        key = schema.entry_key(entry)
        with _store_lock(self.path):
            lines = self.raw_lines()
            replaced = False
            for i, ln in enumerate(lines):
                try:
                    existing_key = schema.entry_key(json.loads(ln))
                except (json.JSONDecodeError, schema.SchemaError) as exc:
                    raise schema.SchemaError(
                        f"{self.path}:{i + 1}: refusing to append over a "
                        f"corrupt store line ({exc}); fix or quarantine "
                        f"the store first") from exc
                if existing_key == key:
                    lines[i] = _dumps(entry)
                    replaced = True
                    break
            if not replaced:
                lines.append(_dumps(entry))
            _atomic_write(self.path, "\n".join(lines) + "\n")


def is_onchip_session_doc(doc: Any) -> bool:
    """Heuristic for legacy (pre-schema) session documents: does this
    look like an on-chip record that must be protected from overwrite?

    v1 entries answer from their own fields; legacy docs infer from the
    probe stage's detected platform and the recorded device kind."""
    if not isinstance(doc, dict):
        return False
    if "schema_version" in doc:
        return (not doc.get("smoke", False)
                and str(doc.get("platform", "")).lower() != "cpu")
    stages = doc.get("stages")
    if isinstance(stages, dict):
        probe = stages.get("probe")
        if isinstance(probe, dict):
            platform = probe.get("result")
            if isinstance(platform, str):
                return platform.lower() != "cpu"
    device = doc.get("device")
    if isinstance(device, str) and device:
        return "cpu" not in device.lower()
    return False
