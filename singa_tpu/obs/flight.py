"""Flight recorder — a bounded in-memory ring of recent structured
events, dumped to disk only when something goes wrong (ISSUE 11).

The JSONL event sink answers "what happened" when someone thought to
turn it on; incidents do not wait for that.  A :class:`FlightRecorder`
is a fixed-capacity deque of the last N event dicts that costs one
dict build + one append per note, does ZERO file I/O on the hot path,
and is active even when ``SINGA_OBS`` is unset — so when a fault fires,
a request is quarantined, recovery runs, or ``TrainRunner`` takes the
fatal path, the owning component can :meth:`~FlightRecorder.dump` the
ring atomically to ``<record dir>/incidents/<ts>-<site>.jsonl`` and
reference it from the durable ``incident``/``train_run`` record
(``flight_ref``), giving the postmortem the engine's last-N timeline
instead of just "something happened".

Design points:

* **per-component rings** — ``ServeEngine`` and ``TrainRunner`` each
  own a recorder (like ``ServeMetrics``): two engines in one process
  never interleave ring contents, and no global state leaks across
  tests.
* **trace-stamped** — every note records the active
  :mod:`singa_tpu.obs.trace` id, so a dump slices cleanly per request.
* **registered dump sites** — ``dump()`` refuses a site name that is
  not a registered fault site (:data:`singa_tpu.faults.sites.SITES`)
  or incident site (:data:`~singa_tpu.faults.sites.INCIDENT_SITES`);
  the static half is singalint rule SGL009 (a typo'd literal site can
  never silently never-dump).
* **fault fires are broadcast** — :func:`singa_tpu.faults.fire` calls
  :func:`broadcast` for every *fired* fault (never per guarded call),
  so each live ring carries the injected-fault line in its timeline.
  Registration is a WeakSet: a garbage-collected engine's ring drops
  out on its own.
* **dumps are gated by a record store** — components only dump when
  they have a ``record_store`` to reference the file from; the
  no-sink/no-store path performs zero file writes (asserted in
  tests/test_faults.py).
"""

from __future__ import annotations

import itertools
import os
import threading
import time
import weakref
from collections import deque
from typing import Any, Dict, List, Optional

from . import trace

__all__ = ["FlightRecorder", "register", "broadcast", "dump_for_store",
           "DEFAULT_CAPACITY"]

DEFAULT_CAPACITY = 256

#: live rings that want fault-fire notes; weak so a dead engine's ring
#: is dropped by the collector, not by an explicit lifecycle hook
_RECORDERS: "weakref.WeakSet[FlightRecorder]" = weakref.WeakSet()

#: guards _RECORDERS against mutation-during-iteration: register()
#: runs on whatever thread builds an engine/runner while broadcast()
#: snapshots the set from the thread a fault fires on — an unguarded
#: ``add`` landing mid-``list(...)`` raises "Set changed size during
#: iteration" on the BROADCASTING thread, i.e. inside faults.fire on
#: the step path (forced-interleaving regression test in
#: tests/test_obs.py).  Notes are delivered OUTSIDE the lock: each
#: ring serializes its own appends, and holding the registry lock
#: across them would couple every engine's hot path to the slowest
#: ring.
_registry_lock = threading.Lock()

#: distinguishes dumps landing within the same second+site+pid
_dump_seq = itertools.count()


def register(rec: "FlightRecorder") -> "FlightRecorder":
    """Subscribe ``rec`` to fault-fire broadcasts (weakly held)."""
    with _registry_lock:
        _RECORDERS.add(rec)
    return rec


def broadcast(kind: str, name: str, **attrs: Any) -> None:
    """Note one event into every registered ring — called by
    ``faults.fire`` for each FIRED fault only, so the no-fault path
    never reaches here."""
    with _registry_lock:
        recorders = list(_RECORDERS)
    for rec in recorders:
        rec.note(kind, name, **attrs)


def dump_for_store(recorder: "FlightRecorder", site: str,
                   record_store: Optional[str],
                   reason: str) -> Optional[str]:
    """The one dump-next-to-the-record-store contract shared by
    ``ServeEngine``/``TrainRunner``: write the ring to
    ``<store dir>/incidents/`` and return the REF — the dump path
    relative to the store's directory, what the record carries as
    ``flight_ref``.  None (and zero file writes) when ``record_store``
    is unset; best-effort like the record itself (an OSError degrades
    to a warning, never a crash on the incident path)."""
    if not record_store:
        return None
    store_dir = os.path.dirname(os.path.abspath(record_store))
    try:
        path = recorder.dump(site, os.path.join(store_dir, "incidents"),
                             reason=reason)
        return os.path.relpath(path, start=store_dir)
    except OSError as e:
        import warnings
        warnings.warn(f"could not dump flight recorder: "
                      f"{type(e).__name__}: {e}", stacklevel=2)
        return None


class FlightRecorder:
    """Bounded ring of the last ``capacity`` structured events."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=self.capacity)
        # notes arrive from the step thread AND (via broadcast/Heartbeat
        # callbacks) monitor threads; the lock keeps dump() snapshots
        # internally consistent
        self._lock = threading.Lock()

    def note(self, kind: str, name: str, **attrs: Any) -> None:
        """Append one event (hot path: dict build + deque append; no
        I/O).  The active trace id is stamped automatically."""
        ev: Dict[str, Any] = {"t": time.time(), "kind": kind,  # singalint: disable=SGL005 dump timestamps must correlate with the JSONL sink's cross-host event timestamps
                              "name": name}
        tid = trace.current_trace_id()
        if tid is not None:
            ev["trace"] = tid
        ev.update(attrs)
        with self._lock:
            self._ring.append(ev)

    def snapshot(self) -> List[Dict[str, Any]]:
        """The ring's events, oldest first (a copy)."""
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def dump(self, site: str, directory: str,
             reason: Optional[str] = None) -> str:
        """Atomically write the ring to
        ``<directory>/<ts>-<site>-<pid>-<seq>.jsonl`` and return the
        file's absolute path.  ``site`` must be a registered fault or
        incident site (typos fail loudly here and statically via
        SGL009).  The write is temp + ``os.replace`` — a crash mid-dump
        never leaves a half-written incident file."""
        from ..faults import sites as fault_sites
        if not fault_sites.is_incident_site(site):
            raise ValueError(
                f"unknown flight-dump site {site!r} (registered fault "
                f"sites: {sorted(fault_sites.SITES)}; incident sites: "
                f"{sorted(fault_sites.INCIDENT_SITES)})")
        import json
        os.makedirs(directory, exist_ok=True)
        fname = (f"{time.strftime('%Y%m%d-%H%M%S')}-{site}-"
                 f"{os.getpid()}-{next(_dump_seq)}.jsonl")
        path = os.path.join(os.path.abspath(directory), fname)
        events = self.snapshot()
        if reason is not None:
            events = events + [{"t": time.time(), "kind": "dump",  # singalint: disable=SGL005 dump timestamps must correlate with the JSONL sink's cross-host event timestamps
                                "name": site, "reason": reason}]
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            for ev in events:
                f.write(json.dumps(ev, sort_keys=True, default=repr)
                        + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path
