"""Metrics/observability (SURVEY.md §5): loss, accuracy, throughput, MFU
accounting, with an optional JSONL sink. No external deps."""

from __future__ import annotations

import json
import time
from typing import Dict, Optional

import numpy as np

__all__ = ["Accuracy", "MeanMeter", "Throughput", "MetricsLogger",
           "accuracy", "peak_flops", "peak_hbm_bw", "mfu"]


def accuracy(logits: np.ndarray, labels: np.ndarray) -> float:
    pred = np.argmax(np.asarray(logits), axis=-1)
    return float((pred == np.asarray(labels)).mean())


class Accuracy:
    def __init__(self):
        self.correct = 0
        self.total = 0

    def update(self, logits, labels) -> None:
        pred = np.argmax(np.asarray(logits), axis=-1)
        labels = np.asarray(labels)
        self.correct += int((pred == labels).sum())
        self.total += labels.size

    @property
    def value(self) -> float:
        return self.correct / max(1, self.total)


class MeanMeter:
    def __init__(self):
        self.sum = 0.0
        self.n = 0

    def update(self, v, n: int = 1) -> None:
        self.sum += float(v) * n
        self.n += n

    @property
    def value(self) -> float:
        return self.sum / max(1, self.n)


class Throughput:
    """items/sec over a sliding window."""

    def __init__(self):
        self.t0 = None
        self.items = 0

    def start(self):
        self.t0 = time.perf_counter()
        self.items = 0

    def update(self, n: int):
        if self.t0 is None:
            self.start()
        self.items += n

    @property
    def value(self) -> float:
        if self.t0 is None:
            return 0.0
        dt = time.perf_counter() - self.t0
        return self.items / max(1e-9, dt)


# peak dense bf16 FLOPs per chip (for MFU accounting, BASELINE.json:5).
# Ordered most-specific-first: matched as substrings of the PJRT
# device_kind (e.g. "TPU v5 lite", "TPU v6 lite", "TPU v4").
_PEAK_FLOPS = (
    ("7x", 197e12),        # this image's tunneled chip reports "TPU7x";
                           # PALLAS_AXON_TPU_GEN=v5e ⇒ v5e-class peak
    ("v5 lite", 197e12),   # v5e bf16
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v6 lite", 918e12),   # Trillium / v6e
    ("v6e", 918e12),
    ("v6", 918e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
    ("cpu", 1e12),
)


def _peak_lookup(table, env_var: str, scale: float, default: float,
                 device_kind: Optional[str]) -> float:
    import os

    import jax
    override = os.environ.get(env_var)
    if override:
        return float(override) * scale
    kind = (device_kind
            or getattr(jax.devices()[0], "device_kind", "cpu")).lower()
    for k, v in table:
        if k in kind:
            return v
    return default


def peak_flops(device_kind: Optional[str] = None) -> float:
    # Unknown accelerator kind (e.g. an experimental PJRT plugin that
    # doesn't embed the vN generation): assume v4-class peak rather than
    # the CPU nominal, which would inflate MFU ~275x.
    return _peak_lookup(_PEAK_FLOPS, "SINGA_PEAK_TFLOPS", 1e12, 275e12,
                        device_kind)


# peak HBM bandwidth per chip (bytes/s) — the roofline's memory bound
_PEAK_BW = (
    ("7x", 819e9),         # tunneled chip reports "TPU7x"; v5e-class
    ("v5 lite", 819e9),    # v5e
    ("v5e", 819e9),
    ("v5p", 2765e9),
    ("v6 lite", 1640e9),   # Trillium / v6e
    ("v6e", 1640e9),
    ("v6", 1640e9),
    ("v5", 2765e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
    ("cpu", 50e9),
)


def peak_hbm_bw(device_kind: Optional[str] = None) -> float:
    return _peak_lookup(_PEAK_BW, "SINGA_PEAK_HBM_GBS", 1e9, 1228e9,
                        device_kind)


def mfu(model_flops_per_step: float, step_time_s: float,
        n_chips: int = 1, device_kind: Optional[str] = None) -> float:
    """Achieved model-FLOPs utilization. model_flops must be the *model's*
    FLOPs (e.g. 6*N*T for transformers), not the compiled module's."""
    return model_flops_per_step / (step_time_s * peak_flops(device_kind) * n_chips)


class MetricsLogger:
    """JSONL sink: one dict per line.

    File I/O is unified onto ``obs.events.JsonlSink`` (same atomic-line,
    thread-safe writer the telemetry layer uses), so all JSONL emission
    in the repo shares one implementation."""

    def __init__(self, path: Optional[str] = None, echo: bool = True):
        from ..obs.events import JsonlSink
        self.path = path
        self.echo = echo
        self._sink = JsonlSink(path) if path else None

    def log(self, **kv) -> None:
        kv.setdefault("t", time.time())  # singalint: disable=SGL005 log-line timestamp correlated with obs events across files, not a duration
        payload = {k: _jsonable(v) for k, v in kv.items()}
        if self._sink:
            self._sink.emit(payload)
        if self.echo:
            print(json.dumps(payload))

    def close(self):
        if self._sink:
            self._sink.close()


def _jsonable(v):
    if isinstance(v, (np.floating, np.integer)):
        return v.item()
    if hasattr(v, "item") and getattr(v, "ndim", 1) == 0:
        return float(v)
    return v
