"""Failure detection / clean abort (SURVEY.md §5).

The reference lineage has none (synchronous SGD; a dead rank hangs the
job). Our plan, stated there: heartbeat + clean abort so a pod failure
surfaces as an error instead of an indefinite hang, with
checkpoint/resume (utils.checkpoint.CheckpointManager) as the recovery
path.  Two mechanisms:

* `Heartbeat` — liveness watchdog for the training loop.  The loop calls
  `beat()` every step; a monitor thread raises the alarm when no beat
  arrives within `timeout` (a hung collective, a dead coordinator, a
  wedged input pipeline all look the same from here — which is the
  point).
* `device_liveness_check` — active probe: submit a trivial op to the
  device and require completion within a deadline.  Catches a dead PJRT
  client / dropped TPU tunnel without waiting for the next step.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Callable, Optional

__all__ = ["Heartbeat", "device_liveness_check", "clean_abort",
           "FailureDetected"]


class FailureDetected(RuntimeError):
    pass


def clean_abort(msg: str, exit_code: int = 42) -> None:
    """Default failure action: loud message, immediate hard exit with a
    recognizable code so the launcher can restart-from-checkpoint.
    os._exit (not sys.exit) because the hung thread we're aborting over
    would block normal interpreter shutdown."""
    print(f"[singa_tpu.failure] FATAL: {msg}", file=sys.stderr, flush=True)
    os._exit(exit_code)


class Heartbeat:
    """Step-liveness watchdog.

        hb = Heartbeat(timeout=300)        # 5 min per step budget
        hb.start()
        for step in ...:
            train_step(...)
            hb.beat(step)
        hb.stop()

    `on_failure(age_s, last_step)` defaults to `clean_abort`; tests pass
    a callback instead.

    Trace contexts (ISSUE 11): the monitor thread deliberately DROPS
    the spawner's ``obs.trace`` context — ``threading.Thread`` never
    inherits contextvars, and this is the designed behavior here, not
    an accident: hang detection observes the whole loop, so attributing
    its events to whichever request/step happened to be active when
    ``start()`` ran would fabricate a causal link the watchdog does not
    have.  ``on_failure`` therefore fires trace-less (asserted in
    tests/test_trace.py); a worker that SHOULD carry a trace uses
    ``obs.trace.capture()``/``attach()`` (see train.ckpt's writer)."""

    def __init__(self, timeout: float = 300.0, check_every: float = 1.0,
                 on_failure: Optional[Callable[[float, int], None]] = None):
        self.timeout = float(timeout)
        self.check_every = float(check_every)
        self.on_failure = on_failure or (
            lambda age, step: clean_abort(
                f"no heartbeat for {age:.1f}s (last step {step}); "
                f"assuming hung collective or dead device"))
        self._last = time.monotonic()
        self._last_step = -1
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._fired = False

    def start(self) -> "Heartbeat":
        # each start gets a FRESH stop event, passed to its own monitor
        # thread.  The old restartable-after-stop() design CLEARED the
        # shared event instead, and a stop()+start() re-arm (the serve
        # engine's recover_on_hang path does exactly this after every
        # hang) could clear it inside the old monitor's wait() window —
        # the old thread missed the brief set, saw a cleared event, and
        # kept running alongside the new monitor: two watchdogs, double
        # on_failure fires (forced-interleaving regression test in
        # tests/test_aux.py).  With a per-generation event, the old
        # thread's event stays set forever once stopped.  Setting the
        # outgoing event first keeps start() safe WITHOUT an
        # intervening stop(): a previous generation must never be
        # orphaned holding an event nothing can set anymore.
        self._stop.set()
        self._stop = threading.Event()
        self._fired = False
        self._last = time.monotonic()
        # ALWAYS a daemon: the monitor exists to watch for wedged
        # threads, so it must never itself keep a dying interpreter
        # alive waiting on a join
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="singa-heartbeat",
                                        args=(self._stop,))
        self._thread.start()
        return self

    def beat(self, step: int = -1) -> None:
        self._last = time.monotonic()
        self._last_step = step

    def stop(self) -> None:
        """Idempotent shutdown: safe before start(), safe to call
        repeatedly, and safe from the monitor thread itself (an
        on_failure callback tearing the watchdog down must not
        self-join) — so TrainRunner.__exit__ can always call it without
        hanging interpreter shutdown."""
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2 * self.check_every)

    @property
    def fired(self) -> bool:
        return self._fired

    def _run(self, stop: threading.Event) -> None:
        # ``stop`` is THIS generation's event (never self._stop, which
        # a re-arm may already have replaced with the next monitor's)
        while not stop.wait(self.check_every):
            age = time.monotonic() - self._last
            if age > self.timeout:
                self._fired = True  # singalint: disable=SGL010 monitor thread is the only writer; start() resets it before the thread exists, readers poll a latch-once bool
                try:
                    self.on_failure(age, self._last_step)
                finally:
                    return

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def device_liveness_check(device=None, timeout: float = 30.0) -> bool:
    """Submit a trivial computation and require completion within
    `timeout` seconds. The probe runs in a *daemon* thread (not a
    ThreadPoolExecutor: its atexit hook joins workers, so a wedged PJRT
    client would hang interpreter shutdown — the exact dead-device case
    this probe exists to detect)."""
    import queue

    import jax
    import jax.numpy as jnp

    q: "queue.Queue" = queue.Queue()

    def probe():
        try:
            if device is not None and hasattr(device, "jax_devices"):
                d = device.jax_devices[0]
            elif device is not None:
                d = device
            else:
                d = jax.devices()[0]
            x = jax.device_put(jnp.ones(()), d)
            q.put(float(jax.block_until_ready(x + 1.0)))
        except Exception:
            q.put(None)

    threading.Thread(target=probe, daemon=True,
                     name="singa-liveness-probe").start()
    try:
        return q.get(timeout=timeout) == 2.0
    except queue.Empty:
        return False
