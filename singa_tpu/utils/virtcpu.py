"""Virtual-CPU platform pinning — the ONE canonical copy of the recipe
used by tests/conftest.py, __graft_entry__.py, bench.py and the
multiprocess test workers (SURVEY.md §4: N virtual devices stand in for
N chips).

This image's sitecustomize force-registers the TPU plugin and overrides
JAX_PLATFORMS programmatically, so pinning requires BOTH (a) the
--xla_force_host_platform_device_count flag in XLA_FLAGS and (b)
jax.config.update("jax_platforms", "cpu") — and both must happen before
the first JAX backend initialization.

Import-light on purpose: importing this module performs no JAX backend
work, so it is safe to use before pinning.
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = ["with_device_count_flag", "pin_virtual_cpu"]

_FLAG = "xla_force_host_platform_device_count"


def with_device_count_flag(flags: str, n: Optional[int]) -> str:
    """Return XLA_FLAGS with the host-device-count flag token replaced
    by --xla_force_host_platform_device_count=n (n=None removes it)."""
    parts = [p for p in flags.split() if _FLAG not in p]
    if n is not None:
        parts.append(f"--{_FLAG}={n}")
    return " ".join(parts)


def pin_virtual_cpu(n: int) -> bool:
    """Try to pin an n-device virtual CPU platform in-process.

    Returns True on success; False if a JAX backend already exists with
    the wrong platform/device-count (the caller must then re-exec in a
    clean subprocess with JAX_PLATFORMS=cpu and the flag set)."""
    from jax._src import xla_bridge

    if xla_bridge._backends:  # backend(s) already initialized
        import jax
        devs = jax.devices()
        return devs[0].platform == "cpu" and len(devs) >= n

    os.environ["XLA_FLAGS"] = with_device_count_flag(
        os.environ.get("XLA_FLAGS", ""), n)

    import jax
    jax.config.update("jax_platforms", "cpu")
    devs = jax.devices()
    return devs[0].platform == "cpu" and len(devs) >= n
