"""Tracing / profiling (SURVEY.md §5).

The reference lineage has per-op timing in the scheduler at best; the
plan stated in the survey: step-time logging + the XLA/device profiler
that comes free from the runtime, plus compiled-module cost analysis so
the ≥45% MFU target (BASELINE.json:2,5) is checkable, not vibes.

* `StepProfiler` — wall-clock per step with warmup discard; feeds MFU
  from the captured graph's XLA cost analysis (true compiled FLOPs, not
  an analytic formula) when a model is attached.
* `device_trace` — context manager around `jax.profiler` traces; the
  dumped trace opens in TensorBoard/XProf with per-HLO timing.
* `profile_model` — one-call summary: compiled FLOPs, bytes accessed,
  arithmetic intensity, step time, MFU.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, List, Optional

import numpy as np

from .metrics import peak_flops

__all__ = ["StepProfiler", "device_trace", "profile_model"]


class StepProfiler:
    """Accumulate per-step wall time; first `warmup` steps discarded
    (compile + cache population).

        prof = StepProfiler(warmup=2)
        for ...:
            with prof.step():
                model.train_step(x, y)
        print(prof.summary(model))
    """

    def __init__(self, warmup: int = 2):
        self.warmup = warmup
        self.times: List[float] = []
        self._n = 0

    @contextlib.contextmanager
    def step(self):
        t0 = time.perf_counter()
        yield
        dt = time.perf_counter() - t0
        self._n += 1
        if self._n > self.warmup:
            self.times.append(dt)
            # mirror into the structured telemetry stream when enabled
            from ..obs import events as obs_events
            obs_events.gauge("profiler.step_ms", round(dt * 1e3, 3),
                             n=self._n)

    @property
    def mean_s(self) -> float:
        return float(np.mean(self.times)) if self.times else 0.0

    @property
    def p50_s(self) -> float:
        return float(np.median(self.times)) if self.times else 0.0

    def summary(self, model=None, device_kind: Optional[str] = None) -> Dict:
        out = {
            "steps_timed": len(self.times),
            "step_time_ms": round(self.mean_s * 1e3, 3),
            "step_time_p50_ms": round(self.p50_s * 1e3, 3),
        }
        g = getattr(model, "graph", None) if model is not None else None
        if g is not None and self.mean_s > 0:
            flops = g.flops()
            if flops:
                achieved = flops / self.mean_s
                out["compiled_gflops_per_step"] = round(flops / 1e9, 6)
                out["achieved_tflops"] = round(achieved / 1e12, 6)
                out["mfu"] = round(achieved / peak_flops(device_kind), 8)
        return out


@contextlib.contextmanager
def device_trace(logdir: str):
    """XLA device trace (TensorBoard/XProf format): per-HLO device
    timing, memory viewer, roofline — free from the runtime."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def profile_model(model, batch, steps: int = 10, warmup: int = 2,
                  device_kind: Optional[str] = None,
                  train: bool = True) -> Dict:
    """Run `steps` compiled steps (train_step, or the eval forward with
    train=False) and return the cost/latency summary (model must be
    compiled with use_graph=True)."""
    import jax

    run = model.train_step if train else (lambda *b: model.eval()(b[0]))
    prof = StepProfiler(warmup=warmup)
    out = None
    for _ in range(warmup + max(1, steps)):
        with prof.step():
            out = run(*batch)
            jax.block_until_ready(out[-1].data if isinstance(out, tuple)
                                  else out.data)
    # cost analysis must come from the graph of the mode we timed — a
    # model that ran train_step earlier also holds the (3x larger) train
    # graph, which would inflate eval MFU.  Run the XLA analysis once.
    g = model.get_graph("train" if train else "eval")
    s = prof.summary(None, device_kind)
    ca = g.cost_analysis() if g is not None else {}
    flops = float(ca.get("flops", 0.0))
    if flops and prof.mean_s > 0:
        achieved = flops / prof.mean_s
        s["compiled_gflops_per_step"] = round(flops / 1e9, 6)
        s["achieved_tflops"] = round(achieved / 1e12, 6)
        s["mfu"] = round(achieved / peak_flops(device_kind), 8)
    if "bytes accessed" in ca and s.get("step_time_ms"):
        ba = float(ca["bytes accessed"])
        s["bytes_accessed_per_step"] = int(ba)
        if flops:
            s["arithmetic_intensity"] = round(flops / ba, 2)
    return s
