"""Analytic FLOP counting from a model's OWN traced operations.

Walks the jaxpr of a forward pass and sums matmul/conv FLOPs
(2 * output elements * reduction length), recursing into scan/cond
sub-jaxprs (scan bodies multiplied by their static trip count — the
thing XLA's cost_analysis gets wrong, which is why the benches use
this counter for MFU).

Born of an r5 audit: the ResNet bench had fed NCHW images to the
zoo's NHWC convs for four rounds, and the hard-coded "published
4.09 GFLOP/image" numerator silently described a network that wasn't
running.  Counting from the traced graph makes the numerator match
the executed architecture by construction; tests pin the zoo models
to their published counts.
"""

from __future__ import annotations

from math import prod

__all__ = ["jaxpr_matmul_conv_flops", "model_forward_flops"]


def jaxpr_matmul_conv_flops(jaxpr) -> float:
    """Sum matmul/conv FLOPs (2*MACs) over a jaxpr, recursing into
    sub-jaxprs; a scan body is multiplied by its trip count."""
    total = 0.0
    for eqn in jaxpr.eqns:
        p = eqn.primitive.name
        if p == "dot_general":
            (lc, _), _ = eqn.params["dimension_numbers"]
            lhs = eqn.invars[0].aval.shape
            out = eqn.outvars[0].aval.shape
            red = prod(lhs[i] for i in lc) if lc else 1
            total += 2.0 * prod(out) * red
        elif p == "conv_general_dilated":
            rhs = eqn.invars[1].aval.shape   # kernel
            out = eqn.outvars[0].aval.shape
            dn = eqn.params["dimension_numbers"]
            cout = rhs[dn.rhs_spec[0]]
            red = prod(rhs) // cout          # Kh*Kw*Cin_per_group
            total += 2.0 * prod(out) * red
        elif p == "cond":
            # one branch executes; charge the costliest (upper bound —
            # a data-dependent choice is unknowable statically)
            branches = eqn.params.get("branches", ())
            total += max((jaxpr_matmul_conv_flops(b.jaxpr)
                          for b in branches), default=0.0)
        else:
            for sub in eqn.params.values():
                subs = sub if isinstance(sub, (tuple, list)) else (sub,)
                for sj in subs:
                    if hasattr(sj, "jaxpr"):
                        inner = jaxpr_matmul_conv_flops(sj.jaxpr)
                        if p == "scan":
                            inner *= eqn.params.get("length", 1)
                        total += inner
    return total


def model_forward_flops(model, x) -> float:
    """Forward matmul+conv FLOPs per SINGLE example of `model` on input
    shaped like `x` (a Tensor or array; only x[:1] is traced — no
    device work).  Eval-mode trace with state snapshot/restore so
    counting can never leak tracers into the live model."""
    import jax

    from .. import autograd
    from ..tensor import Tensor

    data = x.data if isinstance(x, Tensor) else x
    data = data[:1]
    dev = x.device if isinstance(x, Tensor) else None

    saved_training = autograd.is_training()
    autograd.set_training(False)
    snap_p = {n: t.data for n, t in model.get_params().items()}
    snap_b = {n: t.data for n, t in model._get_buffers().items()}
    try:
        def fwd(a):
            return model.forward(
                Tensor(data=a, device=dev, requires_grad=False)).data

        closed = jax.make_jaxpr(fwd)(data)
        return jaxpr_matmul_conv_flops(closed.jaxpr)
    finally:
        autograd.set_training(saved_training)
        for n, t in model.get_params().items():
            t.data = snap_p[n]
        for n, t in model._get_buffers().items():
            t.data = snap_b[n]
