"""Checkpoint / resume (SURVEY.md §5: reference lineage
save_states/load_states writing a zip of tensors; we keep the same API
with atomic writes — host-side .npz plus a json manifest)."""

from __future__ import annotations

import json
import os
import tempfile
from typing import Dict, Optional

import numpy as np

__all__ = ["save_states", "load_states", "save_arrays", "load_arrays"]

_AUX_KEY = "__aux__"


def save_arrays(arrays: Dict[str, np.ndarray], fpath: str,
                aux: Optional[Dict] = None) -> None:
    """Atomic write: temp file in the same dir, then rename."""
    d = os.path.dirname(os.path.abspath(fpath)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            meta = {_AUX_KEY: json.dumps(aux or {})}
            np.savez(f, __meta__=json.dumps(meta), **arrays)
        os.replace(tmp, fpath)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_arrays(fpath: str):
    with np.load(fpath, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
    aux = json.loads(meta.get(_AUX_KEY, "{}"))
    return arrays, aux


def save_states(model, fpath: str, aux_states: Optional[Dict] = None) -> None:
    """Reference API: model.save_states(fpath, aux_states)."""
    states = model.get_states()
    arrays = {}
    for name, t in states.items():
        arrays[name] = np.asarray(t.data, dtype=np.asarray(t.data).dtype)
    aux = dict(aux_states or {})
    if getattr(model, "optimizer", None) is not None:
        aux["optimizer"] = model.optimizer.get_states()
    save_arrays(arrays, fpath, aux)


def load_states(model, fpath: str) -> Dict:
    arrays, aux = load_arrays(fpath)
    model.set_states(arrays)
    if "optimizer" in aux and getattr(model, "optimizer", None) is not None:
        model.optimizer.set_states(aux["optimizer"])
    return aux
