"""Checkpoint / resume (SURVEY.md §5: reference lineage
save_states/load_states writing a zip of tensors; we keep the same API
with atomic writes — host-side .npz plus a json manifest)."""

from __future__ import annotations

import json
import os
import tempfile
from typing import Dict, Optional

import numpy as np

__all__ = ["save_states", "load_states", "save_arrays", "load_arrays",
           "CheckpointManager"]

_AUX_KEY = "__aux__"


def save_arrays(arrays: Dict[str, np.ndarray], fpath: str,
                aux: Optional[Dict] = None) -> None:
    """Atomic write: temp file in the same dir, then rename."""
    d = os.path.dirname(os.path.abspath(fpath)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            meta = {_AUX_KEY: json.dumps(aux or {})}
            np.savez(f, __meta__=json.dumps(meta), **arrays)
        os.replace(tmp, fpath)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_arrays(fpath: str):
    with np.load(fpath, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
    aux = json.loads(meta.get(_AUX_KEY, "{}"))
    return arrays, aux


def save_states(model, fpath: str, aux_states: Optional[Dict] = None) -> None:
    """Reference API: model.save_states(fpath, aux_states)."""
    states = model.get_states()
    arrays = {}
    for name, t in states.items():
        arrays[name] = np.asarray(t.data, dtype=np.asarray(t.data).dtype)
    aux = dict(aux_states or {})
    if getattr(model, "optimizer", None) is not None:
        aux["optimizer"] = model.optimizer.get_states()
    save_arrays(arrays, fpath, aux)


def load_states(model, fpath: str) -> Dict:
    arrays, aux = load_arrays(fpath)
    model.set_states(arrays)
    if "optimizer" in aux and getattr(model, "optimizer", None) is not None:
        model.optimizer.set_states(aux["optimizer"])
    return aux


class CheckpointManager:
    """Stepped checkpoints with retention + resume (SURVEY.md §5: the
    recovery half of the failure-detection story — a dead pod restarts
    and resumes from the newest intact checkpoint; atomic writes mean a
    crash mid-save can never corrupt the latest one).

        ckpt = CheckpointManager("ckpts", keep=3)
        start = ckpt.restore_latest(model)          # 0 if none
        for step in range(start, total):
            ...
            ckpt.save(step, model)                  # every save_every steps
    """

    def __init__(self, directory: str, keep: int = 3, save_every: int = 1):
        self.dir = directory
        self.keep = keep
        self.save_every = max(1, save_every)
        os.makedirs(directory, exist_ok=True)

    def _path(self, step: int) -> str:
        return os.path.join(self.dir, f"ckpt_{step:012d}.npz")

    def steps(self):
        out = []
        for f in os.listdir(self.dir):
            if f.startswith("ckpt_") and f.endswith(".npz"):
                try:
                    out.append(int(f[5:-4]))
                except ValueError:
                    pass
        return sorted(out)

    def save(self, step: int, model, aux: Optional[Dict] = None,
             force: bool = False) -> Optional[str]:
        if not force and step % self.save_every:
            return None
        path = self._path(step)
        a = dict(aux or {})
        a["step"] = int(step)
        save_states(model, path, a)
        for old in self.steps()[:-self.keep]:
            try:
                os.unlink(self._path(old))
            except OSError:
                pass
        return path

    def restore_latest(self, model) -> int:
        """Load the newest intact checkpoint; returns the step after it
        (0 when starting fresh). Only decode/IO failures (torn writes)
        fall back to an older file — a checkpoint that *loads* but does
        not fit the model (shape/arch mismatch) raises, because silently
        restarting from step 0 would also rotate away the good files."""
        for step in reversed(self.steps()):
            try:
                arrays, aux = load_arrays(self._path(step))
            except Exception:
                continue  # torn/corrupt file: fall back to the previous
            model.set_states(arrays)
            if "optimizer" in aux and getattr(model, "optimizer", None) is not None:
                model.optimizer.set_states(aux["optimizer"])
            return step + 1
        return 0
