"""Checkpoint / resume (SURVEY.md §5: reference lineage
save_states/load_states writing a zip of tensors; we keep the same API
with atomic writes — host-side .npz plus a json manifest).

Resume correctness: optimizer moment arrays (momentum buffers, Adam
m/v) are serialized alongside the params under ``__opt__:<i>`` keys with
their {param-name, leaf-count} manifest in the json aux, so a restored
run reproduces the uninterrupted trajectory — the step counter alone is
not enough (a zeroed momentum silently changes the dynamics).

Multi-host: every process participates in gathering sharded arrays to
host (a collective under GSPMD), then only process 0 writes the files;
``CheckpointManager.save`` barriers afterwards so no process races ahead
and reads a half-written checkpoint. All processes read the same path on
restore (shared-filesystem convention, as in the reference lineage).
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import tempfile
from typing import Dict, List, Optional

import numpy as np

__all__ = ["save_states", "load_states", "save_arrays", "load_arrays",
           "atomic_write", "check_opt_manifest", "CheckpointManager"]

_AUX_KEY = "__aux__"
_MANIFEST_KEY = "__arrays__"
_DIGEST_KEY = "__digest__"
_OPT_PREFIX = "__opt__:"


def _process_index() -> int:
    import jax
    return jax.process_index()


def _process_count() -> int:
    import jax
    return jax.process_count()


def _to_host(a) -> np.ndarray:
    """Device -> host copy that works for GSPMD-sharded jax.Arrays.

    Fully-addressable arrays copy directly; multi-host shardings gather
    via process_allgather (a collective — every process must call it)."""
    import jax
    if isinstance(a, jax.Array) and not a.is_fully_addressable:
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(a, tiled=True))
    return np.asarray(a)


def _manifest_of(arrays: Dict[str, np.ndarray]) -> Dict[str, List]:
    return {k: [list(np.asarray(v).shape), str(np.asarray(v).dtype)]
            for k, v in arrays.items()}


def _digest(aux_json: str, manifest_json: str) -> str:
    h = hashlib.sha256()
    h.update(aux_json.encode())
    h.update(manifest_json.encode())
    return h.hexdigest()


def atomic_write(fpath: str, write_fn, mode: str = "wb") -> None:
    """The crash-consistent write protocol, shared by every durable
    file this package lands (npz payloads here, commit markers in
    ``train.ckpt``): temp file in the target dir, ``write_fn(f)``,
    fsync, atomic rename.  The temp file never outlives a failed write
    (ENOSPC, a serialization error, an interrupt) — and the cleanup
    itself must not mask the original error."""
    d = os.path.dirname(os.path.abspath(fpath)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, mode) as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, fpath)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def save_arrays(arrays: Dict[str, np.ndarray], fpath: str,
                aux: Optional[Dict] = None) -> None:
    """Atomic write: temp file in the same dir, fsync, then rename.

    The embedded metadata carries a manifest of every array member
    (name/shape/dtype — *including* the ``__opt__:<i>`` optimizer-moment
    leaves) plus a sha256 digest over aux+manifest, so ``load_arrays``
    can fail loudly on a params/opt mismatch or tampered aux instead of
    handing a silently-inconsistent state to the optimizer."""
    def _write(f):
        aux_json = json.dumps(aux or {}, sort_keys=True)
        manifest_json = json.dumps(_manifest_of(arrays), sort_keys=True)
        meta = {_AUX_KEY: aux_json, _MANIFEST_KEY: manifest_json,
                _DIGEST_KEY: _digest(aux_json, manifest_json)}
        np.savez(f, __meta__=json.dumps(meta), **arrays)

    atomic_write(fpath, _write)


def load_arrays(fpath: str):
    with np.load(fpath, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
    aux_json = meta.get(_AUX_KEY, "{}")
    aux = json.loads(aux_json)
    manifest_json = meta.get(_MANIFEST_KEY)
    if manifest_json is not None:   # pre-manifest files load unchecked
        stored = meta.get(_DIGEST_KEY)
        if stored != _digest(aux_json, manifest_json):
            raise ValueError(
                f"{fpath}: aux/manifest digest mismatch — metadata was "
                f"tampered with or the write was torn")
        manifest = json.loads(manifest_json)
        missing = sorted(set(manifest) - set(arrays))
        extra = sorted(set(arrays) - set(manifest))
        if missing or extra:
            raise ValueError(
                f"{fpath}: array members do not match the manifest "
                f"(missing: {missing}, unexpected: {extra}) — params/"
                f"optimizer-moment set is inconsistent")
        for k, (shape, dtype) in manifest.items():
            a = arrays[k]
            if list(a.shape) != list(shape) or str(a.dtype) != dtype:
                raise ValueError(
                    f"{fpath}: array {k!r} is {a.shape}/{a.dtype} but the "
                    f"manifest recorded {tuple(shape)}/{dtype}")
    return arrays, aux


def _collect(model, aux_states: Optional[Dict]):
    """Gather params + optimizer moments to host. Every process must
    call this: the gather of non-addressable arrays is a collective.
    Fully-addressable arrays skip the device->host copy on processes
    that will not write."""
    writer = _process_index() == 0

    def fetch(a):
        import jax
        if isinstance(a, jax.Array) and not a.is_fully_addressable:
            return _to_host(a)          # collective: all processes join
        return _to_host(a) if writer else None

    states = model.get_states()
    arrays = {}
    for name, t in states.items():
        arrays[name] = fetch(t.data)
    aux = dict(aux_states or {})
    opt = getattr(model, "optimizer", None)
    if opt is not None:
        aux["optimizer"] = opt.get_states()
        aux["opt_signature"] = opt.state_signature()
        slot_arrays = opt.slot_arrays()
        manifest: List = []
        i = 0
        for name in sorted(slot_arrays):
            leaves = slot_arrays[name]
            manifest.append([name, len(leaves)])
            for leaf in leaves:
                arrays[f"{_OPT_PREFIX}{i}"] = fetch(leaf)
                i += 1
        aux["opt_slots"] = manifest
    return arrays, aux


def _barrier(tag: str) -> None:
    if _process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(tag)


def save_states(model, fpath: str, aux_states: Optional[Dict] = None) -> None:
    """Reference API: model.save_states(fpath, aux_states).

    Multi-host: collective gather on every process, write on process 0,
    barrier so no process reads the path before the write lands."""
    arrays, aux = _collect(model, aux_states)
    if _process_index() == 0:
        save_arrays(arrays, fpath, aux)
    _barrier(f"singa_save_states_{os.path.basename(fpath)}")


def check_opt_manifest(arrays: Dict, aux: Dict) -> None:
    """One definition of "the optimizer moments agree with their slot
    manifest", enforced both at load (:func:`_apply`) and by the
    offline auditor (``tools/ckpt_fsck.py``).  Raises ValueError on a
    params/opt-state mismatch; a pre-manifest aux passes unchecked."""
    manifest = aux.get("opt_slots")
    if manifest is None:
        return
    expected = sum(int(n) for _, n in manifest)
    got = sum(1 for k in arrays if k.startswith(_OPT_PREFIX))
    if expected != got:
        raise ValueError(
            f"checkpoint carries {got} optimizer moment arrays but its "
            f"slot manifest lists {expected} — params/opt-state "
            f"mismatch, refusing to load")


def _apply(model, arrays: Dict, aux: Dict) -> None:
    opt = getattr(model, "optimizer", None)
    manifest = aux.get("opt_slots")
    saved_sig = aux.get("opt_signature")
    if opt is not None and manifest is not None and saved_sig is not None \
            and saved_sig != opt.state_signature():
        # leaf counts/shapes can coincide across optimizers (Adam's
        # (m, v) vs GradAccum's {acc, base}) — structure alone cannot
        # catch that, the signature can.  Checked BEFORE any mutation so
        # a rejected restore leaves the model untouched.
        raise ValueError(
            f"checkpoint optimizer state is {saved_sig!r} but the model "
            f"optimizer is {opt.state_signature()!r} — refusing to "
            f"reinterpret moments across optimizers")
    opt_arrays = {k: v for k, v in arrays.items() if k.startswith(_OPT_PREFIX)}
    # checked BEFORE any mutation: a checkpoint whose moment arrays
    # don't match its own slot manifest is torn/mixed — loading the
    # params while zeroing the moments would silently change the
    # training dynamics
    check_opt_manifest(arrays, aux)
    model.set_states({k: v for k, v in arrays.items()
                      if not k.startswith(_OPT_PREFIX)})
    if opt is None:
        return
    if "optimizer" in aux:
        opt.set_states(aux["optimizer"])
    if manifest is not None:
        slots, i = {}, 0
        for name, n_leaves in manifest:
            slots[name] = [opt_arrays[f"{_OPT_PREFIX}{i + j}"]
                           for j in range(n_leaves)]
            i += n_leaves
        opt.load_slot_arrays(slots)
        # compiled executors cache their own slot pytrees: drop them so
        # the next step re-seeds from the restored moments
        if hasattr(model, "_executors"):
            model._executors.clear()


def load_states(model, fpath: str) -> Dict:
    arrays, aux = load_arrays(fpath)
    _apply(model, arrays, aux)
    return aux


class CheckpointManager:
    """Stepped checkpoints with retention + resume (SURVEY.md §5: the
    recovery half of the failure-detection story — a dead pod restarts
    and resumes from the newest intact checkpoint; atomic writes mean a
    crash mid-save can never corrupt the latest one).

        ckpt = CheckpointManager("ckpts", keep=3)
        start = ckpt.restore_latest(model)          # 0 if none
        for step in range(start, total):
            ...
            ckpt.save(step, model)                  # every save_every steps
    """

    def __init__(self, directory: str, keep: int = 3, save_every: int = 1,
                 asynchronous: bool = False):
        """asynchronous: overlap disk IO with training — save() still
        gathers device arrays synchronously (that part is a collective
        and must not race the next step's donation), but the npz write +
        retention pruning run in a background thread.  Call wait() (or
        save()/restore_latest(), which wait implicitly) before reading
        checkpoint files.  Multi-host runs (process_count > 1) always
        save synchronously — the end-of-save barrier is a collective
        that must not interleave with training collectives."""
        self.dir = directory
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.keep = keep
        self.save_every = max(1, save_every)
        self.asynchronous = asynchronous
        self._pending = None
        self._executor = None
        os.makedirs(directory, exist_ok=True)

    def _path(self, step: int) -> str:
        return os.path.join(self.dir, f"ckpt_{step:012d}.npz")

    def steps(self):
        out = []
        for f in os.listdir(self.dir):
            if f.startswith("ckpt_") and f.endswith(".npz"):
                try:
                    out.append(int(f[5:-4]))
                except ValueError:
                    pass
        return sorted(out)

    def save(self, step: int, model, aux: Optional[Dict] = None,
             force: bool = False) -> Optional[str]:
        if not force and step % self.save_every:
            return None
        self.wait()                      # one in-flight write at a time
        path = self._path(step)
        a = dict(aux or {})
        a["step"] = int(step)
        # collective gather on every process; file IO on process 0 only
        arrays, full_aux = _collect(model, a)

        def _write():
            if _process_index() == 0:
                save_arrays(arrays, path, full_aux)
                for old in self.steps()[:-self.keep]:
                    try:
                        os.unlink(self._path(old))
                    except OSError:
                        pass
            _barrier(f"singa_ckpt_{step}")

        # multi-host saves stay synchronous: the end-of-save barrier is a
        # collective, and issuing it from a background thread could
        # interleave with the training step's collectives
        if self.asynchronous and _process_count() == 1:
            # single-worker executor: write failures surface in wait()
            # (future.result re-raises), and its non-daemon worker is
            # joined at interpreter exit, so the final write always lands
            # even without an explicit trailing wait()
            if self._executor is None:
                from concurrent.futures import ThreadPoolExecutor
                self._executor = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="singa-ckpt")
            self._pending = self._executor.submit(_write)
        else:
            _write()
        return path

    def wait(self) -> None:
        """Block until the in-flight asynchronous write (if any) lands;
        re-raises any exception the background write hit."""
        if self._pending is not None:
            pending, self._pending = self._pending, None
            pending.result()

    def restore_latest(self, model) -> int:
        """Load the newest intact checkpoint; returns the step after it
        (0 when starting fresh). Only decode/IO failures (torn writes)
        fall back to an older file — a checkpoint that *loads* but does
        not fit the model (shape/arch mismatch) raises, because silently
        restarting from step 0 would also rotate away the good files."""
        try:
            self.wait()
        except Exception as e:
            # a stale background SAVE failure must not abort recovery
            # (the fall-back contract below still applies to whatever
            # intact files exist on disk) — but it must be REPORTED,
            # because wait() pops the future and nothing else will
            import warnings
            warnings.warn(
                f"a background checkpoint save had failed "
                f"({type(e).__name__}: {e}); restoring from the files "
                f"on disk", stacklevel=2)
        for step in reversed(self.steps()):
            try:
                arrays, aux = load_arrays(self._path(step))
            except Exception:
                continue  # torn/corrupt file: fall back to the previous
            _apply(model, arrays, aux)
            return step + 1
        return 0
