"""Data pipeline: shuffled, prefetched batch loading.

The hot path is the native threaded loader (csrc/dataloader.cc) so batch
assembly overlaps device compute; a pure-python fallback keeps the API
alive if the native library can't build.  Mirrors the reference's native
data path (SURVEY.md §2.2 native checklist)."""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

from .. import _core

__all__ = ["DataLoader", "prefetch_to_device", "synthetic_dataset"]


class DataLoader:
    """Iterate (x, y) minibatches from in-memory arrays.

    One iteration = one epoch. Reshuffles every epoch (seed+epoch on
    both paths, so runs are reproducible).

    Resume: the loader tracks a (epoch, batch) cursor across
    iterations; :meth:`state_dict` / :meth:`load_state_dict` let a
    checkpointing orchestrator (``singa_tpu.train``) capture the exact
    data position and continue the shuffle trajectory mid-epoch after a
    crash — a restored iteration replays the SAME permutation (seed +
    epoch) and starts at the saved batch index.  Note that abandoning
    an epoch mid-iteration leaves the cursor mid-epoch on purpose: the
    next ``__iter__`` resumes, it does not reshuffle."""

    def __init__(self, x: np.ndarray, y: Optional[np.ndarray] = None,
                 batch_size: int = 32, shuffle: bool = True, seed: int = 0,
                 drop_last: bool = False, workers: int = 2,
                 prefetch: int = 4, use_native: Optional[bool] = None,
                 rank: int = 0, world_size: int = 1):
        """rank/world_size: multi-host data parallelism — each process
        loads a contiguous shard of exactly floor(n/world_size) samples
        (equal sizes across ranks, so every rank sees the same batch
        count and shapes — synchronous collectives can't desync; up to
        world_size-1 trailing samples are dropped per epoch).  The
        reference DistOpt workflow partitions input by rank the same
        way.  Defaults keep single-process behavior bit-identical."""
        x = np.asarray(x)
        if np.issubdtype(x.dtype, np.integer):
            # token-id streams (LLM training) stay integral; the native
            # loader's buffers are f32-typed, so the int path uses the
            # python pipeline
            if use_native:
                import warnings
                warnings.warn(
                    "DataLoader(use_native=True) ignored: integer input "
                    "(token ids) routes through the python pipeline — "
                    "the native loader's buffers are f32-typed and "
                    "would corrupt ids", stacklevel=2)
            x = x.astype(np.int32, copy=False)
            use_native = False
        else:
            x = x.astype(np.float32, copy=False)
        y = np.asarray(y, np.int32) if y is not None else None
        if world_size < 1:
            raise ValueError(f"world_size must be >= 1, got {world_size}")
        if not (0 <= rank < world_size):
            raise ValueError(f"rank {rank} outside world {world_size}")
        if world_size > 1:
            per = len(x) // world_size
            if per == 0:
                raise ValueError(
                    f"dataset of {len(x)} samples shards to 0 per rank "
                    f"at world_size={world_size}; every rank would "
                    "silently iterate zero batches")
            lo = rank * per
            x = x[lo:lo + per]
            y = y[lo:lo + per] if y is not None else None
        self.x = x
        self.y = y
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self._epoch = 0       # epochs fully consumed
        self._batch_idx = 0   # batches consumed within the current epoch
        self._len_warned = False
        if use_native is None:
            use_native = _core.available()
        self._native: Optional[_core.NativeLoader] = None
        if use_native and _core.available():
            self._native = _core.NativeLoader(
                self.x, self.y, batch_size, shuffle=shuffle, seed=seed,
                drop_last=drop_last, workers=workers, prefetch=prefetch)

    def __len__(self) -> int:
        n = len(self.x)
        return n // self.batch_size if self.drop_last else \
            (n + self.batch_size - 1) // self.batch_size

    def __iter__(self) -> Iterator[Tuple[np.ndarray, Optional[np.ndarray]]]:
        # "data.next" injection site: fires per batch draw on both
        # pipelines (error/hang before the yield; nan corrupts the
        # yielded float arrays) — the cursor has already advanced, so a
        # caller that retries past an injected error skips the batch,
        # exactly like a genuinely corrupt shard would be skipped
        from .. import faults
        if self._native is not None:
            for _ in range(len(self) - self._batch_idx):
                try:
                    b = self._native.next()
                except StopIteration:
                    # under-delivery (e.g. concurrent close) ends the epoch
                    # cleanly instead of PEP-479 RuntimeError
                    return
                self._batch_idx += 1
                faults.fire("data.next", epoch=self._epoch,
                            batch=self._batch_idx)
                yield faults.corrupt("data.next", b)
            self._epoch += 1
            self._batch_idx = 0
            return
        idx = np.arange(len(self.x))
        if self.shuffle:
            # seed+epoch: the permutation is a pure function of the
            # cursor, so a resumed loader replays the same epoch order
            np.random.RandomState(self.seed + self._epoch).shuffle(idx)
        for b in range(self._batch_idx, len(self)):
            sel = idx[b * self.batch_size:(b + 1) * self.batch_size]
            if len(sel) == 0:
                break
            self._batch_idx = b + 1
            faults.fire("data.next", epoch=self._epoch, batch=b + 1)
            yield faults.corrupt(
                "data.next",
                (self.x[sel], self.y[sel] if self.y is not None else None))
        self._epoch += 1
        self._batch_idx = 0

    # -- resume (singa_tpu.train orchestrator) ---------------------------
    def state_dict(self) -> dict:
        """The loader's position: everything needed to reproduce the
        remaining data trajectory after a crash."""
        return {"epoch": int(self._epoch), "batch_idx": int(self._batch_idx),
                "seed": int(self.seed), "num_samples": int(len(self.x))}

    def load_state_dict(self, state: dict) -> None:
        """Restore a position captured by :meth:`state_dict`.

        Warns once if the underlying dataset length changed between
        save and load (the shuffle trajectory then runs over different
        data — resumption is best-effort, not bit-reproducible).  The
        native loader cannot seek, so restoring a nonzero position
        falls back to the python pipeline; its numpy permutation
        differs from the native loader's (std::mt19937_64) order, so a
        native→python resume is also best-effort, not bit-identical —
        bitwise resume requires staying on one pipeline
        (``use_native=False``)."""
        import warnings
        n = state.get("num_samples")
        if n is not None and int(n) != len(self.x) and not self._len_warned:
            self._len_warned = True
            warnings.warn(
                f"DataLoader dataset length changed between save "
                f"({int(n)} samples) and load ({len(self.x)}): the "
                f"resumed shuffle trajectory covers different data",
                stacklevel=2)
        self.seed = int(state.get("seed", self.seed))
        self._epoch = int(state.get("epoch", 0))
        self._batch_idx = int(state.get("batch_idx", 0))
        if self._native is not None and (self._epoch or self._batch_idx):
            warnings.warn(
                "DataLoader: native loader cannot seek to a saved "
                "position; resuming on the python pipeline (its shuffle "
                "order differs from the native one — resume is "
                "best-effort, not bit-identical)", stacklevel=2)
            self._native.close()
            self._native = None

    def close(self):
        if self._native is not None:
            self._native.close()
            self._native = None


def prefetch_to_device(it, size: int = 2, device=None):
    """Overlap host->device transfer with device compute: keep `size`
    batches in flight as device arrays ahead of the consumer.

    XLA dispatch is async, so `jax.device_put` returns immediately and
    the DMA proceeds while the previous step computes — the train loop
    then never stalls on input transfer (the classic TPU input-pipeline
    pattern).  Works on tuples/lists/dicts of numpy arrays (None
    passthrough); yields the same structure with jax arrays."""
    import collections

    import jax

    dev = device
    if dev is None:
        from .. import device as device_mod
        dev = device_mod.get_default_device()
    jdev = dev.jax_devices[0] if hasattr(dev, "jax_devices") else dev

    def put(batch):
        return jax.tree.map(
            lambda a: a if a is None else jax.device_put(a, jdev), batch,
            is_leaf=lambda a: a is None)

    q = collections.deque()
    it = iter(it)
    try:
        for _ in range(max(1, size)):
            q.append(put(next(it)))
    except StopIteration:
        pass
    while q:
        out = q.popleft()
        try:
            q.append(put(next(it)))
        except StopIteration:
            pass
        yield out


def synthetic_dataset(kind: str = "blobs", n: int = 1024, classes: int = 10,
                      shape=(32, 32, 3), seed: int = 0):
    """Deterministic synthetic datasets for the example/benchmark scripts
    (the image has no dataset downloads; zero egress)."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, classes, n).astype(np.int32)
    if kind == "blobs":
        d = int(np.prod(shape))
        centers = rng.randn(classes, d).astype(np.float32) * 2.0
        x = centers[y] + rng.randn(n, d).astype(np.float32)
        return x.reshape((n,) + tuple(shape)), y
    if kind == "images":
        x = rng.randn(n, *shape).astype(np.float32)
        # plant a class-dependent low-frequency pattern so models can learn
        for c in range(classes):
            mask = y == c
            x[mask, c % shape[0], :, :] += 2.0
        return x, y
    raise ValueError(kind)
