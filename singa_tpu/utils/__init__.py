"""singa_tpu.utils — checkpointing, metrics, data pipeline, profiling,
failure detection (SURVEY.md §5 auxiliary subsystems)."""

from . import checkpoint
from . import data
from . import failure
from . import metrics
from . import profiler
from . import virtcpu

__all__ = ["checkpoint", "data", "failure", "metrics", "profiler", "virtcpu"]
