"""singa_tpu.utils — checkpointing, metrics, data pipeline."""

from . import checkpoint
from . import metrics

__all__ = ["checkpoint", "metrics"]
