"""Step-timing methodology for tunnel-attached TPU benchmarking.

Two measurements, different questions:

``windowed_steps`` — training throughput: windows of K back-to-back
dispatches with ONE fence at the window end, median over windows.
This is how a real training loop runs (nothing fences per step), so it
is the honest throughput number.  r5 probe 3 (tools/dispatch_probe.py overhead)
showed per-step-fenced timing carries ~30 ms/step of host dispatch
overhead on the tunneled chip that pipelined execution fully hides:
fenced 186.8 ms vs 8-step windows 156.4 ms vs 8 steps compiled into ONE
lax.scan program 160.3 ms — windows and the single-program scan agree,
so the remainder is genuine device time, not dispatch artifact.

``fenced_steps`` — per-dispatch latency diagnostic: every step fenced
individually, median.  Includes the dispatch overhead by construction;
kept for cross-round comparability (the r1-r4 committed numbers used
this) and for spotting weather (a 45 s outlier shows up as max).

Both report medians: the tunnel chip has 200x run-to-run weather (one
committed 45 s step amid 250 ms neighbours, r4), so means are
meaningless and a single block-timed window reports outliers.  With
windows, one congested window inflates one sample and the median over
>=5 windows discards it.
"""

from __future__ import annotations

import statistics
import time
from typing import Callable, Optional

__all__ = ["windowed_steps", "fenced_steps"]


def _block(x) -> None:
    """TRUE fence: fetch the value to the host when `x` is small (the
    loss scalar), fall back to block_until_ready otherwise.

    block_until_ready alone is NOT a reliable fence on the tunneled
    axon backend — r5 probe 3/4 caught it returning in microseconds
    for programs whose FLOPs could not have finished (a 17-GFLOP
    matmul "done" in 25 us; a windowed ResNet read that implied 492
    TFLOP/s on a 197-peak chip).  np.asarray of a scalar is a real
    D2H round trip and cannot lie about completion."""
    import jax
    import numpy as np
    size = getattr(x, "size", None)
    if size is not None and size <= 16:
        np.asarray(x)
        return
    if size is not None and getattr(x, "ndim", 0) > 0:
        # large array (e.g. eval logits): fetch ONE element — the slice
        # depends on the whole buffer being computed, so it is a true
        # fence at 4 bytes of transfer instead of the full tensor
        try:
            np.asarray(x[(0,) * x.ndim])
            return
        except Exception:  # pragma: no cover - exotic array types
            pass
    jax.block_until_ready(x)


def windowed_steps(step: Callable[[], object], *, windows: int = 6,
                   window_len: int = 8, warmup: int = 2,
                   budget_left: Optional[Callable[[], float]] = None,
                   min_budget_s: float = 30.0):
    """Median per-step seconds over `windows` windows of `window_len`
    back-to-back un-fenced steps (true fence at each window end only).

    `step()` runs one training/eval step and returns the object to
    fence on (a jax array — e.g. the loss tensor's ``.data``).
    Returns ``(per_step_seconds, stats)`` where stats carries the raw
    window times and the derived per-step min/median/max in ms.

    The budget is consulted after every dispatch and at window ends —
    on a trip the current window is fenced immediately and kept only
    if no complete window exists (scaled by its actual step count).
    Honest limit: dispatches are async, so a fully-stalled window is
    only detected at its closing fence — worst case one window of
    weather (~8 x the stall) is spent before the trip, vs one step
    under the old per-step-fenced loop.  The median over windows keeps
    such a window out of the reported number either way."""
    out = None
    tripped = False
    for _ in range(warmup):
        out = step()
        if budget_left is not None and budget_left() < min_budget_s:
            tripped = True
            break
    if out is not None:
        _block(out)
    wtimes = []
    partial = None          # (seconds, steps) of an aborted window
    done_steps = 0
    for _ in range(windows):
        # honor the budget only once at least one window exists: the
        # caller (bench.py's driver-parsed headline) must ALWAYS get a
        # number, even if weather drained the budget during warmup
        if tripped and (wtimes or partial):
            break
        t0 = time.perf_counter()
        k = 0
        for _ in range(window_len):
            out = step()
            k += 1
            if budget_left is not None and budget_left() < min_budget_s:
                tripped = True
                break
        _block(out)
        dt = time.perf_counter() - t0
        done_steps += k
        if k == window_len:
            wtimes.append(dt)
        else:
            partial = (dt, k)
    if not wtimes and partial is not None and partial[1] > 0:
        wtimes = [partial[0] / partial[1] * window_len]
    if not wtimes:
        raise RuntimeError("budget exhausted before any timed window")
    wtimes.sort()
    med = statistics.median(wtimes)
    stats = {
        "method": "windowed",
        "window_len": window_len,
        "windows": len(wtimes),
        "n": done_steps,
        "window_ms": [round(t * 1e3, 1) for t in wtimes],
        "min": round(wtimes[0] / window_len * 1e3, 1),
        "median": round(med / window_len * 1e3, 1),
        "max": round(wtimes[-1] / window_len * 1e3, 1),
    }
    _emit_timing_gauge("timing.windowed.step_ms", stats)
    return med / window_len, stats


def fenced_steps(step: Callable[[], object], *, steps: int = 8,
                 warmup: int = 1,
                 budget_left: Optional[Callable[[], float]] = None,
                 min_budget_s: float = 30.0):
    """Median per-step seconds with EVERY step individually fenced
    (per-dispatch latency, r1-r4 methodology).  Returns
    ``(per_step_seconds, stats)``."""
    out = None
    for _ in range(warmup):
        out = step()
    if out is not None:
        _block(out)
    times = []
    for _ in range(steps):
        t0 = time.perf_counter()
        _block(step())
        times.append(time.perf_counter() - t0)
        if budget_left is not None and budget_left() < min_budget_s:
            break
    times.sort()
    stats = {
        "method": "fenced",
        "n": len(times),
        "min": round(times[0] * 1e3, 1),
        "median": round(statistics.median(times) * 1e3, 1),
        "mean": round(sum(times) / len(times) * 1e3, 1),
        "max": round(times[-1] * 1e3, 1),
    }
    _emit_timing_gauge("timing.fenced.step_ms", stats)
    return statistics.median(times), stats


def _emit_timing_gauge(name: str, stats: dict) -> None:
    """Mirror a measurement's summary into the structured telemetry
    stream (obs.events) — no-op unless a sink is enabled."""
    from ..obs import events
    events.gauge(name, stats["median"], method=stats["method"],
                 n=stats["n"], min=stats["min"], max=stats["max"])
