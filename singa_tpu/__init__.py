"""singa_tpu — a TPU-native distributed deep-learning training system.

Scope (reference: /root/reference README.md:1-4 — "Distributed deep
learning training system"; capability contract /root/repo/BASELINE.json:5):
the full SINGA surface — device / tensor / autograd / layer / model /
opt(DistOpt) / sonnx — rebuilt TPU-first on JAX/XLA/Pallas: imperative
Python API on top, single-XLA-module compiled training steps underneath,
collectives over ICI via mesh axes.

The `singa` package alias re-exports these modules so reference user
scripts run with only the device line changed.
"""

__version__ = "0.3.0"

from . import _compat  # jax version shims — must run before submodules
from . import device
from . import proto
from . import tensor
from . import autograd
from . import layer
from . import model
from . import opt
from . import graph
from . import obs
from . import faults  # eager: SINGA_FAULTS env activation happens here
from . import ops
from . import parallel
from . import utils

__all__ = ["device", "proto", "tensor", "autograd", "layer", "model", "opt",
           "graph", "obs", "faults", "ops", "parallel", "utils", "sonnx",
           "models", "serve", "train"]


def __getattr__(name):
    # lazy: sonnx pulls in the onnx proto machinery, models pulls model
    # zoo, serve pulls the inference engine, train pulls the run
    # orchestrator
    if name in ("sonnx", "models", "serve", "train"):
        import importlib
        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(name)
