"""singa_tpu.model — the Model API + graph executor.

Capability parity: ``singa.model`` (BASELINE.json:5,8 — "singa.model
Graph mode").  The user writes an *imperative* subclass:

    class MLP(model.Model):
        def __init__(self): ...
        def forward(self, x): ...
        def train_one_batch(self, x, y):
            out = self.forward(x)
            loss = autograd.softmax_cross_entropy(out, y)
            self.optimizer(loss)
            return out, loss

and ``compile(..., use_graph=True)`` makes ``train_one_batch`` execute as
ONE compiled XLA module: the executor traces the user's Python —
forward, tape backward, optimizer update, and (with DistOpt) the
gradient all-reduce — into a single jitted function with donated
buffers.  That is exactly the north-star execution model
(BASELINE.json:5: "compiles the captured computational graph into a
single XLA HLO module", allreduce "swapped for XLA collectives over
ICI").

Functionalization: parameters/buffers are held in mutable Tensor objects
whose ``.data`` is rebound during the trace; the executor threads them
in and out of the jitted step (SURVEY.md §7.3 items 1–2).  Graph
invalidation: keyed on input shapes/dtypes + train flag; shape change →
re-capture (ibid.).
"""

from __future__ import annotations

import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd
from . import tensor as tensor_mod
from .graph import CapturedGraph
from .obs import attr as obs_attr
from .obs import events as obs_events
from .layer import Layer
from .opt import DistOpt, Optimizer
from .tensor import Tensor

__all__ = ["Model", "Module"]

_live_models = weakref.WeakSet()


def _invalidate_all_graphs():
    for m in list(_live_models):
        m._executors.clear()


class Model(Layer):
    """Base model (reference surface: forward / train_one_batch / loss /
    optimizer / compile / save_states / load_states)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.optimizer: Optional[Optimizer] = None
        self.loss_fn: Optional[Callable] = None
        self.graph_mode = False
        self.sequential = False
        self._training = False
        self._executors: Dict[Any, "_StepExecutor"] = {}
        self._compiled_init = False
        self._base_key = jax.random.PRNGKey(np.random.randint(0, 2**31 - 1))
        self._step_count = 0
        _live_models.add(self)

    # -- reference API --------------------------------------------------------
    def set_optimizer(self, opt: Optimizer) -> None:
        self.optimizer = opt

    def set_loss(self, fn) -> None:
        self.loss_fn = fn

    def loss(self, out, ty):
        if self.loss_fn is not None:
            return self.loss_fn(out, ty)
        return autograd.softmax_cross_entropy(out, ty)

    def train(self, mode: bool = True) -> "Model":
        self._training = mode
        autograd.set_training(mode)
        return self

    def eval(self) -> "Model":
        return self.train(False)

    def compile(self, inputs: List[Tensor], is_train: bool = True,
                use_graph: bool = True, sequential: bool = False) -> None:
        """Initialize parameters from example inputs and arm graph mode.

        `sequential` is accepted for reference compatibility (op ordering
        is XLA's concern here)."""
        self.graph_mode = use_graph
        self.sequential = sequential
        self.train(is_train)
        # materialize lazily-created parameters from the example inputs
        prev = autograd.is_training()
        autograd.set_training(False)
        try:
            import os
            mode = os.environ.get("SINGA_JIT_INIT", "auto")
            accel = self._on_accelerator(inputs)
            pending = self._lazy_uninitialized()
            if not pending and accel and mode != "0":
                # everything already materialized (e.g. a sonnx import):
                # an eager dry-run would replay the whole forward on the
                # device for nothing — on a remote accelerator that is
                # hundreds of round trips
                pass
            elif pending and not self.get_params() and (
                    mode == "1" or (mode == "auto" and accel)):
                self._jit_init(inputs)
            else:
                # eager dry-run (CPU default, and the mixed
                # concrete/lazy fallback)
                self.forward(*inputs)
        finally:
            autograd.set_training(prev)
        self._compiled_init = True
        self._executors.clear()

    def _on_accelerator(self, inputs) -> bool:
        for t in inputs:
            if isinstance(t, Tensor):
                return t.device.is_tpu
        return model_device(self).is_tpu

    def _lazy_uninitialized(self) -> list:
        """Layers that override initialize() and have not run it yet."""
        out = []

        def walk(l):
            if type(l).initialize is not Layer.initialize \
                    and not l._initialized:
                out.append(l)
            for s in l._sublayers.values():
                walk(s)

        walk(self)
        return out

    def _jit_init(self, inputs: List[Tensor]) -> None:
        """Materialize all lazily-initialized parameters in ONE compiled
        XLA program instead of an eager per-op dry run.

        The lazy-init forward is traced under jit with the freshly
        created params/buffers (plus the advanced RNG key) as outputs;
        XLA dead-code-eliminates the activation math nothing depends on,
        so the program that actually compiles and runs is just the
        initializers.  The trace consumes PRNG keys in the same order as
        the eager path, so parameter values match up to XLA fusion
        rounding (FMA gives ~1-ulp differences vs the eager ops).  This
        matters on remote/tunneled TPU backends where every eager
        dispatch is a network round trip (BENCH_r02/r03: eager init +
        dry-run forward dominated the bench window)."""
        example = tuple(t.data if isinstance(t, Tensor) else jnp.asarray(t)
                        for t in inputs)
        # preserve each argument's type: Tensor inputs stay Tensors under
        # the trace, raw arrays stay raw (same contract as the eager path)
        was_tensor = tuple(isinstance(t, Tensor) for t in inputs)
        dev = None
        for t in inputs:
            if isinstance(t, Tensor):
                dev = t.device
                break
        pending = self._lazy_uninitialized()
        saved_key = tensor_mod._rng_key
        if saved_key is None:
            saved_key = jax.random.PRNGKey(0)  # _next_key()'s default

        def init_program(batch, key):
            tensor_mod._rng_key = key
            args = tuple(
                Tensor(data=a, device=dev, requires_grad=False) if w else a
                for a, w in zip(batch, was_tensor))
            self.forward(*args)
            params = {n: t.data for n, t in self.get_params().items()}
            bufs = {n: t.data for n, t in self._get_buffers().items()}
            return params, bufs, tensor_mod._rng_key

        try:
            params, bufs, new_key = jax.jit(init_program)(example, saved_key)
        except Exception as e:
            tensor_mod._rng_key = saved_key
            # a failed trace leaves half-initialized layers holding
            # tracers; reset exactly the layers whose initialize() ran
            # (or could have run) under the trace — not the whole model,
            # which would wipe states registered outside initialize() —
            # then fall back to the eager dry-run so forwards that are
            # not jit-traceable (host-side control flow, .to_numpy())
            # keep compiling exactly as before
            for l in pending:
                l._initialized = False
                l._params.clear()
                l._states.clear()
            import warnings
            warnings.warn(
                f"jit-init trace failed ({type(e).__name__}); falling "
                f"back to the eager init dry-run", stacklevel=3)
            self.forward(*inputs)
            return
        tensor_mod._rng_key = new_key
        # the layer tensors hold leaked tracers from the trace — rebind
        # the concrete results by name
        for n, t in self.get_params().items():
            t.data = params[n]
        for n, t in self._get_buffers().items():
            t.data = bufs[n]

    def train_one_batch(self, x, y, *args):
        """Default train step; override for custom behavior (reference
        requires the override — we provide the canonical body)."""
        if self.optimizer is None:
            raise RuntimeError(
                "no optimizer: call model.set_optimizer(...) before training")
        out = self.forward(x)
        ls = self.loss(out, y)
        self.optimizer.backward_and_update(ls)
        return out, ls

    # -- execution entry points ----------------------------------------------
    def __call__(self, *xs):
        if self.graph_mode and self._compiled_init and not autograd.is_training():
            return self._run_graph("eval", self._eval_body, xs)
        return super().__call__(*xs)

    def train_step(self, *batch):
        """Run train_one_batch — compiled when graph mode is on.

        Telemetry: each call is a ``model.train_step`` span (obs.events;
        host wall clock — dispatch is async, see events docstring)."""
        self.train(True)
        with obs_events.span("model.train_step", model=self.name,
                             step=self._step_count,
                             compiled=self.graph_mode):
            if self.graph_mode:
                return self._run_graph("train", self._train_body, batch)
            out = self.train_one_batch(*batch)
            # the compiled path's executor advances the counter; the
            # eager path must too, or every eager span reports step=0
            self._step_count += 1
            return out

    def _train_body(self, batch_tensors):
        return self.train_one_batch(*batch_tensors)

    def _eval_body(self, batch_tensors):
        return self.forward(*batch_tensors)

    # -- the graph executor ---------------------------------------------------
    def _run_graph(self, tag: str, body, batch):
        arrays = tuple(b.data if isinstance(b, Tensor) else jnp.asarray(b)
                       for b in batch)
        key = tuple((a.shape, str(a.dtype)) for a in arrays) + (tag,)
        ex = self._executors.get(key)
        if ex is None:
            ex = _StepExecutor(self, tag, body, arrays)
            self._executors[key] = ex
        return ex(arrays)

    @property
    def graph(self) -> Optional[CapturedGraph]:
        """Most recently captured step graph."""
        return self.get_graph()

    def get_graph(self, tag: Optional[str] = None) -> Optional[CapturedGraph]:
        """Captured graph, optionally filtered by step kind
        ('train' | 'eval') — a model that ran both has one of each."""
        for ex in self._executors.values():
            if ex.captured is not None and (tag is None or ex.tag == tag):
                return ex.captured
        return None

    # -- state I/O ------------------------------------------------------------
    def save_states(self, fpath: str, aux_states: Optional[Dict] = None) -> None:
        from .utils import checkpoint
        checkpoint.save_states(self, fpath, aux_states)

    def load_states(self, fpath: str) -> Dict:
        from .utils import checkpoint
        return checkpoint.load_states(self, fpath)


# reference exposes the same class as Module in places
Module = Model


def _place(a, s):
    """Put `a` onto sharding `s` (no-op when already placed).

    Multi-host: `s` may span devices of other processes, where
    `device_put` is illegal — every process holds the same host-global
    value (executor contract), so each assembles its addressable shards
    from its own copy via make_array_from_callback."""
    if hasattr(a, "sharding") and a.sharding == s:
        return a
    if s.is_fully_addressable:
        return jax.device_put(a, s)
    import numpy as np
    host = np.asarray(a)
    return jax.make_array_from_callback(host.shape, s,
                                        lambda idx: host[idx])


class _StepExecutor:
    """Traces the model's imperative step into one jitted XLA module.

    Input/output plumbing (all dict-of-arrays pytrees):
      params   — trainable tensors      (donated, returned updated)
      buffers  — non-trainable states   (donated, returned updated)
      slots    — optimizer state        (donated, returned updated)
      step     — optimizer step counter (i32 scalar)
      rng      — PRNG key for dropout etc.
      batch    — the input arrays
    With a mesh + DistOpt, the step runs under shard_map over the mesh:
    batch sharded on axis 0 over 'data', params replicated, gradients
    pmean'ed in-graph by DistOpt.reduce_gradients.
    """

    @classmethod
    def for_planning(cls, model: Model, optimizer, slots_abstract,
                     example_sds) -> "_StepExecutor":
        """Abstract executor for shape-only lowering (parallel.planner):
        same field contract as __init__, but slots come in pre-computed
        (eval_shape'd — opt.init on real zeros would allocate) and no
        placement/compile ever happens."""
        ex = cls.__new__(cls)
        ex.model = model
        ex.tag = "train"
        ex.body = model._train_body
        ex.captured = None
        ex.is_train = True
        ex.param_tensors = dict(model.get_params())
        ex.buffer_tensors = dict(model._get_buffers())
        ex.opt = optimizer
        ex.slots = slots_abstract
        ex._out_treedef = None
        ex._build(example_sds)
        return ex

    def __init__(self, model: Model, tag: str, body, example_arrays):
        self.model = model
        self.tag = tag
        self.body = body
        self.captured: Optional[CapturedGraph] = None
        self.is_train = (tag == "train")

        # stable param/buffer ordering
        params = model.get_params()
        buffers = model._get_buffers()
        self.param_tensors: Dict[str, Tensor] = dict(params)
        self.buffer_tensors: Dict[str, Tensor] = dict(buffers)

        opt = model.optimizer if self.is_train else None
        self.opt = opt
        if opt is not None:
            p_arrays = {n: t.data for n, t in self.param_tensors.items()}
            self.slots = opt.init(p_arrays)
            # resume: a restored checkpoint leaves moment arrays in the
            # optimizer's eager store — seed the compiled-step slots from
            # it so resuming reproduces the uninterrupted trajectory.
            # Copy (not alias): this executor donates its slots, and the
            # source arrays may be another live executor's buffers.
            est = getattr(opt, "_eager_state", None) or {}
            if isinstance(opt, DistOpt) and not est:
                est = getattr(opt.opt, "_eager_state", None) or {}
            for n, restored in est.items():
                if n not in self.slots:
                    continue
                # structured slots (GradAccum's {"acc","base"}) are
                # rebuilt by the optimizer's own load_slot_arrays; here
                # structure must already match exactly
                if not _slot_compatible(restored, self.slots[n]):
                    raise ValueError(
                        f"restored optimizer state for {n!r} does not fit "
                        f"this optimizer/model (structure or shape mismatch) "
                        f"— refusing to silently reinitialize moments")
                self.slots[n] = jax.tree.map(jnp.copy, restored)
        else:
            self.slots = {}

        self._out_treedef = None
        self._build(example_arrays)

    # .....................................................................
    def _traced_step(self, params, buffers, slots, step, rng, batch):
        model, opt = self.model, self.opt
        # bind state into the live tensor objects
        from .parallel import mesh as mesh_mod
        saved_key = tensor_mod._rng_key
        tensor_mod._rng_key = rng
        saved_training = autograd.is_training()
        autograd.set_training(self.is_train)
        # trace-scoped batch-axis name, so ops (ring attention) agree with
        # DistOpt.data_axis no matter when jit re-traces this body
        saved_data_axis = mesh_mod.current_data_axis()
        mesh_mod.set_data_axis(opt.data_axis if isinstance(opt, DistOpt)
                               else "data")
        from .parallel import spmd as spmd_mod
        saved_rules = spmd_mod.current_trace_rules()
        spmd_mod.set_trace_rules(getattr(self, "_rules", None))
        saved_opt_state = None
        saved_param_data = {n: t.data for n, t in self.param_tensors.items()}
        saved_buffer_data = {n: t.data for n, t in self.buffer_tensors.items()}
        try:
            for n, t in self.param_tensors.items():
                t.data = params[n]
            for n, t in self.buffer_tensors.items():
                t.data = buffers[n]
            if opt is not None:
                saved_opt_state = (getattr(opt, "_eager_state", None),
                                   opt.step_counter)
                opt._eager_state = dict(slots)
                opt.step_counter = step
                if isinstance(opt, DistOpt):
                    saved_inner_state = (getattr(opt.opt, "_eager_state", None),
                                         opt.opt.step_counter)
                    opt.opt._eager_state = opt._eager_state
                    opt.opt.step_counter = step

            batch_t = tuple(
                Tensor(data=a, device=model_device(model), requires_grad=False)
                for a in batch)
            outs = self.body(batch_t)

            from .parallel import communicator as comm
            dist = isinstance(opt, DistOpt)
            new_params = {n: t.data for n, t in self.param_tensors.items()}
            new_buffers = {}
            for n, t in self.buffer_tensors.items():
                v = t.data
                if dist:
                    v = comm.allreduce(v, opt.data_axis, "mean")
                new_buffers[n] = v
            if opt is not None:
                src = opt.opt._eager_state if isinstance(opt, DistOpt) else opt._eager_state
                new_slots = {n: src.get(n, self.slots.get(n)) for n in self.slots}
            else:
                new_slots = {}

            out_arrays, treedef = _flatten_outs(outs)
            if dist:
                # replicate scalar outputs (loss) for a consistent view
                out_arrays = [comm.allreduce(a, opt.data_axis, "mean")
                              if a.ndim == 0 else a for a in out_arrays]
            self._out_treedef = treedef
            return tuple(out_arrays), new_params, new_buffers, new_slots
        finally:
            # restore concrete bindings — traces (jit/eval_shape) must not
            # leave tracers in the live tensors/optimizer
            tensor_mod._rng_key = saved_key
            autograd.set_training(saved_training)
            mesh_mod.set_data_axis(saved_data_axis)
            spmd_mod.set_trace_rules(saved_rules)
            for n, t in self.param_tensors.items():
                t.data = saved_param_data[n]
            for n, t in self.buffer_tensors.items():
                t.data = saved_buffer_data[n]
            if opt is not None and saved_opt_state is not None:
                opt._eager_state, opt.step_counter = saved_opt_state
                if isinstance(opt, DistOpt):
                    opt.opt._eager_state, opt.opt.step_counter = saved_inner_state

    # .....................................................................
    def _build(self, example_arrays):
        from .parallel import mesh as mesh_mod

        mesh = mesh_mod.current_mesh()
        data_axis = (self.opt.data_axis if isinstance(self.opt, DistOpt)
                     else "data")
        # multi-axis mesh (TP/SP alongside DP) → GSPMD: jit the global-
        # semantics step with rule-derived param shardings and let XLA
        # insert the collectives.  1-D data mesh + DistOpt → shard_map with
        # explicit in-graph pmean (the reference Communicator path).
        extra = [a for a, n in (mesh.shape.items() if mesh else [])
                 if a != data_axis and n > 1]
        # ZeRO-1 weight-update sharding rides the GSPMD path even on a
        # 1-D data mesh: slot shardings over 'data' make XLA partition
        # the update (reduce-scatter grads / update shard / all-gather).
        # Compressed/sparsified allreduce takes precedence (shard_map).
        from .parallel import spmd as spmd_mod
        zero1 = (isinstance(self.opt, DistOpt)
                 and spmd_mod.zero1_axis_for(self.opt, mesh) is not None)
        gspmd = mesh is not None and (bool(extra) or zero1)
        dist = (not gspmd and isinstance(self.opt, DistOpt)
                and mesh is not None and data_axis in mesh.shape)
        self.dist = dist
        self.gspmd = gspmd
        self.mesh = mesh if (dist or gspmd) else None

        def fn(params, buffers, slots, step, rng, *batch):
            return self._traced_step(params, buffers, slots, step, rng, batch)

        if gspmd:
            from .parallel import spmd
            P = mesh_mod.P
            if isinstance(self.opt, DistOpt) and (
                    self.opt.compress_dtype is not None
                    or self.opt.topk_ratio
                    or self.opt.compression is not None):
                import warnings
                warnings.warn(
                    "DistOpt compressed/sparsified allreduce applies only on "
                    "1-D data-parallel meshes (explicit in-graph pmean); on "
                    "multi-axis meshes GSPMD chooses the collectives and "
                    "these options are ignored", stacklevel=2)
            rules = spmd.collect_shard_rules(self.model)
            self._rules = rules   # trace-scoped handoff (_traced_step)
            rep = mesh_mod.NamedSharding(mesh, P())
            p_arrays = {n: t.data for n, t in self.param_tensors.items()}
            b_arrays = {n: t.data for n, t in self.buffer_tensors.items()}
            self._param_sh = spmd.param_shardings(p_arrays, rules, mesh)
            self._buffer_sh = {n: rep for n in b_arrays}
            self._slot_sh = spmd.tree_shardings(
                self.slots, self._param_sh, mesh,
                {n: a.shape for n, a in p_arrays.items()},
                zero1_axis=data_axis if zero1 else None)
            self._rep_sh = rep
            self._batch_sh = tuple(
                mesh_mod.NamedSharding(
                    mesh, spmd.batch_spec(a.shape, a.dtype, mesh, data_axis))
                for a in example_arrays)
            in_sh = (self._param_sh, self._buffer_sh, self._slot_sh, rep,
                     rep) + self._batch_sh
            # step outputs unconstrained; state pinned to its input
            # shardings so donation reuses buffers and steady state never
            # reshards
            out_sh = (None, self._param_sh, self._buffer_sh, self._slot_sh)
            self._jitted = jax.jit(fn, in_shardings=in_sh,
                                   out_shardings=out_sh,
                                   donate_argnums=(0, 1, 2))
            return

        if dist:
            P = mesh_mod.P
            axis = self.opt.data_axis
            # discover output structure once (abstract eval, no device work)
            shapes = jax.eval_shape(
                fn, {n: t.data for n, t in self.param_tensors.items()},
                {n: t.data for n, t in self.buffer_tensors.items()},
                self.slots, jnp.zeros((), jnp.int32), self.model._base_key,
                *[jax.ShapeDtypeStruct(_shard_shape(a.shape, mesh, axis), a.dtype)
                  for a in example_arrays])
            out_specs_leaves = jax.tree.map(
                lambda s: P() if len(s.shape) == 0 else P(axis), shapes[0])
            # optimizer state is replicated EXCEPT the error-feedback
            # residual of compression="int8_ring": per-rank state with a
            # leading world axis, sharded over 'data' so each rank owns
            # exactly its own slice (replicating it would be wrong, not
            # wasteful — the copies diverge by construction, and a
            # checkpoint would capture rank 0's residual for everyone)
            self._ef_sharded = (isinstance(self.opt, DistOpt)
                                and self.opt.compression is not None)
            slot_specs = ({n: {"base": P(), "ef": P(axis)}
                           for n in self.slots} if self._ef_sharded
                          else P())
            out_specs = (out_specs_leaves, P(), P(), slot_specs)
            in_specs = (P(), P(), slot_specs, P(), P()) + tuple(
                P(axis) for _ in example_arrays)
            wrapped = jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                    out_specs=out_specs, check_vma=False)
        else:
            wrapped = fn

        self._jitted = jax.jit(wrapped, donate_argnums=(0, 1, 2))

    def _attr_program(self) -> str:
        """The runtime-attribution ledger key of this executor's
        program, matching the flagship names the cost model lowers
        (tools/lint/hlo.py FLAGSHIP_PROGRAMS) so the measured and
        modeled halves join: ``train_step`` plain, ``train_step_dp2``
        under DistOpt, ``train_step_dp2_int8`` with the int8 ring.  A
        non-train executor keys as ``<tag>_step`` — visible in the
        live view, dropped from ``perf_attr`` records (no modeled
        side)."""
        key = getattr(self, "_attr_key", None)
        if key is None:
            if not self.is_train:
                key = f"{self.tag}_step"
            elif isinstance(self.opt, DistOpt):
                key = ("train_step_dp2_int8"
                       if getattr(self.opt, "compression", None)
                       == "int8_ring" else "train_step_dp2")
            else:
                key = "train_step"
            self._attr_key = key
        return key

    def __call__(self, batch_arrays):
        m = self.model
        params = {n: t.data for n, t in self.param_tensors.items()}
        buffers = {n: t.data for n, t in self.buffer_tensors.items()}
        # resolve the counter to a host int ONCE, before any device work:
        # the post-step advance must not read the device scalar back
        # (int() of a device array is a blocking D2H round trip — on the
        # tunneled TPU that serialized ~RTT into every step, r5 probe 3)
        step_host = int(self.opt.step_counter if self.opt is not None
                        else m._step_count)
        step = jnp.asarray(step_host, jnp.int32)
        rng = jax.random.fold_in(m._base_key, m._step_count)
        place = _place
        if self.dist:
            # place state replicated / batch data-sharded over the mesh the
            # step was compiled against; no-op after the first step
            # (outputs already carry shardings)
            from .parallel import mesh as mesh_mod
            rep = mesh_mod.NamedSharding(self.mesh, mesh_mod.P())
            shard = mesh_mod.NamedSharding(self.mesh, mesh_mod.P(self.opt.data_axis))
            params = {n: place(a, rep) for n, a in params.items()}
            buffers = {n: place(a, rep) for n, a in buffers.items()}
            if getattr(self, "_ef_sharded", False):
                # error-feedback residuals shard over 'data' (per-rank
                # state); everything else in the slot replicates
                self.slots = {
                    n: {k: (place(v, shard) if k == "ef"
                            else jax.tree.map(lambda a: place(a, rep), v))
                        for k, v in s.items()}
                    for n, s in self.slots.items()}
            else:
                self.slots = jax.tree.map(lambda a: place(a, rep),
                                          self.slots)
            step = place(step, rep)
            rng = place(rng, rep)
            batch_arrays = tuple(place(a, shard) for a in batch_arrays)
        elif self.gspmd:
            # place state/batch onto their rule-derived shardings; no-op
            # after the first step
            params = {n: place(a, self._param_sh[n]) for n, a in params.items()}
            buffers = {n: place(a, self._buffer_sh[n]) for n, a in buffers.items()}
            self.slots = {n: jax.tree.map(place, s, self._slot_sh[n])
                          for n, s in self.slots.items()}
            step = place(step, self._rep_sh)
            rng = place(rng, self._rep_sh)
            batch_arrays = tuple(place(a, s)
                                 for a, s in zip(batch_arrays, self._batch_sh))
        else:
            # plain single-device step, but state may still live on a
            # multi-device mesh from an earlier dist/gspmd executor (e.g.
            # eval compiled after set_mesh(None)) — normalize onto the
            # model's device so jit sees consistent placements
            dev = model_device(m).jax_devices[0]

            def _unshard(a):
                if isinstance(a, jax.Array) and len(a.sharding.device_set) > 1:
                    from .utils.checkpoint import _to_host
                    return jax.device_put(_to_host(a), dev)
                return a

            params = {n: _unshard(a) for n, a in params.items()}
            buffers = {n: _unshard(a) for n, a in buffers.items()}
            self.slots = jax.tree.map(_unshard, self.slots)
        if self.captured is None:
            with obs_events.span("graph.compile",
                                 graph=f"{m.name}.{self.tag}"):
                lowered = self._jitted.lower(params, buffers, self.slots,
                                             step, rng, *batch_arrays)
                compiled = lowered.compile()
            # lazy jaxpr capture (shapes only — safe w.r.t. donation)
            absargs = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                (params, buffers, self.slots, step, rng, tuple(batch_arrays)))

            def jaxpr_thunk(absargs=absargs):
                p, b, s, st, rk, batch = absargs
                return jax.make_jaxpr(
                    lambda *a: self._jitted.__wrapped__(*a[:-1], *a[-1]))(
                        p, b, s, st, rk, batch)

            self.captured = CapturedGraph(f"{m.name}.{self.tag}",
                                          lowered=lowered, compiled=compiled,
                                          jaxpr_thunk=jaxpr_thunk)
        from . import faults
        # "device.execute" injection site: error/hang fire HOST-side
        # before the dispatch (so donated buffers are still intact and
        # the caller's retry can re-dispatch this same step); nan
        # corrupts the step outputs after a clean dispatch
        faults.fire("device.execute", graph=f"{m.name}.{self.tag}",
                    step=step_host)
        # runtime attribution (obs.attr): time the jitted dispatch
        # host-side when a ledger is installed — off path is one global
        # read, no clock, no allocation (the overhead-honesty contract)
        led = obs_attr.get()
        t0 = time.perf_counter() if led is not None else 0.0
        with obs_events.span("graph.execute",
                             graph=f"{m.name}.{self.tag}", step=step_host):
            outs, new_params, new_buffers, new_slots = self._jitted(
                params, buffers, self.slots, step, rng, *batch_arrays)
        if led is not None:
            led.note(self._attr_program(), time.perf_counter() - t0)
        outs = faults.corrupt("device.execute", outs)
        # rebind updated state into the live tensors
        for n, t in self.param_tensors.items():
            t.data = new_params[n]
        for n, t in self.buffer_tensors.items():
            t.data = new_buffers[n]
        self.slots = new_slots
        m._step_count += 1
        if self.opt is not None:
            self.opt.step_counter = step_host + 1
            # mirror compiled-step slots into the optimizer's eager store
            # (reference assignment, no copy) so save_states always sees
            # the live moments regardless of execution mode
            self.opt._eager_state = dict(new_slots)
            if isinstance(self.opt, DistOpt):
                self.opt.opt.step_counter = self.opt.step_counter
                self.opt.opt._eager_state = self.opt._eager_state
        return _unflatten_outs(outs, self._out_treedef, m)


def _slot_compatible(restored, fresh) -> bool:
    """True when a restored slot has the same pytree structure and leaf
    shapes as the freshly initialized one (guards shape/arch mismatch)."""
    if fresh is None:
        return restored is None
    ls_r, td_r = jax.tree.flatten(restored)
    ls_f, td_f = jax.tree.flatten(fresh)
    if td_r != td_f or len(ls_r) != len(ls_f):
        return False
    return all(tuple(a.shape) == tuple(b.shape) for a, b in zip(ls_r, ls_f))


def model_device(model: Model):
    for t in model.get_params().values():
        return t.device
    from . import device as device_mod
    return device_mod.get_default_device()


def _shard_shape(shape, mesh, axis):
    if not shape:
        return shape
    n = mesh.shape[axis]
    s = list(shape)
    s[0] = max(1, s[0] // n)
    return tuple(s)


def _flatten_outs(outs):
    """Tensor-pytree -> list of arrays + treedef."""
    leaves, treedef = jax.tree.flatten(
        outs, is_leaf=lambda x: isinstance(x, Tensor))
    arrays = [l.data if isinstance(l, Tensor) else jnp.asarray(l)
              for l in leaves]
    return arrays, treedef


def _unflatten_outs(arrays, treedef, model):
    from . import device as device_mod
    dev = model_device(model)
    tensors = [Tensor(data=a, device=dev, requires_grad=False)
               for a in arrays]
    return jax.tree.unflatten(treedef, tensors)
