"""JAX version compatibility shims, applied at singa_tpu import time.

This image family pins different jax versions across rounds; the code
(and the test suite) targets the modern public API.  Shims only ever
ADD missing attributes — a jax that already provides the API is left
completely untouched.

* ``jax.shard_map`` — promoted from ``jax.experimental.shard_map`` on
  jax < 0.4.35-era builds, adapting the modern ``check_vma`` kwarg to
  the older ``check_rep`` spelling.  Without this, every
  shard_map-based path (the dist executor, pipeline/spmd tests, the
  multiprocess workers) fails with AttributeError on such images.
* ``jax.lax.axis_size`` — the modern static axis-size query; on older
  builds ``jax.core.axis_frame(name)`` carries the same static int.
"""

from __future__ import annotations

import jax

__all__ = ["apply", "jax_version_tuple", "legacy_jax"]


def jax_version_tuple() -> tuple:
    """(major, minor) of the running jax, robust to suffixes."""
    parts = []
    for p in jax.__version__.split(".")[:2]:
        digits = "".join(ch for ch in p if ch.isdigit())
        parts.append(int(digits or 0))
    return tuple(parts)


def legacy_jax() -> bool:
    """True on the jax-0.4.x-era images this repo's growth containers
    pin.  Gates the known pre-existing failures those builds cannot
    pass (ZeRO-1 donation aliasing under GSPMD; old shard_map gradient
    semantics in the pipeline schedule) behind non-strict xfail markers
    so tier-1 signal stays clean there while the tests still run — and
    must pass — on modern jax."""
    return jax_version_tuple() < (0, 5)


def apply() -> None:
    if not hasattr(jax, "shard_map"):
        try:
            from jax.experimental.shard_map import shard_map as _shard_map
        except ImportError:  # pragma: no cover - very old jax
            return

        def shard_map(f=None, /, *, mesh=None, in_specs=None,
                      out_specs=None, check_vma=None, **kw):
            if check_vma is not None and "check_rep" not in kw:
                kw["check_rep"] = check_vma

            def bind(fn):
                return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                                  out_specs=out_specs, **kw)

            # modern API supports both direct and decorator usage
            return bind if f is None else bind(f)

        jax.shard_map = shard_map

    if not hasattr(jax.lax, "axis_size"):
        def axis_size(axis_name):
            frame = jax.core.axis_frame(axis_name)
            # modern axis_frame returns a frame object; this era an int
            return frame.size if hasattr(frame, "size") else int(frame)

        jax.lax.axis_size = axis_size


apply()
