"""CNN family — the reference examples/cnn small models (BASELINE.json:7-8).

Data format is NHWC throughout (TPU-native; XLA tiles the channel-last
conv directly onto the MXU).  Models accept NHWC input; pass
``data_format="NCHW"`` for reference/ONNX-layout inputs.
"""

from __future__ import annotations

from .. import layer
from ._base import Classifier

__all__ = ["CNN", "LeNet5", "AlexNet", "create_model"]


class CNN(Classifier):
    """The reference's simple MNIST CNN: two conv+pool blocks + two FC."""

    def __init__(self, num_classes: int = 10, data_format: str = "NHWC"):
        super().__init__()
        df = data_format
        self.conv1 = layer.Conv2d(32, 3, stride=1, padding=1, data_format=df)
        self.relu1 = layer.ReLU()
        self.pool1 = layer.MaxPool2d(2, 2, data_format=df)
        self.conv2 = layer.Conv2d(64, 3, stride=1, padding=1, data_format=df)
        self.relu2 = layer.ReLU()
        self.pool2 = layer.MaxPool2d(2, 2, data_format=df)
        self.flat = layer.Flatten()
        self.fc1 = layer.Linear(128)
        self.relu3 = layer.ReLU()
        self.fc2 = layer.Linear(num_classes)

    def forward(self, x):
        x = self.pool1(self.relu1(self.conv1(x)))
        x = self.pool2(self.relu2(self.conv2(x)))
        x = self.relu3(self.fc1(self.flat(x)))
        return self.fc2(x)


class LeNet5(Classifier):
    def __init__(self, num_classes: int = 10, data_format: str = "NHWC"):
        super().__init__()
        df = data_format
        self.conv1 = layer.Conv2d(6, 5, padding=2, data_format=df)
        self.pool1 = layer.AvgPool2d(2, 2, data_format=df)
        self.conv2 = layer.Conv2d(16, 5, data_format=df)
        self.pool2 = layer.AvgPool2d(2, 2, data_format=df)
        self.act = layer.Tanh()
        self.flat = layer.Flatten()
        self.fc1 = layer.Linear(120)
        self.fc2 = layer.Linear(84)
        self.head = layer.Linear(num_classes)

    def forward(self, x):
        x = self.pool1(self.act(self.conv1(x)))
        x = self.pool2(self.act(self.conv2(x)))
        x = self.flat(x)
        x = self.act(self.fc1(x))
        x = self.act(self.fc2(x))
        return self.head(x)


class AlexNet(Classifier):
    """AlexNet sized for 224x224 inputs (reference examples/cnn alexnet)."""

    def __init__(self, num_classes: int = 1000, data_format: str = "NHWC",
                 dropout: float = 0.5):
        super().__init__()
        df = data_format
        self.features = layer.Sequential(
            layer.Conv2d(64, 11, stride=4, padding=2, data_format=df),
            layer.ReLU(),
            layer.MaxPool2d(3, 2, data_format=df),
            layer.Conv2d(192, 5, padding=2, data_format=df),
            layer.ReLU(),
            layer.MaxPool2d(3, 2, data_format=df),
            layer.Conv2d(384, 3, padding=1, data_format=df),
            layer.ReLU(),
            layer.Conv2d(256, 3, padding=1, data_format=df),
            layer.ReLU(),
            layer.Conv2d(256, 3, padding=1, data_format=df),
            layer.ReLU(),
            layer.MaxPool2d(3, 2, data_format=df),
        )
        self.flat = layer.Flatten()
        self.drop1 = layer.Dropout(dropout)
        self.fc1 = layer.Linear(4096)
        self.relu1 = layer.ReLU()
        self.drop2 = layer.Dropout(dropout)
        self.fc2 = layer.Linear(4096)
        self.relu2 = layer.ReLU()
        self.head = layer.Linear(num_classes)

    def forward(self, x):
        x = self.flat(self.features(x))
        x = self.relu1(self.fc1(self.drop1(x)))
        x = self.relu2(self.fc2(self.drop2(x)))
        return self.head(x)


def create_model(model_name: str = "cnn", **kwargs):
    """Reference factory (examples/cnn/train_cnn.py model selection)."""
    zoo = {"cnn": CNN, "lenet": LeNet5, "alexnet": AlexNet}
    return zoo[model_name.lower()](**kwargs)
