"""VGG — the reference CIFAR alternative backbone (BASELINE.json:8)."""

from __future__ import annotations

from typing import List, Union

from .. import layer
from ._base import Classifier

__all__ = ["VGG", "vgg11", "vgg13", "vgg16", "vgg19", "create_model"]

_CFGS = {
    "vgg11": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "vgg13": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
              512, 512, "M"],
    "vgg16": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
              "M", 512, 512, 512, "M"],
    "vgg19": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512,
              512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(Classifier):
    def __init__(self, cfg: List[Union[int, str]], num_classes: int = 10,
                 batch_norm: bool = True):
        super().__init__()
        blocks = []
        for v in cfg:
            if v == "M":
                blocks.append(layer.MaxPool2d(2, 2))
            else:
                blocks.append(layer.Conv2d(v, 3, padding=1,
                                           bias=not batch_norm))
                if batch_norm:
                    blocks.append(layer.BatchNorm2d(v))
                blocks.append(layer.ReLU())
        self.features = layer.Sequential(*blocks)
        self.pool = layer.GlobalAvgPool2d()
        self.head = layer.Linear(num_classes)

    def forward(self, x):
        return self.head(self.pool(self.features(x)))


def vgg11(num_classes=10, batch_norm=True) -> VGG:
    return VGG(_CFGS["vgg11"], num_classes, batch_norm)


def vgg13(num_classes=10, batch_norm=True) -> VGG:
    return VGG(_CFGS["vgg13"], num_classes, batch_norm)


def vgg16(num_classes=10, batch_norm=True) -> VGG:
    return VGG(_CFGS["vgg16"], num_classes, batch_norm)


def vgg19(num_classes=10, batch_norm=True) -> VGG:
    return VGG(_CFGS["vgg19"], num_classes, batch_norm)


def create_model(model_name: str = "vgg16", **kwargs) -> VGG:
    return VGG(_CFGS[model_name.lower()], **kwargs)
