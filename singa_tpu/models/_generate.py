"""Autoregressive generation with a static KV cache (VERDICT r2 item 4).

TPU-native decode loop: one jitted `prefill` (prompt forward — flash
attention when the shape tiles — plus cache write) and one jitted
`decode` (Tq=1 against the full cache, position passed as a traced
scalar), so the per-token cost is O(S_max) and INDEPENDENT of how many
tokens have been generated — each decode step re-executes the same
compiled module with a different `pos` value.  Contrast with the r2
`examples/onnx/gpt2.py` loop, which re-ran the full fixed-length
forward per token (O(P^2) total).

Parameters are threaded through jit as arguments (same rebinding
pattern as model._StepExecutor._traced_step) so weights are NOT baked
into the executable as constants.
"""

from __future__ import annotations

import functools
from contextlib import contextmanager
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import autograd
from ..tensor import Tensor

__all__ = ["GenerateMixin"]


@contextmanager
def _bound(model, params: Dict, buffers: Dict):
    from .. import tensor as tensor_mod
    ptens = model.get_params()
    btens = model._get_buffers()
    saved_p = {n: t.data for n, t in ptens.items()}
    saved_b = {n: t.data for n, t in btens.items()}
    saved_training = autograd.is_training()
    saved_key = tensor_mod._rng_key   # any in-trace split must not leak
    autograd.set_training(False)
    try:
        for n, t in ptens.items():
            t.data = params[n]
        for n, t in btens.items():
            t.data = buffers[n]
        yield
    finally:
        autograd.set_training(saved_training)
        tensor_mod._rng_key = saved_key
        for n, t in ptens.items():
            t.data = saved_p[n]
        for n, t in btens.items():
            t.data = saved_b[n]


class _GenSession:
    """Compiled prefill + decode pair for one (batch, prompt, total) shape."""

    def __init__(self, model, batch: int, prompt_len: int, total_len: int):
        self.model = model
        self.total_len = total_len

        def prefill(params, buffers, ids):
            with _bound(model, params, buffers):
                t = Tensor(data=ids, device=_dev(model), requires_grad=False)
                logits, caches = model.forward_cached(
                    t, caches=model.init_caches(batch, total_len), pos=0)
            return logits.data[:, -1, :], caches

        def decode(params, buffers, tok, pos, caches):
            with _bound(model, params, buffers):
                t = Tensor(data=tok, device=_dev(model), requires_grad=False)
                logits, caches = model.forward_cached(t, caches=caches,
                                                      pos=pos)
            return logits.data[:, 0, :], caches

        self.prefill = jax.jit(prefill)
        self.decode = jax.jit(decode, donate_argnums=(4,))


def _dev(model):
    from ..model import model_device
    return model_device(model)


@functools.partial(jax.jit, static_argnums=(1, 3, 4))
def _pick(logits, temperature: float, rng_key, top_k: Optional[int],
          top_p: Optional[float]):
    """Greedy (temperature 0) or sampled pick with optional top-k /
    nucleus (top-p) filtering.  Jitted with the controls static so the
    whole selection is ONE dispatch per decoded token — eager filtering
    would reintroduce the per-token round-trip cost the compiled
    prefill/decode design exists to avoid."""
    if not temperature or temperature <= 0.0:
        return jnp.argmax(logits, axis=-1)
    lg = logits.astype(jnp.float32) / temperature
    if top_k is not None and 0 < top_k < lg.shape[-1]:
        kth = jax.lax.top_k(lg, top_k)[0][:, -1:]
        lg = jnp.where(lg < kth, -jnp.inf, lg)
    if top_p is not None and 0.0 < top_p < 1.0:
        sorted_lg = jnp.sort(lg, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_lg, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative mass >= top_p (the
        # first token is always kept); the cutoff is the SMALLEST kept
        # logit — everything below it is masked
        keep = cum - probs < top_p
        cutoff = jnp.min(jnp.where(keep, sorted_lg, jnp.inf), axis=-1,
                         keepdims=True)
        lg = jnp.where(lg < cutoff, -jnp.inf, lg)
    return jax.random.categorical(rng_key, lg, axis=-1)


class GenerateMixin:
    """Adds `generate()` to decoder models exposing
    `forward_cached(ids, caches, pos)` and `init_caches(batch, max_len)`."""

    def generate(self, prompt_ids, max_new_tokens: int,
                 temperature: float = 0.0, seed: int = 0,
                 eos_id: Optional[int] = None, top_k: Optional[int] = None,
                 top_p: Optional[float] = None) -> np.ndarray:
        """Greedy (temperature=0) or sampled decoding, with optional
        top-k and/or nucleus (top-p) filtering when sampling.

        prompt_ids: int array (B, P). Always returns (B, P +
        max_new_tokens) — static shape. When `eos_id` is given and every
        row has emitted it, decoding stops early and the remaining
        positions are filled with eos_id; per-row truncation is the
        caller's job."""
        ids = np.asarray(prompt_ids)
        if ids.ndim == 1:
            ids = ids[None, :]
        B, P = ids.shape
        S = P + max_new_tokens
        max_pos = getattr(getattr(self, "cfg", None), "max_position", None)
        if max_pos is not None and S > max_pos:
            # positions past max_position would silently clamp inside jit
            # (embedding gather / RoPE-table dynamic_slice) — refuse loudly
            raise ValueError(
                f"prompt ({P}) + max_new_tokens ({max_new_tokens}) = {S} "
                f"exceeds the model's max_position ({max_pos})")
        key = (B, P, S)
        sessions = getattr(self, "_gen_sessions", None)
        if sessions is None:
            sessions = self._gen_sessions = {}
        sess = sessions.get(key)
        if sess is None:
            sess = sessions[key] = _GenSession(self, B, P, S)

        params = {n: t.data for n, t in self.get_params().items()}
        buffers = {n: t.data for n, t in self._get_buffers().items()}
        rng = jax.random.PRNGKey(seed)

        out = np.zeros((B, S), np.int32)
        out[:, :P] = ids
        logits, caches = sess.prefill(params, buffers,
                                      jnp.asarray(ids, jnp.int32))
        done = np.zeros((B,), bool)
        for i in range(max_new_tokens):
            rng, sub = jax.random.split(rng)
            tok = _pick(logits, temperature, sub, top_k, top_p)
            out[:, P + i] = np.asarray(tok)
            if eos_id is not None:
                done |= out[:, P + i] == eos_id
                if bool(np.all(done)):
                    out[:, P + i + 1:] = eos_id   # keep the static shape
                    break
            if i + 1 < max_new_tokens:
                logits, caches = sess.decode(
                    params, buffers, tok[:, None].astype(jnp.int32),
                    jnp.asarray(P + i, jnp.int32), caches)
        return out
