"""Autoregressive generation with a static KV cache (VERDICT r2 item 4).

TPU-native decode loop: one jitted `prefill` (prompt forward — flash
attention when the shape tiles — plus cache write) and one jitted
`decode` (Tq=1 against the full cache, position passed as a traced
scalar), so the per-token cost is O(S_max) and INDEPENDENT of how many
tokens have been generated — each decode step re-executes the same
compiled module with a different `pos` value.  Contrast with the r2
`examples/onnx/gpt2.py` loop, which re-ran the full fixed-length
forward per token (O(P^2) total).

Parameters are threaded through jit as arguments (same rebinding
pattern as model._StepExecutor._traced_step) so weights are NOT baked
into the executable as constants.
"""

from __future__ import annotations

import functools
from contextlib import contextmanager
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import autograd
from ..tensor import Tensor

__all__ = ["GenerateMixin", "prefill_step", "decode_step", "resume_step"]


@contextmanager
def _bound(model, params: Dict, buffers: Dict):
    from .. import tensor as tensor_mod
    ptens = model.get_params()
    btens = model._get_buffers()
    saved_p = {n: t.data for n, t in ptens.items()}
    saved_b = {n: t.data for n, t in btens.items()}
    saved_training = autograd.is_training()
    saved_key = tensor_mod._rng_key   # any in-trace split must not leak
    autograd.set_training(False)
    try:
        for n, t in ptens.items():
            t.data = params[n]
        for n, t in btens.items():
            t.data = buffers[n]
        yield
    finally:
        autograd.set_training(saved_training)
        tensor_mod._rng_key = saved_key
        for n, t in ptens.items():
            t.data = saved_p[n]
        for n, t in btens.items():
            t.data = saved_b[n]


def prefill_step(model, total_len: int, last_only: bool = True):
    """Build the prompt-forward closure shared by `_GenSession` and the
    serving engine (serve.engine): (params, buffers, ids (B, P)) ->
    (logits, caches) with fresh (B, total_len) caches written for
    positions [0, P).  `last_only` returns just the last position's
    (B, V) logits (the generate() path); the engine keeps the full
    (B, P, V) block so it can gather at each request's true length
    inside its own jitted wrapper."""

    def prefill(params, buffers, ids):
        with _bound(model, params, buffers):
            t = Tensor(data=ids, device=_dev(model), requires_grad=False)
            logits, caches = model.forward_cached(
                t, caches=model.init_caches(ids.shape[0], total_len), pos=0)
        lg = logits.data
        return (lg[:, -1, :] if last_only else lg), caches

    return prefill


def decode_step(model):
    """Build the one-token decode closure shared by `_GenSession` and
    the serving engine: (params, buffers, tok (B, 1), pos, caches) ->
    (logits (B, V), caches).  `pos` may be a traced scalar (all rows at
    the same depth — generate()) or a traced (B,) vector (every slot at
    its own depth — serve.engine); the ops layer (rope offset, cache
    scatter, per-row attention limit) handles both inside ONE compiled
    program."""

    def decode(params, buffers, tok, pos, caches):
        with _bound(model, params, buffers):
            t = Tensor(data=tok, device=_dev(model), requires_grad=False)
            logits, caches = model.forward_cached(t, caches=caches,
                                                  pos=pos)
        return logits.data[:, 0, :], caches

    return decode


def resume_step(model):
    """Build the chunked-prefill closure of the paged serving engine
    (serve.engine): (params, buffers, ids (B, C), pos, caches) ->
    (logits (B, C, V), caches).  Unlike :func:`prefill_step` it takes
    the CALLER's caches and a traced scalar ``pos`` offset, so a prompt
    prefills as a sequence of fixed-(B, C) chunks — each chunk writes
    its k/v at [pos, pos+C) and attends the cache below ``pos + C``
    (``cached_sdpa``'s bottom-right-aligned causal window), which is
    what lets a shared-prefix request skip the chunks that are already
    resident in the arena."""

    def resume(params, buffers, ids, pos, caches):
        with _bound(model, params, buffers):
            t = Tensor(data=ids, device=_dev(model), requires_grad=False)
            logits, caches = model.forward_cached(t, caches=caches,
                                                  pos=pos)
        return logits.data, caches

    return resume


class _GenSession:
    """Compiled prefill + whole-generation programs for one
    (batch, prompt, total) shape.

    `decode` is the single-token program (a building block for custom
    host-driven loops); `decode_all_fn` / `beam_all_fn` return
    whole-generation programs — pick/select + decode for all N tokens
    under ONE lax.scan, so generation is exactly two dispatches
    (prefill, decode_all) and one host fetch.  The per-token host
    round-trip a host-driven loop pays (fetch tok, enqueue next step)
    dominates on a remote-attached device (r4 measurement: 74 ms/token
    of ~70 ms tunnel RTT)."""

    def __init__(self, model, batch: int, prompt_len: int, total_len: int):
        self.model = model
        self.prompt_len = prompt_len
        self.total_len = total_len
        self._decode_all_cache: Dict = {}
        self._beam_all_cache: Dict = {}
        # prefill/decode closures shared with serve.engine (one source
        # of truth for the cached forward — the engine's greedy decode
        # is token-identical by construction)
        self.prefill = jax.jit(prefill_step(model, total_len))
        self.decode = jax.jit(decode_step(model), donate_argnums=(4,))

    def decode_all_fn(self, n: int, temperature: float,
                      top_k: Optional[int], top_p: Optional[float],
                      eos_id: Optional[int]):
        """Jitted (params, buffers, logits0, caches, rng) -> (B, n)
        tokens: the full pick→decode loop as one lax.scan.  Sampling
        controls are trace-time constants (same cache-key discipline as
        _pick's static_argnums).  eos semantics match the host loop:
        rows keep decoding until EVERY row has emitted eos, then the
        remaining positions emit eos."""
        key = (n, temperature, top_k, top_p, eos_id)
        fn = self._decode_all_cache.get(key)
        if fn is not None:
            return fn
        model, P = self.model, self.prompt_len

        def decode_all(params, buffers, logits0, caches, rng):
            def body(carry, _):
                logits, pos, caches, rng, done, stopped = carry
                rng, sub = jax.random.split(rng)
                tok = _pick_impl(logits, temperature, sub, top_k, top_p)
                if eos_id is not None:
                    tok = jnp.where(stopped, eos_id, tok)
                    done = done | (tok == eos_id)
                    stopped = jnp.all(done)
                tok = tok.astype(jnp.int32)

                # the final iteration's decode fills cache slot
                # total_len-1 and its logits go unused — still in bounds
                def step(args):
                    logits, caches = args
                    with _bound(model, params, buffers):
                        t = Tensor(data=tok[:, None], device=_dev(model),
                                   requires_grad=False)
                        nxt, caches = model.forward_cached(
                            t, caches=caches, pos=pos)
                    # canonical f32 carry: prefill and decode logits
                    # dtypes can differ (param_dtype casts), and scan /
                    # cond require a stable carry type
                    return nxt.data[:, 0, :].astype(jnp.float32), caches

                if eos_id is not None:
                    # once every row has finished, skip the forward
                    # entirely — the scan still iterates but each
                    # remaining tick is a no-op branch, preserving the
                    # old host loop's early-exit cost profile
                    logits, caches = jax.lax.cond(
                        stopped, lambda args: args, step, (logits, caches))
                else:
                    logits, caches = step((logits, caches))
                return (logits, pos + 1, caches, rng, done, stopped), tok

            B = logits0.shape[0]
            carry = (logits0.astype(jnp.float32),
                     jnp.asarray(P, jnp.int32), caches, rng,
                     jnp.zeros((B,), bool), jnp.asarray(False))
            _, toks = jax.lax.scan(body, carry, None, length=n)
            return jnp.swapaxes(toks, 0, 1)

        # no donate_argnums: caches are not among decode_all's outputs,
        # so XLA cannot alias them (it would just warn) — they die
        # inside the program after their last scan iteration anyway
        fn = jax.jit(decode_all)
        self._decode_all_cache[key] = fn
        return fn

    def beam_all_fn(self, n: int, num_beams: int, eos_id: Optional[int]):
        """Jitted (params, buffers, logits0, caches) ->
        (seqs (B,K,n), scores (B,K), done (B,K), gen_len (B,K)): the
        full beam-search loop — select, beam bookkeeping, cache
        reorder, decode — as one lax.scan.  Semantics mirror the old
        host-driven loop exactly: frozen beams expand only to eos at
        zero incremental score, the cache gather is skipped (runtime
        lax.cond) when every beam kept its slot, and once every beam of
        every row is done the remaining ticks are no-ops."""
        key = (n, num_beams, eos_id)
        fn = self._beam_all_cache.get(key)
        if fn is not None:
            return fn
        model, P, K = self.model, self.prompt_len, num_beams

        def beam_all(params, buffers, logits0, caches):
            BK = logits0.shape[0]
            B = BK // K
            offsets = (jnp.arange(B)[:, None] * K).astype(jnp.int32)
            arangeK = jnp.arange(K, dtype=jnp.int32)

            def tick(carry, i):
                logits, scores, caches, seqs, done, gen_len, stopped = carry
                beam_idx, tok, scores = _beam_select(
                    logits, scores, K,
                    done if eos_id is not None else None,
                    eos_id)
                gather = jnp.take_along_axis
                seqs = gather(seqs, beam_idx[:, :, None], axis=1)
                done = gather(done, beam_idx, axis=1)
                gen_len = gather(gen_len, beam_idx, axis=1)
                seqs = seqs.at[:, :, i].set(tok.astype(jnp.int32))
                if eos_id is not None:
                    # length counts the eos token itself, then freezes
                    gen_len = jnp.where(done, gen_len, i + 1)
                    done = done | (tok == eos_id)
                else:
                    gen_len = jnp.full_like(gen_len, i + 1)

                def advance(args):
                    logits, caches = args

                    def reorder(caches):
                        perm = (beam_idx + offsets).reshape(-1)
                        return _beam_reorder(caches, perm)

                    # skip the full-cache gather when every beam kept
                    # its own slot (always true at K=1)
                    caches = jax.lax.cond(
                        jnp.any(beam_idx != arangeK[None, :]),
                        reorder, lambda c: c, caches)
                    with _bound(model, params, buffers):
                        t = Tensor(data=tok.reshape(-1, 1).astype(
                            jnp.int32), device=_dev(model),
                            requires_grad=False)
                        nxt, caches = model.forward_cached(
                            t, caches=caches, pos=P + i)
                    return nxt.data[:, 0, :].astype(jnp.float32), caches

                if eos_id is not None:
                    # every beam of every row just finished: skip the
                    # reorder + decode, like the old host loop's break
                    stopped = jnp.all(done)
                    logits, caches = jax.lax.cond(
                        stopped, lambda args: args, advance,
                        (logits, caches))
                else:
                    logits, caches = advance((logits, caches))
                return (logits, scores, caches, seqs, done, gen_len,
                        stopped), None

            def body(carry, i):
                if eos_id is None:
                    return tick(carry, i)
                # all beams of all rows finished: every remaining tick
                # is a no-op (the old host loop broke here)
                stopped = carry[-1]
                carry, _ = jax.lax.cond(
                    stopped, lambda c, _i: (c, None), tick, carry, i)
                stopped = jnp.all(carry[4])
                return carry[:-1] + (stopped,), None

            # before the first expansion all K beams are identical:
            # only beam 0 may seed the frontier
            scores0 = jnp.full((B, K), -jnp.inf,
                               jnp.float32).at[:, 0].set(0.0)
            carry = (logits0.astype(jnp.float32), scores0, caches,
                     jnp.zeros((B, K, n), jnp.int32),
                     jnp.zeros((B, K), bool),
                     jnp.zeros((B, K), jnp.int32),
                     jnp.asarray(False))
            carry, _ = jax.lax.scan(body, carry,
                                    jnp.arange(n, dtype=jnp.int32))
            _, scores, _, seqs, done, gen_len, _ = carry
            return seqs, scores, done, gen_len

        fn = jax.jit(beam_all)
        self._beam_all_cache[key] = fn
        return fn


def _dev(model):
    from ..model import model_device
    return model_device(model)


def _pick_impl(logits, temperature: float, rng_key, top_k: Optional[int],
               top_p: Optional[float]):
    """Greedy (temperature 0) or sampled pick with optional top-k /
    nucleus (top-p) filtering.  The controls are trace-time constants
    (closed-over inside decode_all's scan body)."""
    if not temperature or temperature <= 0.0:
        return jnp.argmax(logits, axis=-1)
    lg = logits.astype(jnp.float32) / temperature
    if top_k is not None and 0 < top_k < lg.shape[-1]:
        kth = jax.lax.top_k(lg, top_k)[0][:, -1:]
        lg = jnp.where(lg < kth, -jnp.inf, lg)
    if top_p is not None and 0.0 < top_p < 1.0:
        sorted_lg = jnp.sort(lg, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_lg, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative mass >= top_p (the
        # first token is always kept); the cutoff is the SMALLEST kept
        # logit — everything below it is masked
        keep = cum - probs < top_p
        cutoff = jnp.min(jnp.where(keep, sorted_lg, jnp.inf), axis=-1,
                         keepdims=True)
        lg = jnp.where(lg < cutoff, -jnp.inf, lg)
    return jax.random.categorical(rng_key, lg, axis=-1)


def _beam_select(logits, scores, k: int, done=None, eos_id=None):
    """One beam-search expansion (traced inside beam_all_fn's scan):
    combine the (B*K, V) next-token logits with the (B, K) running
    scores, flatten each batch's K*V candidates, and keep the top K.  A
    finished beam (done mask + eos_id) admits only eos at zero
    incremental cost, so its raw score freezes.  Returns
    (beam_idx (B,K), tok (B,K), new_scores (B,K))."""
    B, K = scores.shape
    V = logits.shape[-1]
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    lp = lp.reshape(B, K, V)
    if done is not None:
        eos_row = jnp.where(jnp.arange(V) == eos_id, 0.0, -jnp.inf)
        lp = jnp.where(done[:, :, None], eos_row, lp)
    cand = scores[:, :, None] + lp
    top, flat_idx = jax.lax.top_k(cand.reshape(B, K * V), k)
    return flat_idx // V, flat_idx % V, top


def _beam_reorder(caches, perm):
    """Gather the KV caches onto the surviving beams (batch axis 0);
    traced inside beam_all_fn's scan."""
    return jax.tree.map(lambda c: jnp.take(c, perm, axis=0), caches)


class GenerateMixin:
    """Adds `generate()` to decoder models exposing
    `forward_cached(ids, caches, pos)` and `init_caches(batch, max_len)`."""

    def _gen_setup(self, prompt_ids, max_new_tokens: int, rows_mult: int,
                   param_dtype=None):
        """Shared session/validation preamble for generate/generate_beam:
        normalize the prompt, enforce max_position, fetch-or-compile the
        (rows, P, S) session, and snapshot params/buffers.

        `param_dtype` (e.g. jnp.bfloat16) casts the float params ONCE
        for the whole generation — decode is weight-read bound, so bf16
        weights halve the per-token HBM traffic vs streaming f32
        masters through the cast inside the step."""
        ids = np.asarray(prompt_ids)
        if ids.ndim == 1:
            ids = ids[None, :]
        B, P = ids.shape
        S = P + max_new_tokens
        max_pos = getattr(getattr(self, "cfg", None), "max_position", None)
        if max_pos is not None and S > max_pos:
            # positions past max_position would silently clamp inside jit
            # (embedding gather / RoPE-table dynamic_slice) — refuse loudly
            raise ValueError(
                f"prompt ({P}) + max_new_tokens ({max_new_tokens}) = {S} "
                f"exceeds the model's max_position ({max_pos})")
        sessions = getattr(self, "_gen_sessions", None)
        if sessions is None:
            sessions = self._gen_sessions = {}
        key = (B * rows_mult, P, S)
        sess = sessions.get(key)
        if sess is None:
            sess = sessions[key] = _GenSession(self, B * rows_mult, P, S)
        params = {n: t.data for n, t in self.get_params().items()}
        buffers = {n: t.data for n, t in self._get_buffers().items()}
        if param_dtype is not None:
            params = {n: (a.astype(param_dtype)
                          if jnp.issubdtype(a.dtype, jnp.floating) else a)
                      for n, a in params.items()}
        return ids, B, P, S, sess, params, buffers

    def generate(self, prompt_ids, max_new_tokens: int,
                 temperature: float = 0.0, seed: int = 0,
                 eos_id: Optional[int] = None, top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 param_dtype=None) -> np.ndarray:
        """Greedy (temperature=0) or sampled decoding, with optional
        top-k and/or nucleus (top-p) filtering when sampling.

        prompt_ids: int array (B, P). Always returns (B, P +
        max_new_tokens) — static shape. When `eos_id` is given and every
        row has emitted it, the remaining positions are filled with
        eos_id; per-row truncation is the caller's job.

        The whole pick→decode loop runs as ONE jitted lax.scan
        (sess.decode_all_fn): two dispatches and one host fetch per
        generation, independent of max_new_tokens — a host-driven
        per-token loop pays a device round-trip per token, which
        dominates on a remote-attached device."""
        ids, B, P, S, sess, params, buffers = self._gen_setup(
            prompt_ids, max_new_tokens, 1, param_dtype)
        rng = jax.random.PRNGKey(seed)

        logits, caches = sess.prefill(params, buffers,
                                      jnp.asarray(ids, jnp.int32))
        # normalize inert controls so they don't fragment the trace
        # cache: greedy ignores top_k/top_p entirely, and out-of-range
        # values are no-ops inside _pick_impl
        temp = float(temperature) if temperature and temperature > 0 \
            else 0.0
        vocab = logits.shape[-1] if hasattr(logits, "shape") else None
        if temp == 0.0 or not (top_k and 0 < top_k < (vocab or top_k + 1)):
            top_k = None
        if temp == 0.0 or not (top_p and 0.0 < top_p < 1.0):
            top_p = None
        fn = sess.decode_all_fn(max_new_tokens, temp, top_k, top_p,
                                eos_id)
        toks = fn(params, buffers, logits, caches, rng)
        return np.concatenate([np.asarray(ids, np.int32),
                               np.asarray(toks, np.int32)], axis=1)

    def generate_beam(self, prompt_ids, max_new_tokens: int,
                      num_beams: int = 4, length_penalty: float = 1.0,
                      eos_id: Optional[int] = None,
                      return_scores: bool = False, param_dtype=None):
        """Beam-search decoding (static shapes: the K beams ride the
        batch axis, so the same compiled prefill as `generate` serves a
        (B*K)-row batch).  The whole search — expansion, beam
        bookkeeping, cache reorder, decode — runs as ONE jitted
        lax.scan (sess.beam_all_fn): two dispatches and one host fetch
        per search, independent of max_new_tokens.

        Once a beam emits `eos_id` its hypothesis is frozen: its only
        expansion is eos at zero cost, so its RAW cumulative score stays
        constant — but it remains in the single K-wide frontier and can
        still be evicted by K continuing candidates with higher raw
        scores (no separate finished-hypothesis pool, unlike e.g. the
        HF implementation).  `length_penalty` is applied only at the
        END, ranking the K survivors by cumulative logprob /
        length**length_penalty.  Returns the best survivor per batch
        row — shape (B, P + max_new_tokens), eos-padded; with
        `return_scores`, also the (B,) cumulative logprob of each
        returned hypothesis (its exact sum of chosen-token logprobs)."""
        K = int(num_beams)
        if K < 1:
            raise ValueError(f"num_beams must be >= 1, got {K}")
        ids, B, P, S, sess, params, buffers = self._gen_setup(
            prompt_ids, max_new_tokens, K, param_dtype)
        rep = np.repeat(ids, K, axis=0)                      # (B*K, P)
        logits, caches = sess.prefill(params, buffers,
                                      jnp.asarray(rep, jnp.int32))
        fn = sess.beam_all_fn(max_new_tokens, K, eos_id)
        seqs, scores, done, gen_len = (np.asarray(a) for a in fn(
            params, buffers, logits, caches))

        final = np.asarray(scores) / np.maximum(
            gen_len, 1).astype(np.float32) ** length_penalty
        best = final.argmax(axis=1)
        out = np.full((B, S), eos_id if eos_id is not None else 0,
                      np.int32)
        out[:, :P] = ids
        for b in range(B):
            n = int(gen_len[b, best[b]]) if eos_id is not None \
                else max_new_tokens
            out[b, P:P + n] = seqs[b, best[b], :n]
            if eos_id is not None and bool(done[b, best[b]]):
                out[b, P + n:] = eos_id
        if return_scores:
            raw = np.asarray(scores)
            return out, raw[np.arange(B), best]
        return out
