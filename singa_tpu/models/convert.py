"""Weight importers from HuggingFace `transformers` models — the
switch-over path for users arriving with pretrained checkpoints
(BASELINE.json:9's interchange story, beyond ONNX files: direct
state-dict conversion, no serialization round-trip).

    import transformers
    hf = transformers.GPT2LMHeadModel.from_pretrained(...)   # or local
    m = models.from_hf(hf)            # singa_tpu model, same logits

Supported: GPT2LMHeadModel -> models.GPT2, LlamaForCausalLM ->
models.Llama, MistralForCausalLM -> models.Llama(sliding_window=W),
MixtralForCausalLM -> models.Llama(num_experts=E),
BertForSequenceClassification -> models.BERT.
Conversions are pure layout mapping (HF Linear stores
(out, in) -> ours (in, out); GPT-2's Conv1D already stores (in, out);
HF's fused c_attn splits into q/k/v).  RoPE needs no permutation: both
sides use the rotate-half convention.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from .. import tensor as tensor_mod
from ..tensor import Tensor

__all__ = ["from_hf", "from_hf_gpt2", "from_hf_llama", "from_hf_bert",
           "from_hf_mistral", "from_hf_mixtral", "to_hf"]


def _np(t) -> np.ndarray:
    return t.detach().cpu().numpy().astype(np.float32)


def _set(params: Dict[str, Tensor], name: str, arr: np.ndarray) -> None:
    if name not in params:
        raise KeyError(f"no such param {name!r} (have e.g. "
                       f"{list(params)[:4]})")
    p = params[name]
    if tuple(p.shape) != tuple(arr.shape):
        raise ValueError(f"{name}: shape {tuple(arr.shape)} does not fit "
                         f"{tuple(p.shape)}")
    p.copy_from(arr)


def _init(model, batch_t: int = 8):
    """Materialize lazy params with a dummy forward."""
    ids = tensor_mod.from_numpy(np.zeros((1, batch_t), np.int32))
    model.compile([ids], is_train=False, use_graph=False)
    return model


def from_hf_gpt2(hf_model, pipeline_stages: int = 0, dropout=None):
    """transformers.GPT2LMHeadModel -> models.GPT2 (tied head).

    `dropout` defaults to the checkpoint's resid_pdrop so fine-tuning
    regularizes like the source model; pass 0.0 for inference parity
    under training mode."""
    from . import transformer as t

    hc = hf_model.config
    act = getattr(hc, "activation_function", "gelu_new")
    if act not in ("gelu_new", "gelu_pytorch_tanh"):
        raise NotImplementedError(
            f"activation_function={act!r}; models.GPT2 implements the "
            "tanh-gelu (gelu_new) GPT-2 — converting would silently "
            "change the activation")
    if dropout is None:
        dropout = float(getattr(hc, "resid_pdrop", 0.0) or 0.0)
    cfg = t.GPT2Config(
        vocab_size=hc.vocab_size, max_position=hc.n_positions,
        dim=hc.n_embd, num_layers=hc.n_layer, num_heads=hc.n_head,
        dropout=dropout, pipeline_stages=pipeline_stages)
    m = _init(t.GPT2(cfg))
    params = m.get_params()
    sd = hf_model.state_dict()

    _set(params, "wte.table", _np(sd["transformer.wte.weight"]))
    _set(params, "wpe.table", _np(sd["transformer.wpe.weight"]))
    _set(params, "ln_f.gamma", _np(sd["transformer.ln_f.weight"]))
    _set(params, "ln_f.beta", _np(sd["transformer.ln_f.bias"]))
    D = hc.n_embd
    for i in range(hc.n_layer):
        hfp = f"transformer.h.{i}."
        our = f"blocks.{i}."
        for ln, theirs in (("ln_1", "ln_1"), ("ln_2", "ln_2")):
            _set(params, f"{our}{ln}.gamma", _np(sd[f"{hfp}{theirs}.weight"]))
            _set(params, f"{our}{ln}.beta", _np(sd[f"{hfp}{theirs}.bias"]))
        # HF Conv1D stores (in, out): c_attn (D, 3D) fuses q|k|v columns
        ca_w = _np(sd[f"{hfp}attn.c_attn.weight"])
        ca_b = _np(sd[f"{hfp}attn.c_attn.bias"])
        for j, which in enumerate(("q_proj", "k_proj", "v_proj")):
            _set(params, f"{our}attn.{which}.W",
                 ca_w[:, j * D:(j + 1) * D])
            _set(params, f"{our}attn.{which}.b",
                 ca_b[j * D:(j + 1) * D])
        _set(params, f"{our}attn.out_proj.W",
             _np(sd[f"{hfp}attn.c_proj.weight"]))
        _set(params, f"{our}attn.out_proj.b",
             _np(sd[f"{hfp}attn.c_proj.bias"]))
        _set(params, f"{our}mlp.c_fc.W", _np(sd[f"{hfp}mlp.c_fc.weight"]))
        _set(params, f"{our}mlp.c_fc.b", _np(sd[f"{hfp}mlp.c_fc.bias"]))
        _set(params, f"{our}mlp.c_proj.W",
             _np(sd[f"{hfp}mlp.c_proj.weight"]))
        _set(params, f"{our}mlp.c_proj.b",
             _np(sd[f"{hfp}mlp.c_proj.bias"]))
    return m


def from_hf_llama(hf_model, pipeline_stages: int = 0):
    """transformers.LlamaForCausalLM -> models.Llama."""
    from . import llama as lm

    hc = hf_model.config
    if getattr(hc, "attention_bias", False) or \
            getattr(hc, "mlp_bias", False):
        raise NotImplementedError(
            "checkpoint uses attention_bias/mlp_bias; models.Llama's "
            "projections are bias-free — silently dropping the biases "
            "would corrupt the logits")
    # Llama-3.1-style RoPE scaling must carry over or the scaled
    # frequency bands diverge from transformers
    scaling, orig_max = 0.0, hc.max_position_embeddings
    rs = getattr(hc, "rope_scaling", None)
    if rs:
        kind = rs.get("rope_type", rs.get("type", "default"))
        if kind == "llama3":
            scaling = float(rs["factor"])
            orig_max = int(rs.get("original_max_position_embeddings",
                                  orig_max))
        elif kind != "default":
            raise NotImplementedError(
                f"rope_scaling type {kind!r} is not supported "
                "(supported: llama3)")
    cfg = lm.LlamaConfig(
        vocab_size=hc.vocab_size, dim=hc.hidden_size,
        num_layers=hc.num_hidden_layers,
        num_heads=hc.num_attention_heads,
        num_kv_heads=getattr(hc, "num_key_value_heads",
                             hc.num_attention_heads),
        ffn_dim=hc.intermediate_size,
        max_position=hc.max_position_embeddings,
        rope_theta=float(getattr(hc, "rope_theta", 10000.0)),
        rope_scaling=scaling,
        rope_scaling_original_max_position=orig_max,
        eps=float(hc.rms_norm_eps),
        pipeline_stages=pipeline_stages)
    m = _init(lm.Llama(cfg))
    params = m.get_params()
    sd = hf_model.state_dict()

    _copy_llama_dense_weights(params, sd, hc.num_hidden_layers)
    return m


def _copy_llama_dense_weights(params, sd, num_layers: int) -> None:
    """Shared Llama/Mistral state-dict copy (identical layouts)."""
    _set(params, "tok_emb.table", _np(sd["model.embed_tokens.weight"]))
    _set(params, "norm_f.gamma", _np(sd["model.norm.weight"]))
    head = sd.get("lm_head.weight",
                  sd["model.embed_tokens.weight"])   # tied fallback
    _set(params, "lm_head.W", _np(head).T)
    for i in range(num_layers):
        hfp = f"model.layers.{i}."
        our = f"blocks.{i}."
        _set(params, f"{our}attn_norm.gamma",
             _np(sd[f"{hfp}input_layernorm.weight"]))
        _set(params, f"{our}ffn_norm.gamma",
             _np(sd[f"{hfp}post_attention_layernorm.weight"]))
        # HF Linear stores (out, in) -> ours (in, out)
        for theirs, ours in (("self_attn.q_proj", "attn.q_proj"),
                             ("self_attn.k_proj", "attn.k_proj"),
                             ("self_attn.v_proj", "attn.v_proj"),
                             ("self_attn.o_proj", "attn.o_proj"),
                             ("mlp.gate_proj", "ffn.gate"),
                             ("mlp.up_proj", "ffn.up"),
                             ("mlp.down_proj", "ffn.down")):
            _set(params, f"{our}{ours}.W",
                 _np(sd[f"{hfp}{theirs}.weight"]).T)


def from_hf_mistral(hf_model, pipeline_stages: int = 0):
    """transformers.MistralForCausalLM -> models.Llama(sliding_window=W)
    — the state-dict layout is Llama's; the architectural delta is the
    sliding-window attention, mapped onto LlamaConfig.sliding_window."""
    from . import llama as lm

    hc = hf_model.config
    hd = getattr(hc, "head_dim", None)
    if hd and hd != hc.hidden_size // hc.num_attention_heads:
        raise NotImplementedError(
            f"custom head_dim={hd} != hidden/heads is not supported")
    cfg = lm.LlamaConfig(
        vocab_size=hc.vocab_size, dim=hc.hidden_size,
        num_layers=hc.num_hidden_layers,
        num_heads=hc.num_attention_heads,
        num_kv_heads=hc.num_key_value_heads,
        ffn_dim=hc.intermediate_size,
        max_position=hc.max_position_embeddings,
        rope_theta=float(getattr(hc, "rope_theta", 10000.0)),
        sliding_window=int(hc.sliding_window or 0),
        eps=float(hc.rms_norm_eps),
        pipeline_stages=pipeline_stages)
    m = _init(lm.Llama(cfg))
    _copy_llama_dense_weights(m.get_params(), hf_model.state_dict(),
                              hc.num_hidden_layers)
    return m


def from_hf_mixtral(hf_model, **kw):
    """transformers.MixtralForCausalLM -> models.Llama(num_experts=E)
    (SwiGLU experts stacked; HF w1=gate, w3=up, w2=down).

    Routing semantics match exactly (full-softmax probs, top-k,
    renormalize); the converted model's capacity factor is set to E/k
    so NO token is ever dropped — HF's dense gather has no capacity
    concept.  Lower moe_capacity_factor afterwards for capacity-bound
    EP training."""
    from . import llama as lm

    if kw:
        raise NotImplementedError(
            f"from_hf_mixtral takes no options (got {sorted(kw)}); "
            "pipeline_stages is incompatible with MoE blocks")
    hc = hf_model.config
    E = hc.num_local_experts
    k = hc.num_experts_per_tok
    if k < 2:
        raise NotImplementedError(
            "num_experts_per_tok=1: HF renormalizes the selected "
            "gate to 1.0 while this framework's k=1 path keeps the "
            "Switch raw-probability gate — logits would silently "
            "diverge")
    cfg = lm.LlamaConfig(
        vocab_size=hc.vocab_size, dim=hc.hidden_size,
        num_layers=hc.num_hidden_layers,
        num_heads=hc.num_attention_heads,
        num_kv_heads=hc.num_key_value_heads,
        ffn_dim=hc.intermediate_size,
        max_position=hc.max_position_embeddings,
        rope_theta=float(hc.rope_theta),
        eps=float(hc.rms_norm_eps),
        num_experts=E, moe_top_k=k,
        sliding_window=int(getattr(hc, "sliding_window", None) or 0),
        moe_capacity_factor=float(E) / k,
        moe_aux_weight=float(getattr(hc, "router_aux_loss_coef", 0.01)))
    m = _init(lm.Llama(cfg))
    params = m.get_params()
    sd = hf_model.state_dict()

    _set(params, "tok_emb.table", _np(sd["model.embed_tokens.weight"]))
    _set(params, "norm_f.gamma", _np(sd["model.norm.weight"]))
    head = sd.get("lm_head.weight",
                  sd["model.embed_tokens.weight"])   # tied fallback
    _set(params, "lm_head.W", _np(head).T)
    for i in range(hc.num_hidden_layers):
        hfp = f"model.layers.{i}."
        our = f"blocks.{i}."
        _set(params, f"{our}attn_norm.gamma",
             _np(sd[f"{hfp}input_layernorm.weight"]))
        _set(params, f"{our}ffn_norm.gamma",
             _np(sd[f"{hfp}post_attention_layernorm.weight"]))
        for theirs, ours in (("self_attn.q_proj", "attn.q_proj"),
                             ("self_attn.k_proj", "attn.k_proj"),
                             ("self_attn.v_proj", "attn.v_proj"),
                             ("self_attn.o_proj", "attn.o_proj")):
            _set(params, f"{our}{ours}.W",
                 _np(sd[f"{hfp}{theirs}.weight"]).T)
        moe = f"{hfp}block_sparse_moe."
        _set(params, f"{our}ffn.router",
             _np(sd[moe + "gate.weight"]).T)
        _set(params, f"{our}ffn.w_gate", np.stack(
            [_np(sd[f"{moe}experts.{e}.w1.weight"]).T for e in range(E)]))
        _set(params, f"{our}ffn.w_in", np.stack(
            [_np(sd[f"{moe}experts.{e}.w3.weight"]).T for e in range(E)]))
        _set(params, f"{our}ffn.w_out", np.stack(
            [_np(sd[f"{moe}experts.{e}.w2.weight"]).T for e in range(E)]))
    return m


def from_hf_bert(hf_model, **kw):
    """transformers.BertForSequenceClassification -> models.BERT
    (exact-erf GELU on both sides)."""
    if kw:
        raise NotImplementedError(
            f"from_hf_bert takes no options (got {sorted(kw)}); "
            "pipeline_stages applies to the decoder families only")
    from . import transformer as t

    hc = hf_model.config
    cfg = t.BERTConfig(
        vocab_size=hc.vocab_size, max_position=hc.max_position_embeddings,
        type_vocab_size=hc.type_vocab_size, dim=hc.hidden_size,
        num_layers=hc.num_hidden_layers, num_heads=hc.num_attention_heads,
        dropout=float(hc.hidden_dropout_prob),
        num_labels=hc.num_labels, ffn_dim=hc.intermediate_size,
        eps=float(hc.layer_norm_eps))
    if getattr(hc, "hidden_act", "gelu") != "gelu":
        raise NotImplementedError(
            f"hidden_act={hc.hidden_act!r}; models.BERT implements the "
            "standard exact-gelu BERT")
    pe = getattr(hc, "position_embedding_type", "absolute")
    if pe != "absolute":
        raise NotImplementedError(
            f"position_embedding_type={pe!r}; models.BERT implements "
            "absolute position embeddings (relative-key checkpoints "
            "would silently lose their distance embeddings)")
    m = _init(t.BERT(cfg))
    params = m.get_params()
    sd = hf_model.state_dict()

    emb = "bert.embeddings."
    _set(params, "wte.table", _np(sd[emb + "word_embeddings.weight"]))
    _set(params, "wpe.table", _np(sd[emb + "position_embeddings.weight"]))
    _set(params, "wtype.table",
         _np(sd[emb + "token_type_embeddings.weight"]))
    _set(params, "ln_emb.gamma", _np(sd[emb + "LayerNorm.weight"]))
    _set(params, "ln_emb.beta", _np(sd[emb + "LayerNorm.bias"]))
    for i in range(hc.num_hidden_layers):
        hfp = f"bert.encoder.layer.{i}."
        our = f"blocks.{i}."
        # HF Linear stores (out, in) -> ours (in, out)
        for theirs, ours in (
                ("attention.self.query", "attn.q_proj"),
                ("attention.self.key", "attn.k_proj"),
                ("attention.self.value", "attn.v_proj"),
                ("attention.output.dense", "attn.out_proj"),
                ("intermediate.dense", "mlp.c_fc"),
                ("output.dense", "mlp.c_proj")):
            _set(params, f"{our}{ours}.W",
                 _np(sd[f"{hfp}{theirs}.weight"]).T)
            _set(params, f"{our}{ours}.b",
                 _np(sd[f"{hfp}{theirs}.bias"]))
        for theirs, ours in (("attention.output.LayerNorm", "ln_1"),
                             ("output.LayerNorm", "ln_2")):
            _set(params, f"{our}{ours}.gamma",
                 _np(sd[f"{hfp}{theirs}.weight"]))
            _set(params, f"{our}{ours}.beta",
                 _np(sd[f"{hfp}{theirs}.bias"]))
    _set(params, "pooler.W", _np(sd["bert.pooler.dense.weight"]).T)
    _set(params, "pooler.b", _np(sd["bert.pooler.dense.bias"]))
    _set(params, "classifier.W", _np(sd["classifier.weight"]).T)
    _set(params, "classifier.b", _np(sd["classifier.bias"]))
    return m


def from_hf(hf_model, **kw):
    """Dispatch on the exact transformers class name (headless/variant
    classes have different state-dict prefixes and are rejected)."""
    name = type(hf_model).__name__
    if name == "GPT2LMHeadModel":
        return from_hf_gpt2(hf_model, **kw)
    if name == "LlamaForCausalLM":
        return from_hf_llama(hf_model, **kw)
    if name == "MistralForCausalLM":
        return from_hf_mistral(hf_model, **kw)
    if name == "MixtralForCausalLM":
        return from_hf_mixtral(hf_model, **kw)
    if name == "BertForSequenceClassification":
        return from_hf_bert(hf_model, **kw)
    raise NotImplementedError(
        f"no converter for {name}; supported: GPT2LMHeadModel, "
        "LlamaForCausalLM, MistralForCausalLM, MixtralForCausalLM, "
        "BertForSequenceClassification")


# ---------------------------------------------------------------------------
# the reverse direction: our trained models -> transformers instances
# (save_pretrained-able; the exit path mirroring from_hf's entry path)
# ---------------------------------------------------------------------------

def _t(arr: np.ndarray):
    import torch
    return torch.from_numpy(np.ascontiguousarray(arr))


def _np_of(params, name) -> np.ndarray:
    return params[name].to_numpy().astype(np.float32)


def to_hf(model):
    """Export a models.GPT2 / models.Llama to a fresh transformers
    model carrying this model's weights (inverse of from_hf; logits
    match).  Returns the transformers instance — call .save_pretrained
    on it to produce a standard HF checkpoint."""
    import transformers

    from . import llama as lm
    from . import transformer as t

    params = model.get_params()
    if isinstance(model, t.GPT2):
        c = model.cfg
        hf = transformers.GPT2LMHeadModel(transformers.GPT2Config(
            vocab_size=c.vocab_size, n_positions=c.max_position,
            n_embd=c.dim, n_layer=c.num_layers, n_head=c.num_heads,
            resid_pdrop=c.dropout, embd_pdrop=c.dropout,
            attn_pdrop=c.dropout))
        sd = {}
        sd["transformer.wte.weight"] = _t(_np_of(params, "wte.table"))
        sd["transformer.wpe.weight"] = _t(_np_of(params, "wpe.table"))
        sd["transformer.ln_f.weight"] = _t(_np_of(params, "ln_f.gamma"))
        sd["transformer.ln_f.bias"] = _t(_np_of(params, "ln_f.beta"))
        sd["lm_head.weight"] = sd["transformer.wte.weight"]  # tied
        for i in range(c.num_layers):
            our = f"blocks.{i}."
            hfp = f"transformer.h.{i}."
            for ln in ("ln_1", "ln_2"):
                sd[f"{hfp}{ln}.weight"] = _t(_np_of(params,
                                                    f"{our}{ln}.gamma"))
                sd[f"{hfp}{ln}.bias"] = _t(_np_of(params,
                                                  f"{our}{ln}.beta"))
            # fuse q|k|v back into Conv1D's (in, 3*out) c_attn
            w = np.concatenate([_np_of(params, f"{our}attn.{p}.W")
                                for p in ("q_proj", "k_proj", "v_proj")],
                               axis=1)
            b = np.concatenate([_np_of(params, f"{our}attn.{p}.b")
                                for p in ("q_proj", "k_proj", "v_proj")])
            sd[f"{hfp}attn.c_attn.weight"] = _t(w)
            sd[f"{hfp}attn.c_attn.bias"] = _t(b)
            sd[f"{hfp}attn.c_proj.weight"] = _t(
                _np_of(params, f"{our}attn.out_proj.W"))
            sd[f"{hfp}attn.c_proj.bias"] = _t(
                _np_of(params, f"{our}attn.out_proj.b"))
            sd[f"{hfp}mlp.c_fc.weight"] = _t(
                _np_of(params, f"{our}mlp.c_fc.W"))
            sd[f"{hfp}mlp.c_fc.bias"] = _t(
                _np_of(params, f"{our}mlp.c_fc.b"))
            sd[f"{hfp}mlp.c_proj.weight"] = _t(
                _np_of(params, f"{our}mlp.c_proj.W"))
            sd[f"{hfp}mlp.c_proj.bias"] = _t(
                _np_of(params, f"{our}mlp.c_proj.b"))
        hf.load_state_dict(sd, strict=False)
        hf.tie_weights()
        return hf.eval()

    if isinstance(model, lm.Llama):
        c = model.cfg
        if c.num_experts:
            raise NotImplementedError(
                "to_hf does not yet export MoE (Mixtral-config) Llama "
                "models — only dense ones")
        rs = None
        if c.rope_scaling:
            rs = {"rope_type": "llama3", "factor": float(c.rope_scaling),
                  "original_max_position_embeddings":
                      int(c.rope_scaling_original_max_position),
                  "low_freq_factor": 1.0, "high_freq_factor": 4.0}
        common = dict(
            vocab_size=c.vocab_size, hidden_size=c.dim,
            intermediate_size=c.ffn_dim, num_hidden_layers=c.num_layers,
            num_attention_heads=c.num_heads,
            num_key_value_heads=c.num_kv_heads,
            max_position_embeddings=c.max_position,
            rope_theta=c.rope_theta, rms_norm_eps=c.eps,
            tie_word_embeddings=False)
        if c.sliding_window:
            # the window is load-bearing: exporting as a plain Llama
            # would silently attend the full context in HF
            if rs:
                raise NotImplementedError(
                    "sliding_window + rope_scaling has no matching HF "
                    "architecture to export to")
            hf = transformers.MistralForCausalLM(
                transformers.MistralConfig(
                    sliding_window=c.sliding_window, **common))
        else:
            hf = transformers.LlamaForCausalLM(transformers.LlamaConfig(
                rope_scaling=rs, attention_bias=False, mlp_bias=False,
                **common))
        sd = {}
        sd["model.embed_tokens.weight"] = _t(_np_of(params,
                                                    "tok_emb.table"))
        sd["model.norm.weight"] = _t(_np_of(params, "norm_f.gamma"))
        sd["lm_head.weight"] = _t(_np_of(params, "lm_head.W").T)
        for i in range(c.num_layers):
            our = f"blocks.{i}."
            hfp = f"model.layers.{i}."
            sd[f"{hfp}input_layernorm.weight"] = _t(
                _np_of(params, f"{our}attn_norm.gamma"))
            sd[f"{hfp}post_attention_layernorm.weight"] = _t(
                _np_of(params, f"{our}ffn_norm.gamma"))
            for theirs, ours in (("self_attn.q_proj", "attn.q_proj"),
                                 ("self_attn.k_proj", "attn.k_proj"),
                                 ("self_attn.v_proj", "attn.v_proj"),
                                 ("self_attn.o_proj", "attn.o_proj"),
                                 ("mlp.gate_proj", "ffn.gate"),
                                 ("mlp.up_proj", "ffn.up"),
                                 ("mlp.down_proj", "ffn.down")):
                sd[f"{hfp}{theirs}.weight"] = _t(
                    _np_of(params, f"{our}{ours}.W").T)
        hf.load_state_dict(sd, strict=False)
        return hf.eval()

    raise NotImplementedError(
        f"to_hf supports models.GPT2 and models.Llama, got "
        f"{type(model).__name__}")
