"""Shared model bases for the zoo."""

from __future__ import annotations

from .. import autograd, model

__all__ = ["Classifier"]


class Classifier(model.Model):
    """Canonical classification step (reference examples/cnn model.py):
    forward → softmax-cross-entropy → opt(loss)."""

    def train_one_batch(self, x, y):
        out = self.forward(x)
        loss = autograd.softmax_cross_entropy(out, y)
        self.optimizer(loss)
        return out, loss
