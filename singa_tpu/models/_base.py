"""Shared model bases for the zoo."""

from __future__ import annotations

import numpy as np

from .. import autograd, model
from ..tensor import Tensor

__all__ = ["Classifier"]


def _cast_to_compute(x: Tensor) -> Tensor:
    """Cast float inputs to the device compute dtype (bf16 on TPU) so
    convs/matmuls ride the MXU at full rate — same boundary-cast design
    as layer.Embedding; params then follow the activation dtype via
    layer._maybe_cast, and BatchNorm keeps f32 statistics internally."""
    dt = getattr(x.device, "default_dtype", None)
    if (dt is not None and np.dtype(dt) != np.dtype(np.float32)
            and np.issubdtype(np.dtype(x.dtype), np.floating)
            and np.dtype(x.dtype) != np.dtype(dt)):
        return autograd.cast(x, dt)
    return x


class Classifier(model.Model):
    """Canonical classification step (reference examples/cnn model.py):
    forward → softmax-cross-entropy → opt(loss); float inputs enter at
    the device compute dtype, logits/loss computed in f32."""

    def __call__(self, *xs):
        xs = tuple(_cast_to_compute(x) if isinstance(x, Tensor) else x
                   for x in xs)
        return super().__call__(*xs)

    def train_one_batch(self, x, y):
        out = self.forward(_cast_to_compute(x))
        loss = autograd.softmax_cross_entropy(out, y)
        self.optimizer(loss)
        return out, loss
