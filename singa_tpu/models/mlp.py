"""MLP — the reference smoke-test workload (examples/mlp on CppCPU,
BASELINE.json:7)."""

from __future__ import annotations

from typing import Sequence

from .. import autograd, layer
from ._base import Classifier

__all__ = ["MLP", "create_model"]


class MLP(Classifier):
    """Configurable fully-connected classifier.

    Reference shape: examples/mlp/model.py — stacked Linear+ReLU with a
    softmax-cross-entropy head and the canonical train_one_batch body.
    """

    def __init__(self, perceptron_size: Sequence[int] = (100,),
                 num_classes: int = 10):
        super().__init__()
        if isinstance(perceptron_size, int):
            perceptron_size = (perceptron_size,)
        self.hidden = [layer.Linear(h) for h in perceptron_size]
        self.acts = [layer.ReLU() for _ in perceptron_size]
        self.head = layer.Linear(num_classes)
        self.num_classes = num_classes

    def forward(self, x):
        if x.ndim > 2:
            x = autograd.flatten(x, 1)
        for fc, act in zip(self.hidden, self.acts):
            x = act(fc(x))
        return self.head(x)


def create_model(pretrained: bool = False, **kwargs) -> MLP:
    """Reference factory signature (examples/mlp)."""
    return MLP(**kwargs)
