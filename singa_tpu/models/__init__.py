"""singa_tpu.models — the model zoo (reference parity: examples/mlp,
examples/cnn model definitions + the ONNX-zoo transformer families,
BASELINE.json:7-11).

Families:
  * mlp          — MLP for MNIST-class data (BASELINE.json:7)
  * cnn          — simple CNN / LeNet-5 / AlexNet (BASELINE.json:7-8)
  * resnet       — ResNet-18/34/50/101/152, CIFAR + ImageNet stems
                   (BASELINE.json:8,10)
  * vgg          — VGG-11/13/16/19 (+BN) (BASELINE.json:8)
  * transformer  — GPT-2 and BERT (BASELINE.json:9)
  * llama        — Llama-3 family, the flagship stretch config
                   (BASELINE.json:11): RMSNorm, RoPE, SwiGLU, GQA

Every model is a singa_tpu.model.Model: imperative forward, trains
eagerly or as one compiled XLA module, shards over a mesh via the
sharding rules each module exports (see singa_tpu.parallel).
"""

from . import mlp
from . import cnn
from . import resnet
from . import vgg
from . import transformer
from . import llama

from .mlp import MLP
from .cnn import CNN, LeNet5, AlexNet
from .resnet import (ResNet, resnet18, resnet34, resnet50, resnet101,
                     resnet152)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19
from .transformer import GPT2, BERT, GPT2Config, BERTConfig
from .llama import Llama, LlamaConfig
from .convert import (from_hf, from_hf_bert, from_hf_gpt2,
                      from_hf_llama, from_hf_mistral,
                      from_hf_mixtral, to_hf)

__all__ = [
    "mlp", "cnn", "resnet", "vgg", "transformer", "llama",
    "MLP", "CNN", "LeNet5", "AlexNet",
    "ResNet", "resnet18", "resnet34", "resnet50", "resnet101", "resnet152",
    "VGG", "vgg11", "vgg13", "vgg16", "vgg19",
    "GPT2", "BERT", "GPT2Config", "BERTConfig",
    "Llama", "LlamaConfig",
    "from_hf", "from_hf_bert", "from_hf_gpt2", "from_hf_llama",
    "from_hf_mistral", "from_hf_mixtral",
    "to_hf",
]
