"""ResNet — the reference's headline CNN workloads: CIFAR ResNet-18 and
ImageNet ResNet-50 (BASELINE.json:8,10).

NHWC + HWIO kernels so every conv lands on the MXU without layout
transposes; BatchNorm running stats thread functionally through the
compiled step (singa_tpu.layer.BatchNorm2d).
"""

from __future__ import annotations

from typing import List, Type

from .. import layer
from ._base import Classifier

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "resnet152", "create_model"]


class BasicBlock(layer.Layer):
    expansion = 1

    def __init__(self, planes: int, stride: int = 1, downsample=None,
                 name=None):
        super().__init__(name)
        self.conv1 = layer.Conv2d(planes, 3, stride=stride, padding=1,
                                  bias=False)
        self.bn1 = layer.BatchNorm2d(planes)
        self.conv2 = layer.Conv2d(planes, 3, stride=1, padding=1, bias=False)
        self.bn2 = layer.BatchNorm2d(planes)
        self.relu = layer.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x if self.downsample is None else self.downsample(x)
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        return self.relu(out + identity)


class Bottleneck(layer.Layer):
    expansion = 4

    def __init__(self, planes: int, stride: int = 1, downsample=None,
                 name=None):
        super().__init__(name)
        self.conv1 = layer.Conv2d(planes, 1, bias=False)
        self.bn1 = layer.BatchNorm2d(planes)
        self.conv2 = layer.Conv2d(planes, 3, stride=stride, padding=1,
                                  bias=False)
        self.bn2 = layer.BatchNorm2d(planes)
        self.conv3 = layer.Conv2d(planes * 4, 1, bias=False)
        self.bn3 = layer.BatchNorm2d(planes * 4)
        self.relu = layer.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x if self.downsample is None else self.downsample(x)
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        return self.relu(out + identity)


def _downsample(planes: int, stride: int) -> layer.Layer:
    return layer.Sequential(
        layer.Conv2d(planes, 1, stride=stride, bias=False),
        layer.BatchNorm2d(planes))


class ResNet(Classifier):
    """ResNet with ImageNet (7x7 s2 + maxpool) or CIFAR (3x3 s1) stem."""

    def __init__(self, block: Type, layers: List[int],
                 num_classes: int = 1000, cifar_stem: bool = False):
        super().__init__()
        self.cifar_stem = cifar_stem
        if cifar_stem:
            self.conv1 = layer.Conv2d(64, 3, stride=1, padding=1, bias=False)
        else:
            self.conv1 = layer.Conv2d(64, 7, stride=2, padding=3, bias=False)
            self.maxpool = layer.MaxPool2d(3, 2, padding=1)
        self.bn1 = layer.BatchNorm2d(64)
        self.relu = layer.ReLU()
        self._in_planes = 64
        self.layer1 = self._make_layer(block, 64, layers[0], 1)
        self.layer2 = self._make_layer(block, 128, layers[1], 2)
        self.layer3 = self._make_layer(block, 256, layers[2], 2)
        self.layer4 = self._make_layer(block, 512, layers[3], 2)
        self.avgpool = layer.GlobalAvgPool2d()
        self.fc = layer.Linear(num_classes)

    def _make_layer(self, block, planes, blocks, stride) -> layer.Layer:
        out_c = planes * block.expansion
        # projection shortcut only when the residual shape changes
        # (canonical ResNet: layer1 of 18/34 keeps the identity)
        ds = (_downsample(out_c, stride)
              if stride != 1 or self._in_planes != out_c else None)
        stages = [block(planes, stride, ds)]
        for _ in range(1, blocks):
            stages.append(block(planes, 1, None))
        self._in_planes = out_c
        return layer.Sequential(*stages)

    def forward(self, x):
        x = self.relu(self.bn1(self.conv1(x)))
        if not self.cifar_stem:
            x = self.maxpool(x)
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        return self.fc(self.avgpool(x))


def resnet18(num_classes=10, cifar_stem=True) -> ResNet:
    """CIFAR ResNet-18 by default (the BASELINE.json:8 config)."""
    return ResNet(BasicBlock, [2, 2, 2, 2], num_classes, cifar_stem)


def resnet34(num_classes=1000, cifar_stem=False) -> ResNet:
    return ResNet(BasicBlock, [3, 4, 6, 3], num_classes, cifar_stem)


def resnet50(num_classes=1000, cifar_stem=False) -> ResNet:
    """ImageNet ResNet-50 (the BASELINE.json:10 DP workload)."""
    return ResNet(Bottleneck, [3, 4, 6, 3], num_classes, cifar_stem)


def resnet101(num_classes=1000, cifar_stem=False) -> ResNet:
    return ResNet(Bottleneck, [3, 4, 23, 3], num_classes, cifar_stem)


def resnet152(num_classes=1000, cifar_stem=False) -> ResNet:
    return ResNet(Bottleneck, [3, 8, 36, 3], num_classes, cifar_stem)


def create_model(model_name: str = "resnet18", **kwargs) -> ResNet:
    zoo = {"resnet18": resnet18, "resnet34": resnet34, "resnet50": resnet50,
           "resnet101": resnet101, "resnet152": resnet152}
    return zoo[model_name.lower()](**kwargs)
