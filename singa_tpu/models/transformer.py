"""Transformer family — GPT-2 and BERT-base, the reference's ONNX-zoo
workloads (BASELINE.json:9).

TPU-first notes:
  * attention routes through singa_tpu.ops.attention (Pallas flash path
    for long sequences, fused-einsum path otherwise);
  * weights are f32 masters cast to the input compute dtype (bf16 on
    TPU) at use — the MXU path;
  * each model exports SHARD_RULES: (regex over param path → partition
    spec tuple) giving Megatron-style tensor parallelism over the
    'model' mesh axis when a multi-axis mesh is installed.  Column
    parallel for qkv/up projections, row parallel for out/down, so each
    block needs exactly one all-reduce pair — inserted by GSPMD, ridden
    over ICI.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp

from .. import autograd, layer, model
from ..tensor import Tensor
from ._generate import GenerateMixin

__all__ = ["GPT2Config", "GPT2", "BERTConfig", "BERT",
           "TRANSFORMER_SHARD_RULES"]

# Megatron-style TP layout over the 'model' axis; the executor matches
# param paths against these regexes (first hit wins) and drops axes the
# installed mesh doesn't have.
TRANSFORMER_SHARD_RULES = [
    (r"(q_proj|k_proj|v_proj|c_fc|fc_in|gate|up)\.W$", (None, "model")),
    (r"(q_proj|k_proj|v_proj|c_fc|fc_in|gate|up)\.b$", ("model",)),
    (r"(out_proj|c_proj|fc_out|down)\.W$", ("model", None)),
    (r"(wte|wpe|wtype|emb\w*)\.table$", (None, "model")),
    (r"lm_head\.W$", (None, "model")),
]


def _positions(ids: Tensor) -> Tensor:
    T = ids.shape[-1]
    pos = jnp.arange(T, dtype=jnp.int32)[None, :]
    return Tensor(data=jnp.broadcast_to(pos, ids.shape), device=ids.device,
                  requires_grad=False)


def _padding_mask(attention_mask: Optional[Tensor]):
    """(B, T) 1/0 mask → (B, 1, 1, T) bool broadcastable over heads/queries."""
    if attention_mask is None:
        return None
    am = attention_mask.data if isinstance(attention_mask, Tensor) \
        else jnp.asarray(attention_mask)
    return (am > 0)[:, None, None, :]


class _MLP(layer.Layer):
    """act: "gelu_tanh" (GPT-2's gelu_new), "gelu" (exact erf — real
    BERT semantics), or "relu"."""

    def __init__(self, hidden: int, act: str = "gelu", name=None):
        super().__init__(name)
        self.c_fc = layer.Linear(hidden)
        if act == "gelu":
            self.act = layer.Gelu(approximate=False)
        elif act == "gelu_tanh":
            self.act = layer.Gelu(approximate=True)
        elif act == "relu":
            self.act = layer.ReLU()
        else:
            raise ValueError(f"unknown _MLP act {act!r}")
        self.c_proj: Optional[layer.Layer] = None
        self._out: Optional[int] = None

    def initialize(self, x):
        self._out = x.shape[-1]
        self.c_proj = layer.Linear(self._out)

    def forward(self, x):
        return self.c_proj(self.act(self.c_fc(x)))


# ---------------------------------------------------------------------------
# GPT-2
# ---------------------------------------------------------------------------

@dataclass
class GPT2Config:
    vocab_size: int = 50257
    max_position: int = 1024
    dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    dropout: float = 0.1
    # opt-in chunked fused tied-head+CE loss (no (B*T, V) logits;
    # train_one_batch then returns (loss, loss) instead of (logits, loss))
    fused_loss: bool = False
    # activation checkpointing per block (layer.Remat; padding masks
    # thread through the checkpoint as saved non-grad residuals, so
    # masked calls remat too)
    remat: bool = False
    # pipeline parallelism over the 'pipe' mesh axis
    # (layer.PipelineStack); padding masks ride the schedule as
    # microbatched extras.  Requires dropout=0.0 for exact sequential
    # parity (the stack falls back to sequential otherwise).  0 = off.
    pipeline_stages: int = 0
    pipeline_microbatches: int = 0

    @staticmethod
    def tiny() -> "GPT2Config":
        return GPT2Config(vocab_size=256, max_position=64, dim=64,
                          num_layers=2, num_heads=4, dropout=0.0)


class _GPT2Block(layer.Layer):
    def __init__(self, cfg: GPT2Config, name=None):
        super().__init__(name)
        self.ln_1 = layer.LayerNorm(cfg.dim)
        self.attn = layer.MultiHeadAttention(cfg.num_heads, cfg.dim,
                                             causal=True)
        self.ln_2 = layer.LayerNorm(cfg.dim)
        self.mlp = _MLP(4 * cfg.dim, "gelu_tanh")   # HF gelu_new
        self.drop = layer.Dropout(cfg.dropout)

    def forward(self, x, mask=None, cache=None, pos=0):
        if cache is not None:
            a, new_cache = self.attn(self.ln_1(x), mask, cache, pos)
            x = x + self.drop(a)
            x = x + self.drop(self.mlp(self.ln_2(x)))
            return x, new_cache
        x = x + self.drop(self.attn(self.ln_1(x), mask))
        x = x + self.drop(self.mlp(self.ln_2(x)))
        return x


class GPT2(GenerateMixin, model.Model):
    """GPT-2 causal LM with tied embeddings (reference ONNX GPT-2,
    BASELINE.json:9)."""

    SHARD_RULES = TRANSFORMER_SHARD_RULES

    def __init__(self, cfg: Optional[GPT2Config] = None, **kw):
        super().__init__()
        self.cfg = cfg or GPT2Config(**kw)
        c = self.cfg
        self.wte = layer.Embedding(c.vocab_size, c.dim)
        self.wpe = layer.Embedding(c.max_position, c.dim)
        self.drop = layer.Dropout(c.dropout)
        blocks = [_GPT2Block(c) for _ in range(c.num_layers)]
        if c.pipeline_stages:
            self.blocks = layer.PipelineStack(
                blocks, stages=c.pipeline_stages,
                n_micro=c.pipeline_microbatches or None, remat=c.remat)
        else:
            if c.remat:
                blocks = [layer.Remat(b) for b in blocks]
            self.blocks = blocks
        self.ln_f = layer.LayerNorm(c.dim)

    def features(self, ids: Tensor,
                 attention_mask: Optional[Tensor] = None) -> Tensor:
        """Final hidden states (B, T, dim) — everything but the tied head."""
        mask = _padding_mask(attention_mask)
        if mask is not None:
            mask = Tensor(data=mask, device=ids.device, requires_grad=False)
        x = self.wte(ids) + self.wpe(_positions(ids))
        x = self.drop(x)
        if isinstance(self.blocks, layer.PipelineStack):
            # mask (None filtered by the stack) rides the GPipe
            # schedule as a microbatched extra
            x = self.blocks(x, mask)
        else:
            for blk in self.blocks:
                # mask is an optional extra; when present, layer.Remat
                # carries it as a saved (non-grad) residual through the
                # checkpoint, so both call forms remat
                x = blk(x) if mask is None else blk(x, mask)
        return self.ln_f(x)

    def _tied_head_w(self, x: Tensor) -> Tensor:
        # tied LM head weight: wte.T, cast to the compute dtype so bf16
        # activations don't promote back to f32
        w = self.wte.table
        if w.dtype != x.dtype:
            w = autograd.cast(w, x.dtype)
        return autograd.transpose(w)

    def forward(self, ids: Tensor, attention_mask: Optional[Tensor] = None):
        x = self.features(ids, attention_mask)
        return autograd.matmul(x, self._tied_head_w(x))

    def train_one_batch(self, ids: Tensor, labels: Optional[Tensor] = None):
        tgt = labels if labels is not None else ids
        if self.cfg.fused_loss:
            x = self.features(ids)
            loss = next_token_loss_fused_w(x, self._tied_head_w(x), tgt)
            self.optimizer(loss)
            return loss, loss
        logits = self.forward(ids)
        loss = next_token_loss(logits, tgt)
        self.optimizer(loss)
        return logits, loss

    # -- KV-cached decoding (ops/kv_cache.py; VERDICT r2 item 4) ------------
    def init_caches(self, batch: int, max_len: int):
        c = self.cfg
        hd = c.dim // c.num_heads
        dtype = self.wte.table.dtype
        if dtype not in (jnp.float32, jnp.bfloat16):
            dtype = jnp.float32
        from ..ops import kv_cache as kv_ops
        return kv_ops.init_cache(c.num_layers, batch, max_len,
                                 c.num_heads, hd, dtype)

    def forward_cached(self, ids: Tensor, caches, pos):
        T = ids.shape[-1]
        if getattr(pos, "ndim", 0):
            # per-row positions (continuous batching — serve.engine):
            # row b embeds absolute positions [pos[b], pos[b]+T)
            grid = pos[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
        else:
            if isinstance(pos, int):
                positions = jnp.arange(pos, pos + T, dtype=jnp.int32)
            else:
                positions = pos + jnp.arange(T, dtype=jnp.int32)
            grid = positions[None, :]
        pos_t = Tensor(data=jnp.broadcast_to(grid, ids.shape),
                       device=ids.device, requires_grad=False)
        x = self.wte(ids) + self.wpe(pos_t)
        x = self.drop(x)
        new_caches = []
        for blk, cache in zip(self.blocks, caches):
            x, nc = blk(x, None, cache, pos)
            new_caches.append(nc)
        x = self.ln_f(x)
        return autograd.matmul(x, self._tied_head_w(x)), new_caches


def next_token_loss(logits: Tensor, ids: Tensor) -> Tensor:
    """Causal-LM loss: predict ids[t+1] from logits[t]."""
    B, T, V = logits.shape
    lg = autograd.reshape(logits[:, :-1, :], (B * (T - 1), V))
    tg = Tensor(data=ids.data[:, 1:].reshape(-1), device=ids.device,
                requires_grad=False)
    return autograd.softmax_cross_entropy(lg, tg)


def next_token_loss_fused_w(x: Tensor, w: Tensor, ids: Tensor,
                            chunk_rows: int = 512) -> Tensor:
    """Causal-LM loss straight from the final hidden states against an
    explicit (dim, V) head weight: the matmul and softmax-CE run fused +
    row-chunked (autograd.fused_linear_cross_entropy), so the (B*T, V)
    logits are never materialized — the memory-lean large-vocab path.
    `w` may be any differentiable Tensor (e.g. a transposed tied
    embedding table); gradients flow through it."""
    B, T, d = x.shape
    h = autograd.reshape(x[:, :-1, :], (B * (T - 1), d))
    tg = Tensor(data=ids.data[:, 1:].reshape(-1), device=ids.device,
                requires_grad=False)
    return autograd.fused_linear_cross_entropy(h, w, tg, chunk_rows)


def next_token_loss_fused(x: Tensor, lm_head, ids: Tensor,
                          chunk_rows: int = 512) -> Tensor:
    """next_token_loss_fused_w against a (possibly lazily-initialized)
    Linear lm-head layer."""
    if not lm_head._initialized:          # fused path skips lm_head(...)
        lm_head.initialize(x)
        lm_head._initialized = True
    return next_token_loss_fused_w(x, lm_head.W, ids, chunk_rows)


# ---------------------------------------------------------------------------
# BERT
# ---------------------------------------------------------------------------

@dataclass
class BERTConfig:
    vocab_size: int = 30522
    max_position: int = 512
    type_vocab_size: int = 2
    dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    dropout: float = 0.1
    num_labels: Optional[int] = None  # optional classification head
    # FFN width; 0 = the standard 4*dim
    ffn_dim: int = 0
    # LayerNorm epsilon (HF/original BERT uses 1e-12)
    eps: float = 1e-12

    @staticmethod
    def tiny(num_labels: Optional[int] = None) -> "BERTConfig":
        return BERTConfig(vocab_size=256, max_position=64, type_vocab_size=2,
                          dim=64, num_layers=2, num_heads=4, dropout=0.0,
                          num_labels=num_labels)


class _BERTBlock(layer.Layer):
    """Post-LN encoder block (original BERT layout)."""

    def __init__(self, cfg: BERTConfig, name=None):
        super().__init__(name)
        self.attn = layer.MultiHeadAttention(cfg.num_heads, cfg.dim,
                                             causal=False)
        self.ln_1 = layer.LayerNorm(cfg.dim, eps=cfg.eps)
        self.mlp = _MLP(cfg.ffn_dim or 4 * cfg.dim, "gelu")
        self.ln_2 = layer.LayerNorm(cfg.dim, eps=cfg.eps)
        self.drop = layer.Dropout(cfg.dropout)

    def forward(self, x, mask=None):
        x = self.ln_1(x + self.drop(self.attn(x, mask)))
        x = self.ln_2(x + self.drop(self.mlp(x)))
        return x


class BERT(model.Model):
    """BERT-base encoder (+pooler, optional classifier) — reference ONNX
    BERT-base (BASELINE.json:9)."""

    SHARD_RULES = TRANSFORMER_SHARD_RULES

    def __init__(self, cfg: Optional[BERTConfig] = None, **kw):
        super().__init__()
        self.cfg = cfg or BERTConfig(**kw)
        c = self.cfg
        self.wte = layer.Embedding(c.vocab_size, c.dim)
        self.wpe = layer.Embedding(c.max_position, c.dim)
        self.wtype = layer.Embedding(c.type_vocab_size, c.dim)
        self.ln_emb = layer.LayerNorm(c.dim, eps=c.eps)
        self.drop = layer.Dropout(c.dropout)
        self.blocks = [_BERTBlock(c) for _ in range(c.num_layers)]
        self.pooler = layer.Linear(c.dim)
        self.pool_act = layer.Tanh()
        self.classifier = (layer.Linear(c.num_labels)
                           if c.num_labels else None)

    def forward(self, ids: Tensor, token_type_ids: Optional[Tensor] = None,
                attention_mask: Optional[Tensor] = None):
        if token_type_ids is None:
            token_type_ids = Tensor(
                data=jnp.zeros(ids.shape, jnp.int32), device=ids.device,
                requires_grad=False)
        mask = _padding_mask(attention_mask)
        if mask is not None:
            mask = Tensor(data=mask, device=ids.device, requires_grad=False)
        x = self.wte(ids) + self.wpe(_positions(ids)) + self.wtype(token_type_ids)
        x = self.drop(self.ln_emb(x))
        for blk in self.blocks:
            x = blk(x, mask)
        pooled = self.pool_act(self.pooler(x[:, 0, :]))
        if self.classifier is not None:
            return self.classifier(pooled)
        return x, pooled

    def train_one_batch(self, ids: Tensor, labels: Tensor,
                        token_type_ids=None, attention_mask=None):
        if self.classifier is None:
            raise RuntimeError("BERT(num_labels=...) required for the "
                               "canonical classification train step")
        out = self.forward(ids, token_type_ids, attention_mask)
        loss = autograd.softmax_cross_entropy(out, labels)
        self.optimizer(loss)
        return out, loss

    def flops_per_token(self, seq_len: int) -> float:
        """Training FLOPs/token ≈ 6·N_matmul + 12·L·dim·T — same
        accounting as Llama.flops_per_token, EXCEPT the embedding
        tables are excluded from N: a classification BERT has no
        vocab-sized output matmul, so (unlike a tied-embedding LM)
        those ~24M params never hit the MXU.  Gather/scatter of the
        embedding rows is memory traffic, not FLOPs."""
        c = self.cfg
        n_embed = (c.vocab_size + c.max_position
                   + c.type_vocab_size) * c.dim
        n_total = sum(p.size for p in self.get_params().values())
        return (6 * (n_total - n_embed)
                + 12 * c.num_layers * c.dim * seq_len)
